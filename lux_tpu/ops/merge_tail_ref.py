"""Reference (host, unoptimized) scheduler for the merge-network tail.

This is the round-4 groundwork for the source-block-grouped tail
(PERF.md "grouped-tail / merge-network design"): a correct, executable
specification of the routing construction, validated by simulation in
tests/test_merge_tail.py. It is NOT wired into any executor and is not
performance code — the real planner must vectorize the walk (34M reals
at RMAT22) and the device side uses the probed Pallas kernels
(tools/probe_merge_kernel.py).

Model
-----
R runs (R a power of two; empty runs pad the tree), each a dst-sorted
sequence of "reals". Levels ℓ = 1..L (L = log2 R) merge adjacent
subtrees: the side of run r at level ℓ is bit ℓ-1 of r, and the node
(subtree) containing it is r >> ℓ. One device pass per level: output
window w (one 128-lane row) of a node reads EXACTLY input slots
[64w, 64w+64) of each side — so a real's emission window at every
level is forced by its slot at the level below, and all slots derive
from its FINAL position:

    slot_L(x) = f(x)                                (root output slot)
    slot_{ℓ-1}(x) = 64 * (slot_ℓ(x) // 128) + rank of x among reals of
                    its (node, side) within that window   (must be < 64)

The construction is one forward walk over the global dst order,
placing reals at the next final slot whose implied per-(node, side)
window ranks all stay below 64; on overflow the final cursor advances
to the next 128-slot row (the skipped slots are the stall pads).
"""

from __future__ import annotations

import numpy as np

BLOCK = 128
WIN = 64
PAD = -1


def _tree_size(nruns: int) -> int:
    """Power-of-two tree width, minimum 2 so there is always at least
    one merge level (a single run still flows through level 1 paired
    with an empty sibling — L = 0 would schedule phantom levels with
    no nodes)."""
    R = 2
    while R < nruns:
        R *= 2
    return R


def schedule(runs):
    """Assign each real a final-stream position.

    ``runs``: list of dst-sorted 1-D int arrays (may be empty); length
    is padded to a power of two internally. Returns (f, order) where
    ``order`` lists reals as (dst, run, pos) triples in global merged
    dst order (ties by run index) and ``f[i]`` is the final slot of
    ``order[i]``.
    """
    R = _tree_size(len(runs))
    L = R.bit_length() - 1

    # Global merged order: (dst, run, pos)
    items = []
    for r, a in enumerate(runs):
        for p, d in enumerate(np.asarray(a)):
            items.append((int(d), r, p))
    items.sort()
    n = len(items)

    # Per (level, node, side) counters: rank within the current window,
    # plus the window id the counter belongs to.
    q = {}
    win = {}
    f = np.zeros(n, np.int64)
    t = 0                     # next candidate final slot
    for i, (_, r, p) in enumerate(items):
        while True:
            ok = True
            # Derive slots top-down at candidate position t.
            slots = {}
            s = t
            for lev in range(L, 0, -1):
                node = r >> lev
                side = (r >> (lev - 1)) & 1
                w = s // BLOCK
                key = (lev, node, side)
                if win.get(key) != w:
                    rank = 0
                else:
                    rank = q[key]
                if rank >= WIN:
                    ok = False
                    break
                slots[lev] = (key, w, rank)
                s = WIN * w + rank   # slot at level lev-1's output
            if ok:
                break
            t = (t // BLOCK + 1) * BLOCK   # stall: next output row
        # Commit.
        f[i] = t
        for lev, (key, w, rank) in slots.items():
            win[key] = w
            q[key] = rank + 1
        t += 1
    return f, items


def derive_level_slots(runs, f, items):
    """Recompute every real's slot at every level from its final
    position (the mechanical top-down derivation) and return
    per-level dicts {(run, pos): slot}."""
    R = _tree_size(len(runs))
    L = R.bit_length() - 1
    out = {lev: {} for lev in range(0, L + 1)}
    # rank bookkeeping identical to schedule()
    q = {}
    win = {}
    for i, (_, r, p) in enumerate(items):
        s = int(f[i])
        out[L][(r, p)] = s
        for lev in range(L, 0, -1):
            node = r >> lev
            side = (r >> (lev - 1)) & 1
            w = s // BLOCK
            key = (lev, node, side)
            if win.get(key) != w:
                q[key] = 0
                win[key] = w
            rank = q[key]
            q[key] = rank + 1
            s = WIN * w + rank
            out[lev - 1][(r, p)] = s
    return out


def _align_up(x: int, a: int) -> int:
    return -(-x // a) * a if a > 1 else x


def schedule_grouped(runs, align_rows: int = 1):
    """Copy-window (round-5) scheduler: the per-row generalization of
    :func:`schedule` that the production planner vectorizes.

    Instead of deriving every level from one global final-slot walk with
    64-per-side window quotas, each level is scheduled independently,
    bottom-up, against the per-row kernel contract: output row o reads
    ONE full 128-lane input row per side (scalar-prefetched ``arow[o]``,
    ``brow[o]``) and an int8 code plane routes lanes (v >= 0 side A
    lane v, v < 0 side B lane v & 127). A row whose codes are
    single-sided is a COPY row — a drained or dominant side streams at
    full rate (128/row) instead of stalling at the 64/64 merge rate,
    which is the entire point (PERF.md: 1.85x -> target <1.5x). The
    walk emits a copy row exactly when the next <=128 merged reals are
    single-sided within one input row.

    A row closes when it holds 128 reals or when the merged order
    needs a real from an input row other than the one the row reads
    for that side (the only stall source left). ``align_rows`` pads
    every leaf/node stream base to that many rows (the Mosaic 8-row
    block constraint; the planner adds remainder bin-packing on top).

    Returns ``(levels, final_items, total_rows)``: ``levels[k]`` is a
    dict of numpy arrays {arow, brow, codes, nvalid, mode} for merge
    level k+1 (mode 0 merge, 1 copy-A, 2 copy-B), ``final_items`` the
    reals as (dst, run, pos, slot) in merged order, ``total_rows`` the
    per-level stream row counts [level0, ..., root].
    """
    R = _tree_size(len(runs))
    L = R.bit_length() - 1

    # Leaf streams: run r dense from an aligned base.
    streams = []
    base = 0
    for r in range(R):
        a = np.asarray(runs[r]) if r < len(runs) else np.empty(0, np.int64)
        streams.append([
            (int(d), r, p, base + p // BLOCK, p % BLOCK)
            for p, d in enumerate(a)
        ])
        base = _align_up(base + (len(a) + BLOCK - 1) // BLOCK, align_rows)
    total_rows = [base]

    levels = []
    for lev in range(1, L + 1):
        arow, brow, codes, nvalid, mode = [], [], [], [], []
        out_streams = []
        ob = 0
        for node in range(R >> lev):
            A, B = streams[2 * node], streams[2 * node + 1]
            out = []
            ia = ib = 0
            while ia < len(A) or ib < len(B):
                ra = A[ia][3] if ia < len(A) else -1
                rb = B[ib][3] if ib < len(B) else -1
                row_codes = np.zeros(BLOCK, np.int8)
                count = 0
                took_a = took_b = False
                while count < BLOCK:
                    ta = A[ia] if ia < len(A) else None
                    tb = B[ib] if ib < len(B) else None
                    if ta is None and tb is None:
                        break
                    # Merged order: (dst, run) — side A holds the lower
                    # run ids of the node, so ties go to A.
                    use_a = tb is None or (
                        ta is not None and ta[:2] <= tb[:2]
                    )
                    if use_a:
                        if ta[3] != ra:
                            break          # next A real is in a later row
                        row_codes[count] = ta[4]
                        out.append((ta[0], ta[1], ta[2], ob, count))
                        ia += 1
                        took_a = True
                    else:
                        if tb[3] != rb:
                            break
                        row_codes[count] = tb[4] - BLOCK
                        out.append((tb[0], tb[1], tb[2], ob, count))
                        ib += 1
                        took_b = True
                    count += 1
                arow.append(ra if took_a else max(rb, 0))
                brow.append(rb if took_b else max(ra, 0))
                codes.append(row_codes)
                nvalid.append(count)
                mode.append(0 if (took_a and took_b) else (1 if took_a else 2))
                ob += 1
            out_streams.append(out)
            # Materialize alignment gap rows so row ids stay physical
            # (nvalid 0: pure pads, contributing nothing).
            while ob != _align_up(ob, align_rows):
                arow.append(0)
                brow.append(0)
                codes.append(np.zeros(BLOCK, np.int8))
                nvalid.append(0)
                mode.append(0)
                ob += 1
        levels.append({
            "arow": np.asarray(arow, np.int32),
            "brow": np.asarray(brow, np.int32),
            "codes": (np.stack(codes) if codes
                      else np.zeros((0, BLOCK), np.int8)),
            "nvalid": np.asarray(nvalid, np.int32),
            "mode": np.asarray(mode, np.int8),
        })
        total_rows.append(ob)
        streams = out_streams

    final_items = [
        (d, r, p, row * BLOCK + lane) for d, r, p, row, lane in streams[0]
    ]
    return levels, final_items, total_rows


def simulate_grouped(runs, values, align_rows: int = 1):
    """Execute the copy-window network with the per-row kernel's exact
    semantics and return (final_stream, final_items).

    Asserts the device contract at every level: codes may only address
    lanes that hold reals (pads are never referenced, so intermediate
    pad lanes can stay garbage on device; only the root is masked by
    ``nvalid``), and the final stream is globally dst-sorted.
    """
    levels, final_items, total_rows = schedule_grouped(runs, align_rows)
    R = _tree_size(len(runs))

    cur = np.zeros((max(total_rows[0], 1), BLOCK), np.float64)
    valid = np.zeros_like(cur, bool)
    base = 0
    for r in range(R):
        a = runs[r] if r < len(runs) else ()
        for p in range(len(a)):
            cur[base + p // BLOCK, p % BLOCK] = values[r][p]
            valid[base + p // BLOCK, p % BLOCK] = True
        base = _align_up(base + (len(a) + BLOCK - 1) // BLOCK, align_rows)

    for k, lv in enumerate(levels):
        lane = lv["codes"].astype(np.int64) & 127
        is_a = lv["codes"] >= 0
        src_row = np.where(is_a, lv["arow"][:, None], lv["brow"][:, None])
        nxt = cur[src_row, lane]
        nvalid = lv["nvalid"]
        iota = np.arange(BLOCK)
        live = iota[None, :] < nvalid[:, None]
        # Contract: every live code addresses a real input lane.
        assert np.all(valid[src_row, lane][live]), (
            "grouped level references a pad lane", k + 1)
        nxt = np.where(live, nxt, 0.0)
        nrows = max(total_rows[k + 1], 1)
        cur = np.zeros((nrows, BLOCK), np.float64)
        cur[: nxt.shape[0]] = nxt
        valid = np.zeros_like(cur, bool)
        valid[: nxt.shape[0]] = live

    dsts = [d for d, _, _, _ in final_items]
    assert all(a <= b for a, b in zip(dsts, dsts[1:])), "dst order broken"
    return cur, final_items


def simulate(runs, values):
    """Execute the network in numpy with the DEVICE KERNEL's semantics
    and return the final stream (values at final slots, zeros at pads).

    ``values``: list of arrays aligned with ``runs`` (the per-real
    contribution values). Each level is applied exactly the way the
    pallas kernel would: output slot o of a node takes input slot
    64*(o//128) + k of side A (k = lane code) or of side B — here
    reconstructed from the per-level slot maps.
    """
    f, items = schedule(runs)
    slots = derive_level_slots(runs, f, items)
    R = _tree_size(len(runs))
    L = R.bit_length() - 1

    # Level-0 streams: one per leaf run (its input layout).
    cur = {}
    for r in range(R):
        cur[r] = np.zeros(BLOCK, np.float64)
    for (r, p), s in slots[0].items():
        if s >= cur[r].shape[0]:
            grow = ((s + BLOCK) // BLOCK) * BLOCK
            cur[r] = np.pad(cur[r], (0, grow - cur[r].shape[0]))
        cur[r][s] = values[r][p]

    # Apply levels: node n at level ℓ merges children 2n (A) and 2n+1
    # (B) of level ℓ-1. Every output slot reads ONE input slot of one
    # side, within the window — emulate via the slot maps.
    for lev in range(1, L + 1):
        nxt = {}
        for node in range(R >> lev):
            nxt[node] = np.zeros(BLOCK, np.float64)
        for (r, p), s in slots[lev].items():
            node = r >> lev
            side = (r >> (lev - 1)) & 1
            s_in = slots[lev - 1][(r, p)]
            # Kernel contract: out slot s reads side input slot s_in
            # with 64*(s//128) <= s_in < 64*(s//128) + 64.
            w = s // BLOCK
            assert WIN * w <= s_in < WIN * w + WIN, (
                "window violation", lev, r, p, s, s_in
            )
            child = 2 * node + side
            v = cur[child][s_in] if s_in < cur[child].shape[0] else 0.0
            if s >= nxt[node].shape[0]:
                grow = ((s + BLOCK) // BLOCK) * BLOCK
                nxt[node] = np.pad(nxt[node], (0, grow - nxt[node].shape[0]))
            nxt[node][s] = v
        cur = nxt
    return cur[0], f, items
