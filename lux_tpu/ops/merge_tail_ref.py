"""Reference (host, unoptimized) scheduler for the merge-network tail.

This is the round-4 groundwork for the source-block-grouped tail
(PERF.md "grouped-tail / merge-network design"): a correct, executable
specification of the routing construction, validated by simulation in
tests/test_merge_tail.py. It is NOT wired into any executor and is not
performance code — the real planner must vectorize the walk (34M reals
at RMAT22) and the device side uses the probed Pallas kernels
(tools/probe_merge_kernel.py).

Model
-----
R runs (R a power of two; empty runs pad the tree), each a dst-sorted
sequence of "reals". Levels ℓ = 1..L (L = log2 R) merge adjacent
subtrees: the side of run r at level ℓ is bit ℓ-1 of r, and the node
(subtree) containing it is r >> ℓ. One device pass per level: output
window w (one 128-lane row) of a node reads EXACTLY input slots
[64w, 64w+64) of each side — so a real's emission window at every
level is forced by its slot at the level below, and all slots derive
from its FINAL position:

    slot_L(x) = f(x)                                (root output slot)
    slot_{ℓ-1}(x) = 64 * (slot_ℓ(x) // 128) + rank of x among reals of
                    its (node, side) within that window   (must be < 64)

The construction is one forward walk over the global dst order,
placing reals at the next final slot whose implied per-(node, side)
window ranks all stay below 64; on overflow the final cursor advances
to the next 128-slot row (the skipped slots are the stall pads).
"""

from __future__ import annotations

import numpy as np

BLOCK = 128
WIN = 64
PAD = -1


def _tree_size(nruns: int) -> int:
    """Power-of-two tree width, minimum 2 so there is always at least
    one merge level (a single run still flows through level 1 paired
    with an empty sibling — L = 0 would schedule phantom levels with
    no nodes)."""
    R = 2
    while R < nruns:
        R *= 2
    return R


def schedule(runs):
    """Assign each real a final-stream position.

    ``runs``: list of dst-sorted 1-D int arrays (may be empty); length
    is padded to a power of two internally. Returns (f, order) where
    ``order`` lists reals as (dst, run, pos) triples in global merged
    dst order (ties by run index) and ``f[i]`` is the final slot of
    ``order[i]``.
    """
    R = _tree_size(len(runs))
    L = R.bit_length() - 1

    # Global merged order: (dst, run, pos)
    items = []
    for r, a in enumerate(runs):
        for p, d in enumerate(np.asarray(a)):
            items.append((int(d), r, p))
    items.sort()
    n = len(items)

    # Per (level, node, side) counters: rank within the current window,
    # plus the window id the counter belongs to.
    q = {}
    win = {}
    f = np.zeros(n, np.int64)
    t = 0                     # next candidate final slot
    for i, (_, r, p) in enumerate(items):
        while True:
            ok = True
            # Derive slots top-down at candidate position t.
            slots = {}
            s = t
            for lev in range(L, 0, -1):
                node = r >> lev
                side = (r >> (lev - 1)) & 1
                w = s // BLOCK
                key = (lev, node, side)
                if win.get(key) != w:
                    rank = 0
                else:
                    rank = q[key]
                if rank >= WIN:
                    ok = False
                    break
                slots[lev] = (key, w, rank)
                s = WIN * w + rank   # slot at level lev-1's output
            if ok:
                break
            t = (t // BLOCK + 1) * BLOCK   # stall: next output row
        # Commit.
        f[i] = t
        for lev, (key, w, rank) in slots.items():
            win[key] = w
            q[key] = rank + 1
        t += 1
    return f, items


def derive_level_slots(runs, f, items):
    """Recompute every real's slot at every level from its final
    position (the mechanical top-down derivation) and return
    per-level dicts {(run, pos): slot}."""
    R = _tree_size(len(runs))
    L = R.bit_length() - 1
    out = {lev: {} for lev in range(0, L + 1)}
    # rank bookkeeping identical to schedule()
    q = {}
    win = {}
    for i, (_, r, p) in enumerate(items):
        s = int(f[i])
        out[L][(r, p)] = s
        for lev in range(L, 0, -1):
            node = r >> lev
            side = (r >> (lev - 1)) & 1
            w = s // BLOCK
            key = (lev, node, side)
            if win.get(key) != w:
                q[key] = 0
                win[key] = w
            rank = q[key]
            q[key] = rank + 1
            s = WIN * w + rank
            out[lev - 1][(r, p)] = s
    return out


def simulate(runs, values):
    """Execute the network in numpy with the DEVICE KERNEL's semantics
    and return the final stream (values at final slots, zeros at pads).

    ``values``: list of arrays aligned with ``runs`` (the per-real
    contribution values). Each level is applied exactly the way the
    pallas kernel would: output slot o of a node takes input slot
    64*(o//128) + k of side A (k = lane code) or of side B — here
    reconstructed from the per-level slot maps.
    """
    f, items = schedule(runs)
    slots = derive_level_slots(runs, f, items)
    R = _tree_size(len(runs))
    L = R.bit_length() - 1

    # Level-0 streams: one per leaf run (its input layout).
    cur = {}
    for r in range(R):
        cur[r] = np.zeros(BLOCK, np.float64)
    for (r, p), s in slots[0].items():
        if s >= cur[r].shape[0]:
            grow = ((s + BLOCK) // BLOCK) * BLOCK
            cur[r] = np.pad(cur[r], (0, grow - cur[r].shape[0]))
        cur[r][s] = values[r][p]

    # Apply levels: node n at level ℓ merges children 2n (A) and 2n+1
    # (B) of level ℓ-1. Every output slot reads ONE input slot of one
    # side, within the window — emulate via the slot maps.
    for lev in range(1, L + 1):
        nxt = {}
        for node in range(R >> lev):
            nxt[node] = np.zeros(BLOCK, np.float64)
        for (r, p), s in slots[lev].items():
            node = r >> lev
            side = (r >> (lev - 1)) & 1
            s_in = slots[lev - 1][(r, p)]
            # Kernel contract: out slot s reads side input slot s_in
            # with 64*(s//128) <= s_in < 64*(s//128) + 64.
            w = s // BLOCK
            assert WIN * w <= s_in < WIN * w + WIN, (
                "window violation", lev, r, p, s, s_in
            )
            child = 2 * node + side
            v = cur[child][s_in] if s_in < cur[child].shape[0] else 0.0
            if s >= nxt[node].shape[0]:
                grow = ((s + BLOCK) // BLOCK) * BLOCK
                nxt[node] = np.pad(nxt[node], (0, grow - nxt[node].shape[0]))
            nxt[node][s] = v
        cur = nxt
    return cur[0], f, items
