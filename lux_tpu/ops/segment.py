"""Segment reductions over sorted CSC edge segments.

The reference performs per-destination reductions with block-cooperative
CUB ``BlockScan`` edge balancing plus ``atomicAdd/Min/Max`` into the
destination slot (pagerank/pagerank_gpu.cu:49-102, sssp/sssp_gpu.cu:48-61).
On TPU the same computation is a *segmented reduction* over edges sorted by
destination — which the CSC format already guarantees. XLA's
scatter-reduce (``jax.ops.segment_*``) is deterministic, unlike CUDA float
atomics: a free reproducibility improvement.

Two strategies:
- ``segment_reduce``: ``jax.ops.segment_{sum,min,max}`` with
  ``indices_are_sorted=True``;
- ``segment_sum_by_rowptr``: cumulative-sum + gather-diff. For sorted sum
  segments ``out[v] = S[end_v] - S[start_v]`` where S is the inclusive
  prefix sum — no scatter at all, purely dense ops (cumsum + two gathers),
  which maps well onto the TPU's VPU. Numerically this reassociates the
  sum; fine for the fixpoint workloads here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

COMBINER_IDENTITY = {
    "sum": 0,
    "min": np.inf,
    "max": -np.inf,
}

_SEGMENT_FNS = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def identity_for(kind: str, dtype) -> jnp.ndarray:
    """Combiner identity as a castable scalar for ``dtype``."""
    if kind == "sum":
        return jnp.zeros((), dtype)
    if kind == "min":
        return (
            jnp.array(jnp.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.array(jnp.iinfo(dtype).max, dtype)
        )
    if kind == "max":
        return (
            jnp.array(-jnp.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.array(jnp.iinfo(dtype).min, dtype)
        )
    raise ValueError(f"unknown combiner {kind!r}")


def segment_reduce(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    kind: str = "sum",
    indices_are_sorted: bool = True,
) -> jnp.ndarray:
    """Reduce ``data`` (edges-first, optional trailing dims) into
    ``num_segments`` destination slots. Empty segments get the combiner
    identity (min → dtype max for ints, +inf for floats)."""
    fn = _SEGMENT_FNS[kind]
    return fn(
        data,
        segment_ids,
        num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def take1d_blocked(z: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``z[idx]`` for huge 1-D ``z`` without scalar gathers.

    TPU scalar gathers run at ~8.5 ns/element (the VPU has no fine-grained
    HBM access) while aligned 128-lane *row* gathers stream at full HBM
    bandwidth (~0.9 ns/row, PERF.md). So: fetch the 128-block containing
    each element as a row, then select the lane with an on-the-fly one-hot
    — ~1.5 KB of streamed traffic per element instead of a ~4.4 KB-equiv
    scalarized access. Exact (pure selection). Chunked with a scan so the
    (len(idx), 128) gather/select intermediates stay bounded.

    Caveat: the gather table ``zz`` is the FULL (padded) ``z`` — tables
    past the ~48 MB gather cliff (ops.tiled_spmv.GATHER_TABLE_BYTES, e.g.
    the RMAT22 flat-path cumsum at ~268 MB) run row gathers ~4x
    off-rate. Still far faster than scalar gathers; the tiled executor's
    zstream_extract segments its tables and is the fast path at scale.
    """
    n = idx.shape[0]
    if n == 0:
        return z[:0]
    zz = jnp.pad(z, (0, (-z.shape[0]) % 128)).reshape(-1, 128)
    iota = jnp.arange(128, dtype=jnp.int32)
    cb = min(1 << 19, n)
    pad = (-n) % cb
    idx_c = jnp.pad(idx, (0, pad)).reshape(-1, cb)

    def body(_, ix):
        rows = zz[(ix >> 7).astype(jnp.int32)]       # (cb, 128) row gather
        lane = (ix & 127).astype(jnp.int32)
        sel = jnp.where(lane[:, None] == iota[None, :], rows, 0)
        return 0, sel.sum(axis=1)

    _, out = jax.lax.scan(body, 0, idx_c)
    return out.reshape(-1)[:n]


# Below this many gathered elements the plain scalar gather's fixed cost
# is noise and the blocked form's extra dense passes aren't worth it.
_BLOCKED_GATHER_MIN = 1 << 17


def segmented_minmax_scan(
    data: jnp.ndarray,
    seg_start: jnp.ndarray,
    kind: str,
) -> jnp.ndarray:
    """Running per-segment min/max over sorted segments, scatter-free.

    ``seg_start`` is a bool array marking the first element of each
    segment. Returns the inclusive segmented scan: position i holds the
    min/max of its segment's elements up to i — gather the last position
    of each segment for the per-segment reduction. Min/max have no
    inverse, so the cumsum-diff trick of :func:`segment_sum_by_rowptr`
    cannot apply; the classic (value, flag) segmented-scan operator is
    associative, so ``lax.associative_scan`` runs it in O(n) work /
    O(log n) depth, replacing XLA's scalar-rate scatter-extremum
    (measured ~45 ns/edge) with dense vector passes.
    """
    if kind == "min":
        pick = jnp.minimum
    elif kind == "max":
        pick = jnp.maximum
    else:
        raise ValueError(f"segmented_minmax_scan: unsupported kind {kind!r}")

    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, pick(av, bv)), af | bf

    # Two-level: associative_scan within fixed chunks under a lax.scan
    # carrying the (value, flag) pair across chunk boundaries. A single
    # associative_scan over the whole 67M-element stream compiles its
    # full log-depth decomposition into the graph (>20 min of XLA time
    # measured); per-chunk scans bound the compiled graph while the
    # runtime stays O(n).
    n = data.shape[0]
    if n == 0:
        return data
    chunk = min(1 << 17, max(n, 1))
    pad = (-n) % chunk
    ident = identity_for(kind, data.dtype)
    d = jnp.pad(data, (0, pad), constant_values=ident).reshape(-1, chunk)
    # Pad elements start their own segments so they cannot absorb carry.
    f = jnp.pad(seg_start, (0, pad), constant_values=True).reshape(-1, chunk)

    def body(cv, ch):
        dv, df = ch
        lv, lf = jax.lax.associative_scan(op, (dv, df), axis=0)
        # lf is the running "a segment started in this chunk at or
        # before here"; positions before the first local start combine
        # with the carry (last value of the previous chunk's stream).
        out = jnp.where(lf, lv, pick(cv, lv))
        return out[-1], out

    # Derive the identity carry FROM data (x*0 + ident) so that under
    # shard_map it inherits data's varying-axes metadata — a replicated
    # constant init trips the scan carry type check.
    init = d[0, 0] * jnp.asarray(0, data.dtype) + jnp.asarray(
        ident, data.dtype
    )
    _, out = jax.lax.scan(body, init, (d, f))
    return out.reshape(-1)[:n]


def segment_minmax_by_rowptr(
    data: jnp.ndarray,
    seg_start: jnp.ndarray,
    end_pos: jnp.ndarray,
    nonempty: jnp.ndarray,
    kind: str,
) -> jnp.ndarray:
    """Per-segment min/max for sorted segments with host-precomputed
    layout: ``seg_start`` (ne,) bool segment-start flags, ``end_pos``
    (nv,) int32 last-element positions (clipped for empty segments),
    ``nonempty`` (nv,) bool. Empty segments get the combiner identity.
    """
    scan = segmented_minmax_scan(data, seg_start, kind)
    ends = take1d_blocked(scan, end_pos)
    ident = identity_for(kind, data.dtype)
    return jnp.where(nonempty, ends, ident)


class BlockMinLayout:
    """Host-precomputed static layout for :func:`segment_minmax_blockmin`.

    For each destination segment [s, e) over a (padded) edge stream cut
    into 128-wide blocks:
    - head row  = the block containing s, lanes [s%128, s%128 + hlen);
    - tail row  = the block containing e-1, lanes [tfrom, tfrom + tlen);
      (for segments inside one block head and tail overlap — harmless,
      min/max are idempotent);
    - interior  = whole blocks fully inside the segment (only segments
      with >= 128ish edges have one), reduced via a block-level
      segmented scan: ``blk_flags`` marks each interior run's first
      block, ``int_end`` its last block, ``has_int`` whether v has one.
    ``segs`` optionally splits the head/tail row gathers into sub-cliff
    table slices (srow/erow are monotone in v because row_ptr is):
    tuples of (v_start, v_end, row_start, row_end).
    """

    def __init__(self, row_ptr: np.ndarray, ne_padded: int,
                 seg_rows: int = 0):
        rp = np.asarray(row_ptr, np.int64)
        nv = rp.shape[0] - 1
        s, e = rp[:-1], rp[1:]
        deg = e - s
        nb = ne_padded // 128
        self.nb = nb
        self.nv = nv
        # Empty segments still need in-range, v-MONOTONE row indices so
        # the static gather-table segmentation (searchsorted on srow /
        # erow) stays valid; their hlen/tlen are zeroed below so they
        # reduce to the identity regardless of what row they point at.
        empty = deg == 0
        s_c = np.minimum(s, max(ne_padded - 1, 0))
        e_c = np.maximum(e, s_c + 1)
        self.srow = (s_c // 128).astype(np.int32)
        self.erow = ((e_c - 1) // 128).astype(np.int32)
        self.smod = (s_c % 128).astype(np.int32)
        bl = -(-s_c // 128)          # first whole block
        br = e_c // 128              # one past last whole block
        self.hlen = np.minimum(e_c - s_c, bl * 128 - s_c).astype(np.int32)
        tfrom = np.maximum(br * 128, s_c)
        self.tfrom_mod = (tfrom - self.erow.astype(np.int64) * 128).astype(
            np.int32
        )
        self.tlen = (e_c - tfrom).astype(np.int32)
        self.hlen[empty] = 0
        self.tlen[empty] = 0
        has_int = (br > bl) & ~empty
        self.has_int = has_int
        flags = np.zeros(nb, bool)
        flags[bl[has_int]] = True
        self.blk_flags = flags
        self.int_end = np.where(has_int, br - 1, 0).astype(np.int32)
        # Static head/tail gather-table segmentation (v-monotone rows).
        if seg_rows and nb > seg_rows:
            bounds = []
            r0 = 0
            while r0 < nb:
                r1 = min(r0 + seg_rows, nb)
                v0 = int(np.searchsorted(self.srow, r0, side="left"))
                v1 = int(np.searchsorted(self.srow, r1, side="left"))
                bounds.append((v0, v1, r0, r1))
                r0 = r1
            self.head_segs = tuple(bounds)
            bounds = []
            r0 = 0
            while r0 < nb:
                r1 = min(r0 + seg_rows, nb)
                v0 = int(np.searchsorted(self.erow, r0, side="left"))
                v1 = int(np.searchsorted(self.erow, r1, side="left"))
                bounds.append((v0, v1, r0, r1))
                r0 = r1
            self.tail_segs = tuple(bounds)
        else:
            self.head_segs = self.tail_segs = ((0, nv, 0, nb),)

    def device_arrays(self):
        """The per-vertex/per-block arrays the jitted reduction needs (a
        dict so executors can device_put / shard-stack them)."""
        return {
            "bm_srow": self.srow, "bm_erow": self.erow,
            "bm_smod": self.smod, "bm_hlen": self.hlen,
            "bm_tfrom": self.tfrom_mod, "bm_tlen": self.tlen,
            "bm_flags": self.blk_flags, "bm_int_end": self.int_end,
            "bm_has_int": self.has_int,
        }


def _masked_row_reduce(d2, row_idx, lane_from, length, kind, segs):
    """Per-vertex reduce of d2[row_idx] over lanes [lane_from,
    lane_from+length), with the row gather split into static sub-cliff
    table slices (rows monotone in v)."""
    iota = jnp.arange(128, dtype=jnp.int32)
    ident = identity_for(kind, d2.dtype)
    outs = []
    for (v0, v1, r0, r1) in segs:
        if v1 <= v0:
            continue
        sl = jax.lax.slice(d2, (r0, 0), (r1, 128))
        rows = sl[jnp.clip(row_idx[v0:v1] - r0, 0, max(r1 - r0 - 1, 0))]
        lf = lane_from[v0:v1][:, None]
        m = (iota[None, :] >= lf) & (
            iota[None, :] < lf + length[v0:v1][:, None]
        )
        masked = jnp.where(m, rows, ident)
        outs.append(
            masked.min(axis=1) if kind == "min" else masked.max(axis=1)
        )
    if not outs:
        return jnp.full(row_idx.shape, ident, d2.dtype)
    return jnp.concatenate(outs)


def segment_minmax_blockmin(data, layout_arrays, head_segs, tail_segs,
                            kind: str):
    """Per-segment min/max via a 128-block hierarchy: one dense
    block-reduce pass + a 128x-smaller block-level segmented scan for
    interiors + masked head/tail row gathers.

    Replaces the edge-level (value, flag) associative scan
    (:func:`segmented_minmax_scan`, measured ~4 ns/edge on v5e — the
    scan's log-depth passes dominate) with ~1 pass of dense reduce plus
    O(nv) extraction. ``data`` must be padded to a 128 multiple with the
    combiner identity. ``layout_arrays`` is BlockMinLayout.device_arrays
    (possibly device-resident / shard-sliced); head/tail segs are the
    static table splits."""
    la = layout_arrays
    d2 = data.reshape(-1, 128)
    red_ax = (lambda a: a.min(axis=1)) if kind == "min" else (
        lambda a: a.max(axis=1)
    )
    m0 = red_ax(d2)
    scan = segmented_minmax_scan(m0, la["bm_flags"], kind)
    interior_all = take1d_blocked(scan, la["bm_int_end"])
    ident = identity_for(kind, data.dtype)
    interior = jnp.where(la["bm_has_int"], interior_all, ident)
    head = _masked_row_reduce(
        d2, la["bm_srow"], la["bm_smod"], la["bm_hlen"], kind, head_segs
    )
    tail = _masked_row_reduce(
        d2, la["bm_erow"], la["bm_tfrom"], la["bm_tlen"], kind, tail_segs
    )
    red = jnp.minimum if kind == "min" else jnp.maximum
    return red(red(head, tail), interior)


def segment_sum_by_rowptr(data: jnp.ndarray, row_ptr: jnp.ndarray) -> jnp.ndarray:
    """Sum sorted segments given CSC offsets, scatter-free.

    ``row_ptr`` is (nv+1,) with segment v spanning
    ``data[row_ptr[v]:row_ptr[v+1]]``. Returns (nv, *data.shape[1:]).
    """
    s = jnp.cumsum(data, axis=0, dtype=data.dtype)
    z = jnp.concatenate(
        [jnp.zeros((1,) + data.shape[1:], data.dtype), s], axis=0
    )
    # One (nv+1)-sized gather, then a dense diff — gathers are the scalar
    # bottleneck on TPU (~8.5 ns/elem), so don't do two of them; for big
    # 1-D inputs, do zero of them (blocked row-gather + lane select). The
    # gate is on len(row_ptr): that is what the gather cost scales with.
    if data.ndim == 1 and row_ptr.shape[0] >= _BLOCKED_GATHER_MIN:
        g = take1d_blocked(z, row_ptr)
    else:
        g = z[row_ptr]
    return g[1:] - g[:-1]


def csc_counting_merge(
    row_ptr: np.ndarray,
    col_src: np.ndarray,
    weights,
    keep: np.ndarray,
    ins_dst: np.ndarray,
    ins_src: np.ndarray,
    ins_w,
    nv: int,
):
    """Merge a kept subset of a CSC edge list with sorted inserts, host-side.

    One counting-sort pass instead of a full ``argsort`` over the merged
    edge list: per-destination survivor counts come from a prefix sum over
    ``keep``, insert counts from a ``bincount``, and every edge's final
    slot is a closed-form offset — kept edges keep their base-relative
    order within each destination segment, inserts (pre-sorted by
    ``(dst, src)``) land after them. O(ne + ni + nv) with no comparison
    sort, deterministic by construction.

    ``keep`` is a boolean mask over the base edges; ``ins_dst``/``ins_src``
    must be sorted by ``(dst, src)``. Returns
    ``(new_row_ptr int64 (nv+1,), new_col_src, new_weights|None)``.
    """
    ne = int(col_src.shape[0])
    ni = int(ins_dst.shape[0])
    if weights is None and ins_w is not None:
        raise ValueError("insert weights given for an unweighted base")
    if weights is not None and ni and ins_w is None:
        raise ValueError("weighted base requires insert weights")

    ex = np.zeros(ne + 1, dtype=np.int64)
    np.cumsum(keep, out=ex[1:])
    kept_per = ex[row_ptr[1:]] - ex[row_ptr[:-1]]
    ins_per = np.bincount(ins_dst, minlength=nv).astype(np.int64)

    new_rp = np.zeros(nv + 1, dtype=np.int64)
    np.cumsum(kept_per + ins_per, out=new_rp[1:])
    total = int(new_rp[-1])

    new_src = np.empty(total, dtype=col_src.dtype)
    has_w = weights is not None
    new_w = np.empty(total, dtype=weights.dtype) if has_w else None

    kept_e = np.nonzero(keep)[0]
    if kept_e.size:
        # Destination of each base edge, recovered from row_ptr without
        # materialising the full col_dst: searchsorted on the kept ids.
        dst_of = np.searchsorted(row_ptr, kept_e, side="right").astype(np.int64) - 1
        pos = new_rp[dst_of] + ex[kept_e] - ex[row_ptr[dst_of]]
        new_src[pos] = col_src[kept_e]
        if has_w:
            new_w[pos] = weights[kept_e]
    if ni:
        first = np.searchsorted(ins_dst, ins_dst)  # first index of each dst run
        rank = np.arange(ni, dtype=np.int64) - first
        d = ins_dst.astype(np.int64)
        pos_i = new_rp[d] + kept_per[d] + rank
        new_src[pos_i] = ins_src.astype(col_src.dtype)
        if has_w:
            new_w[pos_i] = ins_w
    return new_rp, new_src, new_w
