"""Segment reductions over sorted CSC edge segments.

The reference performs per-destination reductions with block-cooperative
CUB ``BlockScan`` edge balancing plus ``atomicAdd/Min/Max`` into the
destination slot (pagerank/pagerank_gpu.cu:49-102, sssp/sssp_gpu.cu:48-61).
On TPU the same computation is a *segmented reduction* over edges sorted by
destination — which the CSC format already guarantees. XLA's
scatter-reduce (``jax.ops.segment_*``) is deterministic, unlike CUDA float
atomics: a free reproducibility improvement.

Two strategies:
- ``segment_reduce``: ``jax.ops.segment_{sum,min,max}`` with
  ``indices_are_sorted=True``;
- ``segment_sum_by_rowptr``: cumulative-sum + gather-diff. For sorted sum
  segments ``out[v] = S[end_v] - S[start_v]`` where S is the inclusive
  prefix sum — no scatter at all, purely dense ops (cumsum + two gathers),
  which maps well onto the TPU's VPU. Numerically this reassociates the
  sum; fine for the fixpoint workloads here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

COMBINER_IDENTITY = {
    "sum": 0,
    "min": np.inf,
    "max": -np.inf,
}

_SEGMENT_FNS = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def identity_for(kind: str, dtype) -> jnp.ndarray:
    """Combiner identity as a castable scalar for ``dtype``."""
    if kind == "sum":
        return jnp.zeros((), dtype)
    if kind == "min":
        return (
            jnp.array(jnp.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.array(jnp.iinfo(dtype).max, dtype)
        )
    if kind == "max":
        return (
            jnp.array(-jnp.inf, dtype)
            if jnp.issubdtype(dtype, jnp.floating)
            else jnp.array(jnp.iinfo(dtype).min, dtype)
        )
    raise ValueError(f"unknown combiner {kind!r}")


def segment_reduce(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    kind: str = "sum",
    indices_are_sorted: bool = True,
) -> jnp.ndarray:
    """Reduce ``data`` (edges-first, optional trailing dims) into
    ``num_segments`` destination slots. Empty segments get the combiner
    identity (min → dtype max for ints, +inf for floats)."""
    fn = _SEGMENT_FNS[kind]
    return fn(
        data,
        segment_ids,
        num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def take1d_blocked(z: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``z[idx]`` for huge 1-D ``z`` without scalar gathers.

    TPU scalar gathers run at ~8.5 ns/element (the VPU has no fine-grained
    HBM access) while aligned 128-lane *row* gathers stream at full HBM
    bandwidth (~0.9 ns/row, PERF.md). So: fetch the 128-block containing
    each element as a row, then select the lane with an on-the-fly one-hot
    — ~1.5 KB of streamed traffic per element instead of a ~4.4 KB-equiv
    scalarized access. Exact (pure selection). Chunked with a scan so the
    (len(idx), 128) gather/select intermediates stay bounded.

    Caveat: the gather table ``zz`` is the FULL (padded) ``z`` — tables
    past the ~48 MB gather cliff (ops.tiled_spmv.GATHER_TABLE_BYTES, e.g.
    the RMAT22 flat-path cumsum at ~268 MB) run row gathers ~4x
    off-rate. Still far faster than scalar gathers; the tiled executor's
    zstream_extract segments its tables and is the fast path at scale.
    """
    n = idx.shape[0]
    if n == 0:
        return z[:0]
    zz = jnp.pad(z, (0, (-z.shape[0]) % 128)).reshape(-1, 128)
    iota = jnp.arange(128, dtype=jnp.int32)
    cb = min(1 << 19, n)
    pad = (-n) % cb
    idx_c = jnp.pad(idx, (0, pad)).reshape(-1, cb)

    def body(_, ix):
        rows = zz[(ix >> 7).astype(jnp.int32)]       # (cb, 128) row gather
        lane = (ix & 127).astype(jnp.int32)
        sel = jnp.where(lane[:, None] == iota[None, :], rows, 0)
        return 0, sel.sum(axis=1)

    _, out = jax.lax.scan(body, 0, idx_c)
    return out.reshape(-1)[:n]


# Below this many gathered elements the plain scalar gather's fixed cost
# is noise and the blocked form's extra dense passes aren't worth it.
_BLOCKED_GATHER_MIN = 1 << 17


def segmented_minmax_scan(
    data: jnp.ndarray,
    seg_start: jnp.ndarray,
    kind: str,
) -> jnp.ndarray:
    """Running per-segment min/max over sorted segments, scatter-free.

    ``seg_start`` is a bool array marking the first element of each
    segment. Returns the inclusive segmented scan: position i holds the
    min/max of its segment's elements up to i — gather the last position
    of each segment for the per-segment reduction. Min/max have no
    inverse, so the cumsum-diff trick of :func:`segment_sum_by_rowptr`
    cannot apply; the classic (value, flag) segmented-scan operator is
    associative, so ``lax.associative_scan`` runs it in O(n) work /
    O(log n) depth, replacing XLA's scalar-rate scatter-extremum
    (measured ~45 ns/edge) with dense vector passes.
    """
    if kind == "min":
        pick = jnp.minimum
    elif kind == "max":
        pick = jnp.maximum
    else:
        raise ValueError(f"segmented_minmax_scan: unsupported kind {kind!r}")

    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, pick(av, bv)), af | bf

    # Two-level: associative_scan within fixed chunks under a lax.scan
    # carrying the (value, flag) pair across chunk boundaries. A single
    # associative_scan over the whole 67M-element stream compiles its
    # full log-depth decomposition into the graph (>20 min of XLA time
    # measured); per-chunk scans bound the compiled graph while the
    # runtime stays O(n).
    n = data.shape[0]
    if n == 0:
        return data
    chunk = min(1 << 17, max(n, 1))
    pad = (-n) % chunk
    ident = identity_for(kind, data.dtype)
    d = jnp.pad(data, (0, pad), constant_values=ident).reshape(-1, chunk)
    # Pad elements start their own segments so they cannot absorb carry.
    f = jnp.pad(seg_start, (0, pad), constant_values=True).reshape(-1, chunk)

    def body(cv, ch):
        dv, df = ch
        lv, lf = jax.lax.associative_scan(op, (dv, df), axis=0)
        # lf is the running "a segment started in this chunk at or
        # before here"; positions before the first local start combine
        # with the carry (last value of the previous chunk's stream).
        out = jnp.where(lf, lv, pick(cv, lv))
        return out[-1], out

    # Derive the identity carry FROM data (x*0 + ident) so that under
    # shard_map it inherits data's varying-axes metadata — a replicated
    # constant init trips the scan carry type check.
    init = d[0, 0] * jnp.asarray(0, data.dtype) + jnp.asarray(
        ident, data.dtype
    )
    _, out = jax.lax.scan(body, init, (d, f))
    return out.reshape(-1)[:n]


def segment_minmax_by_rowptr(
    data: jnp.ndarray,
    seg_start: jnp.ndarray,
    end_pos: jnp.ndarray,
    nonempty: jnp.ndarray,
    kind: str,
) -> jnp.ndarray:
    """Per-segment min/max for sorted segments with host-precomputed
    layout: ``seg_start`` (ne,) bool segment-start flags, ``end_pos``
    (nv,) int32 last-element positions (clipped for empty segments),
    ``nonempty`` (nv,) bool. Empty segments get the combiner identity.
    """
    scan = segmented_minmax_scan(data, seg_start, kind)
    ends = take1d_blocked(scan, end_pos)
    ident = identity_for(kind, data.dtype)
    return jnp.where(nonempty, ends, ident)


def segment_sum_by_rowptr(data: jnp.ndarray, row_ptr: jnp.ndarray) -> jnp.ndarray:
    """Sum sorted segments given CSC offsets, scatter-free.

    ``row_ptr`` is (nv+1,) with segment v spanning
    ``data[row_ptr[v]:row_ptr[v+1]]``. Returns (nv, *data.shape[1:]).
    """
    s = jnp.cumsum(data, axis=0, dtype=data.dtype)
    z = jnp.concatenate(
        [jnp.zeros((1,) + data.shape[1:], data.dtype), s], axis=0
    )
    # One (nv+1)-sized gather, then a dense diff — gathers are the scalar
    # bottleneck on TPU (~8.5 ns/elem), so don't do two of them; for big
    # 1-D inputs, do zero of them (blocked row-gather + lane select). The
    # gate is on len(row_ptr): that is what the gather cost scales with.
    if data.ndim == 1 and row_ptr.shape[0] >= _BLOCKED_GATHER_MIN:
        g = take1d_blocked(z, row_ptr)
    else:
        g = z[row_ptr]
    return g[1:] - g[:-1]
