"""Hybrid SpMV: MXU strip-tiles + a lane-select tail (no scalar gathers).

The pull engine's hot loop is ``acc[dst] = Σ vals[src]`` over a static
graph (the reference's ``pr_kernel`` gather, pagerank/pagerank_gpu.cu:49-102).
Measured TPU v5e rates dictate the design:

- arbitrary 1-element gather: ~8.5 ns/edge (scalarized — the TPU VPU has
  no fine-grained HBM access; this is the reference's atomicAdd/gather
  world and the thing to design away);
- 128-wide **row** gather: ~0.9 ns/row (~540 GB/s — full bandwidth);
- int8 strip matmul: streams at ~520 GB/s through the MXU.

So the only fast irregular primitive is "fetch an aligned 128-block".
Every edge is served by one of two such layouts:

1. **Strip levels** (:class:`StripLevel`): after degree-sort relabeling,
   hub-hub edges concentrate in (R,128) blocks of the adjacency matrix
   (R | 128). Each dense-enough strip is stored as an (R,128) int8 count
   matrix (multi-edges collapse into counts; cells overflowing 127 spill
   the excess to the tail, so the edge partition stays exact) and costs
   one row gather of the source block + one batched (R,128)@(128,2)
   bf16 matmul — the 2 columns are a hi/lo bf16 split of the f32
   operand, keeping ~16 mantissa bits at no extra strip bandwidth.
   A strip of R·128 int8 bytes breaks even vs. per-edge work at about
   R/3 edges (R=8 → ≥3 edges).

2. **Lane-select tail**: a leftover edge costs one 128-wide row gather
   of its source block plus an on-the-fly one-hot lane selection
   (``where(lane == iota, row, 0).sum()``) — pure VPU, *exact* f32, and
   ~512 HBM bytes/edge instead of the 4.4 KB-equivalent of a scalar
   gather. Edges stay CSC-sorted so the per-destination reduction is
   the scatter-free cumsum/row-ptr-diff.

This layout has no reference counterpart — it is what "gather" means on
hardware whose only irregular-access engines are aligned block DMA and
a 128x128 systolic array.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.graph.graph import Graph
from lux_tpu.ops.segment import segment_sum_by_rowptr

BLOCK = 128


# ---------------------------------------------------------------------------
# Host-side planning
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class StripLevel:
    """Dense (r, 128) int8 count strips at one granularity."""

    r: int
    strips: np.ndarray       # (T, r, 128) int8
    rows: np.ndarray         # (T,) int32 dst strip index (sorted ascending)
    cols: np.ndarray         # (T,) int32 src 128-block index

    @property
    def nbytes(self) -> int:
        return self.strips.nbytes

    @property
    def edges(self) -> int:
        return int(self.strips.astype(np.int64).sum())


@dataclasses.dataclass(eq=False)
class HybridPlan:
    """Host-side product of :func:`plan_hybrid` (numpy, internal ids).

    "Internal" vertex ids are positions in the degree-sorted order:
    ``order[p]`` is the external id at internal position p and
    ``rank[v]`` the internal position of external vertex v.
    """

    nv: int
    nvb: int                 # number of 128-blocks (nv padded)
    order: np.ndarray        # (nv,) int32
    rank: np.ndarray         # (nv,) int32
    levels: Tuple[StripLevel, ...]
    tail_sb: np.ndarray      # (M,) int32 src >> 7, CSC (dst-sorted) order
    tail_lane: np.ndarray    # (M,) int8  src & 127
    tail_row_ptr: np.ndarray  # (nv+1,) int64
    out_degrees: np.ndarray  # (nv,) int64, internal order
    in_degrees: np.ndarray   # (nv,) int64, internal order

    @property
    def num_strips(self) -> int:
        return sum(lev.rows.shape[0] for lev in self.levels)

    @property
    def strip_bytes(self) -> int:
        return sum(lev.nbytes for lev in self.levels)

    @property
    def coverage(self) -> float:
        total = self.tail_sb.shape[0] + sum(lev.edges for lev in self.levels)
        return 1.0 - self.tail_sb.shape[0] / max(total, 1)


def _relabel(graph: Graph, reorder: str):
    nv = graph.nv
    if reorder == "degree":
        deg = graph.in_degrees + graph.out_degrees
        order = np.argsort(-deg, kind="stable").astype(np.int32)
    elif reorder == "natural":
        order = np.arange(nv, dtype=np.int32)
    else:
        raise ValueError(f"unknown reorder {reorder!r}")
    rank = np.empty(nv, np.int32)
    rank[order] = np.arange(nv, dtype=np.int32)
    return order, rank


def plan_hybrid(
    graph: Graph,
    levels: Sequence[Tuple[int, int]] = ((8, 4),),
    budget_bytes: int = 6 << 30,
    reorder: str = "degree",
) -> HybridPlan:
    """Partition edges into strip levels + a lane-select tail. Exact.

    ``levels`` is a sequence of ``(r, min_count)`` pairs, consumed in
    order: each level takes the strips (at granularity r x 128) holding
    at least ``min_count`` still-unassigned edges, densest first, within
    what remains of ``budget_bytes``.
    """
    nv = graph.nv
    nvb = (nv + BLOCK - 1) // BLOCK
    order, rank = _relabel(graph, reorder)

    s = rank[graph.col_src].astype(np.int64)
    d = rank[graph.col_dst].astype(np.int64)
    built = []
    remaining = budget_bytes

    for r, min_count in levels:
        if BLOCK % r:
            raise ValueError(f"strip height {r} must divide {BLOCK}")
        if s.size == 0 or remaining <= 0:
            built.append(StripLevel(
                r=r,
                strips=np.zeros((0, r, BLOCK), np.int8),
                rows=np.zeros(0, np.int32),
                cols=np.zeros(0, np.int32),
            ))
            continue
        strip_bytes = r * BLOCK
        strip_id = (d // r) * nvb + (s >> 7)
        uniq_ids, counts = np.unique(strip_id, return_counts=True)
        take = np.argsort(-counts, kind="stable")[: max(remaining // strip_bytes, 0)]
        take = take[counts[take] >= min_count]
        chosen = np.sort(uniq_ids[take])
        slot = np.searchsorted(chosen, strip_id)
        covered = slot < len(chosen)
        if len(chosen):
            covered &= np.equal(
                chosen[np.minimum(slot, len(chosen) - 1)], strip_id
            )

        cell = (d % r) * BLOCK + (s & 127)
        key = slot[covered] * strip_bytes + cell[covered]
        uk, kc = np.unique(key, return_counts=True)
        strips = np.zeros((len(chosen), strip_bytes), np.int8)
        if len(uk):
            strips.ravel()[uk] = np.minimum(kc, 127).astype(np.int8)

        # int8 overflow (>127 parallel edges in one cell): keep the excess.
        spill_s = spill_d = np.empty(0, np.int64)
        over = kc > 127
        if over.any():
            reps = (kc[over] - 127).astype(np.int64)
            ok = uk[over]
            sid = chosen[ok // strip_bytes]
            c = ok % strip_bytes
            spill_d = np.repeat((sid // nvb) * r + c // BLOCK, reps)
            spill_s = np.repeat((sid % nvb) * BLOCK + (c & 127), reps)

        built.append(StripLevel(
            r=r,
            strips=strips.reshape(-1, r, BLOCK),
            rows=(chosen // nvb).astype(np.int32),
            cols=(chosen % nvb).astype(np.int32),
        ))
        remaining -= strips.nbytes
        s = np.concatenate([s[~covered], spill_s])
        d = np.concatenate([d[~covered], spill_d])

    tsort = np.lexsort((s, d))
    s, d = s[tsort], d[tsort]
    tail_row_ptr = np.zeros(nv + 1, np.int64)
    np.cumsum(np.bincount(d, minlength=nv), out=tail_row_ptr[1:])

    return HybridPlan(
        nv=nv,
        nvb=nvb,
        order=order,
        rank=rank,
        levels=tuple(built),
        tail_sb=(s >> 7).astype(np.int32),
        tail_lane=(s & 127).astype(np.int8),
        tail_row_ptr=tail_row_ptr,
        out_degrees=graph.out_degrees[order],
        in_degrees=graph.in_degrees[order],
    )


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DeviceLevel:
    """One strip level on device, chunked for lax.scan (pad strips are
    zero-count → contribute nothing; pad rows use the max strip index so
    per-chunk segment ids stay sorted)."""

    r: int
    strips: jnp.ndarray     # (nchunks, C, r, 128) int8
    rows: jnp.ndarray       # (nchunks, C) int32
    cols: jnp.ndarray       # (nchunks, C) int32


@dataclasses.dataclass
class DeviceHybrid:
    levels: Tuple[DeviceLevel, ...]
    tail_sb: jnp.ndarray        # (nchunks, C) int32 (padded with 0)
    tail_lane: jnp.ndarray      # (nchunks, C) int8
    nvb: int

    @staticmethod
    def build(
        plan: HybridPlan,
        chunk_strips: int = 16384,
        chunk_tail: int = 1 << 19,
        device=None,
    ) -> "DeviceHybrid":
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        nrb_max = lambda r: plan.nvb * (BLOCK // r) - 1

        dlevels = []
        for lev in plan.levels:
            n = lev.rows.shape[0]
            if n == 0:
                dlevels.append(DeviceLevel(
                    r=lev.r,
                    strips=put(np.zeros((0, 1, lev.r, BLOCK), np.int8)),
                    rows=put(np.zeros((0, 1), np.int32)),
                    cols=put(np.zeros((0, 1), np.int32)),
                ))
                continue
            c = min(chunk_strips, n)
            pad = (-n) % c
            st = np.concatenate(
                [lev.strips, np.zeros((pad, lev.r, BLOCK), np.int8)]
            )
            ro = np.concatenate(
                [lev.rows, np.full(pad, nrb_max(lev.r), np.int32)]
            )
            co = np.concatenate([lev.cols, np.zeros(pad, np.int32)])
            k = st.shape[0] // c
            dlevels.append(DeviceLevel(
                r=lev.r,
                strips=put(st.reshape(k, c, lev.r, BLOCK)),
                rows=put(ro.reshape(k, c)),
                cols=put(co.reshape(k, c)),
            ))

        m = plan.tail_sb.shape[0]
        if m == 0:
            sb = np.zeros((0, 1), np.int32)
            lane = np.zeros((0, 1), np.int8)
        else:
            c = min(chunk_tail, m)
            pad = (-m) % c
            sb = np.concatenate([plan.tail_sb, np.zeros(pad, np.int32)])
            lane = np.concatenate([plan.tail_lane, np.zeros(pad, np.int8)])
            sb = sb.reshape(-1, c)
            lane = lane.reshape(-1, c)
        return DeviceHybrid(
            levels=tuple(dlevels),
            tail_sb=put(sb),
            tail_lane=put(lane),
            nvb=plan.nvb,
        )


def _hi_lo_split(x2d: jnp.ndarray):
    """f32 -> two bf16 planes; hi + lo carries ~16 mantissa bits."""
    hi = x2d.astype(jnp.bfloat16)
    lo = (x2d - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def strip_level_spmv(xin: jnp.ndarray, lev: DeviceLevel, nrb: int) -> jnp.ndarray:
    """Σ strip @ x_block per destination row; returns (nrb*r,) f32.

    ``xin`` is the (nvb, 128, 2) hi/lo bf16 operand; ``nrb`` is the number
    of destination strip rows covered (``lev.cols`` may index all of
    ``xin`` while ``lev.rows`` spans only a local destination range, which
    is how the sharded executor reuses this kernel per shard).
    """

    def body(acc, chunk):
        strips, rows, cols = chunk
        xb = xin[cols]                                  # (C, 128, 2) row gather
        prod = jnp.einsum(
            "trj,tjk->trk",
            strips.astype(jnp.bfloat16),
            xb,
            preferred_element_type=jnp.float32,
        )                                               # (C, r, 2)
        contrib = prod[..., 0] + prod[..., 1]           # (C, r) f32
        acc = acc + jax.ops.segment_sum(
            contrib, rows, num_segments=nrb, indices_are_sorted=True
        )
        return acc, None

    acc0 = jnp.zeros((nrb, lev.r), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (lev.strips, lev.rows, lev.cols))
    return acc.reshape(-1)


def lane_select_tail(
    x2d: jnp.ndarray, tail_sb: jnp.ndarray, tail_lane: jnp.ndarray
) -> jnp.ndarray:
    """Per-tail-edge source values via row gather + one-hot lane select.

    Exact f32 (pure selection). ``tail_sb``/``tail_lane`` are the
    (nchunks, C) chunked edge arrays. Returns (M_padded,) in CSC order;
    pad entries past the real tail length are garbage the caller's
    row-ptr (whose last entry is the real length) never reads.
    """
    iota = jnp.arange(BLOCK, dtype=jnp.int32)

    def body(_, chunk):
        sb, lane = chunk
        rows = x2d[sb]                                  # (C, 128) row gather
        sel = jnp.where(
            lane.astype(jnp.int32)[:, None] == iota[None, :], rows, 0.0
        )
        return 0, sel.sum(axis=1)

    _, ys = jax.lax.scan(body, 0, (tail_sb, tail_lane))
    return ys.reshape(-1)


def hybrid_spmv(vals: jnp.ndarray, dh: DeviceHybrid, tail_row_ptr) -> jnp.ndarray:
    """Full Σ vals[src] per destination over all layouts; (nv,) f32 in,
    (nv,) f32 out (internal vertex order)."""
    nv = vals.shape[0]
    pad = dh.nvb * BLOCK - nv
    x2d = jnp.pad(vals, (0, pad)).reshape(dh.nvb, BLOCK)
    hi, lo = _hi_lo_split(x2d)
    xin = jnp.stack([hi, lo], axis=-1)                  # (nvb, 128, 2)

    acc = jnp.zeros(dh.nvb * BLOCK, jnp.float32)
    for lev in dh.levels:
        acc = acc + strip_level_spmv(xin, lev, dh.nvb * (BLOCK // lev.r))
    acc = acc[:nv]

    tail_vals = lane_select_tail(x2d, dh.tail_sb, dh.tail_lane)
    acc = acc + segment_sum_by_rowptr(tail_vals, tail_row_ptr)
    return acc


for _cls, _data, _meta in (
    (DeviceLevel, ["strips", "rows", "cols"], ["r"]),
    (DeviceHybrid, ["levels", "tail_sb", "tail_lane"], ["nvb"]),
):
    jax.tree_util.register_dataclass(_cls, data_fields=_data, meta_fields=_meta)
