"""Hybrid SpMV: MXU strip-tiles + a lane-select tail (no scalar gathers).

The pull engine's hot loop is ``acc[dst] = Σ vals[src]`` over a static
graph (the reference's ``pr_kernel`` gather, pagerank/pagerank_gpu.cu:49-102).
Measured TPU v5e rates dictate the design:

- arbitrary 1-element gather: ~8.5 ns/edge (scalarized — the TPU VPU has
  no fine-grained HBM access; this is the reference's atomicAdd/gather
  world and the thing to design away);
- 128-wide **row** gather: ~0.9 ns/row (~540 GB/s — full bandwidth);
- int8 strip matmul: streams at ~520 GB/s through the MXU.

So the only fast irregular primitive is "fetch an aligned 128-block".
Every edge is served by one of two such layouts:

1. **Strip levels** (:class:`StripLevel`): after degree-sort relabeling,
   hub-hub edges concentrate in (R,128) blocks of the adjacency matrix
   (R | 128). Each dense-enough strip is stored as an (R,128) int8 count
   matrix (multi-edges collapse into counts; cells overflowing 127 spill
   the excess to the tail, so the edge partition stays exact) and costs
   one row gather of the source block + an f32 broadcast-multiply-reduce
   on the VPU (measured 3x faster than the equivalent (R,128)@(128,2)
   bf16 MXU matmul, whose 2-column output tile starves the systolic
   array — and exact f32 per product instead of a hi/lo bf16 split).
   A strip of R·128 int8 bytes breaks even vs. per-edge work at about
   R/3 edges (R=8 → ≥3 edges).
   Per-destination reduction of strip contributions uses NO scatter:
   strips are sorted by destination strip-row, so each row's strips are
   a contiguous range with *plan-time-constant* boundaries; transposed
   Z-stream cumsums plus a static boundary gather-diff (see the layout
   notes above :func:`zstream_boundaries`) replace the 8-wide scatter
   rows of ``jax.ops.segment_sum`` that ran at scalar rate
   (measured 117 ms -> ~3 ms on RMAT22).

2. **Lane-select tail**: a leftover edge costs one 128-wide row gather
   of its source block plus an on-the-fly one-hot lane selection
   (``where(lane == iota, row, 0).sum()``) — pure VPU, *exact* f32, and
   ~512 HBM bytes/edge instead of the 4.4 KB-equivalent of a scalar
   gather. Edges stay CSC-sorted so the per-destination reduction is
   the scatter-free Z-stream boundary diff at the static
   ``tail_row_ptr`` boundaries.

This layout has no reference counterpart — it is what "gather" means on
hardware whose only irregular-access engines are aligned block DMA and
a 128x128 systolic array.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.graph.graph import Graph

BLOCK = 128
# Scan-chunk default for the tail body: measured sweet spot on v5e
# (PERF.md chunk sweep — ~10% faster than 2^19; smaller chunks pipeline
# the gathers better).
DEFAULT_CHUNK_TAIL = 1 << 17
# Strip scan chunk default: strips prefer LARGER chunks than the tail
# (measured sweep: 13.6 ms at 2^15 vs 15.9 at 2^14 vs 31 at 2^11 on the
# RMAT22 (8,4) level; above 2^15 it drifts back up).
DEFAULT_CHUNK_STRIPS = 1 << 15


# ---------------------------------------------------------------------------
# Host-side planning
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class StripLevel:
    """Dense (r, 128) int8 count strips at one granularity."""

    r: int
    strips: np.ndarray       # (T, r, 128) int8
    rows: np.ndarray         # (T,) int32 dst strip index (sorted ascending)
    cols: np.ndarray         # (T,) int32 src 128-block index
    # Cached Σ strips so plan validation against graph.ne does not force
    # a full read of a (possibly mmap'd multi-GB) strip array.
    _edges: int = -1

    @property
    def nbytes(self) -> int:
        return self.strips.nbytes

    @property
    def edges(self) -> int:
        if self._edges < 0:
            self._edges = int(self.strips.sum(dtype=np.int64))
        return self._edges


@dataclasses.dataclass(eq=False)
class HybridPlan:
    """Host-side product of :func:`plan_hybrid` (numpy, internal ids).

    "Internal" vertex ids are positions in the degree-sorted order:
    ``order[p]`` is the external id at internal position p and
    ``rank[v]`` the internal position of external vertex v.
    """

    nv: int
    nvb: int                 # number of 128-blocks (nv padded)
    order: np.ndarray        # (nv,) int32
    rank: np.ndarray         # (nv,) int32
    levels: Tuple[StripLevel, ...]
    tail_sb: np.ndarray      # (M,) int32 src >> 7, CSC (dst-sorted) order
    tail_lane: np.ndarray    # (M,) int8  src & 127
    tail_row_ptr: np.ndarray  # (nv+1,) int64
    out_degrees: np.ndarray  # (nv,) int64, internal order
    in_degrees: np.ndarray   # (nv,) int64, internal order
    # Per-cell count cap used at plan time (excess spilled to the tail).
    # cap <= 15 makes every even-r level nibble-packable on device
    # (two strip rows per int8 byte — see pack_strips); legacy plans
    # used 127 and stay unpacked.
    cap: int = 15
    # Planning config, kept so plan caches can detect a changed request
    # (same r-cascade, different thresholds/budget). None/-1 on legacy
    # caches that predate these fields — treated as "unknown, servable".
    levels_spec: Optional[Tuple[Tuple[int, int], ...]] = None
    budget_bytes: int = -1

    @property
    def num_strips(self) -> int:
        return sum(lev.rows.shape[0] for lev in self.levels)

    @property
    def strip_bytes(self) -> int:
        return sum(lev.nbytes for lev in self.levels)

    @property
    def total_edges(self) -> int:
        return self.tail_sb.shape[0] + sum(lev.edges for lev in self.levels)

    @property
    def coverage(self) -> float:
        return 1.0 - self.tail_sb.shape[0] / max(self.total_edges, 1)


def _relabel(graph: Graph, reorder: str):
    nv = graph.nv
    if reorder == "degree":
        deg = graph.in_degrees + graph.out_degrees
        order = np.argsort(-deg, kind="stable").astype(np.int32)
    elif reorder == "natural":
        order = np.arange(nv, dtype=np.int32)
    else:
        raise ValueError(f"unknown reorder {reorder!r}")
    rank = np.empty(nv, np.int32)
    rank[order] = np.arange(nv, dtype=np.int32)
    return order, rank


# Edge-stream chunk for the banded planner passes (edges per chunk);
# per-chunk temporaries are a few int64/int32 arrays of this length.
_PLAN_CHUNK = 1 << 27
# The banded (streamed) counting path turns on above this edge count;
# below it the direct in-memory path is faster and simpler. Both are
# exact and produce identical plans (tested), so the threshold is a
# pure memory/speed trade.
_PLAN_BANDED_MIN_NE = 1 << 28


def _strip_counts_banded(graph: Graph, rank, r: int, nvb: int,
                         min_count: int, chunk: int = _PLAN_CHUNK):
    """(uniq strip ids, counts) for level 0, streamed in edge chunks.

    Exactly the multiset ``np.unique((d//r)*nvb + (s>>7), counts)``
    restricted to counts >= min_count, but without materializing any
    global int64 per-edge array: the direct form peaks at ~5x 8-byte
    edge arrays (OOM at RMAT27's 2^31 edges on a 133 GB host,
    VERDICT.md weak #4). Strategy: bucket each edge's src-block into
    band-grouped storage (one int32 edge array; the degree relabel
    destroys the CSC dst order, so grouping needs an explicit
    out-of-core pass), then run-length count per band range.

    Dropping counts < min_count here is selection-equivalent to the
    direct path's select-then-filter: strips below min_count can never
    be chosen, and stable tie order among survivors is preserved.

    Bound caveat: the counting batches take whole bands, so a single
    band holding more than ``chunk`` edges is processed in one piece
    (temporaries ~3x its size in int64). After the degree relabel the
    hottest dst rows share band 0; at RMAT27 the top-8 in-degrees sum
    to tens of millions of edges — well under the 2^27 default — so
    this stays a documented caveat, not a practical limit.
    """
    nv, ne = graph.nv, graph.ne
    nbands = (nv + r - 1) // r
    cs, cd = graph.col_src, graph.col_dst

    band_counts = np.zeros(nbands, np.int64)
    for lo in range(0, ne, chunk):
        b = rank[cd[lo:lo + chunk]] // r
        band_counts += np.bincount(b, minlength=nbands)
    band_off = np.zeros(nbands + 1, np.int64)
    np.cumsum(band_counts, out=band_off[1:])

    sblk_by_band = np.empty(ne, np.int32)
    fill = band_off[:-1].copy()
    for lo in range(0, ne, chunk):
        b = rank[cd[lo:lo + chunk]] // r
        sb = (rank[cs[lo:lo + chunk]] >> 7).astype(np.int32)
        idx = np.argsort(b, kind="stable")
        bs = b[idx]
        run_start = np.concatenate(
            [[0], np.flatnonzero(np.diff(bs)) + 1]
        ).astype(np.int64)
        run_len = np.diff(np.append(run_start, len(bs)))
        within = np.arange(len(bs), dtype=np.int64) - np.repeat(
            run_start, run_len
        )
        sblk_by_band[fill[bs] + within] = sb[idx]
        fill[bs[run_start]] += run_len

    uniq_parts, count_parts = [], []
    b_lo = 0
    while b_lo < nbands:
        b_hi = int(
            np.searchsorted(band_off, band_off[b_lo] + chunk, side="right")
        ) - 1
        b_hi = min(max(b_hi, b_lo + 1), nbands)
        e0, e1 = int(band_off[b_lo]), int(band_off[b_hi])
        if e1 > e0:
            band_of_edge = np.repeat(
                np.arange(b_lo, b_hi, dtype=np.int64),
                band_counts[b_lo:b_hi],
            )
            key = band_of_edge * nvb + sblk_by_band[e0:e1]
            uk, kc = np.unique(key, return_counts=True)
            if min_count > 1:
                keep = kc >= min_count
                uk, kc = uk[keep], kc[keep]
            uniq_parts.append(uk)
            count_parts.append(kc.astype(np.int64))
        b_lo = b_hi
    if not uniq_parts:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    return np.concatenate(uniq_parts), np.concatenate(count_parts)


def _cover_chunk(s, d, chosen, r: int, nvb: int, strip_bytes: int):
    """(covered cell keys, tail s, tail d) for one batch of edge ids.

    The single source of truth for the slot/covered/cell coverage
    computation — the direct plan path calls it once over all edges,
    the banded path once per chunk.
    """
    sid = (d // r).astype(np.int64) * nvb + (s >> 7)
    slot = np.searchsorted(chosen, sid)
    covered = slot < len(chosen)
    if len(chosen):
        covered &= np.equal(chosen[np.minimum(slot, len(chosen) - 1)], sid)
    cell = (d % r) * BLOCK + (s & 127)
    key = slot[covered] * strip_bytes + cell[covered]
    return key, s[~covered].astype(np.int32), d[~covered].astype(np.int32)


def _cover_banded(graph: Graph, rank, chosen, r: int, nvb: int,
                  strip_bytes: int, chunk: int = _PLAN_CHUNK):
    """Streamed coverage pass over the whole graph, per edge chunk, so
    only covered keys and the tail int32 ids persist."""
    ne = graph.ne
    cs, cd = graph.col_src, graph.col_dst
    keys, tail_s, tail_d = [], [], []
    for lo in range(0, ne, chunk):
        k, ts, td = _cover_chunk(
            rank[cs[lo:lo + chunk]], rank[cd[lo:lo + chunk]],
            chosen, r, nvb, strip_bytes,
        )
        keys.append(k)
        tail_s.append(ts)
        tail_d.append(td)
    return (
        np.concatenate(keys) if keys else np.zeros(0, np.int64),
        np.concatenate(tail_s) if tail_s else np.zeros(0, np.int32),
        np.concatenate(tail_d) if tail_d else np.zeros(0, np.int32),
    )


def plan_hybrid(
    graph: Graph,
    levels: Sequence[Tuple[int, int]] = ((8, 2),),
    budget_bytes: int = 8 << 30,
    reorder: str = "degree",
    cap: int = 15,
) -> HybridPlan:
    """Partition edges into strip levels + a lane-select tail. Exact.

    ``levels`` is a sequence of ``(r, min_count)`` pairs, consumed in
    order: each level takes the strips (at granularity r x 128) holding
    at least ``min_count`` still-unassigned edges, densest first, within
    what remains of ``budget_bytes`` (booked as unpacked int8 bytes).
    Cells holding more than ``cap`` parallel edges spill the excess to
    the tail; cap <= 15 keeps every even-r level nibble-packable at
    device-build time (opt-in, see DeviceHybrid.build).
    """
    nv = graph.nv
    nvb = (nv + BLOCK - 1) // BLOCK
    order, rank = _relabel(graph, reorder)

    # int32 vertex ids (nv < 2^31 per the format) — at RMAT27 the int64
    # version alone was 34 GB of host arrays; strip ids are computed in
    # int64 where the product can overflow. Above _PLAN_BANDED_MIN_NE
    # edges, level 0 streams the graph through the banded passes instead
    # of materializing s/d/strip_id at all (LUX_PLAN_BANDED=0/1
    # overrides); later levels run on the (much reduced or at least
    # already-paid-for) tail arrays.
    from lux_tpu.utils import flags

    knob = flags.tristate("LUX_PLAN_BANDED")
    banded0 = knob is True or (
        knob is None and graph.ne >= _PLAN_BANDED_MIN_NE
    )
    s = d = None
    if not banded0:
        s = rank[graph.col_src]
        d = rank[graph.col_dst]
    built = []
    remaining = budget_bytes

    for r, min_count in levels:
        if BLOCK % r:
            raise ValueError(f"strip height {r} must divide {BLOCK}")
        if s is None and (graph.ne == 0 or remaining <= 0):
            s = rank[graph.col_src]
            d = rank[graph.col_dst]
        if s is not None and (s.size == 0 or remaining <= 0):
            built.append(StripLevel(
                r=r,
                strips=np.zeros((0, r, BLOCK), np.int8),
                rows=np.zeros(0, np.int32),
                cols=np.zeros(0, np.int32),
            ))
            continue
        # Budget books UNPACKED int8 bytes — nibble packing is an opt-in
        # device-build decision (measured negative, see DeviceHybrid.build)
        # the planner cannot assume; packed builds simply use less HBM
        # than budgeted.
        strip_bytes = r * BLOCK
        if s is None:
            # Banded level 0: counts arrive prefiltered to >= min_count
            # (selection-equivalent to take-then-filter below, since
            # sub-min_count strips are never chosen and stable tie order
            # among survivors is preserved).
            uniq_ids, counts = _strip_counts_banded(
                graph, rank, r, nvb, min_count
            )
            take = np.argsort(-counts, kind="stable")[
                : max(remaining // strip_bytes, 0)
            ]
            chosen = np.sort(uniq_ids[take])
            key, tail_s, tail_d = _cover_banded(
                graph, rank, chosen, r, nvb, strip_bytes
            )
        else:
            strip_id = (d // r).astype(np.int64) * nvb + (s >> 7)
            uniq_ids, counts = np.unique(strip_id, return_counts=True)
            take = np.argsort(-counts, kind="stable")[
                : max(remaining // strip_bytes, 0)
            ]
            take = take[counts[take] >= min_count]
            chosen = np.sort(uniq_ids[take])
            del strip_id
            key, tail_s, tail_d = _cover_chunk(
                s, d, chosen, r, nvb, strip_bytes
            )
        uk, kc = np.unique(key, return_counts=True)
        strips = np.zeros((len(chosen), strip_bytes), np.int8)
        if len(uk):
            strips.ravel()[uk] = np.minimum(kc, cap).astype(np.int8)

        # Count overflow (> cap parallel edges in one cell): keep the excess.
        spill_s = spill_d = np.empty(0, np.int32)
        over = kc > cap
        if over.any():
            reps = (kc[over] - cap).astype(np.int64)
            ok = uk[over]
            sid = chosen[ok // strip_bytes]
            c = ok % strip_bytes
            spill_d = np.repeat(
                (sid // nvb) * r + c // BLOCK, reps
            ).astype(np.int32)
            spill_s = np.repeat(
                (sid % nvb) * BLOCK + (c & 127), reps
            ).astype(np.int32)

        built.append(StripLevel(
            r=r,
            strips=strips.reshape(-1, r, BLOCK),
            rows=(chosen // nvb).astype(np.int32),
            cols=(chosen % nvb).astype(np.int32),
        ))
        remaining -= len(chosen) * strip_bytes
        s = np.concatenate([tail_s, spill_s])
        d = np.concatenate([tail_d, spill_d])

    if s is None:  # banded mode with an empty `levels` sequence
        s = rank[graph.col_src]
        d = rank[graph.col_dst]

    # Tail CSC sort by (d, s). np.lexsort was the planner's real hot
    # spot (40 s on RMAT22's 67M edges, single-core mergesort); packing
    # both ids into one int64 key and radix-sorting (np.sort stable on
    # ints) runs ~7x faster. nv < 2^31 so both ids fit 31 bits.
    vbits = max(int(nv - 1).bit_length(), 1)
    packed = (d.astype(np.int64) << vbits) | s.astype(np.int64)
    packed = np.sort(packed, kind="stable")
    d = (packed >> vbits).astype(np.int32)
    s = (packed & ((1 << vbits) - 1)).astype(np.int32)
    del packed
    tail_row_ptr = np.zeros(nv + 1, np.int64)
    np.cumsum(np.bincount(d, minlength=nv), out=tail_row_ptr[1:])

    return HybridPlan(
        nv=nv,
        nvb=nvb,
        order=order,
        rank=rank,
        levels=tuple(built),
        tail_sb=(s >> 7).astype(np.int32),
        tail_lane=(s & 127).astype(np.int8),
        tail_row_ptr=tail_row_ptr,
        out_degrees=graph.out_degrees[order],
        in_degrees=graph.in_degrees[order],
        cap=cap,
        levels_spec=tuple((int(r), int(t)) for r, t in levels),
        budget_bytes=int(budget_bytes),
    )


_PLAN_ARRAY_FIELDS = (
    "order", "rank", "tail_sb", "tail_lane", "tail_row_ptr",
    "out_degrees", "in_degrees",
)


def save_plan(path: str, plan: HybridPlan) -> None:
    """Persist a plan as a directory of raw ``.npy`` files + ``meta.json``.

    Raw .npy (one array per file) loads via ``np.load(mmap_mode="r")`` —
    effectively instant, paged in at disk bandwidth on first touch. The
    previous single-``.npz`` format streamed the multi-GB strip arrays
    through zipfile CRC32 at ~170 MB/s (46.7 s for the RMAT22 plan);
    ``load_plan`` still reads it for old caches. Writes go to a temp
    directory renamed into place so a crashed save never leaves a
    half-written cache that a later run would trust.
    """
    import json
    import os
    import tempfile

    tmp = tempfile.mkdtemp(
        dir=os.path.dirname(os.path.abspath(path)) or ".",
        prefix=os.path.basename(path) + ".tmp.",
    )
    meta = dict(
        nv=plan.nv, nvb=plan.nvb,
        levels=[lev.r for lev in plan.levels],
        level_edges=[lev.edges for lev in plan.levels],
        cap=plan.cap,
        levels_spec=(
            None if plan.levels_spec is None
            else [list(rt) for rt in plan.levels_spec]
        ),
        budget_bytes=plan.budget_bytes,
    )
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f)
    for name in _PLAN_ARRAY_FIELDS:
        np.save(os.path.join(tmp, name + ".npy"), getattr(plan, name))
    for i, lev in enumerate(plan.levels):
        np.save(os.path.join(tmp, f"lev{i}_strips.npy"), lev.strips)
        np.save(os.path.join(tmp, f"lev{i}_rows.npy"), lev.rows)
        np.save(os.path.join(tmp, f"lev{i}_cols.npy"), lev.cols)
    if os.path.isdir(path):
        import shutil

        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)
    os.replace(tmp, path)


def load_plan(path: str, mmap: bool = True) -> HybridPlan:
    """Load a plan saved by :func:`save_plan` (directory format), or a
    legacy round-1 ``.npz`` file. With ``mmap`` (default) arrays are
    memory-mapped read-only — the caller pays disk I/O only for the
    bytes it actually touches, when it touches them."""
    import json
    import os

    if os.path.isdir(path):
        mode = "r" if mmap else None
        ld = lambda name: np.load(
            os.path.join(path, name + ".npy"), mmap_mode=mode
        )
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        lev_edges = meta.get("level_edges", [-1] * len(meta["levels"]))
        levels = tuple(
            StripLevel(
                r=int(r),
                strips=ld(f"lev{i}_strips"),
                rows=ld(f"lev{i}_rows"),
                cols=ld(f"lev{i}_cols"),
                _edges=int(lev_edges[i]),
            )
            for i, r in enumerate(meta["levels"])
        )
        spec = meta.get("levels_spec")
        return HybridPlan(
            nv=int(meta["nv"]), nvb=int(meta["nvb"]),
            levels=levels,
            cap=int(meta.get("cap", 127)),
            levels_spec=(
                None if spec is None
                else tuple((int(r), int(t)) for r, t in spec)
            ),
            budget_bytes=int(meta.get("budget_bytes", -1)),
            **{name: ld(name) for name in _PLAN_ARRAY_FIELDS},
        )

    with np.load(path) as z:
        levels = tuple(
            StripLevel(
                r=int(z[f"lev{i}_r"]),
                strips=z[f"lev{i}_strips"],
                rows=z[f"lev{i}_rows"],
                cols=z[f"lev{i}_cols"],
            )
            for i in range(int(z["nlevels"]))
        )
        return HybridPlan(
            nv=int(z["nv"]), nvb=int(z["nvb"]),
            order=z["order"], rank=z["rank"],
            levels=levels, tail_sb=z["tail_sb"], tail_lane=z["tail_lane"],
            tail_row_ptr=z["tail_row_ptr"],
            out_degrees=z["out_degrees"], in_degrees=z["in_degrees"],
            cap=127,   # legacy .npz plans predate the nibble cap
        )


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


def _dd_add(a, b):
    """Double-single (hi, lo) addition with renormalization (TwoSum).

    Keeps ~2x f32 precision; used for the sub-chunk-prefix chain so that
    boundary diffs of nearby prefixes cancel to ~eps^2 of stream scale
    instead of eps. Branch-free, broadcasts like +.
    """
    ahi, alo = a
    bhi, blo = b
    s = ahi + bhi
    bb = s - ahi
    err = (ahi - (s - bb)) + (bhi - bb)
    lo = alo + blo + err
    hi2 = s + lo
    lo2 = lo - (hi2 - s)
    return hi2, lo2


# Gathers from tables larger than this run ~4x slower on v5e (measured
# cliff between 64 MB and 139 MB operands; an in-jit lax.slice restores
# the fast rate), so extraction tables are split into segments below it.
GATHER_TABLE_BYTES = 48 << 20


def _warn_big_table(nrows: int, what: str, advice: str = ""):
    """Warn when an unsegmented boundary-extraction gather table crosses
    the measured big-gather cliff (extraction runs ~4x off-rate above it).
    Used by paths whose tables cannot be (or are not yet) segmented: the
    sharded Z-streams (segment splits are per-part data, which
    shard_map's one-trace-for-all-shards model can't make static) and the
    single-device r==128 hub levels (normally tiny). ``advice`` lets the
    caller append a remediation hint."""
    if nrows * BLOCK * 4 > GATHER_TABLE_BYTES:
        import warnings

        warnings.warn(
            f"{what}: boundary-extraction table is "
            f"{nrows * BLOCK * 4 >> 20} MB, above the "
            f"~{GATHER_TABLE_BYTES >> 20} MB gather cliff — extraction "
            f"will run ~4x off-rate{advice}",
            stacklevel=3,
        )


def _subs_per_chunk(r: int) -> int:
    """Transposed-layout sub-chunks per scan chunk: S = 128/r lane
    groups of width r side by side, so the per-sub-chunk cumsum runs on
    a (cs, 128) array — cumsum on a narrow-minor-dim array is ~10x off
    bandwidth (each of its log passes works on 128-lane tiles holding r
    real values)."""
    assert BLOCK % r == 0
    return BLOCK // r


def round_chunk(chunk: int, n: int, r: int) -> int:
    """Scan chunk size: <= chunk (rounded up to a multiple of S so the
    (C, r) contribution block transposes exactly into (cs, 128))."""
    s = _subs_per_chunk(r)
    return max(s, -(-min(chunk, max(n, 1)) // s) * s)


# ---------------------------------------------------------------------------
# Static (plan-time) boundary data for the Z-stream layout
# ---------------------------------------------------------------------------
#
# The device scans are carry-free and emit, per chunk of C items, the
# TRANSPOSED local cumsum: contributions (C, r) reshape to (S, cs, r)
# with S = 128/r sub-chunks of cs = C/S items, transpose to (cs, S*r=128)
# and cumsum along axis 0 — so lane group s of row j holds the sum of
# the first j items of sub-chunk s. Each chunk contributes cs+1 such
# rows (leading zero row) to the flat Z-stream, plus its S sub-chunk
# totals to a small side stream.
#
# A boundary position b in [0, K*C] then maps to
#     row = (b//C)*(cs+1) + (b%C)%cs     (one final zero row for b=K*C)
#     grp = (b%C)//cs                    (lane group, 0..S-1)
# and a range sum is   y[i] = Z[b_{i+1}] - Z[b_i] + (P[sub_{i+1}] -
# P[sub_i])   where P is the double-single prefix over sub-chunk totals
# (sub = b//cs, a GLOBAL sub-chunk index) — rebasing the cumsum to zero
# at every sub-chunk keeps the f32 cancellation error of the Z diff at
# sub-chunk mass. The P term is zero unless the range crosses a
# sub-chunk start, which happens for at most n_subs of the nb output
# rows: those corrections are applied as a tiny static scatter instead
# of widening every gather (the dd hi/lo parts are subtracted separately
# so prefix magnitudes cancel instead of rounding).


def zstream_boundaries(b: np.ndarray, chunk: int, r: int):
    """(row, grp, sub) int32/int64 arrays for sorted positions ``b``."""
    b = b.astype(np.int64)
    s = _subs_per_chunk(r)
    cs = chunk // s
    k = b // chunk
    local = b - k * chunk
    row = k * (cs + 1) + local % cs
    grp = local // cs
    assert int(row.max(initial=0)) < 2**31
    return row.astype(np.int32), grp.astype(np.int32), b // cs


def block_level_boundaries(b: np.ndarray, chunk: int):
    """(row, chunk_index) for the r == 128 split two-gather form: local
    rows are whole 128-lane blocks at flat row ``k*(chunk+1) + j``; P is
    a small (K+1, 128) table row-gathered by chunk index."""
    b = b.astype(np.int64)
    k = b // chunk
    row = k * (chunk + 1) + (b - k * chunk)
    assert int(row.max(initial=0)) < 2**31
    return row.astype(np.int32), k.astype(np.int32)


def crossing_correction(sub: np.ndarray, r: int):
    """Static data for the sparse P-correction scatter.

    ``sub`` (nb,) global sub-chunk index per boundary; output rows i with
    sub[i+1] != sub[i] need P[sub[i+1]] - P[sub[i]] added. Returns
    (flat output positions (|X|*r,), s0 (|X|,), s1 (|X|,)).
    """
    x = np.nonzero(sub[1:] != sub[:-1])[0]
    flat = (x[:, None] * r + np.arange(r)[None, :]).ravel()
    assert flat.size == 0 or int(flat.max()) < 2**31
    return (
        flat.astype(np.int32),
        sub[x].astype(np.int32),
        sub[x + 1].astype(np.int32),
    )


def split_segments(b: np.ndarray, nchunks: int, chunk: int, r: int):
    """Cut the Z-stream into gather tables under GATHER_TABLE_BYTES.

    Cuts fall on chunk boundaries (rows within one chunk interleave
    sub-chunks, so only the chunk index is monotone in ``b``). Returns a
    tuple of (bnd_lo, bnd_hi, row_base, row_cnt); the final zero row
    rides with the last segment.
    """
    s = _subs_per_chunk(r)
    cs = chunk // s
    rows_per_chunk = cs + 1
    kseg = max(GATHER_TABLE_BYTES // (BLOCK * 4) // rows_per_chunk, 1)
    segs = []
    for k0 in range(0, max(nchunks, 1), kseg):
        k1 = min(k0 + kseg, nchunks)
        lo = int(np.searchsorted(b, k0 * chunk, side="left"))
        hi = int(np.searchsorted(b, k1 * chunk, side="left"))
        if k1 == nchunks:
            hi = b.shape[0]                 # include b == K*C boundaries
        segs.append((lo, hi, k0 * rows_per_chunk,
                     (k1 - k0) * rows_per_chunk + (1 if k1 == nchunks else 0)))
    return tuple(segs)


def strip_boundaries(rows: np.ndarray, nchunks: int, chunk: int, nrb: int,
                     r: int):
    """All static boundary data per dst strip-row for a sorted strip list.

    ``rows`` (n,) are the real strips' dst strip-rows, ascending; pad
    strips (indices >= n) are zero-count so any boundary <= n is exact
    against the padded scan stream. Row i's strips span ``[b[i], b[i+1])``
    with ``b = searchsorted(rows, 0..nrb)`` — all plan-time constants.
    Returns (row, grp, xing_idx, xing_s0, xing_s1, segs).
    """
    b = np.searchsorted(rows, np.arange(nrb + 1, dtype=np.int64))
    if r == BLOCK:
        row, grp = block_level_boundaries(b, chunk)
        e = np.zeros(0, np.int32)
        return row, grp, e, e, e, ()
    row, grp, sub = zstream_boundaries(b, chunk, r)
    xi, s0, s1 = crossing_correction(sub, r)
    return row, grp, xi, s0, s1, split_segments(b, nchunks, chunk, r)


# ---------------------------------------------------------------------------
# Device data + kernels
# ---------------------------------------------------------------------------


def resolve_pack(pack, plan_cap: int):
    """One shared gate for the nibble-packing decision: explicit ``pack``
    wins, else the LUX_PACK_STRIPS env opt-in; packing also requires the
    plan's count cap to fit a nibble. An explicit ``pack=True`` that the
    plan cannot satisfy raises (mirroring PushExecutor's blocked_dense
    validation) — only the env opt-in degrades silently. Per-level, r
    must be even (checked at the call sites via ``r % 2 == 0``)."""
    if pack is None:
        from lux_tpu.utils import flags

        pack = flags.get_bool("LUX_PACK_STRIPS")
    elif pack and plan_cap > 15:
        raise ValueError(
            f"pack=True needs a plan with count cap <= 15 (got cap="
            f"{plan_cap}, a legacy/unpacked plan) — replan with cap<=15"
        )
    return bool(pack) and plan_cap <= 15


def pack_strips(strips: np.ndarray) -> np.ndarray:
    """(..., r, 128) int8 counts <= 15 → (..., r/2, 128) uint8 nibbles.

    Row j rides the low nibble, row j + r/2 the high nibble, so the
    device-side unpack is one `& 15`, one `>> 4`, and a lane-axis concat
    that lands in LOGICAL row order — no permutation anywhere. Halves
    the per-iteration strip HBM traffic (the dominant strip-phase byte
    stream); native int4 arrays would do the same but device_put of
    int4 crashes the axon backend (RecursionError, jax 0.8)."""
    r = strips.shape[-2]
    assert r % 2 == 0, "nibble packing needs an even strip height"
    lo = strips[..., : r // 2, :].astype(np.uint8)
    hi = strips[..., r // 2 :, :].astype(np.uint8)
    return lo | (hi << 4)


@dataclasses.dataclass
class DeviceLevel:
    """One strip level on device, chunked for lax.scan (pad strips are
    zero-count → contribute nothing). Boundary fields are the static
    Z-stream data from :func:`strip_boundaries`. ``packed`` marks
    nibble-packed strips ((C, r/2, 128) uint8, see pack_strips)."""

    r: int
    segs: tuple             # static gather-table segmentation
    strips: jnp.ndarray     # (nchunks, C, r, 128) int8 or packed uint8
    cols: jnp.ndarray       # (nchunks, C) int32
    bnd_row: jnp.ndarray    # (nrb+1,) int32
    bnd_grp: jnp.ndarray    # (nrb+1,) int32
    xing_idx: jnp.ndarray   # (|X|*r,) int32 flat output positions
    xing_s0: jnp.ndarray    # (|X|,) int32
    xing_s1: jnp.ndarray    # (|X|,) int32
    packed: bool = False


@dataclasses.dataclass
class DeviceHybrid:
    levels: Tuple[DeviceLevel, ...]
    tail_sb: jnp.ndarray        # (nchunks, C) int32 (padded with 0)
    tail_lane: jnp.ndarray      # (nchunks, C) int8
    tail_bnd_row: jnp.ndarray   # (nv+1,) int32 (tail_row_ptr boundaries)
    tail_bnd_grp: jnp.ndarray   # (nv+1,) int32
    tail_xing_idx: jnp.ndarray  # (|X|,) int32
    tail_xing_s0: jnp.ndarray   # (|X|,) int32
    tail_xing_s1: jnp.ndarray   # (|X|,) int32
    tail_segs: tuple
    nvb: int

    @staticmethod
    def build(
        plan: HybridPlan,
        chunk_strips: int = DEFAULT_CHUNK_STRIPS,
        chunk_tail: int = DEFAULT_CHUNK_TAIL,
        device=None,
        pack=None,
    ) -> "DeviceHybrid":
        """``pack=True`` nibble-packs even-r levels (needs plan.cap <= 15;
        default: the LUX_PACK_STRIPS env knob via :func:`resolve_pack`).
        MEASURED NEGATIVE on v5e (PERF.md round 2): the strip scan is
        VPU-bound, so halving its bytes buys nothing and the unpack adds
        ~60% per-strip time (4.9 → 7.9 ns isolated, 114 → 139 ms/iter
        end-to-end on RMAT22). Kept as an opt-in for hardware where the
        balance differs."""
        put = lambda x: jax.device_put(jnp.asarray(x), device)

        packed = resolve_pack(pack, plan.cap)
        dlevels = []
        for lev in plan.levels:
            nrb = plan.nvb * (BLOCK // lev.r)
            n = lev.rows.shape[0]
            c = round_chunk(chunk_strips, n, lev.r)
            pad = (-n) % c
            st = np.concatenate(
                [lev.strips, np.zeros((pad, lev.r, BLOCK), np.int8)]
            )
            co = np.concatenate(
                [lev.cols.astype(np.int32), np.zeros(pad, np.int32)]
            )
            k = st.shape[0] // c
            row, grp, xi, s0, s1, segs = strip_boundaries(
                lev.rows, k, c, nrb, lev.r
            )
            lev_packed = packed and lev.r % 2 == 0
            rr = lev.r // 2 if lev_packed else lev.r
            if lev_packed:
                st = pack_strips(st)
            dlevels.append(DeviceLevel(
                r=lev.r,
                segs=segs,
                packed=lev_packed,
                strips=put(st.reshape(k, c, rr, BLOCK)),
                cols=put(co.reshape(k, c)),
                bnd_row=put(row),
                bnd_grp=put(grp),
                xing_idx=put(xi),
                xing_s0=put(s0),
                xing_s1=put(s1),
            ))

        m = plan.tail_sb.shape[0]
        c = round_chunk(chunk_tail, m, 1)
        pad = (-m) % c
        sb = np.concatenate([plan.tail_sb, np.zeros(pad, np.int32)])
        lane = np.concatenate([plan.tail_lane, np.zeros(pad, np.int8)])
        k2 = sb.shape[0] // c
        row, grp, sub = zstream_boundaries(plan.tail_row_ptr, c, 1)
        xi, s0, s1 = crossing_correction(sub, 1)
        return DeviceHybrid(
            levels=tuple(dlevels),
            tail_sb=put(sb.reshape(k2, c)),
            tail_lane=put(lane.reshape(k2, c)),
            tail_bnd_row=put(row),
            tail_bnd_grp=put(grp),
            tail_xing_idx=put(xi),
            tail_xing_s0=put(s0),
            tail_xing_s1=put(s1),
            tail_segs=split_segments(plan.tail_row_ptr, k2, c, 1),
            nvb=plan.nvb,
        )


def _transpose_cumsum(contrib: jnp.ndarray):
    """(C, r) contributions → ((cs+1, 128) Z rows, (S, r) sub totals).

    The transpose puts S = 128/r sub-chunks side by side so the cumsum's
    minor dim is exactly 128 (a (S, cs, r) axis-1 cumsum measured ~10x
    slower — every log-pass touches 128-lane tiles holding r values).
    """
    c, r = contrib.shape
    s = _subs_per_chunk(r)
    cs = c // s
    zt = contrib.reshape(s, cs, r).transpose(1, 0, 2).reshape(cs, BLOCK)
    z = jnp.cumsum(zt, axis=0)
    zrows = jnp.concatenate([jnp.zeros((1, BLOCK), jnp.float32), z])
    return zrows, z[-1].reshape(s, r)


def _dd_prefix(totals_flat: jnp.ndarray):
    """(n_subs, r) sub totals → exclusive double-single prefix tables
    (n_subs+1, r) hi and lo."""
    n, r = totals_flat.shape
    z1 = jnp.zeros((1, r), jnp.float32)
    if n == 0:
        return z1, z1
    hi, lo = jax.lax.associative_scan(
        _dd_add, (totals_flat, jnp.zeros_like(totals_flat)), axis=0
    )
    return (
        jnp.concatenate([z1, hi]),
        jnp.concatenate([z1, lo]),
    )


def zstream_extract(
    flatz: jnp.ndarray,
    lev_r: int,
    segs,
    bnd_row: jnp.ndarray,
    bnd_grp: jnp.ndarray,
) -> jnp.ndarray:
    """Gather Z values at static boundaries; returns flat (nb*r,) f32.

    Gathers run per segment against an in-jit slice of the stream (big
    gather tables are ~4x off-rate, GATHER_TABLE_BYTES) and are chunked
    with a scan so the (cb, 128) intermediates stay bounded.
    """
    r = lev_r
    s = _subs_per_chunk(r)
    iota_s = jnp.arange(s, dtype=jnp.int32)
    outs = []
    for (lo, hi, base, cnt) in segs:
        nbs = hi - lo
        if nbs == 0:
            continue
        sub_tbl = jax.lax.slice(flatz, (base, 0), (base + cnt, BLOCK))
        # NOTE: an isolated (8,4)-plan sweep suggested 2^16 here, but
        # end-to-end with the default (8,2) plan it regressed 115 ->
        # 127 ms/iter; 2^19 is the measured end-to-end best.
        cb = min(1 << 19, nbs)
        pad = (-nbs) % cb
        idx = jnp.pad(bnd_row[lo:hi] - base, (0, pad)).reshape(-1, cb)
        grp = jnp.pad(bnd_grp[lo:hi], (0, pad)).reshape(-1, cb)

        def ebody(_, ch):
            ix, g = ch
            rw = sub_tbl[ix].reshape(-1, s, r)           # (cb, S, r)
            sel = g[:, None] == iota_s[None, :]
            gv = jnp.where(sel[:, :, None], rw, 0.0).sum(axis=1)
            return 0, gv.reshape(-1)                     # 1-D: no lane pad

        _, gv = jax.lax.scan(ebody, 0, (idx, grp))
        outs.append(gv.reshape(-1)[: nbs * r])
    return jnp.concatenate(outs)


def strip_level_spmv(x2d: jnp.ndarray, lev: DeviceLevel, nrb: int) -> jnp.ndarray:
    """Σ strip · x_block per destination row; returns (nrb*r,) f32.

    ``x2d`` is the (nvb, 128) f32 operand; ``nrb`` is the number of
    destination strip rows covered (``lev.cols`` may index all of ``x2d``
    while the level's strips span only a local destination range, which is
    how the sharded executor reuses this kernel per shard — boundaries for
    uncovered rows collapse to empty ranges and contribute zero).

    Per-strip contributions are an f32 broadcast-multiply-reduce on the
    VPU (int8 counts convert in-fusion). The per-row reduction is
    scatter-free: transposed sub-chunk cumsums (carry-free scan) + static
    boundary diffs + the sparse double-single P correction — see the
    Z-stream layout notes above; products themselves are exact f32.
    """
    r = lev.r

    def contrib_of(chunk):
        strips, cols = chunk
        xb = x2d[cols]                                  # (C, 128) row gather
        if lev.packed:
            # Nibble unpack: rows 0..r/2-1 in the low nibble, r/2..r-1
            # in the high — the concat lands in logical row order.
            lo = (strips & jnp.uint8(15)).astype(jnp.float32)
            hi = (strips >> jnp.uint8(4)).astype(jnp.float32)
            return jnp.concatenate(
                [
                    (lo * xb[:, None, :]).sum(-1),
                    (hi * xb[:, None, :]).sum(-1),
                ],
                axis=-1,
            )
        return (strips.astype(jnp.float32) * xb[:, None, :]).sum(-1)

    if r == BLOCK:
        # Split two-gather form: a (C+1, 128) local-cumsum block per
        # chunk + a small (K+1, 128) chunk-prefix table (chunk-level
        # rebase only — r=128 levels are small hub tiles).
        # Accuracy note: the chunk-prefix chain here is plain f32 (no
        # double-single compensation), so boundary diffs for hub rows
        # carry eps * level-stream-mass cancellation error — weaker than
        # the r<128 levels' sub-chunk-mass bound. Fine for the small hub
        # levels this branch serves (tests pass at 5e-5 rtol); switch to
        # _dd_prefix on the chunk totals if large r=128 levels become a
        # supported config.
        def body(carry, chunk):
            s_loc = jnp.cumsum(contrib_of(chunk), axis=0)
            out = jnp.concatenate(
                [jnp.zeros((1, r), jnp.float32), s_loc]
            )
            return carry + s_loc[-1], (out, carry)

        carry, (z, pk) = jax.lax.scan(
            body, jnp.zeros((r,), jnp.float32), (lev.strips, lev.cols)
        )
        lf = jnp.concatenate(
            [z.reshape(-1, BLOCK), jnp.zeros((1, BLOCK), jnp.float32)]
        )
        pp = jnp.concatenate([pk, carry[None]])          # (K+1, 128)
        _warn_big_table(lf.shape[0], f"strip level r={BLOCK}")
        gl = lf[lev.bnd_row].reshape(-1)
        gp = pp[lev.bnd_grp].reshape(-1)
        return (gp[r:] - gp[:-r]) + (gl[r:] - gl[:-r])

    def body(_, chunk):
        zrows, totals = _transpose_cumsum(contrib_of(chunk))
        return 0, (zrows, totals)

    _, (z, totals) = jax.lax.scan(body, 0, (lev.strips, lev.cols))
    flatz = jnp.concatenate(
        [z.reshape(-1, BLOCK), jnp.zeros((1, BLOCK), jnp.float32)]
    )
    gl = zstream_extract(flatz, r, lev.segs, lev.bnd_row, lev.bnd_grp)
    y = gl[r:] - gl[:-r]
    ph, pl = _dd_prefix(totals.reshape(-1, r))
    corr = (
        (ph[lev.xing_s1] - ph[lev.xing_s0])
        + (pl[lev.xing_s1] - pl[lev.xing_s0])
    )
    return y.at[lev.xing_idx].add(corr.reshape(-1))


def lane_select_tail_sums(
    x2d: jnp.ndarray,
    tail_sb: jnp.ndarray,
    tail_lane: jnp.ndarray,
    bnd_row: jnp.ndarray,
    bnd_grp: jnp.ndarray,
    xing_idx: jnp.ndarray,
    xing_s0: jnp.ndarray,
    xing_s1: jnp.ndarray,
    segs,
) -> jnp.ndarray:
    """Per-destination sums of tail-edge source values, fused.

    Each tail edge costs one 128-wide row gather of its source block plus
    an on-the-fly one-hot lane selection (exact f32). The per-destination
    reduction is the Z-stream boundary diff at the static
    ``tail_row_ptr`` boundaries (r=1) + the sparse double-single P
    correction. Pad edges past the real tail length land after the last
    boundary and are never read. Returns (nv,) f32.
    """
    iota = jnp.arange(BLOCK, dtype=jnp.int32)

    def body(_, chunk):
        sb, lane = chunk
        rows = x2d[sb]                                  # (C, 128) row gather
        v = jnp.where(
            lane.astype(jnp.int32)[:, None] == iota[None, :], rows, 0.0
        ).sum(axis=1)                                   # (C,)
        zrows, totals = _transpose_cumsum(v[:, None])
        return 0, (zrows, totals)

    _, (z, totals) = jax.lax.scan(body, 0, (tail_sb, tail_lane))
    flatz = jnp.concatenate(
        [z.reshape(-1, BLOCK), jnp.zeros((1, BLOCK), jnp.float32)]
    )
    gl = zstream_extract(flatz, 1, segs, bnd_row, bnd_grp)
    y = gl[1:] - gl[:-1]
    ph, pl = _dd_prefix(totals.reshape(-1, 1))
    corr = (
        (ph[xing_s1] - ph[xing_s0]) + (pl[xing_s1] - pl[xing_s0])
    )
    return y.at[xing_idx].add(corr.reshape(-1))


def vals_to_x2d(vals: jnp.ndarray, dh: DeviceHybrid) -> jnp.ndarray:
    """(nv,) values → (nvb, 128) padded gather operand."""
    pad = dh.nvb * BLOCK - vals.shape[0]
    return jnp.pad(vals, (0, pad)).reshape(dh.nvb, BLOCK)


def strips_sum(x2d: jnp.ndarray, dh: DeviceHybrid, nv: int) -> jnp.ndarray:
    """Σ over all strip levels; (nv,) f32 (internal order)."""
    acc = jnp.zeros(dh.nvb * BLOCK, jnp.float32)
    for lev in dh.levels:
        acc = acc + strip_level_spmv(x2d, lev, dh.nvb * (BLOCK // lev.r))
    return acc[:nv]


def tail_sum(x2d: jnp.ndarray, dh: DeviceHybrid) -> jnp.ndarray:
    """Σ over the lane-select tail; (nv,) f32 (internal order)."""
    return lane_select_tail_sums(
        x2d, dh.tail_sb, dh.tail_lane, dh.tail_bnd_row, dh.tail_bnd_grp,
        dh.tail_xing_idx, dh.tail_xing_s0, dh.tail_xing_s1, dh.tail_segs,
    )


def hybrid_spmv(
    vals: jnp.ndarray, dh: DeviceHybrid, gtail=None
) -> jnp.ndarray:
    """Full Σ vals[src] per destination over all layouts; (nv,) f32 in,
    (nv,) f32 out (internal vertex order).

    ``gtail`` (a :class:`~lux_tpu.ops.merge_tail_kernel.DeviceGroupedTail`)
    swaps the lane-select tail for the grouped merge-network tail —
    opt-in via LUX_GROUPED_TAIL=1 in the executors; both produce per-dst
    sums of the same tail edge set."""
    nv = vals.shape[0]
    x2d = vals_to_x2d(vals, dh)
    if gtail is not None:
        from lux_tpu.ops.merge_tail_kernel import grouped_tail_sums

        return strips_sum(x2d, dh, nv) + grouped_tail_sums(x2d, gtail)
    return strips_sum(x2d, dh, nv) + tail_sum(x2d, dh)


for _cls, _data, _meta in (
    (DeviceLevel,
     ["strips", "cols", "bnd_row", "bnd_grp",
      "xing_idx", "xing_s0", "xing_s1"],
     ["r", "segs", "packed"]),
    (DeviceHybrid,
     ["levels", "tail_sb", "tail_lane", "tail_bnd_row", "tail_bnd_grp",
      "tail_xing_idx", "tail_xing_s0", "tail_xing_s1"],
     ["tail_segs", "nvb"]),
):
    jax.tree_util.register_dataclass(_cls, data_fields=_data, meta_fields=_meta)
