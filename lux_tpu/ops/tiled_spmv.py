"""Tiled-hybrid SpMV: MXU block-sparse tiles + scalar-gather tail.

The pull engine's hot loop is ``acc[dst] = Σ vals[src]`` over a static
graph (the reference's ``pr_kernel`` gather, pagerank/pagerank_gpu.cu:49-102).
On TPU an arbitrary 1-element gather costs ~8.5 ns (scalarized), while a
128×128 tile matmul streams from HBM at ~520 GB/s (~60 ns for a 16 KB int8
tile) and a 128-wide row gather costs ~0.9 ns — so any 128×128 adjacency
tile holding ≳4 edges is cheaper as a dense MXU matvec than as per-edge
gathers.

Scale-free graphs concentrate edges between high-degree vertices. After
relabeling vertices in descending degree order, 50-60 % of an R-MAT
graph's edges fall in 128×128 tiles with ≥16 entries (measured: RMAT22,
62.6 % at ≥16). This module exploits that:

- host side (:func:`plan_tiles`): degree-sort relabeling; count edges per
  128×128 tile; select the densest tiles within an HBM byte budget; store
  them as dense **int8 count tiles** (multi-edges collapse into counts;
  cells overflowing 127 spill the excess back to the tail — exactness is
  preserved); remaining edges become a CSC-sorted COO tail.
- device side (:func:`tiled_spmv`): a `lax.scan` over tile chunks — row
  gather of the source blocks, one batched (128×128)@(128×2) bf16 matmul
  per tile (the 2 columns are a hi/lo bf16 split of the f32 operand, so
  the result keeps ~16 mantissa bits at no extra tile bandwidth), and a
  sorted segment-sum into destination block rows — plus the existing
  gather + row-ptr-diff path for the tail.

This is a TPU-native design with no reference counterpart: the reference
leans on fine-grained HBM atomics (atomicAdd) that the TPU VPU simply
does not have; the MXU *is* the TPU's gather/scatter engine for anything
dense enough to batch.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.graph.graph import Graph

BLOCK = 128
CELLS = BLOCK * BLOCK
TILE_BYTES = CELLS  # int8


@dataclasses.dataclass(eq=False)
class TilePlan:
    """Host-side product of :func:`plan_tiles` (all numpy, internal ids).

    "Internal" vertex ids are positions in the degree-sorted order:
    ``order[p]`` is the external id at internal position p and
    ``rank[v]`` is the internal position of external vertex v.
    """

    nv: int
    nvb: int                       # number of 128-blocks (nv padded)
    order: np.ndarray              # (nv,) int32 external id per internal pos
    rank: np.ndarray               # (nv,) int32 internal pos per external id
    tiles: np.ndarray              # (T, 128, 128) int8 edge counts
    tile_row: np.ndarray           # (T,) int32 dst block, sorted
    tile_col: np.ndarray           # (T,) int32 src block
    tail_src: np.ndarray           # (M,) int32 internal src, CSC order
    tail_row_ptr: np.ndarray       # (nv+1,) int64
    out_degrees: np.ndarray        # (nv,) int64, internal order
    in_degrees: np.ndarray         # (nv,) int64, internal order

    @property
    def num_tiles(self) -> int:
        return int(self.tiles.shape[0])

    @property
    def coverage(self) -> float:
        total = self.tail_src.shape[0] + int(self.tiles.sum(dtype=np.int64))
        return 1.0 - self.tail_src.shape[0] / max(total, 1)


def plan_tiles(
    graph: Graph,
    budget_bytes: int = 3 << 30,
    min_count: int = 8,
    reorder: str = "degree",
) -> TilePlan:
    """Partition a graph's edges into dense int8 count tiles + a COO tail.

    Exact: every edge lands in exactly one of the two representations
    (cells whose count exceeds int8 range spill the excess to the tail).
    """
    nv = graph.nv
    nvb = (nv + BLOCK - 1) // BLOCK

    if reorder == "degree":
        deg = graph.in_degrees + graph.out_degrees
        order = np.argsort(-deg, kind="stable").astype(np.int32)
    elif reorder == "natural":
        order = np.arange(nv, dtype=np.int32)
    else:
        raise ValueError(f"unknown reorder {reorder!r}")
    rank = np.empty(nv, np.int32)
    rank[order] = np.arange(nv, dtype=np.int32)

    s = rank[graph.col_src].astype(np.int64)
    d = rank[graph.col_dst].astype(np.int64)

    tile_id = (d >> 7) * nvb + (s >> 7)
    uniq_ids, counts = np.unique(tile_id, return_counts=True)

    # Densest tiles first, until the byte budget or the density floor.
    max_tiles = max(budget_bytes // TILE_BYTES, 0)
    by_density = np.argsort(-counts, kind="stable")[:max_tiles]
    by_density = by_density[counts[by_density] >= min_count]
    chosen = np.sort(uniq_ids[by_density])          # ascending == (row, col) sorted

    slot = np.searchsorted(chosen, tile_id)
    covered = (slot < len(chosen))
    if len(chosen):
        covered &= np.equal(chosen[np.minimum(slot, len(chosen) - 1)], tile_id)

    # Dense cells: count multi-edges per (tile, cell).
    cell = ((d & 127) << 7) | (s & 127)
    key = slot[covered] * CELLS + cell[covered]
    uk, kc = np.unique(key, return_counts=True)
    clipped = np.minimum(kc, 127)
    tiles = np.zeros((len(chosen), CELLS), np.int8)
    if len(uk):
        tiles.ravel()[uk] = clipped.astype(np.int8)

    # Spill int8 overflow back to explicit edges (rare: >127 parallel edges).
    over = kc > 127
    spill_s = spill_d = np.empty(0, np.int64)
    if over.any():
        reps = (kc[over] - 127).astype(np.int64)
        ok = uk[over]
        tid = chosen[ok // CELLS]
        c = ok % CELLS
        spill_d = np.repeat((tid // nvb) * BLOCK + (c >> 7), reps)
        spill_s = np.repeat((tid % nvb) * BLOCK + (c & 127), reps)

    tail_s = np.concatenate([s[~covered], spill_s])
    tail_d = np.concatenate([d[~covered], spill_d])
    tsort = np.lexsort((tail_s, tail_d))
    tail_s = tail_s[tsort].astype(np.int32)
    tail_row_ptr = np.zeros(nv + 1, np.int64)
    np.cumsum(np.bincount(tail_d, minlength=nv), out=tail_row_ptr[1:])

    return TilePlan(
        nv=nv,
        nvb=nvb,
        order=order,
        rank=rank,
        tiles=tiles.reshape(-1, BLOCK, BLOCK),
        tile_row=(chosen // nvb).astype(np.int32),
        tile_col=(chosen % nvb).astype(np.int32),
        tail_src=tail_s,
        tail_row_ptr=tail_row_ptr,
        out_degrees=graph.out_degrees[order],
        in_degrees=graph.in_degrees[order],
    )


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DeviceTiles:
    """Tile arrays on device, chunked for the scan (zero-padded tiles are
    harmless: zero counts contribute nothing to block row 0)."""

    tiles: jnp.ndarray      # (nchunks, C, 128, 128) int8
    rows: jnp.ndarray       # (nchunks, C) int32
    cols: jnp.ndarray       # (nchunks, C) int32
    nvb: int

    @staticmethod
    def build(plan: TilePlan, chunk: int = 4096, device=None) -> "DeviceTiles":
        t, r, c = plan.tiles, plan.tile_row, plan.tile_col
        n = t.shape[0]
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        if n == 0:
            # lax.scan over zero-length xs is free; don't pay for a dummy
            # chunk of zero matmuls per iteration.
            return DeviceTiles(
                tiles=put(np.zeros((0, 1, BLOCK, BLOCK), np.int8)),
                rows=put(np.zeros((0, 1), np.int32)),
                cols=put(np.zeros((0, 1), np.int32)),
                nvb=plan.nvb,
            )
        chunk = min(chunk, n)
        pad = (-n) % chunk
        if pad:
            # Zero tiles contribute nothing; pad rows with the max block id
            # so per-chunk segment ids stay sorted (indices_are_sorted).
            t = np.concatenate([t, np.zeros((pad, BLOCK, BLOCK), np.int8)])
            r = np.concatenate([r, np.full(pad, plan.nvb - 1, np.int32)])
            c = np.concatenate([c, np.zeros(pad, np.int32)])
        nchunks = t.shape[0] // chunk
        return DeviceTiles(
            tiles=put(t.reshape(nchunks, chunk, BLOCK, BLOCK)),
            rows=put(r.reshape(nchunks, chunk)),
            cols=put(c.reshape(nchunks, chunk)),
            nvb=plan.nvb,
        )


def _hi_lo_split(x2d: jnp.ndarray):
    """f32 -> two bf16 planes; hi + lo carries ~16 mantissa bits."""
    hi = x2d.astype(jnp.bfloat16)
    lo = (x2d - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def tiled_spmv(vals: jnp.ndarray, dt: DeviceTiles) -> jnp.ndarray:
    """acc2d[rb] += Σ_tiles tile @ vals_block[cb]  (f32 in, f32 out).

    ``vals`` is the full (nv,) f32 vector in internal order; returns the
    (nvb*128,) accumulation (trailing pad rows are zero).
    """
    nvb = dt.nvb
    pad = nvb * BLOCK - vals.shape[0]
    x2d = jnp.pad(vals, (0, pad)).reshape(nvb, BLOCK)
    hi, lo = _hi_lo_split(x2d)
    xin = jnp.stack([hi, lo], axis=-1)        # (nvb, 128, 2) bf16

    def body(acc, chunk):
        tiles, rows, cols = chunk
        xb = xin[cols]                         # (C, 128, 2) row gather
        prod = jnp.einsum(
            "tij,tjk->tik",
            tiles.astype(jnp.bfloat16),
            xb,
            preferred_element_type=jnp.float32,
        )                                      # (C, 128, 2)
        contrib = prod[..., 0] + prod[..., 1]  # (C, 128) f32
        acc = acc + jax.ops.segment_sum(
            contrib, rows, num_segments=nvb, indices_are_sorted=True
        )
        return acc, None

    acc0 = jnp.zeros((nvb, BLOCK), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (dt.tiles, dt.rows, dt.cols))
    return acc.reshape(-1)


jax.tree_util.register_dataclass(
    DeviceTiles,
    data_fields=["tiles", "rows", "cols"],
    meta_fields=["nvb"],
)
