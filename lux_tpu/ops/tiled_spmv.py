"""Hybrid SpMV: MXU strip-tiles + a lane-select tail (no scalar gathers).

The pull engine's hot loop is ``acc[dst] = Σ vals[src]`` over a static
graph (the reference's ``pr_kernel`` gather, pagerank/pagerank_gpu.cu:49-102).
Measured TPU v5e rates dictate the design:

- arbitrary 1-element gather: ~8.5 ns/edge (scalarized — the TPU VPU has
  no fine-grained HBM access; this is the reference's atomicAdd/gather
  world and the thing to design away);
- 128-wide **row** gather: ~0.9 ns/row (~540 GB/s — full bandwidth);
- int8 strip matmul: streams at ~520 GB/s through the MXU.

So the only fast irregular primitive is "fetch an aligned 128-block".
Every edge is served by one of two such layouts:

1. **Strip levels** (:class:`StripLevel`): after degree-sort relabeling,
   hub-hub edges concentrate in (R,128) blocks of the adjacency matrix
   (R | 128). Each dense-enough strip is stored as an (R,128) int8 count
   matrix (multi-edges collapse into counts; cells overflowing 127 spill
   the excess to the tail, so the edge partition stays exact) and costs
   one row gather of the source block + an f32 broadcast-multiply-reduce
   on the VPU (measured 3x faster than the equivalent (R,128)@(128,2)
   bf16 MXU matmul, whose 2-column output tile starves the systolic
   array — and exact f32 per product instead of a hi/lo bf16 split).
   A strip of R·128 int8 bytes breaks even vs. per-edge work at about
   R/3 edges (R=8 → ≥3 edges).
   Per-destination reduction of strip contributions uses NO scatter:
   strips are sorted by destination strip-row, so each row's strips are
   a contiguous range with *plan-time-constant* boundaries; chunk-rebased
   prefix pairs plus a static boundary gather-diff (blocked row gathers,
   :func:`boundary_gather_data`) replace the 8-wide scatter rows of
   ``jax.ops.segment_sum`` that ran at scalar rate
   (measured 117 ms -> ~10 ms on RMAT22).

2. **Lane-select tail**: a leftover edge costs one 128-wide row gather
   of its source block plus an on-the-fly one-hot lane selection
   (``where(lane == iota, row, 0).sum()``) — pure VPU, *exact* f32, and
   ~512 HBM bytes/edge instead of the 4.4 KB-equivalent of a scalar
   gather. Edges stay CSC-sorted so the per-destination reduction is
   the scatter-free chunk-rebased prefix-pair diff at the static
   ``tail_row_ptr`` boundaries.

This layout has no reference counterpart — it is what "gather" means on
hardware whose only irregular-access engines are aligned block DMA and
a 128x128 systolic array.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.graph.graph import Graph

BLOCK = 128
# Default prefix-rebase granularities (see rebase_granularity /
# pack_prefix_chunk): small enough that f32 boundary-diff error stays at
# ~eps * (stream mass / thousands), big enough that packing overhead
# (one P-lane group + row padding per sub-chunk) stays a few percent.
REBASE_STRIP = 1024
REBASE_TAIL = 4096


# ---------------------------------------------------------------------------
# Host-side planning
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class StripLevel:
    """Dense (r, 128) int8 count strips at one granularity."""

    r: int
    strips: np.ndarray       # (T, r, 128) int8
    rows: np.ndarray         # (T,) int32 dst strip index (sorted ascending)
    cols: np.ndarray         # (T,) int32 src 128-block index

    @property
    def nbytes(self) -> int:
        return self.strips.nbytes

    @property
    def edges(self) -> int:
        return int(self.strips.astype(np.int64).sum())


@dataclasses.dataclass(eq=False)
class HybridPlan:
    """Host-side product of :func:`plan_hybrid` (numpy, internal ids).

    "Internal" vertex ids are positions in the degree-sorted order:
    ``order[p]`` is the external id at internal position p and
    ``rank[v]`` the internal position of external vertex v.
    """

    nv: int
    nvb: int                 # number of 128-blocks (nv padded)
    order: np.ndarray        # (nv,) int32
    rank: np.ndarray         # (nv,) int32
    levels: Tuple[StripLevel, ...]
    tail_sb: np.ndarray      # (M,) int32 src >> 7, CSC (dst-sorted) order
    tail_lane: np.ndarray    # (M,) int8  src & 127
    tail_row_ptr: np.ndarray  # (nv+1,) int64
    out_degrees: np.ndarray  # (nv,) int64, internal order
    in_degrees: np.ndarray   # (nv,) int64, internal order

    @property
    def num_strips(self) -> int:
        return sum(lev.rows.shape[0] for lev in self.levels)

    @property
    def strip_bytes(self) -> int:
        return sum(lev.nbytes for lev in self.levels)

    @property
    def coverage(self) -> float:
        total = self.tail_sb.shape[0] + sum(lev.edges for lev in self.levels)
        return 1.0 - self.tail_sb.shape[0] / max(total, 1)


def _relabel(graph: Graph, reorder: str):
    nv = graph.nv
    if reorder == "degree":
        deg = graph.in_degrees + graph.out_degrees
        order = np.argsort(-deg, kind="stable").astype(np.int32)
    elif reorder == "natural":
        order = np.arange(nv, dtype=np.int32)
    else:
        raise ValueError(f"unknown reorder {reorder!r}")
    rank = np.empty(nv, np.int32)
    rank[order] = np.arange(nv, dtype=np.int32)
    return order, rank


def plan_hybrid(
    graph: Graph,
    levels: Sequence[Tuple[int, int]] = ((8, 4),),
    budget_bytes: int = 6 << 30,
    reorder: str = "degree",
) -> HybridPlan:
    """Partition edges into strip levels + a lane-select tail. Exact.

    ``levels`` is a sequence of ``(r, min_count)`` pairs, consumed in
    order: each level takes the strips (at granularity r x 128) holding
    at least ``min_count`` still-unassigned edges, densest first, within
    what remains of ``budget_bytes``.
    """
    nv = graph.nv
    nvb = (nv + BLOCK - 1) // BLOCK
    order, rank = _relabel(graph, reorder)

    s = rank[graph.col_src].astype(np.int64)
    d = rank[graph.col_dst].astype(np.int64)
    built = []
    remaining = budget_bytes

    for r, min_count in levels:
        if BLOCK % r or not (r <= 32 or r == BLOCK):
            raise ValueError(
                f"strip height {r} must divide {BLOCK} and be <= 32 (or"
                f" exactly {BLOCK}): the packed prefix layout reserves 2r"
                f" P lanes + at least one cumsum row per 128-lane block"
            )
        if s.size == 0 or remaining <= 0:
            built.append(StripLevel(
                r=r,
                strips=np.zeros((0, r, BLOCK), np.int8),
                rows=np.zeros(0, np.int32),
                cols=np.zeros(0, np.int32),
            ))
            continue
        strip_bytes = r * BLOCK
        strip_id = (d // r) * nvb + (s >> 7)
        uniq_ids, counts = np.unique(strip_id, return_counts=True)
        take = np.argsort(-counts, kind="stable")[: max(remaining // strip_bytes, 0)]
        take = take[counts[take] >= min_count]
        chosen = np.sort(uniq_ids[take])
        slot = np.searchsorted(chosen, strip_id)
        covered = slot < len(chosen)
        if len(chosen):
            covered &= np.equal(
                chosen[np.minimum(slot, len(chosen) - 1)], strip_id
            )

        cell = (d % r) * BLOCK + (s & 127)
        key = slot[covered] * strip_bytes + cell[covered]
        uk, kc = np.unique(key, return_counts=True)
        strips = np.zeros((len(chosen), strip_bytes), np.int8)
        if len(uk):
            strips.ravel()[uk] = np.minimum(kc, 127).astype(np.int8)

        # int8 overflow (>127 parallel edges in one cell): keep the excess.
        spill_s = spill_d = np.empty(0, np.int64)
        over = kc > 127
        if over.any():
            reps = (kc[over] - 127).astype(np.int64)
            ok = uk[over]
            sid = chosen[ok // strip_bytes]
            c = ok % strip_bytes
            spill_d = np.repeat((sid // nvb) * r + c // BLOCK, reps)
            spill_s = np.repeat((sid % nvb) * BLOCK + (c & 127), reps)

        built.append(StripLevel(
            r=r,
            strips=strips.reshape(-1, r, BLOCK),
            rows=(chosen // nvb).astype(np.int32),
            cols=(chosen % nvb).astype(np.int32),
        ))
        remaining -= strips.nbytes
        s = np.concatenate([s[~covered], spill_s])
        d = np.concatenate([d[~covered], spill_d])

    tsort = np.lexsort((s, d))
    s, d = s[tsort], d[tsort]
    tail_row_ptr = np.zeros(nv + 1, np.int64)
    np.cumsum(np.bincount(d, minlength=nv), out=tail_row_ptr[1:])

    return HybridPlan(
        nv=nv,
        nvb=nvb,
        order=order,
        rank=rank,
        levels=tuple(built),
        tail_sb=(s >> 7).astype(np.int32),
        tail_lane=(s & 127).astype(np.int8),
        tail_row_ptr=tail_row_ptr,
        out_degrees=graph.out_degrees[order],
        in_degrees=graph.in_degrees[order],
    )


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


def _rows_per_block(r: int) -> int:
    """Local-cumsum rows packed per 128-lane block (after the 2r P lanes)."""
    assert r <= 32 or r == BLOCK, "packed prefix layout needs r <= 32"
    return BLOCK // r - 2


def _dd_add(a, b):
    """Double-single (hi, lo) addition with renormalization (TwoSum).

    Keeps ~2x f32 precision; used for the chunk-prefix chain so that
    boundary diffs of nearby prefixes cancel to ~eps^2 of stream scale
    instead of eps. Branch-free, broadcasts like +.
    """
    ahi, alo = a
    bhi, blo = b
    s = ahi + bhi
    bb = s - ahi
    err = (ahi - (s - bb)) + (bhi - bb)
    lo = alo + blo + err
    hi2 = s + lo
    lo2 = lo - (hi2 - s)
    return hi2, lo2


def packed_blocks_per_chunk(chunk: int, r: int) -> int:
    return -(-(chunk + 1) // _rows_per_block(r))


def rebase_granularity(chunk: int, default: int) -> int:
    """Sub-chunk size at which prefixes are rebased to zero.

    Must divide the scan chunk; falls back to chunk-level rebasing when
    the chunk isn't a multiple of the default (small inputs, where the
    stream mass — and with it the f32 boundary-diff error — is small
    anyway)."""
    return default if chunk % default == 0 else chunk


def boundary_gather_data(b: np.ndarray, chunk: int, r: int):
    """Static gather data for chunk-rebased prefix-pair extraction.

    The device-side scans emit, per chunk of ``chunk`` items, the
    chunk-LOCAL inclusive cumsum rows (r lanes each, with a leading zero
    row) — prefixes are rebased to zero at every chunk start so their
    magnitude, and hence the f32 cancellation error of a boundary diff,
    stays at chunk scale rather than stream scale. The chunk-global part
    (exclusive chunk prefix P_k, kept in double-single hi/lo f32 — see
    :func:`_dd_add` — so even boundary-crossing diffs cancel to ~eps^2
    of stream scale) rides in the SAME 128-lane block:

        block = [ P_k hi (r) | P_k lo (r) | 128/r - 2 local-cumsum rows ]

    so one row gather fetches all three parts (every materialized array
    keeps a 128-wide minor dim — TPU pads narrow trailing dims to the
    full 128-lane tile, which would inflate an interleaved narrow layout
    by up to 64x). The P and L halves are diffed separately, so the
    total error of a row's sum is ~eps * (sub-chunk mass) + ~eps^2 *
    (stream mass), i.e. roundoff scales with the row's local
    neighborhood, not the whole stream.

    A sorted boundary position ``b`` (in [0, t_pad], t_pad a multiple of
    ``chunk``) decomposes as ``k = b//chunk``, ``j = b%chunk`` and lands
    in packed block ``k*nblk + j//rpb`` at row offset ``j%rpb``
    (``rpb = 128/r - 2``, ``nblk = ceil((chunk+1)/rpb)``; one extra final
    block holds the stream total for b == t_pad). Returns (block_index,
    offset_index) int32 arrays shaped like ``b``.

    For r == 128 a block has no room for P: returns (q, b//chunk) for
    the split two-gather form (local rows are whole 128-lane blocks at
    flat row ``q = k*(chunk+1) + j``; P is a small (K+1, 128) table
    row-gathered by chunk index).
    """
    b = b.astype(np.int64)
    k = b // chunk
    j = b - k * chunk
    if r < BLOCK:
        rpb = _rows_per_block(r)
        nblk = packed_blocks_per_chunk(chunk, r)
        blk = k * nblk + j // rpb
        assert int(blk.max(initial=0)) < 2**31, "level too large for int32"
        return blk.astype(np.int32), (j % rpb).astype(np.int32)
    assert r == BLOCK
    q = k * (chunk + 1) + j
    assert int(q.max(initial=0)) < 2**31
    return q.astype(np.int32), k.astype(np.int32)


def strip_boundaries(rows: np.ndarray, chunk: int, nrb: int, r: int):
    """Boundary gather data per dst strip-row for a sorted strip list.

    ``rows`` (n,) are the real strips' dst strip-rows, ascending; pad
    strips (indices >= n) are zero-count so any boundary <= n is exact
    against the padded scan stream. Row i's strips span ``[b[i], b[i+1])``
    with ``b = searchsorted(rows, 0..nrb)`` — all plan-time constants.
    """
    b = np.searchsorted(rows, np.arange(nrb + 1, dtype=np.int64))
    return boundary_gather_data(b, chunk, r)


def pack_prefix_chunk(contrib: jnp.ndarray, carry, cs: int):
    """Sub-chunk-rebased cumsum + prefix packing for one scan chunk.

    ``contrib`` (C, r) raw per-item contributions, ``carry`` a
    double-single ((r,), (r,)) stream prefix at chunk start, ``cs`` the
    rebase granularity (cs | C). Cumsums run PER SUB-CHUNK of cs items
    (so a boundary diff's f32 cancellation error scales with sub-chunk
    mass, not chunk or stream mass); each sub-chunk's exclusive prefix —
    double-single, via an associative-scan of :func:`_dd_add` — rides in
    its blocks' P lanes. Returns ((S*nblk, 128) packed blocks, new
    carry), laid out per :func:`boundary_gather_data` with chunk=cs.
    """
    c, r = contrib.shape
    s = c // cs
    rpb = _rows_per_block(r)
    nblk = packed_blocks_per_chunk(cs, r)
    s_sub = jnp.cumsum(contrib.reshape(s, cs, r), axis=1)
    totals = s_sub[:, -1, :]                             # (S, r)
    tp_hi, tp_lo = jax.lax.associative_scan(
        _dd_add, (totals, jnp.zeros_like(totals)), axis=0
    )
    z1 = jnp.zeros((1, r), jnp.float32)
    excl = (
        jnp.concatenate([z1, tp_hi[:-1]]),
        jnp.concatenate([z1, tp_lo[:-1]]),
    )
    p_hi, p_lo = _dd_add((carry[0][None, :], carry[1][None, :]), excl)
    new_carry = _dd_add(carry, (tp_hi[-1], tp_lo[-1]))
    lrows = jnp.concatenate([z1[None].repeat(s, 0), s_sub], axis=1)
    lrows = jnp.pad(lrows, ((0, 0), (0, nblk * rpb - (cs + 1)), (0, 0)))
    lpart = lrows.reshape(s, nblk, rpb * r)
    phi = jnp.broadcast_to(p_hi[:, None, :], (s, nblk, r))
    plo = jnp.broadcast_to(p_lo[:, None, :], (s, nblk, r))
    packed = jnp.concatenate([phi, plo, lpart], axis=2)  # (S, nblk, 128)
    return packed.reshape(s * nblk, BLOCK), new_carry


def prefix_pair_extract(
    packed: jnp.ndarray,
    pk: jnp.ndarray,
    carry,
    bnd_blk: jnp.ndarray,
    bnd_off: jnp.ndarray,
    r: int,
) -> jnp.ndarray:
    """Boundary-range sums from a chunk-rebased scan's stacked outputs.

    ``packed`` (K, S*nblk, 128) stacked :func:`pack_prefix_chunk` blocks
    (for r < 128), or (K, C+1, 128) raw local-cumsum rows for r == 128;
    ``pk`` (K, 128) exclusive chunk prefixes (used only for r == 128);
    ``carry`` is the stream total — a double-single ((r,), (r,)) pair
    for r < 128, a plain (128,) array for r == 128. Returns the flat
    (len(bnd)-1)*r per-range sums via the static boundary data of
    :func:`boundary_gather_data`. The P-hi, P-lo and L parts are diffed
    SEPARATELY (in flat 1-D space, ``g[r:] - g[:-r]``) so prefix
    magnitudes cancel instead of rounding.
    """
    nb = bnd_blk.shape[0]
    if r < BLOCK:
        final = jnp.concatenate(
            [carry[0], carry[1], jnp.zeros((BLOCK - 2 * r,), jnp.float32)]
        )
        flat = jnp.concatenate([packed.reshape(-1, BLOCK), final[None]])
        rpb = _rows_per_block(r)
        iota_w = jnp.arange(rpb, dtype=jnp.int32)

        # Chunked extraction: one shot would materialize (nb, 128) f32
        # gather/select intermediates (nb can be nv+1 — gigabytes); the
        # scan bounds them at (cb, 128).
        cb = min(1 << 19, nb)
        pad = (-nb) % cb
        blk_c = jnp.pad(bnd_blk, (0, pad)).reshape(-1, cb)
        off_c = jnp.pad(bnd_off, (0, pad)).reshape(-1, cb)

        def ebody(_, ch):
            blk, off = ch
            rw = flat[blk]                               # (cb, 128)
            gph = rw[:, :r]
            gpl = rw[:, r: 2 * r]
            rl = rw[:, 2 * r:].reshape(-1, rpb, r)
            sel = off[:, None] == iota_w[None, :]
            gl = jnp.where(sel[:, :, None], rl, 0.0).sum(axis=1)
            # 1-D outputs: no narrow-minor-dim lane padding
            return 0, (gph.reshape(-1), gpl.reshape(-1), gl.reshape(-1))

        _, (gph, gpl, gl) = jax.lax.scan(ebody, 0, (blk_c, off_c))
        gph = gph.reshape(-1)[: nb * r]
        gpl = gpl.reshape(-1)[: nb * r]
        gl = gl.reshape(-1)[: nb * r]
        # Diff each part separately: hi parts of nearby prefixes cancel
        # (often exactly, Sterbenz); lo parts carry the residual.
        return (
            (gph[r:] - gph[:-r])
            + (gpl[r:] - gpl[:-r])
            + (gl[r:] - gl[:-r])
        )
    # r == 128: split two-gather form (chunk-level rebase only)
    lf = jnp.concatenate(
        [packed.reshape(-1, BLOCK), jnp.zeros((1, BLOCK), jnp.float32)]
    )
    pp = jnp.concatenate([pk, carry[None]])              # (K+1, 128)
    gl = lf[bnd_blk].reshape(-1)
    gp = pp[bnd_off].reshape(-1)                         # bnd_off holds b//chunk
    return (gp[r:] - gp[:-r]) + (gl[r:] - gl[:-r])


@dataclasses.dataclass
class DeviceLevel:
    """One strip level on device, chunked for lax.scan (pad strips are
    zero-count → contribute nothing). ``bnd_blk``/``bnd_off`` are the
    static boundary gather data from :func:`strip_boundaries`."""

    r: int
    cs: int                 # rebase granularity (boundary data's chunk)
    strips: jnp.ndarray     # (nchunks, C, r, 128) int8
    cols: jnp.ndarray       # (nchunks, C) int32
    bnd_blk: jnp.ndarray    # (nrb+1,) int32
    bnd_off: jnp.ndarray    # (nrb+1,) int32


@dataclasses.dataclass
class DeviceHybrid:
    levels: Tuple[DeviceLevel, ...]
    tail_sb: jnp.ndarray        # (nchunks, C) int32 (padded with 0)
    tail_lane: jnp.ndarray      # (nchunks, C) int8
    tail_bnd_blk: jnp.ndarray   # (nv+1,) int32 (tail_row_ptr boundaries)
    tail_bnd_off: jnp.ndarray   # (nv+1,) int32
    tail_cs: int                # tail rebase granularity
    nvb: int

    @staticmethod
    def build(
        plan: HybridPlan,
        chunk_strips: int = 16384,
        chunk_tail: int = 1 << 19,
        device=None,
    ) -> "DeviceHybrid":
        put = lambda x: jax.device_put(jnp.asarray(x), device)

        dlevels = []
        for lev in plan.levels:
            nrb = plan.nvb * (BLOCK // lev.r)
            n = lev.rows.shape[0]
            if n == 0:
                blk, off = strip_boundaries(lev.rows, 1, nrb, lev.r)
                dlevels.append(DeviceLevel(
                    r=lev.r,
                    cs=1,
                    strips=put(np.zeros((0, 1, lev.r, BLOCK), np.int8)),
                    cols=put(np.zeros((0, 1), np.int32)),
                    bnd_blk=put(blk),
                    bnd_off=put(off),
                ))
                continue
            c = min(chunk_strips, n)
            pad = (-n) % c
            st = np.concatenate(
                [lev.strips, np.zeros((pad, lev.r, BLOCK), np.int8)]
            )
            co = np.concatenate([lev.cols, np.zeros(pad, np.int32)])
            k = st.shape[0] // c
            cs = rebase_granularity(c, REBASE_STRIP) if lev.r < BLOCK else c
            blk, off = strip_boundaries(lev.rows, cs, nrb, lev.r)
            dlevels.append(DeviceLevel(
                r=lev.r,
                cs=cs,
                strips=put(st.reshape(k, c, lev.r, BLOCK)),
                cols=put(co.reshape(k, c)),
                bnd_blk=put(blk),
                bnd_off=put(off),
            ))

        m = plan.tail_sb.shape[0]
        if m == 0:
            sb = np.zeros((0, 1), np.int32)
            lane = np.zeros((0, 1), np.int8)
            c = 1
        else:
            c = min(chunk_tail, m)
            pad = (-m) % c
            sb = np.concatenate([plan.tail_sb, np.zeros(pad, np.int32)])
            lane = np.concatenate([plan.tail_lane, np.zeros(pad, np.int8)])
            sb = sb.reshape(-1, c)
            lane = lane.reshape(-1, c)
        tail_cs = rebase_granularity(c, REBASE_TAIL)
        tblk, toff = boundary_gather_data(plan.tail_row_ptr, tail_cs, 1)
        return DeviceHybrid(
            levels=tuple(dlevels),
            tail_sb=put(sb),
            tail_lane=put(lane),
            tail_bnd_blk=put(tblk),
            tail_bnd_off=put(toff),
            tail_cs=tail_cs,
            nvb=plan.nvb,
        )


def strip_level_spmv(x2d: jnp.ndarray, lev: DeviceLevel, nrb: int) -> jnp.ndarray:
    """Σ strip · x_block per destination row; returns (nrb*r,) f32.

    ``x2d`` is the (nvb, 128) f32 operand; ``nrb`` is the number of
    destination strip rows covered (``lev.cols`` may index all of ``x2d``
    while the level's strips span only a local destination range, which is
    how the sharded executor reuses this kernel per shard — boundaries for
    uncovered rows collapse to empty ranges and contribute zero).

    Per-strip contributions are an f32 broadcast-multiply-reduce on the
    VPU (int8 counts convert in-fusion). The per-row reduction is
    scatter-free: chunk-rebased prefix pairs plus a diff at the static
    row boundaries (see :func:`boundary_gather_data` for layout and
    error analysis); products themselves are exact f32.
    """
    r = lev.r

    def contrib_of(chunk):
        strips, cols = chunk
        xb = x2d[cols]                                  # (C, 128) row gather
        return (strips.astype(jnp.float32) * xb[:, None, :]).sum(-1)

    if r < BLOCK:
        def body(carry, chunk):
            out, ncarry = pack_prefix_chunk(contrib_of(chunk), carry, lev.cs)
            return ncarry, out

        zr = jnp.zeros((r,), jnp.float32)
        carry, packed = jax.lax.scan(
            body, (zr, zr), (lev.strips, lev.cols)
        )
        pk = None
    else:
        def body(carry, chunk):
            s_loc = jnp.cumsum(contrib_of(chunk), axis=0)   # (C, 128)
            out = jnp.concatenate(
                [jnp.zeros((1, r), jnp.float32), s_loc]
            )
            return carry + s_loc[-1], (out, carry)

        carry, (packed, pk) = jax.lax.scan(
            body, jnp.zeros((r,), jnp.float32), (lev.strips, lev.cols)
        )
    return prefix_pair_extract(
        packed, pk, carry, lev.bnd_blk, lev.bnd_off, r
    )


def lane_select_tail_sums(
    x2d: jnp.ndarray,
    tail_sb: jnp.ndarray,
    tail_lane: jnp.ndarray,
    bnd_blk: jnp.ndarray,
    bnd_off: jnp.ndarray,
    cs: int,
) -> jnp.ndarray:
    """Per-destination sums of tail-edge source values, fused.

    Each tail edge costs one 128-wide row gather of its source block plus
    an on-the-fly one-hot lane selection (exact f32). The per-destination
    reduction needs no scatter and no stream-scale cumsum: the scan emits
    chunk-rebased prefix pairs and the static ``tail_row_ptr`` boundaries
    (``bnd_blk``/``bnd_off`` from :func:`boundary_gather_data` at r=1)
    are diffed out. Pad edges past the real tail length land after the
    last boundary and are never read. Returns (nv,) f32.
    """
    iota = jnp.arange(BLOCK, dtype=jnp.int32)

    def body(carry, chunk):
        sb, lane = chunk
        rows = x2d[sb]                                  # (C, 128) row gather
        v = jnp.where(
            lane.astype(jnp.int32)[:, None] == iota[None, :], rows, 0.0
        ).sum(axis=1)                                   # (C,)
        out, ncarry = pack_prefix_chunk(v[:, None], carry, cs)
        return ncarry, out

    z1 = jnp.zeros((1,), jnp.float32)
    carry, packed = jax.lax.scan(body, (z1, z1), (tail_sb, tail_lane))
    return prefix_pair_extract(packed, None, carry, bnd_blk, bnd_off, 1)


def hybrid_spmv(vals: jnp.ndarray, dh: DeviceHybrid) -> jnp.ndarray:
    """Full Σ vals[src] per destination over all layouts; (nv,) f32 in,
    (nv,) f32 out (internal vertex order)."""
    nv = vals.shape[0]
    pad = dh.nvb * BLOCK - nv
    x2d = jnp.pad(vals, (0, pad)).reshape(dh.nvb, BLOCK)

    acc = jnp.zeros(dh.nvb * BLOCK, jnp.float32)
    for lev in dh.levels:
        acc = acc + strip_level_spmv(x2d, lev, dh.nvb * (BLOCK // lev.r))
    acc = acc[:nv]

    return acc + lane_select_tail_sums(
        x2d, dh.tail_sb, dh.tail_lane,
        dh.tail_bnd_blk, dh.tail_bnd_off, dh.tail_cs,
    )


for _cls, _data, _meta in (
    (DeviceLevel, ["strips", "cols", "bnd_blk", "bnd_off"], ["r", "cs"]),
    (DeviceHybrid,
     ["levels", "tail_sb", "tail_lane", "tail_bnd_blk", "tail_bnd_off"],
     ["tail_cs", "nvb"]),
):
    jax.tree_util.register_dataclass(_cls, data_fields=_data, meta_fields=_meta)
