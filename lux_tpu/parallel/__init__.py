from lux_tpu.parallel.mesh import make_mesh, PARTS_AXIS
from lux_tpu.parallel.shard import ShardedGraph

__all__ = ["make_mesh", "PARTS_AXIS", "ShardedGraph"]
