"""Device-mesh construction.

The reference's placement layer is `LuxMapper` (core/lux_mapper.cc): a
Legion mapper that slices one point task per partition round-robin across
nodes/GPUs and routes regions to framebuffer or zero-copy memory. On TPU,
placement *is* the sharding: a 1-D `jax.sharding.Mesh` over all devices
with the graph partition axis named ``parts``. XLA's SPMD partitioner then
plays the mapper's role — one shard of every array per device, collectives
over ICI (and DCN across slices) instead of ZC staging.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PARTS_AXIS = "parts"


def make_mesh(
    num_parts: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """1-D mesh of ``num_parts`` devices (default: all visible devices).

    ``num_parts`` folds node and per-node device counts into one axis the
    way the reference folds them into ``numParts = gpus × nodes``
    (pagerank/pagerank.cc:51-53).
    """
    if devices is None:
        devices = jax.devices()
    if num_parts is not None:
        if num_parts > len(devices):
            raise ValueError(
                f"num_parts={num_parts} > available devices {len(devices)}"
            )
        devices = devices[:num_parts]
    return Mesh(np.asarray(devices), (PARTS_AXIS,))


def parts_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (P, ...) stacked per-part arrays: leading axis on the
    parts axis."""
    return NamedSharding(mesh, P(PARTS_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
