"""Multi-host / multi-slice execution support.

The reference goes multi-node by rebuilding with GASNet (`USE_GASNET=1`,
README.md:33-37); its application code is unchanged — node count only
folds into the partition count. The TPU equivalent keeps the same
property: the engines only see a 1-D ``parts`` mesh, and this module is
where that mesh comes from in distributed settings.

- **single host, N chips**: `make_mesh(N)` (parallel.mesh) — ICI only.
- **multi-host / multi-slice**: call :func:`initialize` once per process
  (JAX's distributed runtime — the GASNet analogue), then
  :func:`make_global_mesh`. Devices are ordered slice-major so that
  neighboring partitions land on the same slice: the ghost-value
  all-gather then decomposes into intra-slice ICI traffic plus a smaller
  inter-slice DCN phase, which XLA schedules automatically from the
  sharding (the "collectives ride ICI, not DCN" layout rule).

Nothing else in the framework changes across 1 chip → v5p-64: the
executors are SPMD over whatever mesh they're handed.
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from lux_tpu.parallel.mesh import make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Start JAX's distributed runtime (no-op if already initialized).

    With TPU metadata available (GCE/GKE), bare ``initialize()`` suffices;
    arguments are for manual clusters.
    """
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        msg = str(e).lower()
        # jax 0.9: "distributed.initialize should only be called once.";
        # older versions said "already initialized".
        if "only be called once" not in msg and "already initialized" not in msg:
            raise


def ordered_devices(devices, num_parts: Optional[int] = None):
    """Slice-major device ordering + the shrink validation, as a pure
    function over anything device-shaped (slice_index / process_index /
    id attributes) so it is unit-testable without a real multi-host
    topology. Returns the full ordered list (shrinking happens in
    make_mesh); raises if ``num_parts`` would orphan a process."""
    devices = sorted(
        devices,
        key=lambda d: (
            getattr(d, "slice_index", 0) or 0,
            d.process_index,
            d.id,
        ),
    )
    if num_parts is not None and num_parts < len(devices):
        kept = devices[:num_parts]
        all_procs = {d.process_index for d in devices}
        kept_procs = {d.process_index for d in kept}
        if kept_procs != all_procs:
            raise ValueError(
                f"num_parts={num_parts} would exclude every device of "
                f"processes {sorted(all_procs - kept_procs)}; all "
                "processes must participate in a multi-controller mesh"
            )
    return devices


def make_global_mesh(num_parts: Optional[int] = None) -> Mesh:
    """1-D ``parts`` mesh over all global devices, slice-major ordered.

    ``num_parts`` may only shrink the mesh as long as every participating
    process keeps at least one device — in multi-controller JAX all
    processes must own a piece of the computation.
    """
    import jax

    return make_mesh(
        num_parts, devices=ordered_devices(jax.devices(), num_parts)
    )
