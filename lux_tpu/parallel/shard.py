"""Sharded (padded, stacked) graph layout for SPMD execution.

The reference gives each GPU a contiguous vertex range plus its in-edge
block (edge-balanced partitioning, core/pull_model.inl:108-131) and lets
Legion materialize whole-region reads for remote vertex values
(pull_model.inl:454-461). The TPU equivalent:

- every per-part array is padded to the maximum part size and stacked into
  a leading ``(P, ...)`` axis sharded over the mesh's ``parts`` axis —
  XLA requires equal shard shapes, so padding replaces Legion's
  variable-size regions;
- a remote vertex read indexes the *flattened padded* value array
  ``(P * max_nv,)``; the per-edge index ``src_pidx = part(src) * max_nv +
  local(src)`` is precomputed on the host once (the analogue of the
  reference's per-part ``in_vtxs`` gather list, pagerank_gpu.cu:229-241);
- pad edges point at a trash segment (``dst_local == max_nv``) so they
  vanish in the segment reduction regardless of combiner; pad vertices
  carry ``vertex_mask == False``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from lux_tpu.graph.graph import Graph
from lux_tpu.graph.partition import ExchangePlan, PartitionInfo
from lux_tpu.utils import flags


def exchange_mode() -> str:
    """The requested sharded exchange mode (``LUX_EXCHANGE``), validated.

    Executors capture this at build time (jit traces once), so a flag
    flip mid-process only affects engines built after it — the serving
    pool keys carry the mode for exactly this reason."""
    v = (flags.get("LUX_EXCHANGE") or "full").strip().lower()
    if v not in ("full", "compact", "frontier"):
        raise ValueError(
            f"LUX_EXCHANGE={v!r}: use 'full' (whole-shard all_gather), "
            "'compact' (needed-rows packed exchange), or 'frontier' "
            "(active-rows packed exchange with static-compact downgrade)"
        )
    return v


def resolve_exchange(sg: "ShardedGraph", log=None, frontier_ok: bool = False):
    """(mode, plan) an executor should build with: the requested mode,
    downgraded to ``("full", None)`` whenever compaction cannot help —
    P=1 (compaction must be a no-op: the build emits the exact full-mode
    program), released edge arrays (no plan can be derived), or an
    unprofitable plan (densest pair needs >= max_nv rows, so packing
    would move more than the all_gather). ``frontier`` additionally
    needs an executor whose exchange carries per-iteration activity
    (``frontier_ok``) — the frontier-less executors honestly run the
    static compact plan instead. Downgrades are logged, never silent."""
    mode = exchange_mode()
    if mode == "full":
        return "full", None
    if sg.num_parts <= 1:
        return "full", None
    plan = sg.exchange_plan()
    why = None
    if plan is None:
        why = "edge arrays were released before a plan was built"
    elif not plan.profitable:
        why = (f"capacity {plan.capacity} >= max_nv {sg.max_nv}: packing "
               "would move more rows than the all_gather")
        plan = None
    if plan is None:
        if log is not None:
            log.info("LUX_EXCHANGE=%s falling back to full: %s", mode, why)
        return "full", None
    if mode == "frontier" and not frontier_ok:
        if log is not None:
            log.info(
                "LUX_EXCHANGE=frontier: this executor's exchange has no "
                "per-iteration activity plane; using the static compact plan"
            )
        return "compact", plan
    return mode, plan


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclasses.dataclass(eq=False)
class ShardedGraph:
    """Host-side stacked/padded CSC shards (device placement happens in the
    executor via a ``NamedSharding`` on the leading axis)."""

    graph: Graph
    info: PartitionInfo
    num_parts: int
    max_nv: int                 # padded per-part vertex count
    max_ne: int                 # padded per-part edge count
    # (P, max_ne) stacked edge arrays:
    src_pidx: np.ndarray        # int32 index into flattened (P*max_nv,) values
    src_global: np.ndarray      # int32 global source id (pad: 0)
    dst_local: np.ndarray       # int32 local dst id; == max_nv for pad edges
    edge_mask: np.ndarray       # bool, False on pad edges
    weights: Optional[np.ndarray]   # int32 or None
    # (P, max_nv + 1):
    local_row_ptr: np.ndarray   # int32 CSC offsets within the part's block
    # (P, max_nv):
    out_degrees: np.ndarray     # int32 (global out-degree of each local vtx)
    in_degrees: np.ndarray      # int32
    vertex_mask: np.ndarray     # bool, False on pad vertices
    # (P,):
    local_nv: np.ndarray        # int32 real vertex count per part
    row_left: np.ndarray        # int64 global id of local vertex 0

    @staticmethod
    def build(
        graph: Graph,
        num_parts: int,
        nv_multiple: int = 8,
        ne_multiple: int = 128,
    ) -> "ShardedGraph":
        info = PartitionInfo.build(graph.row_ptr, num_parts)
        P = num_parts
        part_nv = np.array(
            [max(r - l + 1, 0) for (l, r) in info.bounds], dtype=np.int64
        )
        part_ne = np.array(
            [e - s for (s, e) in info.edge_bounds], dtype=np.int64
        )
        max_nv = _round_up(max(int(part_nv.max()), 1), nv_multiple)
        max_ne = _round_up(max(int(part_ne.max()), 1), ne_multiple)

        # Global vertex id → (part, local id). Parts are contiguous ranges,
        # so part(v) = searchsorted over the range starts.
        lefts = np.array(
            [l for (l, r) in info.bounds if r >= l], dtype=np.int64
        )
        nonempty = np.array(
            [i for i, (l, r) in enumerate(info.bounds) if r >= l],
            dtype=np.int64,
        )

        def part_of(v: np.ndarray) -> np.ndarray:
            idx = np.searchsorted(lefts, v, side="right") - 1
            return nonempty[idx]

        row_left_full = np.zeros(P, dtype=np.int64)
        for i, (l, r) in enumerate(info.bounds):
            row_left_full[i] = l

        src_pidx = np.zeros((P, max_ne), dtype=np.int32)
        src_global = np.zeros((P, max_ne), dtype=np.int32)
        dst_local = np.full((P, max_ne), max_nv, dtype=np.int32)
        edge_mask = np.zeros((P, max_ne), dtype=bool)
        weights = (
            np.zeros((P, max_ne), dtype=np.int32)
            if graph.weights is not None
            else None
        )
        local_row_ptr = np.zeros((P, max_nv + 1), dtype=np.int32)
        out_deg = np.zeros((P, max_nv), dtype=np.int32)
        in_deg = np.zeros((P, max_nv), dtype=np.int32)
        vertex_mask = np.zeros((P, max_nv), dtype=bool)

        g_out = graph.out_degrees
        g_in = graph.in_degrees
        for p, ((l, r), (es, ee)) in enumerate(
            zip(info.bounds, info.edge_bounds)
        ):
            n_v = max(r - l + 1, 0)
            n_e = ee - es
            if n_v == 0:
                continue
            # graph.col_src may be an np.memmap at RMAT27 scale
            # (read_lux_mmap) — slice-then-convert keeps host cost to
            # one part's edges at a time, and the local dsts come from
            # the part's row_ptr slice rather than the global col_dst
            # expansion (an 8.6 GB materialization at 2^31 edges).
            srcs = np.asarray(graph.col_src[es:ee]).astype(np.int64)
            sp = part_of(srcs)
            src_pidx[p, :n_e] = (
                sp * max_nv + (srcs - row_left_full[sp])
            ).astype(np.int32)
            src_global[p, :n_e] = srcs.astype(np.int32)
            local_in = np.diff(graph.row_ptr[l : r + 2])
            dst_local[p, :n_e] = np.repeat(
                np.arange(n_v, dtype=np.int32), local_in
            )
            edge_mask[p, :n_e] = True
            if weights is not None:
                weights[p, :n_e] = graph.weights[es:ee]
            local_row_ptr[p, 1 : n_v + 1] = (
                graph.row_ptr[l + 1 : r + 2] - es
            ).astype(np.int32)
            local_row_ptr[p, n_v + 1 :] = n_e
            out_deg[p, :n_v] = g_out[l : r + 1]
            in_deg[p, :n_v] = g_in[l : r + 1]
            vertex_mask[p, :n_v] = True

        return ShardedGraph(
            graph=graph,
            info=info,
            num_parts=P,
            max_nv=max_nv,
            max_ne=max_ne,
            src_pidx=src_pidx,
            src_global=src_global,
            dst_local=dst_local,
            edge_mask=edge_mask,
            weights=weights,
            local_row_ptr=local_row_ptr,
            out_degrees=out_deg,
            in_degrees=in_deg,
            vertex_mask=vertex_mask,
            local_nv=part_nv.astype(np.int32),
            row_left=row_left_full,
        )

    def release_edge_arrays(self):
        """Drop the stacked per-edge host arrays (the ~13 bytes/edge that
        dominate host RSS at RMAT27 scale) once they are resident on
        device. ``to_padded``/``from_padded`` keep working — they only
        need the partition bounds; ``build_push_csr`` does not."""
        self.src_pidx = self.src_global = None
        self.dst_local = self.edge_mask = self.weights = None

    # -- remote-read index ------------------------------------------------

    def remote_read_counts(self) -> Optional[np.ndarray]:
        """(P, P) int64 matrix C where ``C[q, p]`` is the number of
        *distinct* rows of part p's padded shard table that part q's real
        edges gather — the needed-rows index: row q of the all_gather is
        only useful to part q up to ``C[q, :].sum()`` rows out of
        ``P * max_nv`` exchanged. The exchange ledger (obs/engobs.py)
        prices useful-bytes from the off-diagonal, and the ROADMAP item-1
        needed-rows exchange will send exactly these rows.

        Computed once from ``src_pidx``/``edge_mask`` and cached on the
        instance; returns the cached matrix after
        ``release_edge_arrays``, or None when the arrays were released
        before the index was ever built.
        """
        cached = getattr(self, "_remote_read_counts", None)
        if cached is not None:
            return cached
        if self.src_pidx is None or self.edge_mask is None:
            return None
        P = self.num_parts
        counts = np.zeros((P, P), dtype=np.int64)
        for q in range(P):
            rows = np.unique(self.src_pidx[q][self.edge_mask[q]])
            if rows.size:
                counts[q] += np.bincount(
                    rows // self.max_nv, minlength=P
                ).astype(np.int64)
        self._remote_read_counts = counts
        return counts

    def exchange_plan(self, capacity: Optional[int] = None):
        """Row-granular :class:`ExchangePlan` for the compacted exchange
        (``LUX_EXCHANGE=compact``): per-(sender → receiver) send-row
        index tables derived from the same ``src_pidx``/``edge_mask``
        data that feeds :meth:`remote_read_counts`, padded to one static
        per-pair capacity.

        Cached on the instance (default capacity only) like the
        remote-read index; returns the cached plan after
        ``release_edge_arrays``, or None when the arrays were released
        before a plan was ever built. An explicit ``capacity`` too small
        for the densest pair raises (loud, never truncating)."""
        cached = getattr(self, "_exchange_plan", None)
        if capacity is None and cached is not None:
            return cached
        if self.src_pidx is None or self.edge_mask is None:
            return cached
        plan = ExchangePlan.from_src_pidx(
            self.src_pidx, self.edge_mask, self.max_nv, self.num_parts,
            capacity=capacity,
        )
        if capacity is None:
            self._exchange_plan = plan
        return plan

    # -- push-direction (CSR-by-global-src) view -------------------------

    def build_push_csr(self):
        """Per-shard CSR of the part's edges keyed by *global* source id.

        The reference gives every GPU a full global push row-pointer array
        restricted to its local edge set (the ``nv * numParts`` region,
        core/push_model.inl:321-324,449-465) so any device can expand any
        frontier vertex against its local edges. Same here: shard p's
        ``push_row_ptr`` spans all nv global sources (+2 pad entries so the
        sentinel id ``nv`` reads zero degree), and ``push_dst_local``/
        ``push_weights`` hold the part's edges re-sorted by source.

        Returns (push_row_ptr (P, nv+2) int32, push_dst_local (P, max_ne)
        int32 with pad == max_nv, push_weights (P, max_ne) int32 or None).
        """
        P, nv = self.num_parts, self.graph.nv
        rp = np.zeros((P, nv + 2), dtype=np.int32)
        dstl = np.full((P, self.max_ne), self.max_nv, dtype=np.int32)
        w = (
            np.zeros((P, self.max_ne), dtype=np.int32)
            if self.weights is not None
            else None
        )
        for p in range(P):
            m = self.edge_mask[p]
            n_e = int(m.sum())
            if n_e == 0:
                continue
            srcs = self.src_global[p, :n_e].astype(np.int64)
            order = np.argsort(srcs, kind="stable")
            dstl[p, :n_e] = self.dst_local[p, :n_e][order]
            if w is not None:
                w[p, :n_e] = self.weights[p, :n_e][order]
            counts = np.bincount(srcs, minlength=nv)
            rp[p, 1 : nv + 1] = np.cumsum(counts)
            rp[p, nv + 1] = n_e
        return rp, dstl, w

    # -- host value layout conversions ----------------------------------

    def to_padded(self, global_vals: np.ndarray) -> np.ndarray:
        """(nv, *t) → (P, max_nv, *t), pad slots zero-filled."""
        trailing = global_vals.shape[1:]
        out = np.zeros(
            (self.num_parts, self.max_nv) + trailing, global_vals.dtype
        )
        for p, (l, r) in enumerate(self.info.bounds):
            if r >= l:
                out[p, : r - l + 1] = global_vals[l : r + 1]
        return out

    def from_padded(self, padded: np.ndarray) -> np.ndarray:
        """(P, max_nv, *t) → (nv, *t)."""
        trailing = padded.shape[2:]
        out = np.zeros((self.graph.nv,) + trailing, padded.dtype)
        for p, (l, r) in enumerate(self.info.bounds):
            if r >= l:
                out[l : r + 1] = padded[p, : r - l + 1]
        return out
