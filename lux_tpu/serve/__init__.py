"""Warm-engine query serving with multi-source micro-batching.

The offline CLI pays graph load + XLA compile per invocation; this
subsystem pays them once. A :class:`Session` loads the graph, keeps
compiled executors in a keyed :class:`EnginePool`, answers queries
through a bounded admission queue (:class:`MicroBatcher`), and fronts an
LRU :class:`ResultCache`. K concurrent SSSP root queries inside one
batching window run as ONE dense multi-source sweep
(engine/push.py ``MultiSourcePushExecutor``); root-free fixpoints
(PageRank, CC) are served from the cache. With ``LUX_SERVE_MESH`` (or
``ServeConfig(mesh=...)``) every engine is *sharded* over a device mesh
(``serve/mesh.py``; virtual XLA host devices on CPU) — pool keys embed
the mesh shape so warm multi-chip engines serve with zero recompiles.
``serve/http.py`` is the stdlib JSON front end:
``python -m lux_tpu.serve.http -file g.lux``.
"""

from lux_tpu.serve.batcher import MicroBatcher, Request
from lux_tpu.serve.breaker import CircuitBreaker
from lux_tpu.serve.cache import ResultCache
from lux_tpu.serve.errors import (
    BadQueryError,
    CircuitOpenError,
    DeadlineExceededError,
    PoolOverBudgetError,
    QueueFullError,
    ServeError,
    SnapshotSwapError,
)
from lux_tpu.serve.mesh import MeshSpec, ShardPlanCache, serving_mesh
from lux_tpu.serve.pool import EnginePool
from lux_tpu.serve.session import ServeConfig, Session

__all__ = [
    "Session",
    "ServeConfig",
    "MeshSpec",
    "ShardPlanCache",
    "serving_mesh",
    "EnginePool",
    "ResultCache",
    "MicroBatcher",
    "Request",
    "CircuitBreaker",
    "ServeError",
    "QueueFullError",
    "DeadlineExceededError",
    "BadQueryError",
    "SnapshotSwapError",
    "CircuitOpenError",
    "PoolOverBudgetError",
]
