"""Admission queue + multi-source micro-batcher.

One worker thread drains a bounded queue. On each wakeup it takes the
oldest request, then keeps collecting until either the batching window
closes or the batch is full — the classic inference-serving tradeoff
(window of latency traded for batched throughput), applied to graph
traversal: K root queries that share a (program, graph) key become ONE
dense multi-source sweep (engine/push.py MultiSourcePushExecutor).

Admission control:
- ``submit`` never blocks: a full queue raises ``QueueFullError``
  immediately (backpressure to the client, HTTP 429) instead of
  deadlocking producers behind a slow engine.
- every request may carry a deadline; requests whose deadline passed
  while queued are shed at dequeue with ``DeadlineExceededError`` and an
  `obs` counter increment — they never occupy engine time.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Hashable, List, Optional

from lux_tpu.obs import flight, metrics, spans
from lux_tpu.serve.errors import DeadlineExceededError, QueueFullError
from lux_tpu.utils import faults

# Batch sizes are small integers; the seconds-oriented default bucket
# bounds would collapse them into two buckets.
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, float("inf"))


@dataclass
class Request:
    """One admitted query. ``batch_key`` groups batchable requests (same
    program + graph + engine config); ``batch_key=None`` means the
    request must execute alone. ``payload`` is interpreted by the
    executor callback (for SSSP batches: the root vertex)."""

    app: str
    payload: Any
    batch_key: Optional[Hashable]
    future: Future = field(default_factory=Future)
    deadline: Optional[float] = None      # spans.monotonic() stamp
    enqueued_at: float = field(default_factory=spans.monotonic)
    # Captured at construction on the admitting thread, so the batcher
    # worker can continue the request's trace (spans.adopt).
    trace_id: Optional[str] = field(default_factory=spans.current_trace_id)
    # Per-query cost record (serve/cost.py), filled by the executor
    # callback before the future resolves; None for internal requests
    # (hot-swap barriers) and cost-unaware callers.
    cost: Any = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (now if now is not None else spans.monotonic()) > self.deadline


class MicroBatcher:
    """Bounded admission queue + window-based batch former.

    ``execute(requests)`` is called on the worker thread with a list of
    requests sharing one ``batch_key`` (or a singleton list for
    unbatchable requests); it must resolve every request's future.
    """

    def __init__(
        self,
        execute: Callable[[List[Request]], None],
        max_batch: int = 8,
        window_s: float = 0.003,
        max_queue: int = 64,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 (got {max_batch})")
        self._execute = execute
        self.max_batch = int(max_batch)
        self.window_s = float(window_s)
        self._q: "queue.Queue[Request]" = queue.Queue(maxsize=max_queue)
        self._rejected = metrics.counter("lux_serve_rejected_total")
        self._expired = metrics.counter("lux_serve_deadline_expired_total")
        self._depth = metrics.gauge("lux_serve_queue_depth")
        self._batch_hist = metrics.histogram(
            "lux_serve_batch_size", buckets=BATCH_SIZE_BUCKETS
        )
        # Event, not a bare bool: set by close() on the caller thread,
        # polled by submit() and the worker (LUX301 discipline).
        self._closed = threading.Event()
        self._carry: Optional[Request] = None   # worker-thread-only state
        self._thread = threading.Thread(
            target=self._loop, name="lux-serve-batcher", daemon=True
        )
        self._thread.start()

    # -- producer side ---------------------------------------------------

    def submit(self, req: Request) -> Future:
        """Admit ``req`` or raise ``QueueFullError`` without blocking."""
        if self._closed.is_set():
            raise QueueFullError("server is shutting down")
        with spans.span("serve.admit", app=req.app):
            try:
                self._q.put_nowait(req)
            except queue.Full:
                self._rejected.inc()
                flight.dump(
                    "queue_reject",
                    detail=f"app={req.app} queue full "
                           f"({self._q.maxsize} pending)",
                )
                raise QueueFullError(
                    f"admission queue full ({self._q.maxsize} pending); "
                    "retry"
                ) from None
            self._depth.set(self._q.qsize())
        return req.future

    # -- worker side -----------------------------------------------------

    def _collect(self, first: Request) -> List[Request]:
        """``first`` plus whatever arrives before the window closes, up
        to max_batch. Only requests matching ``first.batch_key`` extend
        the batch; a non-matching arrival ends collection and leads the
        next batch (FIFO across batches, no starvation)."""
        batch = [first]
        if first.batch_key is None or self.max_batch == 1:
            return batch
        t_close = spans.monotonic() + self.window_s
        while len(batch) < self.max_batch:
            remaining = t_close - spans.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt.batch_key == first.batch_key:
                batch.append(nxt)
            else:
                self._carry = nxt
                break
        return batch

    def _loop(self):
        while True:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = self._q.get(timeout=0.1)
                except queue.Empty:
                    if self._closed.is_set():
                        return
                    continue
            t_asm = spans.clock()
            batch = self._collect(first)
            spans.complete(
                "serve.batch_assemble", spans.clock() - t_asm,
                trace_id=first.trace_id, app=first.app, size=len(batch),
            )
            self._depth.set(self._q.qsize())
            now = spans.monotonic()
            live = []
            for r in batch:
                wait = max(0.0, now - r.enqueued_at)
                if r.expired(now):
                    self._expired.inc()
                    spans.complete("serve.queue_wait", wait,
                                   trace_id=r.trace_id, app=r.app,
                                   shed=True)
                    flight.dump(
                        "deadline_shed",
                        detail=f"app={r.app} waited {wait:.3f}s in queue",
                    )
                    r.future.set_exception(DeadlineExceededError(
                        f"deadline expired after {wait:.3f}s in queue"
                    ))
                else:
                    spans.complete("serve.queue_wait", wait,
                                   trace_id=r.trace_id, app=r.app)
                    live.append(r)
            if not live:
                continue
            self._batch_hist.observe(len(live))
            # The lead request's trace owns the engine-side spans: one
            # trace in the batch shows the full admission->batch->engine
            # ->cache chain (the serve_smoke acceptance assertion).
            with spans.adopt(live[0].trace_id):
                with spans.span("serve.batch", app=live[0].app,
                                size=len(live)):
                    try:
                        # Inside the fail-the-batch guard: an injected
                        # raise here resolves every future (terminal
                        # status), never kills the worker thread.
                        faults.point("batcher.assemble")
                        self._execute(live)
                    except Exception as e:  # engine bug: fail the batch, keep serving
                        flight.dump("engine_exception", detail=repr(e))
                        for r in live:
                            if not r.future.done():
                                r.future.set_exception(e)

    def close(self, timeout: float = 5.0):
        """Stop admitting, drain the worker, fail leftover requests."""
        self._closed.set()
        self._thread.join(timeout)
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            r.future.set_exception(QueueFullError("server shut down"))

    def batch_histogram(self) -> dict:
        """Snapshot of the achieved batch-width histogram (/statusz)."""
        return self._batch_hist.snapshot()

    def stats(self) -> dict:
        return {
            "queue_depth": self._q.qsize(),
            "queue_capacity": self._q.maxsize,
            "rejected": int(self._rejected.value),
            "deadline_expired": int(self._expired.value),
            "batches": int(self._batch_hist.count),
            "max_batch": self.max_batch,
            "window_s": self.window_s,
        }
