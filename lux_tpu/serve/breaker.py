"""Per-key circuit breaker for the serving engines.

Retry-with-backoff (serve/session.py) absorbs *transient* engine
failures; a persistently broken executor — poisoned compile cache, bad
device state, a plan that faults on this graph — would still eat every
request's deadline one retry loop at a time. The breaker is the standard
fix: track consecutive failures per key ``(program, fingerprint)`` and,
past ``LUX_BREAKER_THRESHOLD``, shed that program instantly with
:class:`CircuitOpenError` (HTTP 503 + ``Retry-After``) while a
background *half-open probe* rebuilds the pool entry and proves one
execution before traffic returns.

State machine (per key)::

    closed --threshold consecutive failures--> open
    open   --LUX_BREAKER_COOLDOWN_MS elapsed--> half_open (probe launched)
    half_open --probe succeeds--> closed
    half_open --probe fails----> open (cooldown restarts)

Discipline: state transitions happen only under ``make_lock("breaker")``;
the probe itself (an engine rebuild + execution) runs on a tracked
background thread *outside* the lock, so the breaker can never hold its
lock across a compile (LUX303) and never takes the pool lock while
holding its own (no new lock-order edges). Probe threads are joined by
:meth:`drain_probes` (Session.close), mirroring the blessed
``drain_compactions`` shape (LUX304).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Hashable, List

from lux_tpu.obs import metrics, spans
from lux_tpu.serve.errors import CircuitOpenError
from lux_tpu.utils import flags
from lux_tpu.utils.locks import make_lock

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class _Entry:
    __slots__ = ("state", "consecutive", "opened_at", "probing", "opens",
                 "last_error")

    def __init__(self):
        self.state = CLOSED
        self.consecutive = 0
        self.opened_at = 0.0
        self.probing = False
        self.opens = 0
        self.last_error = None


class CircuitBreaker:
    """Thread-safe breaker keyed by an arbitrary hashable (the session
    keys it by ``(app, snapshot fingerprint)``).

    ``probe`` is called on a background thread with the tripped key once
    per half-open transition; it should rebuild whatever the key names
    and return True iff one execution succeeded. Threshold/cooldown are
    read from the flags registry per call, so tests and operators can
    retune a live process.
    """

    def __init__(self, probe: Callable[[Hashable], bool]):
        self._probe = probe
        self._lock = make_lock("breaker")
        self._entries: Dict[Hashable, _Entry] = {}
        self._probe_threads: List[threading.Thread] = []
        self._transitions = {
            s: metrics.counter("lux_breaker_transitions_total", {"to": s})
            for s in (OPEN, HALF_OPEN, CLOSED)
        }
        self._open_gauge = metrics.gauge("lux_breaker_open")

    @staticmethod
    def _threshold() -> int:
        return max(1, flags.get_int("LUX_BREAKER_THRESHOLD"))

    @staticmethod
    def _cooldown_s() -> float:
        return max(0.0, flags.get_float("LUX_BREAKER_COOLDOWN_MS")) / 1e3

    def _shift(self, entry: _Entry, state: str) -> None:
        # Called under self._lock.
        entry.state = state
        self._transitions[state].inc()
        tripped = self._entries.values()  # luxlint: guarded-by=_lock
        self._open_gauge.set(sum(1 for e in tripped if e.state != CLOSED))

    # -- hot path --------------------------------------------------------

    def check(self, key: Hashable) -> None:
        """Raise :class:`CircuitOpenError` while ``key`` is tripped; on
        cooldown expiry, flip to half-open and launch the single-flight
        probe (requests keep shedding until it reports back)."""
        # Lock-free fast path (one GIL-atomic dict probe per request):
        # any non-CLOSED hit is re-read under _lock before a decision.
        # luxlint: disable=LUX301 -- a stale probe only costs one retry
        entry = self._entries.get(key)
        if entry is None or entry.state == CLOSED:
            return
        now = spans.monotonic()
        cooldown = self._cooldown_s()
        launch = False
        with self._lock:
            entry = self._entries[key]
            if entry.state == CLOSED:
                return
            if (entry.state == OPEN and not entry.probing
                    and now - entry.opened_at >= cooldown):
                self._shift(entry, HALF_OPEN)
                entry.probing = True
                launch = True
            state = entry.state
            retry_after = max(0.05, entry.opened_at + cooldown - now)
        if launch:
            t = threading.Thread(target=self._run_probe, args=(key,),
                                 name="lux-breaker-probe", daemon=True)
            with self._lock:
                self._probe_threads.append(t)
            t.start()
        raise CircuitOpenError(
            f"circuit {state} for {key!r} "
            f"({self._threshold()} consecutive engine failures); "
            "background probe will close it",
            retry_after_s=round(retry_after, 3),
        )

    def record_failure(self, key: Hashable, error=None) -> None:
        """One terminal engine failure (post-retry) on ``key``."""
        with self._lock:
            entry = self._entries.setdefault(key, _Entry())
            entry.consecutive += 1
            entry.last_error = repr(error) if error is not None else None
            if entry.state == CLOSED and entry.consecutive >= self._threshold():
                entry.opened_at = spans.monotonic()
                entry.opens += 1
                self._shift(entry, OPEN)

    def record_success(self, key: Hashable) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.consecutive = 0

    # -- probe side ------------------------------------------------------

    def _run_probe(self, key: Hashable) -> None:
        ok = False
        err = None
        with spans.span("serve.breaker_probe", key=str(key)):
            try:
                ok = bool(self._probe(key))
            except Exception as e:
                err = repr(e)
        with self._lock:
            entry = self._entries.setdefault(key, _Entry())
            entry.probing = False
            if ok:
                entry.consecutive = 0
                self._shift(entry, CLOSED)
            else:
                entry.opened_at = spans.monotonic()
                entry.last_error = err or entry.last_error
                self._shift(entry, OPEN)

    def drain_probes(self, timeout: float = 30.0) -> None:
        """Join outstanding probe threads (tests / Session.close)."""
        with self._lock:
            threads = list(self._probe_threads)
        for t in threads:
            t.join(timeout)
        with self._lock:
            self._probe_threads = [
                t for t in self._probe_threads if t.is_alive()
            ]

    # -- introspection ---------------------------------------------------

    def state(self, key: Hashable) -> str:
        with self._lock:
            entry = self._entries.get(key)
            return entry.state if entry is not None else CLOSED

    def stats(self) -> dict:
        """Per-key breaker state for /statusz and flight-recorder dumps."""
        with self._lock:
            entries = {
                str(k): {
                    "state": e.state,
                    "consecutive": e.consecutive,
                    "opens": e.opens,
                    "probing": e.probing,
                    "last_error": e.last_error,
                }
                for k, e in self._entries.items()
            }
        return {
            "threshold": self._threshold(),
            "cooldown_ms": self._cooldown_s() * 1e3,
            "open": sum(1 for e in entries.values()
                        if e["state"] != CLOSED),
            "transitions": {s: int(c.value)
                            for s, c in self._transitions.items()},
            "entries": entries,
        }
