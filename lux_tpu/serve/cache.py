"""LRU result cache keyed by (graph fingerprint, query key).

PageRank/CC answers are root-independent (one converged array serves
every client) and SSSP repeats are common in online traversal traffic
(PAPERS.md: Gunrock's query mix), so a small LRU in front of the engines
turns repeat queries into dictionary hits. Keys must embed the graph
fingerprint — the hardened utils/checkpoint.fingerprint — so a server
rotated onto a new graph can never serve stale arrays.

Eviction is byte-first: entries are priced by their value's nbytes
(tree-summed) and the LRU evicts once the summed bytes exceed
``capacity_bytes`` (``LUX_RESULT_CACHE_BYTES``). An entry count still
bounds the dict — a flood of tiny entries must not grow the key set
unboundedly — but the binding constraint on graph-sized arrays is the
byte budget: one RMAT22 distance array is ~16 MiB, so "256 entries"
silently meant gigabytes before bytes were priced.
"""

from __future__ import annotations

import sys
from collections import OrderedDict
from typing import Any, Hashable, Optional

from lux_tpu.obs import metrics, spans
from lux_tpu.utils import faults, flags
from lux_tpu.utils.locks import make_lock


def _value_nbytes(value: Any) -> int:
    """Recursive nbytes of one cached value: array leaves report their
    buffer size, containers sum their children, everything else falls
    back to sys.getsizeof (host-object overhead, close enough for a
    budget)."""
    nb = getattr(value, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(value, dict):
        return sum(_value_nbytes(v) for v in value.values())
    if isinstance(value, (list, tuple, set, frozenset)):
        return sum(_value_nbytes(v) for v in value)
    return int(sys.getsizeof(value))


class ResultCache:
    """Thread-safe LRU over query results (host numpy arrays)."""

    def __init__(self, capacity: int = 256,
                 capacity_bytes: Optional[int] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1 (got {capacity})")
        self.capacity = capacity
        if capacity_bytes is None:
            capacity_bytes = flags.get_int("LUX_RESULT_CACHE_BYTES")
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1 (got {capacity_bytes})")
        self.capacity_bytes = int(capacity_bytes)
        self._d: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._sizes: dict = {}
        self._bytes = 0
        self._lock = make_lock("cache")
        self._hits = metrics.counter("lux_serve_cache_hits_total")
        self._misses = metrics.counter("lux_serve_cache_misses_total")
        self._evictions = metrics.counter("lux_serve_cache_evictions_total")
        self._invalidations = metrics.counter(
            "lux_serve_cache_invalidations_total"
        )
        self._bytes_gauge = metrics.gauge("lux_result_cache_bytes")

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self._hits.inc()
                hit, out = True, self._d[key]
            else:
                self._misses.inc()
                hit, out = False, None
        spans.complete("serve.cache.get", 0.0, hit=hit)
        return out

    def put(self, key: Hashable, value: Any) -> None:
        with spans.span("serve.cache.put"):
            faults.point("cache.put")
            size = _value_nbytes(value)
            with self._lock:
                if key in self._d:
                    self._bytes -= self._sizes.get(key, 0)
                self._d[key] = value
                self._sizes[key] = size
                self._bytes += size
                self._d.move_to_end(key)
                # Byte budget first (the binding constraint on
                # graph-sized arrays), entry count as the dict bound.
                # The newest entry is never evicted to make room for
                # itself — an oversized value simply occupies the whole
                # budget until the next put.
                while (self._bytes > self.capacity_bytes
                       or len(self._d) > self.capacity) and len(self._d) > 1:
                    k, _ = self._d.popitem(last=False)
                    self._bytes -= self._sizes.pop(k, 0)
                    self._evictions.inc()
                self._bytes_gauge.set(float(self._bytes))

    def keys(self) -> list:
        with self._lock:
            return list(self._d.keys())

    def evict_fingerprint(self, fingerprint: str) -> int:
        """Drop every entry keyed by ``fingerprint`` (hot-swap invalidation).

        Serving keys lead with the graph fingerprint, so entries for a
        retired snapshot are exactly the tuple keys whose first element
        matches. Without this they linger until LRU pressure, pinning the
        dead snapshot's arrays and inflating the /statusz hit-rate with
        unreachable entries."""
        with self._lock:
            victims = [
                k for k in self._d
                if isinstance(k, tuple) and k and k[0] == fingerprint
            ]
            for k in victims:
                del self._d[k]
                self._bytes -= self._sizes.pop(k, 0)
            if victims:
                self._invalidations.inc(len(victims))
                self._bytes_gauge.set(float(self._bytes))
        return len(victims)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def stats(self) -> dict:
        with self._lock:
            nbytes = self._bytes
        return {
            "size": len(self),
            "capacity": self.capacity,
            "bytes": int(nbytes),
            "capacity_bytes": self.capacity_bytes,
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "evictions": int(self._evictions.value),
            "invalidations": int(self._invalidations.value),
        }
