"""LRU result cache keyed by (graph fingerprint, query key).

PageRank/CC answers are root-independent (one converged array serves
every client) and SSSP repeats are common in online traversal traffic
(PAPERS.md: Gunrock's query mix), so a small LRU in front of the engines
turns repeat queries into dictionary hits. Keys must embed the graph
fingerprint — the hardened utils/checkpoint.fingerprint — so a server
rotated onto a new graph can never serve stale arrays.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Hashable, Optional

from lux_tpu.obs import metrics, spans
from lux_tpu.utils import faults
from lux_tpu.utils.locks import make_lock


class ResultCache:
    """Thread-safe LRU over query results (host numpy arrays)."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1 (got {capacity})")
        self.capacity = capacity
        self._d: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._lock = make_lock("cache")
        self._hits = metrics.counter("lux_serve_cache_hits_total")
        self._misses = metrics.counter("lux_serve_cache_misses_total")
        self._evictions = metrics.counter("lux_serve_cache_evictions_total")
        self._invalidations = metrics.counter(
            "lux_serve_cache_invalidations_total"
        )

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self._hits.inc()
                hit, out = True, self._d[key]
            else:
                self._misses.inc()
                hit, out = False, None
        spans.complete("serve.cache.get", 0.0, hit=hit)
        return out

    def put(self, key: Hashable, value: Any) -> None:
        with spans.span("serve.cache.put"):
            faults.point("cache.put")
            with self._lock:
                self._d[key] = value
                self._d.move_to_end(key)
                while len(self._d) > self.capacity:
                    self._d.popitem(last=False)
                    self._evictions.inc()

    def keys(self) -> list:
        with self._lock:
            return list(self._d.keys())

    def evict_fingerprint(self, fingerprint: str) -> int:
        """Drop every entry keyed by ``fingerprint`` (hot-swap invalidation).

        Serving keys lead with the graph fingerprint, so entries for a
        retired snapshot are exactly the tuple keys whose first element
        matches. Without this they linger until LRU pressure, pinning the
        dead snapshot's arrays and inflating the /statusz hit-rate with
        unreachable entries."""
        with self._lock:
            victims = [
                k for k in self._d
                if isinstance(k, tuple) and k and k[0] == fingerprint
            ]
            for k in victims:
                del self._d[k]
            if victims:
                self._invalidations.inc(len(victims))
        return len(victims)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def stats(self) -> dict:
        return {
            "size": len(self),
            "capacity": self.capacity,
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "evictions": int(self._evictions.value),
            "invalidations": int(self._invalidations.value),
        }
