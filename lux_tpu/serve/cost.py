"""Per-query cost attribution: what did answering this request spend?

Latency (obs/slo.py) tells you how long a tenant waited; it says
nothing about what the tenant *consumed* — a cache-hit PageRank and a
12-iteration sharded SSSP sweep both read as "fast". The admission
quotas of ROADMAP item 5 need the consumption signal, per tenant:

- :class:`QueryCost` rides one request end to end (created at
  ``Session.submit``, filled on the batcher thread before the future
  resolves): iterations, engine-execute seconds, exchange bytes,
  direction switches, cache outcome. Batch members split the batch's
  engine cost evenly, so per-query costs sum to the batch totals.
- :class:`CostAccounts` is the per-tenant rollup (SloWindows idiom:
  bounded observation deques + rolling-window quantiles, plus
  cumulative totals). ``snapshot()`` is the ``/costz`` payload; the
  totals are fed in lockstep with the ``lux_query_cost_*{tenant}``
  metrics, so the two always agree.

Tenancy comes from the ``X-Lux-Tenant`` header (serve/http.py) or the
``tenant=`` submit kwarg; unlabeled traffic books to ``default``.
"""

from __future__ import annotations

import bisect
from collections import deque
from typing import Dict, Optional

from ..obs import metrics, spans
from ..obs.slo import MAX_OBSERVATIONS, _quantile, windows_from_flags
from ..utils.locks import make_lock

DEFAULT_TENANT = "default"


class QueryCost:
    """Mutable cost record for one admitted query.

    Written on the batcher thread *before* ``future.set_result`` (the
    happens-before edge done-callbacks and ``.result()`` readers need),
    read after the future resolves.
    """

    __slots__ = ("tenant", "app", "outcome", "iterations",
                 "engine_s", "exchange_bytes", "direction_switches",
                 "latency_s")

    def __init__(self, tenant: Optional[str], app: str):
        self.tenant = str(tenant) if tenant else DEFAULT_TENANT
        self.app = app
        self.outcome = "miss"        # "hit" when the result cache answered
        self.iterations = 0
        self.engine_s = 0.0
        self.exchange_bytes = 0
        self.direction_switches = 0
        self.latency_s = 0.0

    def charge(self, iterations: int = 0, engine_s: float = 0.0,
               exchange_bytes: int = 0, direction_switches: int = 0):
        """Accumulate engine spend (a retried batch charges each
        attempt's share — the tenant consumed that time either way)."""
        self.iterations += int(iterations)
        self.engine_s += float(engine_s)
        self.exchange_bytes += int(exchange_bytes)
        self.direction_switches += int(direction_switches)

    def as_dict(self) -> dict:
        return {
            "tenant": self.tenant, "app": self.app,
            "outcome": self.outcome, "iterations": self.iterations,
            "engine_s": self.engine_s,
            "exchange_bytes": self.exchange_bytes,
            "direction_switches": self.direction_switches,
            "latency_s": self.latency_s,
        }

    def header(self) -> str:
        """Compact ``X-Lux-Cost`` response-header value."""
        return (
            "tenant={};outcome={};iters={};engine_s={:.6f};"
            "exchange_bytes={};switches={}".format(
                self.tenant, self.outcome, self.iterations,
                self.engine_s, self.exchange_bytes,
                self.direction_switches)
        )


class CostAccounts:
    """Per-tenant rolling + cumulative cost accounting (thread-safe).

    The cumulative totals and the ``lux_query_cost_*{tenant}`` metrics
    are incremented in the same :meth:`observe` call, so ``/costz``
    totals and metric deltas can never drift apart.
    """

    def __init__(self, windows=None, now=None):
        self.windows = tuple(windows) if windows else windows_from_flags()
        self._now = now or spans.clock
        self._lock = make_lock("serve.costs")
        self._obs: Dict[str, deque] = {}       # tenant -> (ts, engine_s)
        self._totals: Dict[str, dict] = {}

    def observe(self, cost: QueryCost):
        t = cost.tenant
        now = self._now()
        with self._lock:
            dq = self._obs.get(t)
            if dq is None:
                dq = self._obs[t] = deque(maxlen=MAX_OBSERVATIONS)
            dq.append((now, cost.engine_s))
            tot = self._totals.get(t)
            if tot is None:
                tot = self._totals[t] = {
                    "requests": 0, "hits": 0, "misses": 0,
                    "iterations": 0, "engine_s": 0.0,
                    "exchange_bytes": 0, "direction_switches": 0,
                }
            tot["requests"] += 1
            tot["hits" if cost.outcome == "hit" else "misses"] += 1
            tot["iterations"] += cost.iterations
            tot["engine_s"] += cost.engine_s
            tot["exchange_bytes"] += cost.exchange_bytes
            tot["direction_switches"] += cost.direction_switches
        lbl = {"tenant": t}
        metrics.counter("lux_query_cost_requests_total",
                        dict(lbl, outcome=cost.outcome)).inc()
        metrics.counter("lux_query_cost_engine_seconds", lbl).inc(
            max(0.0, cost.engine_s))
        metrics.counter("lux_query_cost_exchange_bytes", lbl).inc(
            max(0, cost.exchange_bytes))
        metrics.counter("lux_query_cost_iterations_total", lbl).inc(
            max(0, cost.iterations))

    def totals(self) -> Dict[str, dict]:
        with self._lock:
            return {t: dict(v) for t, v in self._totals.items()}

    def snapshot(self) -> dict:
        """The ``/costz`` payload: cumulative totals plus rolling
        engine-seconds quantiles per window per tenant."""
        now = self._now()
        out = {"schema": "costz.v1", "totals": self.totals(),
               "windows": {}}
        with self._lock:
            items = [(t, list(dq)) for t, dq in self._obs.items()]
        for w in self.windows:
            wkey = f"{int(w)}s"
            block = {}
            for tenant, obs in items:
                cut = now - w
                lo = bisect.bisect_right(obs, (cut, float("inf")))
                xs = sorted(x for _ts, x in obs[lo:])
                if not xs:
                    continue
                block[tenant] = {
                    "count": len(xs),
                    "engine_s_p50": _quantile(xs, 0.50),
                    "engine_s_p99": _quantile(xs, 0.99),
                }
            out["windows"][wkey] = block
        return out
