"""Serving-layer error taxonomy.

Each class maps to one HTTP status in serve/http.py and one `obs`
counter, so clients and dashboards see the same three failure modes:
overload (backpressure), timeout (deadline shed), and bad input.
"""

from __future__ import annotations


class ServeError(Exception):
    """Base class for serving failures.

    ``retry_after_s`` (when not None) is surfaced by serve/http.py as a
    ``Retry-After`` header so well-behaved clients back off instead of
    hammering an overloaded or tripped server."""

    http_status = 500
    retry_after_s = None


class QueueFullError(ServeError):
    """The bounded admission queue is full — backpressure, not deadlock.

    The client should retry with backoff; the server sheds instantly
    instead of queueing unboundedly (HTTP 429)."""

    http_status = 429
    retry_after_s = 1.0


class DeadlineExceededError(ServeError):
    """The request's deadline expired before execution started (or the
    batch it rode in missed it); HTTP 504."""

    http_status = 504
    retry_after_s = 1.0


class BadQueryError(ServeError):
    """Malformed query: unknown app, missing/out-of-range parameters
    (HTTP 400)."""

    http_status = 400


class SnapshotSwapError(ServeError):
    """A snapshot hot-swap could not complete (engine warmup timed out or
    failed). The previous version keeps serving — the swap is abandoned,
    not half-applied; the client may retry (HTTP 503)."""

    http_status = 503
    retry_after_s = 2.0


class PoolOverBudgetError(ServeError):
    """The HBM-budgeted engine pool cannot admit this build: its
    memcap.v1-predicted footprint exceeds the per-device budget
    (LUX_HBM_BUDGET_BYTES, default device capacity x
    LUX_HBM_BUDGET_FRAC) even after evicting every cold engine. Shed
    with 503 + Retry-After — admitting would OOM the device, and the
    static tier (LUX703) exists so this is reached only by budgets
    tighter than the bench-scale contract."""

    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 2.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class CircuitOpenError(ServeError):
    """The circuit breaker for this (program, fingerprint) is open: the
    engine failed ``LUX_BREAKER_THRESHOLD`` consecutive times and is
    being rebuilt/probed in the background. Shed with 503 + Retry-After
    instead of burning the batcher on an executor known to be bad."""

    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
