"""Serving-layer error taxonomy.

Each class maps to one HTTP status in serve/http.py and one `obs`
counter, so clients and dashboards see the same three failure modes:
overload (backpressure), timeout (deadline shed), and bad input.
"""

from __future__ import annotations


class ServeError(Exception):
    """Base class for serving failures."""

    http_status = 500


class QueueFullError(ServeError):
    """The bounded admission queue is full — backpressure, not deadlock.

    The client should retry with backoff; the server sheds instantly
    instead of queueing unboundedly (HTTP 429)."""

    http_status = 429


class DeadlineExceededError(ServeError):
    """The request's deadline expired before execution started (or the
    batch it rode in missed it); HTTP 504."""

    http_status = 504


class BadQueryError(ServeError):
    """Malformed query: unknown app, missing/out-of-range parameters
    (HTTP 400)."""

    http_status = 400


class SnapshotSwapError(ServeError):
    """A snapshot hot-swap could not complete (engine warmup timed out or
    failed). The previous version keeps serving — the swap is abandoned,
    not half-applied; the client may retry (HTTP 503)."""

    http_status = 503
