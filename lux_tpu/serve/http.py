"""Stdlib JSON/HTTP front end over a serving Session.

Endpoints:

- ``POST /query`` — body ``{"app": "sssp", "start": 3}`` (apps from the
  program registry: rooted apps take ``"start"``, pagerank ``"ni"``,
  kcore ``"k"``); optional
  ``"deadline_s"`` (per-request deadline), ``"targets": [v, ...]``
  (return only those vertices' values) or ``"full": true`` (the whole
  value array — gated by a size cap so a misdirected client cannot pull
  multi-GB arrays through JSON). Default response carries summary stats
  only.
- ``GET /healthz`` — liveness: graph identity (nv, ne, fingerprint),
  pool warmth, device reachability.
- ``GET /stats`` — pool/cache/batcher counters + latency quantiles.
- ``GET /metrics`` — Prometheus text exposition of the `obs` registry
  (``lux_xla_compiles_total``, ``lux_ir_findings_total``, span
  histograms, ...); ``GET /metrics.json`` keeps the JSON snapshot.
- ``GET /statusz`` — rolling 1-min/5-min SLO windows (p50/p95/p99 per
  app), queue depth, cache hit rate, batch-width histogram, shed and
  recompile counters (JSON; windows set by ``LUX_STATUSZ_WINDOWS``).
- ``GET /costz`` — per-tenant cost accounting (serve/cost.py):
  cumulative totals (requests, engine seconds, exchange bytes,
  iterations, hit/miss) plus rolling engine-seconds quantiles per
  ``LUX_STATUSZ_WINDOWS`` window. Tenancy comes from the
  ``X-Lux-Tenant`` request header on ``POST /query`` (default tenant
  otherwise); each query's own spend comes back in ``X-Lux-Cost``.
- ``GET /snapshot`` — the serving snapshot version, fingerprint, delta
  ratio, and the store's version history.
- ``POST /snapshot`` — admin edit endpoint: body
  ``{"insert": [[u, v], ...], "delete": [[u, v], ...]}`` (weighted
  graphs take ``[u, v, w]`` inserts) applies the batch and hot-swaps
  serving onto version N+1 (serve/session.py ``apply_edits``); the old
  version drains and keeps answering throughout. 503 when warmup of the
  new version times out (the old version keeps serving). Add
  ``"queue": true`` to durably enqueue behind the WAL without swapping,
  or send ``{"flush": true}`` alone to fold the queue / retry an
  aborted swap (serve/session.py ``enqueue_edits``/``flush_edits``).
- ``POST /profilez`` — body ``{"steps": N}``: run a programmatic
  device-timeline capture window (obs/prof.py) over N engine steps and
  return the parsed ``profile.v1`` report. 403 unless ``LUX_PROF_DIR``
  is set; 429 while another capture is in flight.

Every JSON response carries ``X-Lux-Snapshot: <serving version>`` so
clients can observe a hot-swap from response headers alone, and is
counted into ``lux_requests_total{code=...}``. Degraded serving (a
failed N+1 warm; version N still answering) adds ``X-Lux-Degraded``
with the version that failed; shed responses (429/503/504) carry
``Retry-After`` seconds from the error taxonomy (serve/errors.py) or
the circuit breaker's cooldown remainder (serve/breaker.py). Query
responses answered by engines built under a tuned config
(lux_tpu/tune) add ``X-Lux-Tuned: <tuneconf.v1 artifact id>``.

Every ``POST /query`` runs under a root request span (obs/spans.py):
the response carries the trace-id in ``X-Lux-Trace``, and the same id
keys the request's async lane in the Chrome trace. ``SIGUSR1`` (CLI
mode) dumps a flight.v1 postmortem to ``LUX_FLIGHT_DIR``; ``SIGUSR2``
toggles a profiler capture window under ``LUX_PROF_DIR``.

Error mapping: ``BadQueryError`` → 400, ``QueueFullError`` → 429,
``DeadlineExceededError`` → 504 (serve/errors.py owns the taxonomy).

``ThreadingHTTPServer`` gives one thread per in-flight request, which is
exactly what the micro-batcher wants: concurrent requests are all parked
inside the batching window and come out as one multi-source sweep.
"""

from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from lux_tpu.obs import flight, metrics, prof, spans
from lux_tpu.serve.errors import ServeError, BadQueryError
from lux_tpu.serve.session import ServeConfig, Session
from lux_tpu.utils import flags
from lux_tpu.utils.logging import get_logger

# Above this many vertices, "full": true is refused; use "targets".
FULL_VALUES_CAP = 1 << 20


def _jsonable(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def render_result(result: dict, body: dict, nv: int) -> dict:
    """Shape one engine result for the wire: targets / full / summary.

    Per-vertex extras beyond ``values`` (GAS host finalizations: BFS
    ``parent``, labelprop ``labels``, kcore ``alive``) follow the same
    mode as ``values`` — sliced under ``targets``, whole under ``full``,
    dropped in summary mode — so the size cap governs them too. Scalar
    extras (iters, direction split, num_communities, ...) always pass."""
    vals = result["values"]
    extras = {k: v for k, v in result.items()
              if k != "values" and isinstance(v, np.ndarray)
              and v.shape == (nv,)}
    out = {k: _jsonable(v) for k, v in result.items()
           if k != "values" and k not in extras}
    targets = body.get("targets")
    if targets is not None:
        targets = [int(t) for t in targets]
        bad = [t for t in targets if not 0 <= t < nv]
        if bad:
            raise BadQueryError(f"targets out of range [0, {nv}): {bad}")
        out["targets"] = targets
        out["values"] = [_jsonable(vals[t]) for t in targets]
        for k, v in extras.items():
            out[k] = [_jsonable(v[t]) for t in targets]
    elif body.get("full"):
        if nv > FULL_VALUES_CAP:
            raise BadQueryError(
                f"full values refused for nv={nv} > {FULL_VALUES_CAP}; "
                "use 'targets'"
            )
        out["values"] = vals.tolist()
        for k, v in extras.items():
            out[k] = v.tolist()
    else:
        out["summary"] = {
            "min": _jsonable(vals.min()),
            "max": _jsonable(vals.max()),
            "mean": float(np.asarray(vals, dtype=np.float64).mean()),
        }
    return out


class _Handler(BaseHTTPRequestHandler):
    # Set by make_server():
    session: Session = None
    log = None

    protocol_version = "HTTP/1.1"

    def _reply(self, status: int, payload: dict,
               trace_id: str = None, retry_after: float = None,
               cost: str = None, tuned: dict = None,
               evicted: int = None):
        body = json.dumps(payload).encode()
        # Counted HERE and only here, so every terminal status — success,
        # shed, breaker-open, handler bug — lands in one per-code series
        # (the chaos harness sums these against requests issued).
        metrics.counter("lux_requests_total", {"code": str(status)}).inc()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if trace_id:
            self.send_header("X-Lux-Trace", trace_id)
        if cost:
            # What this query spent (serve/cost.py): tenant, cache
            # outcome, iterations, engine seconds, exchange bytes.
            self.send_header("X-Lux-Cost", cost)
        if retry_after is not None:
            # Shed responses (429/503/504) tell clients when to come
            # back instead of letting them hammer a known-bad window.
            self.send_header("Retry-After", f"{max(0.0, retry_after):.3f}")
        if tuned:
            # Tune provenance: which tuneconf.v1 artifact the answering
            # engines were built under (absent on default-config apps),
            # so a client-side A/B can attribute latency to the tuner.
            self.send_header("X-Lux-Tuned", tuned["id"])
        if evicted:
            # Swap summaries note HBM-budget pool evictions: warming
            # N+1 displaced this many cold engines (serve/pool.py
            # footprint-weighted LRU under LUX_HBM_BUDGET_BYTES).
            self.send_header("X-Lux-Evicted", str(evicted))
        if self.session is not None:
            self.send_header("X-Lux-Snapshot", str(self.session.version))
            degraded = self.session.degraded
            if degraded is not None:
                # Stale-while-revalidate marker: the served version is
                # live but a newer one failed to warm (serve/session.py).
                self.send_header("X-Lux-Degraded",
                                 str(degraded.get("failed_version")))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, status: int, body: str,
                    content_type: str = "text/plain; version=0.0.4"):
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):   # route through lux logging
        if self.log is not None:
            self.log.debug("%s " + fmt, self.address_string(), *args)

    def do_GET(self):
        s = self.session
        if self.path == "/healthz":
            pool_warm = len(s.pool) > 0
            try:
                import jax

                device = jax.devices()[0].platform
            except Exception:
                device = None
            self._reply(200 if pool_warm else 503, {
                "ok": bool(pool_warm), "nv": s.graph.nv, "ne": s.graph.ne,
                "fingerprint": s.fingerprint,
                "pool_warm": pool_warm, "engines": len(s.pool),
                "device": device,
            })
        elif self.path == "/stats":
            self._reply(200, s.stats())
        elif self.path == "/statusz":
            self._reply(200, s.statusz())
        elif self.path == "/costz":
            self._reply(200, s.costz())
        elif self.path == "/metrics":
            self._reply_text(200, metrics.render_prometheus())
        elif self.path == "/metrics.json":
            self._reply(200, {"metrics": metrics.snapshot()})
        elif self.path == "/snapshot":
            self._reply(200, s.snapshot_info())
        else:
            self._reply(404, {"error": f"no such endpoint {self.path}"})

    def do_POST(self):
        if self.path == "/snapshot":
            self._post_snapshot()
            return
        if self.path == "/profilez":
            self._post_profilez()
            return
        if self.path != "/query":
            self._reply(404, {"error": f"no such endpoint {self.path}"})
            return
        # The ROOT span of the request trace: handler-thread work plus
        # (via the Future the session blocks on) the batcher/engine
        # spans that adopt this trace-id on other threads.
        with spans.span("http.request", path=self.path) as tid:
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise BadQueryError("body must be a JSON object")
                app = body.get("app")
                params = {
                    k: v for k, v in body.items()
                    if k in ("start", "ni", "k")
                }
                fut = self.session.submit(
                    app, deadline_s=body.get("deadline_s"),
                    tenant=self.headers.get("X-Lux-Tenant"), **params
                )
                result = fut.result()
                qc = getattr(fut, "_lux_cost", None)
                self._reply(
                    200, render_result(result, body, self.session.graph.nv),
                    trace_id=tid,
                    cost=qc.header() if qc is not None else None,
                    tuned=self.session.tuned_for(app),
                )
            except ServeError as e:
                self._reply(e.http_status, {
                    "error": str(e), "kind": type(e).__name__,
                }, trace_id=tid, retry_after=e.retry_after_s)
            except json.JSONDecodeError as e:
                self._reply(400, {"error": f"bad JSON: {e}",
                                  "kind": "BadQueryError"}, trace_id=tid)
            except Exception as e:   # engine bug: surface, keep serving
                self._reply(500, {"error": str(e),
                                  "kind": type(e).__name__}, trace_id=tid)

    def _post_snapshot(self):
        from lux_tpu.graph.delta import EdgeEdits

        # Its own root span: one trace-id covers the whole swap —
        # snapshot.apply, the background warm (it adopts this id), the
        # incremental refresh, and the drain barrier.
        with spans.span("http.request", path=self.path) as tid:
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise BadQueryError("body must be a JSON object")
                if body.get("flush") and not (body.get("insert")
                                              or body.get("delete")):
                    # Revalidate / coalesce: fold whatever is queued (or
                    # retry an aborted swap) without new edits.
                    summary = self.session.flush_edits()
                    self._reply(200, summary, trace_id=tid,
                                evicted=summary.get("hbm_evicted"))
                    return
                try:
                    edits = EdgeEdits.from_lists(
                        insert=body.get("insert", ()),
                        delete=body.get("delete", ()),
                    )
                except (TypeError, ValueError, IndexError) as e:
                    raise BadQueryError(f"bad edit batch: {e}")
                if body.get("queue"):
                    # WAL-backed write-behind: durable immediately,
                    # swapped on the next flush (ROADMAP item 3).
                    summary = self.session.enqueue_edits(edits)
                else:
                    summary = self.session.apply_edits(edits)
                self._reply(200, summary, trace_id=tid,
                            evicted=summary.get("hbm_evicted"))
            except ServeError as e:
                self._reply(e.http_status, {
                    "error": str(e), "kind": type(e).__name__,
                }, trace_id=tid, retry_after=e.retry_after_s)
            except json.JSONDecodeError as e:
                self._reply(400, {"error": f"bad JSON: {e}",
                                  "kind": "BadQueryError"}, trace_id=tid)
            except Exception as e:   # swap bug: surface, keep serving
                self._reply(500, {"error": str(e),
                                  "kind": type(e).__name__}, trace_id=tid)

    def _post_profilez(self):
        """``POST /profilez {"steps": N}`` — programmatic capture
        window: N engine steps under ``jax.profiler.trace``, parsed into
        the ``profile.v1`` report returned as the response body. Guarded:
        403 when ``LUX_PROF_DIR`` is unset (profiling unarmed — captures
        must be an explicit operator decision, not a default-on endpoint
        anyone can hit), 429 when a capture is already in flight (one
        window at a time; concurrent queries keep serving either way)."""
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise BadQueryError("body must be a JSON object")
            if not flags.get("LUX_PROF_DIR"):
                self._reply(403, {
                    "error": "profiling unarmed: set LUX_PROF_DIR",
                    "kind": "ProfilingDisabled"})
                return
            try:
                steps = int(body.get("steps", 8))
            except (TypeError, ValueError):
                raise BadQueryError("'steps' must be an integer")
            rep = self.session.profile_capture(steps)
            self._reply(200, rep)
        except prof.CaptureBusyError as e:
            self._reply(429, {"error": str(e), "kind": "CaptureBusyError"},
                        retry_after=1.0)
        except BadQueryError as e:
            self._reply(400, {"error": str(e), "kind": "BadQueryError"})
        except json.JSONDecodeError as e:
            self._reply(400, {"error": f"bad JSON: {e}",
                              "kind": "BadQueryError"})
        except Exception as e:   # capture bug: surface, keep serving
            self._reply(500, {"error": str(e),
                              "kind": type(e).__name__})

    # query() futures raise ServeError subclasses; unwrap happens via
    # Future.result() re-raising them directly, so do_POST's except
    # clauses see the original types.


def make_server(
    session: Session, host: str = "127.0.0.1", port: int = 8399
) -> ThreadingHTTPServer:
    """An HTTP server bound to ``host:port`` serving ``session``; the
    caller owns ``serve_forever`` (run it in a thread for embedding)."""
    handler = type("LuxServeHandler", (_Handler,), {
        "session": session, "log": get_logger("serve.http"),
    })
    return ThreadingHTTPServer((host, port), handler)


def serve_in_thread(session: Session, host="127.0.0.1", port=0):
    """Start a server on a background thread; returns (server, thread).
    ``port=0`` binds an ephemeral port — read ``server.server_address``."""
    server = make_server(session, host, port)
    t = threading.Thread(
        target=server.serve_forever, name="lux-serve-http", daemon=True
    )
    t.start()
    return server, t


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="lux_tpu.serve", description="warm-engine graph query server"
    )
    p.add_argument("-file", required=True, help="input .lux graph")
    p.add_argument("-host", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8399)
    p.add_argument("-max-batch", type=int, default=8, dest="max_batch",
                   help="multi-source lanes per SSSP sweep")
    p.add_argument("-window-ms", type=float, default=3.0, dest="window_ms",
                   help="micro-batching window")
    p.add_argument("-max-queue", type=int, default=64, dest="max_queue",
                   help="admission queue bound (backpressure beyond)")
    p.add_argument("-deadline-s", type=float, default=None,
                   dest="deadline_s", help="default per-request deadline")
    p.add_argument("-pagerank-iters", type=int, default=20,
                   dest="pagerank_iters")
    p.add_argument("-mesh", default=None,
                   help="serving mesh spec ('8' or 'PxQ'); default "
                   "LUX_SERVE_MESH. Virtual XLA host devices on CPU")
    args = p.parse_args(argv)

    log = get_logger("serve")
    cfg = ServeConfig(
        max_batch=args.max_batch,
        window_s=args.window_ms / 1e3,
        max_queue=args.max_queue,
        default_deadline_s=args.deadline_s,
        pagerank_iters=args.pagerank_iters,
        mesh=args.mesh,
    )
    session = Session(args.file, cfg)
    server = make_server(session, args.host, args.port)
    if flight.install_signal_handler():
        log.info("SIGUSR1 -> flight.v1 postmortem (LUX_FLIGHT_DIR=%s)",
                 flags.get("LUX_FLIGHT_DIR"))
    if prof.install_signal_handler():
        log.info("SIGUSR2 -> profiler capture toggle (LUX_PROF_DIR=%s)",
                 flags.get("LUX_PROF_DIR"))
    log.info(
        "serving %s (nv=%d ne=%d) on http://%s:%d  "
        "[max_batch=%d window=%.1fms queue=%d mesh=%s]",
        args.file, session.graph.nv, session.graph.ne,
        args.host, server.server_address[1],
        cfg.max_batch, cfg.window_s * 1e3, cfg.max_queue,
        session.meshspec.spec,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        session.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
