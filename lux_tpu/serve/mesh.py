"""Serving mesh resolution + the snapshot-keyed shard-plan cache.

Multi-chip serving has two pieces of state the per-query path must never
rebuild:

- the **device mesh** itself: ``LUX_SERVE_MESH`` (or ``ServeConfig.mesh``)
  names a device count (``"8"``) or a ``PxQ`` shape (``"2x4"``), folded
  onto the 1-D ``parts`` axis exactly as the CLI folds ``-parts N``
  (parallel/mesh.py). On a CPU host the mesh is *virtual* — XLA host
  devices via ``--xla_force_host_platform_device_count``, the same
  mechanism the RMAT27 tooling uses — so the whole sharded serving path
  is CI-testable on one machine.
- the **partition plan**: :class:`~lux_tpu.parallel.shard.ShardedGraph`
  is a host-side O(ne) construction (edge-balanced bounds, padded
  stacked CSC shards, the push CSR). Every sharded executor for one
  (snapshot, parts) pair must share ONE plan, and a hot-swap must evict
  the outgoing snapshot's plans the same way it retires its engines —
  that is :class:`ShardPlanCache`, keyed ``(fingerprint, num_parts)``.

Resolution order for virtual devices: the flags are appended to
``XLA_FLAGS`` *before* the first backend touch, so a Session constructed
early in a process gets its mesh for free; once any jax backend is
initialized the device count is frozen and a too-small mesh raises with
the bootstrap instructions (tools/serve_bench.py ``--mesh`` and
tests/conftest.py both set the env up front).
"""

from __future__ import annotations

import dataclasses
import os
from collections import OrderedDict
from typing import Optional, Tuple

from lux_tpu.obs import metrics
from lux_tpu.utils import flags
from lux_tpu.utils.locks import make_lock
from lux_tpu.utils.logging import get_logger


def parse_mesh_spec(spec) -> Tuple[int, ...]:
    """``"8"`` -> (8,), ``"2x4"`` -> (2, 4). Every factor must be a
    positive integer; the product is the partition count (the shape is
    kept for pool keys and /statusz, the 1-D parts axis gets the fold)."""
    text = str(spec).strip().lower()
    if not text:
        raise ValueError(
            "empty mesh spec: use a device count ('8') or a PxQ shape "
            "('2x4'); '1' serves single-chip"
        )
    try:
        shape = tuple(int(d) for d in text.split("x"))
    except ValueError:
        raise ValueError(
            f"bad mesh spec {spec!r}: use a device count ('8') or a "
            "PxQ shape ('2x4')"
        ) from None
    if not shape or any(d < 1 for d in shape):
        raise ValueError(
            f"bad mesh spec {spec!r}: every factor must be >= 1"
        )
    return shape


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A resolved serving mesh: the parsed shape (the pool-key
    component), the folded partition count, and the jax Mesh (None for
    single-chip serving — the executors take the single-device path)."""

    spec: str                  # the string as given ("2x4")
    shape: Tuple[int, ...]     # parsed shape ((2, 4))
    num_parts: int             # folded product (8)
    mesh: object               # jax.sharding.Mesh | None when num_parts == 1


def serving_mesh(spec: Optional[str] = None) -> MeshSpec:
    """Resolve ``spec`` (default: the ``LUX_SERVE_MESH`` flag) to a
    :class:`MeshSpec`, bootstrapping virtual CPU devices when possible."""
    raw = spec if spec is not None else flags.get("LUX_SERVE_MESH")
    shape = parse_mesh_spec(raw if raw is not None else "1")
    n = 1
    for d in shape:
        n *= d
    if n == 1:
        return MeshSpec(spec=str(raw), shape=shape, num_parts=1, mesh=None)
    _ensure_devices(n, str(raw))
    from lux_tpu.parallel.mesh import make_mesh

    return MeshSpec(
        spec=str(raw), shape=shape, num_parts=n, mesh=make_mesh(n)
    )


def _ensure_devices(n: int, spec: str) -> None:
    """Best-effort virtual-device bootstrap, then a hard check.

    Setting XLA_FLAGS is only effective before the first backend touch —
    afterwards it is a harmless no-op, and the ``jax.devices()`` check
    below reports the real capacity either way."""
    from lux_tpu.utils.platform import virtual_cpu_flags

    os.environ["XLA_FLAGS"] = virtual_cpu_flags(n)
    import jax

    forced = flags.get("LUX_PLATFORM")
    if forced:
        try:
            jax.config.update("jax_platforms", forced)
        # luxlint: disable=LUX007 -- best-effort: the jax.devices() check below surfaces any failure
        except Exception:
            pass   # backend already up; the device check decides below
    have = len(jax.devices())
    if have < n:
        raise ValueError(
            f"serving mesh {spec!r} needs {n} devices but only {have} "
            f"are visible. On CPU, set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} (and "
            "LUX_PLATFORM=cpu) before any jax import — "
            "tools/serve_bench.py --mesh does this automatically"
        )


class ShardPlanCache:
    """LRU of host-side partition plans keyed ``(fingerprint, parts)``.

    One :class:`ShardedGraph` build is O(ne) host work (~seconds at
    RMAT24); every sharded executor the pool warms for one snapshot —
    push, multi-source push, pull — shares the entry, and ``apply_edits``
    warms the incoming fingerprint's plan exactly once. The hot-swap
    drain calls :meth:`evict_fingerprint` next to ``pool.retire`` so a
    swap atomically replaces the *mesh* of engines and its plan."""

    def __init__(self):
        self._lock = make_lock("mesh.plans")
        self._plans = OrderedDict()  # luxlint: guarded-by=_lock
        self._hits = metrics.counter("lux_serve_plan_hits_total")
        self._misses = metrics.counter("lux_serve_plan_misses_total")
        self._evicted = metrics.counter("lux_serve_plan_evicted_total")
        self.log = get_logger("serve")

    def get(self, fingerprint: str, graph, num_parts: int):
        """The plan for ``(fingerprint, num_parts)``, building it on
        first request. ``graph`` must be the snapshot's Graph object —
        the executors validate plan/graph identity, so a cached plan
        built from a *different* object with the same content is rebuilt
        in place rather than handed out."""
        from lux_tpu.parallel.shard import ShardedGraph

        key = (fingerprint, int(num_parts))
        with self._lock:
            sg = self._plans.get(key)
            if sg is not None and sg.graph is graph:
                self._plans.move_to_end(key)
                self._hits.inc()
                return sg
            self._misses.inc()
            # Build under the lock for the same reason EnginePool does:
            # two concurrent warmups for one snapshot must not do the
            # O(ne) partition twice.
            # luxlint: disable=LUX303 -- single-build guarantee needs the lock
            sg = ShardedGraph.build(graph, int(num_parts))
            self._plans[key] = sg
            self._plans.move_to_end(key)
            cap = max(1, flags.get_int("LUX_SHARD_PLAN_CACHE"))
            while len(self._plans) > cap:
                old_key, _ = self._plans.popitem(last=False)
                self._evicted.inc()
                self.log.info("shard-plan cache evicted %r (LRU, cap %d)",
                              old_key, cap)
            return sg

    def evict_fingerprint(self, fingerprint: str) -> int:
        """Drop every plan built for ``fingerprint`` (hot-swap drain)."""
        with self._lock:
            victims = [k for k in self._plans if k[0] == fingerprint]
            for k in victims:
                del self._plans[k]
            if victims:
                self._evicted.inc(len(victims))
        return len(victims)

    def clear(self) -> int:
        with self._lock:
            n = len(self._plans)
            self._plans.clear()
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._plans)

    def stats(self) -> dict:
        return {
            "plans": len(self),
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "evicted": int(self._evicted.value),
            "capacity": max(1, flags.get_int("LUX_SHARD_PLAN_CACHE")),
        }


_PLANS = ShardPlanCache()


def plan_cache() -> ShardPlanCache:
    """The process-wide plan cache (sessions serving the same snapshot
    share partition work; keys embed the fingerprint so plans can never
    leak across graphs)."""
    return _PLANS
