"""Warm-engine pool: compiled executors keyed so repeats never recompile.

The CLI path (models/cli.py) builds a fresh executor — graph transfer +
XLA compile — per invocation; a served query must not. The pool keys an
executor by everything that changes its executable: (program name, graph
fingerprint, engine kind, parts, strategy/batch-width), builds it at most
once, warms it (compile outside any request), and hands the same object
to every subsequent query.

Evidence that the contract holds comes at two levels: hit/miss counters
(an engine was or wasn't rebuilt) and a
:class:`~lux_tpu.analysis.sentinel.RecompileSentinel` counting actual
XLA backend compiles per key — builds run under ``expect(key)``, the
session executes queries under ``watch(key)``, and any compile landing
in a watch region is a recompile the stats (and the serve tests) flag.

Residency is HBM-budgeted: callers that know an engine's predicted
per-device footprint (the memcap.v1 admission formula,
``analysis/memck.predicted_engine_bytes``) pass it to :meth:`get`, and
the pool admits the build only if the summed resident bytes fit the
budget (``LUX_HBM_BUDGET_BYTES``, default device capacity x
``LUX_HBM_BUDGET_FRAC``), evicting cold engines by footprint-weighted
LRU first. An engine that cannot fit even in an empty pool is refused
with :class:`~lux_tpu.serve.errors.PoolOverBudgetError` (HTTP 503 +
Retry-After) — shedding beats OOMing the device mid-batch. Warm hits
never evict, so the zero-recompile contract on repeat traffic is
untouched by the budget.
"""

from __future__ import annotations

from typing import Callable, Hashable, Optional

from lux_tpu.analysis.sentinel import RecompileSentinel
from lux_tpu.obs import metrics, spans
from lux_tpu.serve.errors import PoolOverBudgetError
from lux_tpu.utils import faults, flags
from lux_tpu.utils.locks import make_lock


class EnginePool:
    """Thread-safe keyed singleton store for warmed executors."""

    def __init__(self, scope: str = "serve"):
        self._engines = {}
        self._lock = make_lock("pool")
        self._hits = metrics.counter("lux_serve_pool_hits_total")
        self._misses = metrics.counter("lux_serve_pool_misses_total")
        # Created eagerly so a clean pool still exports 0 — the serve
        # dashboards alert on this going nonzero, not on its absence.
        self._ir_findings = metrics.counter("lux_ir_findings_total")
        self._exch_findings = metrics.counter("lux_exch_findings_total")
        self._gas_findings = metrics.counter("lux_gas_findings_total")
        self._retired = metrics.counter("lux_serve_pool_retired_total")
        # HBM residency accounting: predicted resident bytes per key
        # (memcap.v1 admission formula) + last-hit clock for the
        # footprint-weighted LRU.
        self._resident = {}
        self._last_hit = {}
        self._hbm_gauge = metrics.gauge("lux_pool_hbm_resident_bytes")
        self._hbm_evictions = metrics.counter(
            "lux_pool_hbm_evictions_total")
        self.sentinel = RecompileSentinel(scope)

    def get(self, key: Hashable, factory: Callable[[], object],
            footprint_bytes: Optional[int] = None):
        """The executor for ``key``, building (and warming, if the
        executor has a ``warmup``) via ``factory`` on first request.

        ``footprint_bytes`` is the build's predicted per-device resident
        footprint (memcap.v1); when given, admission runs first —
        evicting cold engines until the build fits the HBM budget, or
        raising :class:`PoolOverBudgetError` if it never can. Hits skip
        admission entirely (and refresh the key's LRU clock).

        The build runs under the lock: concurrent first requests for one
        key must not compile twice, and the serving layer funnels engine
        work through one batcher thread anyway."""
        with self._lock:
            ex = self._engines.get(key)
            if ex is not None:
                self._hits.inc()
                self._last_hit[key] = spans.clock()
                return ex
            self._admit(key, footprint_bytes)
            self._misses.inc()
            # spans.span (not trace.span): a build triggered by a live
            # request joins that request's trace; warmup builds root
            # their own.
            with spans.span("serve.engine_build", key=str(key)):
                with self.sentinel.expect(key):
                    faults.point("pool.build")
                    ex = factory()
                    if hasattr(ex, "warmup"):
                        # First-build warmup deliberately holds the lock:
                        # releasing would let a concurrent request compile
                        # the same engine twice. LockWatch hold warnings
                        # track the cost instead.
                        # luxlint: disable=LUX303 -- single-compile guarantee needs the lock
                        ex.warmup()
            self._audit(key, ex)
            self._audit_exchange(key, ex)
            self._audit_programs(key, ex)
            self._engines[key] = ex
            self._last_hit[key] = spans.clock()
            if footprint_bytes is not None:
                self._resident[key] = int(footprint_bytes)
                self._hbm_gauge.set(float(sum(self._resident.values())))
            return ex

    def _admit(self, key: Hashable, footprint_bytes: Optional[int]):
        """Fit ``footprint_bytes`` under the HBM budget, evicting cold
        engines by footprint-weighted LRU (idle_seconds x bytes,
        coldest-and-fattest first). Caller holds the lock. No-op when
        admission is disabled, unpriced, or unbudgeted — the static
        tier (LUX703) already proved bench scales fit real devices, so
        a live budget only engages when configured tighter."""
        if footprint_bytes is None:
            return
        if not flags.get_bool("LUX_MEM_POOL_ADMIT"):
            return
        from lux_tpu.analysis import memck
        budget = memck.hbm_budget_bytes()
        if budget is None:
            return
        need = int(footprint_bytes)
        if need > budget:
            raise PoolOverBudgetError(
                f"engine {key!r} predicted footprint {need} B exceeds "
                f"the per-device HBM budget {budget} B even with an "
                "empty pool (LUX_HBM_BUDGET_BYTES / "
                "LUX_HBM_BUDGET_FRAC)")
        now = spans.clock()
        while sum(self._resident.values()) + need > budget:
            victims = [k for k in self._resident if k in self._engines]
            if not victims:
                # Remaining residency belongs to nothing evictable
                # (stale accounting); drop it rather than deadlock.
                self._resident = {k: v for k, v in self._resident.items()
                                  if k in self._engines}
                if sum(self._resident.values()) + need <= budget:
                    break
                raise PoolOverBudgetError(
                    f"engine {key!r} predicted footprint {need} B does "
                    f"not fit the HBM budget {budget} B and no resident "
                    "engine remains to evict")
            coldest = max(
                victims,
                key=lambda k: (now - self._last_hit.get(k, 0.0))
                * max(1, self._resident[k]))
            del self._engines[coldest]
            self._resident.pop(coldest, None)
            self._last_hit.pop(coldest, None)
            self._retired.inc()
            self._hbm_evictions.inc()
        self._hbm_gauge.set(float(sum(self._resident.values())))

    def _audit(self, key: Hashable, ex) -> None:
        """LUX104 donation audit on the freshly built engine: one abstract
        lowering, no execution. A finding means an iteration buffer the
        engine thinks it reuses is actually copied every step — flagged
        once at build time (``lux_ir_findings_total``), never per query."""
        if not flags.get_bool("LUX_IR_POOL_AUDIT"):
            return
        if not hasattr(ex, "trace_step"):
            return
        from lux_tpu.analysis import ir
        try:
            findings = ir.audit_engine(ex, f"pool@{key}")
        # luxlint: disable=LUX007 -- advisory audit: a failed lowering must never take down a build
        except Exception:
            return
        for f in findings:
            self._ir_findings.inc()
            print(f"EnginePool: {f.format()}")

    def _audit_exchange(self, key: Hashable, ex) -> None:
        """LUX401-403 plan audit on the freshly built engine: pure numpy
        over the live ExchangePlan tables, no tracing. A finding means
        the packed all_to_all this engine is about to serve with drops
        or duplicates rows — flagged once at build time
        (``lux_exch_findings_total``), never per query."""
        if not flags.get_bool("LUX_EXCH_POOL_AUDIT"):
            return
        if getattr(ex, "_xplan", None) is None:
            return
        from lux_tpu.analysis import exchck
        findings = exchck.audit_exchange(ex, f"pool@{key}")
        for f in findings:
            self._exch_findings.inc()
            print(f"EnginePool: {f.format()}")

    def _audit_programs(self, key: Hashable, ex) -> None:
        """LUX601/602/605 program-algebra audit on the freshly built
        engine: probe-grid identity/exactness/annihilation in host
        numpy, no graph trace (gasck caches per program identity, so
        the k-th engine for a program costs a dict lookup). A finding
        means the combiner algebra this engine's masking and sharded
        accumulation rely on does not actually hold — flagged once at
        build time (``lux_gas_findings_total``), never per query."""
        if not flags.get_bool("LUX_GAS_POOL_AUDIT"):
            return
        prog = getattr(ex, "program", None)
        if prog is None:
            return
        from lux_tpu.analysis import gasck
        try:
            findings = gasck.audit_program(prog, f"pool@{key}")
        # luxlint: disable=LUX007 -- advisory audit: a failed probe must never take down a build
        except Exception:
            return
        for f in findings:
            self._gas_findings.inc()
            print(f"EnginePool: {f.format()}")

    def retire(self, predicate: Callable[[Hashable], bool]) -> int:
        """Drop every engine whose key satisfies ``predicate`` (hot-swap:
        the session retires all engines keyed by the outgoing snapshot's
        fingerprint once in-flight queries drain). Dropped executors are
        released to GC — device buffers for a dead snapshot's graph are
        the largest thing a swap frees."""
        with self._lock:
            victims = [k for k in self._engines if predicate(k)]
            for k in victims:
                del self._engines[k]
                self._resident.pop(k, None)
                self._last_hit.pop(k, None)
            if victims:
                self._retired.inc(len(victims))
                self._hbm_gauge.set(float(sum(self._resident.values())))
        return len(victims)

    def __len__(self) -> int:
        with self._lock:
            return len(self._engines)

    def keys(self) -> list:
        """Snapshot of the live engine keys (observability: /statusz
        groups pool entries by the mesh-shape key component)."""
        with self._lock:
            return list(self._engines)

    def hbm_resident_bytes(self) -> int:
        """Summed memcap.v1-predicted bytes of the resident engines
        (only engines admitted with a footprint contribute)."""
        with self._lock:
            return int(sum(self._resident.values()))

    def stats(self) -> dict:
        return {
            "engines": len(self),
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "retired": int(self._retired.value),
            "warmup_compiles": self.sentinel.compiles(),
            "recompiles": self.sentinel.recompiles(),
            "ir_findings": int(self._ir_findings.value),
            "exch_findings": int(self._exch_findings.value),
            "gas_findings": int(self._gas_findings.value),
            "hbm_resident_bytes": self.hbm_resident_bytes(),
            "hbm_evictions": int(self._hbm_evictions.value),
        }

    def close(self):
        self.sentinel.close()
