"""Programmatic serving session: graph loaded once, engines warm, queries
answered through the micro-batcher.

Query routing:

- ``sssp`` (root queries, the dominant online traversal workload) —
  batchable: K concurrent roots inside one batching window run as ONE
  dense multi-source sweep over ``(nv, K)`` values; a batch of one runs
  on the adaptive single-source ``PushExecutor`` (its sparse tiers beat a
  1-lane dense sweep). Both executors live in the warm pool, so neither
  path recompiles after warmup.
- ``pagerank`` — served from the LRU cache of converged results (one
  fixpoint array answers every client at a given iteration count); cache
  misses run the pull executor once.
- ``components`` — root-free like PageRank: one converged labeling is
  cached and sliced per query.

Every result cache key embeds the hardened graph fingerprint
(utils/checkpoint.fingerprint), so answers can never leak across graphs.
"""

from __future__ import annotations

import contextlib
import threading
from concurrent.futures import Future
from typing import Dict, List, Optional, Union

import numpy as np

from lux_tpu.graph.graph import Graph
from lux_tpu.obs import flight, metrics, slo, spans
from lux_tpu.serve.batcher import MicroBatcher, Request
from lux_tpu.serve.cache import ResultCache
from lux_tpu.serve.errors import BadQueryError
from lux_tpu.serve.pool import EnginePool
from lux_tpu.utils import checkpoint
from lux_tpu.utils.logging import get_logger


class ServeConfig:
    """Serving knobs (one object so the HTTP CLI, tools, and tests agree
    on defaults)."""

    def __init__(
        self,
        max_batch: int = 8,          # K: multi-source lanes per sweep
        window_s: float = 0.003,     # batching window
        max_queue: int = 64,         # admission queue bound
        cache_capacity: int = 256,   # LRU entries
        default_deadline_s: Optional[float] = None,
        pagerank_iters: int = 20,    # served fixpoint depth
    ):
        self.max_batch = int(max_batch)
        self.window_s = float(window_s)
        self.max_queue = int(max_queue)
        self.cache_capacity = int(cache_capacity)
        self.default_deadline_s = default_deadline_s
        self.pagerank_iters = int(pagerank_iters)


class Session:
    """One served graph: load once, keep engines warm, answer queries.

    Thread-safe: ``submit``/``query`` may be called from any number of
    request threads; engine work funnels through the batcher thread.
    """

    APPS = ("sssp", "components", "pagerank")

    def __init__(
        self,
        graph: Union[Graph, str],
        config: Optional[ServeConfig] = None,
        warm: bool = True,
    ):
        self.log = get_logger("serve")
        self.config = config or ServeConfig()
        self.graph_path: Optional[str] = None
        if isinstance(graph, str):
            from lux_tpu.native import io as native_io

            self.graph_path = graph
            graph = native_io.read_lux(graph)
        self.graph = graph
        self.fingerprint = checkpoint.fingerprint_hex(graph)
        self.pool = EnginePool()
        self.cache = ResultCache(self.config.cache_capacity)
        self.batcher = MicroBatcher(
            self._execute_batch,
            max_batch=self.config.max_batch,
            window_s=self.config.window_s,
            max_queue=self.config.max_queue,
        )
        self._requests = metrics.counter("lux_serve_requests_total")
        self._latency = metrics.histogram("lux_serve_request_seconds")
        self.slo = slo.SloWindows()
        self._served_keys = set()   # batcher-thread only
        self._closed = False
        self._flight_name = f"session:{self.fingerprint[:12]}"
        flight.add_context(self._flight_name, self._flight_context)
        if warm:
            self.warmup()

    # -- engines ---------------------------------------------------------

    def _engine_key(self, kind: str, extra=()) -> tuple:
        return (kind, self.fingerprint) + tuple(extra)

    def _sssp_single(self):
        from lux_tpu.engine.push import PushExecutor
        from lux_tpu.models.sssp import SSSP

        return self.pool.get(
            self._engine_key("push", ("sssp", 1)),
            lambda: PushExecutor(self.graph, SSSP()),
        )

    def _sssp_multi(self):
        from lux_tpu.engine.push import MultiSourcePushExecutor
        from lux_tpu.models.sssp import SSSP

        k = self.config.max_batch
        return self.pool.get(
            self._engine_key("push_multi", ("sssp", k)),
            lambda: MultiSourcePushExecutor(self.graph, SSSP(), k=k),
        )

    def _components_engine(self):
        from lux_tpu.engine.push import PushExecutor
        from lux_tpu.models.components import ConnectedComponents

        return self.pool.get(
            self._engine_key("push", ("components", 1)),
            lambda: PushExecutor(self.graph, ConnectedComponents()),
        )

    def _pagerank_engine(self):
        from lux_tpu.models.cli import make_executor
        from lux_tpu.models.pagerank import PageRank

        def build():
            from lux_tpu.engine.pull import PullExecutor

            if self.graph_path is None:
                # The tiled fast path persists its hybrid plan next to
                # the graph file; an in-memory graph has none, so serve
                # from the flat pull engine.
                return PullExecutor(self.graph, PageRank())
            import argparse

            # Reuse the CLI's engine-selection policy (tiled when
            # SpMV-shaped) with serving defaults.
            args = argparse.Namespace(
                parts=1, layout="auto", strategy="rowptr",
                levels="8/2", tile_mb=8192, plan_cache=None,
                file=self.graph_path,
            )
            return make_executor(self.graph, PageRank(), args, self.log)

        return self.pool.get(
            self._engine_key("pull", ("pagerank",)), build
        )

    def warmup(self):
        """Build + compile every served engine before traffic arrives.
        After this, the pool miss counter is the recompile count: the
        smoke test asserts it stays flat across the query phase."""
        with spans.span("serve.warmup"):
            with _timed(self.log, "warmup sssp single"):
                self._sssp_single()
            with _timed(self.log, "warmup sssp multi"):
                self._sssp_multi()
            with _timed(self.log, "warmup components"):
                self._components_engine()
            with _timed(self.log, "warmup pagerank"):
                self._pagerank_engine()

    # -- query front door ------------------------------------------------

    def submit(
        self,
        app: str,
        deadline_s: Optional[float] = None,
        **params,
    ) -> Future:
        """Admit one query; returns a Future resolving to a dict with at
        least ``values`` (np.ndarray) and ``iters``. Raises
        ``BadQueryError`` on malformed queries and ``QueueFullError``
        under overload; the Future raises ``DeadlineExceededError`` when
        shed."""
        if self._closed:
            raise BadQueryError("session is closed")
        app = str(app)
        if app not in self.APPS:
            raise BadQueryError(
                f"unknown app {app!r}; serving {list(self.APPS)}"
            )
        self._requests.inc()
        metrics.counter(
            "lux_serve_requests_total", {"app": app}
        ).inc()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline = (
            spans.monotonic() + deadline_s if deadline_s is not None
            else None
        )
        t0 = spans.clock()
        # Programmatic callers have no HTTP root span: the session mints
        # the trace and closes its record when the future resolves, so
        # batcher/engine spans still share one trace-id.
        finish = None
        token = None
        if spans.current_trace_id() is None and spans.enabled():
            tid, finish = spans.open_trace()
            token = spans.activate(tid)
        try:
            if app == "sssp":
                fut = self._submit_sssp(params, deadline)
            elif app == "components":
                fut = self._submit_cached_fixpoint(
                    app, ("components",), self._run_components, deadline
                )
            else:
                ni = int(params.get("ni", self.config.pagerank_iters))
                if ni < 1:
                    raise BadQueryError(
                        f"pagerank ni must be >= 1 (got {ni})"
                    )
                fut = self._submit_cached_fixpoint(
                    app, ("pagerank", ni),
                    lambda: self._run_pagerank(ni), deadline,
                )
        except BaseException:
            if token is not None:
                spans.deactivate(token)
            if finish is not None:
                finish()
            raise
        if token is not None:
            spans.deactivate(token)

        def _done(f, app=app, t0=t0, finish=finish):
            dt = spans.clock() - t0
            self._latency.observe(dt)
            self.slo.observe(app, dt)
            if finish is not None:
                finish()

        fut.add_done_callback(_done)
        return fut

    def query(self, app: str, timeout: Optional[float] = None, **params):
        """Synchronous ``submit``; blocks for the result."""
        return self.submit(app, **params).result(timeout=timeout)

    def _submit_sssp(self, params: dict, deadline) -> Future:
        try:
            start = int(params["start"])
        except (KeyError, TypeError, ValueError):
            raise BadQueryError("sssp needs an integer 'start' root")
        if not 0 <= start < self.graph.nv:
            raise BadQueryError(
                f"sssp start {start} out of range [0, {self.graph.nv})"
            )
        key = (self.fingerprint, "sssp", start)
        hit = self.cache.get(key)
        if hit is not None:
            fut: Future = Future()
            fut.set_result(hit)
            return fut
        req = Request(
            app="sssp", payload=start,
            batch_key=("sssp", self.fingerprint, self.config.max_batch),
            deadline=deadline,
        )
        return self.batcher.submit(req)

    def _submit_cached_fixpoint(self, app, key_tail, run, deadline) -> Future:
        key = (self.fingerprint,) + tuple(key_tail)
        hit = self.cache.get(key)
        if hit is not None:
            fut: Future = Future()
            fut.set_result(hit)
            return fut
        req = Request(app=app, payload=(key, run), batch_key=None,
                      deadline=deadline)
        return self.batcher.submit(req)

    # -- batcher executor callback ---------------------------------------

    @contextlib.contextmanager
    def _watched(self, key):
        """Recompile-sentinel region for one engine execution. A key's
        first served execution may still compile lazily (a fused runner
        jit that warmup's single-step path doesn't reach) and counts as
        warmup; every later execution promises zero compiles — the
        "zero recompiles after the first batch" serving contract."""
        if key in self._served_keys:
            with self.pool.sentinel.watch(key):
                yield
        else:
            with self.pool.sentinel.expect(key):
                yield
            self._served_keys.add(key)

    def _execute_batch(self, batch: List[Request]):
        if batch[0].app == "sssp":
            self._execute_sssp_batch(batch)
            return
        # Unbatchable request (singleton list): cached fixpoint runner.
        (key, run) = batch[0].payload
        hit = self.cache.get(key)   # raced submits may have filled it
        if hit is None:
            hit = run()
            self.cache.put(key, hit)
        batch[0].future.set_result(hit)

    def _execute_sssp_batch(self, batch: List[Request]):
        roots = [r.payload for r in batch]
        if len(batch) == 1:
            key = self._engine_key("push", ("sssp", 1))
            ex = self._sssp_single()
            with self._watched(key), spans.span(
                    "serve.engine", app="sssp", engine="push", lanes=1):
                state, iters = ex.run(start=roots[0])
                results = [np.asarray(state.values)]
        else:
            key = self._engine_key(
                "push_multi", ("sssp", self.config.max_batch)
            )
            ex = self._sssp_multi()
            with self._watched(key), spans.span(
                    "serve.engine", app="sssp", engine="push_multi",
                    lanes=len(roots)):
                state, iters = ex.run(roots)
                results = [
                    ex.values_for(state, j) for j in range(len(roots))
                ]
        for r, root, vals in zip(batch, roots, results):
            out = {"values": vals, "iters": int(iters), "start": root}
            self.cache.put((self.fingerprint, "sssp", root), out)
            r.future.set_result(out)

    def _run_components(self) -> dict:
        ex = self._components_engine()
        with self._watched(self._engine_key("push", ("components", 1))), \
                spans.span("serve.engine", app="components",
                           engine="push"):
            state, iters = ex.run()
        return {"values": np.asarray(state.values), "iters": int(iters)}

    def _run_pagerank(self, ni: int) -> dict:
        from lux_tpu.models.cli import final_values

        ex = self._pagerank_engine()
        with self._watched(self._engine_key("pull", ("pagerank",))), \
                spans.span("serve.engine", app="pagerank", engine="pull",
                           iters=ni):
            vals = ex.run(ni)
        return {"values": final_values(ex, vals), "iters": ni}

    # -- introspection / lifecycle ---------------------------------------

    def stats(self) -> dict:
        s = {
            "graph": {"nv": self.graph.nv, "ne": self.graph.ne,
                      "fingerprint": self.fingerprint},
            "pool": self.pool.stats(),
            "cache": self.cache.stats(),
            "batcher": self.batcher.stats(),
            "requests": int(self._requests.value),
        }
        if self._latency.count:
            s["latency_s"] = {
                "count": self._latency.count,
                "p50": self._latency.quantile(0.5),
                "p99": self._latency.quantile(0.99),
            }
        return s

    def statusz(self) -> dict:
        """Rolling operational view (the /statusz payload): windowed
        SLO quantiles per app, queue pressure, cache efficiency, batch
        width, and the shed/reject/recompile counters that page."""
        b = self.batcher.stats()
        c = self.cache.stats()
        p = self.pool.stats()
        probes = c["hits"] + c["misses"]
        return {
            "windows": self.slo.snapshot(),
            "queue": {"depth": b["queue_depth"],
                      "capacity": b["queue_capacity"]},
            "cache_hit_rate": (c["hits"] / probes) if probes else None,
            "batch_size": self.batcher.batch_histogram(),
            "counters": {
                "requests": int(self._requests.value),
                "rejected": b["rejected"],
                "deadline_expired": b["deadline_expired"],
                "warmup_compiles": p["warmup_compiles"],
                "recompiles": p["recompiles"],
                "ir_findings": p["ir_findings"],
            },
            "flight": flight.counts(),
        }

    def _flight_context(self) -> dict:
        """Context block stamped into every flight.v1 postmortem."""
        return {
            "graph": {"nv": self.graph.nv, "ne": self.graph.ne,
                      "fingerprint": self.fingerprint},
            "pool": self.pool.stats(),
            "cache": self.cache.stats(),
            "batcher": self.batcher.stats(),
            "sentinel": self.pool.sentinel.stats(),
        }

    def close(self):
        if not self._closed:
            self._closed = True
            flight.remove_context(self._flight_name)
            self.batcher.close()
            self.pool.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _timed:
    def __init__(self, log, what):
        self.log, self.what = log, what

    def __enter__(self):
        self.t0 = spans.clock()

    def __exit__(self, *exc):
        self.log.info(
            "%s: %.2fs", self.what, spans.clock() - self.t0
        )
