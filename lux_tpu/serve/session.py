"""Programmatic serving session: graph loaded once, engines warm, queries
answered through the micro-batcher.

Query routing:

- ``sssp`` (root queries, the dominant online traversal workload) —
  batchable: K concurrent roots inside one batching window run as ONE
  dense multi-source sweep over ``(nv, K)`` values; a batch of one runs
  on the adaptive single-source ``PushExecutor`` (its sparse tiers beat a
  1-lane dense sweep). Both executors live in the warm pool, so neither
  path recompiles after warmup.
- ``pagerank`` — served from the LRU cache of converged results (one
  fixpoint array answers every client at a given iteration count); cache
  misses run the pull executor once.
- ``components`` — root-free like PageRank: one converged labeling is
  cached and sliced per query.

Every result cache key embeds the hardened graph fingerprint
(utils/checkpoint.fingerprint), so answers can never leak across graphs.

Dynamic graphs (ISSUE 7): the session serves one
:class:`~lux_tpu.graph.snapshot.SnapshotStore` version at a time.
``apply_edits`` stacks an edit batch into version N+1, warms its engines
on a background thread (the old version keeps serving the whole time),
optionally refreshes cached fixpoints incrementally from version N's
values, then atomically flips the serving pointer and rides a barrier
request through the FIFO batcher — by the time the barrier executes,
every in-flight version-N query has been answered, so the barrier can
retire N's engines and evict its cache entries without failing anyone.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Union

import numpy as np

from lux_tpu.graph.graph import Graph
from lux_tpu.graph.snapshot import Snapshot, SnapshotStore
from lux_tpu.obs import engobs, flight, ledger, metrics, prof, slo, spans
from lux_tpu.serve.batcher import MicroBatcher, Request
from lux_tpu.serve.cost import CostAccounts, QueryCost
from lux_tpu.serve.breaker import CircuitBreaker
from lux_tpu.serve.cache import ResultCache
from lux_tpu.serve.errors import (BadQueryError, QueueFullError,
                                  ServeError, SnapshotSwapError)
from lux_tpu.serve.mesh import plan_cache, serving_mesh
from lux_tpu.serve.pool import EnginePool
from lux_tpu.utils import faults, flags
from lux_tpu.utils.locks import make_lock
from lux_tpu.utils.logging import get_logger


class ServeConfig:
    """Serving knobs (one object so the HTTP CLI, tools, and tests agree
    on defaults)."""

    def __init__(
        self,
        max_batch: int = 8,          # K: multi-source lanes per sweep
        window_s: float = 0.003,     # batching window
        max_queue: int = 64,         # admission queue bound
        cache_capacity: int = 256,   # LRU entries
        default_deadline_s: Optional[float] = None,
        pagerank_iters: int = 20,    # served fixpoint depth
        mesh: Optional[str] = None,  # serving mesh spec; None = LUX_SERVE_MESH
    ):
        self.max_batch = int(max_batch)
        self.window_s = float(window_s)
        self.max_queue = int(max_queue)
        self.cache_capacity = int(cache_capacity)
        self.default_deadline_s = default_deadline_s
        self.pagerank_iters = int(pagerank_iters)
        self.mesh = mesh


def _host_values(ex, state) -> np.ndarray:
    """Host-side per-vertex values from an executor state: sharded
    executors unpad their stacked shards (``gather_values``); flat ones
    hand back ``state.values`` directly."""
    if hasattr(ex, "gather_values"):
        return np.asarray(ex.gather_values(state))
    return np.asarray(state.values)


class Session:
    """One served graph: load once, keep engines warm, answer queries.

    Thread-safe: ``submit``/``query`` may be called from any number of
    request threads; engine work funnels through the batcher thread.
    """

    APPS = ("sssp", "components", "pagerank")

    def __init__(
        self,
        graph: Union[Graph, str, SnapshotStore],
        config: Optional[ServeConfig] = None,
        warm: bool = True,
    ):
        self.log = get_logger("serve")
        self.config = config or ServeConfig()
        # Resolve the serving mesh up front: engine pool keys embed its
        # shape, so one session serves one mesh for its whole lifetime
        # (multi-chip serving, ISSUE 10 — P > 1 routes every engine
        # build through the sharded executors + the shard-plan cache).
        self.meshspec = serving_mesh(self.config.mesh)
        self.graph_path: Optional[str] = None
        if isinstance(graph, SnapshotStore):
            # Crash recovery: serve a store rebuilt by
            # SnapshotStore.recover(base, wal_dir) as-is.
            self.store = graph
        else:
            if isinstance(graph, str):
                from lux_tpu.native import io as native_io

                self.graph_path = graph
                graph = native_io.read_lux(graph)
            self.store = SnapshotStore(graph,
                                       wal_dir=flags.get("LUX_WAL_DIR"))
        self._serving = self.store.current()  # luxlint: publish=_swap_lock
        # The served app list derives from the program registry (every
        # ``servable`` program routes: rooted GAS apps through the
        # micro-batcher, GAS fixpoints through the result cache;
        # weighted-only programs drop off when the graph is unweighted)
        # — shadowing the class-level legacy triple.
        self.APPS, self._gas_rooted, self._gas_fixpoints = (
            self._compute_apps())
        self._degraded = None  # luxlint: publish=_swap_lock
        self._swap_lock = make_lock("session.swap")
        self.breaker = CircuitBreaker(self._breaker_probe)
        self.pool = EnginePool()
        self.cache = ResultCache(self.config.cache_capacity)
        self.batcher = MicroBatcher(
            self._execute_batch,
            max_batch=self.config.max_batch,
            window_s=self.config.window_s,
            max_queue=self.config.max_queue,
        )
        self._requests = metrics.counter("lux_serve_requests_total")
        self._latency = metrics.histogram("lux_serve_request_seconds")
        # app -> reason for every engine that had to drop from the mesh
        # to a per-chip build; /statusz turns a non-empty dict into a
        # warning and the smoke test asserts the counter stays at zero.
        # Leaf lock: writes happen inside pool builds (pool lock held),
        # reads on the /statusz thread — never nest another lock inside.
        self._fallback_lock = make_lock("session.mesh_fallback")
        self._mesh_fallbacks: Dict[str, str] = {}
        # Profile-guided tuning (lux_tpu/tune): (fingerprint, app) ->
        # tuneconf.v1 artifact resolved at warmup. Reads on the query
        # path are lock-free dict.get (entries are immutable and only
        # ever swapped whole); writes share the leaf fallback lock.
        self._tuned: Dict[tuple, dict] = {}
        self._tune_fallbacks: Dict[str, str] = {}
        self.slo = slo.SloWindows()
        self.costs = CostAccounts()
        self._served_keys = set()   # batcher-thread only
        self._closed = False
        self._flight_name = f"session:{self.fingerprint[:12]}"
        flight.add_context(self._flight_name, self._flight_context)
        if warm:
            self.warmup()

    # -- serving snapshot ------------------------------------------------

    @property
    def graph(self) -> Graph:
        """The currently served graph (version ``self.version``)."""
        return self._serving.graph

    @property
    def fingerprint(self) -> str:
        return self._serving.fingerprint

    @property
    def version(self) -> int:
        return self._serving.version

    @property
    def degraded(self) -> Optional[dict]:
        """Non-None while the session serves stale: the last attempt to
        warm version N+1 failed, so version N keeps answering (HTTP
        responses carry ``X-Lux-Degraded``). Cleared by the next
        successful swap."""
        return self._degraded

    # -- engines ---------------------------------------------------------

    def _engine_key(self, kind: str, snap: Snapshot, extra=()) -> tuple:
        # The trailing mesh-shape component makes the key the full
        # (program, fingerprint, batch width, mesh shape) tuple: a warm
        # sharded engine can never answer for a single-chip one (or for
        # a different mesh), and /statusz groups pool entries by it.
        # Sharded keys also carry the exchange mode captured at build
        # (LUX_EXCHANGE): a full-exchange engine warmed before a flag
        # flip must not answer for compact (different executables, same
        # results) — the pool warms a fresh entry instead. When the app
        # serves under a tuned config, the artifact's exchange mode wins
        # over the ambient flag: warmup builds inside the tuned overlay
        # and query threads run outside it, so only the artifact keeps
        # the two key computations identical (a mismatch would miss the
        # pool and recompile per query).
        key = (kind, snap.fingerprint) + tuple(extra)
        if self.sharded:
            from lux_tpu.parallel.shard import exchange_mode

            art = self._tuned_art(extra[0] if extra else None, snap)
            mode = (art or {}).get("config", {}).get("LUX_EXCHANGE") \
                or exchange_mode()
            key = key + (mode,)
        return key + (self.meshspec.shape,)

    @property
    def sharded(self) -> bool:
        return self.meshspec.num_parts > 1

    def _shard_plan(self, snap: Snapshot):
        """The snapshot's partition plan from the process-wide cache —
        every sharded engine for (fingerprint, parts) shares one O(ne)
        host build, and the hot-swap drain evicts it with the engines."""
        return plan_cache().get(
            snap.fingerprint, snap.graph, self.meshspec.num_parts
        )

    def _footprint(self, kind: str, app: str, snap: Snapshot,
                   k: int = 1) -> Optional[int]:
        """Predicted per-device resident bytes for one engine build —
        the committed memcap.v1 admission formula
        (analysis/memck.predicted_engine_bytes), resolved under the
        same exchange mode the engine key carries. None (pool admits
        freely) when admission is off, the artifact prices nothing for
        this build, or pricing itself fails — pricing is advisory
        input to admission, never a reason a build can't start."""
        if not flags.get_bool("LUX_MEM_POOL_ADMIT"):
            return None
        try:
            from lux_tpu.analysis import memck

            mode = ""
            rkind = kind + "_sharded" if self.sharded else kind
            if self.sharded:
                from lux_tpu.parallel.shard import exchange_mode

                art = self._tuned_art(app, snap)
                mode = (art or {}).get("config", {}).get("LUX_EXCHANGE") \
                    or exchange_mode()
            return memck.predicted_engine_bytes(
                app, rkind, mode, snap.graph.nv, snap.graph.ne,
                self.meshspec.num_parts, k=k)
        # luxlint: disable=LUX007 -- advisory pricing must never block a build
        except Exception:
            return None

    def _sssp_single(self, snap: Optional[Snapshot] = None):
        from lux_tpu.engine.push import PushExecutor, ShardedPushExecutor
        from lux_tpu.models.sssp import SSSP

        snap = snap or self._serving
        if self.sharded:
            return self.pool.get(
                self._engine_key("push", snap, ("sssp", 1)),
                self._tuned_build("sssp", snap, lambda: ShardedPushExecutor(
                    snap.graph, SSSP(), mesh=self.meshspec.mesh,
                    sg=self._shard_plan(snap),
                )),
                footprint_bytes=self._footprint("push", "sssp", snap),
            )
        return self.pool.get(
            self._engine_key("push", snap, ("sssp", 1)),
            self._tuned_build(
                "sssp", snap, lambda: PushExecutor(snap.graph, SSSP())),
            footprint_bytes=self._footprint("push", "sssp", snap),
        )

    def _sssp_multi(self, snap: Optional[Snapshot] = None):
        from lux_tpu.engine.push import (MultiSourcePushExecutor,
                                         ShardedMultiSourcePushExecutor)
        from lux_tpu.models.sssp import SSSP

        snap = snap or self._serving
        k = self.config.max_batch
        if self.sharded:
            return self.pool.get(
                self._engine_key("push_multi", snap, ("sssp", k)),
                self._tuned_build(
                    "sssp", snap, lambda: ShardedMultiSourcePushExecutor(
                        snap.graph, SSSP(), k=k, mesh=self.meshspec.mesh,
                        sg=self._shard_plan(snap),
                    )),
                footprint_bytes=self._footprint(
                    "push_multi", "sssp", snap, k=k),
            )
        return self.pool.get(
            self._engine_key("push_multi", snap, ("sssp", k)),
            self._tuned_build("sssp", snap, lambda: MultiSourcePushExecutor(
                snap.graph, SSSP(), k=k)),
            footprint_bytes=self._footprint(
                "push_multi", "sssp", snap, k=k),
        )

    def _components_engine(self, snap: Optional[Snapshot] = None):
        from lux_tpu.engine.push import PushExecutor, ShardedPushExecutor
        from lux_tpu.models.components import ConnectedComponents

        snap = snap or self._serving
        if self.sharded:
            return self.pool.get(
                self._engine_key("push", snap, ("components", 1)),
                self._tuned_build(
                    "components", snap, lambda: ShardedPushExecutor(
                        snap.graph, ConnectedComponents(),
                        mesh=self.meshspec.mesh, sg=self._shard_plan(snap),
                    )),
                footprint_bytes=self._footprint(
                    "push", "components", snap),
            )
        return self.pool.get(
            self._engine_key("push", snap, ("components", 1)),
            self._tuned_build("components", snap, lambda: PushExecutor(
                snap.graph, ConnectedComponents())),
            footprint_bytes=self._footprint("push", "components", snap),
        )

    def _pagerank_engine(self, snap: Optional[Snapshot] = None):
        from lux_tpu.models.cli import make_executor
        from lux_tpu.models.pagerank import PageRank

        snap = snap or self._serving

        def build():
            from lux_tpu.engine.pull import PullExecutor

            if self.graph_path is None or snap.version > 0:
                # The tiled fast path persists its hybrid plan next to
                # the graph file; an in-memory graph has none, and an
                # edited snapshot no longer matches the on-disk plan —
                # both serve from the (sharded, when P > 1) pull engine.
                if self.sharded:
                    from lux_tpu.engine.pull_sharded import \
                        ShardedPullExecutor

                    return ShardedPullExecutor(
                        snap.graph, PageRank(), mesh=self.meshspec.mesh,
                        sg=self._shard_plan(snap),
                    )
                return PullExecutor(snap.graph, PageRank())
            import argparse

            # Reuse the CLI's engine-selection policy (tiled when
            # SpMV-shaped; -parts folds the serving mesh) with serving
            # defaults.
            args = argparse.Namespace(
                parts=self.meshspec.num_parts, layout="auto",
                strategy="rowptr", levels="8/2", tile_mb=8192,
                plan_cache=None, file=self.graph_path,
            )
            return make_executor(snap.graph, PageRank(), args, self.log)

        return self.pool.get(
            self._engine_key("pull", snap, ("pagerank",)),
            self._tuned_build("pagerank", snap, build),
            footprint_bytes=self._footprint("pull", "pagerank", snap),
        )

    # -- GAS apps (direction-optimizing adaptive executor) ----------------

    def _compute_apps(self):
        """(apps, rooted_gas, fixpoint_gas) derived from the registry.

        The legacy triple keeps its order (and its dedicated push/pull
        routes below); programs beyond it serve through the adaptive GAS
        executor. Anything marked ``servable = False`` (colfilter: needs
        a bipartite ratings graph, not the served one) is skipped, as are
        weight-consuming programs when the serving graph has no weights.
        """
        from lux_tpu.engine.gas import GasProgram
        from lux_tpu.models import PROGRAMS

        from lux_tpu.models import capabilities

        weighted = self._serving.graph.weighted
        caps = capabilities()
        legacy = list(Session.APPS)
        apps, rooted, fixpoints = [], [], []
        for name in legacy + sorted(set(PROGRAMS) - set(legacy)):
            cls = PROGRAMS[name]
            if not getattr(cls, "servable", True):
                continue
            if getattr(cls, "needs_weights", False) and not weighted:
                continue
            if name in legacy:
                apps.append(name)
                continue
            if not issubclass(cls, GasProgram):
                continue   # no GAS route for it; not served
            apps.append(name)
            # Rooted routing (multi-source batching vs result-cache
            # fixpoints) follows the gascap.v1 proof matrix, not the
            # class attr — a claimed root init_values ignores must not
            # buy per-query batching it can't serve (LUX606 keeps the
            # declaration honest offline).
            if caps.get(name, {}).get("rooted",
                                      getattr(cls, "rooted", False)):
                rooted.append(name)
            else:
                fixpoints.append(name)
        return tuple(apps), tuple(rooted), tuple(fixpoints)

    def _gas_program(self, app: str, extra=()):
        """Instantiate the GAS program for ``app``; ``extra`` carries
        per-engine parameters beyond the defaults (kcore's k)."""
        from lux_tpu.engine.gas import as_gas
        from lux_tpu.models import get_program

        if app == "kcore" and extra:
            from lux_tpu.models.kcore import KCore

            return as_gas(KCore(k=int(extra[0])))
        return as_gas(get_program(app))

    def _gas_key_extra(self, app: str, extra=()) -> tuple:
        return (app,) + tuple(extra) + (1,)

    def _note_mesh_fallback(self, app: str, why: str) -> None:
        """Record that ``app`` dropped from the mesh to a per-chip
        engine: counter for dashboards, dict for the /statusz warning,
        log line for the operator reading the console."""
        metrics.counter(
            "lux_serve_mesh_fallback_total", {"app": app}).inc()
        with self._fallback_lock:
            self._mesh_fallbacks[app] = why
        self.log.warning(
            "mesh fallback: %s serves per-chip on a %d-part mesh: %s",
            app, self.meshspec.num_parts, why)

    # -- profile-guided tuning (lux_tpu/tune) -----------------------------

    def _tune_engine_kind(self, app: str) -> str:
        """The engine kind a tune artifact for ``app`` is keyed under:
        the app's primary serving executor. Layout choice is part of
        the key on purpose — each layout tunes separately."""
        if app == "pagerank":
            base = "pull"
        elif app in ("sssp", "components"):
            base = "push"
        else:
            base = "gas"
        return base + ("_sharded" if self.sharded else "")

    def _tuned_art(self, app, snap: Snapshot) -> Optional[dict]:
        with self._fallback_lock:
            return self._tuned.get((snap.fingerprint, app))

    def _tuned_overlay(self, app: str, snap: Snapshot):
        """Scoped flag overlay applying ``app``'s tuned config so an
        engine *build* captures the tuned knobs (every tuner-managed
        flag is capture-at-build — the tuned path adds zero per-query
        compiles); a no-op when the app serves under defaults."""
        art = self._tuned_art(app, snap)
        if art is None:
            return contextlib.nullcontext()
        return flags.overrides(art["config"])

    def _load_tuned(self, snap: Snapshot) -> dict:
        """Resolve each served app's ``tuneconf.v1`` artifact for
        ``snap`` from the TuneCache before its engines build. A miss is
        a counted fallback to defaults (``lux_tune_fallback_total``,
        the /statusz tune block) — never silent; an unarmed tuner
        (LUX_TUNE_DIR unset) shows as ``armed: false`` there instead."""
        from lux_tpu.obs import report
        from lux_tpu.tune import key_string, make_key, tune_cache

        tc = tune_cache()
        found: Dict[str, str] = {}
        if not tc.enabled():
            return found
        device_kind = report.device_profile()["device_kind"]
        for app in self.APPS:
            key = make_key(snap.fingerprint, app,
                           self._tune_engine_kind(app),
                           self._mesh_label(), device_kind)
            art = tc.get(key)
            if art is None:
                metrics.counter(
                    "lux_tune_fallback_total", {"app": app}).inc()
                with self._fallback_lock:
                    self._tune_fallbacks[app] = (
                        f"no tuneconf.v1 for {snap.fingerprint[:12]}; "
                        "serving defaults")
                self.log.info(
                    "tune fallback: %s v%d serves under default config "
                    "(no artifact for key %r)", app, snap.version,
                    key_string(key))
                continue
            with self._fallback_lock:
                self._tuned[(snap.fingerprint, app)] = art
                self._tune_fallbacks.pop(app, None)
            found[app] = art["id"]
            self.log.info(
                "tuned config %s for %s v%d: %s (score %.3gs/iter, %d "
                "probes)", art["id"], app, snap.version, art["config"],
                art["score"], len(art.get("score_table") or ()))
        return found

    def tuned_for(self, app: str) -> Optional[dict]:
        """Tune provenance for ``app`` on the serving snapshot
        (``{id, score}`` or None) — the HTTP layer stamps the
        ``X-Lux-Tuned`` response header from it."""
        art = self._tuned_art(str(app), self._serving)
        if art is None:
            return None
        return {"id": art["id"], "score": art["score"]}

    def _tune_block(self) -> dict:
        """The /statusz ``tune`` view: per-app artifact provenance
        (id, score, probe count, age), counted fallbacks, cache
        health."""
        from lux_tpu.tune import tune_cache

        snap = self._serving
        # Artifact created_at is unix wall time (tune/artifact.py), so
        # the age math needs the wall clock, not the span epoch.
        now = time.time()  # luxlint: disable=LUX006 -- age vs artifact created_at needs unix wall time
        with self._fallback_lock:
            arts = {app: a for (fp, app), a in self._tuned.items()
                    if fp == snap.fingerprint}
            fallbacks = dict(self._tune_fallbacks)
        return {
            "armed": tune_cache().enabled(),
            "artifacts": {
                app: {"id": a["id"], "score": a["score"],
                      "config": a["config"],
                      "probes": len(a.get("score_table") or ()),
                      "age_s": round(now - float(a.get("created_at",
                                                       now)), 1)}
                for app, a in sorted(arts.items())
            },
            "fallbacks": fallbacks,
            "cache": tune_cache().stats(),
        }

    def _programs_block(self) -> dict:
        """The /statusz ``programs`` view: where routing's capability
        matrix came from (gascap.v1 artifact id, or the declared-attr
        fallback plus why), the per-program derived bits, and the pool's
        advisory build-time audit count."""
        from lux_tpu.models import capability_report

        rep = capability_report()
        return {
            "source": rep["source"],
            "artifact_id": rep["artifact_id"],
            **({"error": rep["error"]} if rep.get("error") else {}),
            "capabilities": rep["programs"],
            "gas_findings": self.pool.stats()["gas_findings"],
        }

    def _memory_block(self) -> dict:
        """The /statusz ``memory`` view: the HBM budget admission runs
        under, the summed memcap.v1-predicted resident bytes, eviction
        pressure, and where the formula came from (artifact id +
        device capacity)."""
        from lux_tpu.analysis import memck
        from lux_tpu.obs import report

        p = self.pool.stats()
        art = memck._committed()
        try:
            budget = memck.hbm_budget_bytes()
        # luxlint: disable=LUX007 -- a broken budget derivation must not break /statusz
        except Exception:
            budget = None
        return {
            "admission": flags.get_bool("LUX_MEM_POOL_ADMIT"),
            "budget_bytes": budget,
            "resident_bytes": p["hbm_resident_bytes"],
            "evictions": p["hbm_evictions"],
            "artifact_id": (art or {}).get("id"),
            "hbm_capacity_bytes": report.device_profile()
            .get("hbm_capacity_bytes"),
        }

    def _tuned_build(self, app: str, snap: Snapshot, build):
        """Wrap an engine builder so every pool miss — warmup, a
        breaker rebuild, the first use of a sibling key — constructs
        under ``app``'s tuned overlay. Tuned knobs are capture-at-build,
        so this is the single point where they take effect; the query
        path only ever sees warm engines."""
        def wrapped():
            with self._tuned_overlay(app, snap):
                return build()
        return wrapped

    def _gas_single(self, app: str, snap: Optional[Snapshot] = None,
                    extra=()):
        from lux_tpu.engine.gas import AdaptiveExecutor

        snap = snap or self._serving
        key = self._engine_key("gas", snap, self._gas_key_extra(app, extra))
        if self.sharded:
            from lux_tpu.engine.gas_sharded import ShardedAdaptiveExecutor

            def build():
                try:
                    return ShardedAdaptiveExecutor(
                        snap.graph, self._gas_program(app, extra),
                        mesh=self.meshspec.mesh,
                        sg=self._shard_plan(snap),
                    )
                except Exception as e:  # luxlint: disable=LUX007
                    # A per-chip answer is still correct; a dead app is
                    # not. But the drop must be loud: counted, warned on
                    # /statusz, and visible in the log — never silent.
                    self._note_mesh_fallback(app, repr(e))
                    return AdaptiveExecutor(
                        snap.graph, self._gas_program(app, extra))

            return self.pool.get(
                key, self._tuned_build(app, snap, build),
                footprint_bytes=self._footprint("gas", app, snap))
        return self.pool.get(
            key,
            self._tuned_build(app, snap, lambda: AdaptiveExecutor(
                snap.graph, self._gas_program(app, extra))),
            footprint_bytes=self._footprint("gas", app, snap),
        )

    def _gas_multi(self, app: str, snap: Optional[Snapshot] = None):
        from lux_tpu.engine.gas import MultiSourceGasExecutor
        from lux_tpu.models import get_program

        snap = snap or self._serving
        k = self.config.max_batch
        key = self._engine_key("gas_multi", snap, (app, k))
        if self.sharded:
            from lux_tpu.engine.gas_sharded import (
                ShardedMultiSourceGasExecutor)

            def build():
                try:
                    return ShardedMultiSourceGasExecutor(
                        snap.graph, get_program(app), k=k,
                        mesh=self.meshspec.mesh,
                        sg=self._shard_plan(snap),
                    )
                except Exception as e:  # luxlint: disable=LUX007
                    self._note_mesh_fallback(app + "_multi", repr(e))
                    return MultiSourceGasExecutor(
                        snap.graph, get_program(app), k=k)

            return self.pool.get(
                key, self._tuned_build(app, snap, build),
                footprint_bytes=self._footprint(
                    "gas_multi", app, snap, k=k))
        return self.pool.get(
            key,
            self._tuned_build(app, snap, lambda: MultiSourceGasExecutor(
                snap.graph, get_program(app), k=k)),
            footprint_bytes=self._footprint("gas_multi", app, snap, k=k),
        )

    def warmup(self, snap: Optional[Snapshot] = None):
        """Build + compile every served engine before traffic arrives
        (for ``snap``, default the serving snapshot — the hot-swap warms
        the incoming version through this same path). After this, the
        pool miss counter is the recompile count: the smoke test asserts
        it stays flat across the query phase."""
        snap = snap or self._serving
        t_warm0 = spans.clock()
        # Resolve tuned configs BEFORE any engine builds: each app's
        # engines construct inside its tuned overlay, so the tuner's
        # knobs (all capture-at-build) are baked into the warm
        # executables and the query path compiles nothing new.
        tuned = self._load_tuned(snap)
        # Resolve the program capability matrix once, loudly, before
        # traffic: a missing/rejected gascap.v1 artifact demotes routing
        # to the class-attr declarations, and that demotion belongs in
        # the warmup log — not discovered query-by-query.
        from lux_tpu.models import capability_report
        caps = capability_report()
        if caps.get("error"):
            self.log.warning("program capabilities: declared fallback "
                             "(%s)", caps["error"])
        else:
            self.log.info("program capabilities: %s %s", caps["source"],
                          caps.get("artifact_id"))
        # An engine the HBM budget refuses must not abort warmup (and
        # with it server boot): warm what fits, count the skips, and let
        # queries for the rest shed per-request with the typed 503.
        def _warm(label, build, *args, **kw):
            from lux_tpu.serve.errors import PoolOverBudgetError

            with _timed(self.log, f"warmup {label}"):
                try:
                    build(*args, **kw)
                except PoolOverBudgetError as e:
                    metrics.counter("lux_pool_hbm_warm_skips_total",
                                    {"engine": label}).inc()
                    self.log.warning("warmup %s skipped: %s", label, e)

        with spans.span("serve.warmup", version=snap.version):
            faults.point("snapshot.warm")
            _warm("sssp single", self._sssp_single, snap)
            _warm("sssp multi", self._sssp_multi, snap)
            _warm("components", self._components_engine, snap)
            _warm("pagerank", self._pagerank_engine, snap)
            for app in self._gas_rooted:
                _warm(f"{app} gas", self._gas_single, app, snap)
                _warm(f"{app} gas multi", self._gas_multi, app, snap)
            for app in self._gas_fixpoints:
                # kcore's default k is baked into the warm engine key so
                # default-parameter queries hit it; non-default k builds
                # (and warms) a sibling engine on first use.
                extra = (2,) if app == "kcore" else ()
                _warm(f"{app} gas", self._gas_single, app, snap,
                      extra=extra)
        # One durable observation per warmed snapshot: what this config
        # paid to get every served engine compiled and resident.
        ledger.record_run(
            "serve_warmup",
            {"warm_s": spans.clock() - t_warm0, "version": snap.version,
             "nv": int(snap.graph.nv), "ne": int(snap.graph.ne),
             "apps": list(self.APPS),
             "pool": self.pool.stats()},
            graph_fingerprint=snap.fingerprint, program="serve",
            engine_kind="warmup", mesh_shape=self._mesh_label(),
            tuned=tuned,
        )

    def _mesh_label(self) -> str:
        return "x".join(map(str, self.meshspec.shape))

    # -- query front door ------------------------------------------------

    def submit(
        self,
        app: str,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        **params,
    ) -> Future:
        """Admit one query; returns a Future resolving to a dict with at
        least ``values`` (np.ndarray) and ``iters``. Raises
        ``BadQueryError`` on malformed queries and ``QueueFullError``
        under overload; the Future raises ``DeadlineExceededError`` when
        shed. ``tenant`` labels the query's cost record (X-Lux-Tenant
        upstream; unlabeled traffic books to the default tenant)."""
        if self._closed:
            raise BadQueryError("session is closed")
        app = str(app)
        if app not in self.APPS:
            raise BadQueryError(
                f"unknown app {app!r}; serving {list(self.APPS)}"
            )
        cost = QueryCost(tenant, app)
        self._requests.inc()
        metrics.counter(
            "lux_serve_requests_total", {"app": app}
        ).inc()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline = (
            spans.monotonic() + deadline_s if deadline_s is not None
            else None
        )
        t0 = spans.clock()
        # Programmatic callers have no HTTP root span: the session mints
        # the trace and closes its record when the future resolves, so
        # batcher/engine spans still share one trace-id.
        finish = None
        token = None
        if spans.current_trace_id() is None and spans.enabled():
            tid, finish = spans.open_trace()
            token = spans.activate(tid)
        # One read of the serving pointer per request: everything below
        # (cache keys, batch keys, engine lookups) binds to this snapshot,
        # so a hot-swap mid-request can never mix versions.
        snap = self._serving
        try:
            # Shed instantly while this (app, fingerprint)'s breaker is
            # open — no queue slot, no batcher time for an engine known
            # to be failing (503 + Retry-After upstream).
            self.breaker.check((app, snap.fingerprint))
            if app == "sssp":
                fut = self._submit_sssp(params, deadline, snap, cost)
            elif app == "components":
                fut = self._submit_cached_fixpoint(
                    app, ("components",),
                    lambda dl=None: self._run_components(snap, dl),
                    deadline, snap, cost,
                )
            elif app == "pagerank":
                ni = int(params.get("ni", self.config.pagerank_iters))
                if ni < 1:
                    raise BadQueryError(
                        f"pagerank ni must be >= 1 (got {ni})"
                    )
                fut = self._submit_cached_fixpoint(
                    app, ("pagerank", ni),
                    lambda dl=None: self._run_pagerank(ni, snap, dl),
                    deadline, snap, cost,
                )
            elif app in self._gas_rooted:
                fut = self._submit_rooted_gas(app, params, deadline, snap,
                                              cost)
            elif app == "kcore":
                try:
                    k = int(params.get("k", 2))
                except (TypeError, ValueError):
                    raise BadQueryError("kcore k must be an integer")
                if k < 1:
                    raise BadQueryError(
                        f"kcore k must be >= 1 (got {k})"
                    )
                fut = self._submit_cached_fixpoint(
                    app, ("kcore", k),
                    lambda dl=None: self._run_gas_fixpoint(
                        app, snap, dl, extra=(k,)),
                    deadline, snap, cost,
                )
            else:
                # Remaining registry-derived fixpoints (labelprop today).
                fut = self._submit_cached_fixpoint(
                    app, (app,),
                    lambda dl=None: self._run_gas_fixpoint(app, snap, dl),
                    deadline, snap, cost,
                )
        except BaseException:
            if token is not None:
                spans.deactivate(token)
            if finish is not None:
                finish()
            raise
        if token is not None:
            spans.deactivate(token)

        def _done(f, app=app, t0=t0, finish=finish, cost=cost):
            dt = spans.clock() - t0
            self._latency.observe(dt)
            self.slo.observe(app, dt)
            # The batcher thread finished filling the cost record before
            # it resolved the future; book it to the tenant now (shed or
            # failed queries still consumed admission — they book their
            # accumulated, possibly zero, engine spend).
            cost.latency_s = dt
            self.costs.observe(cost)
            if finish is not None:
                finish()

        fut._lux_cost = cost   # readers: HTTP front door (X-Lux-Cost)
        fut.add_done_callback(_done)
        return fut

    def query(self, app: str, timeout: Optional[float] = None, **params):
        """Synchronous ``submit``; blocks for the result."""
        return self.submit(app, **params).result(timeout=timeout)

    def _submit_sssp(self, params: dict, deadline, snap: Snapshot,
                     cost: QueryCost) -> Future:
        try:
            start = int(params["start"])
        except (KeyError, TypeError, ValueError):
            raise BadQueryError("sssp needs an integer 'start' root")
        nv = snap.graph.nv
        if not 0 <= start < nv:
            raise BadQueryError(
                f"sssp start {start} out of range [0, {nv})"
            )
        key = (snap.fingerprint, "sssp", start)
        hit = self.cache.get(key)
        if hit is not None:
            cost.outcome = "hit"     # zero engine spend: the cache paid
            fut: Future = Future()
            fut.set_result(hit)
            return fut
        # The batch key embeds the snapshot fingerprint: queries straddling
        # a hot-swap can never share one dense sweep across two graphs.
        req = Request(
            app="sssp", payload=(snap, start),
            batch_key=("sssp", snap.fingerprint, self.config.max_batch),
            deadline=deadline, cost=cost,
        )
        return self.batcher.submit(req)

    def _submit_rooted_gas(self, app: str, params: dict, deadline,
                           snap: Snapshot, cost: QueryCost) -> Future:
        """Rooted GAS apps (bfs, sssp_delta) ride the same micro-batch
        machinery as sssp: per-root result cache, fingerprinted batch
        key, K-lane dense sweep when a window coalesces."""
        try:
            start = int(params["start"])
        except (KeyError, TypeError, ValueError):
            raise BadQueryError(f"{app} needs an integer 'start' root")
        nv = snap.graph.nv
        if not 0 <= start < nv:
            raise BadQueryError(
                f"{app} start {start} out of range [0, {nv})"
            )
        key = (snap.fingerprint, app, start)
        hit = self.cache.get(key)
        if hit is not None:
            cost.outcome = "hit"
            fut: Future = Future()
            fut.set_result(hit)
            return fut
        req = Request(
            app=app, payload=(snap, start),
            batch_key=(app, snap.fingerprint, self.config.max_batch),
            deadline=deadline, cost=cost,
        )
        return self.batcher.submit(req)

    def _submit_cached_fixpoint(self, app, key_tail, run, deadline,
                                snap: Snapshot, cost: QueryCost) -> Future:
        key = (snap.fingerprint,) + tuple(key_tail)
        hit = self.cache.get(key)
        if hit is not None:
            cost.outcome = "hit"
            fut: Future = Future()
            fut.set_result(hit)
            return fut
        req = Request(app=app, payload=(key, run), batch_key=None,
                      deadline=deadline, cost=cost)
        return self.batcher.submit(req)

    # -- batcher executor callback ---------------------------------------

    @contextlib.contextmanager
    def _watched(self, key):
        """Recompile-sentinel region for one engine execution. A key's
        first served execution may still compile lazily (a fused runner
        jit that warmup's single-step path doesn't reach) and counts as
        warmup; every later execution promises zero compiles — the
        "zero recompiles after the first batch" serving contract."""
        # luxlint: disable=LUX301 -- _served_keys is batcher-thread-only
        if key in self._served_keys:
            with self.pool.sentinel.watch(key):
                yield
        else:
            with self.pool.sentinel.expect(key):
                yield
            # luxlint: disable=LUX301 -- _watched only runs on the batcher thread
            self._served_keys.add(key)

    def _engine_execute(self, app: str, snap: Snapshot, key, deadline, fn):
        """One engine execution with fault injection, bounded
        retry-with-backoff, and circuit-breaker accounting.

        Transient (non-ServeError) failures retry up to LUX_RETRY_MAX
        times with doubling LUX_RETRY_BACKOFF_MS backoff, clamped by the
        batch's deadline — a retry that could not start before the
        deadline fails now instead of burning engine time on an answer
        nobody is waiting for. Terminal failures feed the breaker for
        ``(app, fingerprint)``; successes reset it."""
        bkey = (app, snap.fingerprint)
        attempts = 1 + max(0, flags.get_int("LUX_RETRY_MAX"))
        backoff_s = max(0.0, flags.get_float("LUX_RETRY_BACKOFF_MS")) / 1e3
        for attempt in range(1, attempts + 1):
            try:
                with self._watched(key):
                    faults.point("serve.engine.execute")
                    with prof.region("lux.serve.execute"):
                        out = fn()
            except ServeError:
                raise             # shed/typed errors are not engine faults
            except Exception as e:
                exhausted = attempt >= attempts or (
                    deadline is not None
                    and spans.monotonic() + backoff_s > deadline)
                if exhausted:
                    self.breaker.record_failure(bkey, error=e)
                    raise
                metrics.counter("lux_serve_retries_total",
                                {"app": app}).inc()
                self.log.warning(
                    "engine %s attempt %d/%d failed (%r); retrying in "
                    "%d ms", app, attempt, attempts, e,
                    int(backoff_s * 1e3))
                time.sleep(backoff_s)
                backoff_s *= 2
            else:
                self.breaker.record_success(bkey)
                return out

    def _charge_batch(self, batch: List[Request], ex, iters: int,
                      engine_s: float, switches: int = 0) -> None:
        """Split one engine execution's cost evenly across the batch so
        per-query charges sum to the batch totals (the /costz parity
        invariant). Exchange bytes come from the sharded executor's
        dense estimate; single-chip engines exchange nothing."""
        n = max(1, len(batch))
        exch_total = 0
        fn = getattr(ex, "exchange_bytes_per_iter", None)
        if fn is not None:
            try:
                exch_total = int(fn()) * int(iters)
            except Exception:
                exch_total = 0
        for i, r in enumerate(batch):
            if r.cost is None:
                continue
            # Integer bytes: the first member absorbs the remainder so
            # the shares sum exactly to the total.
            share = exch_total // n + (exch_total % n if i == 0 else 0)
            r.cost.charge(
                iterations=int(iters), engine_s=engine_s / n,
                exchange_bytes=share, direction_switches=int(switches),
            )

    def _cache_put(self, key, value) -> None:
        """Cache insert that degrades instead of failing the request: a
        computed answer is never thrown away because the cache hiccuped
        (serving correctness never depends on the cache — a failed put
        only costs a future recompute)."""
        try:
            self.cache.put(key, value)
        except Exception as e:
            metrics.counter("lux_serve_cache_put_errors_total").inc()
            self.log.warning("cache put failed for %r: %r", key, e)

    def _execute_batch(self, batch: List[Request]):
        if batch[0].app == "sssp":
            self._execute_sssp_batch(batch)
            return
        if batch[0].app in self._gas_rooted:
            self._execute_gas_batch(batch)
            return
        if batch[0].app == "_drain":
            # Hot-swap barrier: FIFO ordering means every request admitted
            # before the swap flipped the serving pointer has already been
            # executed by the time this runs — retiring the old version's
            # state here can fail no in-flight query.
            batch[0].future.set_result(batch[0].payload())
            return
        # Unbatchable request (singleton list): cached fixpoint runner.
        (key, run) = batch[0].payload
        cost = batch[0].cost
        hit = self.cache.get(key)   # raced submits may have filled it
        if hit is None:
            t0 = spans.clock()
            hit = run(batch[0].deadline)
            if cost is not None:
                cost.charge(
                    iterations=int(hit.get("iters", 0)),
                    engine_s=spans.clock() - t0,
                    direction_switches=int(
                        hit.get("direction_switches", 0)),
                )
            self._cache_put(key, hit)
        elif cost is not None:
            cost.outcome = "hit"     # a raced submit filled the cache
        batch[0].future.set_result(hit)

    def _execute_sssp_batch(self, batch: List[Request]):
        snap = batch[0].payload[0]   # batch_key pins one snapshot per batch
        roots = [r.payload[1] for r in batch]
        # A retry must respect the tightest deadline riding the batch.
        deadlines = [r.deadline for r in batch if r.deadline is not None]
        deadline = min(deadlines) if deadlines else None
        if len(batch) == 1:
            key = self._engine_key("push", snap, ("sssp", 1))
            ex = self._sssp_single(snap)

            def run_engine():
                with spans.span("serve.engine", app="sssp", engine="push",
                                lanes=1):
                    state, iters = ex.run(start=roots[0])
                    return [_host_values(ex, state)], int(iters)
        else:
            key = self._engine_key(
                "push_multi", snap, ("sssp", self.config.max_batch)
            )
            ex = self._sssp_multi(snap)

            def run_engine():
                with spans.span("serve.engine", app="sssp",
                                engine="push_multi", lanes=len(roots)):
                    state, iters = ex.run(roots)
                    if hasattr(ex, "gather_values"):
                        # Sharded lanes: one device→host gather + unpad
                        # for the whole batch, then column slices — not
                        # len(roots) separate transfers.
                        allv = ex.gather_values(state)
                        return [
                            np.ascontiguousarray(allv[:, j])
                            for j in range(len(roots))
                        ], int(iters)
                    return [
                        ex.values_for(state, j) for j in range(len(roots))
                    ], int(iters)
        t0 = spans.clock()
        results, iters = self._engine_execute(
            "sssp", snap, key, deadline, run_engine)
        self._charge_batch(batch, ex, iters, spans.clock() - t0)
        for r, root, vals in zip(batch, roots, results):
            out = {"values": vals, "iters": iters, "start": root}
            self._cache_put((snap.fingerprint, "sssp", root), out)
            r.future.set_result(out)

    def _execute_gas_batch(self, batch: List[Request]):
        """Rooted GAS batch: one lane runs the direction-adaptive engine
        (and reports its push/pull split); a coalesced window runs the
        K-lane dense multi-source sweep. Per-root host finalization
        (BFS parents, ...) merges into each result dict."""
        app = batch[0].app
        snap = batch[0].payload[0]
        roots = [r.payload[1] for r in batch]
        deadlines = [r.deadline for r in batch if r.deadline is not None]
        deadline = min(deadlines) if deadlines else None
        prog = self._gas_program(app)
        if len(batch) == 1:
            key = self._engine_key("gas", snap, self._gas_key_extra(app))
            ex = self._gas_single(app, snap)

            def run_engine():
                with spans.span("serve.engine", app=app, engine="gas",
                                lanes=1):
                    state, iters = ex.run(start=roots[0])
                    dirs = {
                        "direction_push": int(ex.push_iters),
                        "direction_pull": int(ex.pull_iters),
                        "direction_switches": int(ex.direction_switches),
                    }
                    return [_host_values(ex, state)], int(iters), dirs
        else:
            key = self._engine_key(
                "gas_multi", snap, (app, self.config.max_batch)
            )
            ex = self._gas_multi(app, snap)

            def run_engine():
                with spans.span("serve.engine", app=app,
                                engine="gas_multi", lanes=len(roots)):
                    state, iters = ex.run(roots)
                    return [
                        ex.values_for(state, j) for j in range(len(roots))
                    ], int(iters), {}
        t0 = spans.clock()
        results, iters, dirs = self._engine_execute(
            app, snap, key, deadline, run_engine)
        self._charge_batch(batch, ex, iters, spans.clock() - t0,
                           switches=dirs.get("direction_switches", 0))
        for r, root, vals in zip(batch, roots, results):
            out = {"values": vals, "iters": iters, "start": root}
            out.update(dirs)
            out.update(prog.finalize_host(snap.graph, vals))
            self._cache_put((snap.fingerprint, app, root), out)
            r.future.set_result(out)

    def _run_components(self, snap: Snapshot,
                        deadline: Optional[float] = None) -> dict:
        ex = self._components_engine(snap)
        key = self._engine_key("push", snap, ("components", 1))

        def run_engine():
            with spans.span("serve.engine", app="components",
                            engine="push"):
                state, iters = ex.run()
                return {"values": _host_values(ex, state),
                        "iters": int(iters)}

        return self._engine_execute("components", snap, key, deadline,
                                    run_engine)

    def _run_pagerank(self, ni: int, snap: Snapshot,
                      deadline: Optional[float] = None) -> dict:
        from lux_tpu.models.cli import final_values

        ex = self._pagerank_engine(snap)
        key = self._engine_key("pull", snap, ("pagerank",))

        def run_engine():
            with spans.span("serve.engine", app="pagerank", engine="pull",
                            iters=ni):
                vals = ex.run(ni)
                return {"values": final_values(ex, vals), "iters": ni}

        return self._engine_execute("pagerank", snap, key, deadline,
                                    run_engine)

    def _run_gas_fixpoint(self, app: str, snap: Snapshot,
                          deadline: Optional[float] = None,
                          extra=()) -> dict:
        """Root-free GAS fixpoint (labelprop, kcore): one adaptive run
        to convergence, host finalization merged into the cached dict."""
        ex = self._gas_single(app, snap, extra=extra)
        key = self._engine_key("gas", snap, self._gas_key_extra(app, extra))
        prog = self._gas_program(app, extra)

        def run_engine():
            with spans.span("serve.engine", app=app, engine="gas"):
                state, iters = ex.run()
                vals = _host_values(ex, state)
                out = {
                    "values": vals, "iters": int(iters),
                    "direction_push": int(ex.push_iters),
                    "direction_pull": int(ex.pull_iters),
                    "direction_switches": int(ex.direction_switches),
                }
                out.update(prog.finalize_host(snap.graph, vals))
                return out

        return self._engine_execute(app, snap, key, deadline, run_engine)

    # -- circuit-breaker probe ---------------------------------------------

    def _breaker_probe(self, bkey) -> bool:
        """Half-open probe (background thread): rebuild the tripped
        program's pool entry and prove ONE execution before the breaker
        closes and traffic returns. Runs under the sentinel's expect —
        rebuild compiles are warmup, and the probe's run reaches any
        lazily-jitted runner so post-probe serving stays recompile-free."""
        app, fp = bkey
        snap = self._serving
        if snap.fingerprint != fp:
            return True   # that snapshot swapped away; nothing to rebuild
        with spans.span("serve.breaker_probe", app=app):
            if app == "sssp":
                key = self._engine_key("push", snap, ("sssp", 1))
                self.pool.retire(lambda k: k == key)
                ex = self._sssp_single(snap)
                with self.pool.sentinel.expect(("probe",) + key):
                    faults.point("serve.engine.execute")
                    ex.run(start=0)
            elif app == "components":
                key = self._engine_key("push", snap, ("components", 1))
                self.pool.retire(lambda k: k == key)
                ex = self._components_engine(snap)
                with self.pool.sentinel.expect(("probe",) + key):
                    faults.point("serve.engine.execute")
                    ex.run()
            elif app in self._gas_rooted:
                key = self._engine_key(
                    "gas", snap, self._gas_key_extra(app))
                self.pool.retire(lambda k: k == key)
                ex = self._gas_single(app, snap)
                with self.pool.sentinel.expect(("probe",) + key):
                    faults.point("serve.engine.execute")
                    ex.run(start=0)
            elif app in self._gas_fixpoints:
                extra = (2,) if app == "kcore" else ()
                key = self._engine_key(
                    "gas", snap, self._gas_key_extra(app, extra))
                self.pool.retire(lambda k: k == key)
                ex = self._gas_single(app, snap, extra=extra)
                with self.pool.sentinel.expect(("probe",) + key):
                    faults.point("serve.engine.execute")
                    ex.run()
            else:
                key = self._engine_key("pull", snap, ("pagerank",))
                self.pool.retire(lambda k: k == key)
                ex = self._pagerank_engine(snap)
                with self.pool.sentinel.expect(("probe",) + key):
                    faults.point("serve.engine.execute")
                    ex.run(1)
        return True

    # -- snapshot hot-swap -----------------------------------------------

    def apply_edits(self, edits, warm_timeout: Optional[float] = None) -> dict:
        """Apply an edit batch and hot-swap serving onto version N+1.

        Sequence (one swap at a time; version N serves throughout):

        1. ``store.apply(edits)`` mints version N+1 (compaction, if the
           delta crossed LUX_DELTA_COMPACT_RATIO, proceeds in its own
           background thread — readers are unaffected either way);
        2. N+1's engines build + compile on a background warm thread,
           bounded by LUX_SNAPSHOT_WARM_TIMEOUT — on timeout or error the
           swap aborts with :class:`SnapshotSwapError` and N keeps
           serving *degraded* (see :attr:`degraded`; N+1 stays minted
           and durable — retry with :meth:`flush_edits`, never by
           re-sending the same edits);
        3. with LUX_INCREMENTAL, cached components/SSSP fixpoints are
           refreshed by warm-started incremental runs and stored under
           N+1's fingerprint *before* the flip (PageRank entries are
           evicted, not refreshed: its served semantics are
           ni-iterations-from-init, which a warm start cannot reproduce
           mid-trajectory — misses recompute on demand);
        4. the serving pointer flips (atomic assignment; every request
           reads it once at admission);
        5. a barrier request rides the FIFO batcher behind all remaining
           version-N work, then retires N's engines and evicts its cache
           entries — zero failed in-flight queries by construction.

        With a WAL armed (LUX_WAL_DIR), ``edits`` is appended (CRC-framed,
        fsync'd) *before* version N+1 is minted, so a crash anywhere in
        the swap loses nothing: :meth:`SnapshotStore.recover` replays the
        log to the exact minted state.

        Returns a summary dict (versions, fingerprints, eviction counts,
        incremental-refresh counts, timings).
        """
        from lux_tpu.graph.delta import EdgeEdits

        if not isinstance(edits, EdgeEdits):
            raise BadQueryError("apply_edits takes an EdgeEdits batch")
        return self._swap_entry(edits, edits, warm_timeout)

    def enqueue_edits(self, edits) -> dict:
        """Durably queue one batch behind the WAL *without* swapping.

        ROADMAP item 3's write-ahead queue: many small batches coalesce
        and the next :meth:`flush_edits` (or ``apply_edits``) folds them
        into ONE hot-swap — one warm, one flip, one drain. Auto-flushes
        once LUX_EDIT_QUEUE_MAX batches are pending."""
        from lux_tpu.graph.delta import EdgeEdits

        if self._closed:
            raise BadQueryError("session is closed")
        if not isinstance(edits, EdgeEdits):
            raise BadQueryError("enqueue_edits takes an EdgeEdits batch")
        try:
            pending = self.store.enqueue(edits)
        except ValueError as e:
            raise BadQueryError(str(e)) from None
        metrics.gauge("lux_serve_pending_edits").set(pending)
        if pending >= max(1, flags.get_int("LUX_EDIT_QUEUE_MAX")):
            return self.flush_edits()
        return {"queued": True, "pending": pending,
                "version": self.version}

    def flush_edits(self, warm_timeout: Optional[float] = None) -> dict:
        """Fold every enqueued batch into one hot-swap (no-op if none).

        Incremental cache refresh applies when exactly one batch is
        pending (the refresh needs the batch's edge lists); multi-batch
        flushes degrade to evict-only, which is always correct.

        This is also the *revalidate* half of stale-while-revalidate:
        after an aborted swap the minted version is still the store head
        (its edits are durable), so a flush with an empty queue re-warms
        and flips onto it rather than re-applying anything."""
        batches = self.store.pending_batches()
        if not batches and self.store.current().version == self.version:
            return {"queued": False, "pending": 0, "version": self.version,
                    "noop": True}
        refresh = batches[0] if len(batches) == 1 else None
        return self._swap_entry(None, refresh, warm_timeout)

    def _swap_entry(self, edits, refresh_edits,
                    warm_timeout: Optional[float]) -> dict:
        if self._closed:
            raise BadQueryError("session is closed")
        if warm_timeout is None:
            warm_timeout = flags.get_float("LUX_SNAPSHOT_WARM_TIMEOUT")
        with self._swap_lock:
            t_swap0 = spans.clock()
            old = self._serving
            finish = None
            token = None
            if spans.current_trace_id() is None and spans.enabled():
                tid, finish = spans.open_trace()
                token = spans.activate(tid)
            try:
                with spans.span("serve.snapshot_swap",
                                old_version=old.version):
                    summary = self._swap(old, edits, refresh_edits,
                                         warm_timeout, t_swap0)
            finally:
                if token is not None:
                    spans.deactivate(token)
                if finish is not None:
                    finish()
            return summary

    def _swap(self, old: Snapshot, edits, refresh_edits,
              warm_timeout: float, t_swap0: float) -> dict:
        try:
            snap = self.store.apply(edits)
        except ValueError as e:
            raise BadQueryError(str(e)) from None
        metrics.gauge("lux_serve_pending_edits").set(0)
        if snap.version == old.version:
            # flush_edits raced another flush; the queue was empty.
            return {"queued": False, "pending": 0, "version": old.version,
                    "noop": True}

        # Warm version N+1's engines off-thread so a stuck compile can't
        # wedge the session; the sentinel sees the builds as expected
        # warmup (pool.get wraps them in expect(key)).
        hbm_evictions0 = self.pool.stats()["hbm_evictions"]
        warm_err: List[BaseException] = []
        tid = spans.current_trace_id()

        def _warm():
            with spans.adopt(tid):
                with spans.span("serve.snapshot_warm",
                                version=snap.version):
                    try:
                        self.warmup(snap)
                    except BaseException as e:   # surfaced to the caller
                        warm_err.append(e)

        t_warm0 = spans.clock()
        warm_thread = threading.Thread(
            target=_warm, name=f"lux-snapshot-warm-v{snap.version}",
            daemon=True,
        )
        warm_thread.start()
        warm_thread.join(warm_timeout)
        warm_s = spans.clock() - t_warm0
        if warm_err and isinstance(warm_err[0], faults.CrashPoint):
            # An injected crash is process death, not a degradable
            # failure: re-raise it past every handler (BaseException) so
            # the harness exercises WAL recovery. The edits are already
            # durable — logged and committed before the warm started.
            raise warm_err[0]
        if warm_thread.is_alive() or warm_err:
            metrics.counter("lux_snapshot_aborts_total").inc()
            why = (f"warmup timed out after {warm_timeout:.1f}s"
                   if warm_thread.is_alive()
                   else f"warmup failed: {warm_err[0]!r}")
            self.log.error("snapshot swap v%d -> v%d aborted: %s",
                           old.version, snap.version, why)
            self._mark_degraded(why, old, snap)
            flight.dump("snapshot_swap_aborted", detail=why)
            raise SnapshotSwapError(
                f"snapshot v{snap.version} not swapped in ({why}); "
                f"v{old.version} still serving"
            )

        refreshed = None
        # Sharded serving degrades to evict-only: the incremental
        # executor warm-starts flat single-device states, which don't
        # compose with the padded per-shard layout. Eviction is always
        # correct — the warmed mesh of N+1 engines is already in the
        # pool by this point, so the flip still costs zero recompiles.
        if (flags.get_bool("LUX_INCREMENTAL") and refresh_edits is not None
                and self.meshspec.num_parts == 1):
            try:
                refreshed = self._incremental_refresh(old, snap,
                                                      refresh_edits)
            except Exception as e:
                # The refresh is an optimization over evict-and-recompute;
                # a minted, durable version must not be abandoned because
                # warm-starting caches failed. Degrade to evict-only.
                metrics.counter("lux_serve_refresh_errors_total").inc()
                flight.dump("incremental_refresh_failed", detail=repr(e))
                self.log.warning(
                    "incremental refresh v%d failed (%r); serving "
                    "evict-only", snap.version, e)
                refreshed = None

        # The atomic flip: requests admitted after this line bind to N+1.
        self._serving = snap  # luxlint: guarded-by=_swap_lock -- apply_edits holds it
        self._degraded = None  # luxlint: guarded-by=_swap_lock -- _swap_entry holds it
        metrics.gauge("lux_serve_degraded").set(0.0)
        metrics.gauge("lux_snapshot_version").set(float(snap.version))
        metrics.counter("lux_snapshot_applies_total").inc()

        drained = self._drain_behind(old)
        # HBM-budget evictions during this swap's warm: N+1's engines
        # admitting over N's residents shows up here (and as
        # X-Lux-Evicted on the HTTP swap response).
        drained["hbm_evicted"] = (self.pool.stats()["hbm_evictions"]
                                  - hbm_evictions0)
        swap_s = spans.clock() - t_swap0
        metrics.histogram("lux_snapshot_swap_seconds").observe(swap_s)
        self.log.info(
            "snapshot swap v%d -> v%d in %.2fs (warm %.2fs, "
            "evicted %d cache entries, retired %d engines)",
            old.version, snap.version, swap_s, warm_s,
            drained["evicted"], drained["retired"],
        )
        return {
            "old_version": old.version,
            "version": snap.version,
            "old_fingerprint": old.fingerprint,
            "fingerprint": snap.fingerprint,
            "nv": snap.graph.nv,
            "ne": snap.graph.ne,
            "delta_ratio": round(snap.ratio, 6),
            "warm_s": warm_s,
            "swap_s": swap_s,
            "refreshed": refreshed,
            **drained,
        }

    def _mark_degraded(self, why: str, old: Snapshot,
                       snap: Snapshot) -> None:
        """Stale-while-revalidate: ``old`` keeps serving, responses grow
        an X-Lux-Degraded header until a later swap lands."""
        self._degraded = {  # luxlint: guarded-by=_swap_lock -- _swap holds it
            "reason": why, "stale_version": old.version,
            "failed_version": snap.version, "since": spans.clock(),
        }
        metrics.gauge("lux_serve_degraded").set(1.0)

    def _drain_behind(self, old: Snapshot) -> dict:
        """Ride a barrier through the FIFO batcher behind every remaining
        version-``old`` request, then retire that version's state."""
        old_fp = old.fingerprint

        def _retire() -> dict:
            evicted = self.cache.evict_fingerprint(old_fp)
            retired = self.pool.retire(
                lambda k: isinstance(k, tuple) and len(k) > 1
                and k[1] == old_fp
            )
            # _served_keys is batcher-thread-only state and the barrier
            # runs on the batcher thread: prune without a lock.
            # luxlint: disable=LUX301 -- barrier runs on the batcher thread
            stale = {k for k in self._served_keys
                     if isinstance(k, tuple) and len(k) > 1
                     and k[1] == old_fp}
            # luxlint: disable=LUX301 -- barrier runs on the batcher thread
            self._served_keys -= stale
            # The outgoing snapshot's partition plans go with its
            # engines — a sharded swap atomically replaces the whole
            # mesh of engines plus the host-side plan they shared.
            plans = plan_cache().evict_fingerprint(old_fp)
            # Tuned configs are fingerprint-keyed like shard plans:
            # version N's artifacts must not influence N+1's engine keys
            # or overlays (the disk artifacts stay — they are evidence).
            from lux_tpu.tune import tune_cache

            tunes = tune_cache().evict_fingerprint(old_fp)
            with self._fallback_lock:
                stale = [k for k in self._tuned if k[0] == old_fp]
                for k in stale:
                    del self._tuned[k]
            return {"evicted": evicted, "retired": retired,
                    "plans_evicted": plans,
                    "tunes_evicted": tunes + len(stale)}

        while True:
            try:
                fut = self.batcher.submit(Request(
                    app="_drain", payload=_retire, batch_key=None,
                ))
                break
            except QueueFullError:
                # The queue is full of real traffic; the barrier must
                # still land (it frees the old snapshot), so back off
                # briefly and retry — admission is FIFO either way.
                time.sleep(0.01)
        return fut.result()

    def _incremental_refresh(self, old: Snapshot, snap: Snapshot,
                             edits) -> dict:
        """Warm-start cached fixpoints from version N's values and store
        them under N+1's fingerprint before the flip.

        Components and cached SSSP roots refresh bitwise (monotone push
        programs; engine/incremental.py proves the warm start exact).
        Cached SSSP roots ride the dense (nv, K) multi-source sweep in
        K-wide batches — the same warmed executable the serving path
        uses, so the refresh compiles nothing.
        """
        from lux_tpu.engine.incremental import IncrementalExecutor
        from lux_tpu.graph.delta import removed_edges
        from lux_tpu.models import incremental_ok
        from lux_tpu.models.components import ConnectedComponents
        from lux_tpu.models.sssp import SSSP

        removed = removed_edges(old.graph, edits.del_src, edits.del_dst)
        inserted = (edits.ins_src, edits.ins_dst)
        out = {"components": 0, "sssp": 0, "touched_frac": None}

        with spans.span("serve.incremental_refresh", version=snap.version):
            # Warm-start eligibility is the LUX604 monotone-convergence
            # proof (gascap.v1 via models.incremental_ok), not this
            # method's opinion — a program whose proof lapsed falls back
            # to the cold recompute path instead of tripping the
            # IncrementalExecutor contract gate mid-swap.
            cc_hit = (self.cache.get((old.fingerprint, "components"))
                      if incremental_ok("components") else None)
            if cc_hit is not None:
                ex = self._components_engine(snap)
                inc = IncrementalExecutor(
                    snap.graph, ConnectedComponents(), push=ex
                )
                key = self._engine_key("push", snap, ("components", 1))
                with self.pool.sentinel.expect(("incremental",) + key), \
                        spans.span("serve.incremental", app="components"):
                    state, iters, info = inc.run(
                        cc_hit["values"], removed=removed,
                        inserted=inserted,
                    )
                self._cache_put(
                    (snap.fingerprint, "components"),
                    {"values": np.asarray(state.values),
                     "iters": int(iters), "incremental": True},
                )
                out["components"] = 1
                out["touched_frac"] = info["touched_frac"]

            roots = [
                k[2] for k in self.cache.keys()
                if isinstance(k, tuple) and len(k) == 3
                and k[0] == old.fingerprint and k[1] == "sssp"
            ] if incremental_ok("sssp") else []
            if roots:
                k_w = self.config.max_batch
                multi = self._sssp_multi(snap)
                inc = IncrementalExecutor(snap.graph, SSSP(), multi=multi)
                mkey = self._engine_key("push_multi", snap, ("sssp", k_w))
                for i in range(0, len(roots), k_w):
                    lane_roots, olds = [], []
                    for r in roots[i:i + k_w]:
                        hit = self.cache.get((old.fingerprint, "sssp", r))
                        if hit is not None:   # LRU may race entries away
                            lane_roots.append(r)
                            olds.append(hit["values"])
                    if not lane_roots:
                        continue
                    with self.pool.sentinel.expect(
                            ("incremental",) + mkey), \
                            spans.span("serve.incremental", app="sssp",
                                       lanes=len(lane_roots)):
                        state, iters, info = inc.run_multi(
                            lane_roots, olds, removed=removed,
                            inserted=inserted,
                        )
                    for j, r in enumerate(lane_roots):
                        self._cache_put(
                            (snap.fingerprint, "sssp", r),
                            {"values": multi.values_for(state, j),
                             "iters": int(iters), "start": r,
                             "incremental": True},
                        )
                    out["sssp"] += len(lane_roots)
                    out["touched_frac"] = info["touched_frac"]
        return out

    def snapshot_info(self) -> dict:
        """The /snapshot GET payload: serving version + store history."""
        snap = self._serving
        return {
            "version": snap.version,
            "fingerprint": snap.fingerprint,
            "nv": snap.graph.nv,
            "ne": snap.graph.ne,
            "delta_ratio": round(snap.ratio, 6),
            "compacted": snap.compacted,
            "history": self.store.history(),
            "pending_edits": self.store.pending_edits(),
            "wal": self.store.wal_stats(),
        }

    # -- introspection / lifecycle ---------------------------------------

    def _mesh_block(self) -> dict:
        """The serving-mesh view shared by ``stats`` and ``/statusz``:
        mesh spec/shape plus live pool entries grouped by the mesh-shape
        component of their key (a hot-swap mid-drain shows both the
        incoming and outgoing mesh populations here)."""
        by_shape: Dict[str, int] = {}
        for k in self.pool.keys():
            shape = (k[-1] if isinstance(k, tuple) and k
                     and isinstance(k[-1], tuple) else None)
            label = "x".join(map(str, shape)) if shape else "?"
            by_shape[label] = by_shape.get(label, 0) + 1
        with self._fallback_lock:
            fallbacks = dict(self._mesh_fallbacks)
        return {
            "spec": self.meshspec.spec,
            "shape": list(self.meshspec.shape),
            "num_parts": self.meshspec.num_parts,
            "pool_entries": by_shape,
            "plans": plan_cache().stats(),
            # Apps that could not build on the mesh and dropped to a
            # per-chip engine (correct answers, none of the scaling).
            # Empty is the healthy state; the serve smoke asserts it.
            "fallbacks": fallbacks,
            **({"warning": "mesh fallback active: "
                           + ", ".join(sorted(fallbacks))}
               if fallbacks else {}),
            # Latest engine-observatory telemetry per engine: phase
            # split, useful-bytes ratio, frontier density ({} until an
            # instrumented run has happened in this process).
            "engobs": self._engobs_block(),
        }

    @staticmethod
    def _engobs_block() -> dict:
        """engobs.latest() with the overlap number labeled for what it
        is: ``exchange_hidden_frac`` is a host-clock *budget* (an upper
        bound — phase fencing serializes the overlap it prices), so each
        record carries a note saying so, plus the device-measured
        ``realized_hidden_frac`` from the latest profile.v1 capture when
        one exists in this process."""
        realized = prof.latest_realized()
        out = {}
        for kind, rec in engobs.latest().items():
            rec = dict(rec)
            if "exchange_hidden_frac" in rec \
                    or "run_exchange_hidden_frac" in rec:
                rec["exchange_hidden_frac_note"] = "budget (upper bound)"
                if realized is not None:
                    rec["realized_hidden_frac"] = realized
            out[kind] = rec
        return out

    def profile_capture(self, steps: int = 8) -> dict:
        """Run a programmatic device-timeline capture window (the
        ``POST /profilez`` handler): ``steps`` fused PageRank steps on
        the serving engine under ``jax.profiler.trace``, parsed into a
        ``profile.v1`` report. Requires ``LUX_PROF_DIR``; raises
        ``prof.CaptureBusyError`` when a capture is already running."""
        from lux_tpu.engine.pull_sharded import hard_sync

        steps = max(1, min(int(steps), 64))
        ex = self._pagerank_engine()
        ex.warmup()
        vals = ex.init_values()
        op_maps = []
        step = getattr(ex, "_step", None)
        dg = getattr(ex, "_device_graph", None)
        if step is not None and dg is not None:
            # The AOT lowering below costs one backend compile — an
            # expect window budgets it so the serving zero-recompile
            # contract (pool recompile counters) stays clean.
            try:
                with self.pool.sentinel.expect(("profilez", "opmap")):
                    op_maps.append(prof.op_map_for(step, vals, dg))
            # A failed op-map build degrades to an untagged (still
            # valid) report; the capture must not fail over it.
            # luxlint: disable=LUX007 -- degraded capture is the outcome
            except Exception as e:
                self.log.warning("profile op-map build failed: %r", e)

        def drive():
            v = vals
            for _ in range(steps):
                v = ex.step(v)
            return hard_sync(v)

        _, rep = prof.profile_window(drive, steps=steps, op_maps=op_maps)
        # A capture is a (config -> realized overlap) observation too:
        # the compact headline numbers go into the ledger (the full
        # profile.v1 artifact stays under LUX_PROF_DIR).
        ledger.record_run(
            "profile",
            {"steps": steps,
             "realized_hidden_frac": rep.get("realized_hidden_frac"),
             "devices": len(rep.get("devices") or {}),
             "nv": int(self.graph.nv), "ne": int(self.graph.ne)},
            graph_fingerprint=self.fingerprint, program="PageRank",
            engine_kind="profilez", mesh_shape=self._mesh_label(),
        )
        return rep

    def costz(self) -> dict:
        """Per-tenant cost accounting (the ``/costz`` payload)."""
        out = self.costs.snapshot()
        out["config"] = {"hash": flags.config_hash()}
        return out

    def mesh_exchange_bytes(self) -> dict:
        """Per-app dense-estimate exchange bytes per iteration for the
        warm sharded engines ({} on a single-chip mesh). serve_bench
        publishes this in the serve_bench.v1 mesh evidence block."""
        if not self.sharded:
            return {}
        out = {}
        for app, get_engine in (
            ("sssp", self._sssp_single),
            ("sssp_multi", self._sssp_multi),
            ("components", self._components_engine),
            ("pagerank", self._pagerank_engine),
        ):
            ex = get_engine()
            fn = getattr(ex, "exchange_bytes_per_iter", None)
            if fn is not None:
                out[app] = int(fn())
        # GAS engines report only when already warm: this accessor must
        # stay cheap (no surprise compiles from an evidence request).
        snap = self._serving
        warm = set(self.pool.keys())
        for app in tuple(self._gas_rooted) + tuple(self._gas_fixpoints):
            extra = (2,) if app == "kcore" else ()
            key = self._engine_key(
                "gas", snap, self._gas_key_extra(app, extra))
            if key not in warm:
                continue
            ex = self._gas_single(app, extra=extra)
            fn = getattr(ex, "exchange_bytes_per_iter", None)
            if fn is not None:
                out["gas_" + app] = int(fn())
        return out

    def stats(self) -> dict:
        snap = self._serving
        s = {
            "graph": {"nv": snap.graph.nv, "ne": snap.graph.ne,
                      "fingerprint": snap.fingerprint},
            "snapshot": {"version": snap.version,
                         "delta_ratio": round(snap.ratio, 6),
                         "compacted": snap.compacted},
            "pool": self.pool.stats(),
            "cache": self.cache.stats(),
            "batcher": self.batcher.stats(),
            "mesh": self._mesh_block(),
            "tune": self._tune_block(),
            "programs": self._programs_block(),
            "memory": self._memory_block(),
            "requests": int(self._requests.value),
        }
        if self._latency.count:
            s["latency_s"] = {
                "count": self._latency.count,
                "p50": self._latency.quantile(0.5),
                "p99": self._latency.quantile(0.99),
            }
        return s

    def statusz(self) -> dict:
        """Rolling operational view (the /statusz payload): windowed
        SLO quantiles per app, queue pressure, cache efficiency, batch
        width, and the shed/reject/recompile counters that page."""
        b = self.batcher.stats()
        c = self.cache.stats()
        p = self.pool.stats()
        probes = c["hits"] + c["misses"]
        return {
            "windows": self.slo.snapshot(),
            # The behavioral flag config this process serves under —
            # two /statusz payloads with different hashes are not
            # comparable evidence (ledger A/B pairing keys on it too).
            "config": {"hash": flags.config_hash()},
            "costs": self.costs.totals(),
            "snapshot": {"version": self.version,
                         "fingerprint": self.fingerprint,
                         "pending_edits": self.store.pending_edits()},
            "breaker": self.breaker.stats(),
            "degraded": self._degraded,
            "faults": {"armed": [dataclasses.asdict(r)
                                 for r in faults.armed()],
                       "injected": faults.counts()},
            "queue": {"depth": b["queue_depth"],
                      "capacity": b["queue_capacity"]},
            "cache_hit_rate": (c["hits"] / probes) if probes else None,
            "batch_size": self.batcher.batch_histogram(),
            "mesh": self._mesh_block(),
            "tune": self._tune_block(),
            "programs": self._programs_block(),
            "memory": self._memory_block(),
            # Latest adaptive-executor direction split (push/pull iters,
            # mid-run switches) per GAS engine kind; {} until one runs.
            "gas": {kind: rec for kind, rec in engobs.latest().items()
                    if kind.startswith("gas")},
            "counters": {
                "requests": int(self._requests.value),
                "rejected": b["rejected"],
                "deadline_expired": b["deadline_expired"],
                "warmup_compiles": p["warmup_compiles"],
                "recompiles": p["recompiles"],
                "ir_findings": p["ir_findings"],
                "gas_findings": p["gas_findings"],
            },
            "flight": flight.counts(),
        }

    def _flight_context(self) -> dict:
        """Context block stamped into every flight.v1 postmortem."""
        return {
            "graph": {"nv": self.graph.nv, "ne": self.graph.ne,
                      "fingerprint": self.fingerprint},
            "snapshot": {"version": self.version},
            "pool": self.pool.stats(),
            "cache": self.cache.stats(),
            "batcher": self.batcher.stats(),
            "sentinel": self.pool.sentinel.stats(),
            "breaker": self.breaker.stats(),
            "degraded": self._degraded,
            "costs": self.costs.totals(),
        }

    def close(self):
        if not self._closed:
            self._closed = True
            flight.remove_context(self._flight_name)
            self.batcher.close()
            self.breaker.drain_probes()
            self.pool.close()
            self.store.drain_compactions()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _timed:
    def __init__(self, log, what):
        self.log, self.what = log, what

    def __enter__(self):
        self.t0 = spans.clock()

    def __exit__(self, *exc):
        self.log.info(
            "%s: %.2fs", self.what, spans.clock() - self.t0
        )
