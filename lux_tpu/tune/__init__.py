"""Profile-guided auto-tuner: close the measurement -> knob loop.

The repo measures everything (engobs phase splits, exchange-ledger
useful_ratio, profile.v1 realized overlap, run-ledger config cohorts)
but a human still picked ``LUX_EXCHANGE``, the GAS hysteresis, and the
grouped tail by hand. This package closes the loop per
(graph fingerprint, program, engine kind, mesh shape, device kind):

- :mod:`space` declares the knob axes the tuner may turn
  (:data:`TUNER_MANAGED`) and enumerates candidates deterministically.
- :mod:`probe` builds an executor under a candidate flag overlay
  (:func:`lux_tpu.utils.flags.overrides`) and scores a short
  fixed-iteration burst from the engobs phase split — never wall-clock
  alone.
- :mod:`search` runs successive halving over the space and returns a
  ``tuneconf.v1`` artifact; every probe and the selection land in the
  run ledger so lux_doctor attributes tuned-vs-default deltas.
- :mod:`artifact` persists/loads the artifact JSON; :mod:`cache` is the
  ShardPlanCache-shaped LRU serving warmup consults, evicted with the
  plan cache on snapshot swaps.

``tools/luxlint.py --tune`` (analysis/tuneck.py, LUX5xx) verifies the
artifacts offline — the config JSON is gated evidence, like plans.
"""

from lux_tpu.tune.artifact import (SCHEMA, key_string, list_artifacts,
                                   load, load_path, make_key, save)
from lux_tpu.tune.cache import TuneCache, tune_cache
from lux_tpu.tune.probe import ProbeResult, run_probe
from lux_tpu.tune.search import tune
from lux_tpu.tune.space import (TUNER_MANAGED, default_candidate,
                                knob_space)

__all__ = [
    "SCHEMA", "TUNER_MANAGED", "TuneCache", "ProbeResult",
    "default_candidate", "key_string", "knob_space", "list_artifacts",
    "load", "load_path", "make_key", "run_probe", "save", "tune",
    "tune_cache",
]
