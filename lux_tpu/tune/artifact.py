"""``tuneconf.v1`` artifacts: the persisted, verifiable output of one
tuner search.

A tune artifact is *evidence*, exactly like a saved partition plan: it
names the workload it was searched for (graph fingerprint, program,
engine kind, mesh shape, device kind), the winning knob assignment, and
the full score table with the run-ledger record ids of every probe that
produced it — so ``luxlint --tune`` can verify the selection offline
and PERF.md claims can cite it. Files are one JSON object each,
written atomically (tmp + rename) under ``LUX_TUNE_DIR`` with a name
derived from the key, so re-tuning the same workload replaces its
artifact in place.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional

SCHEMA = "tuneconf.v1"

# Key fields, in key_string order. device_kind joins the run-ledger key
# quartet because a config searched on one chip is not evidence for
# another (the accelerator survey's reproducibility complaint).
KEY_FIELDS = ("graph_fingerprint", "program", "engine_kind",
              "mesh_shape", "device_kind")

__all__ = ["SCHEMA", "KEY_FIELDS", "make_key", "key_string",
           "artifact_path", "build", "save", "load_path", "load",
           "list_artifacts"]


def make_key(graph_fingerprint: str, program: str, engine_kind: str,
             mesh_shape: str, device_kind: str) -> Dict[str, str]:
    return {
        "graph_fingerprint": str(graph_fingerprint),
        "program": str(program),
        "engine_kind": str(engine_kind),
        "mesh_shape": str(mesh_shape),
        "device_kind": str(device_kind),
    }


def key_string(key: Dict[str, str]) -> str:
    return "|".join(str(key[f]) for f in KEY_FIELDS)


def _key_hash(key: Dict[str, str]) -> str:
    return hashlib.sha1(key_string(key).encode("utf-8")).hexdigest()[:12]


def artifact_path(root: str, key: Dict[str, str]) -> str:
    return os.path.join(root, f"tuneconf-{_key_hash(key)}.json")


def build(key: Dict[str, str], config: Dict[str, str], score: float,
          score_table: List[dict], graph_meta: Dict[str, int],
          tuner: Dict[str, object],
          select_record_id: Optional[str] = None,
          created_at: Optional[float] = None) -> dict:
    """Assemble one artifact dict. The id is content-derived (key +
    winning config + per-row scores), so identical searches mint
    identical ids — determinism is testable end to end."""
    blob = key_string(key) + "\x00" + json.dumps(config, sort_keys=True) \
        + "\x00" + json.dumps(
            [[r["score"], r["iters"], r["rung"]] for r in score_table])
    art = {
        "schema": SCHEMA,
        "id": "tune-" + hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12],
        "created_at": float(time.time() if created_at is None
                            else created_at),
        "key": dict(key),
        "key_string": key_string(key),
        "config": dict(config),
        "score": float(score),
        "score_table": score_table,
        "probe_ledger_ids": [r["probe_record_id"] for r in score_table
                             if r.get("probe_record_id")],
        "graph_meta": dict(graph_meta),
        "tuner": dict(tuner),
    }
    if select_record_id:
        art["select_record_id"] = select_record_id
    return art


def save(root: str, art: dict) -> str:
    """Atomic write; returns the artifact path."""
    os.makedirs(root, exist_ok=True)
    path = artifact_path(root, art["key"])
    fd, tmp = tempfile.mkstemp(dir=root, prefix=".tuneconf-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(art, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_path(path: str) -> dict:
    with open(path) as f:
        art = json.load(f)
    if art.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: schema {art.get('schema')!r}, want {SCHEMA!r}")
    return art


def load(root: str, key: Dict[str, str]) -> Optional[dict]:
    """The persisted artifact for ``key``, or None. A file that exists
    but fails to parse raises — a corrupt artifact must never silently
    become a fallback-to-default."""
    path = artifact_path(root, key)
    if not os.path.exists(path):
        return None
    art = load_path(path)
    if art.get("key_string") != key_string(key):
        raise ValueError(
            f"{path}: key_string {art.get('key_string')!r} does not match "
            f"requested key {key_string(key)!r} (hash collision or "
            "hand-edited artifact)")
    return art


def list_artifacts(root: str) -> List[str]:
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return []
    return [os.path.join(root, e) for e in entries
            if e.startswith("tuneconf-") and e.endswith(".json")]
