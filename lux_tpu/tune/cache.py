"""In-memory LRU of ``tuneconf.v1`` artifacts over the ``LUX_TUNE_DIR``
store — keyed and evicted exactly like :class:`ShardPlanCache`
(serve/mesh.py): the hot-swap drain calls :meth:`evict_fingerprint`
next to the plan eviction, so a snapshot swap atomically retires the
mesh of engines, its partition plan, *and* its tuned configs. The new
fingerprint then misses here and serving falls back to defaults — a
counted (``lux_tune_fallback_total`` lives with the Session, which
knows the app label), never silent, event until someone re-tunes.

Disk artifacts are never deleted on eviction: they are evidence, and
``luxlint --tune`` holds the staleness/fingerprint line on them
offline.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional

from lux_tpu.obs import metrics
from lux_tpu.tune import artifact
from lux_tpu.utils import flags
from lux_tpu.utils.locks import make_lock
from lux_tpu.utils.logging import get_logger

__all__ = ["TuneCache", "tune_cache"]


class TuneCache:
    """LRU of tune artifacts keyed by the artifact key string."""

    def __init__(self, root: Optional[str] = None):
        self._lock = make_lock("tune.cache")
        self._entries = OrderedDict()  # luxlint: guarded-by=_lock
        self._root = root
        self._hits = metrics.counter("lux_tune_hits_total")
        self._misses = metrics.counter("lux_tune_misses_total")
        self._evicted = metrics.counter("lux_tune_evicted_total")
        self.log = get_logger("tune")

    def root(self) -> Optional[str]:
        return self._root if self._root is not None \
            else flags.get("LUX_TUNE_DIR")

    def enabled(self) -> bool:
        return bool(self.root())

    def _cap(self) -> int:
        return max(1, flags.get_int("LUX_TUNE_CACHE"))

    def get(self, key: Dict[str, str]) -> Optional[dict]:
        """The artifact for ``key``: memory first, then one disk load.
        None when no artifact exists (the caller counts the fallback) or
        the cache is disarmed."""
        root = self.root()
        if not root:
            return None
        ks = artifact.key_string(key)
        with self._lock:
            art = self._entries.get(ks)
            if art is not None:
                self._entries.move_to_end(ks)
                self._hits.inc()
                return art
            self._misses.inc()
            art = artifact.load(root, key)
            if art is None:
                return None
            self._entries[ks] = art
            self._entries.move_to_end(ks)
            cap = self._cap()
            while len(self._entries) > cap:
                old_key, _ = self._entries.popitem(last=False)
                self._evicted.inc()
                self.log.info("tune cache evicted %r (LRU, cap %d)",
                              old_key, cap)
            return art

    def put(self, art: dict) -> str:
        """Persist a freshly searched artifact and admit it; returns the
        artifact path."""
        root = self.root()
        if not root:
            raise RuntimeError(
                "TuneCache.put with LUX_TUNE_DIR unset: nowhere to "
                "persist the artifact")
        path = artifact.save(root, art)
        ks = art["key_string"]
        with self._lock:
            self._entries[ks] = art
            self._entries.move_to_end(ks)
            cap = self._cap()
            while len(self._entries) > cap:
                self._entries.popitem(last=False)
                self._evicted.inc()
        return path

    def evict_fingerprint(self, fingerprint: str) -> int:
        """Drop every in-memory entry tuned for ``fingerprint``
        (hot-swap drain). Disk artifacts stay — they are evidence."""
        with self._lock:
            victims = [k for k, a in self._entries.items()
                       if a["key"]["graph_fingerprint"] == fingerprint]
            for k in victims:
                del self._entries[k]
            if victims:
                self._evicted.inc(len(victims))
        return len(victims)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        return {
            "entries": len(self),
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "evicted": int(self._evicted.value),
            "capacity": self._cap(),
            "armed": self.enabled(),
        }


_CACHE = TuneCache()


def tune_cache() -> TuneCache:
    """The process-wide cache (Session warmup, bench --tuned, smoke)."""
    return _CACHE
