"""One tuner probe: build an executor under a candidate flag overlay,
run a short fixed-iteration burst, and score it from the measured phase
split — never wall-clock alone.

The overlay (:func:`lux_tpu.utils.flags.overrides`) is the whole trick:
every tunable knob is captured at executor *build* time, so probing a
candidate is "build under the overlay, run, throw the engine away" —
``os.environ`` is never mutated, concurrent serving threads never see
the candidate, and the ``runrec.v1`` record appended for the probe
carries the candidate config (with its own ``config_hash``) because
``flags.snapshot()`` resolves through the same overlay. lux_doctor's
cohort pairing then works on probe records for free.

Scoring: per-iteration medians of the engobs phase split
(``exchange_s + compute_s``) when the run was phase-fenced, else the
per-iteration wall median, times an instability penalty for direction
switches and exchange self-downgrades — a candidate that flaps
directions or downgrades its frontier send every other iteration is
worse than its phase medians alone suggest.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Dict, Optional

from lux_tpu.obs import ledger
from lux_tpu.obs.iterlog import IterationRecorder
from lux_tpu.utils import flags

__all__ = ["ProbeResult", "run_probe", "score_summary"]

# Executors whose run() takes a positional iteration count and returns
# the value table; everything else is the (max_iters=, **init_kw) ->
# (state, total) fixpoint family.
_PULL_KINDS = frozenset({"pull", "tiled", "pull_sharded", "tiled_sharded"})

# Fixed dispatch chunk for every probe rung: per-iteration host-sync
# overhead depends on the chunk, so rungs must not vary it or scores
# stop being comparable across iteration budgets.
_CHUNK = 4


@dataclasses.dataclass
class ProbeResult:
    candidate: Dict[str, str]
    score: float
    iters: int               # iterations actually run
    record_id: Optional[str]  # runrec.v1 id of the tune_probe record
    detail: dict              # phase medians + stability counters


def _median(xs):
    return float(statistics.median(xs)) if xs else 0.0


def score_summary(summary: dict, iters_run: int, switches: int,
                  downgrades: int, penalty: float) -> tuple:
    """(score, detail) for one probe summary. Lower is better: seconds
    per iteration from phase medians, inflated by the instability
    penalty per switch/downgrade event per iteration."""
    records = summary.get("iterations") or []
    # The first record of a cold run can carry dispatch ramp even after
    # warmup; medians over the rest are the robust center.
    if len(records) >= 3:
        records = records[1:]
    ex_med = _median([r["exchange_s"] for r in records
                      if "exchange_s" in r])
    co_med = _median([r["compute_s"] for r in records
                      if "compute_s" in r])
    if ex_med or co_med:
        base = ex_med + co_med
    else:
        base = _median([r["t_iter_s"] for r in records if "t_iter_s" in r])
        if base == 0.0:
            # No per-iteration records at all (recorder disabled run):
            # fall back to run totals so the probe still orders.
            n = max(1, int(summary.get("num_iters") or iters_run or 1))
            base = float(summary.get("execute_s") or 0.0) / n
    events = max(0, int(switches)) + max(0, int(downgrades))
    score = base * (1.0 + penalty * events / max(1, iters_run))
    detail = {
        "exchange_s_med": ex_med,
        "compute_s_med": co_med,
        "t_iter_s_med": base,
        "direction_switches": int(switches),
        "exchange_downgrades": int(downgrades),
        "penalty": float(penalty),
    }
    return float(score), detail


def run_probe(graph, program, engine_kind: str,
              candidate: Dict[str, str], iters: int, *,
              init_kw: Optional[dict] = None,
              program_name: str = "?",
              graph_fingerprint: Optional[str] = None,
              mesh_shape: str = "1",
              rung: int = 0) -> ProbeResult:
    """Build + run one candidate for ``iters`` iterations and score it.

    The executor is compiled by its own ``warmup()`` before the recorded
    burst, so compile time never pollutes the phase medians — the same
    reason serving warms engines outside the query path.
    """
    from lux_tpu.analysis.ir import build_executor

    init_kw = dict(init_kw or {})
    iters = max(1, int(iters))
    penalty = flags.get_float("LUX_TUNE_PENALTY")
    overlay = dict(candidate)
    overlay["LUX_ENGOBS"] = "1"  # probes exist to be phase-measured
    with flags.overrides(overlay):
        ex = build_executor(engine_kind, graph, program)
        rec = IterationRecorder(engine_kind, int(graph.nv), int(graph.ne),
                                program=program_name)
        if engine_kind in _PULL_KINDS:
            ex.warmup()
            ex.run(iters, recorder=rec)
            iters_run = iters
        elif "multi" in engine_kind:
            # Multi-source executors take the root list positionally.
            start = int(init_kw.get("start", 0))
            ex.warmup(start=start)
            _, iters_run = ex.run([start], max_iters=iters,
                                  chunk=_CHUNK, recorder=rec)
        else:
            ex.warmup(**init_kw)
            _, iters_run = ex.run(max_iters=iters, recorder=rec,
                                  chunk=_CHUNK, **init_kw)
        rec.finish()
        summary = rec.summary()
        switches = getattr(ex, "direction_switches", 0)
        downgrades = getattr(ex, "exchange_downgrades", 0)
        score, detail = score_summary(summary, iters_run, switches,
                                      downgrades, penalty)
        record_id = ledger.record_run(
            "tune_probe",
            {
                "score": score,
                "iters": int(iters_run),
                "exchange_s_med": detail["exchange_s_med"],
                "compute_s_med": detail["compute_s_med"],
                "t_iter_s_med": detail["t_iter_s_med"],
                "direction_switches": detail["direction_switches"],
                "exchange_downgrades": detail["exchange_downgrades"],
            },
            graph_fingerprint=graph_fingerprint,
            program=program_name,
            engine_kind=engine_kind,
            mesh_shape=mesh_shape,
            tune={"candidate": dict(candidate), "rung": int(rung)},
        )
    return ProbeResult(dict(candidate), score, int(iters_run), record_id,
                       detail)
