"""Successive-halving search over the declared knob space.

Budget discipline is the point: a flat sweep at useful iteration counts
costs |space| x iters probes, but most losers are obvious after a short
burst. Rung 0 probes every candidate for ``LUX_TUNE_PROBE_ITERS``
iterations; each later rung keeps the top ``ceil(n / LUX_TUNE_ETA)``
by score and doubles the iteration budget, so total probe work stays
~seconds per workload while the final comparison between surviving
candidates is the best-measured one.

Everything is deterministic under ``LUX_TUNE_SEED``: the candidate
list enumerates in fixed order, oversized spaces subsample with a
seeded RNG (the all-defaults candidate always survives — the score
table must always contain the tuned-vs-default delta), and ties break
on candidate index. Same seed + same graph -> identical winner and
identical score table, which tests/test_tune.py holds as a contract.

``measure`` is injectable for tests: any callable
``(candidate, iters, rung) -> float | ProbeResult`` replaces the real
probe runner, so search logic is testable with a synthetic cost model
and no jax dispatch noise.
"""

from __future__ import annotations

import math
import random
import time
from typing import Callable, Dict, List, Optional

from lux_tpu.obs import ledger
from lux_tpu.tune import artifact, probe, space
from lux_tpu.utils import flags
from lux_tpu.utils.logging import get_logger

__all__ = ["tune"]

log = get_logger("tune")


def _subsample(candidates: List[Dict[str, str]], cap: int,
               seed: int) -> List[Dict[str, str]]:
    """Seeded subsample preserving enumeration order; candidate 0 (the
    all-defaults assignment) always survives."""
    if len(candidates) <= cap:
        return list(candidates)
    rng = random.Random(seed)
    picked = sorted(rng.sample(range(1, len(candidates)),
                               max(0, cap - 1)))
    return [candidates[0]] + [candidates[i] for i in picked]


def _coerce(result, candidate: Dict[str, str],
            iters: int) -> probe.ProbeResult:
    """Normalize an injected measure()'s return to a ProbeResult."""
    if isinstance(result, probe.ProbeResult):
        return result
    return probe.ProbeResult(dict(candidate), float(result), int(iters),
                             None, {})


def tune(graph, program, engine_kind: str, *,
         program_name: str,
         graph_fingerprint: str,
         mesh_shape: str = "1",
         device_kind: Optional[str] = None,
         init_kw: Optional[dict] = None,
         candidates: Optional[List[Dict[str, str]]] = None,
         measure: Optional[Callable] = None,
         created_at: Optional[float] = None) -> dict:
    """Search the knob space for one workload; returns the finished
    ``tuneconf.v1`` artifact dict (not yet persisted — callers decide
    the sink, e.g. :class:`lux_tpu.tune.cache.TuneCache`).

    Every probe and the final selection append run-ledger records, so
    ``lux_doctor`` can attribute tuned-vs-default deltas from the
    stored flag snapshots afterwards.
    """
    if device_kind is None:
        from lux_tpu.obs import report
        device_kind = report.device_profile()["device_kind"]
    key = artifact.make_key(graph_fingerprint, program_name, engine_kind,
                            mesh_shape, device_kind)

    seed = flags.get_int("LUX_TUNE_SEED")
    rungs = max(1, flags.get_int("LUX_TUNE_RUNGS"))
    eta = max(2, flags.get_int("LUX_TUNE_ETA"))
    probe_iters = max(1, flags.get_int("LUX_TUNE_PROBE_ITERS"))
    cap = max(2, flags.get_int("LUX_TUNE_MAX_CANDIDATES"))

    if candidates is None:
        # Footprint-pruned: candidates the memcap.v1 admission formula
        # would refuse at serving time never burn probe wall-clock.
        parts = 1
        try:
            # mesh_shape is the "x"-joined mesh label ("8", "2x4").
            for dim in str(mesh_shape).split("x"):
                parts *= max(1, int(dim))
        except (TypeError, ValueError):
            parts = 1
        candidates = space.knob_space(
            engine_kind, program_name=program_name,
            nv=int(getattr(graph, "nv", 0) or 0),
            ne=int(getattr(graph, "ne", 0) or 0), parts=parts)
    candidates = _subsample(candidates, cap, seed)

    t0 = time.perf_counter()
    score_table: List[dict] = []
    # survivors: (candidate_index, candidate); scored[i] is the latest
    # (score, index) pair for survivor list ordering.
    survivors = list(enumerate(candidates))
    iters = probe_iters
    best: Optional[probe.ProbeResult] = None
    best_idx = 0
    for rung in range(rungs):
        scored = []
        for idx, cand in survivors:
            if measure is not None:
                res = _coerce(measure(cand, iters, rung), cand, iters)
            else:
                res = probe.run_probe(
                    graph, program, engine_kind, cand, iters,
                    init_kw=init_kw, program_name=program_name,
                    graph_fingerprint=graph_fingerprint,
                    mesh_shape=mesh_shape, rung=rung)
            scored.append((res.score, idx, cand, res))
            score_table.append({
                "candidate_index": idx,
                "rung": rung,
                "iters": res.iters,
                "config": dict(cand),
                "score": res.score,
                "probe_record_id": res.record_id,
                "detail": res.detail,
            })
        # Stable ordering: score first, enumeration index breaks ties,
        # so two runs under one seed always pick the same survivors.
        scored.sort(key=lambda t: (t[0], t[1]))
        best = scored[0][3]
        best_idx = scored[0][1]
        keep = max(1, math.ceil(len(scored) / eta))
        survivors = [(idx, cand) for _, idx, cand, _ in scored[:keep]]
        log.info("tune rung %d: %d candidates @ %d iters, best score "
                 "%.3gs/iter (candidate %d)", rung, len(scored), iters,
                 scored[0][0], best_idx)
        if len(survivors) == 1 and rung + 1 < rungs:
            # Nothing left to halve; later rungs would re-measure the
            # lone survivor for no decision value.
            break
        iters *= 2

    assert best is not None
    elapsed = time.perf_counter() - t0
    default_rows = [r for r in score_table if r["candidate_index"] == 0]
    select_id = ledger.record_run(
        "tune_select",
        {
            "score": best.score,
            "default_score": default_rows[-1]["score"] if default_rows
            else best.score,
            "probes": len(score_table),
            "candidates": len(candidates),
            "search_s": elapsed,
        },
        graph_fingerprint=graph_fingerprint,
        program=program_name,
        engine_kind=engine_kind,
        mesh_shape=mesh_shape,
        tune={"winner": dict(best.candidate),
              "winner_index": best_idx,
              "device_kind": device_kind},
    )
    art = artifact.build(
        key, best.candidate, best.score, score_table,
        graph_meta={"nv": int(graph.nv), "ne": int(graph.ne)},
        tuner={
            "seed": seed, "rungs": rungs, "eta": eta,
            "probe_iters": probe_iters, "candidates": len(candidates),
            "penalty": flags.get_float("LUX_TUNE_PENALTY"),
            "search_s": elapsed,
            "winner_index": best_idx,
        },
        select_record_id=select_id,
        created_at=created_at,
    )
    log.info("tune selected candidate %d for %s: %s (score %.3gs/iter, "
             "%d probes, %.1fs)", best_idx, art["key_string"],
             best.candidate or "defaults", best.score, len(score_table),
             elapsed)
    return art
