"""Declared knob space for the profile-guided auto-tuner.

The tuner may only turn knobs that are (a) declared in the flag
registry, (b) captured at executor build time (so applying a winner at
serving warmup never recompiles per query), and (c) bitwise-neutral for
integral-valued programs — direction schedules, exchange packing, and
tail plans all prove bitwise parity in their own gates. That set is
:data:`TUNER_MANAGED`; lux_doctor uses it to recognize "these two
cohorts differ only by tuner-managed flags" and luxlint's LUX502 rejects
any artifact that configures a flag outside it.

Candidates are complete assignments (every managed flag applicable to
the engine kind gets an explicit value, defaults included) so a
persisted ``tuneconf.v1`` is self-describing. Enumeration is
deterministic: axes in fixed order, default value first on each axis,
itertools.product, then constraint pruning — the same engine kind always
yields the same candidate list in the same order, which is what makes
the search reproducible under one seed.

Layout/partition choice is not a flag axis: layout is part of the tune
*key* (`engine_kind`), so each layout with a plan-cache entry tunes
separately and bench.py compares the tuned rows across kinds.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from lux_tpu.utils import flags

__all__ = ["TUNER_MANAGED", "knob_space", "default_candidate",
           "is_sharded", "is_gas", "is_tiled"]

# Every flag the tuner is allowed to set. lux_doctor --tuned and
# luxlint LUX502 both key off this set.
TUNER_MANAGED = frozenset({
    "LUX_EXCHANGE",
    "LUX_EXCHANGE_FRONTIER_FRAC",
    "LUX_GAS_DENSITY_HI",
    "LUX_GAS_DENSITY_LO",
    "LUX_GROUPED_TAIL",
})

_GAS_KINDS = frozenset({"gas", "gas_multi", "gas_sharded",
                        "gas_multi_sharded"})
_TILED_KINDS = frozenset({"tiled", "tiled_sharded"})


def is_sharded(engine_kind: str) -> bool:
    return engine_kind.endswith("sharded")


def is_gas(engine_kind: str) -> bool:
    return engine_kind in _GAS_KINDS


def is_tiled(engine_kind: str) -> bool:
    return engine_kind in _TILED_KINDS


def _sdef(name: str) -> str:
    """Declared default as the string an env var would carry."""
    d = flags.default(name)
    return "" if d is None else str(d)


def _axes(engine_kind: str) -> List:
    """``[(flag, [values...])]`` applicable to the kind; default value
    first on every axis."""
    axes = []
    if is_sharded(engine_kind):
        modes = ["full", "compact"]
        if is_gas(engine_kind):
            # Frontier exchange is the sharded-GAS path; other sharded
            # executors silently run it as compact, which would probe
            # duplicates.
            modes.append("frontier")
        axes.append(("LUX_EXCHANGE", modes))
        axes.append(("LUX_EXCHANGE_FRONTIER_FRAC",
                     [_sdef("LUX_EXCHANGE_FRONTIER_FRAC"),
                      "0.125", "0.5"]))
    if is_gas(engine_kind):
        axes.append(("LUX_GAS_DENSITY_HI",
                     [_sdef("LUX_GAS_DENSITY_HI"), "0.25", "0.9"]))
        axes.append(("LUX_GAS_DENSITY_LO",
                     [_sdef("LUX_GAS_DENSITY_LO"), "0.05"]))
    if is_tiled(engine_kind):
        axes.append(("LUX_GROUPED_TAIL",
                     [_sdef("LUX_GROUPED_TAIL"), "1"]))
    return axes


def _admissible(cand: Dict[str, str]) -> bool:
    """Constraint pruning: frontier fraction only varies when the
    exchange actually runs frontier mode; hysteresis must keep lo < hi
    (equal thresholds would flap every iteration)."""
    if "LUX_EXCHANGE_FRONTIER_FRAC" in cand \
            and cand.get("LUX_EXCHANGE") != "frontier" \
            and cand["LUX_EXCHANGE_FRONTIER_FRAC"] \
            != _sdef("LUX_EXCHANGE_FRONTIER_FRAC"):
        return False
    if "LUX_GAS_DENSITY_HI" in cand:
        if float(cand["LUX_GAS_DENSITY_LO"]) \
                >= float(cand["LUX_GAS_DENSITY_HI"]):
            return False
    return True


def default_candidate(engine_kind: str) -> Dict[str, str]:
    """The all-defaults assignment over the kind's applicable knobs —
    always candidate 0, so a tuned-vs-default delta is in every score
    table."""
    return {flag: values[0] for flag, values in _axes(engine_kind)}


def knob_space(engine_kind: str, *, program_name: Optional[str] = None,
               nv: Optional[int] = None, ne: Optional[int] = None,
               parts: int = 1, k: int = 1) -> List[Dict[str, str]]:
    """Deterministic candidate list for one engine kind. Candidate 0 is
    :func:`default_candidate`; kinds with no applicable knobs get just
    that one (the tuner then records an honest "nothing to tune").

    When the caller supplies the probe context (``program_name`` +
    graph dims), candidates whose memcap.v1-predicted footprint does
    not fit the HBM budget are pruned *before* probing — a candidate
    that would be refused admission at serving time is wasted probe
    wall-clock. Candidate 0 is never pruned (the default config is the
    comparison baseline and the honest fallback)."""
    axes = _axes(engine_kind)
    if not axes:
        return [{}]
    names = [a[0] for a in axes]
    out = []
    for combo in itertools.product(*(a[1] for a in axes)):
        cand = dict(zip(names, combo))
        if _admissible(cand) and cand not in out:
            out.append(cand)
    if program_name and nv and ne:
        out = [out[0]] + [c for c in out[1:]
                          if _fits_budget(c, engine_kind, program_name,
                                          nv, ne, parts, k)]
    return out


def _fits_budget(cand: Dict[str, str], engine_kind: str,
                 program_name: str, nv: int, ne: int,
                 parts: int, k: int) -> bool:
    """True unless the candidate's predicted per-device footprint
    (under its own LUX_EXCHANGE mode) provably exceeds the HBM budget.
    Unknown footprint or no budget means fits — pruning only ever removes
    candidates admission would certainly refuse."""
    try:
        from lux_tpu.analysis import memck

        budget = memck.hbm_budget_bytes()
        if budget is None:
            return True
        mode = cand.get("LUX_EXCHANGE", "")
        pred = memck.predicted_engine_bytes(
            program_name, engine_kind, mode, nv, ne, parts, k=k)
        return pred is None or pred <= budget
    # luxlint: disable=LUX007 -- advisory pruning: a broken predictor keeps the full space
    except Exception:
        return True
