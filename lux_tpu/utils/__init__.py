from lux_tpu.utils.logging import get_logger
from lux_tpu.utils.timing import Timer

__all__ = ["get_logger", "Timer"]
