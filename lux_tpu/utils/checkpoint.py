"""Checkpoint / resume of vertex state.

The reference has none (SURVEY.md §5: state lives in device regions and is
never written back). Here vertex values are plain arrays, so checkpointing
is one compressed npz per snapshot: values + iteration counter + graph
fingerprint (to refuse resuming onto a different graph).

The fingerprint doubles as the serving layer's cache key (serve/session.py):
two graphs must not collide just because their edge *sources* agree, so it
samples all three structural arrays (sources, destinations, offsets).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from lux_tpu.graph.graph import Graph


class CheckpointError(ValueError):
    """A checkpoint file is missing, unreadable, or structurally wrong."""


def _sample_sum(a: np.ndarray, want: int = 1024) -> int:
    """Order-sensitive digest of up to ``want`` evenly-strided elements:
    each sample is weighted by its rank so permutations of the same
    multiset hash differently."""
    s = a[:: max(1, len(a) // want)][:want].astype(np.int64)
    return int(((np.arange(len(s), dtype=np.int64) + 1) * s).sum())


def fingerprint(graph: Graph) -> np.ndarray:
    """Cheap structural hash: counts plus rank-weighted samples of the
    edge sources, edge destinations, and CSC offsets. Sampling col_src
    alone (the pre-serving form) collided for graphs with identical
    sources but different destinations — e.g. the same out-edge multiset
    wired to different targets."""
    return np.array(
        [
            graph.nv,
            graph.ne,
            _sample_sum(graph.col_src),
            _sample_sum(graph.col_dst),
            _sample_sum(graph.row_ptr),
        ],
        dtype=np.int64,
    )


def fingerprint_hex(graph: Graph) -> str:
    """Compact string form of :func:`fingerprint` for dict/cache keys and
    JSON payloads (serving cache, /healthz)."""
    return "-".join(format(int(v) & 0xFFFFFFFFFFFFFFFF, "x")
                    for v in fingerprint(graph))


# Backwards-compatible alias (pre-serving internal name).
_fingerprint = fingerprint


def save(path: str, graph: Graph, values: np.ndarray, iteration: int,
         frontier: Optional[np.ndarray] = None) -> None:
    payload = {
        "values": values,
        "iteration": np.int64(iteration),
        "fingerprint": fingerprint(graph),
    }
    if frontier is not None:
        payload["frontier"] = frontier
    # Through a file object so the exact path is honored (np.savez would
    # silently append ".npz", breaking save->resume with the same path).
    with open(path, "wb") as f:
        np.savez_compressed(f, **payload)


def load(
    path: str, graph: Graph
) -> Tuple[np.ndarray, int, Optional[np.ndarray]]:
    """Load a checkpoint for ``graph``.

    Raises :class:`CheckpointError` (a ``ValueError``) with a clear
    message on a missing file, a non-npz/corrupt file, or an npz missing
    the checkpoint fields — the serving layer hits all three under churn
    and must surface them as client errors, not raw ``KeyError``s."""
    if not os.path.exists(path):
        raise CheckpointError(f"{path}: checkpoint file does not exist")
    try:
        z = np.load(path)
    except Exception as e:
        raise CheckpointError(
            f"{path}: not a readable checkpoint npz ({e})"
        ) from e
    with z:
        missing = {"values", "iteration", "fingerprint"} - set(z.files)
        if missing:
            raise CheckpointError(
                f"{path}: checkpoint is missing field(s) "
                f"{sorted(missing)} (corrupt or not a lux checkpoint)"
            )
        if not np.array_equal(z["fingerprint"], fingerprint(graph)):
            raise CheckpointError(
                f"{path}: checkpoint belongs to a different graph"
            )
        try:
            values = z["values"]
            iteration = int(z["iteration"])
            frontier = z["frontier"] if "frontier" in z.files else None
        except Exception as e:
            raise CheckpointError(
                f"{path}: checkpoint payload unreadable ({e})"
            ) from e
        return values, iteration, frontier
