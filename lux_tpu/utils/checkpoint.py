"""Checkpoint / resume of vertex state.

The reference has none (SURVEY.md §5: state lives in device regions and is
never written back). Here vertex values are plain arrays, so checkpointing
is one compressed npz per snapshot: values + iteration counter + graph
fingerprint (to refuse resuming onto a different graph).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from lux_tpu.graph.graph import Graph


def _fingerprint(graph: Graph) -> np.ndarray:
    # Cheap structural hash: counts plus a sample of the edge array.
    sample = graph.col_src[:: max(1, graph.ne // 1024)][:1024]
    return np.array(
        [graph.nv, graph.ne, int(sample.astype(np.int64).sum())],
        dtype=np.int64,
    )


def save(path: str, graph: Graph, values: np.ndarray, iteration: int,
         frontier: Optional[np.ndarray] = None) -> None:
    payload = {
        "values": values,
        "iteration": np.int64(iteration),
        "fingerprint": _fingerprint(graph),
    }
    if frontier is not None:
        payload["frontier"] = frontier
    # Through a file object so the exact path is honored (np.savez would
    # silently append ".npz", breaking save->resume with the same path).
    with open(path, "wb") as f:
        np.savez_compressed(f, **payload)


def load(
    path: str, graph: Graph
) -> Tuple[np.ndarray, int, Optional[np.ndarray]]:
    with np.load(path) as z:
        if not np.array_equal(z["fingerprint"], _fingerprint(graph)):
            raise ValueError(
                f"{path}: checkpoint belongs to a different graph"
            )
        frontier = z["frontier"] if "frontier" in z.files else None
        return z["values"], int(z["iteration"]), frontier
