"""jax version compatibility shims.

The engines are written against the current jax API (``jax.shard_map``
with ``check_vma=``); older releases only ship
``jax.experimental.shard_map.shard_map`` with the equivalent knob spelled
``check_rep``. This module resolves the difference once so every call
site can stay on the modern spelling.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on jax builds that have it; otherwise the
    experimental entry point. The legacy ``check_rep`` checker predates
    replication rules for ``while``/``scan`` and rejects the fused
    iteration loops outright, so the fallback always disables it —
    the varying-axis check is a static lint, not a semantics change."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )
