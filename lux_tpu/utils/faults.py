"""Seeded, deterministic fault injection for the serving stack.

Every failure path in serve/, graph/snapshot.py, and engine/incremental.py
was untested-by-construction: nothing could make an engine raise, a disk
stall, or a write tear on demand. This registry fixes that with *named
fault points* laced through those layers::

    faults.point("serve.engine.execute")          # maybe raise/delay/crash
    payload = faults.point("wal.fsync", data=payload)   # maybe corrupt

A point is a zero-cost no-op until armed: the hot path pays one module-
global bool check and returns. Arming happens through the ``LUX_FAULTS``
spec (read once via :func:`reconfigure`, never per call) or the
programmatic :func:`arm` / :func:`injected` API::

    LUX_FAULTS="serve.engine.execute:raise:0.25,batcher.assemble:delay_ms:1.0:2"

Spec grammar: ``point:kind:prob[:arg]``, comma-separated. Kinds:

- ``raise``    — raise :class:`FaultInjected` (a transient engine error;
  the serve retry/breaker machinery is expected to absorb it). ``arg``
  (optional int) caps how many times the rule fires — ``raise:1.0:2``
  injects exactly two failures then goes quiet, which is how tests model
  a transient blip.
- ``delay_ms`` — sleep ``arg`` milliseconds (slow device / slow disk).
- ``corrupt``  — flip one byte/element of the ``data`` payload handed to
  the point and return the corrupted copy (torn/bit-rotted write).
  ``arg`` caps fire count like ``raise``.
- ``crash``    — raise :class:`CrashPoint`, a ``BaseException``: no
  ``except Exception`` handler (retry, batch recovery, warm threads) may
  absorb it, modeling sudden process death at that instruction. ``arg``
  caps fire count.

Determinism: each armed rule owns a ``random.Random`` seeded from
``(LUX_FAULTS_SEED, point, kind)``, so a given spec + seed fires on the
same draw sequence every run (thread interleaving can still reorder
*which request* sees a given draw; invariants, not exact victims, are
what chaos runs assert).

Fired injections are counted per ``(point, kind)`` both locally
(:func:`counts`) and in the metrics registry
(``lux_faults_injected_total{point,kind}``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import time
from typing import Dict, List, Optional, Tuple

from lux_tpu.utils import flags
from lux_tpu.utils.locks import make_lock
from lux_tpu.utils.logging import get_logger

__all__ = [
    "POINTS", "KINDS", "FaultInjected", "CrashPoint", "FaultRule",
    "parse", "arm", "disarm", "reconfigure", "armed", "counts",
    "injected", "point",
]

# The registered fault points. point() only accepts these names, so a
# typo'd lace site fails loudly the first time it is armed instead of
# silently never firing.
POINTS = (
    "serve.engine.execute",   # engine run inside the batcher (serve/session.py)
    "pool.build",             # executor build/compile (serve/pool.py)
    "snapshot.warm",          # hot-swap warmup of version N+1 (serve/session.py)
    "cache.put",              # result-cache insert (serve/cache.py)
    "wal.fsync",              # WAL record write+fsync (graph/wal.py)
    "batcher.assemble",       # batch formation on the worker (serve/batcher.py)
)

KINDS = ("raise", "delay_ms", "corrupt", "crash")


class FaultInjected(RuntimeError):
    """A ``raise``-kind fault fired: a *transient* engine/IO failure the
    degradation machinery (retry, breaker, cache bypass) should absorb."""

    def __init__(self, point_name: str):
        super().__init__(f"injected fault at {point_name}")
        self.point = point_name


class CrashPoint(BaseException):
    """A ``crash``-kind fault fired: simulated sudden process death.

    Deliberately a ``BaseException`` so no ``except Exception`` handler
    (retry loops, batch recovery, warm threads) can absorb it — only the
    test/chaos harness that armed it catches it, then exercises the
    recovery path (WAL replay) as a fresh process would.
    """

    def __init__(self, point_name: str):
        super().__init__(f"injected crash at {point_name}")
        self.point = point_name


@dataclasses.dataclass(frozen=True)
class FaultRule:
    point: str
    kind: str
    prob: float
    arg: Optional[float] = None   # delay_ms: milliseconds; others: max fires


class _Armed:
    """One armed rule plus its private seeded RNG and fire budget."""

    def __init__(self, rule: FaultRule, seed: int):
        self.rule = rule
        self.rng = random.Random(f"{seed}:{rule.point}:{rule.kind}")
        self.fires_left = (
            None if rule.kind == "delay_ms" or not rule.arg
            else int(rule.arg)
        )


_enabled = False
_lock = make_lock("faults")
_armed_rules: Dict[str, List[_Armed]] = {}
_counts: Dict[Tuple[str, str], int] = {}
_log = get_logger("faults")


def parse(spec: str) -> List[FaultRule]:
    """``point:kind:prob[:arg]`` comma list -> validated rules."""
    rules: List[FaultRule] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) not in (3, 4):
            raise ValueError(
                f"bad fault spec {part!r}: want point:kind:prob[:arg]"
            )
        name, kind, prob = bits[0], bits[1], bits[2]
        if name not in POINTS:
            raise ValueError(
                f"unknown fault point {name!r}; registered: {list(POINTS)}"
            )
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; kinds: {list(KINDS)}"
            )
        try:
            p = float(prob)
        except ValueError:
            raise ValueError(f"bad probability {prob!r} in {part!r}") from None
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"probability {p} outside [0, 1] in {part!r}")
        arg = None
        if len(bits) == 4:
            try:
                arg = float(bits[3])
            except ValueError:
                raise ValueError(f"bad arg {bits[3]!r} in {part!r}") from None
            if arg < 0:
                raise ValueError(f"negative arg {arg} in {part!r}")
        if kind == "delay_ms" and arg is None:
            raise ValueError(f"delay_ms needs an arg (ms) in {part!r}")
        rules.append(FaultRule(name, kind, p, arg))
    return rules


def arm(spec, seed: Optional[int] = None) -> int:
    """Arm rules (a spec string or an iterable of :class:`FaultRule`),
    replacing whatever was armed before. Returns the armed rule count."""
    global _enabled
    rules = parse(spec) if isinstance(spec, str) else list(spec)
    if seed is None:
        seed = flags.get_int("LUX_FAULTS_SEED")
    with _lock:
        _armed_rules.clear()
        for r in rules:
            _armed_rules.setdefault(r.point, []).append(_Armed(r, seed))
        _enabled = bool(_armed_rules)
    if rules:
        _log.info("faults armed: %s (seed=%d)",
                  ",".join(f"{r.point}:{r.kind}:{r.prob}" +
                           (f":{r.arg:g}" if r.arg is not None else "")
                           for r in rules), seed)
    return len(rules)


def disarm() -> None:
    """Back to the zero-cost no-op path (fire counts are kept)."""
    global _enabled
    with _lock:
        _armed_rules.clear()
        _enabled = False


def reconfigure() -> int:
    """(Re-)read ``LUX_FAULTS``/``LUX_FAULTS_SEED`` and arm accordingly.

    Runs once at import (so any process started with ``LUX_FAULTS`` set
    is faulted without code cooperation) and again from tests/tools that
    mutate the env — never by the hot path."""
    spec = flags.get("LUX_FAULTS") or ""
    if not spec.strip():
        disarm()
        return 0
    return arm(spec)


def armed() -> Tuple[FaultRule, ...]:
    with _lock:
        return tuple(a.rule for rules in _armed_rules.values()
                     for a in rules)


def counts() -> Dict[str, int]:
    """Fired-injection counts as ``{"point:kind": n}`` (since import)."""
    with _lock:
        return {f"{p}:{k}": n for (p, k), n in sorted(_counts.items())}


@contextlib.contextmanager
def injected(spec, seed: Optional[int] = None):
    """Arm ``spec`` for the block, restoring the previous arming after —
    the test-suite idiom for scoped injection."""
    with _lock:
        prev = [a.rule for rules in _armed_rules.values() for a in rules]
    arm(spec, seed=seed)
    try:
        yield
    finally:
        arm(prev)


def point(name: str, data=None):
    """One fault point. Returns ``data`` (possibly corrupted when a
    ``corrupt`` rule fires); may sleep, raise :class:`FaultInjected`, or
    raise :class:`CrashPoint`. When nothing is armed this is one bool
    check and a return."""
    if not _enabled:
        return data
    return _fire(name, data)


def _fire(name: str, data):
    with _lock:
        armed_here = _armed_rules.get(name)
        if not armed_here:
            return data
        actions = []
        for a in armed_here:
            if a.fires_left is not None and a.fires_left <= 0:
                continue
            if a.rng.random() >= a.rule.prob:
                continue
            if a.fires_left is not None:
                a.fires_left -= 1
            key = (name, a.rule.kind)
            _counts[key] = _counts.get(key, 0) + 1
            actions.append(a.rule)
    for rule in actions:
        _count_metric(rule)
        if rule.kind == "delay_ms":
            time.sleep(rule.arg / 1e3)
        elif rule.kind == "corrupt":
            data = _corrupt(data)
        elif rule.kind == "crash":
            _log.error("fault point %s: injected CRASH", name)
            raise CrashPoint(name)
        else:   # raise
            raise FaultInjected(name)
    return data


def _count_metric(rule: FaultRule) -> None:
    # Lazy import: utils must stay importable before obs wires up
    # (mirrors utils/locks.py's discipline).
    try:
        from lux_tpu.obs import metrics
        metrics.counter("lux_faults_injected_total",
                        {"point": rule.point, "kind": rule.kind}).inc()
    except Exception:
        # Injection must work even if the metrics registry is absent
        # (partial import during interpreter teardown).
        pass


def _corrupt(data):
    """Flip one byte/element of ``data`` (bytes or ndarray), returning a
    corrupted *copy*; anything else is returned unchanged."""
    if isinstance(data, (bytes, bytearray)) and len(data):
        buf = bytearray(data)
        # Past the frame head so record *payloads*, not just lengths,
        # get exercised; position is deterministic per payload length.
        pos = len(buf) // 2
        buf[pos] ^= 0xFF
        return bytes(buf)
    try:
        import numpy as np
        if isinstance(data, np.ndarray) and data.size:
            out = data.copy()
            flat = out.reshape(-1)
            flat[flat.shape[0] // 2] = ~flat[flat.shape[0] // 2] \
                if np.issubdtype(out.dtype, np.integer) else -flat[flat.shape[0] // 2]
            return out
    except Exception:
        pass
    return data


# Import-time arming (the obs/trace.py idiom): every entry point — the
# serve CLI, app CLIs, bare scripts — honors LUX_FAULTS from the
# environment; with it unset this is the no-op disarm.
reconfigure()
