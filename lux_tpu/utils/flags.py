"""Central registry for every ``LUX_*`` environment flag.

The knobs grew one module at a time (tiled_spmv, merge_tail_kernel, the
obs layer, bench.py) until ~20 ``os.environ`` reads were scattered with
no single place to discover a flag's name, default, or meaning. This
module is that place: every flag is :func:`define`'d here with a doc
line, call sites read through the typed accessors, and luxlint's
env-flag rules (LUX004/LUX005, lux_tpu/analysis/rules.py) enforce both
"every LUX_* key is declared" and "lux_tpu code reads through flags, not
os.environ".

Accessors re-read ``os.environ`` on every call — flags stay runtime
knobs (CLI flags and tests set env vars after first import; cf.
logging.reconfigure / trace.reconfigure).

:func:`overrides` layers a scoped, context-local overlay on top of the
environment: inside the ``with`` block every accessor (and therefore
:func:`snapshot` / :func:`config_hash`) sees the overlaid values without
mutating ``os.environ`` — the auto-tuner probes candidate configs this
way, and ledger records written under an overlay carry the candidate
config automatically.

``python -m lux_tpu.utils.flags`` prints the flag table.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import os
from typing import Dict, Mapping, Optional

__all__ = [
    "Flag", "define", "declared", "names", "default", "get", "get_int",
    "get_float", "get_bool", "tristate", "table", "snapshot",
    "config_hash", "overrides",
]


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str          # LUX_* env var name
    default: object    # value returned when the env var is unset
    doc: str           # one line: what the flag does / legal values
    kind: str = "str"  # str | path | int | float | bool | tristate


_REGISTRY: Dict[str, Flag] = {}


def define(name: str, default, doc: str, kind: str = "str") -> Flag:
    """Declare a flag. Redefining with a different spec raises — two
    modules silently disagreeing on a default is the failure mode a
    central registry exists to prevent."""
    if not name.startswith("LUX_"):
        raise ValueError(f"flag name must start with LUX_: {name!r}")
    f = Flag(name, default, doc, kind)
    old = _REGISTRY.get(name)
    if old is not None and old != f:
        raise ValueError(f"flag {name} already defined as {old}")
    _REGISTRY[name] = f
    return f


def declared(name: str) -> bool:
    return name in _REGISTRY


def names() -> tuple:
    return tuple(sorted(_REGISTRY))


def _flag(name: str) -> Flag:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"undeclared flag {name!r}: declare it in lux_tpu/utils/flags.py"
        ) from None


def default(name: str):
    """The declared default (modules alias it so constants can't drift
    from the registry)."""
    return _flag(name).default


# Context-local overlay stack. Each layer maps flag name -> str value
# (or None, which masks any env var and forces the declared default).
# contextvars (not a plain global) so a probe running in one serve
# thread can't leak its candidate config into concurrent queries.
_OVERRIDES: contextvars.ContextVar = contextvars.ContextVar(
    "lux_flag_overrides", default=())


def _overlaid(name: str):
    """(hit, value) against the innermost overlay layer naming ``name``."""
    for layer in reversed(_OVERRIDES.get()):
        if name in layer:
            return True, layer[name]
    return False, None


@contextlib.contextmanager
def overrides(mapping: Mapping[str, object]):
    """Scoped flag overlay: inside the block, every accessor resolves
    the given flags to the mapped values (stringified; ``None`` masks
    the env var, restoring the declared default). Layers nest — inner
    wins. Undeclared names raise up front, same contract as the
    accessors, so a typo'd knob can't silently probe the default."""
    frozen = {}
    for name, value in mapping.items():
        _flag(name)
        frozen[name] = None if value is None else str(value)
    token = _OVERRIDES.set(_OVERRIDES.get() + (frozen,))
    try:
        yield
    finally:
        _OVERRIDES.reset(token)


def get(name: str) -> Optional[str]:
    """Raw string value: the innermost :func:`overrides` layer if one
    names this flag, else the env var if set, else the declared default
    (coerced to str unless None)."""
    f = _flag(name)
    hit, ov = _overlaid(name)
    if hit:
        if ov is not None:
            return ov
    else:
        v = os.environ.get(name)
        if v is not None:
            return v
    return f.default if f.default is None else str(f.default)


def get_int(name: str) -> int:
    return int(get(name))


def get_float(name: str) -> float:
    return float(get(name))


def get_bool(name: str) -> bool:
    """Unset → declared default; '' / '0' / 'false' / 'no' / 'off'
    (case-insensitive) → False; anything else → True."""
    f = _flag(name)
    hit, ov = _overlaid(name)
    v = ov if hit else os.environ.get(name)
    if v is None:
        return bool(f.default)
    return v.strip().lower() not in ("", "0", "false", "no", "off")


def tristate(name: str, strict: bool = True) -> Optional[bool]:
    """Three-way override knob: unset/'' → None (auto), '0' → False
    (force off), '1' → True (force on). Other values raise when
    ``strict`` (the flag gates a planning decision that must not be
    silently misread), else behave as unset."""
    _flag(name)
    hit, ov = _overlaid(name)
    v = (ov or "") if hit else os.environ.get(name, "")
    if v == "":
        return None
    if v == "0":
        return False
    if v == "1":
        return True
    if strict:
        raise ValueError(
            f"{name}={v!r}: use '1' (force on), '0' (force off), or unset "
            "(auto)"
        )
    return None


def snapshot() -> Dict[str, Optional[str]]:
    """Effective value of every declared flag, in sorted-name order.

    Secrets-free by construction: only declared ``LUX_*`` flags are
    captured (never the whole environment), and declaring a flag is a
    code-reviewed act. This is the config side of a ledger record
    (obs/ledger.py) — a (config -> metrics) observation is only
    reproducible if the config is complete.
    """
    return {name: get(name) for name in names()}


def config_hash() -> str:
    """Stable 12-hex digest of the behavioral flag config.

    Path-kind flags are excluded: they name artifact sinks (metrics
    files, cache dirs, the ledger dir itself) that differ per run/tmpdir
    without changing behavior, and including them would make identical
    configs hash differently — breaking ledger A/B pairing and
    bench-gate baseline comparability, the two consumers of this hash.
    """
    import hashlib

    items = [
        (name, get(name))
        for name in names()
        if _REGISTRY[name].kind != "path"
    ]
    blob = "\x00".join(f"{k}={'' if v is None else v}" for k, v in items)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


def table() -> str:
    """Human-readable flag table (name, kind, default, doc)."""
    rows = [("flag", "kind", "default", "doc")]
    for name in names():
        f = _REGISTRY[name]
        rows.append((f.name, f.kind, repr(f.default), f.doc))
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    w2 = max(len(r[2]) for r in rows)
    return "\n".join(
        f"{r[0]:<{w0}}  {r[1]:<{w1}}  {r[2]:<{w2}}  {r[3]}" for r in rows
    )


# -- the flags -------------------------------------------------------------
# Observability (lux_tpu/obs, utils/logging.py)
define("LUX_LOG", "INFO",
       "log level for the lux.* logger categories (DEBUG..CRITICAL)")
define("LUX_METRICS", None,
       "append one JSON run-report line (summary + metrics snapshot) per "
       "run to this path", kind="path")
define("LUX_TRACE", None,
       "stream Chrome trace_event JSON-lines to this path", kind="path")
define("LUX_SPANS", True,
       "request-scoped serve spans (obs/spans.py): trace-id propagation, "
       "per-phase histograms, async Chrome events (0 disables)",
       kind="bool")
define("LUX_FLIGHT_DIR", None,
       "arm the flight recorder (obs/flight.py): postmortem flight.v1 "
       "JSON dumps land in this directory", kind="path")
define("LUX_FLIGHT_CAPACITY", 256,
       "flight-recorder ring size: last N completed traces and last N "
       "engine iteration records kept for postmortems", kind="int")
define("LUX_STATUSZ_WINDOWS", "60,300",
       "/statusz rolling SLO window lengths in seconds, comma-separated")
define("LUX_ENGOBS", False,
       "engine performance observatory (obs/engobs.py): run sharded "
       "executors through phase-fenced steps splitting exchange vs "
       "compute time per iteration; off keeps the exact fused programs",
       kind="bool")
define("LUX_PROF_DIR", None,
       "arm the device-timeline profiler (obs/prof.py): capture windows "
       "(bench --profile, POST /profilez, SIGUSR2 toggle) write "
       "TensorBoard artifacts + profile.v1 reports under this directory",
       kind="path")
define("LUX_LEDGER_DIR", None,
       "arm the run ledger (obs/ledger.py): every engine run, bench "
       "entry, serve warmup, and /profilez capture appends one "
       "crc-framed runrec.v1 JSON line under this directory",
       kind="path")
define("LUX_LEDGER_ROTATE_BYTES", 8 << 20,
       "run-ledger segment rotation threshold in bytes: a segment at or "
       "past this size is sealed and a new runrec-NNNNNN.jsonl opens",
       kind="int")
define("LUX_HBM_PEAK_GBPS", None,
       "override the roofline HBM peak (GB/s) when the device-profile "
       "registry (obs/report.py) has no row for this device_kind")
define("LUX_ICI_PEAK_GBPS", None,
       "override the roofline per-chip ICI peak (GB/s) when the "
       "device-profile registry has no row for this device_kind")

# Backend / native toolchain (utils/platform.py, native/build.py)
define("LUX_PLATFORM", None,
       "force the JAX platform (e.g. cpu) before any backend initializes")
define("LUX_NATIVE_CACHE", None,
       "native-library build cache dir (default ~/.cache/lux_tpu_native)",
       kind="path")

# Engine / kernel knobs (engine/pull.py, ops/tiled_spmv.py,
# ops/merge_tail_kernel.py)
define("LUX_EDGE_CHUNK_BYTES", 2 << 30,
       "flat-contribution byte threshold above which the pull engine "
       "runs edge-chunked", kind="int")
define("LUX_DST_SLICE", None,
       "chunked-engine dst-band gather: 1 force, 0 off, unset auto by "
       "traffic", kind="tristate")
define("LUX_SRC_SLICE", None,
       "chunked-engine src-band gather: 1 force, 0 off, unset auto by "
       "span", kind="tristate")
define("LUX_PLAN_BANDED", None,
       "tiled planner level-0 banded passes: 1 force, 0 direct, unset "
       "auto by edge count", kind="tristate")
define("LUX_PACK_STRIPS", False,
       "opt-in nibble packing of even-r strip levels (needs plan count "
       "cap <= 15)", kind="bool")
define("LUX_GROUPED_TAIL", False,
       "opt-in grouped (merge-network) tail phase in the tiled executors",
       kind="bool")

# GAS adaptive executor (engine/gas.py)
define("LUX_GAS", "adaptive",
       "GAS executor direction policy: 'adaptive' picks push vs pull per "
       "iteration from frontier density; 'pull'/'push' pin one direction "
       "(results are bitwise-identical across all three)")
define("LUX_GAS_DENSITY_HI", 0.0625,
       "adaptive GAS hysteresis: frontier density at or above this forces "
       "the pull (dense) direction (the reference's nv/16 crossover, "
       "sssp_gpu.cu:414)", kind="float")
define("LUX_GAS_DENSITY_LO", 0.005,
       "adaptive GAS hysteresis: frontier density at or below this forces "
       "the push (sparse-queue) direction; between the two thresholds the "
       "previous direction sticks", kind="float")

# bench.py suite knobs
define("LUX_BENCH_SCALE", 22, "bench.py R-MAT scale", kind="int")
define("LUX_BENCH_EF", 16, "bench.py R-MAT edge factor", kind="int")
define("LUX_BENCH_ITERS", 50, "bench.py PageRank iterations", kind="int")
define("LUX_BENCH_CACHE", None,
       "bench.py graph cache dir (default <repo>/.bench_cache)",
       kind="path")
define("LUX_BENCH_LAYOUT", "tiled", "bench.py engine layout: tiled|flat")
define("LUX_BENCH_TILE_MB", 8192, "bench.py tiled-plan budget in MB",
       kind="int")
define("LUX_BENCH_LEVELS", "8/2",
       "bench.py tiled plan levels as r/cap[,r/cap...]")
define("LUX_BENCH_SUITE", True,
       "bench.py: run the full suite (0 = headline only)", kind="bool")
define("LUX_BENCH_DEADLINE", 480.0,
       "bench.py total seconds of bench budget", kind="float")
define("LUX_BENCH_GATE_SCALE", 10,
       "tools/bench_gate.py --fast R-MAT scale (tiny graph so the gate "
       "fits in make verify)", kind="int")
define("LUX_BENCH_GATE_TOL", 0.4,
       "bench_gate relative regression tolerance per metric (generous: "
       "sub-ms CPU fast-mode iterations jitter ~25% run to run; tighten "
       "per claim with --tol)",
       kind="float")

# Static analysis, IR tier (analysis/ir.py, analysis/planck.py,
# serve/pool.py)
define("LUX_IR_BLOWUP", 16.0,
       "luxlint-IR LUX103: flag any traced intermediate larger than this "
       "multiple of the step's total input bytes", kind="float")
define("LUX_IR_POOL_AUDIT", True,
       "run the LUX104 donation audit on every engine the serve pool "
       "builds (one abstract lowering per build; 0 disables)", kind="bool")
define("LUX_PLANCK_INFLATION", 8.0,
       "luxlint-IR LUX205: max per-level grouped-tail stream inflation "
       "(rows per level / ceil(reals/128)) a saved plan may carry",
       kind="float")
define("LUX_EXCH_POOL_AUDIT", True,
       "run the LUX401-403 exchange-plan audit on every plan-carrying "
       "engine the serve pool builds (pure numpy over the live "
       "ExchangePlan tables; 0 disables)", kind="bool")
define("LUX_GASCAP_DIR", None,
       "directory holding the gascap.v1 program-capability artifact "
       "(analysis/gasck.py) the registry/serving layers consult; unset = "
       "the committed lux_tpu/analysis/gascap.json", kind="path")
define("LUX_GAS_POOL_AUDIT", True,
       "run the LUX601/602/605 program-algebra audit on every "
       "GAS-program-carrying engine the serve pool builds (cached "
       "per program class; 0 disables)", kind="bool")
define("LUX_GASCK_SEED", 7,
       "luxlint --programs: RNG seed for the probe graphs and the "
       "LUX602 associativity/commutativity probe triples", kind="int")
define("LUX_GASCK_TRIPLES", 64,
       "luxlint --programs: number of seeded probe triples per program "
       "for the LUX602 combiner-algebra proof", kind="int")
define("LUX_GASCK_NV", 24,
       "luxlint --programs: vertex count of the seeded probe graphs the "
       "LUX603 push/pull duality traces run on", kind="int")

# Static analysis, memory tier (analysis/memck.py) and the HBM-budgeted
# pool residency it feeds (serve/pool.py, tune/space.py, obs/report.py)
define("LUX_MEMCAP_DIR", None,
       "directory holding the memcap.v1 HBM-footprint artifact "
       "(analysis/memck.py) the serving admission formula consults; "
       "unset = the committed lux_tpu/analysis/memcap.json", kind="path")
define("LUX_MEM_MODEL_TOL", 0.25,
       "luxlint --memory LUX704/706: max relative slack between the "
       "closed-form footprint model and a traced peak (the model must "
       "upper-bound the trace and stay within this fraction of it)",
       kind="float")
define("LUX_MEM_SWEEP_FACTOR", 2,
       "luxlint --memory LUX704: probe-graph scale multiplier for the "
       "model-honesty sweep (the model derived at the base scale must "
       "bound a re-trace at factor x the base)", kind="int")
define("LUX_MEM_POOL_ADMIT", True,
       "gate new serve-pool engine builds on the memcap.v1 predicted "
       "footprint fitting the HBM budget (0 = admit freely; admission "
       "is also skipped when no budget can be derived)", kind="bool")
define("LUX_HBM_BUDGET_BYTES", 0,
       "per-device HBM byte budget the serve pool admits engine builds "
       "under; 0 = device-profile hbm_capacity_bytes x "
       "LUX_HBM_BUDGET_FRAC (no budget at all when capacity is unknown, "
       "e.g. cpu)", kind="int")
define("LUX_HBM_BUDGET_FRAC", 0.85,
       "fraction of the device-profile HBM capacity the serve pool may "
       "fill with resident engines when LUX_HBM_BUDGET_BYTES is 0 (the "
       "remainder is headroom for XLA scratch and staging)", kind="float")
define("LUX_HBM_CAPACITY_BYTES", None,
       "override the device-profile HBM capacity in bytes when the "
       "registry (obs/report.py) has no row for this device_kind — also "
       "the only way cpu runs get a LUX703 capacity to check against")
define("LUX_RESULT_CACHE_BYTES", 64 << 20,
       "serve ResultCache byte budget: LRU entries evict once their "
       "summed value nbytes exceed this (the entry-count capacity still "
       "bounds the dict)", kind="int")

# Concurrency discipline (utils/locks.py, tools/race_stress.py)
define("LUX_LOCKWATCH", False,
       "wrap every utils/locks.make_lock in the LockWatch sentinel: "
       "per-thread acquisition stacks, online lock-order inversion "
       "detection, lux_lock_{wait,hold}_seconds histograms (set before "
       "import; locks are wrapped at construction)", kind="bool")
define("LUX_LOCK_HOLD_WARN_MS", 250.0,
       "LockWatch: warn + count lux_lock_hold_warnings_total when a "
       "watched lock is held longer than this many ms (0 disables)",
       kind="float")

# Dynamic graphs (graph/snapshot.py, engine/incremental.py,
# serve/session.py)
define("LUX_DELTA_COMPACT_RATIO", 0.05,
       "background-compact a snapshot's delta once pending edits exceed "
       "this fraction of the base edge count", kind="float")
define("LUX_SNAPSHOT_WARM_TIMEOUT", 120.0,
       "seconds to wait for the next snapshot's engines to warm before "
       "aborting the hot-swap (the old version keeps serving)",
       kind="float")
define("LUX_INCREMENTAL", True,
       "warm-start components/cached-SSSP fixpoints from the previous "
       "snapshot's values during a hot-swap instead of recomputing on "
       "demand (0 = evict only)", kind="bool")

# Robustness: fault injection (utils/faults.py), edit WAL (graph/wal.py),
# graceful degradation (serve/session.py, serve/breaker.py)
define("LUX_FAULTS", None,
       "fault-injection spec `point:kind:prob[:arg]`, comma-separated "
       "(kinds: raise|delay_ms|corrupt|crash; see utils/faults.py); "
       "unset/empty = disarmed, the points cost one bool check")
define("LUX_FAULTS_SEED", 0,
       "seed for the per-rule fault-injection RNGs (utils/faults.py)",
       kind="int")
define("LUX_WAL_DIR", None,
       "directory for the edit write-ahead log; when set, Session edits "
       "are CRC-framed + fsync'd to <dir>/lux.wal before any version is "
       "minted, and SnapshotStore.recover replays it on startup (unset = "
       "no durability, the pre-WAL behavior)", kind="path")
define("LUX_EDIT_QUEUE_MAX", 8,
       "Session.enqueue_edits auto-flushes the WAL-backed edit queue "
       "into one hot-swap once this many batches are pending (ROADMAP "
       "item 3: swaps amortize over many small edits)", kind="int")
define("LUX_RETRY_MAX", 2,
       "max engine re-executions after a transient (non-ServeError) "
       "failure per batch, clamped by the request deadline (0 = fail "
       "fast)", kind="int")
define("LUX_RETRY_BACKOFF_MS", 25.0,
       "initial retry backoff in ms, doubling per attempt", kind="float")
define("LUX_BREAKER_THRESHOLD", 5,
       "consecutive engine failures on one (app, fingerprint) before the "
       "circuit breaker opens and sheds that program with 503 + "
       "Retry-After", kind="int")
define("LUX_BREAKER_COOLDOWN_MS", 2000.0,
       "ms an open breaker waits before going half-open and probing the "
       "rebuilt engine in the background", kind="float")

# Sharded-engine exchange path (parallel/shard.py, engine/pull_sharded.py,
# engine/push.py, engine/tiled_sharded.py)
define("LUX_EXCHANGE", "full",
       "sharded-executor value exchange: 'full' all-gathers whole shard "
       "tables every iteration; 'compact' sends only the rows some "
       "receiving part actually reads (fixed-capacity all_to_all of "
       "packed rows + receiver scatter, bitwise-equal results, "
       "local-first overlap); 'frontier' (sharded GAS) sends only the "
       "compact rows whose source vertex is active this iteration, "
       "packed to a static frontier capacity, self-downgrading to the "
       "static compact send on dense iterations — frontier-less "
       "executors run 'compact'. Captured at executor build; P=1 and "
       "unprofitable plans fall back to full")
define("LUX_EXCHANGE_FRONTIER_FRAC", 0.25,
       "frontier-exchange row budget as a fraction of the static "
       "compact capacity (ExchangePlan.frontier_capacity): smaller = "
       "bigger byte win on sparse iterations but earlier self-downgrade "
       "to the static compact send", kind="float")

# Multi-chip serving (serve/mesh.py, serve/session.py)
define("LUX_SERVE_MESH", 1,
       "serving device mesh spec: a device count ('8') or PxQ shape "
       "('2x4', folded onto the 1-D parts axis); 1 = single-chip "
       "serving. On CPU the mesh is virtual (XLA host devices), exactly "
       "as the RMAT27 tooling runs", kind="str")
define("LUX_SHARD_PLAN_CACHE", 8,
       "max (fingerprint, parts) partition plans the serving shard-plan "
       "cache keeps; hot-swaps evict the outgoing fingerprint's plans "
       "regardless", kind="int")

# Profile-guided auto-tuner (lux_tpu/tune/)
define("LUX_TUNE_DIR", None,
       "arm the auto-tuner cache (lux_tpu/tune/): tuneconf.v1 artifacts "
       "are persisted under this directory and serving warmup consults "
       "them; unset = tuner disarmed, every lookup is a counted fallback "
       "to defaults", kind="path")
define("LUX_TUNE_PROBE_ITERS", 6,
       "fixed iteration count of a rung-0 tuner probe; later "
       "successive-halving rungs double it", kind="int")
define("LUX_TUNE_RUNGS", 2,
       "successive-halving rung count for the tuner search (1 = a single "
       "flat sweep, no halving)", kind="int")
define("LUX_TUNE_ETA", 2,
       "successive-halving keep fraction: the top ceil(n/eta) candidates "
       "by score survive each rung", kind="int")
define("LUX_TUNE_SEED", 0,
       "seed for the tuner's candidate subsample + deterministic "
       "tie-breaks (same seed + graph -> identical winner and score "
       "table)", kind="int")
define("LUX_TUNE_MAX_CANDIDATES", 16,
       "cap on rung-0 candidates; larger declared knob spaces are "
       "seeded-subsampled down to this before probing", kind="int")
define("LUX_TUNE_MAX_AGE_S", 604800.0,
       "luxlint --tune staleness bound: a tuneconf.v1 artifact older "
       "than this many seconds is flagged LUX504 (0 disables the bound)",
       kind="float")
define("LUX_TUNE_PENALTY", 0.05,
       "tuner score penalty weight per direction switch / exchange "
       "downgrade, as a fraction of phase time per event per iteration "
       "(instability is a cost even when the phase medians look good)",
       kind="float")
define("LUX_TUNE_CACHE", 8,
       "max tuneconf.v1 entries the in-memory TuneCache keeps "
       "(LRU; hot-swaps evict the outgoing fingerprint's entries "
       "regardless, like LUX_SHARD_PLAN_CACHE)", kind="int")

# Smoke-tool knobs (tools/obs_smoke.py, serve_smoke.py, merge_smoke.py)
define("LUX_SMOKE_SCALE", 10, "smoke tools R-MAT scale", kind="int")
define("LUX_SMOKE_ITERS", 8, "obs_smoke PageRank iterations", kind="int")
define("LUX_SMOKE_QUERIES", 8, "serve_smoke SSSP query count", kind="int")
define("LUX_SMOKE_EDGES", 1 << 20,
       "merge_smoke heavy-tail synthetic edge count", kind="int")


if __name__ == "__main__":
    print(table())
