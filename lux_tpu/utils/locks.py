"""Named locks + the LockWatch runtime sentinel (luxlint-threads tier).

Every lock in the serve/graph/obs layers is built through
:func:`make_lock` so it carries a stable name. Normally that is all the
factory does — it returns a bare ``threading.Lock`` with zero overhead.
Under ``LUX_LOCKWATCH=1`` each lock is wrapped so the process observes
its own locking discipline while it runs:

- **order graph** — whenever a thread acquires lock B while holding lock
  A, the edge A→B is recorded (with a one-time acquisition stack). If
  the reverse path B→…→A was ever observed, that is a lock-order
  inversion: two threads interleaving those paths can deadlock. The
  inversion is recorded with both stacks and counted in
  ``lux_lock_inversions_total`` — ``tools/race_stress.py`` asserts the
  count stays zero under concurrent serve traffic.
- **contention histograms** — ``lux_lock_wait_seconds{lock}`` (time
  blocked in acquire) and ``lux_lock_hold_seconds{lock}`` (time held)
  are mirrored into the metrics registry, so /statusz, Prometheus
  scrapes, and flight.v1 postmortems show which lock is hot.
- **hold warnings** — a hold longer than ``LUX_LOCK_HOLD_WARN_MS`` logs
  one warning and bumps ``lux_lock_hold_warnings_total{lock}`` (the
  EnginePool build-under-lock is the expected emitter: first-build
  compiles legitimately hold the pool lock for seconds).

The static half of this tier lives in ``lux_tpu/analysis/threads.py``
(LUX301–LUX305); this module is the runtime witness for what the AST
cannot see — actual interleavings.

Import discipline: this module is imported by ``lux_tpu.obs`` modules at
module scope, so it must not import ``lux_tpu.obs`` at *its* module
scope — metrics wiring is imported lazily, only when a watched lock is
actually constructed (obs.metrics is stdlib-only and already initialized
by then).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

from lux_tpu.utils import flags
from lux_tpu.utils.logging import get_logger

__all__ = ["make_lock", "WatchedLock", "LockWatch", "WATCH",
           "LOCK_BUCKETS"]

# Lock waits/holds run ~100ns (uncontended obs counters) to seconds
# (engine builds under the pool lock); the default seconds-oriented
# histogram bounds would collapse everything interesting into one
# bucket.
LOCK_BUCKETS = (1e-6, 1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1,
                0.5, 1.0, 5.0, 30.0, float("inf"))

_STACK_LIMIT = 8   # frames kept per recorded acquisition site


def _site_stack() -> List[str]:
    """A trimmed acquisition stack (drops this module's own frames)."""
    frames = traceback.format_stack(limit=_STACK_LIMIT + 2)
    return [f.rstrip() for f in frames
            if "/utils/locks.py" not in f.split(",")[0]][-_STACK_LIMIT:]


class LockWatch:
    """Process-wide observer: per-thread held-lock stacks + the observed
    lock-order graph with online cycle (inversion) detection.

    The watcher's own lock is deliberately a bare ``threading.Lock`` —
    it is the substrate the watched locks report into, and watching it
    would recurse.
    """

    def __init__(self):
        self._glock = threading.Lock()
        self._tls = threading.local()
        # (held_name, acquired_name) -> first-observation record
        self._edges: Dict[Tuple[str, str], dict] = {}
        # held_name -> set of names acquired under it
        self._order: Dict[str, Set[str]] = {}
        self._inversions: List[dict] = []
        self._inverted: Set[Tuple[str, str]] = set()

    # -- per-thread stack --------------------------------------------------

    def _stack(self) -> List[Tuple[str, float]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> List[str]:
        """Names of locks the calling thread currently holds, outermost
        first."""
        return [name for name, _ in self._stack()]

    # -- recording ---------------------------------------------------------

    def note_acquired(self, name: str) -> None:
        stack = self._stack()
        held = [h for h, _ in stack if h != name]
        stack.append((name, time.perf_counter()))
        if not held:
            return
        with self._glock:
            for h in held:
                key = (h, name)
                if key in self._edges:
                    self._edges[key]["count"] += 1
                    continue
                site = _site_stack()
                self._edges[key] = {
                    "held": h, "acquired": name, "count": 1,
                    "thread": threading.current_thread().name,
                    "stack": site,
                }
                self._order.setdefault(h, set()).add(name)
                self._check_inversion(h, name, site)

    def note_released(self, name: str) -> Optional[float]:
        """Pop the newest matching stack entry; returns the hold time in
        seconds, or None if this thread never recorded the acquire."""
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                _, t_acq = stack.pop(i)
                return time.perf_counter() - t_acq
        return None

    def _check_inversion(self, held: str, acquired: str,
                         site: List[str]) -> None:
        """Called with _glock held, right after adding edge held→acquired:
        a pre-existing path acquired→…→held closes a cycle."""
        path = self._path(acquired, held)
        if path is None:
            return
        pair = tuple(sorted((held, acquired)))
        if pair in self._inverted:
            return
        self._inverted.add(pair)
        other = self._edges.get((path[0], path[1]))
        record = {
            "cycle": [held, acquired] + path[1:],
            "held": held,
            "acquired": acquired,
            "thread": threading.current_thread().name,
            "stack": site,
            "prior_stack": other["stack"] if other else [],
            "prior_thread": other["thread"] if other else None,
        }
        self._inversions.append(record)
        self._metric("counter", "lux_lock_inversions_total").inc()
        get_logger("locks").error(
            "lock-order inversion: %s acquired while holding %s, but the "
            "order %s was observed earlier (cycle %s)",
            acquired, held, " -> ".join(path), " -> ".join(record["cycle"]),
        )

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src→…→dst in the observed order graph, or None."""
        seen = {src}
        frontier = [[src]]
        while frontier:
            path = frontier.pop()
            for nxt in self._order.get(path[-1], ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(path + [nxt])
        return None

    @staticmethod
    def _metric(kind: str, name: str, labels: Optional[dict] = None, **kw):
        from lux_tpu.obs import metrics   # lazy: see module docstring
        return getattr(metrics, kind)(name, labels, **kw)

    # -- introspection -----------------------------------------------------

    def inversions(self) -> List[dict]:
        with self._glock:
            return list(self._inversions)

    def assert_no_inversions(self) -> None:
        inv = self.inversions()
        if inv:
            lines = [
                f"  cycle {' -> '.join(r['cycle'])} "
                f"(thread {r['thread']})" for r in inv
            ]
            raise AssertionError(
                f"LockWatch observed {len(inv)} lock-order inversion(s):\n"
                + "\n".join(lines)
            )

    def stats(self) -> dict:
        with self._glock:
            return {
                "edges": len(self._edges),
                "inversions": len(self._inversions),
                "order": {h: sorted(v) for h, v in self._order.items()},
            }

    def reset(self) -> None:
        """Drop all observed state (tests; the per-thread stacks of live
        threads are left alone — they reflect locks actually held)."""
        with self._glock:
            self._edges.clear()
            self._order.clear()
            self._inversions.clear()
            self._inverted.clear()


WATCH = LockWatch()


class WatchedLock:
    """``threading.Lock`` wrapper reporting to a :class:`LockWatch`.

    Histogram objects are cached at construction so the release path
    never touches the metrics registry's own (bare) lock — observing a
    watched lock must not acquire another lock.
    """

    __slots__ = ("name", "_inner", "_watch", "_wait_h", "_hold_h",
                 "_warns")

    def __init__(self, name: str, watch: Optional[LockWatch] = None):
        self.name = name
        self._inner = threading.Lock()
        self._watch = watch if watch is not None else WATCH
        labels = {"lock": name}
        self._wait_h = LockWatch._metric(
            "histogram", "lux_lock_wait_seconds", labels,
            buckets=LOCK_BUCKETS)
        self._hold_h = LockWatch._metric(
            "histogram", "lux_lock_hold_seconds", labels,
            buckets=LOCK_BUCKETS)
        self._warns = LockWatch._metric(
            "counter", "lux_lock_hold_warnings_total", labels)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._wait_h.observe(time.perf_counter() - t0)
            self._watch.note_acquired(self.name)
        return ok

    def release(self) -> None:
        hold = self._watch.note_released(self.name)
        self._inner.release()
        if hold is None:
            return
        self._hold_h.observe(hold)
        warn_s = flags.get_float("LUX_LOCK_HOLD_WARN_MS") / 1e3
        if warn_s > 0 and hold > warn_s:
            self._warns.inc()
            get_logger("locks").warning(
                "lock %s held %.3fs (> LUX_LOCK_HOLD_WARN_MS=%.0fms)",
                self.name, hold, warn_s * 1e3,
            )

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"WatchedLock({self.name!r}, locked={self.locked()})"


def make_lock(name: str):
    """A named lock: bare ``threading.Lock`` normally, a
    :class:`WatchedLock` reporting into :data:`WATCH` under
    ``LUX_LOCKWATCH=1``.

    The flag is read at construction — locks created at import time need
    the env var set before import (tools/race_stress.py sets it first
    thing), which is also why the wrapper costs nothing when off.
    """
    if flags.get_bool("LUX_LOCKWATCH"):
        return WatchedLock(name)
    return threading.Lock()


def hold_quantile(name: str, q: float) -> Optional[float]:
    """The ``lux_lock_hold_seconds{lock=name}`` quantile, or None if the
    lock has no observations (e.g. LockWatch off)."""
    h = LockWatch._metric("histogram", "lux_lock_hold_seconds",
                          {"lock": name}, buckets=LOCK_BUCKETS)
    return h.quantile(q) if h.count else None
