"""Category loggers.

The reference uses Legion logger categories — ``log_lux("graph")``,
``log_pr("pagerank")`` etc. (core/pull_model.inl:20, pagerank/pagerank.cc:26)
— with a compile-time OUTPUT_LEVEL knob (Makefile:23). Here: stdlib logging
with a ``LUX_LOG`` env var as the runtime knob, re-readable at runtime via
``reconfigure()`` (CLI flags set env vars after first import). The
``lux.perf`` category carries the end-of-run telemetry table
(lux_tpu/obs/report.py).
"""

from __future__ import annotations

import logging
import sys

from . import flags

PERF_CATEGORY = "perf"

_CONFIGURED = False
_HANDLER = None


def _apply_level(root: logging.Logger):
    level = (flags.get("LUX_LOG") or "INFO").upper()
    root.setLevel(getattr(logging, level, logging.INFO))


def _configure():
    global _CONFIGURED, _HANDLER
    if _CONFIGURED:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("{%(name)s} %(levelname)s: %(message)s")
    )
    root = logging.getLogger("lux")
    _apply_level(root)
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True
    _HANDLER = handler


def reconfigure():
    """Re-read ``LUX_LOG`` after the environment changed. Keeps the
    single stderr handler; only the level moves."""
    _configure()
    _apply_level(logging.getLogger("lux"))


def get_logger(category: str) -> logging.Logger:
    """e.g. get_logger('graph'), get_logger('pagerank')."""
    _configure()
    return logging.getLogger(f"lux.{category}")


def perf_logger() -> logging.Logger:
    """The ``lux.perf`` category used by the run-report writer."""
    return get_logger(PERF_CATEGORY)
