"""Category loggers.

The reference uses Legion logger categories — ``log_lux("graph")``,
``log_pr("pagerank")`` etc. (core/pull_model.inl:20, pagerank/pagerank.cc:26)
— with a compile-time OUTPUT_LEVEL knob (Makefile:23). Here: stdlib logging
with a ``LUX_LOG`` env var as the runtime knob.
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure():
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("LUX_LOG", "INFO").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("{%(name)s} %(levelname)s: %(message)s")
    )
    root = logging.getLogger("lux")
    root.setLevel(getattr(logging, level, logging.INFO))
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(category: str) -> logging.Logger:
    """e.g. get_logger('graph'), get_logger('pagerank')."""
    _configure()
    return logging.getLogger(f"lux.{category}")
