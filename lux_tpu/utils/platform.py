"""Backend selection hygiene.

In some environments a TPU plugin platform is forced via JAX_PLATFORMS but
its registration can fail (plugin import error, device held elsewhere).
``ensure_backend()`` makes CLIs degrade to CPU instead of crashing.
"""

from __future__ import annotations


def ensure_backend() -> str:
    """Return the platform actually in use, falling back to CPU if the
    configured platform cannot initialize. ``LUX_PLATFORM=cpu`` forces a
    platform regardless of what the environment's sitecustomize set up
    (JAX_PLATFORMS can be overridden before we run)."""
    import os

    import jax

    forced = os.environ.get("LUX_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
        got = jax.devices()[0].platform
        if got != forced:
            # A backend was already initialized before we ran; the config
            # update cannot take effect retroactively.
            raise RuntimeError(
                f"LUX_PLATFORM={forced} requested but backend '{got}' was "
                "already initialized; set the platform before any jax use"
            )
        return got
    try:
        return jax.devices()[0].platform
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
