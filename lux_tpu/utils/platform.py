"""Backend selection hygiene.

In some environments a TPU plugin platform is forced via JAX_PLATFORMS but
its registration can fail (plugin import error, device held elsewhere).
``ensure_backend()`` makes CLIs degrade to CPU instead of crashing.
"""

from __future__ import annotations

import re


def virtual_cpu_flags(n_devices: int, xla_flags: str = None) -> str:
    """Return ``xla_flags`` with ``--xla_force_host_platform_device_count``
    guaranteed to be >= ``n_devices`` (existing larger values are kept;
    smaller ones are replaced). Pass the result as the subprocess/env
    XLA_FLAGS, then force ``jax_platforms=cpu`` via jax.config BEFORE any
    backend initializes (env JAX_PLATFORMS alone is overridden by
    sitecustomize-registered plugins)."""
    import os

    if xla_flags is None:
        xla_flags = os.environ.get("XLA_FLAGS", "")
    pat = r"--xla_force_host_platform_device_count=(\d+)"
    m = re.search(pat, xla_flags)
    if m:
        if int(m.group(1)) >= n_devices:
            return xla_flags
        return re.sub(
            pat, f"--xla_force_host_platform_device_count={n_devices}",
            xla_flags,
        )
    return (
        xla_flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()


def ensure_backend() -> str:
    """Return the platform actually in use, falling back to CPU if the
    configured platform cannot initialize. ``LUX_PLATFORM=cpu`` forces a
    platform regardless of what the environment's sitecustomize set up
    (JAX_PLATFORMS can be overridden before we run)."""
    import jax

    from lux_tpu.utils import flags

    forced = flags.get("LUX_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
        got = jax.devices()[0].platform
        if got != forced:
            # A backend was already initialized before we ran; the config
            # update cannot take effect retroactively.
            raise RuntimeError(
                f"LUX_PLATFORM={forced} requested but backend '{got}' was "
                "already initialized; set the platform before any jax use"
            )
        return got
    try:
        return jax.devices()[0].platform
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
