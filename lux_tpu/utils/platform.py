"""Backend selection hygiene.

In some environments a TPU plugin platform is forced via JAX_PLATFORMS but
its registration can fail (plugin import error, device held elsewhere).
``ensure_backend()`` makes CLIs degrade to CPU instead of crashing.
"""

from __future__ import annotations


def ensure_backend() -> str:
    """Return the platform actually in use, falling back to CPU if the
    configured platform cannot initialize."""
    import jax

    try:
        return jax.devices()[0].platform
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
