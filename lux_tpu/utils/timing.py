"""Wall-clock timing.

The reference brackets its iteration loop with
``Realm::Clock::current_time_in_microseconds`` and prints
``ELAPSED TIME = %7.7f s`` (pagerank/pagerank.cc:108-118); `Timer`
reproduces that measurement discipline: device work must be drained
before reading the clock. Pass ``sync=`` (a value, pytree, or zero-arg
callable producing one) and the timer runs ``jax.block_until_ready`` on
it before taking the exit timestamp, so async dispatch can't make the
bracket lie.
"""

from __future__ import annotations

import time


class Timer:
    def __init__(self, sync=None):
        self._sync = sync

    def __enter__(self):
        self.start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self._sync is not None:
            target = self._sync() if callable(self._sync) else self._sync
            if target is not None:
                import jax

                jax.block_until_ready(target)
        self.elapsed = time.perf_counter() - self.start
        return False

    def print_elapsed(self):
        # Same format string family as the reference (pagerank.cc:117).
        print(f"ELAPSED TIME = {self.elapsed:7.7f} s")
