"""Wall-clock timing.

The reference brackets its iteration loop with
``Realm::Clock::current_time_in_microseconds`` and prints
``ELAPSED TIME = %7.7f s`` (pagerank/pagerank.cc:108-118); `Timer`
reproduces that measurement discipline (device work must be drained before
reading the clock — the executors' ``run`` methods block before
returning, so bracketing them is accurate).
"""

from __future__ import annotations

import time


class Timer:
    def __enter__(self):
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.start
        return False

    def print_elapsed(self):
        # Same format string family as the reference (pagerank.cc:117).
        print(f"ELAPSED TIME = {self.elapsed:7.7f} s")
