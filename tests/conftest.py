"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU-world answer to "multi-node testing without a cluster"
(SURVEY.md §4): every sharded code path runs on 8 simulated devices.
"""

import os
import sys

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
