"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU-world answer to "multi-node testing without a cluster"
(SURVEY.md §4): every sharded code path runs on 8 simulated devices.

Note: this environment's sitecustomize registers an `axon` TPU backend at
interpreter start (so JAX_PLATFORMS from the environment is overridden);
we force the CPU platform through jax.config instead, which works as long
as no backend has been initialized yet.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
