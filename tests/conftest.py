"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU-world answer to "multi-node testing without a cluster"
(SURVEY.md §4): every sharded code path runs on 8 simulated devices.

Note: this environment's sitecustomize registers an `axon` TPU backend at
interpreter start (so JAX_PLATFORMS from the environment is overridden);
we force the CPU platform through jax.config instead, which works as long
as no backend has been initialized yet.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lux_tpu.utils.platform import virtual_cpu_flags  # noqa: E402

os.environ["XLA_FLAGS"] = virtual_cpu_flags(8)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
