"""Seeded LUX401 violation: a real-looking send entry leaks into the
sentinel pad zone of an otherwise correct plan — pad traffic and real
traffic sharing a slot is exactly what the prefix-density proof exists
to forbid.

Loaded by ``tools/luxlint.py --exchange <this file>``; must exit 1 with
exactly LUX401.
"""

import types

import numpy as np


def _base_plan():
    # P=2 parts, max_units=4, unit_rows=1, capacity=2.
    # Receiver-major counts: receiver 0 needs rows {1, 3} of sender 1,
    # receiver 1 needs row {2} of sender 0.
    counts = np.array([[0, 2], [1, 0]], dtype=np.int64)
    send = np.array([[4, 4, 2, 4],
                     [1, 3, 4, 4]], dtype=np.int32)
    recv = np.array([[8, 8, 5, 7],
                     [2, 8, 8, 8]], dtype=np.int32)
    return types.SimpleNamespace(
        num_parts=2, max_units=4, unit_rows=1, capacity=2,
        counts=counts, send_units=send, recv_pos=recv, profitable=True)


_plan = _base_plan()
# expect: LUX401 (real entry in the sentinel pad zone of pair 0 -> 1)
_plan.send_units[0, 3] = 1

PLANS = [
    {
        "name": "lux401-pad-zone-leak",
        "plan": _plan,
        "remote_read_counts": np.array([[0, 2], [1, 0]], dtype=np.int64),
        "row_bytes": 8,
        "declared_bytes_per_iter": 32,
    },
]
