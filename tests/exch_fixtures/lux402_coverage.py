"""Seeded LUX402 violation: one real ``recv_pos`` entry scatters a row
to the wrong flat index (6 instead of sender*max_units + row = 5), so
the receiver's unchanged compute body would read a neighbor's value.
Structure stays legal — bounds, sentinels, and prefix density all hold —
so only the permutation proof can catch it.

Loaded by ``tools/luxlint.py --exchange <this file>``; must exit 1 with
exactly LUX402.
"""

import types

import numpy as np


def _base_plan():
    counts = np.array([[0, 2], [1, 0]], dtype=np.int64)
    send = np.array([[4, 4, 2, 4],
                     [1, 3, 4, 4]], dtype=np.int32)
    recv = np.array([[8, 8, 5, 7],
                     [2, 8, 8, 8]], dtype=np.int32)
    return types.SimpleNamespace(
        num_parts=2, max_units=4, unit_rows=1, capacity=2,
        counts=counts, send_units=send, recv_pos=recv, profitable=True)


_plan = _base_plan()
# expect: LUX402 (sender 1 row 1 lands at flat index 6, bodies read 5)
_plan.recv_pos[0, 2] = 6

PLANS = [
    {
        "name": "lux402-misaligned-recv",
        "plan": _plan,
        "remote_read_counts": np.array([[0, 2], [1, 0]], dtype=np.int64),
        "row_bytes": 8,
        "declared_bytes_per_iter": 32,
    },
]
