"""Seeded LUX403 violation: the executor-declared
``exchange_bytes_per_iter`` (48) disagrees with what the plan actually
prices — ``exchanged_units_per_iter * unit_rows * row_bytes`` =
2*1*2 units * 1 row * 8 B = 32 B. The tables themselves are perfect;
only the profitability-honesty check can see the drift.

Loaded by ``tools/luxlint.py --exchange <this file>``; must exit 1 with
exactly LUX403.
"""

import types

import numpy as np


def _base_plan():
    counts = np.array([[0, 2], [1, 0]], dtype=np.int64)
    send = np.array([[4, 4, 2, 4],
                     [1, 3, 4, 4]], dtype=np.int32)
    recv = np.array([[8, 8, 5, 7],
                     [2, 8, 8, 8]], dtype=np.int32)
    return types.SimpleNamespace(
        num_parts=2, max_units=4, unit_rows=1, capacity=2,
        counts=counts, send_units=send, recv_pos=recv, profitable=True)


PLANS = [
    {
        "name": "lux403-inflated-declared-bytes",
        "plan": _base_plan(),
        "remote_read_counts": np.array([[0, 2], [1, 0]], dtype=np.int64),
        "row_bytes": 8,
        # expect: LUX403 (plan prices 32 B/iter, executor claims 48)
        "declared_bytes_per_iter": 48,
    },
]
