"""Seeded LUX404 violation: a compact-mode step whose "local" branch
reads the gathered table — every data side of the ownership merge then
transitively consumes the collective's result, so nothing is left for
XLA to overlap with the wire time. This is exactly the regression the
overlap proof exists to catch (the real engines compute the local-edge
contribution from their own shard only).

Loaded by ``tools/luxlint.py --exchange <this file>``; must exit 1 with
exactly LUX404.
"""

import jax
import jax.numpy as jnp


def _step_gathered_first(vals):
    n = vals.shape[0]
    tbl = jax.lax.all_gather(vals, "parts")
    flat = tbl.reshape(-1)
    # expect: LUX404 (the "local" side is computed FROM the gathered
    # table, so the merge depends on the collective on every data side)
    local = flat[:n] * 0.5
    remote = flat[n:2 * n] + 1.0
    own = jax.lax.axis_index("parts") == 0
    return jnp.where(own, local, remote)


TRACES = [
    {
        "name": "fixture@lux404-local-reads-gathered",
        "call": _step_gathered_first,
        "args": (jnp.zeros(64, jnp.float32),),
        "carry": (0,),
        "sharded": True,
        "axis_env": (("parts", 4),),
        "exchange_mode": "compact",
        "num_parts": 4,
    },
]
