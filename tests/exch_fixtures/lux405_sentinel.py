"""Seeded LUX405 violation: a compact-mode min-combiner step that pads
the exchanged candidates with 0.0 instead of the min identity (+inf).
Every padded slot would then win the minimum and overwrite a real
distance with zero. The step keeps an honest local/remote merge so the
overlap proof (LUX404) stays green — only the annihilator check fires.

Loaded by ``tools/luxlint.py --exchange <this file>``; must exit 1 with
exactly LUX405.
"""

import jax
import jax.numpy as jnp


def _step_zero_pad(vals):
    n = vals.shape[0]
    tbl = jax.lax.all_gather(vals, "parts")
    flat = tbl.reshape(-1)
    # expect: LUX405 (pad constant 0.0; the min identity is +inf)
    gathered = jnp.where(flat < 1e30, flat, 0.0)[:n]
    local = vals * 0.5
    own = jax.lax.axis_index("parts") == 0
    merged = jnp.where(own, local, gathered)
    return jnp.minimum(merged, vals)


TRACES = [
    {
        "name": "fixture@lux405-zero-pad-min",
        "call": _step_zero_pad,
        "args": (jnp.zeros(64, jnp.float32),),
        "carry": (0,),
        "sharded": True,
        "axis_env": (("parts", 4),),
        "exchange_mode": "compact",
        "combiner": "min",
        "value_dtype": "float32",
        "num_parts": 4,
    },
]
