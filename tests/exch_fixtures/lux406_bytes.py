"""Seeded LUX406 violation: the step's one ``all_gather`` moves
P*(P-1)*n*4 = 4*3*64*4 = 3072 bytes per iteration, but the executor
metadata claims 1024 — the kind of silent drift that makes every
downstream bandwidth model (ledger, bench gate, perf sheet) wrong while
results stay bit-correct.

Loaded by ``tools/luxlint.py --exchange <this file>``; must exit 1 with
exactly LUX406.
"""

import jax
import jax.numpy as jnp


def _step_honest_overlap(vals):
    n = vals.shape[0]
    tbl = jax.lax.all_gather(vals, "parts")
    flat = tbl.reshape(-1)
    local = vals * 0.5
    remote = flat[n:2 * n] + 1.0
    own = jax.lax.axis_index("parts") == 0
    return jnp.where(own, local, remote)


TRACES = [
    {
        "name": "fixture@lux406-understated-bytes",
        "call": _step_honest_overlap,
        "args": (jnp.zeros(64, jnp.float32),),
        "carry": (0,),
        "sharded": True,
        "axis_env": (("parts", 4),),
        "exchange_mode": "compact",
        # expect: LUX406 (the trace's collective moves 3072 B/iter)
        "exchange_bytes": 1024,
        "num_parts": 4,
    },
]
