"""Seeded LUX407 violations: frontier-exchange evidence that lies.

The base plan (and its first, clean PLANS entry) satisfies LUX401-403
and carries honest frontier evidence — frontier capacity inside the
compact capacity, zero truncated active rows, bytes re-derivable from
``P * (P-1) * slots * frontier_row_bytes``. Each seeded entry breaks
exactly one frontier claim, so only the frontier-coverage rule can
catch it:

- ``lux407-truncated-active``: the packer claims it dropped active
  rows instead of downgrading to the static compact send.
- ``lux407-capacity-overflow``: frontier capacity exceeds the compact
  plan's per-pair capacity, so the send cannot reuse its routing.
- ``lux407-sends-overflow``: per-pair send slots exceed the
  admissibility budget the downgrade check enforces.
- ``lux407-bytes-drift``: the advertised frontier bytes diverge from
  the packer's own pricing.

Loaded by ``tools/luxlint.py --exchange <this file>``; must exit 1
with exactly LUX407.
"""

import types

import numpy as np


def _base_plan():
    counts = np.array([[0, 2], [1, 0]], dtype=np.int64)
    send = np.array([[4, 4, 2, 4],
                     [1, 3, 4, 4]], dtype=np.int32)
    recv = np.array([[8, 8, 5, 7],
                     [2, 8, 8, 8]], dtype=np.int32)
    return types.SimpleNamespace(
        num_parts=2, max_units=4, unit_rows=1, capacity=2,
        counts=counts, send_units=send, recv_pos=recv, profitable=True)


def _evidence(**kw):
    out = {
        "remote_read_counts": np.array([[0, 2], [1, 0]], dtype=np.int64),
        "row_bytes": 8,
        "declared_bytes_per_iter": 32,
        # Honest frontier evidence: 1 slot per pair, value + int32 row
        # id = 12 B per row, 2 * (2-1) * 1 * 12 = 24 B per iteration.
        "frontier_capacity": 1,
        "frontier_max_sends": 1,
        "frontier_row_bytes": 12,
        "frontier_bytes_per_iter": 24,
        "frontier_fill_active": 0,
    }
    out.update(kw)
    return out


PLANS = [
    # Clean: honest frontier evidence passes every LUX40x rule.
    {"name": "lux407-clean", "plan": _base_plan(), **_evidence()},
    # expect: LUX407 (active rows truncated instead of downgraded)
    {"name": "lux407-truncated-active", "plan": _base_plan(),
     **_evidence(frontier_fill_active=3)},
    # expect: LUX407 (frontier capacity cannot exceed the compact
    # plan's per-pair capacity it reuses)
    {"name": "lux407-capacity-overflow", "plan": _base_plan(),
     **_evidence(frontier_capacity=5, frontier_bytes_per_iter=120,
                 frontier_max_sends=5)},
    # expect: LUX407 (send slots exceed the admissibility budget)
    {"name": "lux407-sends-overflow", "plan": _base_plan(),
     **_evidence(frontier_max_sends=2, frontier_bytes_per_iter=48)},
    # expect: LUX407 (advertised bytes drift from P*(P-1)*slots*row)
    {"name": "lux407-bytes-drift", "plan": _base_plan(),
     **_evidence(frontier_bytes_per_iter=999)},
]
