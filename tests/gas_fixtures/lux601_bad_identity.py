"""Seeded LUX601 failure: a min-combiner declaring identity 0.

min(x, 0) == 0 collapses every positive value, so the identity-masked
pull and the sentinel-padded frontier exchange would zero live state.
``luxlint --programs`` over this file must exit 1 with exactly LUX601
(the failed identity voids the trace-based proofs, so LUX603/605 stay
silent rather than cascading).
"""

import numpy as np

from lux_tpu.engine.gas import GasProgram


class BadIdentityMin(GasProgram):
    name = "bad_identity_min"
    combiner = "min"
    servable = False
    frontier_ok = False   # honest declaration: only the identity is broken

    def combine_identity(self, dtype):
        return np.zeros((), dtype=dtype)[()]

    def init_values(self, graph, **kw):
        return (np.arange(graph.nv) % 7).astype(np.uint32)

    def init_frontier(self, graph, **kw):
        return np.ones(graph.nv, dtype=bool)

    def gather(self, src_vals, weights):
        return src_vals
