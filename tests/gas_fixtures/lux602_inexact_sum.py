"""Seeded LUX602 failure: a float32 *sum* posing as a reorderable
combiner.

Float addition is not associative — the probe grid's extremes triples
((max + max) + (-max) vs max + (max + (-max))) diverge deterministically
— so segment_reduce reordering and part-order-independent sharded
accumulation are unlicensed. ``luxlint --programs`` over this file must
exit 1 with exactly LUX602 (the identity 0.0 is fine, the trace is
direction-consistent, annihilation holds — only the algebra is broken).
"""

import numpy as np

from lux_tpu.engine.gas import GasProgram

try:
    import jax.numpy as jnp
except Exception:  # pragma: no cover - jax is baked into the image
    jnp = None


class InexactSum(GasProgram):
    name = "inexact_sum"
    combiner = "sum"
    value_dtype = np.float32 if jnp is None else jnp.float32
    servable = False
    frontier_ok = False   # honest declaration: the algebra is inexact

    def init_values(self, graph, **kw):
        return (np.arange(graph.nv) % 5).astype(np.float32)

    def init_frontier(self, graph, **kw):
        return np.ones(graph.nv, dtype=bool)

    def gather(self, src_vals, weights):
        return src_vals * np.float32(0.5)

    def apply(self, old, acc):
        return old + acc
