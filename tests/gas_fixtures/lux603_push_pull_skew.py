"""Seeded LUX603 failure: a ``gather_push`` specialization that drifts
from the pull-direction edge function.

Pull relaxes src+1, push relaxes src+2 — the two directions' dense
accumulators diverge on the first frontier edge, so direction-adaptive
execution (a mid-run push<->pull switch) would change answers.
``luxlint --programs`` over this file must exit 1 with exactly LUX603
(identity, algebra, annihilation, and monotonicity all hold; only the
duality is broken).
"""

import numpy as np

from lux_tpu.engine.gas import GasProgram


class SkewedDirections(GasProgram):
    name = "push_pull_skew"
    combiner = "min"
    servable = False
    frontier_ok = False   # honest declaration: the directions disagree

    def init_values(self, graph, **kw):
        return (np.arange(graph.nv) % 7).astype(np.uint32)

    def init_frontier(self, graph, **kw):
        return np.ones(graph.nv, dtype=bool)

    def gather(self, src_vals, weights):
        return src_vals + np.uint32(1)

    def gather_push(self, src_vals, weights):
        return src_vals + np.uint32(2)
