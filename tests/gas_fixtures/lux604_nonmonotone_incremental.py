"""Seeded LUX604 failure: ``incremental_ok = True`` without the
monotone-convergence proof.

The relax hook emits src - 1.0: messages move *against* the min order
(gather is not inflationary), so a warm start from stale values is not
guaranteed to re-reach the fixpoint — exactly the property
engine/incremental.py's warm-started refresh depends on. ``luxlint
--programs`` over this file must exit 1 with exactly LUX604 (every
frontier proof — identity, algebra, duality, annihilation — holds, so
``frontier_ok`` stays honestly True; only the incremental claim is
refuted).
"""

import numpy as np

from lux_tpu.engine.push import PushProgram

try:
    import jax.numpy as jnp
except Exception:  # pragma: no cover - jax is baked into the image
    jnp = None


class DriftingMin(PushProgram):
    name = "drifting_min"
    combiner = "min"
    value_dtype = np.float32 if jnp is None else jnp.float32
    servable = False
    incremental_ok = True   # the over-claim LUX604 must refute

    def init_values(self, graph, **kw):
        return (np.arange(graph.nv) % 5).astype(np.float32)

    def init_frontier(self, graph, **kw):
        return np.ones(graph.nv, dtype=bool)

    def relax(self, src_vals, weights):
        return src_vals - np.float32(1.0)
