"""Seeded LUX605 failure: an ``apply`` that clobbers state with the
accumulator.

``apply(old, acc) = acc`` means a vertex that received no messages —
whose accumulator slot still holds the combiner identity — gets the
identity written over its live value. The scalar identity is a perfect
annihilator (LUX601 passes), but at the *program* level an
identity-only accumulator mutates state, so the frontier machinery
(which skips exactly those vertices) would diverge from the dense
sweep. ``luxlint --programs`` over this file must exit 1 with exactly
LUX605.
"""

import numpy as np

from lux_tpu.engine.gas import GasProgram


class ClobberingApply(GasProgram):
    name = "clobbering_apply"
    combiner = "min"
    servable = False
    frontier_ok = False   # honest declaration: annihilation is broken

    def init_values(self, graph, **kw):
        return (np.arange(graph.nv) % 7).astype(np.uint32)

    def init_frontier(self, graph, **kw):
        return np.ones(graph.nv, dtype=bool)

    def gather(self, src_vals, weights):
        return src_vals

    def apply(self, old, acc):
        return acc
