"""Seeded LUX606 failure: capability drift between declaration and
proof.

A frontier-less dense-pull program (``frontier = False``) declares
``frontier_ok = True`` — but with no frontier machinery there is no
annihilation/duality proof to license, so the derived matrix says
False and the declaration is an over-claim. ``luxlint --programs``
over this file must exit 1 with exactly LUX606 (no algebra rule fires:
the frontier proofs are n/a for a dense program, which is the point).
"""

import numpy as np

from lux_tpu.engine.gas import GasProgram

try:
    import jax.numpy as jnp
except Exception:  # pragma: no cover - jax is baked into the image
    jnp = None


class OverclaimedDense(GasProgram):
    name = "overclaimed_dense"
    combiner = "sum"
    value_dtype = np.float32 if jnp is None else jnp.float32
    servable = False
    frontier = False
    frontier_ok = True    # the drift LUX606 must catch

    def init_values(self, graph, **kw):
        return np.zeros(graph.nv, dtype=np.float32)

    def init_frontier(self, graph, **kw):
        return np.ones(graph.nv, dtype=bool)
