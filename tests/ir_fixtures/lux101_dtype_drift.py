"""Seeded LUX101 violation: the iteration carry enters as float32 and
leaves as bfloat16 — every iteration converts (or retraces) the carry.

Loaded by ``tools/luxlint.py --ir <this file>``; the CLI must exit 1.
"""

import jax.numpy as jnp


def _step(vals, deg):
    # expect: LUX101
    return (vals / deg).astype(jnp.bfloat16)


TRACES = [{
    "name": "fixture@lux101",
    "call": _step,
    "args": (jnp.zeros(64, jnp.float32), jnp.ones(64, jnp.float32)),
    "carry": (0,),
    "sharded": False,
}]
