"""Seeded LUX102 violation: a ``pure_callback`` inside the step — a
hidden device->host->device round trip per iteration.

Loaded by ``tools/luxlint.py --ir <this file>``; the CLI must exit 1.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _host_degree(vals):
    return np.asarray(vals) * 2


def _step(vals):
    # expect: LUX102
    doubled = jax.pure_callback(
        _host_degree, jax.ShapeDtypeStruct(vals.shape, vals.dtype), vals
    )
    return doubled + 1.0


TRACES = [{
    "name": "fixture@lux102",
    "call": _step,
    "args": (jnp.zeros(64, jnp.float32),),
    "carry": (0,),
    "sharded": False,
}]
