"""Seeded LUX103 violation: an (n,) x (n,) outer product materializes
an (n, n) intermediate — n times the step's inputs, the O(nnz)
broadcast class of bugs.

Loaded by ``tools/luxlint.py --ir <this file>``; the CLI must exit 1.
"""

import jax.numpy as jnp


def _step(vals):
    # expect: LUX103
    pairwise = jnp.outer(vals, vals)     # (512, 512) from two (512,)
    return pairwise.sum(axis=1)


TRACES = [{
    "name": "fixture@lux103",
    "call": _step,
    "args": (jnp.zeros(512, jnp.float32),),
    "carry": (0,),
    "sharded": False,
}]
