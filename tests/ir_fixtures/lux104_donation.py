"""Seeded LUX104 violation: the arg is declared in ``donate_argnums``
but the step only returns a scalar reduction — no output can alias the
donated buffer, so the donation buys nothing.

Loaded by ``tools/luxlint.py --ir <this file>``; the CLI must exit 1.
"""

import jax
import jax.numpy as jnp


def _step(vals):
    return vals.sum()


# expect: LUX104
_jstep = jax.jit(_step, donate_argnums=0)

TRACES = [{
    "name": "fixture@lux104",
    "fn": _jstep,
    "args": (jnp.zeros(64, jnp.float32),),
    "donate": (0,),
    "carry": (),
    "sharded": False,
}]
