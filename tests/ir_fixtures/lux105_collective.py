"""Seeded LUX105 violations, both directions: a ``psum`` in a trace
declared single-shard (dead cross-device traffic), and a trace declared
sharded that never communicates (stale neighbor values forever).

Loaded by ``tools/luxlint.py --ir <this file>``; the CLI must exit 1.
"""

import jax
import jax.numpy as jnp


def _step_with_psum(vals):
    # expect: LUX105 (collective in a single-shard trace)
    return jax.lax.psum(vals, "parts")


def _step_without_exchange(vals):
    # expect: LUX105 (sharded trace with no collective)
    return vals * 0.85 + 0.15


TRACES = [
    {
        "name": "fixture@lux105-single-shard-psum",
        "call": _step_with_psum,
        "args": (jnp.zeros(64, jnp.float32),),
        "carry": (0,),
        "sharded": False,
        "axis_env": (("parts", 4),),
    },
    {
        "name": "fixture@lux105-sharded-no-collective",
        "call": _step_without_exchange,
        "args": (jnp.zeros(64, jnp.float32),),
        "carry": (0,),
        "sharded": True,
    },
]
