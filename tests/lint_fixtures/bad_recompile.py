"""LUX002 fixture: every `# expect:` line must fire recompile-hygiene."""
import jax


def apply(state, rate):
    return state * rate


def make_step(graph):
    def step(state, graph):
        return state

    jitted = jax.jit(step)                     # expect: LUX002
    return jitted


@jax.jit                                       # expect: LUX002
def run_kernel(state):
    return state


def drive(state):
    stepper = jax.jit(apply, donate_argnums=0)
    return stepper(state, 0.85)                # expect: LUX002
