"""LUX001 fixture: every `# expect:` line must fire host-sync-in-hot-loop.

Never imported or executed — parsed by tests/test_analysis.py. The
`engine/` path component puts it in LUX001's scope.
"""
import jax
import numpy as np


def run_loop(step, vals, n):
    for _ in range(n):
        vals = step(vals)
        host = np.asarray(vals)                # expect: LUX001
        jax.block_until_ready(vals)            # expect: LUX001
        jax.device_get(vals)                   # expect: LUX001
        score = float(vals[0])                 # expect: LUX001
        done = vals.sum().item()               # expect: LUX001
    return vals, host, score, done


def run_fixpoint(multi, state, chunk):
    total = 0
    while total < chunk:
        state, done = multi(state, chunk)
        total += hard_sync(done)               # expect: LUX001
    return state
