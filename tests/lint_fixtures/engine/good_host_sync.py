"""LUX001 fixture: zero findings expected.

Syncs outside the loop, host-tainted conversions, and sync in
non-hot-path functions are all legal.
"""
import jax
import numpy as np


def run_loop(step, vals, n):
    for _ in range(n):
        vals = step(vals)
    jax.block_until_ready(vals)        # after the loop: legal
    return vals


def run_fixpoint(multi, state, chunk):
    # One fetch outside any loop; converting the fetched HOST value
    # inside the loop is free and must not be flagged.
    done_h = jax.device_get(multi(state, chunk))
    total = 0
    for _ in range(chunk):
        total += int(np.asarray(done_h).reshape(-1)[0])
    return total


def warmup(step, vals):
    # Not a run/fixpoint/pipelined function: syncs per dispatch by design.
    for _ in range(2):
        jax.block_until_ready(step(vals))
