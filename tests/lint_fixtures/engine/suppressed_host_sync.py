"""LUX001 fixture: two real violations, both suppressed with a reason —
the report must show 0 findings and 2 suppressed."""
import jax


def run_flush(step, vals, n):
    for i in range(n):
        vals = step(vals)
        jax.block_until_ready(vals)  # luxlint: disable=LUX001 -- designed flush point
        # luxlint: disable=all -- comment-only line covers the next line
        jax.device_get(vals)
    return vals
