"""LUX002 fixture: zero findings expected — donated buffers, static
scalars, and non-step jits are all legal."""
from functools import partial

import jax
import jax.numpy as jnp


def advance(state, k):
    return state + k


def make_step(graph):
    def step(state, graph):
        return state

    return jax.jit(step, donate_argnums=0)


@partial(jax.jit, donate_argnums=0)
def run_phase(state):
    return state


def drive(state):
    stepper = jax.jit(advance, static_argnums=1)
    out = stepper(state, 16)          # static arg: legal
    mapped = jax.jit(jnp.sqrt)        # not a buffer-carrying step
    return mapped(out)
