"""LUX004/LUX005 fixture. The `lux_tpu/` path component puts it in
LUX005's scope; LUX004 applies everywhere."""
import os

from lux_tpu.utils import flags

MODE = os.environ.get("LUX_FAKE_MODE", "")     # expect: LUX004, LUX005
LEVEL = os.environ["LUX_LOG"]                  # expect: LUX005
DEPTH = flags.get_int("LUX_NOT_DECLARED")      # expect: LUX004
