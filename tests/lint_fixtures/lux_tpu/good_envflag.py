"""LUX004/LUX005 fixture: zero findings expected — declared flags read
through the registry accessors; environment WRITES stay legal."""
import os

from lux_tpu.utils import flags

LEVEL = flags.get("LUX_LOG")
SCALE = flags.get_int("LUX_SMOKE_SCALE")
os.environ.setdefault("LUX_PLATFORM", "cpu")   # write, not a read
os.environ["LUX_LOG"] = "DEBUG"                # store context: legal
