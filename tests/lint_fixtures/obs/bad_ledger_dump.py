"""LUX010 fixtures: run metrics leaving the process as ad-hoc JSON.

A run summary written with a bare json.dump is invisible to lux_doctor
and the auto-tuner corpus: no crc framing, no rotation, no
(graph, program, engine, mesh, config_hash) key to reproduce it under.
Every run-metrics write goes through lux_tpu.obs.ledger.record_run."""
import json


def dump_summary(summary, path):
    with open(path, "w") as f:
        json.dump(summary, f)  # expect: LUX010


def dump_telemetry_line(telemetry):
    return json.dumps(telemetry)  # expect: LUX010


def dump_nested(run_record, f):
    json.dump(run_record["metrics"], f)  # expect: LUX010
