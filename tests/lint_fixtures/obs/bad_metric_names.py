"""LUX008 fixtures: metric handles violating the name or creation
discipline. Names must match lux_[a-z0-9_]+(_total|_seconds|_bytes)?;
handles must not be minted per call (each creation round-trips the
registry lock) — never in a loop, and in obs/ code a constant-shaped
handle must live at module scope."""
from lux_tpu.obs import metrics

GOOD_TOP = metrics.counter("lux_requests_total")


def count_batches(batches):
    for b in batches:
        c = metrics.counter("lux_batches_total")  # expect: LUX008
        c.inc(len(b))


def watch(queue):
    while queue:
        metrics.gauge("lux_queue_depth").set(len(queue))  # expect: LUX008
        queue.pop()


def bad_names():
    metrics.counter("requests_total")  # expect: LUX008
    metrics.gauge("lux_QueueDepth")  # expect: LUX008
    metrics.histogram("lux-latency-seconds")  # expect: LUX008


def per_call_handle():
    # Constant name, constant labels: nothing stops this living at
    # module scope, so every call churns the registry lock for nothing.
    h = metrics.histogram("lux_step_seconds", {"phase": "step"})  # expect: LUX008
    return h
