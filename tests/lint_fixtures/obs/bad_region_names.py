"""LUX009 fixtures: profiler region names breaking the naming
contract. Literal names passed to prof.region / jax.named_scope /
jax.profiler.TraceAnnotation must fullmatch lux.[a-z0-9_.]+ — anything
else never joins the profile.v1 phase accounting and the time it
brackets silently vanishes from exchange/compute attribution."""
import jax

from lux_tpu.obs import prof
from lux_tpu.obs.prof import region


def missing_prefix(fn):
    with prof.region("pull.exchange"):  # expect: LUX009
        return fn()


def wrong_case(fn):
    with prof.region("lux.Pull.Exchange"):  # expect: LUX009
        return fn()


def bare_import(fn):
    with region("exchange"):  # expect: LUX009
        return fn()


def raw_named_scope(fn):
    with jax.named_scope("my scope"):  # expect: LUX009
        return fn()


def raw_annotation(fn):
    with jax.profiler.TraceAnnotation("Step-1"):  # expect: LUX009
        return fn()
