"""LUX010 clean fixture: run metrics routed through the ledger API;
non-metric artifacts (plans, reports, payloads) keep json freely."""
import json

from lux_tpu.obs import ledger


def record(summary_dict):
    # The discipline: one durable runrec.v1 observation per run.
    return ledger.record_run(
        "engine_run", summary_dict, program="PageRank",
        engine_kind="pull",
    )


def write_plan_meta(meta, path):
    # Artifact writes that are not run metrics stay plain JSON.
    with open(path, "w") as f:
        json.dump(meta, f)


def wire_payload(payload):
    return json.dumps(payload)
