"""LUX008-clean metric creation: disciplined names minted once at
module scope, plus the legal function-scope shapes — dynamic label
values (the handle genuinely varies per call) and non-literal names
(WAL replay counters resolved from records)."""
from lux_tpu.obs import metrics

REQUESTS = metrics.counter("lux_requests_total")
DEPTH = metrics.gauge("lux_queue_depth")
LAT = metrics.histogram("lux_iteration_seconds")
BYTES = metrics.counter("lux_exchange_bytes")


def per_engine(engine):
    # Dynamic labels: one handle per engine value cannot be hoisted.
    return metrics.counter("lux_iterations_total", {"engine": engine})


def replay(record):
    # Non-literal name: the registry key comes from data, not code.
    return metrics.counter(record["name"], record["labels"])
