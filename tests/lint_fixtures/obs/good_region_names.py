"""LUX009 negative fixtures: compliant or out-of-scope region names —
zero findings expected."""
import jax

from lux_tpu.obs import prof
from lux_tpu.obs.prof import region


def compliant(fn):
    with prof.region("lux.pull_sharded.exchange"):
        return fn()


def compliant_bare(fn):
    with region("lux.serve.execute"):
        return fn()


def compliant_scope(fn):
    with jax.named_scope("lux.tiled.compute_0"):
        return fn()


def dynamic_name(fn, tag):
    # Non-literal names validate at runtime (prof.region raises on a
    # bad name); the static rule only judges literals.
    with prof.region(tag):
        return fn()


def unrelated_region(fn):
    # Some other library's `region` — not the prof one; out of scope.
    class _Tracer:
        def region(self, name):
            import contextlib

            return contextlib.nullcontext()

    with _Tracer().region("whatever"):
        return fn()
