"""LUX003 fixture: every `# expect:` line must fire kernel-shape-contract.

Lives under an `ops/` path component; "kernel" in the basename arms the
dtype-contract checks.
"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def make_specs(codes, row_idx):
    spec = pl.BlockSpec((8, 64), lambda i: (i, 0))        # expect: LUX003
    spec2 = pl.BlockSpec((5, 128), lambda i: (i, 0))      # expect: LUX003
    out = jax.ShapeDtypeStruct((16, 100), jnp.float32)    # expect: LUX003
    codes_w = codes.astype(jnp.int16)                     # expect: LUX003
    rows = row_idx.astype(jnp.int64)                      # expect: LUX003
    return spec, spec2, out, codes_w, rows
