"""LUX003 fixture: zero findings expected — 128-lane blocks, 8-row (or
scalar-prefetch single-row) sublanes, contract dtypes."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def make_specs(codes, row_idx, nvb):
    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    row_spec = pl.BlockSpec((1, 128), lambda i: (i, 0))   # per-row form
    out = jax.ShapeDtypeStruct((nvb, 128), jnp.float32)   # symbolic rows
    codes_w = codes.astype(jnp.int8)
    rows = row_idx.astype(jnp.int32)
    return spec, row_spec, out, codes_w, rows
