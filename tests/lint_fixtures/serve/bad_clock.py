"""LUX006 fixture. The `serve/` path component puts it in scope; every
raw time.* read must be flagged, whatever it feeds."""
import time


def handle(req, window_s):
    t0 = time.perf_counter()                   # expect: LUX006
    deadline = time.monotonic() + window_s     # expect: LUX006
    stamp = time.time()                        # expect: LUX006
    ns = time.perf_counter_ns()                # expect: LUX006
    return t0, deadline, stamp, ns
