"""LUX007 fixtures: broad serve-path handlers that drop errors on the
floor — the request waiting on the result never hears about them."""


def drop_with_pass(engine):
    try:
        return engine.run()
    except Exception:  # expect: LUX007
        pass


def log_and_drop(engine, log):
    try:
        return engine.run()
    except:  # expect: LUX007
        log.warning("engine failed; carrying on")


def print_and_bail(engine):
    try:
        return engine.run()
    except (ValueError, BaseException) as e:  # expect: LUX007
        print("dropping", e)
        return None
