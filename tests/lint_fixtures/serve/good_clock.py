"""LUX006 fixture: serve code stamping time through the obs helpers —
one clock source for durations (trace epoch) and one for deadlines."""
import time

from lux_tpu.obs import spans


def handle(req, window_s):
    t0 = spans.clock()
    deadline = spans.monotonic() + window_s
    time.sleep(0.0)            # sleeping is not reading a clock
    return spans.clock() - t0, deadline
