"""LUX007-clean handlers: broad catches stay legal while the failure
remains observable (re-raised, typed, or resolved into a future)."""


class WrappedError(Exception):
    pass


def rethrow_typed(engine):
    try:
        return engine.run()
    except Exception as e:
        raise WrappedError(f"engine failed: {e}") from e


def fail_the_batch(batch):
    try:
        batch.execute()
    except Exception as e:
        for r in batch.requests:
            r.future.set_exception(e)


def record_then_degrade(engine, counters):
    try:
        return engine.run()
    except Exception:
        counters.cache_put_errors += 1
        return engine.fallback()


def narrow_catch_may_pass(value):
    try:
        return int(value)
    except ValueError:
        pass
    return 0
