"""LUX303 fixture: unbounded blocking while a lock is held."""
import queue
import threading
import time

_lock = threading.Lock()
_q = queue.Queue()


def drain(worker):
    with _lock:
        item = _q.get()                           # expect: LUX303
        worker.join()                             # expect: LUX303
        return item


def nap():
    with _lock:
        time.sleep(0.1)                           # expect: LUX303
