"""LUX302 fixture: A->B in forward, B->A in backward — a static cycle."""
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()


def forward():
    with a_lock:
        with b_lock:                              # expect: LUX302
            return 1


def backward():
    with b_lock:
        with a_lock:                              # expect: LUX302
            return 2
