"""LUX305 fixture: publish-pointer discipline violations."""
import threading


class Server:
    def __init__(self, snap):
        self._swap_lock = threading.Lock()
        self._serving = snap      # luxlint: publish=_swap_lock

    def swap(self, snap):
        self._serving = snap                      # expect: LUX305

    def answer(self):
        a = self._serving
        b = self._serving                         # expect: LUX305
        return a, b

    def double_flip(self, snap):
        with self._swap_lock:
            self._serving = snap
            self._serving = snap                  # expect: LUX305
