"""LUX301 fixture: thread-shared attrs accessed without their lock."""
import threading


class Worker:
    def __init__(self):
        self.jobs_done = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for _ in range(8):
            self.jobs_done += 1                   # expect: LUX301

    def report(self):
        return self.jobs_done                     # expect: LUX301

    def close(self):
        self._thread.join(1.0)
