"""LUX304 fixture: spawned threads with no join/drain path."""
import threading


def fire_and_forget(fn):
    t = threading.Thread(target=fn, daemon=True)  # expect: LUX304
    t.start()


def spawn_inline(fn):
    threading.Thread(target=fn).start()           # expect: LUX304
