"""LUX303 clean: bounded waits under the lock, slow work outside it."""
import queue
import threading
import time

_lock = threading.Lock()
_q = queue.Queue()


def drain(worker):
    with _lock:
        item = _q.get(timeout=0.5)
    worker.join(1.0)
    return item


def nap():
    time.sleep(0.1)
    with _lock:
        return _q.qsize()
