"""LUX302 clean: every function acquires in the same global order."""
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()


def forward():
    with a_lock:
        with b_lock:
            return 1


def also_forward():
    with a_lock, b_lock:
        return 2
