"""LUX305 clean: one write per swap under the declared lock; readers
grab the pointer once into a local."""
import threading


class Server:
    def __init__(self, snap):
        self._swap_lock = threading.Lock()
        self._serving = snap      # luxlint: publish=_swap_lock

    def swap(self, snap):
        with self._swap_lock:
            self._serving = snap

    def answer(self):
        snap = self._serving
        return snap, snap
