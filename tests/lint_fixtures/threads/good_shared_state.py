"""LUX301 clean: shared attrs guarded by their declared lock, plus the
guarded-by annotation for a cross-method holder."""
import threading


class Worker:
    def __init__(self):
        self.jobs_done = 0            # luxlint: guarded-by=_lock
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for _ in range(8):
            with self._lock:
                self.jobs_done += 1

    def _bump_locked(self):
        self.jobs_done += 1           # luxlint: guarded-by=_lock -- callers hold it

    def report(self):
        with self._lock:
            return self.jobs_done

    def close(self):
        self._thread.join(1.0)
