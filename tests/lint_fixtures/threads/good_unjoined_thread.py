"""LUX304 clean: join directly, return to the caller, or register in a
container a drain function joins (the drain_compactions shape)."""
import threading

_threads = []


def run_sync(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(5.0)


def spawn_for_caller(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t


def spawn_registered(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    _threads.append(t)


def drain(timeout=5.0):
    for t in _threads:
        t.join(timeout)
