"""Seeded LUX701 violation: a memcap.v1 artifact whose only entry is
structurally rotten — the model is missing coefficients, the recorded
peak is negative, and the probe dims are absent. Admission math over
this entry would be garbage-in, so the structure rule fails it before
any formula is evaluated.

Loaded by ``tools/luxlint.py --memory <this file>``; the CLI must exit
1 with exactly LUX701.
"""

# expect: LUX701
MEMCAP = {
    "schema": "memcap.v1",
    "id": "memcap-000000000000",
    "probe": {"nv": 96, "ne": 400},
    "targets": {
        "sssp@push": {
            "kind": "push",
            "model": {"per_vertex_bytes": 4.0},   # missing two fields
            "peak_bytes": -3,                      # not a positive int
            "probe": {},                           # no dims
        },
    },
}
