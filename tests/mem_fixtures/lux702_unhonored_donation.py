"""Seeded LUX702 violation: the trace spec declares the carry donated,
but the jit wasn't built with ``donate_argnums`` — the lowered HLO
carries no input/output aliasing, so both copies of the carry stay
live and the declared donation buys nothing. LUX104 would call this
"audited"; the memory tier prices it into the peak.

Loaded by ``tools/luxlint.py --memory <this file>``; the CLI must exit
1 with exactly LUX702.
"""

import jax
import jax.numpy as jnp


def _step(vals, deg):
    return jnp.minimum(vals, vals[::-1] + deg)


# expect: LUX702 -- donation declared below, never lowered into the jit
_jstep = jax.jit(_step)

TARGETS = {
    "fixture@lux702": {
        "fn": _jstep,
        "args": (jnp.zeros(64, jnp.float32), jnp.ones(64, jnp.float32)),
        "donate": (0,),
        "carry": (0,),
        "sharded": False,
        "nv": 64,
        "ne": 64,
    },
}
