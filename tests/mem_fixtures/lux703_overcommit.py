"""Seeded LUX703 violation: an honest step over 4096-vertex float32
state (~16 KiB live) against a declared device capacity of 1 KiB. The
derived model predicts a peak that cannot fit, and the budget rule
fails closed here — offline — instead of OOMing on-device.

Loaded by ``tools/luxlint.py --memory <this file>``; the CLI must exit
1 with exactly LUX703.
"""

import jax.numpy as jnp


def _step(vals):
    return jnp.minimum(vals, vals[::-1])


# expect: LUX703
CAPACITY_BYTES = 1024

TARGETS = {
    "fixture@lux703": {
        "call": _step,
        "args": (jnp.zeros(4096, jnp.float32),),
        "carry": (0,),
        "sharded": False,
        "nv": 4096,
        "ne": 4096,
    },
}
