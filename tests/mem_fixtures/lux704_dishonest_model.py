"""Seeded LUX704 violation: a claimed closed-form footprint model that
prices the whole engine at one byte. The traced peak is ~KiBs, so the
formula serving would trust under-estimates the footprint — admission
would over-pack the device and the OOM arrives at runtime instead of
in verify.

Loaded by ``tools/luxlint.py --memory <this file>``; the CLI must exit
1 with exactly LUX704.
"""

import jax.numpy as jnp


def _step(vals, deg):
    return jnp.minimum(vals, vals[::-1] + deg)


TARGETS = {
    "fixture@lux704": {
        "call": _step,
        "args": (jnp.zeros(256, jnp.float32), jnp.ones(256, jnp.float32)),
        "carry": (0,),
        "sharded": False,
        "nv": 256,
        "ne": 256,
    },
}

# expect: LUX704 -- one byte covers nothing
MODELS = {
    "fixture@lux704": {
        "per_vertex_bytes": 0.0,
        "per_edge_bytes": 0.0,
        "fixed_bytes": 1,
    },
}
