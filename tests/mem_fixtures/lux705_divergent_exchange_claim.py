"""Seeded LUX705 violation: a full-exchange step whose traced
all-gather stages real buffers, but whose ``exchange_bytes`` claim
(the figure ``exchange_bytes_per_iter()`` would report to serving and
the exchange gate) matches none of the collectives actually lowered.
The peak the walk prices and the claim observability reports have
diverged — one of them is lying.

Loaded by ``tools/luxlint.py --memory <this file>``; the CLI must exit
1 with exactly LUX705.
"""

import jax
import jax.numpy as jnp


def _step(vals):
    got = jax.lax.all_gather(vals, "p")
    return jnp.min(got, axis=0)


TARGETS = {
    "fixture@lux705": {
        "call": _step,
        "args": (jnp.zeros(16, jnp.float32),),
        "carry": (0,),
        "sharded": False,
        "axis_env": (("p", 8),),
        "exchange_mode": "full",
        # expect: LUX705 -- the traced all-gather moves 8*16*4 bytes/part
        "exchange_bytes": 12345,
        "num_parts": 8,
        "nv": 16,
        "ne": 16,
    },
}
