"""Seeded LUX706 violation: the committed memcap.v1 stand-in carries
an admission formula calibrated against some long-gone build — it
predicts one byte where a fresh trace peaks at ~KiBs. Serving would
admit engines against the stale footprint; the drift rule demands the
artifact be regenerated instead.

Loaded by ``tools/luxlint.py --memory <this file>``; the CLI must exit
1 with exactly LUX706.
"""

import jax.numpy as jnp


def _step(vals, deg):
    return jnp.minimum(vals, vals[::-1] + deg)


TARGETS = {
    "fixture@lux706": {
        "call": _step,
        "args": (jnp.zeros(256, jnp.float32), jnp.ones(256, jnp.float32)),
        "carry": (0,),
        "sharded": False,
        "nv": 256,
        "ne": 256,
    },
}

# expect: LUX706 -- a formula from a build that no longer exists
COMMITTED = {
    "schema": "memcap.v1",
    "targets": {
        "fixture@lux706": {
            "k": 1,
            "model": {
                "per_vertex_bytes": 0.0,
                "per_edge_bytes": 0.0,
                "fixed_bytes": 1,
            },
        },
    },
}
