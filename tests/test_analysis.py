"""luxlint: rule engine, per-rule fixtures, CLI contract, flag registry,
and the runtime tracing-discipline sentinels.

Fixture convention (tests/lint_fixtures/): `bad_*` files carry
`# expect: LUXNNN[, LUXNNN]` markers on exactly the lines a finding must
anchor to; `good_*` files must produce zero findings. Rules scope by
path fragment, so fixtures live under engine/ / ops/ / lux_tpu/
subdirectories to arm the path-scoped rules.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from lux_tpu.analysis import all_rules, run_paths, run_source
from lux_tpu.analysis.core import load_declared_flags, suppressions_for
from lux_tpu.utils import flags

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
FIXTURES = os.path.join(TESTS, "lint_fixtures")
LUXLINT = os.path.join(REPO, "tools", "luxlint.py")

_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z0-9_,\s]+?)\s*$")

BAD_FIXTURES = (
    "engine/bad_host_sync.py",
    "bad_recompile.py",
    "ops/bad_kernel_specs.py",
    "lux_tpu/bad_envflag.py",
    "serve/bad_clock.py",
    "serve/bad_swallow.py",
    "obs/bad_metric_names.py",
    "obs/bad_region_names.py",
    "obs/bad_ledger_dump.py",
)
GOOD_FIXTURES = (
    "engine/good_host_sync.py",
    "good_recompile.py",
    "ops/good_kernel_specs.py",
    "lux_tpu/good_envflag.py",
    "serve/good_clock.py",
    "serve/good_swallow.py",
    "obs/good_metric_names.py",
    "obs/good_region_names.py",
    "obs/good_ledger_dump.py",
)


def _expected(path):
    want = {}
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            m = _EXPECT_RE.search(line)
            if m:
                want[i] = sorted(
                    s.strip() for s in m.group(1).split(",") if s.strip()
                )
    return want


def _lint(path, rules=None):
    with open(path) as fh:
        src = fh.read()
    return run_source(src, path, rules or all_rules(), load_declared_flags())


def _by_line(findings):
    out = {}
    for f in findings:
        out.setdefault(f.line, []).append(f.rule)
    return {k: sorted(v) for k, v in out.items()}


# -- rules vs fixtures ----------------------------------------------------


@pytest.mark.parametrize("rel", BAD_FIXTURES)
def test_bad_fixture_fires_exactly_where_expected(rel):
    path = os.path.join(FIXTURES, rel)
    res = _lint(path)
    assert res.error is None
    want = _expected(path)
    assert want, f"{rel} has no expect markers"
    assert _by_line(res.findings) == want
    assert res.suppressed == []


@pytest.mark.parametrize("rel", GOOD_FIXTURES)
def test_good_fixture_is_clean(rel):
    res = _lint(os.path.join(FIXTURES, rel))
    assert res.error is None
    assert res.findings == [] and res.suppressed == []


def test_suppression_with_reason_is_counted_not_silent():
    res = _lint(os.path.join(FIXTURES, "engine", "suppressed_host_sync.py"))
    assert res.findings == []
    assert len(res.suppressed) == 2
    assert {f.rule for f in res.suppressed} == {"LUX001"}


def test_suppressions_for_ids_reasons_and_comment_lines():
    supp = suppressions_for([
        "x = 1  # luxlint: disable=LUX001,LUX002 -- reason text",
        "# luxlint: disable=all",
        "y = 2",
    ])
    assert supp[1] == {"LUX001", "LUX002"}
    assert supp[2] == {"all"}
    assert supp[3] == {"all"}      # comment-only line covers the next line
    assert 4 not in supp


def test_rule_selection_runs_subset():
    path = os.path.join(FIXTURES, "lux_tpu", "bad_envflag.py")
    rules = [r for r in all_rules() if r.id == "LUX004"]
    res = _lint(path, rules)
    assert {f.rule for f in res.findings} == {"LUX004"}
    assert len(res.findings) == 2


def test_report_json_and_summary_schema():
    report = run_paths([FIXTURES], all_rules())
    expected_total = sum(
        len(ids)
        for rel in BAD_FIXTURES
        for ids in _expected(os.path.join(FIXTURES, rel)).values()
    )
    payload = json.loads(report.to_json())
    s = payload["summary"]
    assert s["schema"] == "luxlint.v1"
    assert s["findings"] == expected_total
    assert s["suppressed"] == 2
    assert s["ok"] is False
    assert sum(s["by_rule"].values()) == expected_total
    for f in payload["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message"}


def test_syntax_error_is_reported_not_crashed():
    res = run_source("def broken(:\n", "engine/x.py", all_rules(), set())
    assert res.error and "x.py" in res.error


# -- CLI contract ---------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, LUXLINT, *args],
        capture_output=True, text=True, cwd=REPO,
    )


def _summary_line(stdout):
    lines = [l for l in stdout.splitlines() if l.startswith("LUXLINT ")]
    assert lines, stdout
    return json.loads(lines[-1][len("LUXLINT "):])


def test_cli_full_tree_is_green():
    # The gate `make lint` runs: the shipped tree must lint clean (every
    # intentional sync point suppressed with a reason, every flag
    # declared), and the last stdout line must be the greppable summary.
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    s = _summary_line(proc.stdout)
    assert s["ok"] is True and s["findings"] == 0 and s["errors"] == 0
    assert s["files"] > 50
    assert s["suppressed"] >= 2    # pull flush + push chunk fetch


def test_cli_nonzero_on_seeded_violation(tmp_path):
    bad = tmp_path / "engine" / "run_bad.py"
    bad.parent.mkdir()
    bad.write_text(
        "def run(step, vals, n):\n"
        "    for _ in range(n):\n"
        "        vals = step(vals)\n"
        "        done = vals.item()\n"
        "    return vals, done\n"
    )
    proc = _run_cli(str(tmp_path))
    assert proc.returncode == 1
    s = _summary_line(proc.stdout)
    assert s["by_rule"] == {"LUX001": 1}
    assert f"{bad}:4" in proc.stdout


def test_cli_json_output_parses():
    proc = _run_cli("--json", os.path.join(FIXTURES, "lux_tpu"))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout.rsplit("LUXLINT ", 1)[0])
    assert doc["summary"]["findings"] == 4
    assert {f["rule"] for f in doc["findings"]} == {"LUX004", "LUX005"}


def test_cli_rejects_unknown_rule_id():
    proc = _run_cli("--select", "LUX999")
    assert proc.returncode == 2
    assert "LUX999" in proc.stderr


# -- flag registry --------------------------------------------------------


def test_flags_accessors(monkeypatch):
    assert "LUX_LOG" in flags.names()
    with pytest.raises(KeyError):
        flags.get("LUX_NOT_A_FLAG")
    assert flags.default("LUX_EDGE_CHUNK_BYTES") == 2 << 30

    monkeypatch.delenv("LUX_BENCH_SCALE", raising=False)
    assert flags.get_int("LUX_BENCH_SCALE") == 22

    monkeypatch.delenv("LUX_PACK_STRIPS", raising=False)
    assert flags.get_bool("LUX_PACK_STRIPS") is False
    monkeypatch.setenv("LUX_PACK_STRIPS", "1")
    assert flags.get_bool("LUX_PACK_STRIPS") is True
    monkeypatch.setenv("LUX_PACK_STRIPS", "off")
    assert flags.get_bool("LUX_PACK_STRIPS") is False

    monkeypatch.delenv("LUX_PLAN_BANDED", raising=False)
    assert flags.tristate("LUX_PLAN_BANDED") is None
    monkeypatch.setenv("LUX_PLAN_BANDED", "1")
    assert flags.tristate("LUX_PLAN_BANDED") is True
    monkeypatch.setenv("LUX_PLAN_BANDED", "0")
    assert flags.tristate("LUX_PLAN_BANDED") is False
    monkeypatch.setenv("LUX_PLAN_BANDED", "yes")
    with pytest.raises(ValueError):
        flags.tristate("LUX_PLAN_BANDED")
    assert flags.tristate("LUX_PLAN_BANDED", strict=False) is None


def test_flags_define_guards():
    with pytest.raises(ValueError):
        flags.define("LUX_LOG", "DEBUG", "conflicting redefinition")
    with pytest.raises(ValueError):
        flags.define("NOT_LUX_PREFIXED", 1, "bad prefix")
    # Identical redefinition is a no-op (idempotent re-imports).
    f = flags.define(
        "LUX_LOG", "INFO",
        "log level for the lux.* logger categories (DEBUG..CRITICAL)",
    )
    assert f.name == "LUX_LOG"


def test_flags_module_prints_table():
    proc = subprocess.run(
        [sys.executable, "-m", "lux_tpu.utils.flags"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "LUX_LOG" in proc.stdout
    assert "LUX_EDGE_CHUNK_BYTES" in proc.stdout
    # every declared flag appears
    for name in flags.names():
        assert name in proc.stdout


# -- runtime sentinels ----------------------------------------------------


def test_recompile_sentinel_counts_compiles_not_cache_hits():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from lux_tpu.analysis.sentinel import RecompileError, RecompileSentinel
    from lux_tpu.obs import metrics

    sent = RecompileSentinel("test")
    if not sent.available:
        sent.close()
        pytest.skip("jax monitoring hook unavailable in this jax")
    try:
        @jax.jit
        def f(x):
            return x * 2 + 1

        # Inputs built OUTSIDE the regions: jnp.arange dispatches its
        # own compiled executable, which must not pollute the counts.
        x8, x16 = jnp.arange(8), jnp.arange(16)

        with sent.expect("k"):
            f(x8).block_until_ready()
        warm = sent.compiles("k")
        assert warm >= 1

        with sent.watch("k"):
            f(x8).block_until_ready()              # executable cache hit
        assert sent.recompiles("k") == 0
        sent.assert_zero_recompiles()

        jax.jit(lambda x: x - 3)(x8)               # outside any region
        assert sent.compiles("k") == warm
        assert sent.recompiles() == 0

        with sent.watch("k"):
            f(x16).block_until_ready()             # new shape: recompile
        assert sent.recompiles("k") == 1
        with pytest.raises(RecompileError):
            sent.assert_zero_recompiles()
        st = sent.stats()
        assert st["per_key"]["k"]["serve"] == 1

        # Mirrored onto the obs registry for LUX_METRICS dumps.
        hits = [
            m for m in metrics.snapshot()
            if m["name"] == "lux_xla_compiles_total"
            and m["labels"].get("key") == "k"
            and m["labels"].get("phase") == "serve"
        ]
        assert hits and hits[0]["value"] >= 1
    finally:
        sent.close()


def test_host_transfer_guard_blocks_and_allows():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from lux_tpu.analysis.sentinel import HostTransferError, HostTransferGuard

    x = jnp.arange(8)
    with HostTransferGuard("unit") as g:
        with pytest.raises(HostTransferError):
            jax.device_get(x)
        with pytest.raises(HostTransferError):
            jax.block_until_ready(x)
        with g.allow():               # intended sync point
            assert int(jax.device_get(x)[3]) == 3
    # Entry points restored on exit.
    assert int(jax.device_get(x)[0]) == 0
    assert jax.block_until_ready(x) is x


def test_host_transfer_guard_around_engine_loop():
    # The discipline LUX001 checks statically, enforced at runtime: a
    # pull fused-step loop body must issue no device->host transfer
    # between intended sync points.
    jax = pytest.importorskip("jax")

    from lux_tpu.analysis.sentinel import HostTransferGuard
    from lux_tpu.engine.pull import PullExecutor
    from lux_tpu.graph import generate
    from lux_tpu.models.pagerank import PageRank

    g = generate.gnp(300, 1800, seed=77)
    ex = PullExecutor(g, PageRank())
    vals = ex.init_values()
    with HostTransferGuard("pull-loop") as guard:
        for _ in range(4):
            vals = ex.step(vals)      # stays on device
        with guard.allow():
            jax.block_until_ready(vals)
    assert vals.shape[0] == g.nv


def test_flags_define_outside_registry_is_lux004():
    # Satellite of the registry-drift contract: LUX004's allowed-key set
    # is generated from utils/flags.py, so a define() anywhere else is
    # registry drift by construction — including via an import alias.
    direct = (
        "from lux_tpu.utils import flags\n"
        "flags.define('LUX_ROGUE', 1, 'drift', kind='int')\n"
    )
    aliased = (
        "from lux_tpu.utils.flags import define\n"
        "define('LUX_ROGUE', 1, 'drift', kind='int')\n"
    )
    for src in (direct, aliased):
        res = run_source(
            src, "lux_tpu/engine/rogue.py", all_rules(),
            load_declared_flags())
        assert any(
            f.rule == "LUX004" and "declaration site" in f.message
            for f in res.findings
        ), (src, res.findings)
    # The registry itself is the one legitimate declaration site.
    res = run_source(
        direct, "lux_tpu/utils/flags.py", all_rules(),
        load_declared_flags())
    assert not any(
        "declaration site" in f.message for f in res.findings)


def test_ir_flags_are_registered():
    # The IR tier's knobs went through the registry (LUX004 would flag
    # their use otherwise).
    assert flags.get_float("LUX_IR_BLOWUP") == 16.0
    assert flags.get_bool("LUX_IR_POOL_AUDIT") is True
    assert flags.get_float("LUX_PLANCK_INFLATION") == 8.0


def test_recompile_sentinel_thread_safe_under_concurrent_warmups():
    # EnginePool serializes builds per pool, but nothing stops several
    # pools (or a pool and test traffic) compiling at once: concurrent
    # expect() regions on distinct threads must not lose counts, and
    # attribution must stay per-thread (TLS region stack).
    import threading

    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from lux_tpu.analysis.sentinel import RecompileSentinel

    sent = RecompileSentinel("race")
    if not sent.available:
        sent.close()
        pytest.skip("jax monitoring hook unavailable in this jax")
    n = 8
    barrier = threading.Barrier(n)
    errors = []

    def warm(i):
        try:
            barrier.wait()
            with sent.expect(f"k{i}"):
                # Distinct shape per thread: each warmup really compiles.
                jax.jit(lambda x: x * 2 + i)(
                    jnp.arange(8 + i)).block_until_ready()
        except Exception as e:   # pragma: no cover - surfaced below
            errors.append(e)

    try:
        threads = [threading.Thread(target=warm, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        per_key = sent.stats()["per_key"]
        assert set(per_key) == {f"k{i}" for i in range(n)}
        # No lost updates: the total equals the per-key sum, with at
        # least one real compile attributed to every thread's region.
        total = sent.compiles()
        assert total == sum(v.get("warmup", 0) for v in per_key.values())
        assert all(v.get("warmup", 0) >= 1 for v in per_key.values())
        assert sent.recompiles() == 0
    finally:
        sent.close()


def test_host_transfer_guard_allow_is_reentrant():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from lux_tpu.analysis.sentinel import HostTransferError, HostTransferGuard

    x = jnp.arange(8)
    with HostTransferGuard("nested") as g:
        with g.allow():
            with g.allow():           # nested window: still open
                assert int(jax.device_get(x)[1]) == 1
            # Inner exit must not close the outer window.
            assert int(jax.device_get(x)[2]) == 2
        with pytest.raises(HostTransferError):
            jax.device_get(x)
        # An exception inside a window must not leak the allow depth.
        with pytest.raises(RuntimeError):
            with g.allow():
                raise RuntimeError("boom")
        with pytest.raises(HostTransferError):
            jax.device_get(x)
    assert int(jax.device_get(x)[0]) == 0
