"""End-to-end CLI tests (subprocess, forced-CPU, sharded via -parts)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from lux_tpu.graph import Graph, generate, write_lux

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(module, *args, timeout=180):
    env = dict(os.environ)
    env["LUX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", module, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )


@pytest.fixture(scope="module")
def graphs(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    g = generate.rmat(9, 8, seed=1)
    write_lux(str(d / "g.lux"), g)
    write_lux(str(d / "u.lux"), generate.undirected(g))
    rng = np.random.default_rng(0)
    u = rng.integers(0, 100, 800)
    i = rng.integers(100, 160, 800)
    w = rng.integers(1, 6, 800).astype(np.int32)
    gw = Graph.from_edges(np.r_[u, i], np.r_[i, u], nv=160, weights=np.r_[w, w])
    write_lux(str(d / "w.lux"), gw)
    return d


def test_cli_pagerank_check(graphs):
    r = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "5", "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout and "ELAPSED TIME" in r.stdout


def test_cli_telemetry_flags(graphs, tmp_path):
    import json

    mpath = str(tmp_path / "metrics.jsonl")
    tpath = str(tmp_path / "trace.jsonl")
    r = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "4",
        "-metrics", mpath, "-trace", tpath,
    )
    assert r.returncode == 0, r.stderr
    runs = [json.loads(line) for line in open(mpath)]
    assert runs and runs[-1]["num_iters"] == 4
    assert len(runs[-1]["iterations"]) == 4
    assert runs[-1]["compile_s"] > 0 and runs[-1]["execute_s"] > 0
    events = [json.loads(line) for line in open(tpath)]
    assert sum(e["ph"] == "B" for e in events) == \
        sum(e["ph"] == "E" for e in events) > 0
    # the run report table goes to the lux.perf logger on stderr
    assert "{lux.perf}" in r.stderr and "run report:" in r.stderr


def test_cli_telemetry_verbose_push(graphs, tmp_path):
    import json

    mpath = str(tmp_path / "metrics.jsonl")
    r = run_cli(
        "lux_tpu.models.components",
        "-file", str(graphs / "u.lux"), "-verbose",
        "--metrics", mpath,  # double-dash alias
    )
    assert r.returncode == 0, r.stderr
    run = [json.loads(line) for line in open(mpath)][-1]
    assert run["engine"] == "push" and run["num_iters"] > 0
    # the verbose loop records per-iteration frontier sizes
    assert all("frontier" in rec for rec in run["iterations"])


def test_cli_pagerank_sharded(graphs):
    r = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "5", "-parts", "8", "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout


def test_cli_sssp_and_components(graphs):
    r = run_cli(
        "lux_tpu.models.sssp",
        "-file", str(graphs / "u.lux"), "-start", "0", "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout and "iterations =" in r.stdout
    r = run_cli(
        "lux_tpu.models.components",
        "-file", str(graphs / "u.lux"), "-check", "-parts", "2",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout


def test_cli_sharded_verbose_per_part(graphs):
    # VERDICT r2 #7: sharded -verbose must print a per-shard breakdown
    # (the reference's per-GPU activeNodes/loadTime/compTime/updateTime,
    # sssp/sssp_gpu.cu:516-518). Phases are separately dispatched; the
    # walls are mesh-lockstep, the activeNodes/edges counters per shard.
    r = run_cli(
        "lux_tpu.models.sssp",
        "-file", str(graphs / "u.lux"), "-start", "0", "-parts", "4",
        "-verbose", "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout
    for p in range(4):
        assert f"part {p}: activeNodes" in r.stdout, r.stdout
    line = next(l for l in r.stdout.splitlines() if "part 0:" in l)
    for field in ("edges", "loadTime", "compTime", "updateTime"):
        assert field in line, line


def test_cli_sharded_pull_verbose_phases(graphs):
    # Sharded pull (flat + tiled) -verbose: separately-dispatched phase
    # walls per iteration (exchange/comp/update; tiled adds strips/tail).
    r = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "2", "-parts", "4",
        "-verbose", "-layout", "flat",
    )
    assert r.returncode == 0, r.stderr
    assert "exchange" in r.stdout and "update" in r.stdout, r.stdout
    r = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "2", "-parts", "4",
        "-verbose",
    )
    assert r.returncode == 0, r.stderr
    assert "strips" in r.stdout and "tail" in r.stdout, r.stdout


def test_cli_colfilter(graphs):
    r = run_cli(
        "lux_tpu.models.colfilter",
        "-file", str(graphs / "w.lux"), "-ni", "3", "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout


def test_cli_colfilter_unweighted_graph_fails_cleanly(graphs):
    r = run_cli(
        "lux_tpu.models.colfilter", "-file", str(graphs / "g.lux"), "-ni", "3"
    )
    assert r.returncode == 1
    assert "weighted" in r.stderr


def test_cli_save_resume(graphs, tmp_path):
    ck = str(tmp_path / "ck.npz")
    r = run_cli(
        "lux_tpu.models.sssp",
        "-file", str(graphs / "u.lux"), "-start", "0", "-ni", "2",
        "-save", ck,
    )
    assert r.returncode == 0, r.stderr
    r = run_cli(
        "lux_tpu.models.sssp",
        "-file", str(graphs / "u.lux"), "-start", "0", "-resume", ck,
        "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout


def test_cli_pagerank_tiled_default_and_flat_override(graphs):
    """-layout auto (default) routes SpMV-shaped programs through the
    tiled hybrid executor (VERDICT r1: the benched fast path must be
    reachable from the apps), caching the plan next to the graph."""
    r = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "5", "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout
    assert "hybrid plan" in r.stderr
    plans = [p for p in os.listdir(graphs) if ".plan_" in p]
    assert plans, "plan cache file not written next to the graph"
    # Second run loads the cached plan (no re-planning log line).
    r2 = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "5", "-check",
    )
    assert r2.returncode == 0, r2.stderr
    assert "[PASS]" in r2.stdout
    # Flat override still works and passes the same check.
    r3 = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "5", "-check",
        "-layout", "flat",
    )
    assert r3.returncode == 0, r3.stderr
    assert "[PASS]" in r3.stdout
    assert "hybrid plan" not in r3.stderr


def test_cli_pagerank_tiled_sharded(graphs):
    """-parts 8 + tiled layout = ShardedTiledExecutor on the CPU mesh."""
    r = run_cli(
        "lux_tpu.models.pagerank",
        "-file", str(graphs / "g.lux"), "-ni", "5", "-parts", "8",
        "-layout", "tiled", "-check",
    )
    assert r.returncode == 0, r.stderr
    assert "[PASS]" in r.stdout
    assert "hybrid plan" in r.stderr


def test_cli_layout_tiled_rejects_non_spmv(graphs):
    r = run_cli(
        "lux_tpu.models.colfilter",
        "-file", str(graphs / "w.lux"), "-ni", "2", "-layout", "tiled",
    )
    assert r.returncode != 0
    assert "not SpMV-shaped" in r.stderr
