"""Collaborative filtering parity + training-progress tests."""

import numpy as np
import pytest

from lux_tpu.engine.pull import PullExecutor
from lux_tpu.engine.pull_sharded import ShardedPullExecutor
from lux_tpu.graph import Graph, generate
from lux_tpu.models.colfilter import (
    CollaborativeFiltering,
    reference_colfilter,
    rmse,
)
from lux_tpu.parallel.mesh import make_mesh


def bipartite_ratings(n_users=60, n_items=40, ne=800, seed=0):
    """users 0..n_users-1 rate items n_users..n_users+n_items-1; edges in
    both directions so both sides update (the reference treats the graph
    as one vertex space)."""
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, size=ne)
    i = rng.integers(n_users, n_users + n_items, size=ne)
    w = rng.integers(1, 6, size=ne).astype(np.int32)
    src = np.concatenate([u, i])
    dst = np.concatenate([i, u])
    ww = np.concatenate([w, w])
    return Graph.from_edges(src, dst, nv=n_users + n_items, weights=ww)


@pytest.mark.parametrize("strategy", ["rowptr", "segment"])
def test_cf_parity_single_device(strategy):
    g = bipartite_ratings()
    ex = PullExecutor(g, CollaborativeFiltering(), sum_strategy=strategy)
    got = np.asarray(ex.run(5))
    want = reference_colfilter(g, 5)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-7)


def test_cf_parity_sharded():
    g = bipartite_ratings(seed=2)
    ex = ShardedPullExecutor(g, CollaborativeFiltering(), mesh=make_mesh(8))
    got = ex.gather_values(ex.run(5))
    want = reference_colfilter(g, 5)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-7)


def test_cf_parity_edge_chunked():
    # The NetFlix-scale path: contributions never materialize beyond one
    # (C, K) chunk. A tiny chunk forces many windows, exercising the
    # boundary gather + double-single chunk-prefix rebase.
    g = bipartite_ratings(seed=5)
    flat = PullExecutor(g, CollaborativeFiltering(), edge_chunk=0)
    chunked = PullExecutor(g, CollaborativeFiltering(), edge_chunk=128)
    a = np.asarray(flat.run(5))
    b = np.asarray(chunked.run(5))
    np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(
        b, reference_colfilter(g, 5), rtol=1e-4, atol=1e-7
    )


def test_edge_chunked_scalar_program():
    # Chunked execution is program-generic for sum combiners: PageRank
    # (scalar values, no weights) must agree with the flat engine.
    from lux_tpu.models import PageRank

    g = generate.rmat(10, 8, seed=3)
    flat = PullExecutor(g, PageRank(), edge_chunk=0)
    chunked = PullExecutor(g, PageRank(), edge_chunk=512)
    np.testing.assert_allclose(
        np.asarray(chunked.run(5)), np.asarray(flat.run(5)),
        rtol=5e-5, atol=1e-9,
    )


def test_edge_chunked_dst_slice_parity(monkeypatch):
    # The dst-slice gather (per-chunk dynamic_slice band instead of a
    # full-table gather — the big-table-cliff fix) must be numerically
    # identical to the full gather for both K-vector and scalar programs.
    from lux_tpu.models import PageRank

    monkeypatch.setenv("LUX_DST_SLICE", "1")
    g = bipartite_ratings(seed=5)
    sliced = PullExecutor(g, CollaborativeFiltering(), edge_chunk=128)
    assert sliced._dst_span > 0, "dst-slice path not enabled"
    monkeypatch.setenv("LUX_DST_SLICE", "0")
    full = PullExecutor(g, CollaborativeFiltering(), edge_chunk=128)
    assert full._dst_span == 0
    np.testing.assert_array_equal(
        np.asarray(sliced.run(5)), np.asarray(full.run(5))
    )

    monkeypatch.setenv("LUX_DST_SLICE", "1")
    gp = generate.rmat(10, 8, seed=3)
    sliced = PullExecutor(gp, PageRank(), edge_chunk=512)
    assert sliced._dst_span > 0
    np.testing.assert_allclose(
        np.asarray(sliced.run(5)),
        np.asarray(PullExecutor(gp, PageRank(), edge_chunk=0).run(5)),
        rtol=5e-5, atol=1e-9,
    )


def test_edge_chunked_auto_threshold(monkeypatch):
    # Auto mode picks chunked exactly when the flat (ne, K) contribution
    # array would cross LUX_EDGE_CHUNK_BYTES.
    g = bipartite_ratings(seed=7)
    flat_bytes = g.ne * 20 * 4
    monkeypatch.setenv("LUX_EDGE_CHUNK_BYTES", str(flat_bytes + 1))
    assert PullExecutor(g, CollaborativeFiltering()).edge_chunk == 0
    monkeypatch.setenv("LUX_EDGE_CHUNK_BYTES", str(flat_bytes - 1))
    ex = PullExecutor(g, CollaborativeFiltering())
    assert ex.edge_chunk > 0
    np.testing.assert_allclose(
        np.asarray(ex.run(3)), reference_colfilter(g, 3),
        rtol=1e-4, atol=1e-7,
    )


def test_edge_chunked_src_band_parity(monkeypatch):
    # Source-band gathers (per-chunk lax.cond; the bipartite item-side
    # src slice, PERF.md round-2 lever) must be numerically identical to
    # full-table src gathers. Tiny chunks make user-dst chunks pure
    # item-source (narrow band) while item-dst chunks stay wide.
    from lux_tpu.engine.pull import _src_slice_plan

    g = bipartite_ratings(seed=9)
    monkeypatch.setenv("LUX_SRC_SLICE", "1")
    banded = PullExecutor(g, CollaborativeFiltering(), edge_chunk=128)
    monkeypatch.setenv("LUX_SRC_SLICE", "0")
    full = PullExecutor(g, CollaborativeFiltering(), edge_chunk=128)
    assert full._src_span == 0
    np.testing.assert_array_equal(
        np.asarray(banded.run(5)), np.asarray(full.run(5))
    )
    # The plan itself: at least the user-dst chunks must qualify.
    span, src_lo, flags = _src_slice_plan(
        g.col_src, g.ne, 128, g.nv, row_bytes=1 << 20
    )
    assert span == 0 or flags.any()


def test_boundary_dense_auto_chunk_degrades(monkeypatch):
    # A graph whose rows are nearly all empty packs too many row
    # boundaries into one edge window; the AUTO path must degrade
    # (larger windows, then the flat engine) instead of failing
    # (ADVICE r2). An explicit edge_chunk keeps the hard error.
    from lux_tpu.models import PageRank

    g = generate.star_graph(1000)   # ne=999 < nv+1 boundaries
    monkeypatch.setenv("LUX_EDGE_CHUNK_BYTES", "1")  # force auto-chunked
    ex = PullExecutor(g, PageRank())
    assert ex.edge_chunk == 0       # degraded to flat, not an error
    np.testing.assert_allclose(
        np.asarray(ex.run(3)),
        np.asarray(PullExecutor(g, PageRank(), edge_chunk=0).run(3)),
        rtol=5e-5, atol=1e-9,
    )
    with pytest.raises(ValueError, match="does not compress"):
        PullExecutor(g, PageRank(), edge_chunk=64)


def test_cf_requires_weights():
    g = generate.gnp(50, 200, seed=1)  # unweighted
    with pytest.raises(ValueError):
        PullExecutor(g, CollaborativeFiltering())


def test_cf_training_reduces_rmse():
    g = bipartite_ratings(seed=3)
    ex = PullExecutor(g, CollaborativeFiltering())
    v0 = np.asarray(ex.init_values())
    v200 = np.asarray(ex.run(200))
    assert rmse(g, v200) < rmse(g, v0)
