"""Delta graphs + snapshot store: merge parity, edit semantics,
compaction round-trips, fingerprint stability."""

import threading

import numpy as np
import pytest

from lux_tpu.graph import (DeltaGraph, EdgeEdits, Graph, SnapshotStore,
                           generate)
from lux_tpu.graph.delta import _edge_keys, removed_edges
from lux_tpu.ops.segment import csc_counting_merge
from lux_tpu.utils import checkpoint


def _random_edits(g, rng, n_ins, n_del, weighted=False):
    ins = [
        (int(rng.integers(g.nv)), int(rng.integers(g.nv)))
        + ((int(rng.integers(1, 10)),) if weighted else ())
        for _ in range(n_ins)
    ]
    dels = []
    if n_del:
        eidx = rng.choice(g.ne, size=min(n_del, g.ne), replace=False)
        dels = [(int(g.col_src[e]), int(g.col_dst[e])) for e in eidx]
    return EdgeEdits.from_lists(insert=ins, delete=dels), ins, dels


def _naive_merge(g, ins, dels):
    """Reference comparator: mask deleted pairs, append sorted inserts,
    rebuild with Graph.from_edges (stable sort by dst)."""
    if dels:
        dk = np.unique(_edge_keys(
            np.array([d[0] for d in dels]), np.array([d[1] for d in dels]),
            g.nv))
        keep = ~np.isin(_edge_keys(g.col_src, g.col_dst, g.nv), dk)
    else:
        keep = np.ones(g.ne, dtype=bool)
    i_s = np.array([i[0] for i in ins], dtype=np.int64)
    i_d = np.array([i[1] for i in ins], dtype=np.int64)
    order = np.argsort(_edge_keys(i_s, i_d, g.nv), kind="stable")
    w = None
    if g.weighted:
        i_w = np.array([i[2] for i in ins], dtype=g.weights.dtype)
        w = np.concatenate([g.weights[keep], i_w[order]])
    return Graph.from_edges(
        np.concatenate([g.col_src[keep].astype(np.int64), i_s[order]]),
        np.concatenate([g.col_dst[keep].astype(np.int64), i_d[order]]),
        g.nv, weights=w,
    )


SEEDS = [
    ("rmat", lambda s: generate.rmat(7, 8, seed=s)),
    ("small_world", lambda s: generate.small_world(256, 6, 0.1, seed=s)),
]


@pytest.mark.parametrize("name,make", SEEDS, ids=[s[0] for s in SEEDS])
@pytest.mark.parametrize("kind", ["inserts", "deletes", "mixed", "empty"])
def test_merged_matches_naive_rebuild(name, make, kind):
    """Property: merged() is bitwise-equal to a from-scratch
    Graph.from_edges over the surviving edge list, for random insert-only,
    delete-only, mixed, and empty batches on both synthetic families."""
    rng = np.random.default_rng(hash((name, kind)) % 2**31)
    g = make(3)
    n = max(1, g.ne // 50)
    n_ins = n if kind in ("inserts", "mixed") else 0
    n_del = n if kind in ("deletes", "mixed") else 0
    ed, ins, dels = _random_edits(g, rng, n_ins, n_del)
    m = DeltaGraph.fresh(g).stack(ed).merged()
    ref = _naive_merge(g, ins, dels)
    assert m.nv == ref.nv and m.ne == ref.ne
    np.testing.assert_array_equal(m.row_ptr, ref.row_ptr)
    np.testing.assert_array_equal(m.col_src, ref.col_src)


def test_merged_weighted_parity():
    g = generate.gnp(200, 1500, seed=11, weighted=True)
    rng = np.random.default_rng(11)
    ed, ins, dels = _random_edits(g, rng, 20, 20, weighted=True)
    m = DeltaGraph.fresh(g).stack(ed).merged()
    ref = _naive_merge(g, ins, dels)
    np.testing.assert_array_equal(m.row_ptr, ref.row_ptr)
    np.testing.assert_array_equal(m.col_src, ref.col_src)
    np.testing.assert_array_equal(m.weights, ref.weights)


def test_empty_delta_returns_base_identity():
    """No pending edits -> merged() IS the base object (fingerprint and
    any cached executor state stay valid)."""
    g = generate.rmat(7, 8, seed=1)
    assert DeltaGraph.fresh(g).merged() is g


def test_delete_removes_all_parallel_copies():
    g = Graph.from_edges(np.array([0, 0, 1]), np.array([1, 1, 2]), 3)
    assert g.ne == 3
    m = DeltaGraph.fresh(g).stack(
        EdgeEdits.from_lists(delete=[(0, 1)])
    ).merged()
    assert m.ne == 1
    np.testing.assert_array_equal(m.col_src, [1])


def test_delete_then_reinsert_single_batch_keeps_edge():
    """Within one batch deletes apply before inserts: delete+insert of
    the same pair leaves exactly one copy."""
    g = Graph.from_edges(np.array([0, 1]), np.array([1, 2]), 3)
    m = DeltaGraph.fresh(g).stack(
        EdgeEdits.from_lists(insert=[(0, 1)], delete=[(0, 1)])
    ).merged()
    assert m.ne == 2
    keys = _edge_keys(m.col_src, m.col_dst, m.nv)
    assert (keys == 0 + 1 * 3).sum() == 1


def test_stacked_batches_delete_pending_insert():
    """A later batch's delete removes an earlier batch's pending insert."""
    g = Graph.from_edges(np.array([0]), np.array([1]), 4)
    dg = DeltaGraph.fresh(g)
    dg = dg.stack(EdgeEdits.from_lists(insert=[(2, 3)]))
    dg = dg.stack(EdgeEdits.from_lists(delete=[(2, 3)]))
    assert dg.merged().ne == 1


def test_stack_is_value_semantics():
    """stack() never mutates the receiver: a snapshot holding the old
    delta still merges to the old graph."""
    g = generate.gnp(100, 600, seed=7)
    d0 = DeltaGraph.fresh(g)
    d1 = d0.stack(EdgeEdits.from_lists(insert=[(1, 2)]))
    assert d0.merged() is g
    assert d1.merged().ne == g.ne + 1


def test_edits_validate_vertex_range():
    g = generate.gnp(50, 200, seed=3)
    with pytest.raises(ValueError, match="vertex ids outside"):
        DeltaGraph.fresh(g).stack(
            EdgeEdits.from_lists(insert=[(0, g.nv)])
        )


def test_weighted_base_requires_insert_weights():
    g = generate.gnp(50, 200, seed=3, weighted=True)
    with pytest.raises(ValueError, match="requires insert weights"):
        DeltaGraph.fresh(g).stack(EdgeEdits.from_lists(insert=[(0, 1)]))
    with pytest.raises(ValueError, match="unweighted base"):
        DeltaGraph.fresh(generate.gnp(50, 200, seed=3)).stack(
            EdgeEdits.from_lists(insert=[(0, 1, 5)])
        )


def test_removed_edges_reports_actual_copies():
    g = Graph.from_edges(np.array([0, 0, 1]), np.array([1, 1, 2]), 3)
    rs, rd, _ = removed_edges(g, np.array([0]), np.array([1]))
    assert list(rs) == [0, 0] and list(rd) == [1, 1]
    rs, rd, _ = removed_edges(g, np.array([2]), np.array([0]))  # absent
    assert rs.size == 0


def test_csc_counting_merge_weight_mismatch_raises():
    g = generate.gnp(20, 60, seed=1, weighted=True)
    keep = np.ones(g.ne, dtype=bool)
    ins = np.array([1], dtype=np.int64)
    with pytest.raises(ValueError):
        csc_counting_merge(g.row_ptr, g.col_src, g.weights, keep,
                           ins, ins, None, g.nv)


# -- snapshot store -------------------------------------------------------


def test_snapshot_store_versions_and_fingerprints():
    g = generate.rmat(7, 8, seed=5)
    st = SnapshotStore(g)
    s0 = st.current()
    assert s0.version == 0 and s0.graph is g
    s1 = st.apply(EdgeEdits.from_lists(insert=[(1, 2), (3, 4)]))
    assert st.current() is s1 and s1.version == 1
    assert s1.fingerprint != s0.fingerprint
    assert s1.graph.ne == g.ne + 2
    assert st.get(0) is s0
    with pytest.raises(KeyError):
        st.get(7)
    hist = st.history()
    assert [h["version"] for h in hist] == [0, 1]
    st.drain_compactions()


def test_compaction_preserves_fingerprint_and_graph():
    """Compaction re-anchors the delta on its merged CSC: the fingerprint
    (and the graph object readers hold) must not change — the round-trip
    is a bitwise no-op."""
    g = generate.rmat(7, 8, seed=6)
    st = SnapshotStore(g)
    s1 = st.apply(EdgeEdits.from_lists(
        insert=[(0, 1), (2, 3)], delete=[(int(g.col_src[0]),
                                          int(g.col_dst[0]))]))
    g1 = s1.graph
    fp1 = s1.fingerprint
    s1.compact()
    assert s1.compacted
    assert s1.graph is g1
    assert s1.fingerprint == fp1
    assert s1.delta.delta_edges == 0
    # further edits stack on the compacted anchor identically
    s2_graph = s1.delta.stack(
        EdgeEdits.from_lists(insert=[(5, 6)])).merged()
    assert s2_graph.ne == g1.ne + 1
    st.drain_compactions()


def test_background_compaction_triggers_past_ratio(monkeypatch):
    monkeypatch.setenv("LUX_DELTA_COMPACT_RATIO", "0.0")
    g = generate.gnp(100, 500, seed=9)
    st = SnapshotStore(g)
    fired = threading.Event()
    s1 = st.apply(EdgeEdits.from_lists(insert=[(1, 2)]),
                  on_compact=lambda s: fired.set())
    assert fired.wait(10.0), "background compaction never ran"
    st.drain_compactions()
    assert s1.compacted
    assert s1.fingerprint == checkpoint.fingerprint_hex(s1.graph)


def test_no_compaction_below_ratio(monkeypatch):
    monkeypatch.setenv("LUX_DELTA_COMPACT_RATIO", "0.5")
    g = generate.gnp(100, 500, seed=9)
    st = SnapshotStore(g)
    s1 = st.apply(EdgeEdits.from_lists(insert=[(1, 2)]))
    st.drain_compactions()
    assert not s1.compacted and s1.delta.delta_edges == 1
