"""Engine performance observatory (LUX_ENGOBS): the remote-read index,
phase-fenced exchange/compute timing on the sharded engines, the
zero-overhead-off contract (sentinel-asserted), the bench regression
gate, and the supporting metrics/statusz surfaces."""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from lux_tpu.engine.pull_sharded import ShardedPullExecutor
from lux_tpu.engine.push import ShardedPushExecutor
from lux_tpu.graph import generate
from lux_tpu.models.pagerank import PageRank, reference_pagerank
from lux_tpu.models.sssp import SSSP, reference_sssp
from lux_tpu.obs import engobs, metrics, report
from lux_tpu.obs.spans import SPAN_BUCKETS
from lux_tpu.parallel.mesh import make_mesh
from lux_tpu.parallel.shard import ShardedGraph

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)


def _load_bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _last_run(path):
    return report.read_last(path)


# -- remote-read index (exchange ledger input) ----------------------------


def test_remote_read_counts_matches_bruteforce():
    g = generate.gnp(300, 2400, seed=31)
    sg = ShardedGraph.build(g, 4)
    counts = sg.remote_read_counts()
    assert counts is not None and counts.shape == (4, 4)
    # Brute force: part q's distinct gathered rows, bucketed by owner.
    want = np.zeros((4, 4), dtype=np.int64)
    for q in range(4):
        rows = np.unique(sg.src_pidx[q][sg.edge_mask[q]])
        for r in rows:
            want[q, int(r) // sg.max_nv] += 1
    np.testing.assert_array_equal(counts, want)
    # Cached: second call returns the same object without recomputing.
    assert sg.remote_read_counts() is counts


def test_useful_exchange_prices_off_diagonal():
    g = generate.gnp(300, 2400, seed=32)
    sg = ShardedGraph.build(g, 4)
    got = engobs.useful_exchange(sg, row_bytes=8)
    assert got is not None
    counts = sg.remote_read_counts()
    useful = int(counts.sum() - counts.trace())
    assert got["useful_rows"] == useful
    assert got["exchanged_rows"] == 4 * 3 * sg.max_nv
    assert got["useful_bytes_per_iter"] == useful * 8
    assert 0.0 < got["ratio"] <= 1.0


# -- phase-fenced runs on the 8-virtual-device mesh -----------------------


def test_pull_sharded_phase_split_recorded(tmp_path, monkeypatch):
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    monkeypatch.setenv("LUX_ENGOBS", "1")
    engobs.reset()
    g = generate.gnp(400, 3200, seed=33)
    ex = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(4))
    got = ex.gather_values(ex.run(6))
    np.testing.assert_allclose(got, reference_pagerank(g, 6), rtol=2e-5)

    run = _last_run(mpath)
    assert run["engine"] == "pull_sharded" and run["parts"] == 4
    ph = run["phases"]
    assert ph["exchange_s"] > 0 and ph["compute_s"] > 0
    assert 0.0 < ph["exchange_frac"] < 1.0
    assert len(run["iterations"]) == 6
    assert all(r["exchange_s"] >= 0 and r["compute_s"] > 0
               for r in run["iterations"])
    # Exchange ledger rode along: useful bytes never exceed exchanged.
    assert 0.0 < run["useful_ratio"] <= 1.0
    assert run["useful_bytes_per_iter"] <= run["exchange_bytes_per_iter"]
    assert run["hbm_bytes_per_iter"] > 0
    # /statusz's latest-table view carries the same split.
    latest = engobs.latest()["pull_sharded"]
    assert latest["run_exchange_frac"] == pytest.approx(
        ph["exchange_frac"])


def test_push_sharded_phase_split_and_frontier(tmp_path, monkeypatch):
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    monkeypatch.setenv("LUX_ENGOBS", "1")
    engobs.reset()
    g = generate.gnp(300, 2000, seed=34, weighted=True)
    ex = ShardedPushExecutor(g, SSSP(), mesh=make_mesh(4))
    state, iters = ex.run(start=0)
    np.testing.assert_allclose(
        ex.gather_values(state), reference_sssp(g, 0), rtol=1e-6)

    run = _last_run(mpath)
    assert run["engine"] == "push_sharded"
    assert run["phases"]["exchange_s"] > 0
    assert run["phases"]["compute_s"] > 0
    # Every phase-fenced iteration carries frontier + branch.
    assert len(run["iterations"]) == run["num_iters"] == iters
    for r in run["iterations"]:
        assert r["frontier"] is not None
        assert r["branch"] == "dense" or r["branch"].startswith("sparse")
    assert run["iterations"][-1]["frontier"] == 0


def test_engobs_off_is_default_fused_path_with_zero_recompiles(monkeypatch):
    from lux_tpu.analysis.sentinel import RecompileSentinel

    monkeypatch.delenv("LUX_ENGOBS", raising=False)
    assert not engobs.enabled()
    sent = RecompileSentinel("engobs-off")
    if not sent.available:
        sent.close()
        pytest.skip("jax monitoring hook unavailable in this jax")
    try:
        g = generate.gnp(400, 3200, seed=33)
        ex = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(4))
        with sent.expect("pull"):
            base = ex.gather_values(ex.run(6))
        with sent.watch("pull"):
            again = ex.gather_values(ex.run(6))
        sent.assert_zero_recompiles()
        # Off path is the exact pre-observatory fused program: bitwise
        # stable across runs, no phase executables ever built.
        np.testing.assert_array_equal(np.asarray(base), np.asarray(again))
        assert not hasattr(ex, "_pjits")
    finally:
        sent.close()

    # Measurement mode changes dispatch granularity, not the math.
    monkeypatch.setenv("LUX_ENGOBS", "1")
    ex2 = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(4))
    phased = ex2.gather_values(ex2.run(6))
    np.testing.assert_allclose(phased, base, rtol=1e-6, atol=1e-12)


# -- bench regression gate ------------------------------------------------


def _doc(metrics_map, **ctx):
    context = {"mode": "fast", "scale": 10, "ef": 8, "layout": "tiled",
               "platform": "cpu", "exchange": "full", "device_kind": "cpu"}
    context.update(ctx)
    return {"schema": "bench_gate.v1", "mode": context["mode"],
            "context": context, "cmd": "test", "metrics": metrics_map}


def test_bench_gate_compare_directions():
    bg = _load_bench_gate()
    base = {"headline_gteps": 1.0, "sssp_rmat.ms_per_iter": 10.0}
    # Better on both axes (throughput up, latency down) passes.
    rows, ok = bg.compare(
        {"headline_gteps": 1.2, "sssp_rmat.ms_per_iter": 8.0}, base, 0.1)
    assert ok and all(r["ok"] for r in rows)
    by = {r["metric"]: r for r in rows}
    assert by["headline_gteps"]["better"] == "higher"
    assert by["sssp_rmat.ms_per_iter"]["better"] == "lower"
    # Throughput collapse beyond tolerance fails.
    _, ok = bg.compare(
        {"headline_gteps": 0.5, "sssp_rmat.ms_per_iter": 10.0}, base, 0.1)
    assert not ok
    # Latency blowup beyond tolerance fails.
    _, ok = bg.compare(
        {"headline_gteps": 1.0, "sssp_rmat.ms_per_iter": 15.0}, base, 0.1)
    assert not ok
    # Within tolerance passes in both directions.
    rows, ok = bg.compare(
        {"headline_gteps": 0.95, "sssp_rmat.ms_per_iter": 10.5}, base, 0.1)
    assert ok and len(rows) == 2


def test_bench_gate_legacy_baseline_fails_closed():
    bg = _load_bench_gate()
    cur = _doc({})["context"]
    ok, reason = bg.comparable(cur, {"mode": None, "scale": 16,
                                     "ef": None, "layout": "tiled",
                                     "platform": None})
    assert not ok and "mode" in reason
    ok, _ = bg.comparable(cur, dict(cur))
    assert ok


def test_bench_gate_seeded_regression_exits_nonzero(tmp_path):
    base = _doc({"headline_gteps": 1.0, "achieved_gbps": 100.0})
    cur = _doc({"headline_gteps": 0.4, "achieved_gbps": 100.0})
    bpath, cpath = str(tmp_path / "base.json"), str(tmp_path / "cur.json")
    with open(bpath, "w") as f:
        json.dump(base, f)
    with open(cpath, "w") as f:
        json.dump(cur, f)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "--replay", cpath, "--baseline", bpath, "--tol", "0.25"],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1]
                         .split("BENCH_GATE ", 1)[1])
    assert summary["ok"] is False and summary["compared"] == 2
    assert "REGRESSED" in proc.stdout
    # Same doc replayed against itself passes with rc 0.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "--replay", bpath, "--baseline", bpath],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_bench_r06_artifact_is_gate_lineage():
    path = os.path.join(REPO, "BENCH_r06.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["schema"] == "bench_gate.v1"
    assert doc["context"]["mode"] == "fast"
    assert doc["metrics"]["headline_gteps"] > 0
    assert "roofline" in doc


# -- fine-grained span buckets --------------------------------------------


def test_span_buckets_resolve_submillisecond_phases():
    metrics.reset()
    h = metrics.histogram("lux_span_seconds", {"span": "t.exchange"},
                          buckets=SPAN_BUCKETS)
    for _ in range(100):
        h.observe(1.5e-4)          # 150 us: a realistic exchange fence
    q50 = h.quantile(0.5)
    # The 2-5-10 ladder brackets 150 us by [100 us, 200 us]: the estimate
    # may not leave that bucket (the old decade ladder put everything
    # below 1 ms into one bin and reported ~ms-scale medians).
    assert 1e-4 <= q50 <= 2e-4
    h2 = metrics.histogram("lux_span_seconds", {"span": "t.compute"},
                           buckets=SPAN_BUCKETS)
    for _ in range(100):
        h2.observe(3.0e-5)         # 30 us compute bracket
    assert 2e-5 <= h2.quantile(0.5) <= 5e-5


# -- prometheus rendering of the new per-iteration metrics ----------------


def test_render_prometheus_escapes_mesh_shape_labels():
    metrics.reset()
    metrics.gauge("lux_exchange_useful_ratio",
                  {"engine": 'pull"shard\\ed\n2x4'}).set(0.5)
    out = metrics.render_prometheus()
    line = next(l for l in out.splitlines()
                if l.startswith("lux_exchange_useful_ratio{"))
    assert '\\"' in line and "\\\\" in line and "\\n" in line
    assert "\n2x4" not in line              # raw newline must not survive


def test_counter_handles_survive_hot_swap():
    # A hot-swap tears down engines and mints fresh recorder handles; the
    # registry must hand back the same family so counters stay monotone.
    metrics.reset()
    c1 = metrics.counter("lux_iterations_total", {"engine": "pull_sharded"})
    c1.inc(5)
    c2 = metrics.counter("lux_iterations_total", {"engine": "pull_sharded"})
    assert c2 is c1
    c2.inc(3)
    assert c1.value == 8


# -- /statusz mesh block --------------------------------------------------


@pytest.mark.slow
def test_statusz_mesh_block_schema_with_and_without_mesh(monkeypatch):
    from lux_tpu.serve import ServeConfig, Session

    def cfg(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("window_s", 0.01)
        kw.setdefault("max_queue", 64)
        kw.setdefault("pagerank_iters", 4)
        return ServeConfig(**kw)

    g = generate.gnp(200, 1200, seed=35)
    engobs.reset()
    engobs.note("pull_sharded", run_exchange_frac=0.4, useful_ratio=0.7)
    monkeypatch.setenv("LUX_SERVE_MESH", "2x2")
    with Session(g, cfg(), warm=False) as s:
        m = s.statusz()["mesh"]
        assert set(m) >= {"spec", "shape", "num_parts", "pool_entries",
                          "plans", "engobs"}
        assert m["num_parts"] == 4
        assert m["engobs"]["pull_sharded"]["useful_ratio"] == 0.7
        json.dumps(m)               # must stay JSON-serializable
    monkeypatch.delenv("LUX_SERVE_MESH")
    with Session(g, cfg(), warm=False) as s:
        m = s.statusz()["mesh"]
        assert m["num_parts"] == 1
        assert isinstance(m["engobs"], dict)   # schema stable off-mesh
        json.dumps(m)
