"""Compacted needed-rows exchange: plan edge cases, full-vs-compact
parity, and the P=1 passthrough (8-device virtual mesh via conftest)."""

import numpy as np
import pytest

from lux_tpu.engine.pull_sharded import ShardedPullExecutor
from lux_tpu.engine.push import (
    ShardedMultiSourcePushExecutor,
    ShardedPushExecutor,
)
from lux_tpu.graph import generate
from lux_tpu.graph.partition import ExchangePlan
from lux_tpu.models.pagerank import PageRank
from lux_tpu.models.sssp import SSSP, reference_sssp
from lux_tpu.parallel.mesh import make_mesh
from lux_tpu.parallel.shard import ShardedGraph, resolve_exchange


def _empty_needs(P):
    return [[np.zeros(0, np.int64)] * P for _ in range(P)]


# -- plan construction edge cases -----------------------------------------


def test_plan_zero_remote_readers():
    """No part reads anything remote: every table slot is a sentinel and
    the (minimum-capacity) plan still beats the full all-gather."""
    P, max_units = 4, 64
    plan = ExchangePlan.from_needs(_empty_needs(P), max_units, P)
    assert plan.counts.sum() == 0
    assert plan.capacity == 8  # max(required=0, 1) rounded to the lane 8
    assert plan.profitable
    assert (plan.send_units == max_units).all()          # sender sentinel
    assert (plan.recv_pos == P * max_units).all()        # trash-row slot
    assert plan.exchanged_units_per_iter == P * (P - 1) * 8


def test_plan_empty_parts():
    """A part with no vertices neither sends nor receives: its counts
    row and column stay zero and its table slots stay sentinels."""
    P, max_units = 4, 16
    needs = _empty_needs(P)
    # Parts 0..2 each read rows [0, 1] of the next part; part 3 is empty.
    for q in range(3):
        needs[q][(q + 1) % 3] = np.array([0, 1], dtype=np.int64)
    plan = ExchangePlan.from_needs(needs, max_units, P)
    assert plan.counts[3].sum() == 0 and plan.counts[:, 3].sum() == 0
    send = plan.send_units.reshape(P, P, plan.capacity)
    recv = plan.recv_pos.reshape(P, P, plan.capacity)
    assert (send[3] == max_units).all()
    assert (recv[3] == P * max_units).all()
    # The populated pair round-trips: sender rows scatter to the flat
    # positions the compute bodies index.
    np.testing.assert_array_equal(send[1, 0, :2], [0, 1])
    np.testing.assert_array_equal(recv[0, 1, :2],
                                  [1 * max_units, 1 * max_units + 1])


def test_plan_all_remote_worst_case_unprofitable():
    """Every part reads every row of every other part: capacity can't
    beat max_units, so the plan is unprofitable and resolve_exchange
    downgrades to the full path."""
    P, max_units = 4, 16
    needs = [[np.arange(max_units, dtype=np.int64)] * P for _ in range(P)]
    plan = ExchangePlan.from_needs(needs, max_units, P)
    assert plan.capacity >= max_units
    assert not plan.profitable


def test_resolve_falls_back_on_dense_graph(monkeypatch):
    """gnp's uniform sources read ~every remote row: the resolved mode
    must be full with no plan (and the executor must still build)."""
    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    g = generate.gnp(400, 12000, seed=3)
    sg = ShardedGraph.build(g, 8)
    mode, plan = resolve_exchange(sg)
    assert (mode, plan) == ("full", None)
    ex = ShardedPushExecutor(g, SSSP(), mesh=make_mesh(8))
    assert ex.exchange_mode == "full" and ex._xplan is None


def test_plan_capacity_overflow_fails_loudly():
    P, max_units = 4, 16
    needs = _empty_needs(P)
    needs[0][1] = np.arange(10, dtype=np.int64)
    with pytest.raises(ValueError, match="refusing to truncate"):
        ExchangePlan.from_needs(needs, max_units, P, capacity=4)
    # An explicit capacity that does fit is honored un-rounded.
    plan = ExchangePlan.from_needs(needs, max_units, P, capacity=11)
    assert plan.capacity == 11


def test_plan_counts_match_remote_read_counts():
    """from_src_pidx prices with the exact matrix the exchange ledger
    reads (remote_read_counts), so the two can never disagree."""
    g = generate.halo(4, 128, hubs=8)
    sg = ShardedGraph.build(g, 4)
    plan = ExchangePlan.from_src_pidx(
        sg.src_pidx, sg.edge_mask, sg.max_nv, 4)
    np.testing.assert_array_equal(plan.counts, sg.remote_read_counts())


# -- executor parity and passthrough ---------------------------------------


def _run_both(monkeypatch, build, run):
    out = {}
    for mode in ("full", "compact"):
        monkeypatch.setenv("LUX_EXCHANGE", mode)
        ex = run_ex = build()
        out[mode] = (ex, run(run_ex))
    return out


@pytest.mark.parametrize("app", ["sssp", "components"])
def test_push_parity_full_vs_compact(monkeypatch, app):
    from lux_tpu.models.components import ConnectedComponents

    g = generate.halo(8, 128, hubs=8, weighted=True)
    mesh = make_mesh(8)
    prog, kw = ((SSSP(), {"start": 0}) if app == "sssp"
                else (ConnectedComponents(), {}))
    out = _run_both(
        monkeypatch,
        lambda: ShardedPushExecutor(g, prog, mesh=mesh),
        lambda ex: ex.gather_values(ex.run(**kw)[0]),
    )
    assert out["compact"][0]._xplan is not None, "compact did not engage"
    np.testing.assert_array_equal(out["full"][1], out["compact"][1])
    # Compact must also price strictly below the full exchange.
    assert (out["compact"][0].exchange_bytes_per_iter()
            < out["full"][0].exchange_bytes_per_iter())


def test_pull_parity_full_vs_compact(monkeypatch):
    g = generate.halo(8, 128, hubs=8)
    mesh = make_mesh(8)
    out = _run_both(
        monkeypatch,
        lambda: ShardedPullExecutor(g, PageRank(), mesh=mesh),
        lambda ex: ex.gather_values(ex.run(6, flush_every=0)),
    )
    assert out["compact"][0]._xplan is not None, "compact did not engage"
    np.testing.assert_array_equal(out["full"][1], out["compact"][1])


def test_multi_source_p1_passthrough(monkeypatch):
    """P=1 under LUX_EXCHANGE=compact is a no-op: full mode, no plan, no
    tables — and answers still match the host oracle."""
    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    g = generate.gnp(300, 2400, seed=11, weighted=True)
    roots = [0, 7, 55]
    ex = ShardedMultiSourcePushExecutor(g, SSSP(), k=3, mesh=make_mesh(1))
    assert ex.exchange_mode == "full" and ex._xplan is None
    assert "xch_send" not in ex._dg
    state, _ = ex.run(roots)
    got = ex.gather_values(state)
    for lane, r in enumerate(roots):
        np.testing.assert_array_equal(got[:, lane], reference_sssp(g, r))


def test_multi_source_compact_bytes_measured(monkeypatch):
    """Satellite 2: the multi-source executor's exchange_bytes_per_iter
    reports the measured packed figure when compact, not the dense
    estimate."""
    g = generate.halo(8, 128, hubs=8, weighted=True)
    mesh = make_mesh(8)
    out = _run_both(
        monkeypatch,
        lambda: ShardedMultiSourcePushExecutor(g, SSSP(), k=2, mesh=mesh),
        lambda ex: ex.gather_values(ex.run([0, 300])[0]),
    )
    ex_c = out["compact"][0]
    assert ex_c._xplan is not None, "compact did not engage"
    np.testing.assert_array_equal(out["full"][1], out["compact"][1])
    assert (ex_c.exchange_bytes_per_iter()
            == ex_c._xplan.exchange_bytes_per_iter(5 * ex_c.k))
    assert (ex_c.exchange_bytes_per_iter()
            < out["full"][0].exchange_bytes_per_iter())


# -- self-downgrade coverage ----------------------------------------------


def test_released_edge_arrays_downgrade_logs_once(monkeypatch, caplog):
    """Releasing the host edge arrays before a plan exists leaves nothing
    to derive tables from: compact must self-downgrade to the full path
    and say so exactly once — silent coverage loss is the failure mode
    the log exists to prevent."""
    import logging

    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    g = generate.halo(4, 128, hubs=8)
    sg = ShardedGraph.build(g, 4)
    sg.release_edge_arrays()
    assert sg.exchange_plan() is None
    log = logging.getLogger("lux-test-downgrade")
    with caplog.at_level(logging.INFO, logger="lux-test-downgrade"):
        mode, plan = resolve_exchange(sg, log=log)
    assert (mode, plan) == ("full", None)
    records = [r for r in caplog.records
               if "falling back to full" in r.getMessage()]
    assert len(records) == 1
    assert "released" in records[0].getMessage()


def test_release_after_plan_keeps_compact(monkeypatch):
    """Release AFTER the plan was built: the cached tables are all the
    exchange needs, so compaction stays engaged."""
    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    g = generate.halo(4, 128, hubs=8)
    sg = ShardedGraph.build(g, 4)
    plan = sg.exchange_plan()
    assert plan is not None and plan.profitable
    sg.release_edge_arrays()
    mode, got = resolve_exchange(sg)
    assert mode == "compact" and got is plan


def test_serving_keys_carry_requested_mode(monkeypatch):
    """A dense graph downgrades every sharded engine to the full
    exchange, but pool keys still carry the REQUESTED mode — a warm
    full-mode engine from before a flag flip must never answer for a
    compact request, even when both would build the same program."""
    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    from lux_tpu.obs import metrics
    from lux_tpu.serve import ServeConfig, Session

    metrics.reset()
    g = generate.gnp(400, 12000, seed=3, weighted=True)
    cfg = ServeConfig(max_batch=4, window_s=0.01, max_queue=64,
                      pagerank_iters=4, mesh="8")
    with Session(g, cfg, warm=False) as s:
        np.testing.assert_array_equal(
            s.query("sssp", start=0, timeout=120)["values"],
            reference_sssp(g, 0))
        keys = s.pool.keys()
        assert keys, "no engine was built"
        assert all("compact" in k for k in keys)
        # ... while the engines themselves run the downgraded full path.
        for k in keys:
            ex = s.pool._engines[k]
            assert getattr(ex, "exchange_mode", "full") == "full"
            assert getattr(ex, "_xplan", None) is None
