"""luxlint exchange tier: the LUX401-403 plan verifier (exchck), the
LUX404-406 dataflow rules, artifact save/load round-trips, the registry
matrix gate, the serve-pool audit hook, the --exchange CLI, and the
span-hash --baseline ratchet.

Seeded-violation convention (tests/exch_fixtures/): each ``lux4NN_*.py``
module exposes ``PLANS`` or ``TRACES`` and must make
``luxlint --exchange`` exit 1 with exactly its own rule firing.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lux_tpu.analysis import exchck, ir  # noqa: E402
from lux_tpu.engine.program import EdgeCtx  # noqa: E402
from lux_tpu.engine.pull_sharded import ShardedPullExecutor  # noqa: E402
from lux_tpu.graph import generate, partition  # noqa: E402
from lux_tpu.models.pagerank import PageRank  # noqa: E402
from lux_tpu.obs import engobs, metrics  # noqa: E402
from lux_tpu.ops.segment import segment_reduce  # noqa: E402
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh  # noqa: E402
from lux_tpu.parallel.shard import ShardedGraph  # noqa: E402
from lux_tpu.serve.pool import EnginePool  # noqa: E402

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
LUXLINT = os.path.join(REPO, "tools", "luxlint.py")
EXCH_FIXTURES = os.path.join(TESTS, "exch_fixtures")


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, LUXLINT, *argv],
        capture_output=True, text=True, cwd=REPO,
    )


def _summary_line(stdout):
    lines = [l for l in stdout.splitlines() if l.startswith("LUXLINT ")]
    assert lines, stdout
    return json.loads(lines[-1][len("LUXLINT "):])


def _rules(result):
    return sorted({f.rule for f in result.findings})


def _hand_plan():
    """P=2, max_units=4, unit_rows=1, capacity=2: receiver 0 needs rows
    {1, 3} of sender 1, receiver 1 needs row {2} of sender 0."""
    return types.SimpleNamespace(
        num_parts=2, max_units=4, unit_rows=1, capacity=2,
        counts=np.array([[0, 2], [1, 0]], dtype=np.int64),
        send_units=np.array([[4, 4, 2, 4],
                             [1, 3, 4, 4]], dtype=np.int32),
        recv_pos=np.array([[8, 8, 5, 7],
                           [2, 8, 8, 8]], dtype=np.int32),
        profitable=True)


def _hand_view(**kw):
    kw.setdefault("remote_read_counts",
                  np.array([[0, 2], [1, 0]], dtype=np.int64))
    kw.setdefault("row_bytes", 8)
    kw.setdefault("declared_bytes_per_iter", 32)
    plan = kw.pop("plan", None) or _hand_plan()
    return exchck.plan_view(plan, **kw)


def _live_plan():
    g = generate.halo(8, 128, hubs=8)
    sg = ShardedGraph.build(g, 8)
    return sg, sg.exchange_plan()


# -- format mirror -------------------------------------------------------


def test_constants_mirror_partition():
    # exchck must stay loadable in a jax-free interpreter, so it mirrors
    # the artifact format instead of importing graph/partition.
    assert exchck.EXCH_ARRAYS == partition.EXCHANGE_PLAN_ARRAYS
    assert exchck.EXCH_FORMAT == partition.EXCHANGE_PLAN_FORMAT


# -- plan rules over hand-built views ------------------------------------


def test_hand_plan_is_clean():
    res = exchck.verify_exchange_plan(_hand_view(), "unit@clean")
    assert res.findings == [] and res.error is None


def test_structure_pad_zone_leak():
    plan = _hand_plan()
    plan.send_units[0, 3] = 1
    res = exchck.verify_exchange_plan(_hand_view(plan=plan), "unit@leak")
    assert _rules(res) == ["LUX401"]


def test_structure_diagonal_real_entry():
    plan = _hand_plan()
    plan.recv_pos[0, 0] = 3   # own-pair slot carries a real position
    res = exchck.verify_exchange_plan(_hand_view(plan=plan), "unit@diag")
    assert "LUX401" in _rules(res)


def test_structure_capacity_truncated():
    plan = _hand_plan()
    plan.counts[0, 1] = 3     # densest pair now needs 3 > capacity 2
    view = _hand_view(plan=plan, remote_read_counts=None)
    res = exchck.verify_exchange_plan(view, "unit@trunc")
    assert _rules(res) == ["LUX401"]
    assert "capacity" in res.findings[0].message


def test_coverage_misrouted_row():
    plan = _hand_plan()
    plan.recv_pos[0, 2] = 6   # sender 1 row 1 should land at 4 + 1 = 5
    res = exchck.verify_exchange_plan(_hand_view(plan=plan), "unit@misroute")
    assert _rules(res) == ["LUX402"]


def test_coverage_duplicate_send_row():
    plan = _hand_plan()
    plan.send_units[0 + 1, 0:2] = [1, 1]   # row 1 sent twice, row 3 never
    plan.recv_pos[0, 2:4] = [5, 5]
    res = exchck.verify_exchange_plan(_hand_view(plan=plan), "unit@dup")
    assert "LUX402" in _rules(res)


def test_coverage_conservation_against_remote_reads():
    view = _hand_view(
        remote_read_counts=np.array([[0, 2], [2, 0]], dtype=np.int64))
    res = exchck.verify_exchange_plan(view, "unit@conservation")
    assert _rules(res) == ["LUX402"]
    assert "remote-read index" in res.findings[0].message


def test_profitability_declared_drift():
    res = exchck.verify_exchange_plan(
        _hand_view(declared_bytes_per_iter=48), "unit@declared")
    assert _rules(res) == ["LUX403"]


def test_profitability_false_claim():
    plan = _hand_plan()
    plan.capacity = 4         # == max_units, yet still claims profitable
    plan.send_units = np.full((2, 8), 4, np.int32)
    plan.recv_pos = np.full((2, 8), 8, np.int32)
    plan.send_units[0, 4] = 2
    plan.send_units[1, 0:2] = [1, 3]
    plan.recv_pos[0, 4:6] = [5, 7]
    plan.recv_pos[1, 0] = 2
    view = exchck.plan_view(plan)
    res = exchck.verify_exchange_plan(view, "unit@claim")
    assert _rules(res) == ["LUX403"]
    assert "profitable" in res.findings[0].message


def test_profitability_ledger_drift():
    ledger = {"useful_rows": 3, "exchanged_rows": 4,
              "useful_bytes_per_iter": 999, "ratio": 0.75}
    res = exchck.verify_exchange_plan(
        _hand_view(ledger=ledger), "unit@ledger")
    assert _rules(res) == ["LUX403"]


# -- artifact round-trip -------------------------------------------------


def test_artifact_roundtrip_clean(tmp_path):
    sg, plan = _live_plan()
    rb = 8
    ledger = engobs.useful_exchange(
        sg, rb, exchanged_rows=plan.exchanged_units_per_iter)
    d = str(tmp_path / "xplan")
    partition.save_exchange_artifact(
        plan, d, remote_read_counts=sg.remote_read_counts(),
        row_bytes=rb, ledger=ledger)
    view = exchck.load_exchange_artifact(d)
    assert view.declared_bytes_per_iter == plan.exchange_bytes_per_iter(rb)
    res = exchck.verify_exchange_plan(view, d)
    assert res.findings == [] and res.error is None
    # The dir-level entry point agrees and a corrupted copy fails.
    report = exchck.verify_exchange_dirs([d])
    assert report.ok
    arr = np.load(os.path.join(d, "recv_pos.npy"))
    arr[0, -1] = 0
    np.save(os.path.join(d, "recv_pos.npy"), arr)
    report = exchck.verify_exchange_dirs([d])
    assert not report.ok


def test_artifact_unknown_format_rejected(tmp_path):
    _, plan = _live_plan()
    d = str(tmp_path / "xplan")
    partition.save_exchange_artifact(plan, d)
    meta = json.load(open(os.path.join(d, "meta.json")))
    meta["format"] = 99
    json.dump(meta, open(os.path.join(d, "meta.json"), "w"))
    with pytest.raises(ValueError, match="unknown format"):
        exchck.load_exchange_artifact(d)
    # Through the dir runner: an error result, not a crash.
    report = exchck.verify_exchange_dirs([d])
    assert not report.ok and report.results[0].error


# -- registry matrix gate ------------------------------------------------


def test_exchange_matrix_clean_and_fast():
    # The acceptance gate `make lint-exchange` runs: every full+compact
    # sharded target plus its live plan verifies clean, within the
    # PERF.md tier budget.
    report = ir.run_exchange_matrix()
    assert report.ok, report.format_human()
    assert report.summary()["schema"] == "luxlint-exchange.v1"
    names = {r.path for r in report.results}
    # Both halves are present: dataflow targets and their plan twins.
    assert any(n.endswith("+compact") for n in names)
    assert any(n.endswith("/plan") for n in names)
    # Round 17 grew the matrix by the gas_sharded targets plus a third
    # (frontier) exchange mode for every frontier program; the PERF.md
    # tier budget moved 2 s -> 4 s with it (~2.5 s measured).
    assert report.elapsed_s <= 4.0, f"tier budget blown: {report.elapsed_s}"


# -- the overlap proof catches the flipped body --------------------------


class _FlippedPull(ShardedPullExecutor):
    """The compact pull body with the overlap contract deliberately
    broken: the "local" branch gathers from the exchanged flat table, so
    both sides of the ownership merge depend on the collective."""

    def _comp_block(self, vals_blk, flat, dg):
        prog = self.program
        max_nv = self.sg.max_nv
        sidx = dg["src_pidx"][0]
        dst_vals = vals_blk[0][jnp.minimum(dg["dst_local"][0], max_nv - 1)]
        w = dg["weights"][0] if "weights" in dg else None

        def contrib_from(src_vals):
            return prog.edge_contrib(EdgeCtx(
                src_vals=src_vals, dst_vals=dst_vals, weights=w))

        own = jax.lax.axis_index(PARTS_AXIS)
        base = own * max_nv
        local = (sidx >= base) & (sidx < base + max_nv)
        c_local = contrib_from(flat[jnp.clip(sidx - base, 0, max_nv - 1)])
        c_remote = contrib_from(flat[sidx])
        mask = local.reshape(local.shape + (1,) * (c_local.ndim - 1))
        contrib = jnp.where(mask, c_local, c_remote)
        return segment_reduce(
            contrib, dg["dst_local"][0], num_segments=max_nv + 1,
            kind=prog.combiner)[:max_nv]


def test_flipped_compact_pull_trips_overlap_proof(monkeypatch):
    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    g = generate.halo(8, 128, hubs=8)
    ex = _FlippedPull(g, PageRank(), mesh=make_mesh(8))
    assert ex.exchange_mode == "compact", "compact did not engage"
    t = ir.target_from_spec("flipped@pull_sharded+compact", ex.trace_step())
    res = ir.check_target(t, [ir.OverlapProof()])
    assert _rules(res) == ["LUX404"]
    assert "every data side" in res.findings[0].message
    # The unmodified executor proves clean under the identical setup.
    ok = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(8))
    res = ir.check_target(
        ir.target_from_spec("stock@pull_sharded+compact", ok.trace_step()),
        [ir.OverlapProof()])
    assert res.findings == []


# -- seeded fixtures through the CLI -------------------------------------


@pytest.mark.parametrize("rule,stem", [
    ("LUX401", "lux401_structure"),
    ("LUX402", "lux402_coverage"),
    ("LUX403", "lux403_profitability"),
    ("LUX404", "lux404_overlap"),
    ("LUX405", "lux405_sentinel"),
    ("LUX406", "lux406_bytes"),
    ("LUX407", "lux407_frontier"),
])
def test_cli_fixture_fails_with_exactly_its_rule(rule, stem):
    proc = _run_cli("--exchange", os.path.join(EXCH_FIXTURES, stem + ".py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    summary = _summary_line(proc.stdout)
    assert summary["schema"] == "luxlint-exchange.v1"
    assert list(summary["by_rule"]) == [rule], summary


def test_cli_select_filters_exchange_rules():
    fix = os.path.join(EXCH_FIXTURES, "lux401_structure.py")
    proc = _run_cli("--exchange", fix, "--select", "LUX402")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert _summary_line(proc.stdout)["findings"] == 0


def test_cli_rejects_mixed_tiers():
    proc = _run_cli("--exchange", "--ir")
    assert proc.returncode == 2
    assert "separate" in proc.stderr


def test_cli_path_without_plans_or_traces_errors(tmp_path):
    p = tmp_path / "empty_fixture.py"
    p.write_text("X = 1\n")
    proc = _run_cli("--exchange", str(p))
    assert proc.returncode == 1
    assert "neither TRACES nor PLANS" in proc.stdout


# -- serve-pool audit hook -----------------------------------------------


def _corrupt_engine():
    plan = _hand_plan()
    plan.recv_pos[0, 2] = 6
    return types.SimpleNamespace(_xplan=plan)


def test_pool_audit_flags_corrupt_plan(capsys):
    metrics.reset()
    pool = EnginePool("test-exch")
    ex = pool.get("k1", _corrupt_engine)
    assert ex is not None
    assert pool.stats()["exch_findings"] == 1
    assert "LUX402" in capsys.readouterr().out


def test_pool_audit_clean_live_engine(monkeypatch):
    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    metrics.reset()
    g = generate.halo(8, 128, hubs=8)
    pool = EnginePool("test-exch")
    ex = pool.get(
        "k2", lambda: ShardedPullExecutor(g, PageRank(), mesh=make_mesh(8)))
    assert ex._xplan is not None
    assert pool.stats()["exch_findings"] == 0


def test_pool_audit_disabled_by_flag(monkeypatch):
    monkeypatch.setenv("LUX_EXCH_POOL_AUDIT", "0")
    metrics.reset()
    pool = EnginePool("test-exch")
    pool.get("k3", _corrupt_engine)
    assert pool.stats()["exch_findings"] == 0


def test_audit_exchange_survives_garbage():
    ex = types.SimpleNamespace(_xplan=types.SimpleNamespace(garbage=True))
    findings = exchck.audit_exchange(ex, "pool@garbage")
    assert findings and findings[0].rule == "LUX401"
    assert "audit crashed" in findings[0].message


# -- span-hash baseline ratchet ------------------------------------------


def test_baseline_survives_line_shift(tmp_path):
    bad = tmp_path / "engine" / "run_bad.py"
    bad.parent.mkdir()
    bad.write_text(
        "def run(step, vals, n):\n"
        "    for _ in range(n):\n"
        "        vals = step(vals)\n"
        "        done = vals.item()\n"
        "    return vals, done\n"
    )
    base = str(tmp_path / "baseline.json")
    proc = _run_cli(str(tmp_path / "engine"), "--baseline", base)
    assert proc.returncode == 0 and "baseline written" in proc.stdout
    # Shift the finding two lines down: the span-hash key is untouched,
    # so the ratchet still masks it (a line-number key would re-fire).
    bad.write_text(
        "# a comment\n"
        "# another comment\n" + bad.read_text())
    proc = _run_cli(str(tmp_path / "engine"), "--baseline", base)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout
    # Rewriting the flagged line itself re-opens the finding.
    bad.write_text(bad.read_text().replace(
        "done = vals.item()", "done2 = vals.item()"))
    proc = _run_cli(str(tmp_path / "engine"), "--baseline", base)
    assert proc.returncode == 1
    assert "[new]" in proc.stdout


def test_baseline_ratchets_exchange_tier(tmp_path):
    fix = os.path.join(EXCH_FIXTURES, "lux403_profitability.py")
    base = str(tmp_path / "exch_baseline.json")
    p1 = _run_cli("--exchange", fix, "--baseline", base)
    assert p1.returncode == 0 and "baseline written" in p1.stdout
    keys = json.load(open(base))["keys"]
    assert keys and keys[0].startswith("LUX403")
    p2 = _run_cli("--exchange", fix, "--baseline", base)
    assert p2.returncode == 0 and "0 new" in p2.stdout
