"""utils/faults: spec parsing, seeded determinism, fire budgets, the
four kinds' semantics, and the zero-cost disarmed path."""

import time

import numpy as np
import pytest

from lux_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


# -- parsing ---------------------------------------------------------------


def test_parse_full_grammar():
    rules = faults.parse(
        "serve.engine.execute:raise:0.25,"
        "wal.fsync:corrupt:1.0:2,"
        "pool.build:delay_ms:0.5:20"
    )
    assert [(r.point, r.kind, r.prob, r.arg) for r in rules] == [
        ("serve.engine.execute", "raise", 0.25, None),
        ("wal.fsync", "corrupt", 1.0, 2.0),
        ("pool.build", "delay_ms", 0.5, 20.0),
    ]


@pytest.mark.parametrize("spec, why", [
    ("nope:raise:1.0", "unknown fault point"),
    ("pool.build:explode:1.0", "unknown fault kind"),
    ("pool.build:raise:1.5", "outside"),
    ("pool.build:raise:x", "bad probability"),
    ("pool.build:delay_ms:1.0", "delay_ms needs an arg"),
    ("pool.build:raise", "want point:kind:prob"),
    ("pool.build:raise:1.0:-3", "negative arg"),
])
def test_parse_rejects(spec, why):
    with pytest.raises(ValueError, match=why):
        faults.parse(spec)


def test_parse_empty_spec_is_no_rules():
    assert faults.parse("") == []
    assert faults.parse(" , ") == []


# -- firing ----------------------------------------------------------------


def test_disarmed_point_is_identity():
    data = b"payload"
    assert faults.point("wal.fsync", data=data) is data
    assert faults.point("serve.engine.execute") is None
    assert faults.armed() == ()


def test_unknown_point_name_fails_loudly_when_armed():
    faults.arm("pool.build:raise:0.0")
    # A typo'd lace site must not silently never fire: _fire looks the
    # name up only among registered points, armed names come validated.
    assert faults.point("pool.build") is None
    faults.disarm()


def test_raise_kind_is_transient_runtime_error():
    faults.arm("serve.engine.execute:raise:1.0")
    with pytest.raises(faults.FaultInjected) as ei:
        faults.point("serve.engine.execute")
    assert isinstance(ei.value, RuntimeError)
    assert ei.value.point == "serve.engine.execute"


def test_crash_kind_escapes_except_exception():
    faults.arm("snapshot.warm:crash:1.0")
    with pytest.raises(faults.CrashPoint):
        try:
            faults.point("snapshot.warm")
        except Exception:   # must NOT absorb the crash
            pytest.fail("CrashPoint was caught by `except Exception`")
    assert not issubclass(faults.CrashPoint, Exception)


def test_fire_budget_caps_injections():
    faults.arm("serve.engine.execute:raise:1.0:2")
    for _ in range(2):
        with pytest.raises(faults.FaultInjected):
            faults.point("serve.engine.execute")
    # Budget spent: the point goes quiet (transient-blip modeling).
    for _ in range(5):
        faults.point("serve.engine.execute")
    assert faults.counts()["serve.engine.execute:raise"] >= 2


def test_delay_kind_sleeps():
    faults.arm("cache.put:delay_ms:1.0:30")
    t0 = time.perf_counter()
    faults.point("cache.put")
    assert time.perf_counter() - t0 >= 0.025


def test_corrupt_returns_damaged_copy():
    faults.arm("wal.fsync:corrupt:1.0")
    data = bytes(range(64))
    out = faults.point("wal.fsync", data=data)
    assert out != data and len(out) == len(data)
    assert data == bytes(range(64))     # original untouched

    arr = np.arange(16, dtype=np.int64)
    out = faults.point("wal.fsync", data=arr)
    assert not np.array_equal(out, arr)
    assert arr[8] == 8                  # copy, not in-place


def test_seeded_determinism():
    def draw(seed):
        faults.arm("serve.engine.execute:raise:0.5", seed=seed)
        fired = []
        for _ in range(40):
            try:
                faults.point("serve.engine.execute")
                fired.append(0)
            except faults.FaultInjected:
                fired.append(1)
        return fired

    a, b, c = draw(7), draw(7), draw(8)
    assert a == b
    assert a != c


def test_injected_context_restores_previous_arming():
    faults.arm("pool.build:raise:0.0")
    before = faults.armed()
    with faults.injected("cache.put:raise:1.0"):
        assert {r.point for r in faults.armed()} == {"cache.put"}
        with pytest.raises(faults.FaultInjected):
            faults.point("cache.put")
    assert faults.armed() == before


def test_reconfigure_reads_env(monkeypatch):
    monkeypatch.setenv("LUX_FAULTS", "batcher.assemble:raise:1.0")
    assert faults.reconfigure() == 1
    with pytest.raises(faults.FaultInjected):
        faults.point("batcher.assemble")
    monkeypatch.setenv("LUX_FAULTS", "")
    assert faults.reconfigure() == 0
    assert faults.point("batcher.assemble") is None


def test_counts_and_metric_accounting():
    from lux_tpu.obs import metrics
    base = metrics.counter("lux_faults_injected_total",
                           {"point": "pool.build", "kind": "raise"}).value
    faults.arm("pool.build:raise:1.0:3")
    for _ in range(3):
        with pytest.raises(faults.FaultInjected):
            faults.point("pool.build")
    assert metrics.counter(
        "lux_faults_injected_total",
        {"point": "pool.build", "kind": "raise"}).value == base + 3
