"""Graph format + data model tests (golden tests vs. hand-built graphs)."""

import numpy as np
import pytest

from lux_tpu.graph import Graph, generate, read_lux, write_lux, detect_layout
from lux_tpu.graph.format import convert_edge_list


def tiny_graph():
    # 0→1, 0→2, 1→2, 2→0, 3→2 ; nv=4 (vertex 3 has no in-edges)
    src = [0, 0, 1, 2, 3]
    dst = [1, 2, 2, 0, 2]
    return Graph.from_edges(np.array(src), np.array(dst), nv=4)


def test_from_edges_csc():
    g = tiny_graph()
    assert g.nv == 4 and g.ne == 5
    # Edges sorted by dst: dst order = [0, 1, 2, 2, 2]
    np.testing.assert_array_equal(g.row_ptr, [0, 1, 2, 5, 5])
    np.testing.assert_array_equal(g.col_src, [2, 0, 0, 1, 3])
    np.testing.assert_array_equal(g.in_degrees, [1, 1, 3, 0])
    np.testing.assert_array_equal(g.out_degrees, [2, 1, 1, 1])
    np.testing.assert_array_equal(g.col_dst, [0, 1, 2, 2, 2])


def test_csr_roundtrip():
    g = tiny_graph()
    csr = g.csr()
    np.testing.assert_array_equal(csr.row_ptr, [0, 2, 3, 4, 5])
    # out-edges grouped by src: 0→{1,2}, 1→{2}, 2→{0}, 3→{2}
    np.testing.assert_array_equal(csr.col_dst, [1, 2, 2, 0, 2])


def test_lux_roundtrip(tmp_path):
    g = generate.gnp(100, 700, seed=3)
    p = str(tmp_path / "g.lux")
    write_lux(p, g)
    nv, ne, has_w, has_d = detect_layout(p)
    assert (nv, ne, has_w, has_d) == (100, 700, False, True)
    g2 = read_lux(p)
    np.testing.assert_array_equal(g.row_ptr, g2.row_ptr)
    np.testing.assert_array_equal(g.col_src, g2.col_src)
    assert g2.weights is None


def test_lux_roundtrip_weighted(tmp_path):
    g = generate.gnp(50, 300, seed=4, weighted=True)
    p = str(tmp_path / "w.lux")
    write_lux(p, g, include_degrees=False)
    nv, ne, has_w, has_d = detect_layout(p)
    assert (nv, ne, has_w, has_d) == (50, 300, True, False)
    g2 = read_lux(p)
    np.testing.assert_array_equal(g.weights, g2.weights)


def test_read_lux_mmap_matches_read_lux(tmp_path):
    # The RMAT27-scale mapped reader must agree with the materializing
    # one, including precomputed out-degrees and weighted layouts, and
    # must feed ShardedGraph.build identically (memmap col_src path).
    from lux_tpu.graph import read_lux_mmap
    from lux_tpu.parallel.shard import ShardedGraph

    for weighted in (False, True):
        g = generate.gnp(100, 700, seed=5, weighted=weighted)
        p = str(tmp_path / f"m{int(weighted)}.lux")
        write_lux(p, g)
        a, b = read_lux(p), read_lux_mmap(p)
        np.testing.assert_array_equal(a.row_ptr, b.row_ptr)
        np.testing.assert_array_equal(a.col_src, np.asarray(b.col_src))
        np.testing.assert_array_equal(a.out_degrees, b.out_degrees)
        if weighted:
            np.testing.assert_array_equal(a.weights, np.asarray(b.weights))
        sa = ShardedGraph.build(a, 4)
        sb = ShardedGraph.build(b, 4)
        for f in ("src_pidx", "dst_local", "edge_mask", "local_row_ptr"):
            np.testing.assert_array_equal(getattr(sa, f), getattr(sb, f))


def test_binary_layout_is_reference_compatible(tmp_path):
    """Byte-level check of the layout in tools/converter.cc:108-124."""
    g = tiny_graph()
    p = str(tmp_path / "t.lux")
    write_lux(p, g)
    raw = open(p, "rb").read()
    assert len(raw) == 12 + 8 * 4 + 4 * 5 + 4 * 4
    assert np.frombuffer(raw[:4], "<u4")[0] == 4
    assert np.frombuffer(raw[4:12], "<u8")[0] == 5
    ends = np.frombuffer(raw[12:44], "<u8")
    np.testing.assert_array_equal(ends, [1, 2, 5, 5])
    cols = np.frombuffer(raw[44:64], "<u4")
    np.testing.assert_array_equal(cols, [2, 0, 0, 1, 3])
    degs = np.frombuffer(raw[64:80], "<u4")
    np.testing.assert_array_equal(degs, [2, 1, 1, 1])


def test_converter_cli(tmp_path):
    el = tmp_path / "edges.txt"
    el.write_text("0 1\n0 2\n1 2\n2 0\n3 2\n")
    out = str(tmp_path / "c.lux")
    convert_edge_list(str(el), out, nv=4, ne=5)
    g = read_lux(out)
    np.testing.assert_array_equal(g.col_src, [2, 0, 0, 1, 3])


def test_monotone_rowptr_rejected(tmp_path):
    g = tiny_graph()
    p = str(tmp_path / "bad.lux")
    write_lux(p, g, include_degrees=False)
    raw = bytearray(open(p, "rb").read())
    raw[12:20] = np.asarray([5], "<u8").tobytes()  # row end 5 then 2: non-monotone
    open(p, "wb").write(bytes(raw))
    with pytest.raises(ValueError):
        read_lux(p)


def test_rmat_streaming_build_matches_from_edges():
    """rmat() builds CSC by two-pass counting sort; must equal the
    materialize-then-sort construction exactly (incl. weight permutation)."""
    from lux_tpu.graph.generate import rmat, rmat_edges

    scale, ef = 8, 4
    g = rmat(scale, ef, seed=11, weighted=True)
    srcs, dsts = [], []
    for s, d in rmat_edges(scale, (1 << scale) * ef, seed=11, batch=1 << 24):
        srcs.append(s)
        dsts.append(d)
    import numpy as _np

    w = _np.random.default_rng(12).integers(
        1, 101, size=(1 << scale) * ef, dtype=_np.int32
    )
    g2 = Graph.from_edges(
        _np.concatenate(srcs), _np.concatenate(dsts), nv=1 << scale, weights=w
    )
    _np.testing.assert_array_equal(g.row_ptr, g2.row_ptr)
    _np.testing.assert_array_equal(g.col_src, g2.col_src)
    _np.testing.assert_array_equal(g.weights, g2.weights)


def test_rmat_streaming_batched_placement():
    """Multiple small batches must still yield a stable global dst order."""
    from lux_tpu.graph.generate import rmat_edges

    scale, ne = 6, 512
    srcs, dsts = [], []
    for s, d in rmat_edges(scale, ne, seed=3, batch=100):
        srcs.append(s)
        dsts.append(d)
    import numpy as _np

    full = Graph.from_edges(
        _np.concatenate(srcs), _np.concatenate(dsts), nv=1 << scale
    )
    from lux_tpu.graph import generate as gen

    g = gen.rmat(scale, ne // (1 << scale), seed=3, batch=100)
    _np.testing.assert_array_equal(g.row_ptr, full.row_ptr)
    _np.testing.assert_array_equal(g.col_src, full.col_src)
