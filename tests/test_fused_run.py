"""Parity of the fused on-device loop (run(..., flush_every=0)) vs the
per-step dispatch path, for all four pull executors. bench.py times the
fused path exclusively, so it must compute exactly what step() computes
(same trace, same donation semantics, dynamic trip count)."""

import numpy as np
import pytest

from lux_tpu.engine.pull import PullExecutor
from lux_tpu.engine.pull_sharded import ShardedPullExecutor
from lux_tpu.engine.tiled import TiledPullExecutor
from lux_tpu.engine.tiled_sharded import ShardedTiledExecutor
from lux_tpu.graph import generate
from lux_tpu.models.pagerank import PageRank


@pytest.fixture(scope="module")
def graph():
    return generate.rmat(9, 8, seed=11)


def test_fused_matches_pipelined_plain(graph):
    ex = PullExecutor(graph, PageRank())
    a = np.asarray(ex.run(7, flush_every=1))
    b = np.asarray(ex.run(7, flush_every=0))
    np.testing.assert_array_equal(a, b)


def test_fused_matches_pipelined_tiled(graph):
    ex = TiledPullExecutor(
        graph, PageRank(), levels=((8, 2),), chunk_strips=16, chunk_tail=64
    )
    a = np.asarray(ex.run(7, flush_every=1))
    b = np.asarray(ex.run(7, flush_every=0))
    np.testing.assert_array_equal(a, b)


def test_fused_matches_pipelined_sharded(graph):
    ex = ShardedPullExecutor(graph, PageRank(), num_parts=4)
    a = ex.gather_values(ex.run(7, flush_every=1))
    b = ex.gather_values(ex.run(7, flush_every=0))
    np.testing.assert_array_equal(a, b)


def test_fused_matches_pipelined_tiled_sharded(graph):
    ex = ShardedTiledExecutor(
        graph, PageRank(), num_parts=4,
        levels=((8, 2),), chunk_strips=16, chunk_tail=64,
    )
    a = ex.gather_values(ex.run(7, flush_every=1))
    b = ex.gather_values(ex.run(7, flush_every=0))
    np.testing.assert_array_equal(a, b)


def test_fused_dynamic_trip_count_no_recompile(graph):
    """Different N must reuse the same compiled fused loop (dynamic bound):
    a recompile per N would reintroduce the ~150-300 ms-per-dispatch cost
    the fused path exists to avoid."""
    ex = PullExecutor(graph, PageRank())
    v3 = np.asarray(ex.run(3, flush_every=0))
    compiles_after_first = ex._jrun._cache_size()
    v5 = np.asarray(ex.run(5, flush_every=0))
    assert ex._jrun._cache_size() == compiles_after_first
    want3 = np.asarray(ex.run(3, flush_every=1))
    want5 = np.asarray(ex.run(5, flush_every=1))
    np.testing.assert_array_equal(v3, want3)
    np.testing.assert_array_equal(v5, want5)
