"""GAS subsystem: direction-optimizing adaptive executor, the widened
program registry (BFS, weighted delta-SSSP, label propagation, k-core),
legacy-model adapters, direction telemetry, and serving integration.

The load-bearing contract under test: pull, push, and adaptive schedules
produce **bitwise-equal** values for every frontier program (both
directions materialize the same dense accumulator), and the adaptive
policy actually switches direction mid-run on frontier curves that cross
the hysteresis band.
"""

import numpy as np
import pytest

from lux_tpu.engine.gas import (
    AdaptiveExecutor,
    GasProgram,
    MultiSourceGasExecutor,
    PullGasAdapter,
    PushGasAdapter,
    as_gas,
)
from lux_tpu.engine.push import PushExecutor
from lux_tpu.graph import generate
from lux_tpu.models import ENGINE_KINDS, PROGRAMS, ROOTED_APPS, get_program
from lux_tpu.models.bfs import BFS, bfs_parents, reference_bfs
from lux_tpu.models.kcore import KCore, reference_kcore
from lux_tpu.models.labelprop import LabelPropagation, reference_labelprop
from lux_tpu.models.pagerank import PageRank, reference_pagerank
from lux_tpu.models.sssp import SSSP, reference_sssp
from lux_tpu.models.sssp_delta import DeltaSSSP, reference_sssp_delta
from lux_tpu.obs.iterlog import IterationRecorder
from lux_tpu.serve import ServeConfig, Session


def _rmat_w(scale=9, seed=3):
    return generate.undirected(
        generate.rmat(scale, 8, seed=seed, weighted=True))


def _run_values(program, g, mode, **init_kw):
    ex = AdaptiveExecutor(g, program, mode=mode)
    state, iters = ex.run(**init_kw)
    return np.asarray(state.values), iters, ex


# -- host-oracle parity per program ---------------------------------------


def test_bfs_matches_oracle():
    g = _rmat_w()
    depth_ref, parent_ref = reference_bfs(g, 1)
    ex = AdaptiveExecutor(g, BFS())
    state, _ = ex.run(start=1)
    np.testing.assert_array_equal(np.asarray(state.values), depth_ref)
    np.testing.assert_array_equal(ex.finalize(state)["parent"], parent_ref)


def test_sssp_delta_matches_dijkstra():
    g = _rmat_w()
    vals, _, _ = _run_values(DeltaSSSP(), g, "adaptive", start=0)
    np.testing.assert_array_equal(vals, reference_sssp_delta(g, 0))


def test_labelprop_matches_oracle():
    g = _rmat_w()
    vals, iters, ex = _run_values(LabelPropagation(), g, "adaptive")
    np.testing.assert_array_equal(vals, reference_labelprop(g))
    fin = LabelPropagation().finalize_host(g, vals)
    assert fin["num_communities"] >= 1
    np.testing.assert_array_equal(fin["labels"], vals >> np.uint32(8))


def test_kcore_matches_peeling_oracle():
    g = _rmat_w()
    for k in (2, 3):
        vals, _, ex = _run_values(KCore(k=k), g, "adaptive")
        ref = reference_kcore(g, k)
        np.testing.assert_array_equal(vals, ref)
        fin = KCore(k=k).finalize_host(g, vals)
        assert fin["core_size"] == int((ref >= k).sum())


def test_kcore_rejects_bad_k():
    with pytest.raises(ValueError):
        KCore(k=0)


# -- bitwise parity across directions -------------------------------------


@pytest.mark.parametrize("make,init_kw", [
    (BFS, {"start": 1}),
    (DeltaSSSP, {"start": 0}),
    (LabelPropagation, {}),
    (KCore, {}),
])
def test_pinned_directions_bitwise_equal(make, init_kw):
    """pull == push == adaptive, bit for bit, for every frontier
    program: both directions build the same dense accumulator."""
    g = _rmat_w()
    pull, i_pull, _ = _run_values(make(), g, "pull", **init_kw)
    push, i_push, _ = _run_values(make(), g, "push", **init_kw)
    adap, i_adap, _ = _run_values(make(), g, "adaptive", **init_kw)
    np.testing.assert_array_equal(pull, push)
    np.testing.assert_array_equal(pull, adap)
    assert i_pull == i_push == i_adap


def test_bfs_adaptive_switches_mid_run():
    """On an RMAT frontier curve (small wave -> big wave -> tail) the
    adaptive policy must actually change direction at least once, and
    the switch must not perturb the result."""
    g = generate.undirected(generate.rmat(10, 8, seed=3, weighted=True))
    vals, _, ex = _run_values(BFS(), g, "adaptive", start=1)
    assert ex.direction_switches >= 1
    assert ex.push_iters >= 1 and ex.pull_iters >= 1
    pinned, _, _ = _run_values(BFS(), g, "pull", start=1)
    np.testing.assert_array_equal(vals, pinned)


# -- legacy adapters ------------------------------------------------------


def test_push_adapter_sssp_bitwise_matches_push_engine():
    g = generate.gnp(400, 3000, seed=103, weighted=True)
    prog = as_gas(SSSP())
    assert isinstance(prog, PushGasAdapter) and prog.rooted
    vals, _, _ = _run_values(prog, g, "adaptive", start=5)
    ref_state, _ = PushExecutor(g, SSSP()).run(start=5)
    np.testing.assert_array_equal(vals, np.asarray(ref_state.values))
    np.testing.assert_array_equal(vals, reference_sssp(g, 5))


def test_pull_adapter_pagerank_matches_reference():
    g = generate.gnp(300, 2400, seed=7)
    prog = as_gas(PageRank())
    assert isinstance(prog, PullGasAdapter) and not prog.frontier
    ex = AdaptiveExecutor(g, prog)
    assert ex.mode == "pull"    # frontier-less: direction is forced
    state, iters = ex.run(max_iters=20)
    assert iters == 20
    np.testing.assert_allclose(
        np.asarray(state.values), reference_pagerank(g, 20),
        rtol=1e-5, atol=1e-7)


def test_frontierless_run_requires_max_iters():
    g = generate.gnp(50, 200, seed=1)
    ex = AdaptiveExecutor(g, as_gas(PageRank()))
    with pytest.raises(ValueError):
        ex.run()


def test_as_gas_rejects_unknown_model():
    with pytest.raises(TypeError):
        as_gas(object())


def test_bad_mode_rejected():
    g = generate.gnp(50, 200, seed=1)
    with pytest.raises(ValueError):
        AdaptiveExecutor(g, BFS(), mode="sideways")


# -- multi-source batching ------------------------------------------------


def test_multi_source_gas_matches_single_lanes():
    g = _rmat_w()
    roots = [2, 3, 4]
    mx = MultiSourceGasExecutor(g, BFS(), k=4)   # k > len(roots): padding
    state, _ = mx.run(roots)
    for j, r in enumerate(roots):
        single, _, _ = _run_values(BFS(), g, "adaptive", start=r)
        np.testing.assert_array_equal(mx.values_for(state, j), single)
        fin = mx.finalize_for(state, j)
        np.testing.assert_array_equal(
            fin["parent"], bfs_parents(g, single))


def test_multi_source_gas_rejects_frontierless():
    g = generate.gnp(50, 200, seed=1)
    with pytest.raises(ValueError):
        MultiSourceGasExecutor(g, PageRank(), k=2)


# -- registry derivation --------------------------------------------------


def test_rooted_apps_derived_from_program_attr():
    assert ROOTED_APPS == frozenset({"bfs", "sssp", "sssp_delta"})
    for name in ROOTED_APPS:
        assert getattr(PROGRAMS[name], "rooted", False)


def test_registry_gas_coverage():
    """Every registered program runs under some GAS kind, and every
    gas_multi program is rooted."""
    for name, kinds in ENGINE_KINDS.items():
        assert any(k.startswith("gas") for k in kinds), name
        if "gas_multi" in kinds:
            assert name in ROOTED_APPS
    # the registry instantiates cleanly through the one factory
    for name in PROGRAMS:
        assert get_program(name).name == name


# -- direction telemetry --------------------------------------------------


def test_recorder_directions_feed_crossovers():
    rec = IterationRecorder("gas", nv=100, ne=800, program="BFS")
    rec.start()
    rec.flush(3, frontier_sizes=[1, 10, 60], directions=[1, 1, 0])
    rec.flush(5, frontier_sizes=[8, 2], directions=[1, 1])
    s = rec.finish()
    branches = [r["branch"] for r in s["iterations"]]
    assert branches == ["push", "push", "pull", "push", "push"]
    assert [(c["from"], c["to"]) for c in s["crossovers"]] == [
        ("push", "pull"), ("pull", "push")]


def test_adaptive_run_notes_direction_split():
    from lux_tpu.obs import engobs

    g = _rmat_w()
    _, iters, ex = _run_values(BFS(), g, "adaptive", start=1)
    latest = engobs.latest().get("gas")
    assert latest is not None
    assert latest["num_iters"] == iters
    assert latest["direction_push"] == ex.push_iters
    assert latest["direction_pull"] == ex.pull_iters
    assert latest["direction_switches"] == ex.direction_switches
    assert latest["direction_push"] + latest["direction_pull"] == iters


# -- serving integration --------------------------------------------------


@pytest.fixture(scope="module")
def gas_session():
    g = _rmat_w(scale=8, seed=5)
    s = Session(g, ServeConfig(max_batch=4, window_s=0.001))
    yield s, g
    s.close()


def test_session_apps_derived_from_registry(gas_session):
    s, _ = gas_session
    assert set(s.APPS) >= {"sssp", "components", "pagerank", "bfs",
                           "sssp_delta", "labelprop", "kcore"}
    assert "colfilter" not in s.APPS   # servable = False
    assert s._gas_rooted == ("bfs", "sssp_delta")


def test_session_unweighted_graph_drops_weighted_apps():
    g = generate.gnp(200, 1200, seed=11)   # unweighted
    s = Session(g, ServeConfig(max_batch=2, window_s=0.001))
    try:
        assert "sssp_delta" not in s.APPS
        assert "bfs" in s.APPS
    finally:
        s.close()


def test_session_serves_gas_apps_with_oracle_agreement(gas_session):
    s, g = gas_session
    r = s.query("bfs", start=1)
    depth, parent = reference_bfs(g, 1)
    np.testing.assert_array_equal(r["values"], depth)
    np.testing.assert_array_equal(r["parent"], parent)
    assert r["direction_push"] + r["direction_pull"] == r["iters"]

    r = s.query("sssp_delta", start=0)
    np.testing.assert_array_equal(r["values"], reference_sssp_delta(g, 0))

    r = s.query("labelprop")
    np.testing.assert_array_equal(r["values"], reference_labelprop(g))
    assert r["num_communities"] == np.unique(r["labels"]).size

    r = s.query("kcore", k=3)
    ref = reference_kcore(g, 3)
    np.testing.assert_array_equal(r["values"], ref)
    assert r["core_size"] == int((ref >= 3).sum())


def test_session_gas_batch_lanes_match_singles(gas_session):
    s, g = gas_session
    roots = [2, 3, 4, 5]
    futs = [s.submit("bfs", start=r) for r in roots]
    for r, f in zip(roots, futs):
        out = f.result(timeout=60)
        depth, parent = reference_bfs(g, r)
        np.testing.assert_array_equal(out["values"], depth)
        np.testing.assert_array_equal(out["parent"], parent)


def test_session_kcore_validates_k(gas_session):
    from lux_tpu.serve import BadQueryError

    s, _ = gas_session
    with pytest.raises(BadQueryError):
        s.query("kcore", k=0)
    with pytest.raises(BadQueryError):
        s.query("kcore", k="three")


def test_statusz_carries_gas_direction_split(gas_session):
    s, _ = gas_session
    s.query("bfs", start=6)
    block = s.statusz()["gas"]
    assert "gas" in block
    rec = block["gas"]
    assert rec["direction_push"] + rec["direction_pull"] \
        == rec["num_iters"]
