"""ShardedAdaptiveExecutor: direction-adaptive GAS on the mesh — the
bitwise parity matrix against the single-device AdaptiveExecutor, the
zero-recompile contract across direction switches and exchange modes,
the frontier-exchange edge cases (empty frontier, dense self-downgrade,
tiny-capacity overflow, P=1 inertness), the engobs phase split, and the
serving layer's counted mesh-fallback path."""

import numpy as np
import pytest

import jax

from lux_tpu.analysis.sentinel import RecompileSentinel
from lux_tpu.engine.gas import AdaptiveExecutor, GasState, as_gas
from lux_tpu.engine.gas_sharded import (
    ShardedAdaptiveExecutor,
    ShardedMultiSourceGasExecutor,
)
from lux_tpu.graph import generate
from lux_tpu.models import ENGINE_KINDS, PROGRAMS, get_program
from lux_tpu.models.bfs import reference_bfs
from lux_tpu.obs import engobs, metrics, report

# Per-program init kwargs and (for the frontier-less pull programs) the
# iteration budget run() requires.
INIT = {
    "pagerank": {}, "sssp": {"start": 1}, "components": {},
    "colfilter": {}, "bfs": {"start": 1}, "sssp_delta": {"start": 0},
    "labelprop": {}, "kcore": {},
}
MAXIT = {"pagerank": 6, "colfilter": 4}


@pytest.fixture(scope="module")
def graph():
    return generate.rmat(8, 8, seed=5, weighted=True)


@pytest.fixture(scope="module")
def refs(graph):
    """Single-device AdaptiveExecutor oracle, computed once per app."""
    cache = {}

    def get(name):
        if name not in cache:
            prog = as_gas(get_program(name))
            ex = AdaptiveExecutor(
                graph, prog, mode="adaptive" if prog.frontier else None)
            st, iters = ex.run(max_iters=MAXIT.get(name), **INIT[name])
            cache[name] = (np.asarray(jax.device_get(st.values)), iters)
        return cache[name]

    return get


def _build(graph, name, xmode, monkeypatch, num_parts=8, **kw):
    monkeypatch.setenv("LUX_EXCHANGE", xmode)
    prog = as_gas(get_program(name))
    return ShardedAdaptiveExecutor(
        graph, get_program(name), num_parts=num_parts,
        mode="adaptive" if prog.frontier else None, **kw)


# -- bitwise parity matrix: every program x every exchange mode ----------


@pytest.mark.parametrize("name", sorted(PROGRAMS))
def test_parity_all_modes(graph, refs, name, monkeypatch):
    ref_vals, ref_iters = refs(name)
    frontier = as_gas(get_program(name)).frontier
    for xmode in ("full", "compact", "frontier"):
        ex = _build(graph, name, xmode, monkeypatch)
        st, iters = ex.run(max_iters=MAXIT.get(name), **INIT[name])
        np.testing.assert_array_equal(
            ex.gather_values(st), ref_vals,
            err_msg=f"{name} P=8 LUX_EXCHANGE={xmode}")
        assert iters == ref_iters
        if xmode == "frontier" and not frontier:
            # Honest downgrade: no activity plane to refine with.
            assert ex.exchange_mode != "frontier"


def test_pinned_directions_parity(graph, monkeypatch):
    """Pinned push and pinned pull agree bitwise with the same pin on
    one device, under both packed exchanges."""
    for name in ("bfs", "sssp"):
        prog = as_gas(get_program(name))
        for pin in ("push", "pull"):
            ref_st, _ = AdaptiveExecutor(graph, prog, mode=pin).run(
                **INIT[name])
            ref_vals = np.asarray(jax.device_get(ref_st.values))
            for xmode in ("compact", "frontier"):
                monkeypatch.setenv("LUX_EXCHANGE", xmode)
                ex = ShardedAdaptiveExecutor(
                    graph, get_program(name), num_parts=2, mode=pin)
                st, _ = ex.run(**INIT[name])
                np.testing.assert_array_equal(
                    ex.gather_values(st), ref_vals,
                    err_msg=f"pin {name}/{pin} LUX_EXCHANGE={xmode}")


def test_multi_source_lanes_parity(graph, monkeypatch):
    roots = [2, 9, 17]
    monkeypatch.setenv("LUX_EXCHANGE", "frontier")
    mx = ShardedMultiSourceGasExecutor(
        graph, get_program("bfs"), k=4, num_parts=8)
    # The K-lane exchange has no single-lane activity plane: honest
    # downgrade to the static compact plan, never a dynamic send.
    assert mx.exchange_mode == "compact"
    state, _ = mx.run(roots)
    assert mx.exchange_downgrades == 0
    for j, r in enumerate(roots):
        ref_st, _ = AdaptiveExecutor(
            graph, as_gas(get_program("bfs")), mode="adaptive").run(start=r)
        np.testing.assert_array_equal(
            mx.values_for(state, j),
            np.asarray(jax.device_get(ref_st.values)),
            err_msg=f"lane {j} root {r}")


def test_engine_kind_registry():
    """Every program runs sharded; every rooted program batches sharded
    (the LUX104/LUX105 trace matrix builds from this registry)."""
    for name, cls in PROGRAMS.items():
        kinds = ENGINE_KINDS[name]
        assert "gas_sharded" in kinds, name
        assert ("gas_multi_sharded" in kinds) == bool(
            getattr(cls, "rooted", False)), name


# -- zero recompiles across direction switches and both sends ------------


def test_adaptive_switches_without_recompile(graph, monkeypatch):
    monkeypatch.setenv("LUX_EXCHANGE", "frontier")
    sent = RecompileSentinel("gas-sharded")
    if not sent.available:
        sent.close()
        pytest.skip("jax monitoring hook unavailable in this jax")
    try:
        with sent.expect("bfs"):
            ex = ShardedAdaptiveExecutor(
                graph, get_program("bfs"), num_parts=8, mode="adaptive")
            ex.warmup(start=1)
        with sent.watch("bfs"):
            st, iters = ex.run(start=1)
            st2, _ = ex.run(start=7)
        sent.assert_zero_recompiles()
    finally:
        sent.close()
    # The run actually exercised both directions and a switch — the
    # hysteresis crossed hi/lo at least once on this graph.
    assert ex.push_iters > 0 and ex.pull_iters > 0
    assert ex.direction_switches >= 1
    ref_st, _ = AdaptiveExecutor(
        graph, as_gas(get_program("bfs")), mode="adaptive").run(start=7)
    np.testing.assert_array_equal(
        ex.gather_values(st2), np.asarray(jax.device_get(ref_st.values)))


# -- frontier-exchange edge cases ----------------------------------------


@pytest.mark.parametrize("xmode", ["compact", "frontier"])
def test_empty_frontier_iteration_is_identity(graph, monkeypatch, xmode):
    """A step with no active vertices exchanges only identities: values
    come back bitwise unchanged and the new frontier is empty."""
    ex = _build(graph, "bfs", xmode, monkeypatch, num_parts=4)
    state = ex.init_state(start=1)
    empty = GasState(
        state.values, state.frontier & False, state.direction)
    before = ex.gather_values(empty)
    new_state, cnt = ex.step(empty)      # donates `empty`
    assert int(np.asarray(jax.device_get(cnt)).sum()) == 0
    np.testing.assert_array_equal(ex.gather_values(new_state), before)
    assert not np.asarray(jax.device_get(new_state.frontier)).any()


def test_dense_frontier_self_downgrades(graph, refs, monkeypatch):
    """labelprop starts all-active: the admissibility guard must route
    dense iterations onto the static compact send (counted, never
    truncated) while results stay bitwise equal."""
    ex = _build(graph, "labelprop", "frontier", monkeypatch)
    assert ex.exchange_mode == "frontier"
    st, _ = ex.run()
    assert ex.exchange_downgrades >= 1
    np.testing.assert_array_equal(ex.gather_values(st), refs("labelprop")[0])


def test_tiny_capacity_overflow_downgrades_not_truncates(
        graph, refs, monkeypatch):
    """With the frontier budget squeezed to ~one row per pair, almost
    every iteration overflows: all of them must downgrade and the final
    values must still match the oracle exactly."""
    monkeypatch.setenv("LUX_EXCHANGE_FRONTIER_FRAC", "0.001")
    ex = _build(graph, "bfs", "frontier", monkeypatch)
    assert ex.exchange_mode == "frontier" and ex.frontier_cap >= 1
    st, iters = ex.run(start=1)
    assert ex.exchange_downgrades >= 1
    assert iters == refs("bfs")[1]
    np.testing.assert_array_equal(ex.gather_values(st), refs("bfs")[0])


def test_p1_exchange_is_inert(graph, refs, monkeypatch):
    """One part: every exchange mode resolves to the no-op full path
    and the advertised cross-device traffic is zero."""
    ex = _build(graph, "bfs", "frontier", monkeypatch, num_parts=1)
    assert ex.exchange_mode == "full" and ex._xplan is None
    assert ex.exchange_bytes_per_iter() == 0
    assert ex.frontier_evidence() is None
    st, iters = ex.run(start=1)
    assert iters == refs("bfs")[1]
    np.testing.assert_array_equal(ex.gather_values(st), refs("bfs")[0])


def test_bfs_parent_plane_under_frontier(graph, monkeypatch):
    """finalize() derives the parent plane from exact depths: the
    sentinel-padded dynamic exchange must not perturb the min-id
    tie-break on the index-valued plane."""
    ex = _build(graph, "bfs", "frontier", monkeypatch)
    st, _ = ex.run(start=1)
    depth_ref, parent_ref = reference_bfs(graph, start=1)
    np.testing.assert_array_equal(ex.gather_values(st), depth_ref)
    np.testing.assert_array_equal(ex.finalize(st)["parent"], parent_ref)


def test_frontier_evidence_satisfies_lux407(graph, monkeypatch):
    """The live executor's LUX407 evidence passes its own lint rule
    against the live plan (the fixture file covers the violations)."""
    from lux_tpu.analysis import exchck

    ex = _build(graph, "bfs", "frontier", monkeypatch)
    fe = ex.frontier_evidence()
    assert fe is not None and 1 <= fe["frontier_capacity"]
    assert fe["frontier_capacity"] <= ex._xplan.capacity
    view = exchck.plan_view(
        ex._xplan, row_bytes=ex._row_bytes(),
        declared_bytes_per_iter=ex.exchange_bytes_per_iter(),
        remote_read_counts=ex.sg.remote_read_counts(), **fe)
    findings = []
    for rule in exchck.all_exchange_rules():
        findings.extend(rule.check(view, "<live>") or [])
    assert not findings, [f.format() for f in findings]


# -- engobs phase split ---------------------------------------------------


def test_engobs_phased_run_labels_branches(graph, refs, tmp_path, monkeypatch):
    ref_vals = refs("bfs")[0]     # materialize before LUX_METRICS is set
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    monkeypatch.setenv("LUX_ENGOBS", "1")
    monkeypatch.setenv("LUX_EXCHANGE", "frontier")
    engobs.reset()
    ex = ShardedAdaptiveExecutor(
        graph, get_program("bfs"), num_parts=4, mode="adaptive")
    st, iters = ex.run(start=1)
    np.testing.assert_array_equal(ex.gather_values(st), ref_vals)

    run = report.read_last(mpath)
    assert run["engine"] == "gas_sharded" and run["num_iters"] == iters
    ph = run["phases"]
    assert ph["exchange_s"] > 0 and ph["compute_s"] > 0
    labels = [r["branch"] for r in run["iterations"]]
    assert set(labels) <= {
        "push", "pull", "pull/frontier", "pull/downgraded"}
    assert sum(lbl == "push" for lbl in labels) == ex.push_iters
    assert (sum(lbl == "pull/downgraded" for lbl in labels)
            == ex.exchange_downgrades)
    note = engobs.latest()["gas_sharded"]
    assert note["direction_switches"] == ex.direction_switches


# -- serving: counted, never-silent mesh fallback -------------------------


def test_serve_mesh_fallback_is_counted_and_surfaced(graph, monkeypatch):
    from lux_tpu.engine import gas_sharded as engine_mod
    from lux_tpu.serve.session import ServeConfig, Session

    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    ctr = metrics.counter("lux_serve_mesh_fallback_total", {"app": "bfs"})
    base = ctr.value
    depth_ref, parent_ref = reference_bfs(graph, start=1)

    # Healthy sharded session: bfs serves from the mesh, counter still.
    with Session(graph, ServeConfig(mesh="2"), warm=False) as s:
        got = s.query("bfs", start=1, timeout=300)
        np.testing.assert_array_equal(got["values"], depth_ref)
        np.testing.assert_array_equal(got["parent"], parent_ref)
        assert s.stats()["mesh"]["fallbacks"] == {}
        assert "warning" not in s.stats()["mesh"]
        assert ctr.value == base

    # Broken mesh build: the per-chip engine still answers, and the
    # drop is counted and shouted on /statusz.
    def boom(*a, **kw):
        raise RuntimeError("forced mesh build failure")

    # session.py imports the class at build time, so patching the
    # engine module is what its `from ... import` resolves.
    monkeypatch.setattr(engine_mod, "ShardedAdaptiveExecutor", boom)
    with Session(graph, ServeConfig(mesh="2"), warm=False) as s2:
        got2 = s2.query("bfs", start=1, timeout=300)
        np.testing.assert_array_equal(got2["values"], depth_ref)
        assert "bfs" in s2.stats()["mesh"]["fallbacks"]
        assert "mesh fallback active" in s2.stats()["mesh"]["warning"]
        assert ctr.value == base + 1
