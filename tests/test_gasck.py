"""luxlint program-contract tier: the LUX601-606 prover (gasck), the
gascap.v1 capability artifact, the capability-derived registry/serving
surfaces, the IncrementalExecutor contract gate, the serve-pool audit
hook, and the --programs CLI.

Seeded-violation convention (tests/gas_fixtures/): each ``lux6NN_*.py``
module defines one broken program and must make ``luxlint --programs``
exit 1 with exactly its own rule firing.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from lux_tpu.analysis import gasck  # noqa: E402
from lux_tpu.analysis.gasck import ProgramContractError  # noqa: E402
from lux_tpu.engine.gas import AdaptiveExecutor, GasProgram  # noqa: E402
from lux_tpu.engine.incremental import IncrementalExecutor  # noqa: E402
from lux_tpu.graph.graph import Graph  # noqa: E402
from lux_tpu.models.bfs import BFS  # noqa: E402
from lux_tpu.models.components import ConnectedComponents  # noqa: E402
from lux_tpu.models.sssp import SSSP  # noqa: E402
from lux_tpu.models.sssp_delta import DeltaSSSP  # noqa: E402
from lux_tpu.serve.pool import EnginePool  # noqa: E402
from lux_tpu.utils import flags  # noqa: E402

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
LUXLINT = os.path.join(REPO, "tools", "luxlint.py")
GAS_FIXTURES = os.path.join(TESTS, "gas_fixtures")


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, LUXLINT, *argv],
        capture_output=True, text=True, cwd=REPO,
    )


def _summary_line(stdout):
    lines = [l for l in stdout.splitlines() if l.startswith("LUXLINT ")]
    assert lines, stdout
    return json.loads(lines[-1][len("LUXLINT "):])


def _rules(report):
    return sorted({f.rule for f in report.findings})


def _ring(nv=12):
    src = np.arange(nv, dtype=np.int64)
    return Graph.from_edges(src, (src + 1) % nv, nv)


@pytest.fixture(scope="module")
def registry():
    """One registry prove shared by the matrix assertions (~1s)."""
    return gasck.prove_registry()


# -- probe-grid + scalar-proof units --------------------------------------


def test_probe_grid_extremes_and_hygiene():
    grid = gasck._probe_grid(
        np.array([3.0, -0.0, np.nan], dtype=np.float32),
        np.float32(0.0), np.dtype(np.float32), seed=7)
    assert np.inf in grid and -np.inf in grid
    assert np.finfo(np.float32).max in grid
    assert not np.isnan(grid).any()          # NaN has its own policy probe
    assert not ((grid == 0) & np.signbit(grid)).any()   # no -0.0


def test_identity_check_rejects_zero_for_min():
    probes = gasck._probe_grid(np.array([], dtype=np.uint32),
                               np.uint32(0), np.dtype(np.uint32), seed=7)
    ok, msg, _ = gasck._check_identity(
        np.minimum, np.uint32(0), probes, np.dtype(np.uint32))
    assert not ok and "p=" in msg


def test_identity_check_accepts_engine_identities():
    for combiner, dtype in (("min", np.uint32), ("sum", np.float32),
                            ("max", np.uint32), ("min", np.float32)):
        ident = gasck._identity_np(combiner, np.dtype(dtype))
        probes = gasck._probe_grid(np.array([], dtype=dtype), ident,
                                   np.dtype(dtype), seed=7)
        ok, msg, _ = gasck._check_identity(
            gasck._np_op(combiner), ident, probes, np.dtype(dtype))
        assert ok, (combiner, dtype, msg)


def test_identity_check_flags_asymmetric_nan_policy():
    def lopsided(a, b):
        # NaN is absorbed only from the right operand: push and pull
        # would disagree as soon as edge order differs.
        return np.where(np.isnan(np.asarray(b)), a, np.minimum(a, b))
    probes = np.array([0.0, 1.0], dtype=np.float32)
    ok, msg, _ = gasck._check_identity(
        lopsided, np.float32(np.inf), probes, np.dtype(np.float32))
    assert not ok and "NaN" in msg


def test_algebra_float_sum_is_inexact():
    probes = gasck._probe_grid(np.array([], dtype=np.float32),
                               np.float32(0), np.dtype(np.float32), seed=7)
    ok, msg = gasck._check_algebra(np.add, probes, seed=7, triples=16)
    assert not ok and "associative" in msg


def test_algebra_uint_sum_and_minmax_are_exact():
    for op, dtype in ((np.add, np.uint32), (np.minimum, np.uint32),
                      (np.maximum, np.uint32), (np.minimum, np.float32)):
        ident = np.array(0, dtype=dtype)[()]
        probes = gasck._probe_grid(np.array([], dtype=dtype), ident,
                                   np.dtype(dtype), seed=7)
        ok, msg = gasck._check_algebra(op, probes, seed=7, triples=32)
        assert ok, (op.__name__, dtype, msg)


def test_derive_rooted_from_init_hooks():
    g = gasck._seed_graphs(16, 7)["plain"]
    assert gasck._derive_rooted(BFS(), g)
    assert not gasck._derive_rooted(ConnectedComponents(), g)


# -- registry proof + derived matrix --------------------------------------


def test_registry_proves_clean(registry):
    report, _ = registry
    assert report.ok
    assert report.schema == "luxlint-programs.v1"
    assert len(report.results) == 8
    assert not any(r.error for r in report.results)


def test_registry_derived_matrix(registry):
    _, art = registry
    derived = {n: c["derived"] for n, c in art["programs"].items()}
    assert derived["sssp"] == {"rooted": True, "frontier_ok": True,
                               "incremental_ok": True}
    assert derived["components"]["incremental_ok"]
    assert derived["bfs"]["rooted"] and derived["bfs"]["frontier_ok"]
    assert derived["sssp_delta"] == {"rooted": True, "frontier_ok": True,
                                     "incremental_ok": False}
    # Dense pull programs earn no frontier license.
    assert not derived["pagerank"]["frontier_ok"]
    assert not derived["colfilter"]["frontier_ok"]
    assert {n for n, d in derived.items() if d["frontier_ok"]} == {
        "bfs", "components", "kcore", "labelprop", "sssp", "sssp_delta"}


def test_registry_matches_committed_artifact(registry):
    """The LUX606 offline ratchet: a capability-changing edit must
    regenerate lux_tpu/analysis/gascap.json or verify fails."""
    _, art = registry
    committed = gasck.load_capmap(gasck.capmap_path())
    assert committed["id"] == art["id"]


# -- seeded fixtures: each fails with exactly its rule --------------------


@pytest.mark.parametrize("stem,rule", [
    ("lux601_bad_identity", "LUX601"),
    ("lux602_inexact_sum", "LUX602"),
    ("lux603_push_pull_skew", "LUX603"),
    ("lux604_nonmonotone_incremental", "LUX604"),
    ("lux605_clobbering_apply", "LUX605"),
    ("lux606_overclaimed_frontier", "LUX606"),
])
def test_fixture_fails_with_exactly_its_rule(stem, rule):
    path = os.path.join(GAS_FIXTURES, stem + ".py")
    report = gasck.verify_fixture_paths([path])
    assert not report.ok
    assert _rules(report) == [rule]
    assert not any(r.error for r in report.results)


def test_fixture_select_filters_rules():
    path = os.path.join(GAS_FIXTURES, "lux602_inexact_sum.py")
    report = gasck.verify_fixture_paths([path], select=("LUX601",))
    assert report.ok    # the LUX602 finding is filtered out


# -- the gather_push seam the prover licenses -----------------------------


def test_engine_push_path_consumes_gather_push():
    """LUX603 exists because the engines really do run gather_push on
    the push branch: a skewed override makes pinned-push diverge from
    pinned-pull, and an equal override keeps them bitwise identical."""
    class Skewed(GasProgram):
        name = "skewed"
        servable = False
        frontier_ok = False

        def init_values(self, graph, **kw):
            v = np.full(graph.nv, graph.nv, dtype=np.uint32)
            v[0] = 0
            return v

        def init_frontier(self, graph, **kw):
            f = np.zeros(graph.nv, dtype=bool)
            f[0] = True
            return f

        def gather(self, src_vals, weights):
            return src_vals + np.uint32(1)

        def gather_push(self, src_vals, weights):
            return src_vals + np.uint32(2)

    class Aligned(Skewed):
        name = "aligned"

        def gather_push(self, src_vals, weights):
            return src_vals + np.uint32(1)

    g = _ring(8)
    pull, _ = AdaptiveExecutor(g, Skewed(), mode="pull").run(max_iters=2)
    push, _ = AdaptiveExecutor(g, Skewed(), mode="push").run(max_iters=2)
    assert not np.array_equal(np.asarray(pull.values),
                              np.asarray(push.values))
    apull, _ = AdaptiveExecutor(g, Aligned(), mode="pull").run(max_iters=2)
    apush, _ = AdaptiveExecutor(g, Aligned(), mode="push").run(max_iters=2)
    np.testing.assert_array_equal(np.asarray(apull.values),
                                  np.asarray(apush.values))


# -- gascap.v1 artifact ----------------------------------------------------


def test_capmap_round_trip(tmp_path):
    art = gasck.build_capmap({"x": {"derived": {"rooted": True}}},
                             {"seed": 7})
    path = str(tmp_path / "gascap.json")
    gasck.save_capmap(art, path)
    loaded = gasck.load_capmap(path)
    assert loaded["id"] == art["id"]
    assert loaded["programs"] == art["programs"]


def test_capmap_id_is_content_addressed_not_timestamped():
    a = gasck.build_capmap({"x": {"d": 1}}, {"seed": 7})
    b = gasck.build_capmap({"x": {"d": 1}}, {"seed": 7})
    c = gasck.build_capmap({"x": {"d": 2}}, {"seed": 7})
    assert a["id"] == b["id"]       # created_at excluded from the id
    assert a["id"] != c["id"]


def test_capmap_tamper_rejected(tmp_path):
    art = gasck.build_capmap(
        {"sssp": {"derived": {"incremental_ok": False}}}, {"seed": 7})
    path = str(tmp_path / "gascap.json")
    gasck.save_capmap(art, path)
    doc = json.loads(open(path).read())
    doc["programs"]["sssp"]["derived"]["incremental_ok"] = True
    with open(path, "w") as fh:
        json.dump(doc, fh)
    with pytest.raises(ValueError, match="content hash"):
        gasck.load_capmap(path)


def test_capmap_path_honors_flag(tmp_path):
    with flags.overrides({"LUX_GASCAP_DIR": str(tmp_path)}):
        assert gasck.capmap_path() == str(tmp_path / "gascap.json")
    assert gasck.capmap_path().endswith(
        os.path.join("analysis", "gascap.json"))


# -- capability-derived registry surfaces ---------------------------------


def test_models_capabilities_come_from_artifact():
    import lux_tpu.models as models

    rep = models.capability_report(refresh=True)
    assert rep["source"] == "artifact"
    assert rep["error"] is None
    assert rep["artifact_id"].startswith("gascap-")
    assert models.rooted_apps() == frozenset({"bfs", "sssp", "sssp_delta"})
    assert models.incremental_ok("sssp")
    assert models.incremental_ok("components")
    assert not models.incremental_ok("bfs")
    assert models.frontier_ok("labelprop")
    assert not models.frontier_ok("pagerank")


def test_models_fall_back_to_declared_when_artifact_missing(tmp_path):
    import lux_tpu.models as models

    try:
        with flags.overrides({"LUX_GASCAP_DIR": str(tmp_path)}):
            rep = models.capability_report(refresh=True)
            assert rep["source"] == "declared"
            assert "artifact missing" in rep["error"]
            # Declarations carry the same bits, so routing still works.
            assert models.rooted_apps() == frozenset(
                {"bfs", "sssp", "sssp_delta"})
    finally:
        assert models.capability_report(refresh=True)["source"] == \
            "artifact"


def test_models_reject_tampered_artifact(tmp_path):
    import lux_tpu.models as models

    art = json.loads(open(gasck.capmap_path()).read())
    art["programs"]["sssp"]["derived"]["rooted"] = False
    with open(tmp_path / "gascap.json", "w") as fh:
        json.dump(art, fh)
    try:
        with flags.overrides({"LUX_GASCAP_DIR": str(tmp_path)}):
            rep = models.capability_report(refresh=True)
            assert rep["source"] == "declared"
            assert "artifact rejected" in rep["error"]
    finally:
        models.capability_report(refresh=True)


# -- the IncrementalExecutor contract gate --------------------------------


def test_require_incremental_accepts_proven_programs():
    gasck.require_incremental(SSSP())
    gasck.require_incremental(ConnectedComponents())


def test_incremental_gate_rejects_programs_without_relax():
    with pytest.raises(ProgramContractError, match="LUX604") as ei:
        IncrementalExecutor(_ring(), BFS())
    assert "relax" in str(ei.value)
    with pytest.raises(ProgramContractError, match="LUX604"):
        gasck.require_incremental(DeltaSSSP())


def test_incremental_gate_names_failed_subcheck():
    class Claimant(ConnectedComponents):
        name = "claimant"

        def relax(self, src_vals, weights):
            return src_vals + np.uint32(1)

    # relax moves against the max order (messages exceed their source,
    # so a stale warm start can't be re-reached) -> the gate quotes the
    # inflationarity sub-check, not a generic refusal.
    with pytest.raises(ProgramContractError, match="inflationary"):
        gasck.require_incremental(Claimant())


def test_incremental_executor_still_accepts_proven_programs():
    from lux_tpu.engine.program import as_gas

    g = _ring(16)
    ref, _ = AdaptiveExecutor(g, as_gas(ConnectedComponents())).run()
    inc = IncrementalExecutor(g, ConnectedComponents())
    # No-edit refresh from the converged labels: the gate admits the
    # proven program and the warm start reproduces the fixpoint bitwise.
    state, iters, info = inc.run(np.asarray(ref.values))
    np.testing.assert_array_equal(np.asarray(state.values),
                                  np.asarray(ref.values))


# -- serve-pool audit + session surfaces ----------------------------------


def test_pool_audit_is_advisory_and_counted():
    class BrokenApply(GasProgram):
        name = "pool_broken_apply"
        servable = False
        frontier_ok = False

        def init_values(self, graph, **kw):
            return np.zeros(graph.nv, dtype=np.uint32)

        def init_frontier(self, graph, **kw):
            return np.ones(graph.nv, dtype=bool)

        def gather(self, src_vals, weights):
            return src_vals

        def apply(self, old, acc):
            return acc

    pool = EnginePool(scope="test-gasck")
    try:
        before = pool.stats()["gas_findings"]
        ex = pool.get(("k1",), lambda: types.SimpleNamespace(
            program=BrokenApply()))
        assert ex is not None            # advisory: the build survives
        after = pool.stats()["gas_findings"]
        assert after >= before + 1
        # Cache hit on a second engine for the same program identity.
        pool.get(("k2",), lambda: types.SimpleNamespace(
            program=BrokenApply()))
        assert pool.stats()["gas_findings"] >= after + 1
    finally:
        pool.close()


def test_pool_audit_clean_program_and_flag_gate():
    pool = EnginePool(scope="test-gasck-clean")
    try:
        before = pool.stats()["gas_findings"]
        pool.get(("k",), lambda: types.SimpleNamespace(program=SSSP()))
        assert pool.stats()["gas_findings"] == before
        with flags.overrides({"LUX_GAS_POOL_AUDIT": "0"}):
            pool.get(("k3",), lambda: types.SimpleNamespace(
                program=types.SimpleNamespace(combiner="bogus")))
        assert pool.stats()["gas_findings"] == before   # gated off
    finally:
        pool.close()


def test_session_statusz_programs_block():
    from lux_tpu.obs import metrics
    from lux_tpu.serve.session import Session

    # The findings counter is process-global by design (dashboards sum
    # one series); assert the session adds nothing, not absolute zero.
    before = int(metrics.counter("lux_gas_findings_total").value)
    src = np.array([0, 1, 2, 3], dtype=np.int64)
    g = Graph.from_edges(src, (src + 1) % 4, 4)
    with Session(g, warm=False) as s:
        blk = s.statusz()["programs"]
        assert blk["source"] == "artifact"
        assert blk["artifact_id"].startswith("gascap-")
        assert "error" not in blk
        assert blk["capabilities"]["sssp"]["incremental_ok"]
        assert blk["gas_findings"] == before
        assert s.statusz()["counters"]["gas_findings"] == before
        assert s.stats()["programs"]["source"] == "artifact"


# -- the --programs CLI ----------------------------------------------------


def test_cli_registry_clean():
    r = _run_cli("--programs")
    assert r.returncode == 0, r.stdout + r.stderr
    s = _summary_line(r.stdout)
    assert s["schema"] == "luxlint-programs.v1"
    assert s["ok"] and s["findings"] == 0 and s["files"] == 8


def test_cli_fixture_exits_one_with_its_rule():
    r = _run_cli("--programs",
                 os.path.join(GAS_FIXTURES, "lux603_push_pull_skew.py"))
    assert r.returncode == 1
    s = _summary_line(r.stdout)
    assert s["by_rule"] == {"LUX603": 1}
    assert "direction-adaptive execution" in r.stdout


def test_cli_select_subsets_rules():
    r = _run_cli("--programs", "--select", "LUX601",
                 os.path.join(GAS_FIXTURES, "lux602_inexact_sum.py"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert _summary_line(r.stdout)["findings"] == 0


def test_cli_gascap_out_writes_artifact(tmp_path):
    out = str(tmp_path / "gascap.json")
    r = _run_cli("--programs", "--gascap-out", out)
    assert r.returncode == 0, r.stdout + r.stderr
    art = gasck.load_capmap(out)
    assert art["id"] == gasck.load_capmap(gasck.capmap_path())["id"]


def test_cli_baseline_ratchet(tmp_path):
    base = str(tmp_path / "programs.baseline.json")
    fix = os.path.join(GAS_FIXTURES, "lux601_bad_identity.py")
    first = _run_cli("--programs", fix, "--baseline", base)
    assert first.returncode == 0          # snapshot written, run passes
    assert os.path.exists(base)
    second = _run_cli("--programs", fix, "--baseline", base)
    assert second.returncode == 0         # known finding: ratcheted
    third = _run_cli("--programs",
                     os.path.join(GAS_FIXTURES,
                                  "lux605_clobbering_apply.py"),
                     "--baseline", base)
    assert third.returncode == 1          # new finding escapes the ratchet
    assert "[new]" in third.stdout


def test_cli_tiers_are_mutually_exclusive():
    r = _run_cli("--programs", "--ir")
    assert r.returncode == 2
    assert "separate tiers" in r.stderr


def test_cli_list_rules_documents_the_tier():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rule in ("LUX601", "LUX602", "LUX603", "LUX604", "LUX605",
                 "LUX606"):
        assert rule in r.stdout
