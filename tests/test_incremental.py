"""Incremental recompute: warm-started fixpoints over edit batches must
be bitwise-equal (SSSP/CC) or tolerance-equal (PageRank) to from-scratch
runs, and measurably cheaper in iterations."""

import numpy as np
import pytest

from lux_tpu.engine.incremental import (IncrementalExecutor, invalidate,
                                        incremental_pagerank)
from lux_tpu.engine.push import MultiSourcePushExecutor, PushExecutor
from lux_tpu.engine.pull import PullExecutor
from lux_tpu.graph import DeltaGraph, EdgeEdits, generate
from lux_tpu.graph.delta import removed_edges
from lux_tpu.models.components import ConnectedComponents, \
    reference_components
from lux_tpu.models.pagerank import PageRank, reference_pagerank, true_ranks
from lux_tpu.models.sssp import SSSP, reference_sssp


def _edit(g, seed, n_ins, n_del):
    """A random edit batch plus the (removed, inserted) arrays the
    incremental path consumes and the merged new graph."""
    rng = np.random.default_rng(seed)
    ins = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
           for _ in range(n_ins)]
    dels = []
    if n_del:
        eidx = rng.choice(g.ne, size=min(n_del, g.ne), replace=False)
        dels = [(int(g.col_src[e]), int(g.col_dst[e])) for e in eidx]
    ed = EdgeEdits.from_lists(insert=ins, delete=dels)
    new_g = DeltaGraph.fresh(g).stack(ed).merged()
    removed = removed_edges(g, ed.del_src, ed.del_dst)
    inserted = (ed.ins_src, ed.ins_dst)
    return new_g, removed, inserted


@pytest.fixture(scope="module")
def base():
    return generate.rmat(8, 8, seed=21)


@pytest.mark.parametrize("seed,n_ins,n_del", [
    (1, 20, 0),    # insert-only
    (2, 0, 20),    # delete-only
    (3, 15, 15),   # mixed
    (4, 0, 0),     # empty batch: warm state already at fixpoint
])
def test_sssp_bitwise_parity(base, seed, n_ins, n_del):
    g = base
    start = 3
    old_state, full_old = PushExecutor(g, SSSP()).run(start=start)
    old = np.asarray(old_state.values)
    new_g, removed, inserted = _edit(g, seed, n_ins, n_del)

    state, inc_iters, info = IncrementalExecutor(new_g, SSSP()).run(
        old, removed=removed, inserted=inserted, start=start
    )
    got = np.asarray(state.values)
    np.testing.assert_array_equal(got, reference_sssp(new_g, start))
    full_state, full_iters = PushExecutor(new_g, SSSP()).run(start=start)
    np.testing.assert_array_equal(got, np.asarray(full_state.values))
    assert info["touched_frac"] <= 1.0
    if n_ins == n_del == 0:
        # No edits -> nothing reset, frontier empty, converges instantly.
        assert info["reset"] == 0 and inc_iters <= 1


@pytest.mark.parametrize("seed,n_ins,n_del", [(5, 25, 0), (6, 0, 25),
                                              (7, 12, 12)])
def test_components_bitwise_parity(base, seed, n_ins, n_del):
    """Directed label propagation: incremental must match the from-scratch
    push fixpoint bitwise (the union-find oracle only applies to
    symmetric graphs — see test_components_symmetric_oracle)."""
    g = base
    old_state, _ = PushExecutor(g, ConnectedComponents()).run()
    old = np.asarray(old_state.values)
    new_g, removed, inserted = _edit(g, seed, n_ins, n_del)

    state, _, _ = IncrementalExecutor(new_g, ConnectedComponents()).run(
        old, removed=removed, inserted=inserted
    )
    got = np.asarray(state.values)
    full_state, _ = PushExecutor(new_g, ConnectedComponents()).run()
    np.testing.assert_array_equal(got, np.asarray(full_state.values))


def test_components_symmetric_oracle():
    """On a symmetric graph with symmetrized edits the incremental
    fixpoint matches the union-find oracle bitwise."""
    g = generate.undirected(generate.gnp(200, 350, seed=205))
    old_state, _ = PushExecutor(g, ConnectedComponents()).run()
    old = np.asarray(old_state.values)
    rng = np.random.default_rng(205)
    pairs = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
             for _ in range(8)]
    ins = [p for (u, v) in pairs for p in ((u, v), (v, u))]
    eidx = rng.choice(g.ne, size=8, replace=False)
    dels = [p for e in eidx
            for p in ((int(g.col_src[e]), int(g.col_dst[e])),
                      (int(g.col_dst[e]), int(g.col_src[e])))]
    ed = EdgeEdits.from_lists(insert=ins, delete=dels)
    new_g = DeltaGraph.fresh(g).stack(ed).merged()
    state, _, _ = IncrementalExecutor(new_g, ConnectedComponents()).run(
        old, removed=removed_edges(g, ed.del_src, ed.del_dst),
        inserted=(ed.ins_src, ed.ins_dst)
    )
    np.testing.assert_array_equal(np.asarray(state.values),
                                  reference_components(new_g))


def test_sssp_weighted_parity():
    g = generate.gnp(400, 3000, seed=31, weighted=True)
    rng = np.random.default_rng(31)
    ins = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)),
            int(rng.integers(1, 9))) for _ in range(15)]
    eidx = rng.choice(g.ne, size=15, replace=False)
    dels = [(int(g.col_src[e]), int(g.col_dst[e])) for e in eidx]
    ed = EdgeEdits.from_lists(insert=ins, delete=dels)
    new_g = DeltaGraph.fresh(g).stack(ed).merged()
    old_state, _ = PushExecutor(g, SSSP()).run(start=0)
    state, _, _ = IncrementalExecutor(new_g, SSSP()).run(
        np.asarray(old_state.values),
        removed=removed_edges(g, ed.del_src, ed.del_dst),
        inserted=(ed.ins_src, ed.ins_dst), start=0,
    )
    full_state, _ = PushExecutor(new_g, SSSP()).run(start=0)
    np.testing.assert_array_equal(np.asarray(state.values),
                                  np.asarray(full_state.values))


def test_multi_source_warm_lanes(base):
    """K warm lanes through one dense sweep: each lane bitwise-equal to
    the single-source oracle on the new graph."""
    g = base
    roots = [0, 9, 44, 200]
    cols = []
    for r in roots:
        st, _ = PushExecutor(g, SSSP()).run(start=r)
        cols.append(np.asarray(st.values))
    new_g, removed, inserted = _edit(g, 8, 10, 10)
    inc = IncrementalExecutor(new_g, SSSP(), k=len(roots))
    state, _, info = inc.run_multi(roots, cols, removed=removed,
                                   inserted=inserted)
    for j, r in enumerate(roots):
        np.testing.assert_array_equal(
            inc.multi.values_for(state, j), reference_sssp(new_g, r)
        )
    assert 0.0 <= info["touched_frac"] <= 1.0


def test_multi_source_pads_short_batches(base):
    g = base
    st, _ = PushExecutor(g, SSSP()).run(start=7)
    old = np.asarray(st.values)
    new_g, removed, inserted = _edit(g, 9, 5, 5)
    inc = IncrementalExecutor(new_g, SSSP(), k=4)
    state, _, _ = inc.run_multi([7], [old], removed=removed,
                                inserted=inserted)
    want = reference_sssp(new_g, 7)
    for j in range(4):
        np.testing.assert_array_equal(inc.multi.values_for(state, j), want)
    with pytest.raises(ValueError):
        inc.run_multi([1, 2], [old])   # one column per root
    with pytest.raises(ValueError, match="no MultiSourcePushExecutor"):
        IncrementalExecutor(new_g, SSSP()).run_multi([1], [old])


def test_shape_mismatch_rejected(base):
    g = base
    with pytest.raises(ValueError, match="snapshots never change nv"):
        IncrementalExecutor(g, SSSP()).run(
            np.zeros(g.nv - 1, dtype=np.uint32), start=0
        )


def test_invalidate_only_resets_unsupported(base):
    """Removing a non-supporting edge resets nothing; removing the sole
    support of a vertex resets it (and, transitively, its dependents)."""
    g = generate.gnp(300, 1200, seed=41)
    st, _ = PushExecutor(g, SSSP()).run(start=0)
    old = np.asarray(st.values)
    init = np.asarray(SSSP().init_values(g, start=0))
    # An edge u->v that does NOT support v: old[u]+1 != old[v].
    prog = SSSP()
    for e in range(g.ne):
        u, v = int(g.col_src[e]), int(g.col_dst[e])
        if old[u] + 1 != old[v]:
            reset = invalidate(prog, g, old, init, [u], [v], None)
            assert not reset.any()
            break
    # The sole support: pick a v at distance d whose only in-edge from
    # distance d-1 is unique.
    reset_any = invalidate(prog, g, old, init,
                           g.col_src.astype(np.int64),
                           g.col_dst.astype(np.int64),
                           g.weights)
    # Deleting every edge resets every reachable non-root vertex.
    reachable = (old != init) | (np.arange(g.nv) == 0)
    assert (reset_any == ((old != init) & reachable)).all()


def test_incremental_fewer_iterations(base):
    """The measurable-speedup contract: a 1% edit batch converges in
    strictly fewer push iterations than the from-scratch run."""
    g = base
    start = 3
    old_state, _ = PushExecutor(g, SSSP()).run(start=start)
    old = np.asarray(old_state.values)
    n = max(1, g.ne // 100)
    new_g, removed, inserted = _edit(g, 10, n, n)
    _, full_iters = PushExecutor(new_g, SSSP()).run(start=start, chunk=1)
    _, inc_iters, info = IncrementalExecutor(new_g, SSSP()).run(
        old, removed=removed, inserted=inserted, start=start, chunk=1
    )
    assert inc_iters < full_iters
    assert info["touched_frac"] < 1.0


def test_parity_after_compaction_round_trip(base):
    """Warm-start off a compacted snapshot's graph: compaction re-anchors
    the CSC but must not perturb incremental results."""
    g = base
    st, _ = PushExecutor(g, SSSP()).run(start=3)
    old = np.asarray(st.values)
    rng = np.random.default_rng(50)
    ed = EdgeEdits.from_lists(
        insert=[(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
                for _ in range(10)])
    dg = DeltaGraph.fresh(g).stack(ed)
    compacted = DeltaGraph.fresh(dg.merged())   # the compaction re-anchor
    state, _, _ = IncrementalExecutor(compacted.merged(), SSSP()).run(
        old, inserted=(ed.ins_src, ed.ins_dst), start=3
    )
    np.testing.assert_array_equal(np.asarray(state.values),
                                  reference_sssp(dg.merged(), 3))


def test_trace_step_shapes(base):
    """The luxlint-IR hook returns the wrapped push step with a warm
    state of the audited shapes."""
    spec = IncrementalExecutor(base, SSSP()).trace_step(start=0)
    assert spec["kind"] == "push_incremental"
    state = spec["args"][0]
    assert state.values.shape == (base.nv,)


def test_incremental_pagerank_tolerance(base):
    g = base
    ni = 50
    old_stored = np.asarray(PullExecutor(g, PageRank()).run(ni))
    new_g, _, _ = _edit(g, 12, 10, 10)
    stored, iters = incremental_pagerank(
        PullExecutor(new_g, PageRank()), old_stored, g.out_degrees,
        ni, tol=1e-7,
    )
    # reference_pagerank returns the same stored (pre-divided) convention;
    # compare true rank mass so the tolerance is degree-independent.
    want = np.asarray(true_ranks(reference_pagerank(new_g, ni),
                                 new_g.out_degrees))
    got = np.asarray(true_ranks(stored, new_g.out_degrees))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-6)
    assert iters < ni   # warm start converges early
