"""luxlint-IR: the jaxpr-tier rules (LUX101-105), the registry trace
matrix, the grouped-plan artifact verifier (LUX201-205), the serve-pool
donation-audit hook, and the CLI tiers (--ir / --plans / --changed /
--baseline).

Seeded-violation convention (tests/ir_fixtures/): each ``lux1NN_*.py``
module exposes ``TRACES`` and must make ``luxlint --ir`` exit 1 with
exactly its own rule firing.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lux_tpu.analysis import ir, planck  # noqa: E402
from lux_tpu.models import ENGINE_KINDS  # noqa: E402
from lux_tpu.ops import merge_tail_plan as mtp  # noqa: E402

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
LUXLINT = os.path.join(REPO, "tools", "luxlint.py")
IR_FIXTURES = os.path.join(TESTS, "ir_fixtures")


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, LUXLINT, *argv],
        capture_output=True, text=True, cwd=REPO,
    )


def _summary_line(stdout):
    lines = [l for l in stdout.splitlines() if l.startswith("LUXLINT ")]
    assert lines, stdout
    return json.loads(lines[-1][len("LUXLINT "):])


def _rules(report):
    return sorted({f.rule for r in report.results for f in r.findings})


def _spec_target(call=None, **spec):
    if call is not None:
        spec.setdefault("call", call)
    spec.setdefault("args", (jnp.zeros(64, jnp.float32),))
    return ir.target_from_spec(spec.pop("name", "unit@test"), spec)


# -- IR rule units ------------------------------------------------------


def _matrix_names():
    """Expected registry-matrix target names: every program x capable
    executor, plus a ``+compact`` variant per sharded kind (the compact
    fixture graphs are chosen so the plan always engages — a fallback
    would shrink collective-audit coverage and fail here), plus a
    ``+frontier`` variant for every frontier program on the adaptive
    sharded GAS engine (frontier-less programs downgrade to compact by
    design and carry no extra target)."""
    from lux_tpu.engine.gas import as_gas
    from lux_tpu.models import get_program

    want = {f"{p}@{k}" for p, kinds in ENGINE_KINDS.items() for k in kinds}
    want |= {f"{p}@{k}+compact" for p, kinds in ENGINE_KINDS.items()
             for k in kinds if k.endswith("sharded")}
    want |= {f"{p}@gas_sharded+frontier"
             for p, kinds in ENGINE_KINDS.items()
             if "gas_sharded" in kinds
             and as_gas(get_program(p)).frontier}
    return want


def test_registry_matrix_is_clean_and_complete():
    # The acceptance gate `make lint-ir` runs: every registered program x
    # capable executor traces, and the shipped tree carries no findings.
    targets = ir.registry_targets()
    assert {t.name for t in targets} == _matrix_names()
    report = ir.run_targets(targets)
    assert report.ok, report.format_human()
    assert report.summary()["schema"] == "luxlint.ir.v1"


def test_dtype_drift_on_carry():
    t = _spec_target(lambda v: (v * 2).astype(jnp.bfloat16))
    report = ir.run_targets([t], [ir.DtypeDrift()])
    assert _rules(report) == ["LUX101"]
    assert "bfloat16" in report.results[0].findings[0].message


def test_dtype_drift_carry_cannot_roundtrip():
    # More carry leaves than step outputs: the carry cannot survive the
    # step at all — one target-level finding, not a crash.
    t = _spec_target(
        lambda a, b: a + b,
        args=(jnp.zeros(8), jnp.zeros(8)), carry=(0, 1),
    )
    report = ir.run_targets([t], [ir.DtypeDrift()])
    assert _rules(report) == ["LUX101"]
    assert "round" in report.results[0].findings[0].message


def test_host_callback_detected_through_jit_nesting():
    def step(v):
        return jax.jit(lambda x: jax.pure_callback(
            lambda y: np.asarray(y) * 2,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x))(v)

    report = ir.run_targets([_spec_target(step)], [ir.HostCallback()])
    assert _rules(report) == ["LUX102"]


def test_footprint_blowup_respects_flag(monkeypatch):
    t = _spec_target(lambda v: jnp.outer(v, v).sum(axis=1),
                     args=(jnp.zeros(512, jnp.float32),))
    report = ir.run_targets([t], [ir.FootprintBlowup()])
    assert _rules(report) == ["LUX103"]
    monkeypatch.setenv("LUX_IR_BLOWUP", "100000")
    report = ir.run_targets([t], [ir.FootprintBlowup()])
    assert report.ok


def test_donation_audit_passes_aliased_flags_unusable():
    good = jax.jit(lambda v: v * 2, donate_argnums=0)
    bad = jax.jit(lambda v: v.sum(), donate_argnums=0)
    x = (jnp.zeros(64, jnp.float32),)
    rep = ir.run_targets(
        [_spec_target(fn=good, args=x, donate=(0,)),
         _spec_target(fn=bad, args=x, donate=(0,), carry=(), name="u@bad")],
        [ir.DonationAudit()],
    )
    assert not rep.results[0].findings
    assert [f.rule for f in rep.results[1].findings] == ["LUX104"]


def test_collective_audit_both_directions():
    psum = _spec_target(lambda v: jax.lax.psum(v, "p"),
                        axis_env=(("p", 4),))
    silent = _spec_target(lambda v: v * 0.5, sharded=True)
    rep = ir.run_targets([psum, silent], [ir.CollectiveAudit()])
    assert [f.rule for r in rep.results for f in r.findings] == \
        ["LUX105", "LUX105"]


def test_trace_failure_is_error_not_crash():
    def boom(v):
        raise RuntimeError("fixture trace bomb")

    report = ir.run_targets([_spec_target(boom)])
    assert not report.ok
    assert "trace failed" in report.results[0].error


@pytest.mark.parametrize("fixture,rule", [
    ("lux101_dtype_drift.py", "LUX101"),
    ("lux102_host_callback.py", "LUX102"),
    ("lux103_blowup.py", "LUX103"),
    ("lux104_donation.py", "LUX104"),
    ("lux105_collective.py", "LUX105"),
])
def test_seeded_fixture_fires_exactly_its_rule(fixture, rule):
    targets = ir.load_fixture_targets(os.path.join(IR_FIXTURES, fixture))
    report = ir.run_targets(targets)
    assert not report.ok
    assert _rules(report) == [rule]
    assert report.summary()["errors"] == 0


# -- grouped-plan verifier (planck) -------------------------------------


@pytest.fixture(scope="module")
def small_plan():
    rng = np.random.default_rng(3)
    sizes = np.minimum(
        rng.lognormal(5.0, 1.2, size=48).astype(np.int64) + 1, 4000)
    m = int(sizes.sum())
    sb = np.repeat(np.arange(sizes.size), sizes)
    rng.shuffle(sb)
    lane = rng.integers(0, 128, size=m)
    dst = np.sort(rng.integers(0, 64, size=m))
    row_ptr = np.searchsorted(dst, np.arange(65))
    return mtp.plan_grouped_tail(sb, lane, row_ptr)


def _mutable(plan, **over):
    """A SimpleNamespace copy of the plan with writable arrays."""
    import types

    d = {n: np.array(getattr(plan, n)) for n in planck.PLAN_ARRAYS}
    d.update(n_edges=plan.n_edges, n_levels=plan.n_levels)
    d.update(over)
    return types.SimpleNamespace(**d)


def test_planner_output_verifies_clean(small_plan):
    res = planck.verify_plan(small_plan)
    assert not res.findings and res.error is None


def test_plan_contract_parity_with_ops():
    # planck duplicates the artifact contract to stay jax-free; this is
    # the drift tripwire the duplication comment promises.
    assert planck.PLAN_ARRAYS == mtp.PLAN_ARRAYS
    assert planck.PLAN_FORMAT == mtp.PLAN_FORMAT


def test_plan_loader_roundtrip(tmp_path, small_plan):
    path = str(tmp_path / "plan")
    mtp.save_grouped_plan(path, small_plan)
    loaded = planck.load_plan_artifact(path)
    assert loaded.n_edges == small_plan.n_edges
    assert loaded.n_levels == small_plan.n_levels
    for name in planck.PLAN_ARRAYS:
        np.testing.assert_array_equal(
            np.asarray(getattr(loaded, name)),
            np.asarray(getattr(small_plan, name)), err_msg=name)
    assert not planck.verify_plan(loaded, path).findings


def test_plan_structure_rejects_nonmonotone_level_ptr(small_plan):
    lp = np.array(small_plan.level_ptr)
    lp[2] = lp[1] - 1
    res = planck.verify_plan(_mutable(small_plan, level_ptr=lp))
    assert "LUX201" in {f.rule for f in res.findings}


def test_plan_conservation_rejects_extra_real(small_plan):
    nv = np.array(small_plan.nvalid)
    idx = int(np.argmax(nv < planck.BLOCK))
    assert nv[idx] < planck.BLOCK
    nv[idx] += 1
    res = planck.verify_plan(_mutable(small_plan, nvalid=nv))
    assert {f.rule for f in res.findings} == {"LUX202"}


def test_plan_code_plane_rejects_pad_garbage(small_plan):
    codes = np.array(small_plan.codes)
    nv = np.asarray(small_plan.nvalid)
    idx = int(np.argmax(nv < planck.BLOCK))
    codes[idx, -1] = 3
    res = planck.verify_plan(_mutable(small_plan, codes=codes))
    assert {f.rule for f in res.findings} == {"LUX203"}


def test_plan_code_plane_rejects_wrong_side_lane(small_plan):
    codes = np.array(small_plan.codes)
    nv = np.asarray(small_plan.nvalid)
    r0 = int(small_plan.level_ptr[1])
    idx = int(np.argmax(nv[:r0] > 0))   # a live level-0 (copy-A) row
    codes[idx, 0] = -5
    res = planck.verify_plan(_mutable(small_plan, codes=codes))
    assert {f.rule for f in res.findings} == {"LUX203"}


def test_plan_code_plane_rejects_unknown_mode(small_plan):
    mode = np.array(small_plan.mode)
    mode[0] = 7
    res = planck.verify_plan(_mutable(small_plan, mode=mode))
    assert "LUX203" in {f.rule for f in res.findings}


def test_plan_alignment_rejects_shifted_boundary(small_plan):
    lp = np.array(small_plan.level_ptr)
    lp[1] += 1   # still monotone: every level holds >= 8 rows
    res = planck.verify_plan(_mutable(small_plan, level_ptr=lp))
    assert "LUX204" in {f.rule for f in res.findings}


def test_plan_copy_rate_bound_is_flag_tunable(small_plan, monkeypatch):
    monkeypatch.setenv("LUX_PLANCK_INFLATION", "0.01")
    res = planck.verify_plan(small_plan)
    assert "LUX205" in {f.rule for f in res.findings}


def test_unloadable_plan_dir_is_error(tmp_path):
    report = planck.verify_plan_dirs([str(tmp_path / "nope")])
    assert not report.ok
    assert "unloadable" in report.results[0].error


# -- serve-pool donation audit ------------------------------------------


class _BadDonationEngine:
    def trace_step(self):
        fn = jax.jit(lambda v: v.sum(), donate_argnums=0)
        return {"kind": "bad", "fn": fn,
                "args": (jnp.zeros(64, jnp.float32),),
                "donate": (0,), "carry": (), "sharded": False}


def test_pool_build_runs_donation_audit(recwarn):
    from lux_tpu.obs import metrics
    from lux_tpu.serve.pool import EnginePool

    before = metrics.counter("lux_ir_findings_total").value
    pool = EnginePool(scope="t_ir_audit")
    try:
        pool.get("bad", _BadDonationEngine)
        assert metrics.counter("lux_ir_findings_total").value == before + 1
        assert pool.stats()["ir_findings"] >= 1
    finally:
        pool.close()


def test_pool_audit_disabled_by_flag(monkeypatch, recwarn):
    from lux_tpu.obs import metrics
    from lux_tpu.serve.pool import EnginePool

    monkeypatch.setenv("LUX_IR_POOL_AUDIT", "0")
    before = metrics.counter("lux_ir_findings_total").value
    pool = EnginePool(scope="t_ir_audit_off")
    try:
        pool.get("bad", _BadDonationEngine)
        assert metrics.counter("lux_ir_findings_total").value == before
    finally:
        pool.close()


def test_pool_concurrent_first_requests_build_once():
    from lux_tpu.serve.pool import EnginePool

    pool = EnginePool(scope="t_ir_race")
    builds = []
    barrier = threading.Barrier(8)

    def factory():
        builds.append(1)
        return object()

    def worker(out, i):
        barrier.wait()
        out[i] = pool.get("k", factory)

    try:
        got = [None] * 8
        threads = [threading.Thread(target=worker, args=(got, i))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(builds) == 1
        assert len({id(g) for g in got}) == 1
        assert len(pool) == 1
    finally:
        pool.close()


# -- CLI tiers ----------------------------------------------------------


def test_cli_ir_matrix_is_green():
    proc = _run_cli("--ir")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    s = _summary_line(proc.stdout)
    assert s["schema"] == "luxlint.ir.v1"
    assert s["files"] == len(_matrix_names())
    assert s["findings"] == 0 and s["errors"] == 0


def test_cli_ir_fixture_exits_nonzero():
    proc = _run_cli("--ir", os.path.join(IR_FIXTURES,
                                         "lux104_donation.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert _summary_line(proc.stdout)["by_rule"] == {"LUX104": 1}


def test_cli_plans_accepts_good_rejects_corrupt(tmp_path, small_plan):
    good = str(tmp_path / "plan")
    mtp.save_grouped_plan(good, small_plan)
    proc = _run_cli("--plans", good)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert _summary_line(proc.stdout)["schema"] == "luxlint.plan.v1"

    lp = np.load(os.path.join(good, "level_ptr.npy"))
    lp[2] = lp[1] - 1
    np.save(os.path.join(good, "level_ptr.npy"), lp)
    proc = _run_cli("--plans", good)
    assert proc.returncode == 1
    assert "LUX201" in proc.stdout


def test_cli_baseline_masks_known_findings(tmp_path):
    bad = tmp_path / "engine" / "run_bad.py"
    bad.parent.mkdir()
    bad.write_text(
        "def run(step, vals, n):\n"
        "    for _ in range(n):\n"
        "        vals = step(vals)\n"
        "        done = vals.item()\n"
        "    return vals, done\n"
    )
    base = str(tmp_path / "baseline.json")
    # First run snapshots the pre-existing finding and passes.
    proc = _run_cli(str(tmp_path / "engine"), "--baseline", base)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "baseline written" in proc.stdout
    # Unchanged tree: the known finding stays masked.
    proc = _run_cli(str(tmp_path / "engine"), "--baseline", base)
    assert proc.returncode == 0
    assert "0 new" in proc.stdout
    # A fresh violation is NOT masked.
    worse = tmp_path / "engine" / "run_worse.py"
    worse.write_text(
        "def run(step, vals, n):\n"
        "    for _ in range(n):\n"
        "        x = float(vals.sum())\n"
        "    return x\n"
    )
    proc = _run_cli(str(tmp_path / "engine"), "--baseline", base)
    assert proc.returncode == 1
    assert "[new]" in proc.stdout


def test_cli_changed_emits_summary():
    # Content depends on git state; the contract is: it runs, restricts
    # to changed files, and still emits the greppable summary line.
    proc = _run_cli("--changed")
    assert proc.returncode in (0, 1), proc.stdout + proc.stderr
    assert _summary_line(proc.stdout)["schema"] == "luxlint.v1"
