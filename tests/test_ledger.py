"""Run ledger + per-query cost attribution.

Covers the durability contract (crc framing, rotation, WAL torn-tail
reopen, interior-corruption strictness, concurrent writers), the
``flags.config_hash`` reproducibility rules (path-kind flags excluded),
the ``lux doctor`` A/B attributor on a seeded regression, and the serve
cost pipeline: per-tenant totals that agree exactly with the
``lux_query_cost_*`` metrics, cache-hit outcomes, and the unarmed
zero-cost default.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from lux_tpu.graph import generate
from lux_tpu.obs import ledger, metrics
from lux_tpu.serve import ServeConfig, Session
from lux_tpu.serve.cost import DEFAULT_TENANT, CostAccounts, QueryCost
from lux_tpu.utils import flags

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
DOCTOR = os.path.join(REPO, "tools", "lux_doctor.py")


@pytest.fixture
def armed(tmp_path, monkeypatch):
    """Arm the ledger at a fresh directory; disarm afterwards."""
    root = str(tmp_path / "ledger")
    monkeypatch.setenv("LUX_LEDGER_DIR", root)
    ledger.reset()
    yield root
    ledger.reset()


def _metric_value(name, **labels):
    for m in metrics.snapshot():
        if m["name"] == name and m["labels"] == labels:
            return m["value"]
    return None


# -- framing + durability -------------------------------------------------


def test_record_run_roundtrip_and_frame(armed):
    rid = ledger.record_run(
        "engine_run", {"gteps": 1.5, "nv": 100, "ne": 700},
        program="PageRank", engine_kind="pull",
    )
    assert rid
    segs = ledger.RunLedger(armed).segments()
    assert len(segs) == 1
    raw = open(segs[0], "rb").read()
    assert raw.startswith(b"LUXRR1 ") and raw.endswith(b"\n")
    (rec,) = ledger.read_all(armed, strict=True)
    assert rec["schema"] == ledger.SCHEMA
    assert rec["id"] == rid
    assert rec["kind"] == "engine_run"
    assert rec["metrics"]["gteps"] == 1.5
    key = rec["key"]
    assert key["graph_fingerprint"] == "nv100-ne700"   # weak fallback
    assert key["program"] == "PageRank"
    assert key["config_hash"] == flags.config_hash()
    assert rec["key_string"] == ledger.key_string(**key)
    assert rec["config"].get("LUX_LEDGER_ROTATE_BYTES") is not None


def test_unarmed_record_run_is_none(tmp_path, monkeypatch):
    monkeypatch.delenv("LUX_LEDGER_DIR", raising=False)
    ledger.reset()
    assert not ledger.enabled()
    assert ledger.record_run("engine_run", {"gteps": 1.0}) is None
    assert ledger.read_all() == []


def test_torn_tail_is_truncated_on_reopen(armed):
    led = ledger.RunLedger(armed)
    ledger.record_run("engine_run", {"gteps": 1.0}, program="A")
    seg = led.segments()[0]
    with open(seg, "ab") as f:
        f.write(b"LUXRR1 0000dead {\"half\": ")       # crash mid-append
    ledger.record_run("engine_run", {"gteps": 2.0}, program="B")
    recs = ledger.read_all(armed, strict=True)        # strict: no bad lines
    assert [r["key"]["program"] for r in recs] == ["A", "B"]
    v = ledger.validate_dir(armed)
    assert v["ok"] == 2 and v["interior_bad"] == 0 and v["torn_segments"] == 0


def test_crc_bad_final_line_is_torn_not_corrupt(armed):
    led = ledger.RunLedger(armed)
    ledger.record_run("engine_run", {"gteps": 1.0}, program="A")
    with open(led.segments()[0], "ab") as f:
        f.write(b"LUXRR1 00000000 {\"bad\": \"crc\"}\n")
    ledger.record_run("engine_run", {"gteps": 2.0}, program="B")
    recs = ledger.read_all(armed, strict=True)
    assert [r["key"]["program"] for r in recs] == ["A", "B"]


def test_interior_corruption_raises_strict_skips_lenient(armed):
    led = ledger.RunLedger(armed)
    led.append({"schema": ledger.SCHEMA, "n": 1})
    led.append({"schema": ledger.SCHEMA, "n": 2})
    seg = led.segments()[0]
    buf = bytearray(open(seg, "rb").read())
    first_nl = buf.index(b"\n")
    buf[first_nl - 2] ^= 0xFF                # flip a byte mid-record
    open(seg, "wb").write(bytes(buf))
    with pytest.raises(ledger.LedgerCorruptError):
        ledger.read_all(armed, strict=True)
    lenient = ledger.read_all(armed)
    assert [r["n"] for r in lenient] == [2]
    v = ledger.validate_dir(armed)
    assert v["interior_bad"] == 1
    # Reopen-for-append must NOT truncate interior corruption away: the
    # valid line after it proves those bytes were once durable.
    led.append({"schema": ledger.SCHEMA, "n": 3})
    assert [r["n"] for r in ledger.read_all(armed)] == [2, 3]
    assert ledger.validate_dir(armed)["interior_bad"] == 1


def test_rotation_and_latest_index(armed, monkeypatch):
    monkeypatch.setenv("LUX_LEDGER_ROTATE_BYTES", "1")   # rotate every append
    for i in range(4):
        ledger.record_run("engine_run", {"i": i, "nv": 8, "ne": 8},
                          program="PageRank", engine_kind="pull")
    led = ledger.RunLedger(armed)
    assert len(led.segments()) == 4
    recs = led.read(strict=True)
    assert [r["metrics"]["i"] for r in recs] == [0, 1, 2, 3]
    key = recs[-1]["key_string"]
    assert led.latest(key)["metrics"]["i"] == 3


def test_concurrent_writers_all_land(armed):
    led = ledger.RunLedger(armed)

    def spin(w):
        for i in range(25):
            led.append({"schema": ledger.SCHEMA, "w": w, "i": i})

    threads = [threading.Thread(target=spin, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = led.read(strict=True)
    assert len(recs) == 200
    assert len({r["id"] for r in recs}) == 200


# -- config_hash ----------------------------------------------------------


def test_config_hash_ignores_path_flags(monkeypatch):
    base = flags.config_hash()
    monkeypatch.setenv("LUX_LEDGER_DIR", "/some/other/place")
    assert flags.config_hash() == base      # path kind: artifact sink
    monkeypatch.setenv("LUX_METRICS", "/tmp/m.json")
    assert flags.config_hash() == base


def test_config_hash_tracks_behavior_flags(monkeypatch):
    base = flags.config_hash()
    monkeypatch.setenv("LUX_LEDGER_ROTATE_BYTES", "12345")
    changed = flags.config_hash()
    assert changed != base
    monkeypatch.setenv("LUX_LEDGER_ROTATE_BYTES", "12345")
    assert flags.config_hash() == changed   # deterministic
    assert flags.snapshot()["LUX_LEDGER_ROTATE_BYTES"] == "12345"


# -- lux doctor -----------------------------------------------------------


def test_doctor_attributes_phase_and_flag(armed, monkeypatch):
    def seed(gteps, exchange_s, n=2):
        for _ in range(n):
            ledger.record_run(
                "engine_run",
                {"gteps": gteps, "execute_s": 1.0 / gteps,
                 "phases": {"exchange_s": exchange_s, "compute_s": 0.30}},
                graph_fingerprint="fp-doctor", program="PageRank",
                engine_kind="pull", mesh_shape="1x8",
            )

    monkeypatch.setenv("LUX_LEDGER_ROTATE_BYTES", "8388608")
    seed(gteps=2.0, exchange_s=0.10)                 # cohort A
    monkeypatch.setenv("LUX_LEDGER_ROTATE_BYTES", "4194304")
    seed(gteps=1.0, exchange_s=0.50)                 # cohort B: regressed
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, DOCTOR, "--dir", armed, "--json"],
        capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 3, proc.stderr
    report = json.loads(proc.stdout)
    assert report["schema"] == "doctor.v1" and report["ok"] is False
    (pair,) = report["pairs"]
    assert pair["key"]["graph_fingerprint"] == "fp-doctor"
    regressed = {r["metric"] for r in pair["regressions"]}
    assert "gteps" in regressed and "phases.exchange_s" in regressed
    assert pair["phase"] == "exchange"
    diff = pair["config_diff"]
    assert diff == {"LUX_LEDGER_ROTATE_BYTES":
                    {"a": "8388608", "b": "4194304"}}


def test_doctor_clean_on_single_cohort(armed):
    ledger.record_run("engine_run", {"gteps": 1.0}, program="PageRank")
    proc = subprocess.run(
        [sys.executable, DOCTOR, "--dir", armed, "--json"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] is True and report["pairs"] == []


# -- query cost accounting ------------------------------------------------


def test_query_cost_accumulates_and_renders():
    c = QueryCost(None, "sssp")
    assert c.tenant == DEFAULT_TENANT and c.outcome == "miss"
    c.charge(iterations=5, engine_s=0.25, exchange_bytes=1024,
             direction_switches=1)
    c.charge(iterations=2, engine_s=0.05)
    d = c.as_dict()
    assert d["iterations"] == 7 and d["exchange_bytes"] == 1024
    assert d["engine_s"] == pytest.approx(0.30)
    hdr = QueryCost("acme", "pagerank")
    hdr.outcome = "hit"
    assert hdr.header() == ("tenant=acme;outcome=hit;iters=0;"
                            "engine_s=0.000000;exchange_bytes=0;switches=0")


def test_cost_accounts_totals_match_metrics_exactly():
    """The parity invariant: /costz totals and the lux_query_cost_*
    metric values are incremented in the same observe() call, so for a
    tenant only this accountant touches they are EQUAL, not close."""
    clock = [100.0]
    acc = CostAccounts(windows=(60.0,), now=lambda: clock[0])
    tenant = "parity-tenant"
    spent = []
    for i, outcome in enumerate(["miss", "miss", "hit"]):
        c = QueryCost(tenant, "sssp")
        c.outcome = outcome
        if outcome == "miss":
            c.charge(iterations=3 + i, engine_s=0.01 * (i + 1),
                     exchange_bytes=512 * (i + 1))
        acc.observe(c)
        spent.append(c)
        clock[0] += 1.0
    tot = acc.totals()[tenant]
    assert tot["requests"] == 3 and tot["hits"] == 1 and tot["misses"] == 2
    assert tot["iterations"] == sum(c.iterations for c in spent)
    assert tot["engine_s"] == sum(c.engine_s for c in spent)
    assert tot["exchange_bytes"] == sum(c.exchange_bytes for c in spent)
    assert _metric_value("lux_query_cost_engine_seconds",
                         tenant=tenant) == tot["engine_s"]
    assert _metric_value("lux_query_cost_exchange_bytes",
                         tenant=tenant) == tot["exchange_bytes"]
    assert _metric_value("lux_query_cost_iterations_total",
                         tenant=tenant) == tot["iterations"]
    assert _metric_value("lux_query_cost_requests_total",
                         tenant=tenant, outcome="miss") == 2
    assert _metric_value("lux_query_cost_requests_total",
                         tenant=tenant, outcome="hit") == 1
    snap = acc.snapshot()
    assert snap["schema"] == "costz.v1"
    w = snap["windows"]["60s"][tenant]
    assert w["count"] == 3 and w["engine_s_p50"] >= 0.0


# -- serve end to end: costs + ledger feed-ins ----------------------------


@pytest.fixture(scope="module")
def costed_session(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("led") / "ledger")
    os.environ["LUX_LEDGER_DIR"] = root
    ledger.reset()
    g = generate.gnp(200, 1200, seed=311)
    cfg = ServeConfig(max_batch=2, window_s=0.05, max_queue=32,
                      pagerank_iters=3)
    try:
        with Session(g, cfg) as s:
            yield g, s, root
    finally:
        os.environ.pop("LUX_LEDGER_DIR", None)
        ledger.reset()


def test_serve_costs_per_tenant_and_ledger_records(costed_session):
    _g, s, root = costed_session
    tenant = "acme-test"
    futs = [s.submit("sssp", start=r, tenant=tenant) for r in (1, 7, 42)]
    for f in futs:
        f.result(60)
    costs = [f._lux_cost for f in futs]
    assert all(c.tenant == tenant and c.outcome == "miss" for c in costs)
    assert all(c.iterations > 0 and c.engine_s > 0.0 for c in costs)
    # Per-query shares sum exactly to the tenant totals (batch members
    # split the batch's engine seconds / exchange bytes with no loss).
    tot = s.costs.totals()[tenant]
    assert tot["requests"] == 3 and tot["misses"] == 3
    assert tot["iterations"] == sum(c.iterations for c in costs)
    assert tot["engine_s"] == pytest.approx(
        sum(c.engine_s for c in costs))
    assert tot["exchange_bytes"] == sum(c.exchange_bytes for c in costs)
    # Metric parity for this tenant (only this session books it).
    assert _metric_value("lux_query_cost_engine_seconds",
                         tenant=tenant) == pytest.approx(tot["engine_s"])
    # Cache hit books as outcome=hit with zero engine spend.
    s.query("pagerank", tenant=tenant, timeout=60)
    hit = s.submit("pagerank", tenant=tenant)
    hit.result(60)
    assert hit._lux_cost.outcome == "hit"
    assert hit._lux_cost.engine_s == 0.0
    assert s.costs.totals()[tenant]["hits"] >= 1
    # Unlabeled traffic books to the default tenant.
    s.query("sssp", start=3, timeout=60)
    assert DEFAULT_TENANT in s.costs.totals()
    # /costz payload carries the reproducibility hash.
    cz = s.costz()
    assert cz["schema"] == "costz.v1"
    assert cz["config"]["hash"] == flags.config_hash()
    assert cz["totals"][tenant]["requests"] >= 5
    assert s.statusz()["config"]["hash"] == flags.config_hash()
    # The armed ledger collected the feed-ins: warmup + engine runs.
    recs = ledger.read_all(root, strict=True)
    kinds = {r["kind"] for r in recs}
    assert "serve_warmup" in kinds and "engine_run" in kinds
    warm = next(r for r in recs if r["kind"] == "serve_warmup")
    assert warm["key"]["program"] == "serve"
    assert warm["metrics"]["warm_s"] > 0.0
    assert warm["key"]["config_hash"] == flags.config_hash()
    assert ledger.validate_dir(root)["interior_bad"] == 0
