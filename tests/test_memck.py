"""luxlint memory tier: the LUX701-706 prover (memck), the memcap.v1
footprint artifact, the HBM-budgeted EnginePool admission it feeds,
the tuner's footprint pruning, and the --memory CLI.

Seeded-violation convention (tests/mem_fixtures/): each ``lux7NN_*.py``
module seeds one broken contract and must make ``luxlint --memory``
exit 1 with exactly its own rule firing.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from lux_tpu.analysis import memck  # noqa: E402
from lux_tpu.graph.graph import Graph  # noqa: E402
from lux_tpu.serve.errors import PoolOverBudgetError  # noqa: E402
from lux_tpu.serve.pool import EnginePool  # noqa: E402
from lux_tpu.tune import space  # noqa: E402
from lux_tpu.utils import flags  # noqa: E402

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
LUXLINT = os.path.join(REPO, "tools", "luxlint.py")
MEM_FIXTURES = os.path.join(TESTS, "mem_fixtures")


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, LUXLINT, *argv],
        capture_output=True, text=True, cwd=REPO,
    )


def _summary_line(stdout):
    lines = [l for l in stdout.splitlines() if l.startswith("LUXLINT ")]
    assert lines, stdout
    return json.loads(lines[-1][len("LUXLINT "):])


def _rules(report):
    return sorted({f.rule for f in report.findings})


# -- liveness walk + attribution units ------------------------------------


def test_walk_scope_frees_intermediates_at_last_use():
    def chain(x):
        a = x * 2.0          # dies after b
        b = a + 1.0          # dies after c
        c = b * 3.0
        return c

    closed = jax.make_jaxpr(chain)(np.zeros(1024, np.float32))
    peak, snap, inputs = memck._walk_scope(closed.jaxpr, 1.0)
    # Input pinned + at most two coexisting intermediates: had nothing
    # freed, the chain would peak at input + 3 temporaries.
    assert inputs == 4096
    assert peak <= 3 * 4096
    assert peak >= 2 * 4096


def test_walk_scope_pins_inputs_and_outputs():
    def keep(x, y):
        return x + y, x

    closed = jax.make_jaxpr(keep)(np.zeros(256, np.float32),
                                  np.zeros(256, np.float32))
    peak, _, inputs = memck._walk_scope(closed.jaxpr, 1.0)
    assert inputs == 2 * 1024
    assert peak >= 3 * 1024      # both inputs + the sum, all pinned


def test_classify_attributes_by_probe_unit():
    assert memck._classify(96.0, 96, 400) == "vertex"
    assert memck._classify(400.0, 96, 400) == "edge"
    assert memck._classify(800.0, 96, 400) == "edge"
    assert memck._classify(7.0, 96, 400) == "fixed"


def test_eval_model_scales_lanes_and_parts():
    model = {"per_vertex_bytes": 4.0, "per_edge_bytes": 2.0,
             "fixed_bytes": 100}
    base = memck.eval_model(model, 96, 400, 1)
    assert base == 4.0 * 96 + 2.0 * 400 + 100
    # P divides the linear terms (ceil'd), never the constant.
    sharded = memck.eval_model(model, 96, 400, 8)
    assert sharded == 4.0 * 12 + 2.0 * 50 + 100
    # K lanes scale the vertex-proportional state.
    wide = memck.eval_model(model, 96, 400, 1, k=4, k_probe=2)
    assert wide == 4.0 * 2 * 96 + 2.0 * 400 + 100


def test_model_honesty_floor_tolerates_toy_scale_padding():
    model = {"per_vertex_bytes": 4.0, "per_edge_bytes": 0.0,
             "fixed_bytes": 0}
    # 2x over at toy scale (absolute slack ~KiB): quantisation noise.
    assert memck._check_model_honesty("t", model, 4.0 * 96 / 2,
                                      96, 0, 1) == []
    # Under-estimation never gets a floor.
    under = memck._check_model_honesty("t", model, 4.0 * 96 * 2, 96, 0, 1)
    assert [f.rule for f in under] == ["LUX704"]


def test_donation_report_prices_unhonored_alias():
    args = (np.zeros(64, np.float32), np.ones(64, np.float32))

    def step(vals, deg):
        return vals + deg

    from lux_tpu.analysis import ir
    honored = ir.target_from_spec("t", {
        "fn": jax.jit(step, donate_argnums=0), "args": args,
        "donate": (0,), "carry": (0,)})
    rep = memck._donation_report(honored)
    assert rep["checked"] and rep["leak_bytes"] == 0

    flipped = ir.target_from_spec("t", {
        "fn": jax.jit(step), "args": args,
        "donate": (0,), "carry": (0,)})
    rep = memck._donation_report(flipped)
    assert rep["checked"]
    assert rep["leak_bytes"] == 64 * 4


# -- registry proof + committed artifact ----------------------------------


@pytest.fixture(scope="module")
def registry():
    """One registry prove shared by the assertions (trace + lowering of
    every registry target: the expensive part, staged once)."""
    return memck.prove_registry()


def test_registry_proves_clean(registry):
    report, art = registry
    assert report.ok, [f.format() for r in report.results
                       for f in r.findings]
    assert report.schema == "luxlint-memory.v1"
    assert not any(r.error for r in report.results)
    assert len(art["targets"]) >= 30


def test_registry_matches_committed_artifact(registry):
    """The LUX706 offline ratchet: a footprint-changing edit must
    regenerate lux_tpu/analysis/memcap.json or verify fails."""
    _, art = registry
    committed = memck.load_memcap(memck.memcap_path())
    assert committed["id"] == art["id"]


def test_registry_models_bound_their_own_probe(registry):
    _, art = registry
    for name, entry in art["targets"].items():
        pred = memck.eval_model(entry["model"], entry["probe"]["nv"],
                                entry["probe"]["ne"], entry["parts"],
                                k=entry["k"], k_probe=entry["k"])
        assert pred + 1e-6 >= entry["peak_bytes"], name


def test_registry_covers_exchange_mode_variants(registry):
    _, art = registry
    names = set(art["targets"])
    assert "sssp@push" in names
    assert "sssp@push_sharded" in names
    assert "sssp@push_sharded+compact" in names
    assert any(n.endswith("+frontier") for n in names)
    # Sharded entries price their staging.
    assert art["targets"]["sssp@push_sharded"]["staging_bytes"] > 0


# -- seeded fixtures: each fails with exactly its rule --------------------


@pytest.mark.parametrize("stem,rule", [
    ("lux701_malformed_artifact", "LUX701"),
    ("lux702_unhonored_donation", "LUX702"),
    ("lux703_overcommit", "LUX703"),
    ("lux704_dishonest_model", "LUX704"),
    ("lux705_divergent_exchange_claim", "LUX705"),
    ("lux706_stale_committed", "LUX706"),
])
def test_fixture_fails_with_exactly_its_rule(stem, rule):
    path = os.path.join(MEM_FIXTURES, stem + ".py")
    report = memck.verify_fixture_paths([path])
    assert not report.ok
    assert _rules(report) == [rule]
    assert not any(r.error for r in report.results)


def test_fixture_select_filters_rules():
    path = os.path.join(MEM_FIXTURES, "lux704_dishonest_model.py")
    report = memck.verify_fixture_paths([path], select=("LUX701",))
    assert report.ok    # the LUX704 finding is filtered out


# -- memcap.v1 artifact ----------------------------------------------------


def test_memcap_round_trip(tmp_path):
    art = memck.build_memcap(
        {"x@push": {"model": {"per_vertex_bytes": 4.0,
                              "per_edge_bytes": 0.0, "fixed_bytes": 8},
                    "peak_bytes": 392, "probe": {"nv": 96, "ne": 400}}},
        {"seed": 7})
    path = str(tmp_path / "memcap.json")
    memck.save_memcap(art, path)
    loaded = memck.load_memcap(path)
    assert loaded["id"] == art["id"]
    assert loaded["targets"] == art["targets"]


def test_memcap_id_is_content_addressed_not_timestamped():
    a = memck.build_memcap({"x": {"d": 1}}, {"seed": 7})
    b = memck.build_memcap({"x": {"d": 1}}, {"seed": 7})
    c = memck.build_memcap({"x": {"d": 2}}, {"seed": 7})
    assert a["id"] == b["id"]       # created_at excluded from the id
    assert a["id"] != c["id"]


def test_memcap_tamper_rejected(tmp_path):
    art = memck.build_memcap(
        {"x@push": {"peak_bytes": 100}}, {"seed": 7})
    path = str(tmp_path / "memcap.json")
    memck.save_memcap(art, path)
    doc = json.loads(open(path).read())
    doc["targets"]["x@push"]["peak_bytes"] = 1   # hand-shrunk footprint
    with open(path, "w") as fh:
        json.dump(doc, fh)
    with pytest.raises(ValueError, match="content hash"):
        memck.load_memcap(path)


def test_memcap_path_honors_flag(tmp_path):
    with flags.overrides({"LUX_MEMCAP_DIR": str(tmp_path)}):
        assert memck.memcap_path() == str(tmp_path / "memcap.json")
    assert memck.memcap_path().endswith(
        os.path.join("analysis", "memcap.json"))


# -- the serving admission formula ----------------------------------------


def test_predicted_engine_bytes_from_committed_artifact():
    art = memck.load_memcap(memck.memcap_path())
    pred = memck.predicted_engine_bytes("sssp", "push", "", 96, 400, 1,
                                        art=art)
    assert pred is not None
    assert pred >= art["targets"]["sssp@push"]["peak_bytes"]
    # Exchange-mode variants resolve to their own entry.
    compact = memck.predicted_engine_bytes("sssp", "push_sharded",
                                           "compact", 96, 400, 8, art=art)
    assert compact is not None and compact > 0
    # Unknown app under a known kind: costliest same-kind entry.
    assert memck.predicted_engine_bytes("nope", "push", "", 96, 400, 1,
                                        art=art) is not None
    # Unknown kind prices nothing — admission runs open, not wrong.
    assert memck.predicted_engine_bytes("sssp", "bogus", "", 96, 400, 1,
                                        art=art) is None


def test_hbm_budget_flag_overrides_capacity():
    with flags.overrides({"LUX_HBM_BUDGET_BYTES": "12345"}):
        assert memck.hbm_budget_bytes() == 12345
    with flags.overrides({"LUX_HBM_CAPACITY_BYTES": str(1 << 30),
                          "LUX_HBM_BUDGET_FRAC": "0.5"}):
        assert memck.hbm_budget_bytes() == (1 << 29)


# -- HBM-budgeted pool admission ------------------------------------------


def test_pool_evicts_cold_engine_by_footprint_and_keeps_warm_hits():
    pool = EnginePool(scope="test-memck")
    try:
        with flags.overrides({"LUX_HBM_BUDGET_BYTES": "1000"}):
            ev0 = pool.stats()["hbm_evictions"]
            rc0 = pool.stats()["recompiles"]
            a = pool.get(("a",), lambda: types.SimpleNamespace(),
                         footprint_bytes=600)
            # Warm hit: no admission, no eviction, no rebuild.
            assert pool.get(("a",), lambda: types.SimpleNamespace(),
                            footprint_bytes=600) is a
            assert pool.stats()["hbm_evictions"] == ev0
            assert pool.hbm_resident_bytes() == 600
            # Second engine does not fit: the cold one is evicted.
            pool.get(("b",), lambda: types.SimpleNamespace(),
                     footprint_bytes=600)
            assert pool.stats()["hbm_evictions"] == ev0 + 1
            assert pool.hbm_resident_bytes() == 600
            assert pool.keys() == [("b",)]
            assert pool.stats()["recompiles"] == rc0
    finally:
        pool.close()


def test_pool_refuses_engine_larger_than_budget():
    pool = EnginePool(scope="test-memck-refuse")
    try:
        with flags.overrides({"LUX_HBM_BUDGET_BYTES": "1000"}):
            with pytest.raises(PoolOverBudgetError) as ei:
                pool.get(("fat",), lambda: types.SimpleNamespace(),
                         footprint_bytes=2000)
            assert ei.value.http_status == 503
            assert ei.value.retry_after_s > 0
        assert len(pool) == 0
    finally:
        pool.close()


def test_pool_admission_gate_and_unpriced_builds():
    pool = EnginePool(scope="test-memck-gate")
    try:
        with flags.overrides({"LUX_HBM_BUDGET_BYTES": "1000",
                              "LUX_MEM_POOL_ADMIT": "0"}):
            pool.get(("fat",), lambda: types.SimpleNamespace(),
                     footprint_bytes=2000)    # gated off: admitted
        with flags.overrides({"LUX_HBM_BUDGET_BYTES": "1000"}):
            # Unpriced builds admit freely (no formula, no refusal).
            pool.get(("unpriced",), lambda: types.SimpleNamespace())
        assert len(pool) == 2
    finally:
        pool.close()


def test_pool_retire_releases_residency():
    pool = EnginePool(scope="test-memck-retire")
    try:
        with flags.overrides({"LUX_HBM_BUDGET_BYTES": "1000"}):
            pool.get(("a", "f1"), lambda: types.SimpleNamespace(),
                     footprint_bytes=400)
            pool.get(("b", "f2"), lambda: types.SimpleNamespace(),
                     footprint_bytes=400)
            assert pool.hbm_resident_bytes() == 800
            pool.retire(lambda k: k[1] == "f1")
            assert pool.hbm_resident_bytes() == 400
    finally:
        pool.close()


def test_session_statusz_memory_block():
    from lux_tpu.obs import metrics
    from lux_tpu.serve.session import Session

    # The eviction counter is process-global by design (dashboards sum
    # one series); assert the session adds nothing, not absolute zero.
    before = int(metrics.counter("lux_pool_hbm_evictions_total").value)
    src = np.array([0, 1, 2, 3], dtype=np.int64)
    g = Graph.from_edges(src, (src + 1) % 4, 4)
    with Session(g, warm=False) as s:
        blk = s.statusz()["memory"]
        assert blk["admission"] is True
        assert blk["artifact_id"].startswith("memcap-")
        assert blk["resident_bytes"] == 0
        assert blk["evictions"] == before
        # CPU profile exposes no HBM: budget runs open by default.
        assert blk["budget_bytes"] is None
        assert s.stats()["memory"]["artifact_id"] == blk["artifact_id"]


# -- tuner footprint pruning ----------------------------------------------


def test_knob_space_prunes_unaffordable_candidates():
    full = space.knob_space("push_sharded")
    assert len(full) > 1
    # No budget (CPU profile): the probe context changes nothing.
    assert space.knob_space("push_sharded", program_name="sssp",
                            nv=4096, ne=16384, parts=8) == full
    with flags.overrides({"LUX_HBM_BUDGET_BYTES": "1"}):
        pruned = space.knob_space("push_sharded", program_name="sssp",
                                  nv=4096, ne=16384, parts=8)
    # Candidate 0 (all defaults) survives any budget; the rest cannot
    # fit one byte.
    assert pruned == [full[0]]


# -- the --memory CLI ------------------------------------------------------


def test_cli_memcap_out_reproduces_committed_artifact(tmp_path):
    out = str(tmp_path / "memcap.json")
    r = _run_cli("--memory", "--memcap-out", out)
    assert r.returncode == 0, r.stdout + r.stderr
    s = _summary_line(r.stdout)
    assert s["schema"] == "luxlint-memory.v1"
    assert s["ok"] and s["findings"] == 0
    art = memck.load_memcap(out)
    assert art["id"] == memck.load_memcap(memck.memcap_path())["id"]


def test_cli_fixture_exits_one_with_its_rule():
    r = _run_cli("--memory",
                 os.path.join(MEM_FIXTURES, "lux703_overcommit.py"))
    assert r.returncode == 1
    s = _summary_line(r.stdout)
    assert s["by_rule"] == {"LUX703": 1}
    assert "HBM capacity" in r.stdout


def test_cli_select_subsets_rules():
    r = _run_cli("--memory", "--select", "LUX701",
                 os.path.join(MEM_FIXTURES, "lux704_dishonest_model.py"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert _summary_line(r.stdout)["findings"] == 0


def test_cli_baseline_ratchet(tmp_path):
    base = str(tmp_path / "memory.baseline.json")
    fix = os.path.join(MEM_FIXTURES, "lux704_dishonest_model.py")
    first = _run_cli("--memory", fix, "--baseline", base)
    assert first.returncode == 0          # snapshot written, run passes
    assert os.path.exists(base)
    second = _run_cli("--memory", fix, "--baseline", base)
    assert second.returncode == 0         # known finding: ratcheted
    third = _run_cli("--memory",
                     os.path.join(MEM_FIXTURES, "lux703_overcommit.py"),
                     "--baseline", base)
    assert third.returncode == 1          # new finding escapes the ratchet
    assert "[new]" in third.stdout


def test_cli_changed_contract():
    # Content depends on git state; the contract is: it runs (or early-
    # exits when no footprint-relevant file changed) and still emits
    # the greppable summary line with this tier's schema.
    r = _run_cli("--memory", "--changed")
    assert r.returncode in (0, 1), r.stdout + r.stderr
    assert _summary_line(r.stdout)["schema"] == "luxlint-memory.v1"


def test_cli_tiers_are_mutually_exclusive():
    r = _run_cli("--memory", "--ir")
    assert r.returncode == 2
    assert "separate tiers" in r.stderr


def test_cli_list_rules_documents_the_tier():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rule in ("LUX701", "LUX702", "LUX703", "LUX704", "LUX705",
                 "LUX706"):
        assert rule in r.stdout
