"""Design validation for the round-4 merge-network tail scheduler
(lux_tpu/ops/merge_tail_ref.py): the one-walk final-position assignment
with per-(level, node, side) window quotas must yield, at EVERY level,
emission windows that read only their own 64-slot input ranges (the
device kernel's contract, asserted inside simulate()), and a final
stream whose reals are globally dst-sorted — so per-destination sums
are cumsum boundary-diffs at static positions."""

import numpy as np
import pytest

from lux_tpu.ops.merge_tail_ref import BLOCK, schedule, simulate


def random_runs(rng, nruns, ndst, lam):
    runs, values = [], []
    for _ in range(nruns):
        k = int(rng.poisson(lam))
        d = np.sort(rng.integers(0, ndst, k))
        runs.append(d)
        values.append(rng.standard_normal(k))
    return runs, values


@pytest.mark.parametrize("seed,nruns,ndst,lam", [
    (0, 8, 50, 12), (1, 16, 30, 5), (2, 5, 200, 40),
    (3, 32, 64, 9), (4, 2, 10, 3), (5, 9, 1, 20),
])
def test_merge_network_end_to_end(seed, nruns, ndst, lam):
    rng = np.random.default_rng(seed)
    runs, values = random_runs(rng, nruns, ndst, lam)
    final, f, items = simulate(runs, values)   # asserts window contract

    # Final stream: reals at f in globally non-decreasing dst order,
    # pads zero → per-dst sums = sums over contiguous slot ranges.
    dsts = np.array([d for d, _, _ in items])
    assert np.all(np.diff(dsts) >= 0)
    assert np.all(np.diff(f) > 0)              # strictly increasing slots
    got_vals = final[f]
    want_vals = np.array(
        [values[r][p] for _, r, p in items]
    )
    np.testing.assert_allclose(got_vals, want_vals)
    # Everything off the real positions is zero (pads).
    mask = np.ones(final.shape[0], bool)
    mask[f] = False
    assert np.all(final[mask] == 0.0)

    # Per-destination sums against the oracle.
    acc = np.zeros(ndst)
    for (d, r, p) in items:
        acc[d] += values[r][p]
    got = np.zeros(ndst)
    for i, (d, _, _) in enumerate(items):
        got[d] += final[f[i]]
    np.testing.assert_allclose(got, acc)


def test_stall_padding_is_bounded_on_random_runs():
    # The walk's stall pads should stay a small multiple of the real
    # count on random (Kronecker-like) dst distributions.
    rng = np.random.default_rng(7)
    runs, values = random_runs(rng, 16, 500, 60)
    n = sum(len(r) for r in runs)
    final, f, items = simulate(runs, values)
    rows = final.shape[0] // BLOCK
    assert rows * BLOCK <= 4 * n + 4 * BLOCK, (rows * BLOCK, n)


def test_degenerate_single_and_empty_runs():
    # R is floored at 2 so a lone run (or no runs) still flows through
    # one real merge level instead of scheduling phantom nodes.
    final, f, items = simulate([np.array([0, 1, 2])],
                               [np.array([1.0, 2.0, 3.0])])
    np.testing.assert_allclose(final[f], [1.0, 2.0, 3.0])
    final, f, items = simulate([], [])
    assert len(items) == 0 and final.shape[0] >= BLOCK
