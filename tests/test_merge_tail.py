"""Design validation for the round-4 merge-network tail scheduler
(lux_tpu/ops/merge_tail_ref.py): the one-walk final-position assignment
with per-(level, node, side) window quotas must yield, at EVERY level,
emission windows that read only their own 64-slot input ranges (the
device kernel's contract, asserted inside simulate()), and a final
stream whose reals are globally dst-sorted — so per-destination sums
are cumsum boundary-diffs at static positions."""

import numpy as np
import pytest

from lux_tpu.ops.merge_tail_ref import BLOCK, schedule, simulate


def random_runs(rng, nruns, ndst, lam):
    runs, values = [], []
    for _ in range(nruns):
        k = int(rng.poisson(lam))
        d = np.sort(rng.integers(0, ndst, k))
        runs.append(d)
        values.append(rng.standard_normal(k))
    return runs, values


@pytest.mark.parametrize("seed,nruns,ndst,lam", [
    (0, 8, 50, 12), (1, 16, 30, 5), (2, 5, 200, 40),
    (3, 32, 64, 9), (4, 2, 10, 3), (5, 9, 1, 20),
])
def test_merge_network_end_to_end(seed, nruns, ndst, lam):
    rng = np.random.default_rng(seed)
    runs, values = random_runs(rng, nruns, ndst, lam)
    final, f, items = simulate(runs, values)   # asserts window contract

    # Final stream: reals at f in globally non-decreasing dst order,
    # pads zero → per-dst sums = sums over contiguous slot ranges.
    dsts = np.array([d for d, _, _ in items])
    assert np.all(np.diff(dsts) >= 0)
    assert np.all(np.diff(f) > 0)              # strictly increasing slots
    got_vals = final[f]
    want_vals = np.array(
        [values[r][p] for _, r, p in items]
    )
    np.testing.assert_allclose(got_vals, want_vals)
    # Everything off the real positions is zero (pads).
    mask = np.ones(final.shape[0], bool)
    mask[f] = False
    assert np.all(final[mask] == 0.0)

    # Per-destination sums against the oracle.
    acc = np.zeros(ndst)
    for (d, r, p) in items:
        acc[d] += values[r][p]
    got = np.zeros(ndst)
    for i, (d, _, _) in enumerate(items):
        got[d] += final[f[i]]
    np.testing.assert_allclose(got, acc)


def test_stall_padding_is_bounded_on_random_runs():
    # The walk's stall pads should stay a small multiple of the real
    # count on random (Kronecker-like) dst distributions.
    rng = np.random.default_rng(7)
    runs, values = random_runs(rng, 16, 500, 60)
    n = sum(len(r) for r in runs)
    final, f, items = simulate(runs, values)
    rows = final.shape[0] // BLOCK
    assert rows * BLOCK <= 4 * n + 4 * BLOCK, (rows * BLOCK, n)


def test_degenerate_single_and_empty_runs():
    # R is floored at 2 so a lone run (or no runs) still flows through
    # one real merge level instead of scheduling phantom nodes.
    final, f, items = simulate([np.array([0, 1, 2])],
                               [np.array([1.0, 2.0, 3.0])])
    np.testing.assert_allclose(final[f], [1.0, 2.0, 3.0])
    final, f, items = simulate([], [])
    assert len(items) == 0 and final.shape[0] >= BLOCK


# -- round-5 copy-window scheduler + production planner ------------------

from lux_tpu.ops.merge_tail_ref import (      # noqa: E402
    _align_up,
    _tree_size,
    schedule_grouped,
    simulate_grouped,
)


@pytest.mark.parametrize("seed,align", [
    (0, 1), (1, 1), (2, 8), (3, 8), (4, 1), (5, 8),
])
def test_grouped_schedule_end_to_end(seed, align):
    rng = np.random.default_rng(seed)
    runs, values = random_runs(rng, int(rng.integers(1, 12)), 60, 25)
    final, items = simulate_grouped(runs, values, align_rows=align)
    # simulate_grouped asserts the kernel contract (codes only address
    # real lanes) and global dst order internally.
    got = {(r, p): final[row, lane] for _, r, p, row, lane in (
        (d, r, p, s // BLOCK, s % BLOCK) for d, r, p, s in items)}
    for r, vs in enumerate(values):
        for p, v in enumerate(vs):
            assert got[(r, p)] == v


def test_grouped_copy_rows_stream_at_full_rate():
    # Two runs over disjoint dst ranges: after the first run drains,
    # every remaining row must be a single-sided copy row carrying a
    # full 128 reals (not the 64/64 merge rate).
    a = np.zeros(64, np.int64)                 # run 0: all dst 0
    b = np.full(512, 1, np.int64)              # run 1: all dst 1, larger
    levels, items, rows = schedule_grouped([a, b])
    lv = levels[0]
    copy_b = (lv["mode"] == 2) & (lv["nvalid"] == BLOCK)
    assert copy_b.sum() >= 3, lv["mode"]       # 512/128 - boundary row


def test_planner_matches_reference_planes():
    from lux_tpu.ops import merge_tail_plan as mtp

    def ref_leaf_layout(runs, align):
        R = _tree_size(len(runs))
        recs = []
        base = 0
        for r in range(R):
            a = (np.asarray(runs[r]) if r < len(runs)
                 else np.empty(0, np.int64))
            for p, d in enumerate(a):
                recs.append((int(d), r, base + p // BLOCK, p % BLOCK))
            base = _align_up(
                base + (len(a) + BLOCK - 1) // BLOCK, align)
        recs.sort()
        if not recs:
            z = np.zeros(0, np.int64)
            return z, z, z, z
        d, leaf, row, lane = map(np.asarray, zip(*recs))
        return d, leaf, row, lane

    for seed in range(6):
        rng = np.random.default_rng(seed)
        runs, _ = random_runs(rng, int(rng.integers(1, 10)), 40, 30)
        for align in (1, 8):
            ref_levels, ref_items, ref_rows = schedule_grouped(runs, align)
            d, leaf, row, lane = ref_leaf_layout(runs, align)
            levels, frow, flane, rows = mtp.plan_merge_network(
                d, leaf, row, lane, len(runs), align_rows=align)
            assert rows == ref_rows[1:]
            for lv, rlv in zip(levels, ref_levels):
                for key in ("arow", "brow", "codes", "nvalid", "mode"):
                    np.testing.assert_array_equal(lv[key], rlv[key])
            ref_slots = np.asarray([s for *_, s in ref_items])
            np.testing.assert_array_equal(frow * BLOCK + flane, ref_slots)


def _random_tail(rng, nsb, nv, m):
    """A synthetic hybrid-plan tail: (sb, lane, row_ptr) in CSC order."""
    sb = rng.integers(0, nsb, size=m)
    lane = rng.integers(0, BLOCK, size=m)
    dst = np.sort(rng.integers(0, nv, size=m))
    row_ptr = np.searchsorted(dst, np.arange(nv + 1))
    return sb, lane, row_ptr, dst


@pytest.mark.parametrize("nsb,nv,m", [(6, 40, 400), (48, 700, 15000),
                                      (3, 5, 0)])
def test_grouped_tail_plan_bitwise_sums(nsb, nv, m):
    # Integral source values keep every f32 addition exact, so the
    # grouped network's per-dst sums must be BITWISE equal to the
    # scatter oracle regardless of addend order.
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops import merge_tail_plan as mtp
    from lux_tpu.ops.merge_tail_kernel import (
        DeviceGroupedTail,
        grouped_tail_sums,
    )

    rng = np.random.default_rng(nsb * 1000 + m)
    sb, lane, row_ptr, dst = _random_tail(rng, nsb, nv, m)
    plan = mtp.plan_grouped_tail(sb, lane, row_ptr)
    gt = DeviceGroupedTail.build(plan)
    x2d = rng.integers(-40, 40, size=(nsb, BLOCK)).astype(np.float32)
    got = np.asarray(jax.jit(grouped_tail_sums)(jnp.asarray(x2d), gt))
    want = np.zeros(nv, np.float64)
    np.add.at(want, dst, x2d[sb, lane].astype(np.float64))
    np.testing.assert_array_equal(got, want.astype(np.float32))


def test_grouped_plan_cache_roundtrip(tmp_path):
    from lux_tpu.ops import merge_tail_plan as mtp

    rng = np.random.default_rng(9)
    sb, lane, row_ptr, _ = _random_tail(rng, 20, 300, 5000)
    plan = mtp.plan_grouped_tail(sb, lane, row_ptr)
    path = str(tmp_path / "gtail.luxplan")
    mtp.save_grouped_plan(path, plan)
    loaded = mtp.load_grouped_plan(path)
    for name in mtp._PLAN_ARRAYS:
        np.testing.assert_array_equal(
            getattr(plan, name), getattr(loaded, name))
    assert loaded.n_edges == plan.n_edges
    assert loaded.stats == plan.stats
    # Overwrite must replace, not merge.
    mtp.save_grouped_plan(path, plan)
    assert mtp.load_grouped_plan(path).n_edges == plan.n_edges


def test_hybrid_spmv_grouped_tail_parity():
    # Full hybrid_spmv: the grouped tail and the lane-select tail must
    # produce BITWISE-identical per-dst sums on integral values (every
    # per-dst total < 2^24, so f32 addition is exact in any order).
    import jax.numpy as jnp

    from lux_tpu.graph.generate import rmat
    from lux_tpu.ops import merge_tail_plan as mtp
    from lux_tpu.ops.merge_tail_kernel import DeviceGroupedTail
    from lux_tpu.ops.tiled_spmv import (
        DeviceHybrid,
        hybrid_spmv,
        plan_hybrid,
    )

    g = rmat(11, 12, seed=5)
    plan = plan_hybrid(g, levels=((8, 2),))
    dh = DeviceHybrid.build(plan, chunk_strips=16, chunk_tail=64)
    gplan = mtp.plan_grouped_tail(
        plan.tail_sb, plan.tail_lane, plan.tail_row_ptr)
    gt = DeviceGroupedTail.build(gplan)
    rng = np.random.default_rng(0)
    vals = jnp.asarray(
        rng.integers(0, 8, size=g.nv).astype(np.float32))
    base = np.asarray(hybrid_spmv(vals, dh))
    grouped = np.asarray(hybrid_spmv(vals, dh, gt))
    np.testing.assert_array_equal(base, grouped)


def test_executor_grouped_tail_pagerank_parity(monkeypatch):
    # End-to-end through TiledPullExecutor: LUX_GROUPED_TAIL=1 PageRank
    # matches the lane-select run to f32 summation-order noise.
    from lux_tpu.engine.tiled import TiledPullExecutor
    from lux_tpu.graph.generate import rmat
    from lux_tpu.models.pagerank import PageRank

    g = rmat(10, 14, seed=3)
    ex0 = TiledPullExecutor(g, PageRank(), chunk_strips=16, chunk_tail=64)
    monkeypatch.setenv("LUX_GROUPED_TAIL", "1")
    ex1 = TiledPullExecutor(g, PageRank(), chunk_strips=16, chunk_tail=64)
    assert ex0.gtail is None and ex1.gtail is not None
    assert ex1.gtail_stats["n_edges"] == ex1.plan.tail_sb.shape[0]
    v0 = np.asarray(ex0.run(8))
    v1 = np.asarray(ex1.run(8))
    np.testing.assert_allclose(v0, v1, rtol=1e-5, atol=1e-8)
    # Per-level timed phase path reports one entry per network level.
    out, times = ex1.phase_step(ex1.init_values())
    nlev = ex1.gtail.n_levels
    assert all(f"tail_level{k}" in times for k in range(nlev + 1))


@pytest.mark.slow
def test_planner_scales_to_a_million_reals():
    # Acceptance: a >= 1M-real heavy-tailed stream plans in seconds.
    import time

    from lux_tpu.ops import merge_tail_plan as mtp

    rng = np.random.default_rng(2)
    nsb = 1024
    sizes = np.minimum(
        rng.lognormal(6.4, 1.3, size=nsb).astype(np.int64) + 1, 79237)
    m = int(sizes.sum())
    assert m >= 1_000_000
    sb = np.repeat(np.arange(nsb), sizes)
    nv = 1 << 17
    dst = np.sort(rng.integers(0, nv, size=m))
    sb = sb[np.lexsort((sb, dst))]
    lane = rng.integers(0, BLOCK, size=m)
    row_ptr = np.searchsorted(dst, np.arange(nv + 1))
    t0 = time.perf_counter()
    plan = mtp.plan_grouped_tail(sb, lane, row_ptr)
    dt = time.perf_counter() - t0
    assert dt < 60, dt
    assert plan.stats["mean_inflation"] < 1.5, plan.stats
