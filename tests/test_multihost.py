"""Multi-host module tests: slice-major ordering/shrink-validation unit
tests plus a REAL two-process multi-controller run (jax.distributed over
localhost gloo CPU collectives) — the "same code, more nodes" contract
the reference gets from its GASNet rebuild (/root/reference/README.md:33-37).
"""

import os
import subprocess
import sys
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fake_dev(slice_index, process_index, id_):
    return types.SimpleNamespace(
        slice_index=slice_index, process_index=process_index, id=id_
    )


def test_ordered_devices_slice_major():
    from lux_tpu.parallel.multihost import ordered_devices

    # Shuffled input: two slices x two processes x two devices. The
    # ordering must group by slice first (neighboring partitions share a
    # slice, so the ghost all-gather rides ICI before DCN), then process,
    # then id.
    devs = [
        fake_dev(1, 3, 7), fake_dev(0, 0, 1), fake_dev(1, 2, 4),
        fake_dev(0, 1, 2), fake_dev(0, 0, 0), fake_dev(1, 2, 5),
        fake_dev(0, 1, 3), fake_dev(1, 3, 6),
    ]
    got = [(d.slice_index, d.process_index, d.id)
           for d in ordered_devices(devs)]
    assert got == [
        (0, 0, 0), (0, 0, 1), (0, 1, 2), (0, 1, 3),
        (1, 2, 4), (1, 2, 5), (1, 3, 6), (1, 3, 7),
    ]
    # slice_index None (single-slice backends) sorts like 0.
    devs_none = [fake_dev(None, 0, 1), fake_dev(None, 0, 0)]
    assert [d.id for d in ordered_devices(devs_none)] == [0, 1]


def test_ordered_devices_shrink_validation():
    from lux_tpu.parallel.multihost import ordered_devices

    devs = [fake_dev(0, 0, 0), fake_dev(0, 0, 1),
            fake_dev(0, 1, 2), fake_dev(0, 1, 3)]
    # Shrinking to 3 keeps a device on both processes: fine.
    assert len(ordered_devices(devs, num_parts=3)) == 4
    # Shrinking to 2 orphans process 1: multi-controller JAX requires
    # every process to own part of the computation.
    with pytest.raises(ValueError, match="processes \\[1\\]"):
        ordered_devices(devs, num_parts=2)


_WORKER = r"""
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from lux_tpu.parallel.multihost import initialize, make_global_mesh

initialize(f"127.0.0.1:{{port}}", num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from lux_tpu.engine.pull_sharded import ShardedPullExecutor
from lux_tpu.graph import generate
from lux_tpu.models import PageRank

mesh = make_global_mesh()
g = generate.rmat(8, 8, seed=5)
ex = ShardedPullExecutor(g, PageRank(), mesh=mesh)
vals = ex.run(5, flush_every=0)
# Replicate the padded shard stack so every process can fetch it whole
# (device_get of a sharded global array would touch non-addressable
# shards in multi-controller mode).
rep = jax.jit(lambda v: v, out_shardings=NamedSharding(mesh, P()))(vals)
if pid == 0:
    np.save(out, ex.gather_values(rep))
print(f"proc {{pid}} done", flush=True)
"""


def test_two_process_pagerank_parity(tmp_path):
    """Two OS processes, two CPU devices each, one global 4-way mesh:
    the sharded executor must produce single-process-identical PageRank
    values over jax.distributed + gloo."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.format(repo=REPO))
    out = str(tmp_path / "final.npy")
    env = dict(os.environ)
    env.pop("LUX_PLATFORM", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), port, out],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        logs = [p.communicate(timeout=240)[0] for p in procs]
    finally:
        for p in procs:   # a hung gloo peer must not outlive the test
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, lg in zip(procs, logs):
        assert p.returncode == 0, lg
    got = np.load(out)

    from lux_tpu.graph import generate
    from lux_tpu.models.pagerank import reference_pagerank

    g = generate.rmat(8, 8, seed=5)
    want = reference_pagerank(g, 5)
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)
