"""Native C++ IO paths vs numpy fallbacks (skipped if no toolchain)."""

import numpy as np
import pytest

from lux_tpu.graph import Graph, generate, write_lux
from lux_tpu.graph import format as lux_format


def native_lib():
    try:
        from lux_tpu.native.build import load_library

        return load_library()
    except Exception:
        return None


pytestmark = pytest.mark.skipif(
    native_lib() is None, reason="native toolchain unavailable"
)


def test_native_load_matches_python(tmp_path):
    from lux_tpu.native import io as nio

    g = generate.rmat(10, 8, seed=3, weighted=True)
    p = str(tmp_path / "g.lux")
    write_lux(p, g)
    g2 = nio.read_lux(p)
    np.testing.assert_array_equal(g.row_ptr, g2.row_ptr)
    np.testing.assert_array_equal(g.col_src, g2.col_src)
    np.testing.assert_array_equal(g.weights, g2.weights)
    g3 = lux_format.read_lux(p)
    np.testing.assert_array_equal(g2.col_src, g3.col_src)


def test_native_convert_matches_python(tmp_path):
    from lux_tpu.native import io as nio

    rng = np.random.default_rng(5)
    ne, nv = 500, 64
    src = rng.integers(0, nv, ne)
    dst = rng.integers(0, nv, ne)
    w = rng.integers(-3, 100, ne)
    el = tmp_path / "edges.txt"
    el.write_text(
        "".join(f"{s} {d} {x}\n" for s, d, x in zip(src, dst, w))
    )
    out_native = str(tmp_path / "n.lux")
    nio.convert_edge_list(str(el), out_native, nv, ne, weighted=True)
    want = Graph.from_edges(src, dst, nv=nv, weights=w.astype(np.int32))
    got = lux_format.read_lux(out_native)
    np.testing.assert_array_equal(got.row_ptr, want.row_ptr)
    np.testing.assert_array_equal(got.col_src, want.col_src)
    np.testing.assert_array_equal(got.weights, want.weights)  # stability
    np.testing.assert_array_equal(got.out_degrees, want.out_degrees)


def test_native_convert_rejects_bad_ids(tmp_path):
    lib = native_lib()
    el = tmp_path / "bad.txt"
    el.write_text("0 1\n5 2\n")  # 5 >= nv
    rc = lib.lux_convert_edge_list(
        str(el).encode(), str(tmp_path / "x.lux").encode(), 4, 2, 0
    )
    assert rc == -2


def test_native_csr_matches_numpy():
    g = generate.rmat(9, 8, seed=7, weighted=True)
    native = g._csr_native()
    assert native is not None
    ref = g._csr_numpy()
    np.testing.assert_array_equal(native.row_ptr, ref.row_ptr)
    np.testing.assert_array_equal(native.col_dst, ref.col_dst)
    np.testing.assert_array_equal(native.weights, ref.weights)


def test_native_load_detects_size_mismatch(tmp_path):
    lib = native_lib()
    p = tmp_path / "trunc.lux"
    g = generate.gnp(50, 200, seed=1)
    write_lux(str(p), g, include_degrees=False)
    data = p.read_bytes()[:-100]
    p.write_bytes(data)
    row_ends = np.zeros(50, np.int64)
    cols = np.zeros(200, np.int32)
    import ctypes

    rc = lib.lux_load(
        str(p).encode(),
        ctypes.c_uint32(50),
        ctypes.c_uint64(200),
        ctypes.c_void_p(row_ends.ctypes.data),
        ctypes.c_void_p(cols.ctypes.data),
        None,
    )
    assert rc == -3
