"""Telemetry subsystem tests: metrics registry semantics, trace-file
round-trips, and end-to-end iteration logs from real executor runs."""

import json

import numpy as np
import pytest

from lux_tpu import obs
from lux_tpu.engine.pull import PullExecutor
from lux_tpu.engine.push import PushExecutor
from lux_tpu.graph import generate
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.pagerank import PageRank
from lux_tpu.obs import metrics, report, trace


@pytest.fixture(autouse=True)
def clean_telemetry(monkeypatch):
    """Every test starts with telemetry off and an empty registry; env
    mutations inside the test are undone and re-read at teardown."""
    monkeypatch.delenv("LUX_METRICS", raising=False)
    monkeypatch.delenv("LUX_TRACE", raising=False)
    trace.reconfigure()
    metrics.reset()
    yield
    monkeypatch.undo()
    trace.reconfigure()
    metrics.reset()


# -- metrics registry -----------------------------------------------------


def test_counter_semantics():
    c = metrics.counter("t_iters", {"engine": "pull"})
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_semantics():
    g = metrics.gauge("t_bytes")
    g.set(10)
    g.inc(5)
    g.dec(3)
    assert g.value == 12


def test_histogram_semantics():
    h = metrics.histogram("t_secs", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 3 and h.sum == pytest.approx(5.55)
    snap = h.snapshot()
    assert [b["count"] for b in snap["buckets"]] == [1, 1, 1]
    assert snap["buckets"][-1]["le"] == "+Inf"


def test_label_dedup_and_kind_conflict():
    a = metrics.counter("t_dedup", {"engine": "pull", "k": "1"})
    b = metrics.counter("t_dedup", {"k": "1", "engine": "pull"})
    assert a is b  # label order is irrelevant to identity
    c = metrics.counter("t_dedup", {"engine": "push"})
    assert c is not a
    with pytest.raises(TypeError):
        metrics.gauge("t_dedup", {"engine": "pull", "k": "1"})


def test_snapshot_json_roundtrip():
    metrics.counter("t_snap").inc(2)
    metrics.histogram("t_snap_h").observe(0.2)
    snap = json.loads(json.dumps(metrics.snapshot()))
    names = [m["name"] for m in snap]
    assert names == sorted(names) and "t_snap" in names


# -- trace writer ---------------------------------------------------------


def test_trace_span_pairs(tmp_path, monkeypatch):
    path = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("LUX_TRACE", path)
    trace.reconfigure()
    assert trace.enabled()
    with trace.span("outer", cat="test", detail=1):
        with trace.span("inner", cat="test"):
            pass
    trace.pair("retro", 1.0, 2.0, cat="test")
    trace.instant("mark", cat="test")
    monkeypatch.delenv("LUX_TRACE")
    trace.reconfigure()  # closes the writer

    events = [json.loads(line) for line in open(path)]
    assert all("ph" in e and "name" in e for e in events if e["ph"] != "M")
    b = [e for e in events if e["ph"] == "B"]
    e = [e for e in events if e["ph"] == "E"]
    assert len(b) == len(e) == 3
    # spans nest: inner's B after outer's B, E before outer's E
    by = {(ev["name"], ev["ph"]): ev["ts"] for ev in b + e}
    assert by[("outer", "B")] <= by[("inner", "B")]
    assert by[("inner", "E")] <= by[("outer", "E")]
    retro_b, retro_e = by[("retro", "B")], by[("retro", "E")]
    assert retro_e - retro_b == pytest.approx(1e6)  # 1 s in us


def test_trace_disabled_is_noop(tmp_path):
    assert not trace.enabled()
    with trace.span("nothing"):
        pass
    trace.begin("x")
    trace.end("x")  # must not raise with no writer


# -- gteps definition -----------------------------------------------------


def test_gteps_definition():
    assert obs.gteps(2_000_000_000, 5, 10.0) == pytest.approx(1.0)
    assert obs.gteps(100, 0, 1.0) == 0.0
    assert obs.gteps(100, 5, 0.0) == 0.0


# -- recorder + executors end to end --------------------------------------


def _last_run(path):
    return report.read_last(path)


def test_pull_run_iteration_log(tmp_path, monkeypatch):
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    g = generate.rmat(8, 8, seed=1)
    ex = PullExecutor(g, PageRank())
    ex.warmup()
    ex.run(6, flush_every=0)
    run = _last_run(mpath)
    assert run["schema"] == "lux.run_telemetry.v1"
    assert run["engine"] == "pull" and run["program"] == "PageRank"
    assert run["num_iters"] == 6 and len(run["iterations"]) == 6
    cum = [r["t_cum_s"] for r in run["iterations"]]
    assert all(b >= a for a, b in zip(cum, cum[1:]))
    assert run["compile_s"] > 0  # warmup + fused-probe compile
    assert run["execute_s"] > 0
    assert run["gteps"] == pytest.approx(
        obs.gteps(run["ne"], run["num_iters"], run["execute_s"]))
    assert [m for m in run["metrics"] if m["name"] == "lux_iterations_total"]


def test_pull_run_pipelined_flush_windows(tmp_path, monkeypatch):
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    g = generate.rmat(8, 8, seed=1)
    ex = PullExecutor(g, PageRank())
    ex.warmup()
    ex.run(7, flush_every=3)  # windows: 3 + 3 + 1
    run = _last_run(mpath)
    assert run["num_iters"] == 7 and len(run["iterations"]) == 7
    assert [r["flush_span"] for r in run["iterations"]] == \
        [1, 1, 1, 2, 2, 2, 3]
    assert [r["iter"] for r in run["iterations"]] == list(range(7))


def test_push_run_frontier_log(tmp_path, monkeypatch):
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    g = generate.undirected(generate.rmat(8, 8, seed=3))
    ex = PushExecutor(g, ConnectedComponents())
    ex.warmup()
    state, iters = ex.run(max_iters=32)
    run = _last_run(mpath)
    assert run["engine"] == "push"
    assert run["num_iters"] == iters and len(run["iterations"]) == iters
    frontiers = [r["frontier"] for r in run["iterations"]]
    assert all(isinstance(f, int) and f >= 0 for f in frontiers)
    assert frontiers[-1] == 0  # fixpoint: final frontier is empty


def test_disabled_recorder_is_null():
    g = generate.rmat(6, 8, seed=1)
    rec = obs.recorder_for("pull", g)
    assert rec is obs.NULL_RECORDER and not rec.enabled
    # and a run with telemetry off writes nothing anywhere
    ex = PullExecutor(g, PageRank())
    out = ex.run(2, flush_every=0)
    assert out.shape == (g.nv,)


def test_recorder_runs_append_jsonl(tmp_path, monkeypatch):
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    g = generate.rmat(6, 8, seed=1)
    ex = PullExecutor(g, PageRank())
    ex.run(2, flush_every=0)
    ex.run(3, flush_every=0)
    runs = [json.loads(line) for line in open(mpath)]
    assert [r["num_iters"] for r in runs] == [2, 3]


def test_exchange_bytes_sharded(tmp_path, monkeypatch):
    import jax

    from lux_tpu.engine.pull_sharded import ShardedPullExecutor
    from lux_tpu.parallel.mesh import make_mesh

    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable in this jax build "
                    "(sharded engines cannot construct)")
    mpath = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("LUX_METRICS", mpath)
    g = generate.rmat(8, 8, seed=1)
    ex = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(2))
    ex.warmup()
    ex.run(3, flush_every=0)
    run = _last_run(mpath)
    assert run["engine"] == "pull_sharded"
    expected = 2 * 1 * ex.sg.max_nv * 4  # P(P-1) x shard floats
    assert run["exchange_bytes_per_iter"] == expected
    assert run["exchange_bytes_total"] == expected * 3


# -- satellites: Timer sync + logging reconfigure -------------------------


def test_timer_sync_blocks_async_result():
    import jax
    import jax.numpy as jnp

    from lux_tpu.utils.timing import Timer

    x = jnp.arange(1024.0)
    y = None
    with Timer(sync=lambda: y) as t:
        y = jax.jit(lambda v: v * 2)(x)
    assert t.elapsed >= 0 and float(y[0]) == 0.0


def test_timer_sync_callable_and_format(capsys):
    from lux_tpu.utils.timing import Timer

    done = []
    with Timer(sync=lambda: done.append(1)) as t:
        pass
    assert done == [1]  # the callable ran at exit
    t.print_elapsed()
    out = capsys.readouterr().out
    assert out.startswith("ELAPSED TIME = ") and out.endswith(" s\n")


def test_logging_reconfigure(monkeypatch):
    import logging as py_logging

    from lux_tpu.utils import logging as lux_logging

    lux_logging.get_logger("test")
    root = py_logging.getLogger("lux")
    monkeypatch.setenv("LUX_LOG", "DEBUG")
    lux_logging.reconfigure()
    assert root.level == py_logging.DEBUG
    monkeypatch.setenv("LUX_LOG", "WARNING")
    lux_logging.reconfigure()
    assert root.level == py_logging.WARNING
    # single handler no matter how often reconfigure runs
    lux_logging.reconfigure()
    assert len(root.handlers) == 1
    assert lux_logging.perf_logger().name == "lux.perf"


def test_report_read_last_empty(tmp_path):
    p = tmp_path / "empty.jsonl"
    p.write_text("\n")
    with pytest.raises(ValueError):
        report.read_last(str(p))
