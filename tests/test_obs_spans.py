"""Request-scoped spans, Prometheus exposition, rolling SLO windows, and
the flight-recorder postmortem pipeline (ISSUE 6).

The serve-layer smoke (`make serve-obs` / tools/serve_smoke.py) proves
the integrated story over HTTP; these tests pin the component contracts:
trace-id propagation across the batcher's worker thread, exposition
format, window quantile math on a seeded stream, dump triggers, and the
bounded-memory ring property.
"""

import json
import threading

import pytest

from lux_tpu.obs import flight, metrics, slo, spans, trace
from lux_tpu.serve.batcher import MicroBatcher, Request
from lux_tpu.serve.errors import DeadlineExceededError


@pytest.fixture(autouse=True)
def clean_telemetry(monkeypatch):
    """Every test starts with telemetry env clean, empty registry, and
    empty flight rings; mutations are undone and re-read at teardown."""
    for var in ("LUX_METRICS", "LUX_TRACE", "LUX_FLIGHT_DIR",
                "LUX_FLIGHT_CAPACITY", "LUX_SPANS",
                "LUX_STATUSZ_WINDOWS"):
        monkeypatch.delenv(var, raising=False)
    trace.reconfigure()
    flight.reconfigure()
    flight.reset()
    metrics.reset()
    yield
    monkeypatch.undo()
    trace.reconfigure()
    flight.reconfigure()
    flight.reset()
    metrics.reset()


@pytest.fixture()
def sink():
    """Collect completed trace records from the spans layer."""
    records = []
    spans.add_sink(records.append)
    yield records
    spans.remove_sink(records.append)


# -- span API -------------------------------------------------------------


def test_span_nesting_one_record_per_root(sink):
    with spans.span("outer", app="t") as tid:
        assert tid and spans.current_trace_id() == tid
        with spans.span("inner") as inner_tid:
            assert inner_tid == tid          # nested spans share the trace
    assert spans.current_trace_id() is None  # context restored

    assert len(sink) == 1
    rec = sink[0]
    assert rec["trace_id"] == tid
    by_name = {s["name"]: s for s in rec["spans"]}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["outer"]["dur_s"] >= by_name["inner"]["dur_s"]
    assert by_name["outer"]["attrs"] == {"app": "t"}
    assert rec["duration_s"] >= 0

    # Per-phase histograms landed in the registry.
    snap = {m["name"]: m for m in metrics.snapshot()}
    assert snap["lux_span_seconds"]["count"] >= 1


def test_spans_disabled_by_flag(monkeypatch, sink):
    monkeypatch.setenv("LUX_SPANS", "0")
    with spans.span("x") as tid:
        assert tid is None
        assert spans.current_trace_id() is None
    assert sink == []


def test_trace_id_propagates_across_batcher_thread(sink):
    """The admitting thread's trace-id must reach the batcher worker:
    Request captures it, the worker adopts it, and the engine-side work
    sees the same id (the one-trace-per-request chain)."""
    seen = {}
    done = threading.Event()

    def execute(batch):
        seen["worker_tid"] = spans.current_trace_id()
        seen["worker_thread"] = threading.current_thread().name
        for r in batch:
            r.future.set_result("ok")
        done.set()

    b = MicroBatcher(execute, max_batch=1, window_s=0.001, max_queue=8)
    try:
        with spans.span("root", app="t") as tid:
            fut = b.submit(Request(app="t", payload=None, batch_key=None))
            assert fut.result(10) == "ok"
            assert done.wait(10)
    finally:
        b.close()

    assert seen["worker_tid"] == tid
    assert seen["worker_thread"] != threading.current_thread().name
    rec = next(r for r in sink if r["trace_id"] == tid)
    names = {s["name"] for s in rec["spans"]}
    assert {"root", "serve.admit", "serve.queue_wait"} <= names


# -- Prometheus exposition ------------------------------------------------


def _parse_prometheus(text):
    """The ~10-line parser the exposition must survive."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        series, value = line.rsplit(" ", 1)
        name, _, labels = series.partition("{")
        out[(name, labels.rstrip("}"))] = float(value)
    return out


def test_metrics_prometheus_exposition_parses():
    metrics.counter("t_reqs", {"app": "sssp"}).inc(3)
    metrics.gauge("t_depth").set(7)
    h = metrics.histogram("t_lat", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)

    text = metrics.render_prometheus()
    assert text.endswith("\n")
    samples = _parse_prometheus(text)

    assert samples[("t_reqs", 'app="sssp"')] == 3
    assert samples[("t_depth", "")] == 7
    # Buckets are CUMULATIVE and capped by the +Inf bucket == count.
    assert samples[("t_lat_bucket", 'le="0.1"')] == 1
    assert samples[("t_lat_bucket", 'le="1"')] == 2
    assert samples[("t_lat_bucket", 'le="+Inf"')] == 3
    assert samples[("t_lat_count", "")] == 3
    assert samples[("t_lat_sum", "")] == pytest.approx(5.55)
    # One TYPE line per family.
    types = [l for l in text.splitlines() if l.startswith("# TYPE t_lat ")]
    assert types == ["# TYPE t_lat histogram"]


def test_prometheus_label_escaping():
    metrics.counter("t_esc", {"k": 'a"b\\c\nd'}).inc()
    text = metrics.render_prometheus()
    assert '{k="a\\"b\\\\c\\nd"}' in text


# -- rolling SLO windows --------------------------------------------------


def test_slo_window_math_with_seeded_stream():
    clock = [1000.0]
    w = slo.SloWindows(windows=(60.0, 300.0), now=lambda: clock[0])

    # 100 observations, one per second: latency i ms at t=1000+i.
    for i in range(100):
        clock[0] = 1000.0 + i
        w.observe("sssp", i / 1000.0)
    clock[0] = 1099.0   # time of the last observation

    snap = w.snapshot()
    assert set(snap) == {"60s", "300s"}
    # 300s window holds all 100 points: p50 of 0..99ms.
    full = snap["300s"]["sssp"]
    assert full["count"] == 100
    assert full["p50"] == pytest.approx(0.0495, abs=1e-4)
    assert full["p99"] == pytest.approx(0.09801, abs=1e-4)
    # 60s window holds t in [1039, 1099] -> latencies 39..99ms (61 pts).
    recent = snap["60s"]["sssp"]
    assert recent["count"] == 61
    assert recent["p50"] == pytest.approx(0.069, abs=1e-4)
    assert recent["p95"] == pytest.approx(0.096, abs=1e-4)

    # Everything ages out.
    clock[0] = 3000.0
    assert w.snapshot()["300s"] == {}


def test_slo_windows_from_flags(monkeypatch):
    monkeypatch.setenv("LUX_STATUSZ_WINDOWS", "10, 60,10")
    assert slo.windows_from_flags() == (10.0, 60.0)
    monkeypatch.setenv("LUX_STATUSZ_WINDOWS", "garbage")
    assert slo.windows_from_flags() == (60.0, 300.0)


# -- flight recorder ------------------------------------------------------


def _stalled_batcher(max_queue=8, fail=None):
    release = threading.Event()
    started = threading.Event()

    def execute(batch):
        started.set()
        release.wait(10)
        if fail is not None:
            raise fail
        for r in batch:
            r.future.set_result("done")

    b = MicroBatcher(execute, max_batch=1, window_s=0.01,
                     max_queue=max_queue)
    return b, release, started


def _arm(monkeypatch, tmp_path):
    d = tmp_path / "flight"
    monkeypatch.setenv("LUX_FLIGHT_DIR", str(d))
    flight.reconfigure()
    return d


def _dumps(d):
    return sorted(d.glob("flight-*.json")) if d.exists() else []


def test_flight_dump_on_deadline_shed(monkeypatch, tmp_path, sink):
    d = _arm(monkeypatch, tmp_path)
    with spans.span("doomed"):
        pass                       # one completed trace in the ring
    b, release, started = _stalled_batcher()
    try:
        blocker = b.submit(Request(app="x", payload=None, batch_key=None))
        assert started.wait(5)
        expired = b.submit(Request(
            app="x", payload=None, batch_key=None,
            deadline=spans.monotonic() - 0.001,
        ))
        release.set()
        with pytest.raises(DeadlineExceededError):
            expired.result(10)
        blocker.result(10)
    finally:
        release.set()
        b.close()

    files = _dumps(d)
    assert len(files) == 1
    doc = json.loads(files[0].read_text())
    assert doc["schema"] == "flight.v1"
    assert doc["reason"] == "deadline_shed"
    assert "waited" in doc["detail"]
    assert any(t.get("spans") for t in doc["traces"])
    assert isinstance(doc["metrics"], list) and doc["flags"]
    assert doc["flags"]["LUX_FLIGHT_DIR"] == str(d)


def test_flight_dump_on_engine_exception(monkeypatch, tmp_path):
    d = _arm(monkeypatch, tmp_path)
    boom = RuntimeError("engine exploded")
    b, release, started = _stalled_batcher(fail=boom)
    try:
        fut = b.submit(Request(app="x", payload=None, batch_key=None))
        assert started.wait(5)
        release.set()
        with pytest.raises(RuntimeError, match="engine exploded"):
            fut.result(10)
    finally:
        release.set()
        b.close()

    files = _dumps(d)
    assert len(files) == 1
    doc = json.loads(files[0].read_text())
    assert doc["reason"] == "engine_exception"
    assert "engine exploded" in doc["detail"]


def test_flight_dump_debounced_and_forced(monkeypatch, tmp_path):
    d = _arm(monkeypatch, tmp_path)
    assert flight.dump("storm") is not None
    assert flight.dump("storm") is None          # within debounce window
    assert flight.dump("other_reason") is not None   # per-reason debounce
    assert flight.dump("storm", force=True) is not None
    assert len(_dumps(d)) == 3


def test_flight_unarmed_is_inert(tmp_path):
    assert not flight.enabled()
    assert flight.dump("ignored") is None
    spans_before = flight.counts()
    with spans.span("unrecorded"):
        pass
    assert flight.counts() == spans_before
    assert list(tmp_path.iterdir()) == []


def test_flight_ring_is_bounded(monkeypatch, tmp_path):
    monkeypatch.setenv("LUX_FLIGHT_CAPACITY", "4")
    _arm(monkeypatch, tmp_path)
    for i in range(100):
        with spans.span("burst", i=i):
            pass
        flight.note_iteration({"iteration": i})
    c = flight.counts()
    assert c == {"traces": 4, "iterations": 4, "capacity": 4}
    # The ring keeps the NEWEST records.
    path = flight.dump("overflow", force=True)
    doc = json.loads(open(path).read())
    kept = [t["spans"][0]["attrs"]["i"] for t in doc["traces"]]
    assert kept == [96, 97, 98, 99]
    assert [r["iteration"] for r in doc["iterations"]] == [96, 97, 98, 99]
