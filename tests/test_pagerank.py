"""PageRank parity: jitted engine vs. host numpy oracle."""

import numpy as np
import pytest

from lux_tpu.engine.pull import PullExecutor
from lux_tpu.graph import generate
from lux_tpu.models.pagerank import PageRank, reference_pagerank, true_ranks


@pytest.mark.parametrize("strategy", ["rowptr", "segment"])
def test_pagerank_parity_random(strategy):
    g = generate.gnp(500, 4000, seed=7)
    ex = PullExecutor(g, PageRank(), sum_strategy=strategy)
    got = np.asarray(ex.run(10))
    want = reference_pagerank(g, 10)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-9)


def test_pagerank_parity_rmat():
    g = generate.rmat(10, 8, seed=1)
    ex = PullExecutor(g, PageRank())
    got = np.asarray(ex.run(10))
    want = reference_pagerank(g, 10)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-9)


def test_pagerank_sink_and_source_vertices():
    # Star: center has out-edges only; leaves are sinks (out-degree 0 in
    # the directed star), exercising both branches of the degree divide.
    g = generate.star_graph(10)
    ex = PullExecutor(g, PageRank())
    got = np.asarray(ex.run(5))
    want = reference_pagerank(g, 5)
    np.testing.assert_allclose(got, want, rtol=2e-5)


def test_pagerank_mass_interpretation():
    # With the reference's formula, one iteration from uniform gives
    # r(v) = 0.85/nv + 0.15 * sum_in(1/nv / outdeg(src)).
    g = generate.cycle_graph(4)  # every vertex: in=out=1
    ex = PullExecutor(g, PageRank())
    got = np.asarray(ex.run(1))
    expected = 0.85 / 4 + 0.15 * 0.25
    np.testing.assert_allclose(got, np.full(4, expected), rtol=1e-6)
    np.testing.assert_allclose(
        true_ranks(got, g.out_degrees), np.full(4, expected), rtol=1e-6
    )


def test_run_is_pipelined_and_deterministic():
    g = generate.gnp(200, 1500, seed=9)
    ex = PullExecutor(g, PageRank())
    a = np.asarray(ex.run(7))
    b = np.asarray(ex.run(7))
    np.testing.assert_array_equal(a, b)  # XLA segment sums are deterministic
