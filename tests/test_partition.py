"""Partitioner tests: must reproduce the reference greedy sweep
(core/pull_model.inl:108-131) bound-for-bound."""

import numpy as np

from lux_tpu.graph import Graph, generate
from lux_tpu.graph.partition import PartitionInfo, edge_balanced_bounds


def reference_sweep(row_ptr, num_parts):
    """The reference greedy sweep's semantics (close part at v when the
    running in-degree sum exceeds cap), with lux_tpu's two documented
    divergences: overflow merges into the last part, and trailing
    zero-in-degree vertices are folded into the last non-empty part."""
    nv = len(row_ptr) - 1
    ne = int(row_ptr[-1])
    cap = (ne + num_parts - 1) // num_parts
    bounds, left, cnt = [], 0, 0
    for v in range(nv):
        cnt += row_ptr[v + 1] - row_ptr[v]
        if cnt > cap and len(bounds) < num_parts - 1:
            bounds.append((left, v))
            cnt = 0
            left = v + 1
    if left <= nv - 1:
        bounds.append((left, nv - 1))
        left = nv
    while len(bounds) < num_parts:
        bounds.append((left, left - 1))
    return bounds


def test_matches_reference_sweep_random():
    for seed in range(5):
        g = generate.gnp(200, 2000, seed=seed)
        for parts in (1, 2, 3, 4, 8):
            got = edge_balanced_bounds(g.row_ptr, parts)
            want = reference_sweep(g.row_ptr, parts)
            assert got == want, (seed, parts)


def test_matches_reference_sweep_skewed():
    # Star: all edges land on a few hubs.
    g = generate.undirected(generate.star_graph(64))
    for parts in (2, 4, 8):
        assert edge_balanced_bounds(g.row_ptr, parts) == reference_sweep(
            g.row_ptr, parts
        )


def test_bounds_cover_and_balance():
    g = generate.rmat(12, 8, seed=1)
    parts = 8
    info = PartitionInfo.build(g.row_ptr, parts)
    covered = []
    total_edges = 0
    for (l, r), (es, ee) in zip(info.bounds, info.edge_bounds):
        if r >= l:
            covered.extend(range(l, r + 1))
            total_edges += ee - es
    assert covered == list(range(g.nv))
    assert total_edges == g.ne
    # Every non-final part's edges fit under cap + max-degree slack.
    cap = (g.ne + parts - 1) // parts
    maxdeg = int(g.in_degrees.max())
    for (es, ee) in info.edge_bounds[:-1]:
        assert ee - es <= cap + maxdeg


def test_frontier_slots_math():
    g = generate.gnp(1000, 8000, seed=2)
    info = PartitionInfo.build(g.row_ptr, 4)
    for (l, r), slots in zip(info.bounds, info.frontier_slots):
        assert slots == max(r - l, 0) // 16 + 100


def test_empty_padding_parts():
    g = generate.path_graph(4)  # 3 edges, ask for 8 parts
    bounds = edge_balanced_bounds(g.row_ptr, 8)
    assert len(bounds) == 8
    nvs = [max(r - l + 1, 0) for l, r in bounds]
    assert sum(nvs) >= 4  # all vertices covered by the non-empty parts
