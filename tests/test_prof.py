"""obs/prof.py: the profile.v1 parser against adversarial Chrome
traces, the interval algebra, region-name validation, the device-profile
registry, and the bench-gate device_kind fail-closed rule.

The smoke (`make prof-smoke`) proves the pipeline against a REAL
jax.profiler capture; these tests feed the parser synthetic traces a
real capture cannot reliably produce — nested regions, zero-length
events, out-of-order timestamps, multi-device streams, missing
durations, gzip truncation — and require either correct math or a loud
``ProfileParseError``, never a silently wrong report.
"""

import gzip
import json
import os
import sys

import pytest

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
sys.path.insert(0, REPO)

from lux_tpu.obs import prof, report  # noqa: E402

OPS = {"module": "jit_step", "ops": {
    "all-gather.1": "lux.test.exchange",
    "fusion.2": "lux.test.compute",
}}


def ev(name, ts, dur, pid=1, hlo_op=None, module="jit_step", **extra):
    e = {"ph": "X", "name": name, "ts": ts, "dur": dur, "pid": pid,
         "tid": 1}
    if hlo_op is not None:
        e["args"] = {"hlo_op": hlo_op, "hlo_module": module}
    e.update(extra)
    return e


def parse(events, **kw):
    kw.setdefault("op_maps", [OPS])
    return prof.parse_events({"traceEvents": events}, **kw)


# -- interval algebra ------------------------------------------------------


def test_merge_coalesces_and_drops_empty():
    assert prof.merge_intervals([(5, 7), (0, 2), (1, 3), (7, 7)]) == \
        [(0.0, 3.0), (5.0, 7.0)]
    assert prof.union_total([(0.0, 3.0), (5.0, 7.0)]) == 5.0


def test_intersect_merged():
    a = prof.merge_intervals([(0, 10)])
    b = prof.merge_intervals([(2, 4), (6, 8), (9, 12)])
    assert prof.intersect_merged(a, b) == [(2.0, 4.0), (6.0, 8.0),
                                          (9.0, 10.0)]


# -- classification and the union/intersection math ------------------------


def test_two_phase_union_and_overlap():
    rep = parse([
        ev("all-gather.1", 0, 10, hlo_op="all-gather.1"),
        ev("fusion.2", 5, 10, hlo_op="fusion.2"),
    ])
    d = rep["devices"]["1"]
    assert d["exchange_us"] == 10 and d["compute_us"] == 10
    assert d["overlap_us"] == 5 and d["union_us"] == 15
    assert d["realized_hidden_frac"] == 0.5
    assert rep["realized_hidden_frac"] == 0.5
    assert rep["tags"] == ["lux.test.compute", "lux.test.exchange"]


def test_nested_regions_do_not_double_count():
    # Nested/overlapping events of ONE phase must union, not sum: three
    # nested exchange ops spanning [0, 10] are 10us of exchange.
    rep = parse([
        ev("all-gather.1", 0, 10, hlo_op="all-gather.1"),
        ev("all-gather.1", 2, 4, hlo_op="all-gather.1"),
        ev("all-gather.1", 3, 2, hlo_op="all-gather.1"),
    ])
    assert rep["devices"]["1"]["exchange_us"] == 10


def test_zero_length_events_are_harmless():
    rep = parse([
        ev("all-gather.1", 5, 0, hlo_op="all-gather.1"),
        ev("fusion.2", 0, 4, hlo_op="fusion.2"),
    ])
    d = rep["devices"]["1"]
    assert d["exchange_us"] == 0 and d["compute_us"] == 4
    assert d["realized_hidden_frac"] is None  # no exchange time to hide


def test_out_of_order_timestamps():
    # Chrome traces carry no ordering guarantee; the math must not.
    rep = parse([
        ev("fusion.2", 100, 10, hlo_op="fusion.2"),
        ev("all-gather.1", 0, 10, hlo_op="all-gather.1"),
        ev("fusion.2", 4, 2, hlo_op="fusion.2"),
    ])
    d = rep["devices"]["1"]
    assert d["exchange_us"] == 10 and d["compute_us"] == 12
    assert d["overlap_us"] == 2
    assert d["span_us"] == 110


def test_multi_device_streams_stay_separate():
    rep = parse([
        ev("all-gather.1", 0, 10, pid=1, hlo_op="all-gather.1"),
        ev("fusion.2", 0, 10, pid=2, hlo_op="fusion.2"),
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/device:TPU:1"}},
    ])
    assert set(rep["devices"]) == {"1", "2"}
    # Device 1 has exchange only, device 2 compute only — concurrent
    # streams on DIFFERENT devices are not overlap on either.
    assert rep["devices"]["1"]["overlap_us"] == 0
    assert rep["devices"]["2"]["overlap_us"] == 0
    assert rep["devices"]["2"]["device"] == "/device:TPU:1"
    assert rep["realized_hidden_frac"] == 0.0


def test_missing_dur_counts_as_instant():
    d = parse([
        ev("all-gather.1", 0, 10, hlo_op="all-gather.1"),
        {"ph": "X", "name": "fusion.2", "ts": 3, "pid": 1, "tid": 1,
         "args": {"hlo_op": "fusion.2", "hlo_module": "jit_step"}},
    ])["devices"]["1"]
    assert d["compute_us"] == 0 and d["exchange_us"] == 10


def test_non_numeric_ts_is_loud():
    with pytest.raises(prof.ProfileParseError, match="non-numeric"):
        parse([ev("all-gather.1", "soon", 10, hlo_op="all-gather.1")])


def test_non_object_event_is_loud():
    with pytest.raises(prof.ProfileParseError, match="non-object"):
        parse(["not-an-event"])


def test_host_regions_never_join_device_unions():
    # A host TraceAnnotation span covering the whole window must not
    # manufacture overlap (async dispatch!): device overlap stays 0.
    rep = parse([
        ev("lux.serve.execute", 0, 100),          # host span, no hlo_op
        ev("all-gather.1", 0, 10, hlo_op="all-gather.1"),
        ev("fusion.2", 20, 10, hlo_op="fusion.2"),
    ])
    assert rep["devices"]["1"]["overlap_us"] == 0
    assert rep["host_regions"]["lux.serve.execute"]["count"] == 1
    assert "lux.serve.execute" in rep["tags"]


def test_non_lux_host_spans_ignored():
    rep = parse([ev("SomeFrameworkSpan", 0, 50)])
    assert rep["host_regions"] == {} and rep["devices"] == {}


def test_unknown_ops_count_busy_not_phase():
    d = parse([ev("copy.3", 0, 10, hlo_op="copy.3")])["devices"]["1"]
    assert d["busy_us"] == 10
    assert d["exchange_us"] == 0 and d["compute_us"] == 0


def test_ambiguous_op_only_fallback_declines():
    maps = [
        {"module": "a", "ops": {"op.1": "lux.a.exchange"}},
        {"module": "b", "ops": {"op.1": "lux.b.compute"}},
    ]
    rep = parse([ev("op.1", 0, 10, hlo_op="op.1", module="c")],
                op_maps=maps)
    d = rep["devices"]["1"]
    # Module "c" matches neither map and the op name is ambiguous
    # across them -> unclassified, never guessed.
    assert d["exchange_us"] == 0 and d["compute_us"] == 0


def test_gzip_truncated_artifact_is_loud(tmp_path):
    whole = gzip.compress(json.dumps(
        {"traceEvents": [ev("fusion.2", 0, 10, hlo_op="fusion.2")] * 100}
    ).encode())
    p = tmp_path / "t.trace.json.gz"
    p.write_bytes(whole[:len(whole) // 2])
    with pytest.raises(prof.ProfileParseError):
        prof.parse(str(p))


def test_bare_event_list_and_missing_file(tmp_path):
    p = tmp_path / "bare.trace.json"
    p.write_text(json.dumps([ev("fusion.2", 0, 4, hlo_op="fusion.2")]))
    assert prof.parse(str(p), op_maps=[OPS])["devices"]["1"][
        "compute_us"] == 4
    with pytest.raises(prof.ProfileParseError):
        prof.find_trace_artifact(str(tmp_path))  # no .gz artifact


def test_validate_rejects_broken_invariants():
    rep = parse([ev("all-gather.1", 0, 10, hlo_op="all-gather.1")])
    bad = json.loads(json.dumps(rep))
    bad["devices"]["1"]["union_us"] = 3.0     # < max phase
    with pytest.raises(prof.ProfileParseError, match="union"):
        prof.validate(bad)
    worse = json.loads(json.dumps(rep))
    worse["realized_hidden_frac"] = 1.5
    with pytest.raises(prof.ProfileParseError, match="outside"):
        prof.validate(worse)


def test_steps_cross_check_blocks():
    rep = parse(
        [ev("fusion.2", 0, 2_000_000, hlo_op="fusion.2")],
        steps=4, iterlog_summary={"num_iters": 4, "execute_s": 2.0})
    st = rep["steps"]
    assert st["captured"] == 4
    assert st["steps_per_s"] == pytest.approx(2.0)
    assert st["iterlog"]["steps_per_s"] == pytest.approx(2.0)


# -- region-name discipline at runtime -------------------------------------


def test_region_rejects_bad_names():
    for bad in ("pull.exchange", "lux.Pull", "lux.", "LUX.x", "lux x"):
        with pytest.raises(ValueError):
            prof.region(bad)
    prof.region("lux.pull_sharded.exchange")   # must not raise


def test_op_map_from_hlo():
    hlo = """HloModule jit_step, entry_computation_layout={()->f32[]}
  %all-gather.1 = f32[8]{0} all-gather(x), metadata={op_name="jit(step)/lux.pull_sharded.exchange/all_gather"}
  %fusion.2 = f32[8]{0} fusion(y), metadata={op_name="jit(step)/outer/lux.pull_sharded.compute/mul"}
  %copy.3 = f32[8]{0} copy(z), metadata={op_name="jit(step)/plain/mul"}
"""
    m = prof.op_map_from_hlo(hlo)
    assert m["module"] == "jit_step"
    assert m["ops"] == {
        "all-gather.1": "lux.pull_sharded.exchange",
        "fusion.2": "lux.pull_sharded.compute",
    }


# -- device-profile registry ------------------------------------------------


def test_device_profile_rows_and_overrides(monkeypatch):
    v5e = report.device_profile("TPU v5e")
    assert v5e["hbm_peak_gbps"] == 819.0 and v5e["known"]
    v5p = report.device_profile("TPU v5p")
    assert v5p["hbm_peak_gbps"] > v5e["hbm_peak_gbps"]
    cpu = report.device_profile("cpu")
    assert cpu["known"] and cpu["hbm_peak_gbps"] is None
    unk = report.device_profile("TPU v9")
    assert not unk["known"] and unk["hbm_peak_gbps"] is None
    monkeypatch.setenv("LUX_HBM_PEAK_GBPS", "1234.5")
    assert report.device_profile("TPU v9")["hbm_peak_gbps"] == 1234.5


def test_roofline_unknown_kind_yields_none_frac(monkeypatch):
    monkeypatch.setattr(report, "_kind_cache", ["TPU v99"])
    summary = {"num_iters": 10, "execute_s": 1.0,
               "hbm_bytes_per_iter": 10**9,
               "exchange_bytes_per_iter": 10**8, "parts": 2}
    roof = report.roofline(summary)
    assert roof["device_kind"] == "TPU v99"
    assert roof["hbm_gbps"] == pytest.approx(10.0)
    assert roof["hbm_frac"] is None and roof["ici_frac"] is None
    # The n/a rendering must survive the report table.
    table = report._format_table({
        "engine": "pull", "program": "PageRank", "nv": 1, "ne": 1,
        "num_iters": 10, "compile_s": 0.0, "execute_s": 1.0,
        "gteps": 0.1, "roofline": roof})
    assert "n/a" in table


# -- bench-gate device_kind context ----------------------------------------


def _gate():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_gate
    return bench_gate


def test_gate_fails_closed_on_foreign_chip():
    bg = _gate()
    cur = {"mode": "fast", "scale": 10, "ef": 8, "layout": "flat",
           "platform": "tpu", "exchange": "full",
           "device_kind": "TPU v5e"}
    ok, reason = bg.comparable(cur, dict(cur, device_kind="TPU v5p"))
    assert not ok and "device_kind" in reason
    ok, _ = bg.comparable(cur, dict(cur))
    assert ok
    # Baseline predating the device_kind key: fail closed on TPU...
    legacy = dict(cur)
    legacy.pop("device_kind")
    ok, reason = bg.comparable(cur, legacy)
    assert not ok and "device_kind" in reason
    # ...but cpu-vs-cpu stays comparable (the kind IS the platform).
    cur_cpu = dict(cur, platform="cpu", device_kind="cpu")
    legacy_cpu = dict(cur_cpu)
    legacy_cpu.pop("device_kind")
    ok, reason = bg.comparable(cur_cpu, legacy_cpu)
    assert ok, reason
