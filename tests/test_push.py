"""Push engine: SSSP + CC parity vs host oracles, invariant checkers,
single-device and 8-way sharded."""

import numpy as np
import pytest

from lux_tpu.engine.check import check, count_violations
from lux_tpu.engine.push import PushExecutor, ShardedPushExecutor
from lux_tpu.graph import generate
from lux_tpu.models.components import ConnectedComponents, reference_components
from lux_tpu.models.sssp import SSSP, reference_sssp
from lux_tpu.parallel.mesh import make_mesh


def test_sssp_path_graph():
    g = generate.path_graph(10)
    ex = PushExecutor(g, SSSP())
    state, iters = ex.run(start=0)
    np.testing.assert_array_equal(
        np.asarray(state.values), np.arange(10, dtype=np.uint32)
    )
    assert check(g, np.asarray(state.values), SSSP(), verbose=False)


def test_sssp_random_parity():
    g = generate.gnp(400, 2400, seed=3)
    ex = PushExecutor(g, SSSP())
    state, _ = ex.run(start=5)
    got = np.asarray(state.values)
    np.testing.assert_array_equal(got, reference_sssp(g, start=5))
    assert count_violations(g, got, SSSP()) == 0


def test_sssp_unreachable_stays_infinite():
    g = generate.path_graph(6)  # directed: nothing reaches vertex 0
    ex = PushExecutor(g, SSSP())
    state, _ = ex.run(start=3)
    got = np.asarray(state.values)
    assert got[3] == 0 and got[5] == 2
    assert got[0] == g.nv and got[1] == g.nv and got[2] == g.nv


def test_sssp_detects_bad_values():
    g = generate.gnp(100, 600, seed=1)
    state, _ = PushExecutor(g, SSSP()).run(start=0)
    vals = np.asarray(state.values).copy()
    reached = np.flatnonzero(vals < g.nv // 2)
    if len(reached) > 1:
        vals[reached[1]] = 0 if reached[1] != 0 else 1  # corrupt
        vals[reached[0]] += 3
    assert count_violations(g, vals, SSSP()) >= 0  # runs; then force a fail:
    vals[:] = 0
    vals[0] = g.nv  # some edge (0->x) now has dst 0 <= src nv+1 ok; invert:
    # make one *violating* edge explicitly: dst > src+1
    src0 = g.col_src[0]
    vals[:] = 1
    vals[src0] = 0
    dst0 = g.col_dst[0]
    vals[dst0] = 5  # 5 > 0+1 → violation
    assert count_violations(g, vals, SSSP()) >= 1


def test_cc_two_components():
    # Two disjoint undirected cycles: 0-4, 5-9.
    import numpy as _np

    src = _np.array([0, 1, 2, 3, 4, 5, 6, 7, 8, 9])
    dst = _np.array([1, 2, 3, 4, 0, 6, 7, 8, 9, 5])
    from lux_tpu.graph import Graph

    g = generate.undirected(Graph.from_edges(src, dst, nv=10))
    ex = PushExecutor(g, ConnectedComponents())
    state, _ = ex.run()
    got = np.asarray(state.values)
    np.testing.assert_array_equal(got[:5], np.full(5, 4))
    np.testing.assert_array_equal(got[5:], np.full(5, 9))
    assert check(g, got, ConnectedComponents(), verbose=False)


def test_cc_random_parity():
    g = generate.undirected(generate.gnp(300, 500, seed=11))
    ex = PushExecutor(g, ConnectedComponents())
    state, _ = ex.run()
    got = np.asarray(state.values)
    np.testing.assert_array_equal(got, reference_components(g))
    assert count_violations(g, got, ConnectedComponents()) == 0


@pytest.mark.parametrize("parts", [2, 8])
def test_sharded_sssp_parity(parts):
    g = generate.gnp(500, 3000, seed=9)
    ex = ShardedPushExecutor(g, SSSP(), mesh=make_mesh(parts))
    state, _ = ex.run(start=0)
    got = ex.gather_values(state)
    np.testing.assert_array_equal(got, reference_sssp(g, start=0))


@pytest.mark.parametrize("parts", [8])
def test_sharded_cc_parity(parts):
    g = generate.undirected(generate.gnp(400, 700, seed=13))
    ex = ShardedPushExecutor(g, ConnectedComponents(), mesh=make_mesh(parts))
    state, _ = ex.run()
    got = ex.gather_values(state)
    np.testing.assert_array_equal(got, reference_components(g))


@pytest.mark.parametrize("parts", [2, 8])
def test_sharded_sparse_branch_taken_and_correct(parts):
    """The distributed frontier path: late small-frontier iterations must
    run through the sparse branch (bounded queue + push-CSR expansion)
    and still reach the exact oracle fixpoint."""
    g = generate.gnp(2000, 16000, seed=31)
    ex = ShardedPushExecutor(
        g, SSSP(), mesh=make_mesh(parts), queue_frac=4, edge_budget_frac=2
    )
    state, iters = ex.run(start=0)
    assert ex.sparse_iters > 0, "sparse branch never taken"
    assert ex.sparse_iters < iters, "dense fallback never taken"
    got = ex.gather_values(state)
    np.testing.assert_array_equal(got, reference_sssp(g, start=0))


def test_sharded_sparse_long_chain_all_sparse():
    # Single-vertex frontier each iteration: every iteration should take
    # the sparse branch on the mesh, like the single-device equivalent.
    g = generate.path_graph(1100)
    ex = ShardedPushExecutor(g, SSSP(), mesh=make_mesh(4), queue_frac=1)
    assert ex.sparse
    state, iters = ex.run(start=0)
    assert ex.sparse_iters == iters
    np.testing.assert_array_equal(
        ex.gather_values(state), np.arange(1100, dtype=np.uint32)
    )


def test_sharded_sparse_weighted_cc():
    # CC's dense initial frontier must fall back dense on iter 1 on the
    # mesh too, then the label fixpoint must match the oracle.
    g = generate.undirected(generate.gnp(600, 1200, seed=33, weighted=True))
    ex = ShardedPushExecutor(
        g, ConnectedComponents(), mesh=make_mesh(8), queue_frac=2,
        edge_budget_frac=1,
    )
    state, iters = ex.run()
    assert ex.sparse_iters < iters, "dense fallback never taken"
    got = ex.gather_values(state)
    np.testing.assert_array_equal(got, reference_components(g))


def test_blocked_dense_sssp_parity():
    # Force the packed-table row-gather + segmented-scan dense path on a
    # small graph and require the exact oracle fixpoint (including empty
    # and trailing-empty rows of the CSC).
    g = generate.gnp(700, 5000, seed=41)
    ex = PushExecutor(g, SSSP(), blocked_dense=True)
    assert ex.blocked_dense
    state, _ = ex.run(start=0)
    np.testing.assert_array_equal(
        np.asarray(state.values), reference_sssp(g, start=0)
    )


def test_blocked_dense_cc_parity_weighted():
    # max combiner + weights plumbed through the blocked chunks.
    g = generate.undirected(generate.gnp(400, 900, seed=43, weighted=True))
    ex = PushExecutor(g, ConnectedComponents(), blocked_dense=True)
    state, _ = ex.run()
    np.testing.assert_array_equal(
        np.asarray(state.values), reference_components(g)
    )


def test_blocked_dense_matches_plain_dense():
    g = generate.gnp(1000, 9000, seed=47)
    a, _ = PushExecutor(g, SSSP(), blocked_dense=True).run(start=2)
    b, _ = PushExecutor(g, SSSP(), blocked_dense=False).run(start=2)
    np.testing.assert_array_equal(np.asarray(a.values), np.asarray(b.values))


@pytest.mark.parametrize("parts", [2, 8])
def test_sharded_blocked_dense_parity(parts):
    # Force the blocked dense path on the mesh: packed-table all-gather,
    # per-shard row-gather + lane select, segmented-scan reduction.
    g = generate.gnp(900, 7000, seed=51)
    ex = ShardedPushExecutor(
        g, SSSP(), mesh=make_mesh(parts), blocked_dense=True
    )
    assert ex.blocked_dense
    state, _ = ex.run(start=0)
    np.testing.assert_array_equal(
        ex.gather_values(state), reference_sssp(g, start=0)
    )


def test_sharded_blocked_dense_weighted_cc():
    g = generate.undirected(generate.gnp(500, 1100, seed=53, weighted=True))
    ex = ShardedPushExecutor(
        g, ConnectedComponents(), mesh=make_mesh(4), blocked_dense=True
    )
    state, _ = ex.run()
    np.testing.assert_array_equal(
        ex.gather_values(state), reference_components(g)
    )


def test_sharded_blocked_matches_plain(parts=4):
    g = generate.gnp(800, 6000, seed=55)
    a, _ = ShardedPushExecutor(
        g, SSSP(), mesh=make_mesh(parts), blocked_dense=True
    ).run(start=1)
    b, _ = ShardedPushExecutor(
        g, SSSP(), mesh=make_mesh(parts), blocked_dense=False
    ).run(start=1)
    np.testing.assert_array_equal(
        np.asarray(a.values), np.asarray(b.values)
    )


def test_segmented_minmax_scan_unit():
    import jax.numpy as jnp

    from lux_tpu.ops.segment import segment_minmax_by_rowptr

    # rows: [5,3,9 | 7 | (empty) | 2,8]
    data = jnp.asarray(np.array([5, 3, 9, 7, 2, 8], np.uint32))
    row_ptr = np.array([0, 3, 4, 4, 6], np.int64)
    seg_start = jnp.asarray(np.array([1, 0, 0, 1, 1, 0], bool))
    end_pos = jnp.asarray(np.clip(row_ptr[1:] - 1, 0, 5).astype(np.int32))
    nonempty = jnp.asarray(np.diff(row_ptr) > 0)
    got = segment_minmax_by_rowptr(data, seg_start, end_pos, nonempty, "min")
    want = np.array([3, 7, np.iinfo(np.uint32).max, 2], np.uint32)
    np.testing.assert_array_equal(np.asarray(got), want)
    got = segment_minmax_by_rowptr(data, seg_start, end_pos, nonempty, "max")
    want = np.array([9, 7, 0, 8], np.uint32)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_chunked_halt_runs_exact_fixpoint():
    # Fixpoint must be unchanged by chunked on-device early-exit iteration.
    g = generate.path_graph(20)
    ex = PushExecutor(g, SSSP())
    state, iters = ex.run(start=0)
    assert iters >= 19  # needs the full diameter plus window slack
    np.testing.assert_array_equal(
        np.asarray(state.values), np.arange(20, dtype=np.uint32)
    )


def test_sparse_path_taken_and_correct():
    """Force tiny budgets so early iterations go sparse, later go dense;
    fixpoint must equal the dense-only run and the oracle."""
    g = generate.gnp(2000, 16000, seed=21)
    dense_only = PushExecutor(g, SSSP(), sparse=False)
    sd, _ = dense_only.run(start=0)
    adaptive = PushExecutor(g, SSSP(), queue_frac=4, edge_budget_frac=2)
    sa, _ = adaptive.run(start=0)
    np.testing.assert_array_equal(
        np.asarray(sa.values), np.asarray(sd.values)
    )
    np.testing.assert_array_equal(np.asarray(sa.values), reference_sssp(g, 0))


def test_sparse_overflow_falls_back_dense():
    # CC starts with a full frontier: sparse preconditions fail on iter 1,
    # so the cond must take the dense branch and still be correct.
    g = generate.undirected(generate.gnp(500, 900, seed=23))
    ex = PushExecutor(g, ConnectedComponents(), queue_frac=64)
    state, _ = ex.run()
    np.testing.assert_array_equal(
        np.asarray(state.values), reference_components(g)
    )


def test_sparse_weighted_graph():
    # Weighted graphs exercise the csr_weights permutation in the sparse
    # expansion (SSSP ignores weights, but the plumbing must not crash).
    g = generate.gnp(800, 6400, seed=25, weighted=True)
    ex = PushExecutor(g, SSSP())
    state, _ = ex.run(start=3)
    np.testing.assert_array_equal(
        np.asarray(state.values), reference_sssp(g, 3)
    )


def test_sparse_path_graph_long_chain():
    # Path graph: frontier is a single vertex every iteration — the
    # sparse path runs every iteration (ne=1099 >= the 1024 sparse gate).
    g = generate.path_graph(1100)
    ex = PushExecutor(g, SSSP(), queue_frac=1)
    assert ex.sparse
    state, iters = ex.run(start=0)
    np.testing.assert_array_equal(
        np.asarray(state.values), np.arange(1100, dtype=np.uint32)
    )
