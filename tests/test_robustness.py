"""Graceful degradation: bounded retry, circuit breaker lifecycle,
stale-while-revalidate serving, and the shed-response HTTP contract."""

import json
import time
import urllib.error
import urllib.request

import pytest

from lux_tpu.graph import EdgeEdits, generate
from lux_tpu.obs import metrics
from lux_tpu.serve import (CircuitBreaker, CircuitOpenError, ServeConfig,
                           Session, SnapshotSwapError)
from lux_tpu.serve.breaker import CLOSED, HALF_OPEN, OPEN
from lux_tpu.serve.errors import (DeadlineExceededError, QueueFullError)
from lux_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


def _cfg(**kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("window_s", 0.001)
    kw.setdefault("pagerank_iters", 3)
    return ServeConfig(**kw)


def _graph(seed=21):
    return generate.gnp(100, 600, seed=seed)


# -- error taxonomy --------------------------------------------------------


def test_shed_errors_carry_retry_after():
    assert QueueFullError("x").retry_after_s == 1.0
    assert DeadlineExceededError("x").retry_after_s == 1.0
    assert SnapshotSwapError("x").retry_after_s == 2.0
    e = CircuitOpenError("x", retry_after_s=0.75)
    assert e.http_status == 503 and e.retry_after_s == 0.75


# -- breaker unit ----------------------------------------------------------


def test_breaker_opens_at_threshold(monkeypatch):
    monkeypatch.setenv("LUX_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("LUX_BREAKER_COOLDOWN_MS", "60000")
    br = CircuitBreaker(lambda key: True)
    key = ("sssp", "fp")
    for _ in range(2):
        br.record_failure(key, error=RuntimeError("boom"))
    br.check(key)                         # still closed
    assert br.state(key) == CLOSED
    br.record_failure(key, error=RuntimeError("boom"))
    assert br.state(key) == OPEN
    with pytest.raises(CircuitOpenError) as ei:
        br.check(key)
    assert ei.value.retry_after_s > 0
    s = br.stats()
    assert s["open"] == 1
    assert s["entries"][str(key)]["consecutive"] == 3
    assert "boom" in s["entries"][str(key)]["last_error"]


def test_breaker_success_resets_consecutive(monkeypatch):
    monkeypatch.setenv("LUX_BREAKER_THRESHOLD", "3")
    br = CircuitBreaker(lambda key: True)
    key = ("a", "b")
    br.record_failure(key)
    br.record_failure(key)
    br.record_success(key)
    br.record_failure(key)
    br.record_failure(key)
    assert br.state(key) == CLOSED        # never hit 3 in a row


def test_breaker_halfopen_probe_closes(monkeypatch):
    monkeypatch.setenv("LUX_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("LUX_BREAKER_COOLDOWN_MS", "50")
    probed = []

    def probe(key):
        probed.append(key)
        return True

    br = CircuitBreaker(probe)
    key = ("sssp", "fp")
    br.record_failure(key)
    assert br.state(key) == OPEN
    time.sleep(0.08)
    # Cooldown elapsed: this check flips to half-open, launches the
    # single-flight probe, and STILL sheds (probe hasn't reported).
    with pytest.raises(CircuitOpenError):
        br.check(key)
    br.drain_probes()
    assert probed == [key]
    assert br.state(key) == CLOSED
    br.check(key)                         # closed: no raise
    t = br.stats()["transitions"]
    assert t[OPEN] >= 1 and t[HALF_OPEN] >= 1 and t[CLOSED] >= 1


def test_breaker_failed_probe_reopens(monkeypatch):
    monkeypatch.setenv("LUX_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("LUX_BREAKER_COOLDOWN_MS", "50")
    br = CircuitBreaker(lambda key: (_ for _ in ()).throw(RuntimeError()))
    key = ("k",)
    br.record_failure(key)
    time.sleep(0.08)
    with pytest.raises(CircuitOpenError):
        br.check(key)
    br.drain_probes()
    assert br.state(key) == OPEN          # probe failed: cooldown restarts
    with pytest.raises(CircuitOpenError):
        br.check(key)


# -- session retry / breaker integration -----------------------------------


def test_transient_engine_fault_is_retried_away(monkeypatch):
    monkeypatch.setenv("LUX_RETRY_MAX", "2")
    monkeypatch.setenv("LUX_RETRY_BACKOFF_MS", "5")
    metrics.reset()
    g = _graph()
    with Session(g, _cfg(), warm=False) as s:
        # Exactly two injected failures: attempts 1+2 fail, attempt 3
        # answers — the client never sees the blip.
        faults.arm("serve.engine.execute:raise:1.0:2")
        out = s.query("sssp", start=3, timeout=60)
        assert out["values"].shape == (g.nv,)
        assert metrics.counter("lux_serve_retries_total",
                               {"app": "sssp"}).value == 2
        assert s.breaker.state(("sssp", s.fingerprint)) == CLOSED


def test_breaker_full_cycle_through_session(monkeypatch):
    monkeypatch.setenv("LUX_RETRY_MAX", "0")
    monkeypatch.setenv("LUX_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("LUX_BREAKER_COOLDOWN_MS", "60000")
    g = _graph()
    with Session(g, _cfg(), warm=True) as s:
        bkey = ("sssp", s.fingerprint)
        faults.arm("serve.engine.execute:raise:1.0")
        for start in (1, 2):              # distinct roots: no cache hits
            with pytest.raises(faults.FaultInjected):
                s.query("sssp", start=start, timeout=60)
        assert s.breaker.state(bkey) == OPEN
        # Open: shed synchronously, before the queue.
        with pytest.raises(CircuitOpenError):
            s.submit("sssp", start=3)
        assert s.statusz()["breaker"]["open"] == 1

        # Heal the engine, shrink the cooldown (flags re-read per call),
        # and let the half-open probe rebuild + prove the pool entry.
        faults.disarm()
        monkeypatch.setenv("LUX_BREAKER_COOLDOWN_MS", "1")
        time.sleep(0.01)
        with pytest.raises(CircuitOpenError):
            s.submit("sssp", start=3)
        s.breaker.drain_probes()
        assert s.breaker.state(bkey) == CLOSED
        out = s.query("sssp", start=3, timeout=60)
        assert out["values"].shape == (g.nv,)
        # Probe compiles count as expected warmup, not recompiles.
        assert s.pool.stats()["recompiles"] == 0


def test_serve_error_is_not_retried(monkeypatch):
    monkeypatch.setenv("LUX_RETRY_MAX", "3")
    metrics.reset()
    g = _graph()
    with Session(g, _cfg(), warm=False) as s:
        with pytest.raises(Exception, match="out of range"):
            s.query("sssp", start=10**9, timeout=60)
        assert metrics.counter("lux_serve_retries_total",
                               {"app": "sssp"}).value == 0


# -- stale-while-revalidate ------------------------------------------------


def test_failed_warm_serves_stale_then_revalidates():
    g = _graph()
    with Session(g, _cfg(), warm=False) as s:
        before = s.query("sssp", start=0, timeout=60)
        faults.arm("snapshot.warm:raise:1.0:1")
        with pytest.raises(SnapshotSwapError):
            s.apply_edits(EdgeEdits.from_lists(insert=[(0, 7), (1, 9)]))
        faults.disarm()
        # Version 0 still answers; the session says so.
        assert s.version == 0
        assert s.degraded is not None
        assert s.degraded["failed_version"] == 1
        again = s.query("sssp", start=0, timeout=60)
        assert again["values"].shape == before["values"].shape
        # Revalidate: the minted version is still the store head; flush
        # retries the warm WITHOUT re-applying the edits.
        out = s.flush_edits()
        assert out["version"] == 1 and s.version == 1
        assert s.degraded is None
        assert s.store.current().version == 1


def test_enqueue_coalesces_and_autoflushes(monkeypatch):
    monkeypatch.setenv("LUX_EDIT_QUEUE_MAX", "3")
    g = _graph()
    with Session(g, _cfg(), warm=False) as s:
        r1 = s.enqueue_edits(EdgeEdits.from_lists(insert=[(0, 5)]))
        r2 = s.enqueue_edits(EdgeEdits.from_lists(insert=[(1, 6)]))
        assert (r1["pending"], r2["pending"]) == (1, 2)
        assert s.version == 0                 # nothing swapped yet
        r3 = s.enqueue_edits(EdgeEdits.from_lists(insert=[(2, 7)]))
        # Third enqueue crossed LUX_EDIT_QUEUE_MAX: ONE swap folds all 3.
        assert r3["version"] == 1 and s.version == 1
        assert s.graph.ne == g.ne + 3
        assert s.flush_edits()["noop"] is True


# -- HTTP contract ---------------------------------------------------------


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_http_degraded_header_breaker_503_and_request_counts(monkeypatch):
    from lux_tpu.serve.http import serve_in_thread

    monkeypatch.setenv("LUX_RETRY_MAX", "0")
    monkeypatch.setenv("LUX_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("LUX_BREAKER_COOLDOWN_MS", "60000")
    metrics.reset()
    g = _graph()
    s = Session(g, _cfg(), warm=False)
    server, thread = serve_in_thread(s)
    base = "http://127.0.0.1:%d" % server.server_address[1]
    try:
        code, hdrs, body = _post(base, "/query", {"app": "sssp",
                                                  "start": 0})
        assert code == 200 and "X-Lux-Degraded" not in hdrs

        # Trip the breaker: one failure at threshold 1, then shed.
        faults.arm("serve.engine.execute:raise:1.0")
        code, hdrs, body = _post(base, "/query", {"app": "sssp",
                                                  "start": 1})
        assert code == 500 and body["kind"] == "FaultInjected"
        code, hdrs, body = _post(base, "/query", {"app": "sssp",
                                                  "start": 2})
        assert code == 503 and body["kind"] == "CircuitOpenError"
        assert float(hdrs["Retry-After"]) > 0
        # /statusz must stay JSON-serializable with rules armed (the
        # armed FaultRules are rendered as dicts, not dataclasses).
        code, _, statusz = _get(base, "/statusz")
        assert code == 200
        assert statusz["faults"]["armed"][0]["point"] == \
            "serve.engine.execute"
        assert statusz["faults"]["injected"]["serve.engine.execute:raise"] >= 1
        faults.disarm()

        # Degraded serving: a failed warm leaves the marker header on
        # every response until a later swap lands.
        faults.arm("snapshot.warm:raise:1.0:1")
        code, hdrs, body = _post(base, "/snapshot",
                                 {"insert": [[0, 9], [3, 8]]})
        assert code == 503 and body["kind"] == "SnapshotSwapError"
        assert float(hdrs["Retry-After"]) > 0
        faults.disarm()
        code, hdrs, body = _get(base, "/healthz")
        assert hdrs["X-Lux-Degraded"] == "1"
        assert hdrs["X-Lux-Snapshot"] == "0"

        code, hdrs, body = _post(base, "/snapshot", {"flush": True})
        assert code == 200 and body["version"] == 1
        code, hdrs, body = _get(base, "/healthz")
        assert "X-Lux-Degraded" not in hdrs
        assert hdrs["X-Lux-Snapshot"] == "1"

        # Every terminal response landed in the per-code counter.
        assert metrics.counter("lux_requests_total",
                               {"code": "200"}).value >= 2
        assert metrics.counter("lux_requests_total",
                               {"code": "503"}).value >= 2
        assert metrics.counter("lux_requests_total",
                               {"code": "500"}).value >= 1
    finally:
        server.shutdown()
        s.close()


def test_http_queue_true_enqueues_without_swap(monkeypatch):
    from lux_tpu.serve.http import serve_in_thread

    monkeypatch.setenv("LUX_EDIT_QUEUE_MAX", "100")
    g = _graph()
    s = Session(g, _cfg(), warm=False)
    server, thread = serve_in_thread(s)
    base = "http://127.0.0.1:%d" % server.server_address[1]
    try:
        code, hdrs, body = _post(base, "/snapshot",
                                 {"insert": [[0, 9]], "queue": True})
        assert code == 200 and body == {"queued": True, "pending": 1,
                                        "version": 0}
        code, hdrs, body = _post(base, "/snapshot", {"flush": True})
        assert code == 200 and body["version"] == 1
    finally:
        server.shutdown()
        s.close()
