"""Segment-reduction ops: blocked gather kernel + rowptr sum gate."""

import jax.numpy as jnp
import numpy as np
import pytest

import lux_tpu.ops.segment as seg


def test_take1d_blocked_matches_plain_gather():
    rng = np.random.default_rng(3)
    z = rng.standard_normal(100_003).astype(np.float32)
    idx = rng.integers(0, z.size, size=70_001)
    got = np.asarray(seg.take1d_blocked(z, idx.astype(np.int32)))
    np.testing.assert_array_equal(got, z[idx])


def test_take1d_blocked_edge_positions():
    z = np.arange(257, dtype=np.float32)
    idx = np.array([0, 1, 127, 128, 129, 255, 256], np.int64)
    got = np.asarray(seg.take1d_blocked(z, idx))
    np.testing.assert_array_equal(got, z[idx])


@pytest.mark.parametrize("force_blocked", [False, True])
def test_rowptr_sum_same_result_on_both_gate_sides(
    monkeypatch, force_blocked
):
    """The blocked fast path (normally gated behind 2^17 boundaries) must
    compute exactly what the scalar-gather path computes."""
    if force_blocked:
        monkeypatch.setattr(seg, "_BLOCKED_GATHER_MIN", 1)
    rng = np.random.default_rng(5)
    nv = 300
    counts = rng.integers(0, 9, size=nv)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    data = rng.standard_normal(int(row_ptr[-1])).astype(np.float32)
    got = np.asarray(seg.segment_sum_by_rowptr(data, row_ptr))
    want = np.array([
        data[row_ptr[v]: row_ptr[v + 1]].astype(np.float64).sum()
        for v in range(nv)
    ])
    # The cumsum-diff reduction's absolute error scales with the prefix
    # magnitude (~eps * |running sum|), not the row's own sum.
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=2e-5)


def test_segment_minmax_blockmin_fuzz():
    # The block-min hierarchy (one 128-block reduce + block-level
    # segmented scan + masked head/tail rows) must agree with the
    # scatter oracle for every segment shape: empty, inside-one-block,
    # block-aligned, multi-block, trailing-empty — across both
    # segmentation modes of the head/tail gather tables.
    from lux_tpu.ops.segment import (
        BlockMinLayout,
        segment_minmax_blockmin,
        segment_reduce,
    )

    rng = np.random.default_rng(0)
    for trial in range(6):
        nv = int(rng.integers(3, 400))
        ne = int(rng.integers(0, 3000))
        deg = rng.multinomial(ne, rng.dirichlet(np.ones(nv) * 0.3))
        rp = np.zeros(nv + 1, np.int64)
        np.cumsum(deg, out=rp[1:])
        nep = -(-max(ne, 1) // 128) * 128
        for kind in ("min", "max"):
            data = rng.integers(0, 1 << 24, ne).astype(np.uint32)
            ident = np.uint32(0xFFFFFFFF) if kind == "min" else np.uint32(0)
            padded = np.full(nep, ident, np.uint32)
            padded[:ne] = data
            ids = np.repeat(np.arange(nv), deg)
            want = np.asarray(segment_reduce(
                jnp.asarray(data), jnp.asarray(ids), nv, kind
            ))
            for seg_rows in (0, 4):
                lay = BlockMinLayout(rp, nep, seg_rows=seg_rows)
                la = {k: jnp.asarray(v)
                      for k, v in lay.device_arrays().items()}
                got = np.asarray(segment_minmax_blockmin(
                    jnp.asarray(padded), la, lay.head_segs,
                    lay.tail_segs, kind,
                ))
                np.testing.assert_array_equal(got, want)


def test_rowptr_sum_empty_and_single_element_segments():
    # Deterministic layout: empty segments at the start, middle, and end,
    # plus single-element runs — boundary diff must give exact zeros for
    # empties and the lone element for singletons.
    row_ptr = np.array([0, 0, 1, 1, 4, 5, 5], np.int64)
    data = np.array([10.0, 1.0, 2.0, 3.0, -7.0], np.float32)
    got = np.asarray(seg.segment_sum_by_rowptr(jnp.asarray(data), row_ptr))
    np.testing.assert_array_equal(
        got, np.array([0.0, 10.0, 0.0, 6.0, -7.0, 0.0], np.float32))


def test_rowptr_sum_no_edges_at_all():
    row_ptr = np.zeros(8, np.int64)
    got = np.asarray(seg.segment_sum_by_rowptr(
        jnp.asarray(np.zeros(0, np.float32)), row_ptr))
    np.testing.assert_array_equal(got, np.zeros(7, np.float32))


def test_blockmin_head_tail_at_block_boundaries():
    # Segments chosen to pin every head/tail extraction case of
    # BlockMinLayout exactly at 128-lane block edges: a full aligned
    # block, a singleton at the last lane of a block, a singleton at the
    # first lane of the next one, a straddler, an empty segment between
    # them, and a tail ending mid-block.
    from lux_tpu.ops.segment import BlockMinLayout, segment_minmax_blockmin

    bounds = [0, 128, 255, 256, 258, 258, 300]   # nv = 6 segments
    rp = np.asarray(bounds, np.int64)
    ne = int(rp[-1])
    nep = -(-ne // 128) * 128
    rng = np.random.default_rng(11)
    data = rng.integers(0, 1 << 24, ne).astype(np.uint32)
    for kind in ("min", "max"):
        ident = np.uint32(0xFFFFFFFF) if kind == "min" else np.uint32(0)
        padded = np.full(nep, ident, np.uint32)
        padded[:ne] = data
        want = np.array([
            getattr(data[s:e], kind)() if e > s else ident
            for s, e in zip(bounds[:-1], bounds[1:])
        ], np.uint32)
        for seg_rows in (0, 1):
            lay = BlockMinLayout(rp, nep, seg_rows=seg_rows)
            la = {k: jnp.asarray(v) for k, v in lay.device_arrays().items()}
            got = np.asarray(segment_minmax_blockmin(
                jnp.asarray(padded), la, lay.head_segs, lay.tail_segs, kind))
            np.testing.assert_array_equal(got, want)
