"""Segment-reduction ops: blocked gather kernel + rowptr sum gate."""

import numpy as np
import pytest

import lux_tpu.ops.segment as seg


def test_take1d_blocked_matches_plain_gather():
    rng = np.random.default_rng(3)
    z = rng.standard_normal(100_003).astype(np.float32)
    idx = rng.integers(0, z.size, size=70_001)
    got = np.asarray(seg.take1d_blocked(z, idx.astype(np.int32)))
    np.testing.assert_array_equal(got, z[idx])


def test_take1d_blocked_edge_positions():
    z = np.arange(257, dtype=np.float32)
    idx = np.array([0, 1, 127, 128, 129, 255, 256], np.int64)
    got = np.asarray(seg.take1d_blocked(z, idx))
    np.testing.assert_array_equal(got, z[idx])


@pytest.mark.parametrize("force_blocked", [False, True])
def test_rowptr_sum_same_result_on_both_gate_sides(
    monkeypatch, force_blocked
):
    """The blocked fast path (normally gated behind 2^17 boundaries) must
    compute exactly what the scalar-gather path computes."""
    if force_blocked:
        monkeypatch.setattr(seg, "_BLOCKED_GATHER_MIN", 1)
    rng = np.random.default_rng(5)
    nv = 300
    counts = rng.integers(0, 9, size=nv)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    data = rng.standard_normal(int(row_ptr[-1])).astype(np.float32)
    got = np.asarray(seg.segment_sum_by_rowptr(data, row_ptr))
    want = np.array([
        data[row_ptr[v]: row_ptr[v + 1]].astype(np.float64).sum()
        for v in range(nv)
    ])
    # The cumsum-diff reduction's absolute error scales with the prefix
    # magnitude (~eps * |running sum|), not the row's own sum.
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=2e-5)
