"""Serving subsystem: multi-source batching parity, admission control
(backpressure + deadline shedding), warm-engine pool, LRU result cache,
session routing, HTTP front end."""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from lux_tpu.engine.push import MultiSourcePushExecutor, PushExecutor
from lux_tpu.graph import generate
from lux_tpu.models.components import reference_components
from lux_tpu.models.pagerank import reference_pagerank
from lux_tpu.models.sssp import SSSP, reference_sssp
from lux_tpu.obs import metrics
from lux_tpu.serve import (
    BadQueryError,
    DeadlineExceededError,
    EnginePool,
    MicroBatcher,
    QueueFullError,
    Request,
    ResultCache,
    ServeConfig,
    Session,
)


# -- multi-source micro-batching: the tentpole mechanism ------------------


def test_multi_source_sssp_matches_sequential_int():
    """K roots served in one (nv, K) sweep must be bit-identical to K
    sequential single-source PushExecutor runs."""
    g = generate.gnp(500, 3500, seed=101)
    roots = [0, 3, 77, 401]
    mx = MultiSourcePushExecutor(g, SSSP(), k=len(roots))
    state, _ = mx.run(roots)
    for j, r in enumerate(roots):
        seq_state, _ = PushExecutor(g, SSSP()).run(start=r)
        np.testing.assert_array_equal(
            mx.values_for(state, j), np.asarray(seq_state.values)
        )
        np.testing.assert_array_equal(
            mx.values_for(state, j), reference_sssp(g, r)
        )


def test_multi_source_sssp_matches_sequential_weighted():
    """Weighted graphs exercise the (ne, 1)-broadcast weight plumbing in
    the batched relax."""
    g = generate.gnp(400, 3000, seed=103, weighted=True)
    roots = [5, 9, 250]
    mx = MultiSourcePushExecutor(g, SSSP(), k=3)
    state, _ = mx.run(roots)
    for j, r in enumerate(roots):
        seq_state, _ = PushExecutor(g, SSSP()).run(start=r)
        np.testing.assert_array_equal(
            mx.values_for(state, j), np.asarray(seq_state.values)
        )


def test_multi_source_pads_short_batches():
    """Fewer than k roots: lanes are padded by repeating the last root,
    so results are unchanged and the executable shape is stable (the
    zero-recompile contract)."""
    g = generate.gnp(300, 2000, seed=105)
    mx = MultiSourcePushExecutor(g, SSSP(), k=4)
    state, _ = mx.run([7])
    want = reference_sssp(g, 7)
    for j in range(4):
        np.testing.assert_array_equal(mx.values_for(state, j), want)


def test_multi_source_rejects_bad_widths():
    g = generate.gnp(50, 200, seed=1)
    with pytest.raises(ValueError):
        MultiSourcePushExecutor(g, SSSP(), k=0)
    mx = MultiSourcePushExecutor(g, SSSP(), k=2)
    with pytest.raises(ValueError):
        mx.run([1, 2, 3])   # more roots than lanes
    with pytest.raises(ValueError):
        mx.run([])


# -- admission control ----------------------------------------------------


def _stalled_batcher(max_queue, max_batch=1):
    """A batcher whose executor blocks until released (deterministic
    queue-full / deadline scenarios without timing races)."""
    release = threading.Event()
    started = threading.Event()

    def execute(batch):
        started.set()
        release.wait(10)
        for r in batch:
            r.future.set_result("done")

    b = MicroBatcher(execute, max_batch=max_batch, window_s=0.01,
                     max_queue=max_queue)
    return b, release, started


def test_queue_full_rejects_with_backpressure():
    """A full admission queue must reject instantly (QueueFullError +
    counter), never block the producer."""
    metrics.reset()
    b, release, started = _stalled_batcher(max_queue=2)
    try:
        first = b.submit(Request(app="x", payload=None, batch_key=None))
        assert started.wait(5), "worker never picked up a request"
        # Worker is stalled holding `first`; now fill the queue.
        q1 = b.submit(Request(app="x", payload=None, batch_key=None))
        q2 = b.submit(Request(app="x", payload=None, batch_key=None))
        t0 = time.monotonic()
        with pytest.raises(QueueFullError):
            b.submit(Request(app="x", payload=None, batch_key=None))
        assert time.monotonic() - t0 < 1.0, "rejection blocked"
        assert metrics.counter("lux_serve_rejected_total").value == 1
        release.set()
        assert first.result(10) == "done"
        assert q1.result(10) == "done" and q2.result(10) == "done"
    finally:
        release.set()
        b.close()


def test_deadline_expired_requests_are_shed():
    """Requests whose deadline passed while queued raise
    DeadlineExceededError and bump the obs counter; fresh requests in
    the same batch still execute."""
    metrics.reset()
    b, release, started = _stalled_batcher(max_queue=8)
    try:
        blocker = b.submit(Request(app="x", payload=None, batch_key=None))
        assert started.wait(5)
        expired = b.submit(Request(
            app="x", payload=None, batch_key=None,
            deadline=time.monotonic() - 0.001,   # already dead
        ))
        fresh = b.submit(Request(
            app="x", payload=None, batch_key=None,
            deadline=time.monotonic() + 30,
        ))
        release.set()
        with pytest.raises(DeadlineExceededError):
            expired.result(10)
        assert fresh.result(10) == "done"
        assert blocker.result(10) == "done"
        assert metrics.counter(
            "lux_serve_deadline_expired_total").value == 1
    finally:
        release.set()
        b.close()


def test_batcher_forms_multi_request_batches():
    """Requests sharing a batch_key inside the window coalesce into one
    execute() call; a non-matching key ends the batch and leads the
    next one (FIFO, no starvation)."""
    sizes = []
    done = threading.Event()

    def execute(batch):
        sizes.append([r.payload for r in batch])
        for r in batch:
            r.future.set_result(len(batch))
        if len(sizes) >= 2:
            done.set()

    b = MicroBatcher(execute, max_batch=8, window_s=0.25, max_queue=32)
    try:
        futs = [
            b.submit(Request(app="s", payload=i, batch_key="A"))
            for i in range(4)
        ]
        other = b.submit(Request(app="s", payload="b0", batch_key="B"))
        assert done.wait(10)
        assert futs[0].result(5) == 4      # all four A's in one batch
        assert other.result(5) == 1
        assert sizes[0] == [0, 1, 2, 3] and sizes[1] == ["b0"]
    finally:
        b.close()


# -- pool + cache ---------------------------------------------------------


def test_engine_pool_builds_once():
    metrics.reset()
    pool = EnginePool()
    builds = []
    k = ("push", "fp", "sssp", 1)
    a = pool.get(k, lambda: builds.append(1) or object())
    bb = pool.get(k, lambda: builds.append(1) or object())
    assert a is bb and builds == [1]
    st = pool.stats()
    assert st == {"engines": 1, "hits": 1, "misses": 1, "retired": 0,
                  "warmup_compiles": 0, "recompiles": 0,
                  "ir_findings": 0, "exch_findings": 0,
                  "gas_findings": 0, "hbm_resident_bytes": 0,
                  "hbm_evictions": 0}
    pool.close()


def test_result_cache_lru_evicts_oldest():
    metrics.reset()
    c = ResultCache(capacity=2)
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1        # refresh a
    c.put("c", 3)                 # evicts b
    assert c.get("b") is None
    assert c.get("a") == 1 and c.get("c") == 3
    st = c.stats()
    assert st["evictions"] == 1 and st["size"] == 2


def test_result_cache_evicts_by_value_bytes():
    metrics.reset()
    c = ResultCache(capacity=256, capacity_bytes=10_000)
    c.put("a", np.zeros(1024, np.float32))       # 4096 B
    c.put("b", np.zeros(1024, np.float32))
    assert c.get("a") is not None                # refresh a
    c.put("c", np.zeros(1024, np.float32))       # 12 KiB > budget: b goes
    assert c.get("b") is None
    assert c.get("a") is not None and c.get("c") is not None
    st = c.stats()
    assert st["size"] == 2 and st["bytes"] == 8192
    assert st["capacity_bytes"] == 10_000 and st["evictions"] == 1
    # Tree-valued entries price their array leaves.
    c.put("d", {"values": np.zeros(512, np.float32), "iters": 3})
    assert c.stats()["bytes"] >= 8192 - 4096 + 2048


def test_result_cache_oversized_entry_occupies_whole_budget():
    metrics.reset()
    c = ResultCache(capacity=4, capacity_bytes=1000)
    c.put("small", np.zeros(8, np.float32))
    c.put("huge", np.zeros(4096, np.float32))    # over budget by itself
    assert c.get("huge") is not None             # newest never self-evicts
    assert c.get("small") is None
    c.put("next", np.zeros(8, np.float32))       # displaces the whale
    assert c.get("huge") is None
    assert c.get("next") is not None


# -- session routing ------------------------------------------------------


@pytest.fixture(scope="module")
def served():
    g = generate.gnp(400, 2800, seed=201)
    cfg = ServeConfig(max_batch=4, window_s=0.25, max_queue=64,
                      pagerank_iters=4)
    with Session(g, cfg) as s:
        yield g, s


def test_session_batched_sssp_parity(served):
    g, s = served
    roots = [2, 9, 55, 120]
    futs = [s.submit("sssp", start=r) for r in roots]
    for f, r in zip(futs, roots):
        np.testing.assert_array_equal(
            f.result(60)["values"], reference_sssp(g, r)
        )


def test_session_serves_cached_fixpoints(served):
    g, s = served
    pr = s.query("pagerank", timeout=60)
    np.testing.assert_allclose(
        pr["values"], reference_pagerank(g, 4), rtol=1e-3, atol=1e-7
    )
    before = s.cache.stats()["hits"]
    s.query("pagerank", timeout=60)
    assert s.cache.stats()["hits"] == before + 1


def test_session_components(served):
    gd = generate.undirected(generate.gnp(200, 350, seed=205))
    with Session(gd, ServeConfig(max_batch=2, window_s=0.01)) as s2:
        out = s2.query("components", timeout=60)
        np.testing.assert_array_equal(
            out["values"], reference_components(gd)
        )


def test_session_rejects_bad_queries(served):
    _, s = served
    with pytest.raises(BadQueryError):
        s.submit("no_such_app")
    with pytest.raises(BadQueryError):
        s.submit("sssp")                       # missing start
    with pytest.raises(BadQueryError):
        s.submit("sssp", start=10**9)          # out of range
    with pytest.raises(BadQueryError):
        s.submit("pagerank", ni=0)


def test_session_no_rebuild_after_warmup(served):
    _, s = served
    misses = s.pool.stats()["misses"]
    s.query("sssp", start=33, timeout=60)
    s.query("pagerank", timeout=60)
    assert s.pool.stats()["misses"] == misses


def test_session_zero_recompiles_after_first_batch(served):
    # The serving claim, machine-checked at the XLA level (pool misses
    # only prove no executor was REBUILT): after a key's first batch,
    # repeat queries must reuse the warmed executable — the
    # RecompileSentinel sees zero compiles in every watch region.
    _, s = served
    sent = s.pool.sentinel
    if not sent.available:
        pytest.skip("jax monitoring hook unavailable in this jax")
    # First batch per engine key + batch shape (absorbed as warmup).
    s.query("sssp", start=7, timeout=60)
    for f in [s.submit("sssp", start=r) for r in (3, 4, 5, 6)]:
        f.result(60)
    s.query("pagerank", timeout=60)
    s.query("components", timeout=60)
    # Repeat traffic with cache-missing parameters so real engine work
    # runs (distinct roots, distinct pagerank depth).
    s.query("sssp", start=101, timeout=60)
    for f in [s.submit("sssp", start=r) for r in (102, 103, 104, 105)]:
        f.result(60)
    s.query("pagerank", ni=5, timeout=60)
    sent.assert_zero_recompiles()
    assert s.pool.stats()["recompiles"] == 0


# -- HTTP front end -------------------------------------------------------


def test_http_end_to_end():
    from lux_tpu.serve.http import serve_in_thread

    g = generate.gnp(200, 1200, seed=301)
    s = Session(g, ServeConfig(max_batch=2, window_s=0.01,
                               pagerank_iters=3))
    server, _ = serve_in_thread(s, port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        health = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert health["ok"] and health["nv"] == g.nv

        req = urllib.request.Request(
            base + "/query",
            json.dumps({"app": "sssp", "start": 5, "full": True}).encode(),
            {"Content-Type": "application/json"},
        )
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        np.testing.assert_array_equal(
            np.asarray(out["values"], np.uint32), reference_sssp(g, 5)
        )

        bad = urllib.request.Request(
            base + "/query", json.dumps({"app": "sssp"}).encode(),
            {"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400

        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=10).read())
        assert stats["pool"]["misses"] >= 1
    finally:
        server.shutdown()
        s.close()
