"""Multi-chip serving: mesh-keyed warm pool, sharded engines behind
Session, partition-plan cache, and hot-swap of a whole engine mesh.

The conftest forces 8 virtual CPU devices, so a 2x4 (or 8-way) serving
mesh is real sharded execution — the same collectives as TPU, minus the
wires.
"""

import threading

import numpy as np
import pytest

from lux_tpu.graph import DeltaGraph, EdgeEdits, generate
from lux_tpu.models.sssp import SSSP, reference_sssp
from lux_tpu.obs import metrics
from lux_tpu.serve import ServeConfig, Session
from lux_tpu.serve.mesh import (MeshSpec, ShardPlanCache, parse_mesh_spec,
                                serving_mesh)
from lux_tpu.serve.pool import EnginePool


def _cfg(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("window_s", 0.01)
    kw.setdefault("max_queue", 64)
    kw.setdefault("pagerank_iters", 4)
    return ServeConfig(**kw)


def _edits(g, seed, n):
    rng = np.random.default_rng(seed)
    ins = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
           for _ in range(n)]
    eidx = rng.choice(g.ne, size=n, replace=False)
    dels = [(int(g.col_src[e]), int(g.col_dst[e])) for e in eidx]
    return EdgeEdits.from_lists(insert=ins, delete=dels)


# -- mesh spec parsing / resolution -------------------------------------


def test_parse_mesh_spec():
    assert parse_mesh_spec("8") == (8,)
    assert parse_mesh_spec("2x4") == (2, 4)
    assert parse_mesh_spec("1") == (1,)
    assert parse_mesh_spec(" 4 x 2 ") == (4, 2)
    assert parse_mesh_spec(4) == (4,)


@pytest.mark.parametrize("bad", ["", "0", "2x0", "-4", "axb", "2x", None])
def test_parse_mesh_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_mesh_spec(bad)


def test_serving_mesh_resolves_flag(monkeypatch):
    monkeypatch.setenv("LUX_SERVE_MESH", "2x4")
    ms = serving_mesh()
    assert isinstance(ms, MeshSpec)
    assert ms.shape == (2, 4) and ms.num_parts == 8
    assert ms.mesh is not None


def test_serving_mesh_single_chip_has_no_mesh():
    ms = serving_mesh("1")
    assert ms.num_parts == 1 and ms.mesh is None


def test_serving_mesh_rejects_oversubscription(monkeypatch):
    # conftest pins 8 virtual devices; 64 parts cannot be satisfied.
    # (The bootstrap widens XLA_FLAGS before it can check — restore it.)
    import os

    monkeypatch.setenv("XLA_FLAGS", os.environ.get("XLA_FLAGS", ""))
    with pytest.raises(ValueError, match="devices"):
        serving_mesh("64")


# -- partition-plan cache ------------------------------------------------


def test_plan_cache_shares_one_build_per_fingerprint():
    metrics.reset()   # counters are registry-shared; fresh cache, fresh counts
    g = generate.gnp(200, 1200, seed=7)
    pc = ShardPlanCache()
    a = pc.get("fp0", g, 4)
    b = pc.get("fp0", g, 4)
    assert a is b and len(pc) == 1
    # A different parts count is a different plan.
    c = pc.get("fp0", g, 2)
    assert c is not a and len(pc) == 2
    st = pc.stats()
    assert st["hits"] == 1 and st["misses"] == 2


def test_plan_cache_rebuilds_on_graph_identity_change():
    g1 = generate.gnp(200, 1200, seed=7)
    g2 = generate.gnp(200, 1200, seed=7)   # equal content, new object
    pc = ShardPlanCache()
    a = pc.get("fp0", g1, 4)
    b = pc.get("fp0", g2, 4)   # same key, different Graph object
    assert b is not a and b.graph is g2


def test_plan_cache_evict_fingerprint():
    g = generate.gnp(150, 800, seed=8)
    pc = ShardPlanCache()
    pc.get("old", g, 2)
    pc.get("old", g, 4)
    pc.get("new", g, 2)
    assert pc.evict_fingerprint("old") == 2
    assert len(pc) == 1
    assert pc.evict_fingerprint("gone") == 0


def test_plan_cache_lru_bound(monkeypatch):
    metrics.reset()
    monkeypatch.setenv("LUX_SHARD_PLAN_CACHE", "2")
    g = generate.gnp(150, 800, seed=8)
    pc = ShardPlanCache()
    pc.get("a", g, 2)
    pc.get("b", g, 2)
    pc.get("c", g, 2)
    assert len(pc) == 2
    assert pc.stats()["evicted"] == 1


# -- sharded serving through Session ------------------------------------


def test_sharded_session_parity_and_mesh_keys():
    metrics.reset()
    g = generate.gnp(300, 2000, seed=411, weighted=True)
    with Session(g, _cfg(mesh="1"), warm=False) as s1, \
            Session(g, _cfg(mesh="2x4"), warm=False) as s8:
        assert s8.meshspec.num_parts == 8
        # SSSP + components bitwise; pagerank float-order tolerant.
        for r in (0, 7, 133):
            a = s1.query("sssp", start=r, timeout=120)
            b = s8.query("sssp", start=r, timeout=120)
            np.testing.assert_array_equal(a["values"], b["values"])
            np.testing.assert_array_equal(b["values"],
                                          reference_sssp(g, r))
        np.testing.assert_array_equal(
            s1.query("components", timeout=120)["values"],
            s8.query("components", timeout=120)["values"])
        np.testing.assert_allclose(
            s1.query("pagerank", timeout=120)["values"],
            s8.query("pagerank", timeout=120)["values"],
            rtol=1e-5, atol=1e-8)
        # Every pool key carries its session's mesh shape.
        assert all(k[-1] == (2, 4) for k in s8.pool.keys())
        assert all(k[-1] == (1,) for k in s1.pool.keys())
        assert s8.stats()["pool"]["recompiles"] == 0
        assert s1.stats()["pool"]["recompiles"] == 0


def test_sharded_batched_lanes_parity():
    metrics.reset()
    g = generate.gnp(300, 2000, seed=412)
    roots = [2, 9, 55, 201]
    with Session(g, _cfg(mesh="8"), warm=False) as s:
        futs = [s.submit("sssp", start=r) for r in roots]
        for r, f in zip(roots, futs):
            np.testing.assert_array_equal(
                f.result(timeout=120)["values"], reference_sssp(g, r))
        # The batched lanes came off the sharded multi engine.
        assert any(k[0] == "push_multi" for k in s.pool.keys())
        assert s.stats()["pool"]["recompiles"] == 0


def test_sharded_warm_path_zero_recompiles():
    metrics.reset()
    g = generate.gnp(250, 1500, seed=413)
    with Session(g, _cfg(mesh="8"), warm=False) as s:
        for _ in range(3):
            s.query("sssp", start=1, timeout=120)
            s.query("components", timeout=120)
        st = s.stats()["pool"]
        assert st["recompiles"] == 0
        assert st["warmup_compiles"] > 0
        s.pool.sentinel.assert_zero_recompiles()


def test_sharded_hot_swap_retires_engine_mesh_under_load():
    metrics.reset()
    g = generate.gnp(300, 2000, seed=414)
    ed = _edits(g, 415, 12)
    new_g = DeltaGraph.fresh(g).stack(ed).merged()
    with Session(g, _cfg(mesh="2x4"), warm=False) as s:
        s.query("sssp", start=3, timeout=120)
        s.query("components", timeout=120)
        warmed = len(s.pool)
        errors, results = [], []

        def hammer():
            try:
                for r in (1, 4, 7):
                    results.append(
                        (r, s.query("sssp", start=r, timeout=120)))
            except Exception as e:   # any failure fails the test
                errors.append(repr(e))

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        summary = s.apply_edits(ed)
        for t in threads:
            t.join()
        assert not errors, errors
        assert summary["retired"] >= warmed
        assert summary["plans_evicted"] >= 1
        # Post-swap answers come from v1's sharded engines, bitwise.
        out = s.query("sssp", start=3, timeout=120)
        np.testing.assert_array_equal(out["values"],
                                      reference_sssp(new_g, 3))
        # In-flight answers were correct for whichever version ran them.
        for r, res in results:
            v = np.asarray(res["values"])
            ok = (np.array_equal(v, reference_sssp(g, r))
                  or np.array_equal(v, reference_sssp(new_g, r)))
            assert ok, f"root {r} matches neither version"
        assert s.stats()["pool"]["recompiles"] == 0


def test_pool_builds_once_under_concurrent_get_with_mesh_keys():
    metrics.reset()
    g = generate.gnp(200, 1200, seed=416)
    pool = EnginePool("test-mesh")
    built = []

    def factory():
        from lux_tpu.engine.push import PushExecutor

        built.append(1)
        return PushExecutor(g, SSSP())

    key = ("push", "fp", "sssp", 1, (2, 4))
    got = []
    threads = [
        threading.Thread(target=lambda: got.append(pool.get(key, factory)))
        for _ in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(built) == 1
    assert all(e is got[0] for e in got)
    assert pool.stats()["misses"] == 1 and pool.stats()["hits"] == 5
    pool.close()


def test_stats_and_statusz_report_mesh():
    metrics.reset()
    g = generate.gnp(200, 1200, seed=417)
    with Session(g, _cfg(mesh="2x4"), warm=False) as s:
        s.query("sssp", start=0, timeout=120)
        for doc in (s.stats(), s.statusz()):
            m = doc["mesh"]
            assert m["spec"] == "2x4"
            assert m["shape"] == [2, 4] and m["num_parts"] == 8
            assert m["pool_entries"].get("2x4", 0) >= 1
            assert m["plans"]["plans"] >= 1
        eb = s.mesh_exchange_bytes()
        assert set(eb) == {"sssp", "sssp_multi", "components",
                           "pagerank"}
        assert all(isinstance(v, int) and v > 0 for v in eb.values())


def test_single_chip_session_mesh_block_is_inert():
    metrics.reset()
    g = generate.gnp(150, 800, seed=418)
    with Session(g, _cfg(mesh="1"), warm=False) as s:
        s.query("sssp", start=0, timeout=120)
        m = s.stats()["mesh"]
        assert m["num_parts"] == 1
        assert s.mesh_exchange_bytes() == {}


# -- sharded multi-source executor directly ------------------------------


def test_sharded_multi_source_parity_weighted():
    from lux_tpu.engine.push import (MultiSourcePushExecutor,
                                     ShardedMultiSourcePushExecutor)

    g = generate.gnp(300, 2200, seed=419, weighted=True)
    roots = [0, 3, 77, 201]
    ref = MultiSourcePushExecutor(g, SSSP(), k=4)
    rstate, riters = ref.run(roots)
    ex = ShardedMultiSourcePushExecutor(g, SSSP(), k=4, num_parts=8)
    state, iters = ex.run(roots)
    assert int(iters) == int(riters)
    allv = ex.gather_values(state)
    assert allv.shape == (g.nv, 4)
    for j, r in enumerate(roots):
        np.testing.assert_array_equal(allv[:, j], ref.values_for(rstate, j))
        np.testing.assert_array_equal(ex.values_for(state, j),
                                      reference_sssp(g, r))


def test_sharded_multi_source_pads_short_batches():
    from lux_tpu.engine.push import ShardedMultiSourcePushExecutor

    g = generate.gnp(250, 1500, seed=420)
    ex = ShardedMultiSourcePushExecutor(g, SSSP(), k=4, num_parts=4)
    state, _ = ex.run([7])   # right-pads by repeating the last root
    np.testing.assert_array_equal(ex.values_for(state, 0),
                                  reference_sssp(g, 7))


def test_sharded_multi_source_rejects_bad_widths():
    from lux_tpu.engine.push import ShardedMultiSourcePushExecutor

    g = generate.gnp(100, 500, seed=421)
    with pytest.raises(ValueError):
        ShardedMultiSourcePushExecutor(g, SSSP(), k=0, num_parts=2)
    ex = ShardedMultiSourcePushExecutor(g, SSSP(), k=2, num_parts=2)
    with pytest.raises(ValueError):
        ex.init_state([])
    with pytest.raises(ValueError):
        ex.init_state([1, 2, 3])


def test_sharded_executors_accept_prebuilt_plan():
    from lux_tpu.engine.push import (ShardedMultiSourcePushExecutor,
                                     ShardedPushExecutor)
    from lux_tpu.parallel.shard import ShardedGraph

    g = generate.gnp(200, 1200, seed=422)
    sg = ShardedGraph.build(g, 4)
    a = ShardedPushExecutor(g, SSSP(), num_parts=4, sg=sg)
    b = ShardedMultiSourcePushExecutor(g, SSSP(), k=2, num_parts=4, sg=sg)
    assert a.sg is sg and b.sg is sg
    with pytest.raises(ValueError):
        ShardedPushExecutor(g, SSSP(), num_parts=2, sg=sg)
    g2 = generate.gnp(200, 1200, seed=422)
    with pytest.raises(ValueError):
        ShardedPushExecutor(g2, SSSP(), num_parts=4, sg=sg)
