"""Sharded pull engine: parity vs single-device on an 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from lux_tpu.engine.pull import PullExecutor
from lux_tpu.engine.pull_sharded import ShardedPullExecutor
from lux_tpu.graph import generate
from lux_tpu.models.pagerank import PageRank, reference_pagerank
from lux_tpu.parallel.mesh import make_mesh
from lux_tpu.parallel.shard import ShardedGraph


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_graph_layout():
    g = generate.gnp(300, 2400, seed=5)
    sg = ShardedGraph.build(g, 4)
    # Round-trip values through the padded layout.
    vals = np.arange(g.nv, dtype=np.float32)
    np.testing.assert_array_equal(sg.from_padded(sg.to_padded(vals)), vals)
    # Every real edge accounted for exactly once.
    assert int(sg.edge_mask.sum()) == g.ne
    # src_pidx decodes back to the global source id.
    for p in range(4):
        m = sg.edge_mask[p]
        pidx = sg.src_pidx[p][m]
        part = pidx // sg.max_nv
        local = pidx % sg.max_nv
        glob = sg.row_left[part] + local
        np.testing.assert_array_equal(glob, sg.src_global[p][m])


@pytest.mark.parametrize("parts", [2, 8])
@pytest.mark.parametrize("strategy", ["rowptr", "segment"])
def test_sharded_pagerank_parity(parts, strategy):
    g = generate.gnp(500, 4000, seed=7)
    mesh = make_mesh(parts)
    ex = ShardedPullExecutor(g, PageRank(), mesh=mesh, sum_strategy=strategy)
    got = ex.gather_values(ex.run(10))
    want = reference_pagerank(g, 10)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-9)


def test_sharded_matches_single_device_exactly_structured():
    g = generate.rmat(9, 8, seed=2)
    single = np.asarray(PullExecutor(g, PageRank()).run(6))
    ex = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(8))
    sharded = ex.gather_values(ex.run(6))
    # rowptr cumsum order differs between global and per-shard prefix sums;
    # only reassociation-level differences are acceptable.
    np.testing.assert_allclose(sharded, single, rtol=1e-5, atol=1e-10)


def test_sharded_skewed_graph_with_empty_parts():
    # Star graph: nearly all edges into part 0; later parts nearly empty.
    g = generate.undirected(generate.star_graph(40))
    ex = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(8))
    got = ex.gather_values(ex.run(5))
    want = reference_pagerank(g, 5)
    np.testing.assert_allclose(got, want, rtol=2e-5)
