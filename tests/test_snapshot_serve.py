"""Serving hot-swap: apply_edits drains version N while N+1 warms,
invalidates caches by fingerprint, refreshes fixpoints incrementally,
and never fails an in-flight query or recompiles a warmed engine."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from lux_tpu.graph import DeltaGraph, EdgeEdits, generate
from lux_tpu.models.sssp import reference_sssp
from lux_tpu.obs import metrics
from lux_tpu.serve import (BadQueryError, ServeConfig, Session,
                           SnapshotSwapError)


def _cfg(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("window_s", 0.01)
    kw.setdefault("max_queue", 64)
    kw.setdefault("pagerank_iters", 4)
    return ServeConfig(**kw)


def _edits(g, seed, n):
    rng = np.random.default_rng(seed)
    ins = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
           for _ in range(n)]
    eidx = rng.choice(g.ne, size=n, replace=False)
    dels = [(int(g.col_src[e]), int(g.col_dst[e])) for e in eidx]
    return EdgeEdits.from_lists(insert=ins, delete=dels)


def test_apply_edits_flips_version_and_serves_new_graph():
    metrics.reset()
    g = generate.gnp(300, 2000, seed=401)
    with Session(g, _cfg()) as s:
        assert s.version == 0
        base_fp = s.fingerprint
        s.query("sssp", start=3, timeout=60)
        ed = _edits(g, 402, 15)
        summary = s.apply_edits(ed)
        assert (s.version, summary["version"]) == (1, 1)
        assert summary["old_fingerprint"] == base_fp
        assert s.fingerprint == summary["fingerprint"] != base_fp
        new_g = DeltaGraph.fresh(g).stack(ed).merged()
        assert s.graph.ne == new_g.ne == summary["ne"]
        out = s.query("sssp", start=3, timeout=60)
        np.testing.assert_array_equal(out["values"],
                                      reference_sssp(new_g, 3))
        info = s.snapshot_info()
        assert info["version"] == 1
        assert [h["version"] for h in info["history"]] == [0, 1]
        assert s.stats()["snapshot"]["version"] == 1
        assert metrics.counter("lux_snapshot_applies_total").value == 1


def test_swap_evicts_old_cache_and_retires_old_engines():
    metrics.reset()
    g = generate.gnp(300, 2000, seed=403)
    with Session(g, _cfg()) as s:
        old_fp = s.fingerprint
        s.query("sssp", start=1, timeout=60)
        s.query("components", timeout=60)
        s.query("pagerank", timeout=60)
        engines_before = s.pool.stats()["engines"]
        summary = s.apply_edits(_edits(g, 404, 10))
        assert summary["evicted"] >= 3   # sssp + components + pagerank
        assert summary["retired"] == engines_before  # all v0 engines
        assert not any(
            isinstance(k, tuple) and len(k) > 1 and k[0] == old_fp
            for k in s.cache.keys()
        )
        assert s.pool.stats()["retired"] == engines_before
        assert s.cache.stats()["invalidations"] == summary["evicted"]


def test_incremental_refresh_keeps_fixpoints_warm_and_correct():
    """With LUX_INCREMENTAL the swap re-populates cached SSSP/components
    under the new fingerprint from warm starts — served answers right
    after the swap are cache hits AND bitwise-correct."""
    metrics.reset()
    g = generate.gnp(300, 2000, seed=405)
    with Session(g, _cfg()) as s:
        roots = [2, 9, 55]
        for r in roots:
            s.query("sssp", start=r, timeout=60)
        s.query("components", timeout=60)
        ed = _edits(g, 406, 10)
        summary = s.apply_edits(ed)
        assert summary["refreshed"]["sssp"] == len(roots)
        assert summary["refreshed"]["components"] == 1
        new_g = DeltaGraph.fresh(g).stack(ed).merged()
        hits_before = s.cache.stats()["hits"]
        for r in roots:
            out = s.query("sssp", start=r, timeout=60)
            assert out.get("incremental") is True
            np.testing.assert_array_equal(out["values"],
                                          reference_sssp(new_g, r))
        assert s.cache.stats()["hits"] == hits_before + len(roots)


def test_lux_incremental_off_is_evict_only(monkeypatch):
    monkeypatch.setenv("LUX_INCREMENTAL", "0")
    metrics.reset()
    g = generate.gnp(200, 1200, seed=407)
    with Session(g, _cfg()) as s:
        s.query("sssp", start=5, timeout=60)
        summary = s.apply_edits(_edits(g, 408, 5))
        assert summary["refreshed"] is None
        assert summary["evicted"] >= 1
        # Recompute-on-demand still correct.
        new_g = s.graph
        out = s.query("sssp", start=5, timeout=60)
        np.testing.assert_array_equal(out["values"],
                                      reference_sssp(new_g, 5))


def test_warm_timeout_aborts_swap_and_old_version_keeps_serving(
        monkeypatch):
    metrics.reset()
    g = generate.gnp(200, 1200, seed=409)
    with Session(g, _cfg()) as s:
        fp0 = s.fingerprint
        stall = threading.Event()
        real_warmup = s.warmup

        def slow_warmup(snap=None):
            if snap is not None and snap.version > 0:
                stall.wait(5)   # longer than warm_timeout below
            return real_warmup(snap)

        monkeypatch.setattr(s, "warmup", slow_warmup)
        with pytest.raises(SnapshotSwapError, match="still serving"):
            s.apply_edits(_edits(g, 410, 5), warm_timeout=0.05)
        stall.set()
        assert s.version == 0 and s.fingerprint == fp0
        assert metrics.counter("lux_snapshot_aborts_total").value == 1
        out = s.query("sssp", start=2, timeout=60)   # v0 still serves
        np.testing.assert_array_equal(out["values"], reference_sssp(g, 2))


def test_in_flight_queries_survive_swap_zero_recompiles():
    """Queries admitted before/during the swap all succeed (each bound to
    exactly one snapshot), and the warmed engines never recompile —
    the zero-recompile serving contract holds across hot-swaps."""
    metrics.reset()
    g = generate.gnp(300, 2000, seed=411)
    with Session(g, _cfg(window_s=0.05)) as s:
        sent = s.pool.sentinel
        # Absorb per-key first-batch compiles before the measured phase.
        s.query("sssp", start=0, timeout=60)
        for f in [s.submit("sssp", start=r) for r in (1, 2, 3, 4)]:
            f.result(60)
        ed = _edits(g, 412, 10)
        new_g = DeltaGraph.fresh(g).stack(ed).merged()

        errors, results = [], {}
        stop = threading.Event()

        def pound():
            i = 0
            while not stop.is_set():
                r = i % 40
                try:
                    out = s.query("sssp", start=r, timeout=60)
                    results[r] = (s.version if "incremental" not in out
                                  else None, out)
                except Exception as e:   # any failure fails the test
                    errors.append(e)
                i += 1

        threads = [threading.Thread(target=pound) for _ in range(3)]
        for t in threads:
            t.start()
        summary = s.apply_edits(ed)
        # Post-swap traffic lands on v1 with the same executables.
        for f in [s.submit("sssp", start=r) for r in (5, 6, 7, 8)]:
            f.result(60)
        stop.set()
        for t in threads:
            t.join(30)
        assert not errors, errors
        assert summary["version"] == 1
        out = s.query("sssp", start=9, timeout=60)
        np.testing.assert_array_equal(out["values"],
                                      reference_sssp(new_g, 9))
        if sent.available:
            sent.assert_zero_recompiles()
        assert s.pool.stats()["recompiles"] == 0


def test_apply_edits_validates_input():
    g = generate.gnp(100, 500, seed=413)
    with Session(g, _cfg()) as s:
        with pytest.raises(BadQueryError, match="EdgeEdits"):
            s.apply_edits([(0, 1)])
        with pytest.raises(BadQueryError, match="vertex ids outside"):
            s.apply_edits(EdgeEdits.from_lists(insert=[(0, g.nv)]))
        assert s.version == 0


# -- HTTP front end -------------------------------------------------------


def _post(base, path, payload, timeout=60):
    req = urllib.request.Request(
        base + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    resp = urllib.request.urlopen(req, timeout=timeout)
    return json.loads(resp.read()), dict(resp.headers)


def test_http_snapshot_endpoints_and_header():
    from lux_tpu.serve.http import serve_in_thread

    g = generate.gnp(200, 1200, seed=415)
    s = Session(g, _cfg(max_batch=2))
    server, _ = serve_in_thread(s, port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        out, hdr = _post(base, "/query",
                         {"app": "sssp", "start": 5, "full": True})
        assert hdr["X-Lux-Snapshot"] == "0"
        np.testing.assert_array_equal(
            np.asarray(out["values"], np.uint32), reference_sssp(g, 5))

        resp = urllib.request.urlopen(base + "/snapshot", timeout=10)
        info = json.loads(resp.read())
        assert info["version"] == 0 and info["ne"] == g.ne

        rng = np.random.default_rng(416)
        ins = [[int(rng.integers(g.nv)), int(rng.integers(g.nv))]
               for _ in range(8)]
        dels = [[int(g.col_src[e]), int(g.col_dst[e])]
                for e in rng.choice(g.ne, size=8, replace=False)]
        summary, hdr = _post(base, "/snapshot",
                             {"insert": ins, "delete": dels})
        assert summary["version"] == 1
        assert hdr["X-Lux-Snapshot"] == "1"

        new_g = DeltaGraph.fresh(g).stack(
            EdgeEdits.from_lists(
                insert=[tuple(p) for p in ins],
                delete=[tuple(p) for p in dels])).merged()
        out, hdr = _post(base, "/query",
                         {"app": "sssp", "start": 5, "full": True})
        assert hdr["X-Lux-Snapshot"] == "1"
        np.testing.assert_array_equal(
            np.asarray(out["values"], np.uint32),
            reference_sssp(new_g, 5))

        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, "/snapshot", {"insert": [[0, g.nv + 7]]})
        assert ei.value.code == 400
        assert json.loads(urllib.request.urlopen(
            base + "/snapshot", timeout=10).read())["version"] == 1
    finally:
        server.shutdown()
        s.close()
