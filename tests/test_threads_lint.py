"""luxlint-threads: the concurrency tier (LUX301-305), the annotation
conventions, the CLI --threads contract, and the LockWatch runtime
sentinel (lux_tpu/utils/locks.py).

Fixture convention mirrors test_analysis.py: `bad_*` files under
tests/lint_fixtures/threads/ carry `# expect: LUX3NN` markers on exactly
the lines a finding must anchor to; `good_*` files must lint clean.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time

import pytest

from lux_tpu.analysis.core import run_source
from lux_tpu.analysis.threads import (all_thread_rules, build_lock_graph,
                                      run_threads)
from lux_tpu.obs import metrics
from lux_tpu.utils import locks

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
FIXTURES = os.path.join(TESTS, "lint_fixtures", "threads")
LUXLINT = os.path.join(REPO, "tools", "luxlint.py")

_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z0-9_,\s]+?)\s*$")

BAD_FIXTURES = (
    "bad_shared_state.py",
    "bad_lock_order.py",
    "bad_blocking_under_lock.py",
    "bad_unjoined_thread.py",
    "bad_publish.py",
)
GOOD_FIXTURES = (
    "good_shared_state.py",
    "good_lock_order.py",
    "good_blocking_under_lock.py",
    "good_unjoined_thread.py",
    "good_publish.py",
)
# bad fixture -> the one rule it seeds
RULE_OF = {
    "bad_shared_state.py": "LUX301",
    "bad_lock_order.py": "LUX302",
    "bad_blocking_under_lock.py": "LUX303",
    "bad_unjoined_thread.py": "LUX304",
    "bad_publish.py": "LUX305",
}


def _expected(path):
    want = {}
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            m = _EXPECT_RE.search(line)
            if m:
                want[i] = sorted(
                    s.strip() for s in m.group(1).split(",") if s.strip()
                )
    return want


def _lint_threads(path):
    report = run_threads([path], graph_paths=[path])
    (res,) = report.results
    return res


def _by_line(findings):
    out = {}
    for f in findings:
        out.setdefault(f.line, []).append(f.rule)
    return {k: sorted(v) for k, v in out.items()}


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, LUXLINT, *args],
        capture_output=True, text=True, cwd=REPO,
    )


def _summary_line(stdout):
    lines = [l for l in stdout.splitlines() if l.startswith("LUXLINT ")]
    assert lines, stdout
    return json.loads(lines[-1][len("LUXLINT "):])


# -- rules vs fixtures ----------------------------------------------------


@pytest.mark.parametrize("rel", BAD_FIXTURES)
def test_bad_fixture_fires_exactly_where_expected(rel):
    path = os.path.join(FIXTURES, rel)
    res = _lint_threads(path)
    assert res.error is None
    want = _expected(path)
    assert want, f"{rel} has no expect markers"
    assert _by_line(res.findings) == want
    assert {f.rule for f in res.findings} == {RULE_OF[rel]}


@pytest.mark.parametrize("rel", GOOD_FIXTURES)
def test_good_fixture_is_clean(rel):
    res = _lint_threads(os.path.join(FIXTURES, rel))
    assert res.error is None
    assert res.findings == []


# -- LUX301 semantics -----------------------------------------------------


_WORKER_TMPL = """
import threading


class W:
    def __init__(self):
        self.n = 0{decl}
        self._lock = threading.Lock()
        self._aux_lock = threading.Lock()
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        with self.{guard}:
            self.n += 1

    def read(self):
        with self._lock:
            return self.n

    def close(self):
        self._t.join(1.0)
"""


def _lint_source(src):
    return run_source(src, "t.py", all_thread_rules())


def test_guarded_by_declaration_requires_that_specific_lock():
    # Declared guarded-by=_lock: guarding with a *different* lock is
    # still a finding; guarding with the declared one is clean.
    decl = "            # luxlint: guarded-by=_lock"
    src = _WORKER_TMPL.format(decl="  # luxlint: guarded-by=_lock",
                              guard="_aux_lock")
    res = _lint_source(src)
    assert [f.rule for f in res.findings] == ["LUX301"], (decl, res.findings)
    src = _WORKER_TMPL.format(decl="  # luxlint: guarded-by=_lock",
                              guard="_lock")
    assert _lint_source(src).findings == []


def test_any_lock_suffices_without_a_declaration():
    src = _WORKER_TMPL.format(decl="", guard="_aux_lock")
    assert _lint_source(src).findings == []


def test_sync_primitive_attrs_are_exempt():
    src = """
import queue
import threading


class W:
    def __init__(self):
        self.q = queue.Queue()
        self.done = threading.Event()
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        self.q.put(1)
        self.done.set()

    def close(self):
        self.done.wait(1.0)
        self._t.join(1.0)
"""
    assert _lint_source(src).findings == []


def test_suppression_counts_not_silent():
    src = """
import threading


class W:
    def __init__(self):
        self.n = 0
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        # luxlint: disable=LUX301 -- single-writer by construction
        self.n += 1

    def read(self):
        return self.n  # luxlint: disable=LUX301 -- approximate stat read

    def close(self):
        self._t.join(1.0)
"""
    res = _lint_source(src)
    assert res.findings == []
    assert len(res.suppressed) == 2


def test_worker_registration_counts_as_thread_entry():
    # The MicroBatcher shape: a method reference handed to a
    # *batcher/worker* consumer runs on that consumer's thread.
    src = """
class S:
    def __init__(self, batcher_cls):
        self.hits = 0
        self.batcher = batcher_cls(self._execute)

    def _execute(self, batch):
        self.hits += 1

    def stats(self):
        return self.hits
"""
    res = _lint_source(src)
    assert {f.rule for f in res.findings} == {"LUX301"}
    assert len(res.findings) == 2


# -- LUX302 cross-file graph ----------------------------------------------


def test_lock_order_cycle_across_files(tmp_path):
    (tmp_path / "m1.py").write_text(
        "import threading\n"
        "a_lock = threading.Lock()\n"
        "b_lock = threading.Lock()\n\n\n"
        "def fwd():\n"
        "    with a_lock:\n"
        "        with b_lock:\n"
        "            pass\n"
    )
    m2 = tmp_path / "m2.py"
    m2.write_text(
        "import m1\n\n\n"
        "def bwd():\n"
        "    with m1.b_lock:\n"
        "        with m1.a_lock:\n"
        "            pass\n"
    )
    # Lint only m2 (the --changed shape) with the graph built over the
    # whole tree: the inversion against m1's order must still fire.
    report = run_threads([str(m2)], graph_paths=[str(tmp_path)])
    (res,) = report.results
    assert [f.rule for f in res.findings] == ["LUX302"]
    assert "m1.a_lock" in res.findings[0].message


def test_lock_graph_consistent_order_has_no_cycles(tmp_path):
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "a_lock = threading.Lock()\n"
        "b_lock = threading.Lock()\n\n\n"
        "def f():\n"
        "    with a_lock:\n"
        "        with b_lock:\n"
        "            pass\n\n\n"
        "def g():\n"
        "    with a_lock, b_lock:\n"
        "        pass\n"
    )
    assert build_lock_graph([str(tmp_path)]) == {}


# -- CLI contract ---------------------------------------------------------


def test_cli_threads_full_tree_is_green():
    # The gate `make lint-threads` runs: the shipped tree must lint
    # clean under all five LUX30x rules, intentional exceptions
    # suppressed with reasons and *counted*.
    proc = _run_cli("--threads")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    s = _summary_line(proc.stdout)
    assert s["schema"] == "luxlint-threads.v1"
    assert s["ok"] is True and s["findings"] == 0 and s["errors"] == 0
    assert s["files"] > 50
    assert s["suppressed"] >= 5    # pool warmup + session _served_keys


@pytest.mark.parametrize("rel", BAD_FIXTURES)
def test_cli_threads_rc1_on_each_seeded_fixture(rel):
    proc = _run_cli("--threads", "--json",
                    os.path.join("tests", "lint_fixtures", "threads", rel))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    s = _summary_line(proc.stdout)
    assert s["schema"] == "luxlint-threads.v1" and s["ok"] is False
    assert set(s["by_rule"]) == {RULE_OF[rel]}
    payload = json.loads(proc.stdout[:proc.stdout.rfind("LUXLINT ")])
    assert payload["summary"]["schema"] == "luxlint-threads.v1"
    assert all(f["rule"] == RULE_OF[rel] for f in payload["findings"])


def test_cli_list_rules_includes_threads_tier():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rid in ("LUX301", "LUX302", "LUX303", "LUX304", "LUX305"):
        assert rid in proc.stdout


def test_cli_threads_baseline_ratchet(tmp_path):
    fix = os.path.join("tests", "lint_fixtures", "threads", "bad_publish.py")
    base = str(tmp_path / "threads_baseline.json")
    p1 = _run_cli("--threads", fix, "--baseline", base)
    assert p1.returncode == 0 and "baseline written" in p1.stdout
    keys = json.load(open(base))["keys"]
    assert keys and all(k.startswith("LUX305\t") for k in keys)
    # Same findings again: ratchet holds.
    p2 = _run_cli("--threads", fix, "--baseline", base)
    assert p2.returncode == 0, p2.stdout
    # A finding outside the snapshot is new -> fail.
    p3 = _run_cli("--threads", fix,
                  os.path.join("tests", "lint_fixtures", "threads",
                               "bad_shared_state.py"),
                  "--baseline", base)
    assert p3.returncode == 1 and "[new]" in p3.stdout


# -- LockWatch runtime sentinel -------------------------------------------


def test_make_lock_inert_without_flag(monkeypatch):
    monkeypatch.delenv("LUX_LOCKWATCH", raising=False)
    lk = locks.make_lock("tw.inert")
    assert isinstance(lk, type(threading.Lock()))
    assert not isinstance(lk, locks.WatchedLock)


def test_make_lock_watched_under_flag(monkeypatch):
    monkeypatch.setenv("LUX_LOCKWATCH", "1")
    lk = locks.make_lock("tw.watched")
    assert isinstance(lk, locks.WatchedLock)
    with lk:
        assert lk.locked()
    assert not lk.locked()


def test_lockwatch_detects_abba_inversion():
    watch = locks.LockWatch()
    a = locks.WatchedLock("tw.abba.a", watch=watch)
    b = locks.WatchedLock("tw.abba.b", watch=watch)
    with a:
        with b:
            pass
    assert watch.inversions() == []
    with b:
        with a:
            pass
    inv = watch.inversions()
    assert len(inv) == 1
    assert set(inv[0]["cycle"]) == {"tw.abba.a", "tw.abba.b"}
    assert inv[0]["stack"] and inv[0]["prior_stack"]
    with pytest.raises(AssertionError, match="inversion"):
        watch.assert_no_inversions()
    # The same pair never double-reports.
    with b:
        with a:
            pass
    assert len(watch.inversions()) == 1


def test_lockwatch_consistent_order_is_clean():
    watch = locks.LockWatch()
    a = locks.WatchedLock("tw.ok.a", watch=watch)
    b = locks.WatchedLock("tw.ok.b", watch=watch)
    for _ in range(3):
        with a:
            with b:
                pass
    watch.assert_no_inversions()
    st = watch.stats()
    assert st["inversions"] == 0
    assert st["order"] == {"tw.ok.a": ["tw.ok.b"]}
    watch.reset()
    assert watch.stats()["edges"] == 0


def test_lockwatch_hold_and_wait_histograms():
    lk = locks.WatchedLock("tw.hist", watch=locks.LockWatch())
    with lk:
        time.sleep(0.002)
    q = locks.hold_quantile("tw.hist", 0.99)
    assert q is not None and q > 0
    wait_h = metrics.histogram("lux_lock_wait_seconds", {"lock": "tw.hist"},
                               buckets=locks.LOCK_BUCKETS)
    assert wait_h.count >= 1
    assert locks.hold_quantile("tw.never-used", 0.99) is None


def test_lockwatch_hold_warning_counter(monkeypatch):
    monkeypatch.setenv("LUX_LOCK_HOLD_WARN_MS", "1")
    lk = locks.WatchedLock("tw.warn", watch=locks.LockWatch())
    with lk:
        time.sleep(0.01)
    c = metrics.counter("lux_lock_hold_warnings_total", {"lock": "tw.warn"})
    assert c.value >= 1
    monkeypatch.setenv("LUX_LOCK_HOLD_WARN_MS", "0")   # 0 disables
    before = c.value
    with lk:
        time.sleep(0.01)
    assert c.value == before
