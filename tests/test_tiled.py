"""Hybrid SpMV executor: plan exactness + PageRank parity."""

import numpy as np
import pytest

from lux_tpu.engine.tiled import TiledPullExecutor
from lux_tpu.graph import generate
from lux_tpu.graph.graph import Graph
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.pagerank import PageRank, reference_pagerank
from lux_tpu.ops.tiled_spmv import BLOCK, plan_hybrid


def edge_multiset(s, d):
    return sorted(zip(np.asarray(s).tolist(), np.asarray(d).tolist()))


def plan_edge_multiset(plan):
    """Reconstruct the (internal-id) edge multiset a plan represents."""
    edges = []
    for lev in plan.levels:
        if lev.strips.shape[0] == 0:
            continue
        t = lev.strips.astype(np.int64)
        slots, cells = np.nonzero(t.reshape(t.shape[0], -1))
        for slot, cell in zip(slots, cells):
            d = lev.rows[slot] * lev.r + cell // BLOCK
            s = lev.cols[slot] * BLOCK + (cell % BLOCK)
            edges += [(int(s), int(d))] * int(t[slot].reshape(-1)[cell])
    tail_d = np.repeat(
        np.arange(plan.nv), np.diff(plan.tail_row_ptr).astype(np.int64)
    )
    tail_s = plan.tail_sb.astype(np.int64) * BLOCK + plan.tail_lane.astype(
        np.int64
    )
    edges += list(zip(tail_s.tolist(), tail_d.tolist()))
    return sorted(edges)


@pytest.mark.parametrize(
    "levels", [((8, 1),), ((8, 4),), ((128, 4), (8, 2)), ((32, 2),)]
)
def test_plan_is_exact_partition(levels):
    g = generate.rmat(9, 8, seed=3)
    plan = plan_hybrid(g, levels=levels)
    s_int = plan.rank[g.col_src]
    d_int = plan.rank[g.col_dst]
    assert plan_edge_multiset(plan) == edge_multiset(s_int, d_int)


def test_plan_spills_count_overflow_exactly():
    # 300 parallel edges in one cell: count clips at the nibble cap (15),
    # the other 285 must reappear in the tail; with the legacy cap (127)
    # the clip point moves but the edge multiset is still exact.
    src = np.concatenate([np.full(300, 2), [0, 1, 3]])
    dst = np.concatenate([np.full(300, 5), [4, 4, 4]])
    g = Graph.from_edges(src, dst, nv=8)
    for cap in (15, 127):
        plan = plan_hybrid(g, levels=((8, 1),), cap=cap)
        s_int = plan.rank[g.col_src]
        d_int = plan.rank[g.col_dst]
        assert max(lev.strips.max() for lev in plan.levels) == cap
        assert plan_edge_multiset(plan) == edge_multiset(s_int, d_int)


def test_packed_strips_roundtrip_and_parity():
    # Nibble packing must be lossless and the packed executor must match
    # the plain engine bit-for-tolerance.
    from lux_tpu.ops.tiled_spmv import pack_strips

    rng = np.random.default_rng(0)
    st = rng.integers(0, 16, (5, 8, 128)).astype(np.int8)
    pk = pack_strips(st)
    assert pk.shape == (5, 4, 128) and pk.dtype == np.uint8
    np.testing.assert_array_equal(pk & 15, st[:, :4, :].astype(np.uint8))
    np.testing.assert_array_equal(pk >> 4, st[:, 4:, :].astype(np.uint8))

    from lux_tpu.engine.pull import PullExecutor

    g = generate.rmat(10, 16, seed=4)
    tex = TiledPullExecutor(
        g, PageRank(), levels=((8, 1),), chunk_tail=64, pack=True
    )
    assert any(l.packed for l in tex.dhybrid.levels)
    pex = PullExecutor(g, PageRank())
    np.testing.assert_allclose(
        np.asarray(tex.run(4)), np.asarray(pex.run(4)),
        rtol=5e-5, atol=1e-9,
    )


def test_sharded_packed_parity():
    from lux_tpu.engine.tiled_sharded import ShardedTiledExecutor
    from lux_tpu.parallel.mesh import make_mesh

    g = generate.rmat(10, 8, seed=6)
    ex = ShardedTiledExecutor(
        g, PageRank(), mesh=make_mesh(4), levels=((8, 1),),
        chunk_strips=16, chunk_tail=64, pack=True,
    )
    got = np.asarray(ex.gather_values(ex.run(5)))
    want = reference_pagerank(g, 5)
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def test_plan_rejects_unpackable_strip_heights():
    g = generate.rmat(9, 8, seed=3)
    for bad in (3, 48, 256):
        with pytest.raises(ValueError, match="strip height"):
            plan_hybrid(g, levels=((bad, 2),))


def test_plan_respects_budget_and_density_floor():
    g = generate.rmat(9, 8, seed=3)
    # budget_bytes counts DEVICE bytes: packed strips cost r*128/2 each.
    plan = plan_hybrid(g, levels=((8, 1),), budget_bytes=4 * 8 * BLOCK // 2)
    assert plan.num_strips <= 4
    legacy = plan_hybrid(
        g, levels=((8, 1),), budget_bytes=4 * 8 * BLOCK, cap=127
    )
    assert legacy.num_strips <= 4
    plan2 = plan_hybrid(g, levels=((8, 10**9),))
    assert plan2.num_strips == 0
    assert plan2.tail_sb.shape[0] == g.ne
    assert plan2.coverage == 0.0


@pytest.mark.parametrize(
    "levels",
    [((8, 1),), ((8, 4),), ((128, 8), (8, 2)),
     ((2, 2),), ((16, 2),), ((64, 2),), ((32, 4), (4, 2))],
)
def test_hybrid_pagerank_parity_rmat(levels):
    g = generate.rmat(10, 8, seed=1)
    ex = TiledPullExecutor(
        g, PageRank(), levels=levels, chunk_strips=16, chunk_tail=64
    )
    got = np.asarray(ex.run(10))
    want = reference_pagerank(g, 10)
    # Strip products are exact f32 (VPU mul-reduce); the per-row
    # cumsum-diff reductions reassociate, leaving f32-roundoff wiggle.
    # The lane-select tail is exact f32 selection.
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def test_hybrid_pagerank_parity_gnp():
    g = generate.gnp(500, 4000, seed=7)
    ex = TiledPullExecutor(
        g, PageRank(), levels=((8, 2),), chunk_strips=8, chunk_tail=128
    )
    got = np.asarray(ex.run(10))
    want = reference_pagerank(g, 10)
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def test_hybrid_all_tail_matches_plain_executor():
    from lux_tpu.engine.pull import PullExecutor

    g = generate.rmat(9, 8, seed=5)
    # min_count so high nothing tiles: pure lane-select path. Selection is
    # exact f32, but the per-destination sums run in degree-sorted edge
    # order, so f32 reassociation leaves ~1e-5 relative wiggle vs. the
    # plain executor's CSC-order sums.
    tex = TiledPullExecutor(g, PageRank(), levels=((8, 10**9),), chunk_tail=64)
    pex = PullExecutor(g, PageRank())
    a = np.asarray(tex.run(3))
    b = np.asarray(pex.run(3))
    np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-9)


def test_plan_save_load_roundtrip(tmp_path):
    from lux_tpu.ops.tiled_spmv import load_plan, save_plan

    g = generate.rmat(9, 8, seed=3)
    plan = plan_hybrid(g, levels=((128, 4), (8, 2)))
    path = str(tmp_path / "plan.npz")
    save_plan(path, plan)
    back = load_plan(path)
    assert back.nv == plan.nv and back.nvb == plan.nvb
    assert plan_edge_multiset(back) == plan_edge_multiset(plan)
    np.testing.assert_array_equal(back.order, plan.order)
    np.testing.assert_array_equal(back.tail_row_ptr, plan.tail_row_ptr)
    ex = TiledPullExecutor(g, PageRank(), plan=back, chunk_strips=16,
                           chunk_tail=64)
    got = np.asarray(ex.run(5))
    want = reference_pagerank(g, 5)
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def test_plan_legacy_npz_load(tmp_path):
    # Round-1 caches are single .npz files; load_plan keeps that reader
    # and get_cached_plan probes the legacy key before replanning.
    from lux_tpu.engine.tiled import get_cached_plan
    from lux_tpu.ops.tiled_spmv import load_plan

    g = generate.rmat(9, 8, seed=3)
    plan = plan_hybrid(g, levels=((8, 2),))
    legacy = str(tmp_path / "plan.npz")
    data = dict(
        nv=plan.nv, nvb=plan.nvb, order=plan.order, rank=plan.rank,
        nlevels=len(plan.levels),
        tail_sb=plan.tail_sb, tail_lane=plan.tail_lane,
        tail_row_ptr=plan.tail_row_ptr,
        out_degrees=plan.out_degrees, in_degrees=plan.in_degrees,
    )
    for i, lev in enumerate(plan.levels):
        data[f"lev{i}_r"] = lev.r
        data[f"lev{i}_strips"] = lev.strips
        data[f"lev{i}_rows"] = lev.rows
        data[f"lev{i}_cols"] = lev.cols
    np.savez(legacy, **data)
    back = load_plan(legacy)
    assert plan_edge_multiset(back) == plan_edge_multiset(plan)
    served = get_cached_plan(
        g, str(tmp_path / "plan.luxplan"), levels=((8, 2),), cap=127
    )
    np.testing.assert_array_equal(served.order, plan.order)
    np.testing.assert_array_equal(served.tail_sb, plan.tail_sb)


def test_plan_cache_detects_config_change(tmp_path):
    # Same r-cascade, different threshold or budget → replan, not serve
    # (current saves record levels_spec/budget_bytes; ADVICE r2).
    from lux_tpu.engine.tiled import get_cached_plan

    g = generate.rmat(9, 8, seed=3)
    path = str(tmp_path / "plan.luxplan")
    first = get_cached_plan(g, path, levels=((8, 2),), budget_bytes=1 << 20)
    assert first.levels_spec == ((8, 2),)
    served = get_cached_plan(g, path, levels=((8, 2),), budget_bytes=1 << 20)
    np.testing.assert_array_equal(served.tail_sb, first.tail_sb)
    rethr = get_cached_plan(g, path, levels=((8, 4),), budget_bytes=1 << 20)
    assert rethr.levels_spec == ((8, 4),)
    assert rethr.tail_sb.shape[0] > first.tail_sb.shape[0]
    rebud = get_cached_plan(g, path, levels=((8, 4),), budget_bytes=1 << 10)
    assert rebud.budget_bytes == 1 << 10
    assert rebud.num_strips < rethr.num_strips


def test_legacy_cap_served_unless_packing(tmp_path):
    # A cap-127 cache is fully servable when nibble packing is off (the
    # default); only a real packing request forces the replan (ADVICE r2
    # medium). The replan must land at the ORIGINAL .luxplan path.
    import os

    from lux_tpu.engine.tiled import get_cached_plan
    from lux_tpu.ops.tiled_spmv import plan_hybrid as ph, save_plan

    g = generate.rmat(9, 8, seed=3)
    legacy = str(tmp_path / "plan.npz")
    save_plan(legacy + ".dir", ph(g, levels=((8, 2),), cap=127))
    os.rename(legacy + ".dir", legacy)   # simulate a legacy-keyed cache
    path = str(tmp_path / "plan.luxplan")
    served = get_cached_plan(g, path, levels=((8, 2),), cap=15)
    assert served.cap == 127             # served, not replanned
    assert not os.path.exists(path)
    replanned = get_cached_plan(g, path, levels=((8, 2),), cap=15, pack=True)
    assert replanned.cap <= 15
    assert os.path.exists(path)          # saved under the .luxplan name


def test_explicit_pack_on_unpackable_plan_raises():
    from lux_tpu.ops.tiled_spmv import DeviceHybrid

    g = generate.rmat(9, 8, seed=3)
    plan = plan_hybrid(g, levels=((8, 2),), cap=127)
    with pytest.raises(ValueError, match="cap"):
        DeviceHybrid.build(plan, pack=True)


def test_hybrid_run_resumes_from_external_vals():
    g = generate.rmat(9, 8, seed=5)
    ex = TiledPullExecutor(g, PageRank(), levels=((8, 1),), chunk_tail=64)
    full = np.asarray(ex.run(6))
    half = ex.run(3)
    resumed = np.asarray(ex.run(3, vals=half))
    np.testing.assert_allclose(resumed, full, rtol=1e-6)


def test_hybrid_step_and_init_speak_external_order():
    # The public step()/init_values() surface must match PullExecutor's
    # (cli.py drives executors through them), despite the internal
    # degree-sorted layout.
    from lux_tpu.engine.pull import PullExecutor

    g = generate.rmat(9, 8, seed=11)
    tex = TiledPullExecutor(g, PageRank(), levels=((8, 1),), chunk_tail=64)
    pex = PullExecutor(g, PageRank())
    np.testing.assert_allclose(
        np.asarray(tex.init_values()), np.asarray(pex.init_values())
    )
    a = np.asarray(tex.step(tex.step(tex.init_values())))
    b = np.asarray(pex.step(pex.step(pex.init_values())))
    np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-9)


def test_hybrid_rejects_non_spmv_programs():
    g = generate.rmat(8, 8, seed=5)
    with pytest.raises(ValueError, match="identity|source value"):
        TiledPullExecutor(g, ConnectedComponents())


@pytest.mark.parametrize(
    "levels", [((8, 2),), ((8, 1),), ((128, 4), (8, 2)), ()]
)
def test_banded_plan_identical_to_direct(levels, monkeypatch):
    # The streamed (banded) level-0 counting path must produce a plan
    # byte-identical to the direct in-memory path — same strips, same
    # tail, same selection tie-breaks — on skewed, uniform, and
    # bipartite-weighted graphs.
    graphs = [
        generate.rmat(10, 8, seed=4),
        generate.gnp(700, 6000, seed=1),
        generate.bipartite_ratings(300, 24, 3000, seed=2),
    ]
    fields = (
        "order", "rank", "tail_sb", "tail_lane", "tail_row_ptr",
    )
    for g in graphs:
        monkeypatch.setenv("LUX_PLAN_BANDED", "0")
        direct = plan_hybrid(g, levels=levels, budget_bytes=64 << 10)
        monkeypatch.setenv("LUX_PLAN_BANDED", "1")
        banded = plan_hybrid(g, levels=levels, budget_bytes=64 << 10)
        for name in fields:
            np.testing.assert_array_equal(
                getattr(direct, name), getattr(banded, name), err_msg=name
            )
        assert len(direct.levels) == len(banded.levels)
        for ld, lb in zip(direct.levels, banded.levels):
            np.testing.assert_array_equal(ld.strips, lb.strips)
            np.testing.assert_array_equal(ld.rows, lb.rows)
            np.testing.assert_array_equal(ld.cols, lb.cols)


def test_banded_helpers_multichunk():
    # The streaming machinery (cross-chunk fill bookkeeping, band
    # batching) only engages above _PLAN_CHUNK edges in production;
    # drive the helpers directly with a tiny chunk so CI covers the
    # multi-chunk paths.
    from lux_tpu.ops.tiled_spmv import (
        _cover_banded, _relabel, _strip_counts_banded,
    )

    g = generate.rmat(10, 8, seed=6)
    _, rank = _relabel(g, "degree")
    r, nvb = 8, (g.nv + BLOCK - 1) // BLOCK
    big_u, big_c = _strip_counts_banded(g, rank, r, nvb, 2)
    small_u, small_c = _strip_counts_banded(g, rank, r, nvb, 2, chunk=64)
    np.testing.assert_array_equal(big_u, small_u)
    np.testing.assert_array_equal(big_c, small_c)

    chosen = np.sort(big_u[np.argsort(-big_c, kind="stable")][:32])
    out_big = _cover_banded(g, rank, chosen, r, nvb, r * BLOCK)
    out_small = _cover_banded(g, rank, chosen, r, nvb, r * BLOCK, chunk=64)
    for a, b in zip(out_big, out_small):
        np.testing.assert_array_equal(a, b)
