"""Sharded hybrid SpMV executor: partitioning + multi-device parity."""

import numpy as np
import pytest

from lux_tpu.engine.tiled_sharded import (
    ShardedTiledExecutor,
    partition_plan,
)
from lux_tpu.graph import generate
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.pagerank import PageRank, reference_pagerank
from lux_tpu.ops.tiled_spmv import BLOCK, plan_hybrid
from lux_tpu.parallel.mesh import make_mesh


def test_partition_plan_covers_blocks_disjointly():
    g = generate.rmat(10, 8, seed=3)
    plan = plan_hybrid(g, levels=((8, 2),))
    part = partition_plan(plan, 8)
    seen = np.concatenate(part.blocks)
    assert np.array_equal(np.sort(seen), np.arange(plan.nvb))
    for p, blocks in enumerate(part.blocks):
        assert np.array_equal(part.owner[blocks], np.full(len(blocks), p))
        assert np.array_equal(blocks, np.sort(blocks))   # ascending
    assert part.max_nvb >= 1


def test_partition_plan_balances_counts_and_tail():
    # Snake-dealing by descending tail cost must balance BOTH the block
    # counts (padding: every padded per-shard array and the per-iteration
    # all-gather/reduce-scatter are sized by the WORST count) and the
    # tail-edge bytes (per-iteration work) — the contiguous cut could
    # only trade one against the other (~2x each on degree-sorted order).
    g = generate.rmat(14, 8, seed=2)
    plan = plan_hybrid(g, levels=((8, 2),))
    tail_per_v = np.diff(plan.tail_row_ptr)
    tail_blk = np.pad(
        tail_per_v, (0, plan.nvb * BLOCK - plan.nv)
    ).reshape(plan.nvb, BLOCK).sum(axis=1)
    for parts in (4, 8):
        part = partition_plan(plan, parts)
        counts = np.array([len(b) for b in part.blocks])
        assert part.max_nvb == counts.max() == -(-plan.nvb // parts)
        tails = np.array([tail_blk[b].sum() for b in part.blocks])
        assert tails.max() <= 1.10 * max(tails.mean(), 1)


def test_partition_plan_more_parts_than_blocks():
    g = generate.gnp(200, 1000, seed=1)  # nvb=2 blocks < 8 parts
    plan = plan_hybrid(g, levels=((8, 1),))
    assert plan.nvb < 8
    part = partition_plan(plan, 8)
    counts = np.array([len(b) for b in part.blocks])
    assert counts.sum() == plan.nvb and counts.max() <= 1


@pytest.mark.parametrize(
    "levels", [((8, 1),), ((8, 4),), ((128, 8), (8, 2))]
)
def test_sharded_tiled_pagerank_parity(levels):
    g = generate.rmat(10, 8, seed=1)
    ex = ShardedTiledExecutor(
        g, PageRank(), mesh=make_mesh(8), levels=levels,
        chunk_strips=16, chunk_tail=64,
    )
    got = ex.gather_values(ex.run(10))
    want = reference_pagerank(g, 10)
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def test_sharded_tiled_matches_single_device_tiled():
    from lux_tpu.engine.tiled import TiledPullExecutor

    g = generate.rmat(10, 8, seed=9)
    sx = ShardedTiledExecutor(
        g, PageRank(), mesh=make_mesh(8), levels=((8, 2),),
        chunk_strips=16, chunk_tail=64,
    )
    tx = TiledPullExecutor(
        g, PageRank(), levels=((8, 2),), chunk_strips=16, chunk_tail=64
    )
    a = sx.gather_values(sx.run(5))
    b = np.asarray(tx.run(5))
    np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-9)


def test_sharded_tiled_small_mesh_and_resume():
    g = generate.gnp(600, 5000, seed=7)
    ex = ShardedTiledExecutor(
        g, PageRank(), mesh=make_mesh(4), levels=((8, 1),),
        chunk_strips=8, chunk_tail=64,
    )
    full = ex.gather_values(ex.run(6))
    half = ex.run(3)
    resumed = ex.gather_values(ex.run(3, vals=half))
    np.testing.assert_allclose(resumed, full, rtol=1e-6)


def test_sharded_tiled_all_tail():
    # Density floor so high nothing tiles: the sharded lane-select path
    # alone must still be exact.
    g = generate.rmat(9, 8, seed=5)
    ex = ShardedTiledExecutor(
        g, PageRank(), mesh=make_mesh(8), levels=((8, 10**9),),
        chunk_tail=64,
    )
    got = ex.gather_values(ex.run(5))
    want = reference_pagerank(g, 5)
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def test_sharded_tiled_rejects_non_spmv_programs():
    g = generate.rmat(8, 8, seed=5)
    with pytest.raises(ValueError, match="identity|source value"):
        ShardedTiledExecutor(g, ConnectedComponents(), mesh=make_mesh(2))
