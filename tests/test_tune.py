"""Profile-guided auto-tuner (lux_tpu/tune).

Covers the scoped flag overlay (``flags.overrides``: nesting,
None-masking, undeclared rejection, contextvar thread isolation, and
snapshot/config_hash resolving through it), the declared knob space
(determinism, default-first, constraint pruning), the successive-halving
search (same seed + graph -> identical winner and score table; a seeded
synthetic where a known-better non-default exchange mode must be found;
the subsample keeping the all-defaults candidate), tuneconf.v1 artifact
persistence, the TuneCache LRU/evict-on-swap contract, the LUX501-504
offline verifier on seeded corruptions, probe scoring units, and the
serving integration: warmup applies the artifact's capture-at-build
knobs, misses are counted fallbacks, and a hot-swap evicts the tuned
config with the plan cache.
"""

import copy
import math
import os
import subprocess
import sys
import threading
import types

import numpy as np
import pytest

from lux_tpu.analysis import tuneck
from lux_tpu.obs import ledger, metrics
from lux_tpu.tune import artifact, probe, space
from lux_tpu.tune.cache import TuneCache, tune_cache
from lux_tpu.tune.search import tune
from lux_tpu.utils import flags

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
LUXLINT = os.path.join(REPO, "tools", "luxlint.py")

FP = "ab12" * 10   # a plausible checkpoint fingerprint

# Deterministic synthetic cost model for the search's injectable
# measure seam: compact exchange is known-better, full (the default)
# is worst, frontier sits between; tiny knob terms totally order the
# table so argmin is unique.
_BASE_COST = {"full": 4.0, "compact": 1.0, "frontier": 2.0}


def _measure(cand, iters, rung):
    c = _BASE_COST[cand.get("LUX_EXCHANGE", "full")]
    c += 0.01 * float(cand.get("LUX_GAS_DENSITY_HI", "0.0625"))
    c += 0.001 * float(cand.get("LUX_GAS_DENSITY_LO", "0.005"))
    c += 0.0001 * float(cand.get("LUX_EXCHANGE_FRONTIER_FRAC", "0.25"))
    return c


def _graph_stub(nv=100, ne=800):
    # tune() with an injected measure only reads graph.nv/graph.ne.
    return types.SimpleNamespace(nv=nv, ne=ne)


def _synthetic_tune(engine_kind="gas_sharded", measure=_measure, **kw):
    kw.setdefault("program_name", "bfs")
    kw.setdefault("graph_fingerprint", FP)
    kw.setdefault("mesh_shape", "2")
    kw.setdefault("device_kind", "cpu")
    return tune(_graph_stub(), object(), engine_kind,
                measure=measure, **kw)


@pytest.fixture
def tune_dir(tmp_path, monkeypatch):
    """Arm LUX_TUNE_DIR at a fresh store; reset the singleton cache."""
    root = str(tmp_path / "tune")
    monkeypatch.setenv("LUX_TUNE_DIR", root)
    tune_cache().clear()
    yield root
    tune_cache().clear()


# -- flags.overrides ------------------------------------------------------


def test_overrides_scoped_and_nested():
    assert flags.get("LUX_EXCHANGE") == "full"
    with flags.overrides({"LUX_EXCHANGE": "compact"}):
        assert flags.get("LUX_EXCHANGE") == "compact"
        with flags.overrides({"LUX_EXCHANGE": "frontier"}):
            assert flags.get("LUX_EXCHANGE") == "frontier"
        assert flags.get("LUX_EXCHANGE") == "compact"
    assert flags.get("LUX_EXCHANGE") == "full"


def test_overrides_values_stringified_and_typed_accessors():
    with flags.overrides({"LUX_GAS_DENSITY_HI": 0.25,
                          "LUX_TUNE_PROBE_ITERS": 3}):
        assert flags.get("LUX_GAS_DENSITY_HI") == "0.25"
        assert flags.get_float("LUX_GAS_DENSITY_HI") == 0.25
        assert flags.get_int("LUX_TUNE_PROBE_ITERS") == 3


def test_overrides_none_masks_env(monkeypatch):
    monkeypatch.setenv("LUX_EXCHANGE", "compact")
    assert flags.get("LUX_EXCHANGE") == "compact"
    with flags.overrides({"LUX_EXCHANGE": None}):
        # None masks the env var: the declared default wins.
        assert flags.get("LUX_EXCHANGE") == "full"
    assert flags.get("LUX_EXCHANGE") == "compact"


def test_overrides_undeclared_raises_before_applying():
    with pytest.raises(KeyError, match="undeclared"):
        with flags.overrides({"LUX_EXCHANGE": "compact",
                              "LUX_NO_SUCH_KNOB": "1"}):
            pytest.fail("overlay with a typo'd knob must not enter")
    assert flags.get("LUX_EXCHANGE") == "full"


def test_overrides_thread_isolation():
    """The overlay is context-local: a candidate config being probed in
    one thread must never leak into another (concurrent serving)."""
    seen = {}

    def worker():
        seen["worker"] = flags.get("LUX_EXCHANGE")

    with flags.overrides({"LUX_EXCHANGE": "compact"}):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert flags.get("LUX_EXCHANGE") == "compact"
    assert seen["worker"] == "full"


def test_snapshot_and_config_hash_resolve_through_overlay():
    base_hash = flags.config_hash()
    with flags.overrides({"LUX_EXCHANGE": "compact"}):
        assert flags.snapshot()["LUX_EXCHANGE"] == "compact"
        assert flags.config_hash() != base_hash
    assert flags.config_hash() == base_hash


# -- knob space -----------------------------------------------------------


def test_knob_space_default_first_and_deterministic():
    cands = space.knob_space("gas_sharded")
    assert cands[0] == space.default_candidate("gas_sharded")
    assert cands[0]["LUX_EXCHANGE"] == "full"
    assert cands == space.knob_space("gas_sharded")
    modes = {c["LUX_EXCHANGE"] for c in cands}
    assert modes == {"full", "compact", "frontier"}


def test_knob_space_constraint_pruning():
    frac_default = str(flags.default("LUX_EXCHANGE_FRONTIER_FRAC"))
    for cand in space.knob_space("gas_sharded"):
        # Frontier fraction only varies when the exchange runs frontier.
        if cand["LUX_EXCHANGE"] != "frontier":
            assert cand["LUX_EXCHANGE_FRONTIER_FRAC"] == frac_default
        # Hysteresis must keep lo < hi.
        assert float(cand["LUX_GAS_DENSITY_LO"]) \
            < float(cand["LUX_GAS_DENSITY_HI"])


def test_knob_space_kinds():
    assert space.knob_space("pull") == [{}]
    assert space.knob_space("push") == [{}]
    assert len(space.knob_space("tiled")) == 2
    gas = space.knob_space("gas")
    assert all(set(c) == {"LUX_GAS_DENSITY_HI", "LUX_GAS_DENSITY_LO"}
               for c in gas)
    assert len(gas) < len(space.knob_space("gas_sharded"))


def test_knob_space_only_tuner_managed():
    for kind in ("gas", "gas_sharded", "pull_sharded", "tiled",
                 "tiled_sharded", "push"):
        for cand in space.knob_space(kind):
            assert set(cand) <= space.TUNER_MANAGED, (kind, cand)


# -- search ---------------------------------------------------------------


def test_tune_same_seed_identical_winner_and_score_table():
    a = _synthetic_tune()
    b = _synthetic_tune()
    assert a["id"] == b["id"]
    assert a["config"] == b["config"]
    assert a["score_table"] == b["score_table"]


def test_tune_finds_known_better_exchange():
    art = _synthetic_tune()
    assert art["config"]["LUX_EXCHANGE"] == "compact", art["config"]
    defaults = [r for r in art["score_table"] if r["candidate_index"] == 0]
    assert defaults, "the all-defaults candidate must always be probed"
    assert defaults[-1]["score"] > art["score"]
    # The winner is the argmin of the final rung, ties on index.
    last = max(r["rung"] for r in art["score_table"])
    final = [r for r in art["score_table"] if r["rung"] == last]
    best = min(final, key=lambda r: (r["score"], r["candidate_index"]))
    assert best["config"] == art["config"]


def test_tune_successive_halving_shape():
    art = _synthetic_tune()
    by_rung = {}
    for row in art["score_table"]:
        by_rung.setdefault(row["rung"], []).append(row)
    cap = flags.get_int("LUX_TUNE_MAX_CANDIDATES")
    eta = flags.get_int("LUX_TUNE_ETA")
    iters0 = flags.get_int("LUX_TUNE_PROBE_ITERS")
    assert len(by_rung[0]) == min(cap, len(space.knob_space("gas_sharded")))
    assert len(by_rung[1]) == math.ceil(len(by_rung[0]) / eta)
    assert all(r["iters"] == iters0 for r in by_rung[0])
    assert all(r["iters"] == 2 * iters0 for r in by_rung[1])


def test_tune_subsample_keeps_default_candidate():
    with flags.overrides({"LUX_TUNE_MAX_CANDIDATES": "4"}):
        art = _synthetic_tune()
    assert art["tuner"]["candidates"] == 4
    assert any(r["candidate_index"] == 0 for r in art["score_table"])
    # Still deterministic under the tightened cap.
    with flags.overrides({"LUX_TUNE_MAX_CANDIDATES": "4"}):
        assert _synthetic_tune()["id"] == art["id"]


def test_tune_lone_candidate_stops_early():
    """A kind with nothing to tune records one honest all-defaults rung
    instead of re-measuring the lone survivor."""
    art = _synthetic_tune("pull", measure=lambda c, i, r: 1.0)
    assert art["config"] == {}
    assert [r["rung"] for r in art["score_table"]] == [0]


def test_tune_select_lands_in_ledger(tmp_path, monkeypatch):
    monkeypatch.setenv("LUX_LEDGER_DIR", str(tmp_path / "ledger"))
    ledger.reset()
    try:
        art = _synthetic_tune()
        recs = ledger.read_all(strict=True)
        selects = [r for r in recs if r["kind"] == "tune_select"]
        assert len(selects) == 1
        assert selects[0]["tune"]["winner"] == art["config"]
        assert art["select_record_id"] == selects[0]["id"]
        # Injected measure -> no probe records, and the artifact says so.
        assert art["probe_ledger_ids"] == []
    finally:
        ledger.reset()


# -- artifact persistence -------------------------------------------------


def test_artifact_roundtrip(tune_dir):
    art = _synthetic_tune()
    path = artifact.save(tune_dir, art)
    assert os.path.basename(path).startswith("tuneconf-")
    assert artifact.load(tune_dir, art["key"]) == art
    other = artifact.make_key("cd34" * 10, "bfs", "gas_sharded", "2", "cpu")
    assert artifact.load(tune_dir, other) is None
    assert artifact.list_artifacts(tune_dir) == [path]


def test_artifact_key_mismatch_raises(tune_dir):
    art = _synthetic_tune()
    path = artifact.save(tune_dir, art)
    # A hand-edited key must never silently serve for another workload.
    edited = dict(art, key_string="tampered")
    import json as _json
    with open(path, "w") as f:
        _json.dump(edited, f)
    with pytest.raises(ValueError, match="key_string"):
        artifact.load(tune_dir, art["key"])


def test_artifact_bad_schema_raises(tune_dir):
    art = dict(_synthetic_tune(), schema="tuneconf.v0")
    path = os.path.join(tune_dir, "tuneconf-000000000000.json")
    os.makedirs(tune_dir, exist_ok=True)
    import json as _json
    with open(path, "w") as f:
        _json.dump(art, f)
    with pytest.raises(ValueError, match="schema"):
        artifact.load_path(path)


# -- TuneCache ------------------------------------------------------------


def _art_for(fp, program="bfs"):
    return _synthetic_tune(graph_fingerprint=fp, program_name=program)


def test_cache_disarmed_is_inert(monkeypatch):
    monkeypatch.delenv("LUX_TUNE_DIR", raising=False)
    tc = TuneCache()
    assert not tc.enabled()
    assert tc.get(artifact.make_key(FP, "bfs", "gas_sharded", "2",
                                    "cpu")) is None
    with pytest.raises(RuntimeError, match="LUX_TUNE_DIR"):
        tc.put(_synthetic_tune())


def test_cache_hit_miss_and_disk_reload(tune_dir):
    tc = TuneCache(root=tune_dir)
    art = _art_for(FP)
    tc.put(art)
    assert tc.get(art["key"]) == art          # memory hit
    tc.clear()
    assert len(tc) == 0
    assert tc.get(art["key"])["id"] == art["id"]   # miss -> disk load
    stats = tc.stats()
    assert stats["armed"] and stats["entries"] == 1


def test_cache_lru_eviction(tune_dir):
    tc = TuneCache(root=tune_dir)
    arts = [_art_for(f"{i:02x}" * 20) for i in range(3)]
    with flags.overrides({"LUX_TUNE_CACHE": "2"}):
        for a in arts:
            tc.put(a)
        assert len(tc) == 2
        # The oldest entry was evicted from memory, never from disk.
        assert tc.get(arts[0]["key"])["id"] == arts[0]["id"]
        assert os.path.exists(artifact.artifact_path(tune_dir,
                                                     arts[1]["key"]))


def test_cache_evict_fingerprint_keeps_disk(tune_dir):
    tc = TuneCache(root=tune_dir)
    keep_fp = "cd34" * 10
    for program in ("bfs", "labelprop"):
        tc.put(_art_for(FP, program))
    tc.put(_art_for(keep_fp))
    assert tc.evict_fingerprint(FP) == 2
    assert len(tc) == 1
    # Disk artifacts are evidence: the swap only drops memory entries,
    # and a later get() reloads the persisted file.
    reloaded = tc.get(_art_for(FP)["key"])
    assert reloaded is not None
    assert reloaded["key"]["graph_fingerprint"] == FP


# -- tuneck (LUX501-504) --------------------------------------------------


def _rule_ids(art):
    res = tuneck.verify_artifact(art)
    assert res.error is None, res.error
    return sorted({f.rule for f in res.findings})


def test_tuneck_clean_artifact():
    assert _rule_ids(_synthetic_tune()) == []


def test_tuneck_lux501_structure():
    art = copy.deepcopy(_synthetic_tune())
    art["schema"] = "tuneconf.v0"
    art["id"] = "not-an-id"
    del art["key"]["device_kind"]
    # The gutted key also trips LUX504's key well-formedness check.
    assert "LUX501" in _rule_ids(art)
    art2 = copy.deepcopy(_synthetic_tune())
    del art2["score_table"][0]["iters"]
    assert "LUX501" in _rule_ids(art2)


def test_tuneck_lux502_knob_domains():
    art = copy.deepcopy(_synthetic_tune())
    art["config"]["LUX_NO_SUCH_KNOB"] = "1"       # undeclared
    art["config"]["LUX_ENGOBS"] = "1"             # declared, not managed
    assert "LUX502" in _rule_ids(art)
    art2 = copy.deepcopy(_synthetic_tune())
    art2["score_table"][0]["config"] = {"LUX_EXCHANGE": "bogus"}
    assert "LUX502" in _rule_ids(art2)
    art3 = copy.deepcopy(_synthetic_tune())
    art3["config"]["LUX_GAS_DENSITY_HI"] = "0.05"
    art3["config"]["LUX_GAS_DENSITY_LO"] = "0.5"  # inverted hysteresis
    findings = tuneck.verify_artifact(art3).findings
    assert any(f.rule == "LUX502" and "hysteresis" in f.message
               for f in findings)


def test_tuneck_lux503_selection():
    art = copy.deepcopy(_synthetic_tune())
    # Swap the winner for the (valid, managed) default candidate: the
    # artifact no longer matches the final rung's argmin.
    default_row = next(r for r in art["score_table"]
                       if r["candidate_index"] == 0)
    art["config"] = dict(default_row["config"])
    art["score"] = default_row["score"]
    assert "LUX503" in _rule_ids(art)

    art2 = copy.deepcopy(_synthetic_tune())
    art2["probe_ledger_ids"] = ["run-deadbeef"]   # ids not in the table
    assert _rule_ids(art2) == ["LUX503"]

    art3 = copy.deepcopy(_synthetic_tune())
    for row in art3["score_table"]:
        if row["candidate_index"] == 0:
            row["candidate_index"] = 99          # default never probed
    findings = tuneck.verify_artifact(art3).findings
    assert any(f.rule == "LUX503" and "default candidate" in f.message
               for f in findings)

    art4 = copy.deepcopy(_synthetic_tune())
    art4["score_table"][0]["score"] = float("nan")
    assert "LUX503" in _rule_ids(art4)


def test_tuneck_lux504_staleness():
    old = _synthetic_tune(created_at=1.0)        # 1970: long past any bound
    assert _rule_ids(old) == ["LUX504"]
    with flags.overrides({"LUX_TUNE_MAX_AGE_S": "0"}):
        assert _rule_ids(old) == []              # 0 disables the age bound

    art = copy.deepcopy(_synthetic_tune())
    art["created_at"] = art["created_at"] + 86400.0   # the future
    assert _rule_ids(art) == ["LUX504"]

    art2 = copy.deepcopy(_synthetic_tune())
    art2["key"]["graph_fingerprint"] = "?"
    art2["key_string"] = artifact.key_string(art2["key"])
    art2["graph_meta"] = {"nv": 0, "ne": -1}
    assert "LUX504" in _rule_ids(art2)


def test_luxlint_tune_cli(tune_dir):
    clean = _synthetic_tune()
    artifact.save(tune_dir, clean)
    proc = subprocess.run(
        [sys.executable, LUXLINT, "--tune", tune_dir],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout[-500:]
    corrupt = copy.deepcopy(clean)
    corrupt["key"]["program"] = "labelprop"
    corrupt["key_string"] = artifact.key_string(corrupt["key"])
    corrupt["config"]["LUX_ENGOBS"] = "1"
    artifact.save(tune_dir, corrupt)
    proc = subprocess.run(
        [sys.executable, LUXLINT, "--tune", tune_dir],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout[-500:]
    assert "LUX502" in proc.stdout


# -- probe scoring --------------------------------------------------------


def test_score_summary_phase_medians_drop_first_record():
    summary = {"iterations": [
        {"exchange_s": 10.0, "compute_s": 10.0},   # cold-start ramp
        {"exchange_s": 1.0, "compute_s": 2.0},
        {"exchange_s": 1.0, "compute_s": 2.0},
    ]}
    score, detail = probe.score_summary(summary, 3, 0, 0, 0.05)
    assert score == pytest.approx(3.0)
    assert detail["exchange_s_med"] == pytest.approx(1.0)
    assert detail["compute_s_med"] == pytest.approx(2.0)


def test_score_summary_instability_penalty():
    summary = {"iterations": [{"exchange_s": 1.0, "compute_s": 1.0},
                              {"exchange_s": 1.0, "compute_s": 1.0}]}
    calm, _ = probe.score_summary(summary, 4, 0, 0, 0.5)
    flappy, detail = probe.score_summary(summary, 4, 2, 2, 0.5)
    assert flappy == pytest.approx(calm * 1.5)   # 1 + 0.5 * 4/4
    assert detail["direction_switches"] == 2


def test_score_summary_fallbacks():
    wall = {"iterations": [{"t_iter_s": 2.0}, {"t_iter_s": 4.0}]}
    score, detail = probe.score_summary(wall, 2, 0, 0, 0.0)
    assert score == pytest.approx(3.0)
    assert detail["exchange_s_med"] == 0.0
    totals = {"iterations": [], "num_iters": 5, "execute_s": 10.0}
    score2, _ = probe.score_summary(totals, 5, 0, 0, 0.0)
    assert score2 == pytest.approx(2.0)


# -- serving integration --------------------------------------------------


def _session_artifact(g, fp, app="bfs"):
    """A tuneconf.v1 for ``app`` on a single-device session, tuned to a
    distinctly non-default density hysteresis (capture-at-build)."""
    from lux_tpu.obs import report

    def measure(cand, iters, rung):
        # hi=0.9, lo=0.05 is known-better; defaults are worst.
        return 2.0 - float(cand.get("LUX_GAS_DENSITY_HI", "0")) \
            - float(cand.get("LUX_GAS_DENSITY_LO", "0"))

    art = tune(g, object(), "gas", program_name=app,
               graph_fingerprint=fp, mesh_shape="1",
               device_kind=report.device_profile()["device_kind"],
               measure=measure)
    assert art["config"]["LUX_GAS_DENSITY_HI"] == "0.9"
    assert art["config"]["LUX_GAS_DENSITY_LO"] == "0.05"
    return art


def test_session_warmup_applies_tuned_config(tune_dir):
    from lux_tpu.graph import generate
    from lux_tpu.models.bfs import reference_bfs
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.utils.checkpoint import fingerprint_hex

    metrics.reset()
    g = generate.gnp(300, 2000, seed=181)
    art = _session_artifact(g, fingerprint_hex(g))
    tune_cache().put(art)
    with Session(g, ServeConfig(max_batch=4, window_s=0.01,
                                pagerank_iters=4)) as s:
        prov = s.tuned_for("bfs")
        assert prov == {"id": art["id"], "score": art["score"]}
        engine = s._gas_single("bfs")
        # Tuned knobs are capture-at-build: the warmup engine carries
        # the artifact's hysteresis, not the declared defaults.
        assert engine.hi_count == math.ceil(0.9 * g.nv)
        assert engine.lo_count == math.ceil(0.05 * g.nv)
        tb = s.statusz()["tune"]
        assert tb["armed"]
        assert tb["artifacts"]["bfs"]["id"] == art["id"]
        assert tb["artifacts"]["bfs"]["probes"] == len(art["score_table"])
        # Every other app is a counted fallback, never silent.
        assert "bfs" not in tb["fallbacks"]
        assert "pagerank" in tb["fallbacks"]
        fallbacks = sum(m["value"] for m in metrics.snapshot()
                        if m["name"] == "lux_tune_fallback_total")
        assert fallbacks == len(tb["fallbacks"]) > 0
        assert s.tuned_for("pagerank") is None
        # Tuning is bitwise-neutral for integral programs.
        out = s.query("bfs", start=3, timeout=60)
        depth, _parent = reference_bfs(g, 3)
        np.testing.assert_array_equal(out["values"], depth)


def test_session_swap_evicts_tuned_config(tune_dir):
    from lux_tpu.graph import EdgeEdits, generate
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.utils.checkpoint import fingerprint_hex

    metrics.reset()
    g = generate.gnp(300, 2000, seed=182)
    old_fp = fingerprint_hex(g)
    tune_cache().put(_session_artifact(g, old_fp))
    with Session(g, ServeConfig(max_batch=4, window_s=0.01,
                                pagerank_iters=4)) as s:
        assert s.tuned_for("bfs") is not None
        s.apply_edits(EdgeEdits.from_lists(insert=[(0, g.nv - 1),
                                                   (1, g.nv - 2)]))
        assert s.fingerprint != old_fp
        # The swap retires the tuned config with the engines and the
        # shard plan: the new fingerprint has no artifact, so bfs is a
        # counted fallback until someone re-tunes.
        assert s.tuned_for("bfs") is None
        tb = s.statusz()["tune"]
        assert "bfs" in tb["fallbacks"]
        assert tb["artifacts"] == {}
        from lux_tpu.obs import report
        key = artifact.make_key(old_fp, "bfs", "gas", "1",
                                report.device_profile()["device_kind"])
        # The old artifact is still on disk (evidence), only the
        # in-memory entry was dropped.
        assert os.path.exists(artifact.artifact_path(tune_dir, key))
