"""utils + parallel helpers coverage."""

import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.parallel.mesh import make_mesh
from lux_tpu.parallel.multihost import make_global_mesh
from lux_tpu.utils import checkpoint
from lux_tpu.utils.timing import Timer


def test_checkpoint_roundtrip(tmp_path):
    g = generate.gnp(100, 500, seed=1)
    vals = np.random.default_rng(0).random(g.nv).astype(np.float32)
    fr = vals > 0.5
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, g, vals, 7, frontier=fr)
    v2, it, f2 = checkpoint.load(p, g)
    np.testing.assert_array_equal(vals, v2)
    np.testing.assert_array_equal(fr, f2)
    assert it == 7


def test_checkpoint_honors_exact_path(tmp_path):
    # np.savez would append ".npz" to a suffixless path, breaking the CLI
    # save->resume cycle that passes the same -save/-resume string.
    g = generate.gnp(50, 200, seed=3)
    p = str(tmp_path / "ck")
    checkpoint.save(p, g, np.ones(50, np.float32), 2)
    vals, it, fr = checkpoint.load(p, g)
    assert it == 2


def test_checkpoint_rejects_other_graph(tmp_path):
    g1 = generate.gnp(100, 500, seed=1)
    g2 = generate.gnp(100, 500, seed=2)
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, g1, np.zeros(100, np.float32), 1)
    with pytest.raises(ValueError):
        checkpoint.load(p, g2)


def test_checkpoint_without_frontier(tmp_path):
    g = generate.gnp(50, 200, seed=3)
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, g, np.ones(50, np.float32), 3)
    vals, it, fr = checkpoint.load(p, g)
    assert fr is None and it == 3


def test_timer():
    with Timer() as t:
        sum(range(1000))
    assert t.elapsed >= 0


def test_global_mesh_matches_local_on_single_host():
    m1 = make_mesh(8)
    m2 = make_global_mesh(8)
    assert m1.devices.shape == m2.devices.shape
    with pytest.raises(ValueError):
        make_global_mesh(1000)


def test_graft_entry_contract():
    import __graft_entry__ as ge
    import jax

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == args[0].shape
    ge.dryrun_multichip(4)
