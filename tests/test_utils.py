"""utils + parallel helpers coverage."""

import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.parallel.mesh import make_mesh
from lux_tpu.parallel.multihost import make_global_mesh
from lux_tpu.utils import checkpoint
from lux_tpu.utils.timing import Timer


def test_checkpoint_roundtrip(tmp_path):
    g = generate.gnp(100, 500, seed=1)
    vals = np.random.default_rng(0).random(g.nv).astype(np.float32)
    fr = vals > 0.5
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, g, vals, 7, frontier=fr)
    v2, it, f2 = checkpoint.load(p, g)
    np.testing.assert_array_equal(vals, v2)
    np.testing.assert_array_equal(fr, f2)
    assert it == 7


def test_checkpoint_honors_exact_path(tmp_path):
    # np.savez would append ".npz" to a suffixless path, breaking the CLI
    # save->resume cycle that passes the same -save/-resume string.
    g = generate.gnp(50, 200, seed=3)
    p = str(tmp_path / "ck")
    checkpoint.save(p, g, np.ones(50, np.float32), 2)
    vals, it, fr = checkpoint.load(p, g)
    assert it == 2


def test_checkpoint_rejects_other_graph(tmp_path):
    g1 = generate.gnp(100, 500, seed=1)
    g2 = generate.gnp(100, 500, seed=2)
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, g1, np.zeros(100, np.float32), 1)
    with pytest.raises(ValueError):
        checkpoint.load(p, g2)


def test_checkpoint_without_frontier(tmp_path):
    g = generate.gnp(50, 200, seed=3)
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, g, np.ones(50, np.float32), 3)
    vals, it, fr = checkpoint.load(p, g)
    assert fr is None and it == 3


def test_fingerprint_sees_edge_destinations():
    # Same nv/ne and identical col_src but different destinations: the
    # old fingerprint (col_src samples only) collided here, so a resume
    # or a served cache hit could cross graphs silently.
    from lux_tpu.graph.graph import Graph

    g1 = Graph.from_edges([0, 0], [1, 2], nv=3)
    g2 = Graph.from_edges([0, 0], [1, 1], nv=3)
    np.testing.assert_array_equal(g1.col_src, g2.col_src)
    f1 = checkpoint.fingerprint(g1)
    f2 = checkpoint.fingerprint(g2)
    assert not np.array_equal(f1, f2)
    assert checkpoint.fingerprint_hex(g1) != checkpoint.fingerprint_hex(g2)


def test_fingerprint_deterministic_and_source_sensitive():
    g = generate.gnp(200, 900, seed=5)
    np.testing.assert_array_equal(
        checkpoint.fingerprint(g), checkpoint.fingerprint(g)
    )
    h = generate.gnp(200, 900, seed=6)
    assert checkpoint.fingerprint_hex(g) != checkpoint.fingerprint_hex(h)


def test_checkpoint_load_missing_file(tmp_path):
    g = generate.gnp(20, 50, seed=1)
    with pytest.raises(checkpoint.CheckpointError, match="does not exist"):
        checkpoint.load(str(tmp_path / "nope.npz"), g)


def test_checkpoint_load_corrupt_file(tmp_path):
    g = generate.gnp(20, 50, seed=1)
    p = tmp_path / "bad.npz"
    p.write_bytes(b"this is not an npz archive")
    with pytest.raises(checkpoint.CheckpointError, match="not a readable"):
        checkpoint.load(str(p), g)


def test_checkpoint_load_missing_fields(tmp_path):
    g = generate.gnp(20, 50, seed=1)
    p = str(tmp_path / "partial.npz")
    np.savez(p, values=np.zeros(20, np.float32))  # no iteration/fingerprint
    with pytest.raises(checkpoint.CheckpointError, match="missing"):
        checkpoint.load(p, g)


def test_checkpoint_mismatch_is_checkpoint_error(tmp_path):
    # CheckpointError subclasses ValueError, so pre-existing callers that
    # catch ValueError keep working.
    g1 = generate.gnp(40, 100, seed=1)
    g2 = generate.gnp(40, 100, seed=2)
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, g1, np.zeros(40, np.float32), 1)
    with pytest.raises(checkpoint.CheckpointError, match="different graph"):
        checkpoint.load(p, g2)
    assert issubclass(checkpoint.CheckpointError, ValueError)


def test_timer():
    with Timer() as t:
        sum(range(1000))
    assert t.elapsed >= 0


def test_global_mesh_matches_local_on_single_host():
    m1 = make_mesh(8)
    m2 = make_global_mesh(8)
    assert m1.devices.shape == m2.devices.shape
    with pytest.raises(ValueError):
        make_global_mesh(1000)


def test_graft_entry_contract():
    import __graft_entry__ as ge
    import jax

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == args[0].shape
    ge.dryrun_multichip(4)


def test_edge_index_dtype_2_31_boundary():
    """At ne = 2^31 the reference's E_ID=uint64 headroom (README.md:79-86)
    must kick in: int32 row offsets would overflow. Without x64 enabled
    JAX would silently downcast int64 → int32, so the dtype helper must
    refuse rather than overflow."""
    import jax
    import jax.numpy as jnp
    import pytest

    from lux_tpu.engine.pull import _edge_index_dtype

    assert _edge_index_dtype(2**31 - 1) == jnp.int32
    if jax.config.jax_enable_x64:
        assert _edge_index_dtype(2**31) == jnp.int64
    else:
        with pytest.raises(ValueError, match="2\\^31"):
            _edge_index_dtype(2**31)


def test_virtual_cpu_flags():
    from lux_tpu.utils.platform import virtual_cpu_flags

    assert (
        virtual_cpu_flags(8, "")
        == "--xla_force_host_platform_device_count=8"
    )
    assert (
        virtual_cpu_flags(8, "--xla_force_host_platform_device_count=2")
        == "--xla_force_host_platform_device_count=8"
    )
    kept = "--xla_force_host_platform_device_count=16"
    assert virtual_cpu_flags(8, kept) == kept
    assert (
        virtual_cpu_flags(4, "--a --xla_force_host_platform_device_count=2 --b")
        == "--a --xla_force_host_platform_device_count=4 --b"
    )


def test_col_dst_cached():
    import numpy as np

    from lux_tpu.graph import generate

    g = generate.rmat(6, 4, seed=0)
    a = g.col_dst
    assert g.col_dst is a  # cached, not recomputed
    want = np.repeat(np.arange(g.nv), np.diff(g.row_ptr))
    np.testing.assert_array_equal(a, want)


def test_lane_pad_width_policy():
    from lux_tpu.engine.pull import lane_pad_width

    assert lane_pad_width(()) == (0, 0)          # scalar values
    assert lane_pad_width(None) == (0, 0)
    assert lane_pad_width((20,)) == (20, 128)    # CF's K=20
    assert lane_pad_width((128,)) == (128, 0)    # already lane-aligned
    assert lane_pad_width((200,)) == (200, 256)
    assert lane_pad_width((4, 5)) == (20, 0)     # rank-2: no lane pad
