"""graph/wal: frame format, torn-write policy, fingerprint chaining,
replay parity, compaction, and crash-point recovery through the store."""

import os
import struct

import numpy as np
import pytest

from lux_tpu.graph import DeltaGraph, EdgeEdits, generate
from lux_tpu.graph.snapshot import SnapshotStore
from lux_tpu.graph.wal import (MAGIC, RecoveryResult, Wal, WalCorruptError,
                               read_records, replay)
from lux_tpu.utils import checkpoint, faults


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


def _graph(seed=11):
    return generate.gnp(120, 700, seed=seed)


def _edits(g, seed, n=10):
    rng = np.random.default_rng(seed)
    ins = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
           for _ in range(n)]
    eidx = rng.choice(g.ne, size=n // 2, replace=False)
    dels = [(int(g.col_src[e]), int(g.col_dst[e])) for e in eidx]
    return EdgeEdits.from_lists(insert=ins, delete=dels)


def _wal_path(d):
    return os.path.join(str(d), "lux.wal")


# -- append / read roundtrip ----------------------------------------------


def test_append_and_read_roundtrip(tmp_path):
    g = _graph()
    fp = checkpoint.fingerprint_hex(g)
    w = Wal(str(tmp_path))
    e = _edits(g, 1)
    assert w.append_edits(e, fp) == 1
    assert w.append_commit(1, "f" * 64) == 2
    recs, torn = read_records(w.path)
    assert not torn
    assert [r.kind for r in recs] == ["edits", "commit"]
    assert recs[0].base_fp == fp
    np.testing.assert_array_equal(recs[0].edits.ins_src, e.ins_src)
    np.testing.assert_array_equal(recs[0].edits.del_dst, e.del_dst)
    assert recs[1].version == 1 and recs[1].fingerprint == "f" * 64
    assert w.stats()["records"] == 2


def test_weighted_edits_roundtrip(tmp_path):
    w = Wal(str(tmp_path))
    e = EdgeEdits.from_lists(insert=[(0, 1, 7), (2, 3, 9)],
                             delete=[(4, 5)])
    w.append_edits(e, "a" * 64)
    (rec,), _ = read_records(w.path)
    np.testing.assert_array_equal(rec.edits.ins_w, e.ins_w)


def test_reopen_resumes_sequence(tmp_path):
    w = Wal(str(tmp_path))
    w.append_edits(EdgeEdits.from_lists(insert=[(0, 1)]), "a" * 64)
    w2 = Wal(str(tmp_path))
    assert w2.append_commit(1, "b" * 64) == 2


# -- torn-write policy -----------------------------------------------------


def test_torn_final_record_is_truncated(tmp_path):
    g = _graph()
    w = Wal(str(tmp_path))
    w.append_edits(_edits(g, 1), "a" * 64)
    size_after_first = os.path.getsize(w.path)
    w.append_edits(_edits(g, 2), "a" * 64)
    # Tear the second frame mid-payload, as a crash mid-append would.
    os.truncate(w.path, size_after_first + 9)
    recs, torn = read_records(w.path)
    assert torn and len(recs) == 1
    # Re-opening repairs the file in place and appends cleanly after.
    w2 = Wal(str(tmp_path))
    assert os.path.getsize(w2.path) == size_after_first
    w2.append_commit(1, "b" * 64)
    recs, torn = read_records(w2.path)
    assert not torn and [r.kind for r in recs] == ["edits", "commit"]


def test_corrupt_final_record_counts_as_torn(tmp_path):
    g = _graph()
    w = Wal(str(tmp_path))
    w.append_edits(_edits(g, 1), "a" * 64)
    with open(w.path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        last = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last[0] ^ 0xFF]))
    recs, torn = read_records(w.path)
    assert torn and recs == []


def test_crc_damage_before_final_record_raises(tmp_path):
    g = _graph()
    w = Wal(str(tmp_path))
    w.append_edits(_edits(g, 1), "a" * 64)
    w.append_commit(1, "b" * 64)
    # Flip a byte inside the FIRST record's payload: interior rot, not a
    # torn tail — replay must refuse rather than skip.
    with open(w.path, "r+b") as f:
        f.seek(len(MAGIC) + struct.calcsize("<II") + 40)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(WalCorruptError, match="CRC mismatch"):
        read_records(w.path)


def test_injected_corruption_is_crc_detectable(tmp_path):
    g = _graph()
    w = Wal(str(tmp_path))
    with faults.injected("wal.fsync:corrupt:1.0:1"):
        w.append_edits(_edits(g, 1), "a" * 64)   # written bytes are bad
    w.append_commit(1, "b" * 64)                 # clean record after
    # The CRC was computed pre-corruption, so the damaged record fails
    # its checksum mid-file -> interior damage, loud failure.
    with pytest.raises(WalCorruptError):
        read_records(w.path)


def test_bad_magic_raises(tmp_path):
    p = _wal_path(tmp_path)
    with open(p, "wb") as f:
        f.write(b"NOTAWAL!" + b"\x00" * 32)
    with pytest.raises(WalCorruptError, match="magic"):
        read_records(p)


# -- replay ----------------------------------------------------------------


def test_replay_no_log_returns_base(tmp_path):
    g = _graph()
    r = replay(g, str(tmp_path))
    assert isinstance(r, RecoveryResult)
    assert r.graph is g and r.version == 0 and r.pending == ()


def test_store_recovery_is_bitwise_identical(tmp_path):
    g = _graph()
    store = SnapshotStore(g, wal_dir=str(tmp_path))
    e1, e2 = _edits(g, 1), _edits(g, 2)
    store.apply(e1)
    store.apply(e2)
    head = store.current()
    expect = DeltaGraph.fresh(g).stack(e1).merged()
    expect = DeltaGraph.fresh(expect).stack(e2).merged()

    recovered = SnapshotStore.recover(_graph(), str(tmp_path))
    rhead = recovered.current()
    assert rhead.version == head.version == 2
    assert rhead.fingerprint == head.fingerprint
    np.testing.assert_array_equal(rhead.graph.row_ptr, expect.row_ptr)
    np.testing.assert_array_equal(rhead.graph.col_src, expect.col_src)


def test_recovery_restages_uncommitted_batches(tmp_path):
    g = _graph()
    store = SnapshotStore(g, wal_dir=str(tmp_path))
    store.apply(_edits(g, 1))
    committed_fp = store.current().fingerprint
    store.enqueue(_edits(g, 2))      # logged, never minted

    recovered = SnapshotStore.recover(_graph(), str(tmp_path))
    assert recovered.current().version == 1
    assert recovered.current().fingerprint == committed_fp
    assert recovered.pending_edits() == 1
    # The next apply mints exactly what the dead process would have.
    snap = recovered.apply()
    assert snap.version == 2

    fresh = SnapshotStore(_graph(), wal_dir=None)
    fresh.apply(_edits(g, 1))
    fresh.apply(_edits(g, 2))
    assert snap.fingerprint == fresh.current().fingerprint


def test_replay_wrong_base_raises(tmp_path):
    g = _graph()
    store = SnapshotStore(g, wal_dir=str(tmp_path))
    store.apply(_edits(g, 1))
    with pytest.raises(WalCorruptError, match="does not chain"):
        replay(_graph(seed=99), str(tmp_path))


def test_replay_skips_compacted_prefix(tmp_path):
    g = _graph()
    store = SnapshotStore(g, wal_dir=str(tmp_path))
    store.apply(_edits(g, 1))
    mid = store.current()
    store.apply(_edits(g, 2))
    head = store.current()
    # Replay from the v1 graph: the v0->v1 records predate it and must
    # be skipped until the chain anchors at v1's fingerprint.
    r = replay(mid.graph, str(tmp_path))
    assert r.version == 2
    assert r.fingerprint == head.fingerprint
    assert r.skipped >= 1


def test_compact_drops_committed_prefix(tmp_path):
    g = _graph()
    store = SnapshotStore(g, wal_dir=str(tmp_path))
    store.apply(_edits(g, 1))
    fp1 = store.current().fingerprint
    store.apply(_edits(g, 2))
    w = store._wal
    dropped = w.compact(fp1)
    assert dropped == 2              # edits + commit for v1
    r = replay(store.get(1).graph, str(tmp_path))
    assert r.version == 2 and r.skipped == 0
    with pytest.raises(ValueError, match="no commit record"):
        w.compact("0" * 64)


# -- crash-point recovery through the serving session ---------------------


def test_crash_during_warm_recovers_bitwise(tmp_path, monkeypatch):
    from lux_tpu.serve import ServeConfig, Session

    monkeypatch.setenv("LUX_WAL_DIR", str(tmp_path))
    g = _graph()
    s = Session(g, ServeConfig(max_batch=2, window_s=0.001), warm=False)
    s.apply_edits(_edits(g, 5))
    surviving_fp = s.store.current().fingerprint
    faults.arm("snapshot.warm:crash:1.0")
    # The crash fires between the durable mint and the serving flip; it
    # must escape every `except Exception` on the way out.
    with pytest.raises(faults.CrashPoint):
        s.apply_edits(_edits(g, 6))
    faults.disarm()
    crashed_head = s.store.current()
    assert crashed_head.version == 2          # minted before the crash
    assert s.version == 1                     # never served
    s.close()

    recovered = SnapshotStore.recover(_graph(), str(tmp_path))
    assert recovered.current().version == 2
    assert recovered.current().fingerprint == crashed_head.fingerprint
    assert recovered.current().fingerprint != surviving_fp
    # A fresh session serves the recovered store directly.
    s2 = Session(recovered, ServeConfig(max_batch=2, window_s=0.001),
                 warm=False)
    assert s2.version == 2
    out = s2.query("sssp", start=0, timeout=60)
    assert out["values"].shape == (g.nv,)
    s2.close()
