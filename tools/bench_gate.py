#!/usr/bin/env python3
"""Bench regression gate: run bench.py, emit a ``bench_gate.v1`` JSON
round artifact, and fail when a tracked metric regresses past tolerance
against the newest committed ``BENCH_*.json`` baseline — the luxlint
``--baseline`` ratchet idiom applied to performance.

Usage:
  python tools/bench_gate.py --fast                 # make bench-gate
  python tools/bench_gate.py --fast --record BENCH_r06.json
  python tools/bench_gate.py --replay CUR.json --baseline BASE.json

``--fast`` runs the suite on a tiny graph (LUX_BENCH_GATE_SCALE,
default 10) so the gate fits in `make verify`; full mode uses the
bench defaults (scale 22). Rounds only compare against baselines with
the same context (mode, scale, edge factor, layout, platform,
device_kind) — the
r01-r05 full-scale TPU artifacts are kept as history, not gates, for a
fast CPU round. ``--replay`` feeds a previously-emitted bench_gate.v1
JSON through the comparison (no bench run) — the seeded-regression test
and postmortem re-checks use it.

Metric direction is inferred from the name: ``*_ms_per_iter`` /
``*_s`` / ``*_seconds`` regress upward, everything else (gteps, GB/s,
peak fractions) regresses downward.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lux_tpu.utils import flags  # noqa: E402

_LOWER_IS_BETTER = re.compile(r"(_ms_per_iter|ms_per_iter|_seconds|_s)$")
# Context keys that must match for two rounds to be comparable.
_CONTEXT_KEYS = ("mode", "scale", "ef", "layout", "platform", "exchange",
                 "device_kind", "tuned")


def log(msg):
    print(f"# {msg}", file=sys.stderr, flush=True)


# -- metric extraction -----------------------------------------------------


def metrics_from_headline(headline: dict) -> dict:
    """Flatten a bench.py headline (either output line) into one
    ``name -> float`` map the comparison walks."""
    out = {}
    if isinstance(headline.get("value"), (int, float)):
        out["headline_gteps"] = float(headline["value"])
    for key in ("achieved_gbps", "hbm_peak_frac", "smallworld_gteps"):
        v = headline.get(key)
        if isinstance(v, (int, float)):
            out[key] = float(v)
    for name, res in (headline.get("suite") or {}).items():
        if not isinstance(res, dict):
            continue
        for key in ("gteps", "ms_per_iter", "achieved_gbps",
                    "hbm_peak_frac"):
            v = res.get(key)
            if isinstance(v, (int, float)):
                out[f"{name}.{key}"] = float(v)
    return out


def roofline_from_headline(headline: dict) -> dict:
    """The roofline block PERF.md's evidence policy v3 requires: the
    achieved-vs-peak fractions from the headline telemetry (attached by
    obs/report.py) plus the headline's byte-model fraction."""
    out = {}
    if isinstance(headline.get("hbm_peak_frac"), (int, float)):
        out["headline_hbm_frac"] = headline["hbm_peak_frac"]
    tel = headline.get("telemetry") or {}
    roof = tel.get("roofline") or {}
    for key, v in roof.items():
        if isinstance(v, (int, float)):
            out[key] = v
    return out


# -- baselines -------------------------------------------------------------


def _round_num(path: str) -> int:
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def find_baseline(repo: str, exclude: str = None):
    """Newest committed BENCH_r0N.json (highest round number), skipping
    the file this run is about to write."""
    cands = sorted(glob.glob(os.path.join(repo, "BENCH_*.json")),
                   key=_round_num)
    if exclude:
        ex = os.path.abspath(exclude)
        cands = [c for c in cands if os.path.abspath(c) != ex]
    return cands[-1] if cands else None


def load_baseline(path: str) -> dict:
    """Read either artifact shape: a bench_gate.v1 doc (r06+) or the
    driver-recorded ``{n, cmd, rc, tail, parsed}`` shape (r01-r05)."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") == "bench_gate.v1":
        return {"metrics": doc.get("metrics") or {},
                "context": doc.get("context") or {}}
    parsed = doc.get("parsed") or {}
    ctx = {}
    m = re.search(r"rmat(\d+)", str(parsed.get("metric", "")))
    if m:
        ctx["scale"] = int(m.group(1))
    if parsed.get("layout"):
        ctx["layout"] = parsed["layout"]
    return {"metrics": metrics_from_headline(parsed), "context": ctx}


def comparable(cur_ctx: dict, base_ctx: dict):
    """(ok, reason): contexts must agree on every key both sides carry;
    a baseline missing a key (legacy artifacts) fails closed on mode —
    a full-scale TPU round must never gate a fast CPU round."""
    for key in _CONTEXT_KEYS:
        c, b = cur_ctx.get(key), base_ctx.get(key)
        if key == "exchange" and b is None:
            # Baselines recorded before the exchange key existed ran
            # under the then-only full exchange.
            b = flags.default("LUX_EXCHANGE")
        if key == "tuned":
            # Artifacts recorded before the auto-tuner existed ran
            # under default configs; a tuned round must never ratchet
            # against them (nor vice versa) — same idiom as exchange.
            c = bool(c)
            b = bool(b)
        if key == "device_kind" and b is None:
            # A baseline that never recorded its chip could have come
            # from ANY device; numbers from different chips are
            # different experiments, so fail closed rather than ratchet
            # a v5e round against (say) a v5p artifact — unless both
            # sides already agree on platform=cpu, where the kind is
            # the platform.
            if cur_ctx.get("platform") == "cpu" \
                    and base_ctx.get("platform") == "cpu":
                continue
            return False, "baseline has no device_kind context"
        if b is None and key in ("ef", "platform", "mode"):
            if key == "mode" and cur_ctx.get("mode") == "fast":
                return False, "legacy baseline has no fast-mode context"
            continue
        if c != b:
            return False, f"context mismatch on {key}: {c!r} vs {b!r}"
    return True, None


# -- comparison ------------------------------------------------------------


def compare(current: dict, baseline: dict, tol: float):
    """Per-metric regression check over the intersection of the two
    metric maps. Returns (rows, ok): a row per shared metric with the
    signed relative delta; ``ok`` is False when any metric moved in its
    bad direction by more than ``tol``."""
    rows = []
    ok = True
    for name in sorted(set(current) & set(baseline)):
        base, cur = float(baseline[name]), float(current[name])
        if base == 0.0:
            continue
        lower_better = bool(_LOWER_IS_BETTER.search(name))
        delta = (cur - base) / abs(base)
        regressed = delta > tol if lower_better else delta < -tol
        rows.append({
            "metric": name, "base": base, "cur": cur,
            "delta_frac": round(delta, 4), "tol": tol,
            "better": "lower" if lower_better else "higher",
            "ok": not regressed,
        })
        ok = ok and not regressed
    return rows, ok


# -- running the bench -----------------------------------------------------


def run_bench(fast: bool):
    """Run bench.py as a subprocess; returns (headline, context, cmd).
    The headline is the LAST JSON stdout line (suite-enriched when the
    suite ran); context comes from the effective knobs plus the
    platform bench logs to stderr."""
    env = dict(os.environ)
    if fast:
        env.setdefault("LUX_BENCH_SCALE",
                       str(flags.get_int("LUX_BENCH_GATE_SCALE")))
        env.setdefault("LUX_BENCH_EF", "8")
        env.setdefault("LUX_BENCH_ITERS", "8")
        env.setdefault("LUX_BENCH_DEADLINE", "20")
    cmd = [sys.executable, os.path.join(REPO, "bench.py")]
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                          env=env)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        raise SystemExit(f"bench.py failed (rc={proc.returncode}):\n"
                         f"{proc.stdout[-2000:]}")
    headline = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            headline = json.loads(line)
    if headline is None:
        raise SystemExit("bench.py printed no JSON headline")
    m = re.search(r"^# platform: (\S+)", proc.stderr, re.M)
    mk = re.search(r"^# device_kind: (.+)$", proc.stderr, re.M)
    context = {
        "mode": "fast" if fast else "full",
        "scale": int(env.get("LUX_BENCH_SCALE",
                             flags.default("LUX_BENCH_SCALE"))),
        "ef": int(env.get("LUX_BENCH_EF", flags.default("LUX_BENCH_EF"))),
        "layout": env.get("LUX_BENCH_LAYOUT",
                          flags.default("LUX_BENCH_LAYOUT")),
        # The requested sharded exchange mode: two bench runs with
        # different LUX_EXCHANGE settings are different experiments and
        # must never ratchet against each other silently.
        "exchange": env.get("LUX_EXCHANGE", flags.default("LUX_EXCHANGE")),
        "platform": m.group(1) if m else "unknown",
        # The chip the numbers came from (jax device_kind); rounds from
        # different chips never ratchet against each other.
        "device_kind": mk.group(1).strip() if mk else "unknown",
        # Whether the suite ran bench.py --tuned (TuneCache winners
        # next to the default rows). Tuned and default rounds are
        # different experiments: a tuned round ratcheting a default
        # baseline would bake the tuner's win into the floor.
        "tuned": bool(headline.get("tuned")),
        # Reproducibility stamp, NOT a gate key (comparable() never
        # reads it): the flag-registry hash that keys this round's run
        # ledger records, so a gate artifact can be joined back to its
        # runrec.v1 evidence.
        "config_hash": flags.config_hash(),
    }
    return headline, context, " ".join(cmd)


def build_doc(headline: dict, context: dict, cmd: str) -> dict:
    return {
        "schema": "bench_gate.v1",
        "mode": context.get("mode"),
        "context": context,
        "cmd": cmd,
        "metrics": metrics_from_headline(headline),
        "roofline": roofline_from_headline(headline),
        # `parsed` mirrors the r01-r05 artifact field so existing
        # BENCH_r0N readers keep working on r06+.
        "parsed": headline,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fast", action="store_true",
                    help="tiny-graph suite (LUX_BENCH_GATE_SCALE) for "
                    "make verify")
    ap.add_argument("--replay", metavar="JSON",
                    help="compare a previously-emitted bench_gate.v1 doc "
                    "instead of running bench.py")
    ap.add_argument("--baseline", metavar="JSON",
                    help="explicit baseline (default: newest BENCH_*.json)")
    ap.add_argument("--out", metavar="JSON",
                    help="also write the bench_gate.v1 doc here")
    ap.add_argument("--record", metavar="BENCH_rNN.json",
                    help="record this round as a BENCH lineage artifact")
    ap.add_argument("--tol", type=float, default=None,
                    help="relative regression tolerance (default "
                    "LUX_BENCH_GATE_TOL)")
    args = ap.parse_args(argv)

    tol = args.tol if args.tol is not None else flags.get_float(
        "LUX_BENCH_GATE_TOL")

    if args.replay:
        with open(args.replay) as f:
            doc = json.load(f)
        if doc.get("schema") != "bench_gate.v1":
            raise SystemExit(f"{args.replay}: not a bench_gate.v1 doc")
    else:
        headline, context, cmd = run_bench(args.fast)
        doc = build_doc(headline, context, cmd)

    base_path = args.baseline or find_baseline(REPO, exclude=args.record)
    if base_path:
        base = load_baseline(base_path)
        ok_ctx, reason = comparable(doc.get("context") or {},
                                    base["context"])
        doc["baseline"] = {"path": os.path.basename(base_path),
                           "comparable": ok_ctx, "reason": reason}
        if ok_ctx:
            rows, ok = compare(doc["metrics"], base["metrics"], tol)
            doc["comparison"], doc["ok"] = rows, ok
        else:
            log(f"baseline {os.path.basename(base_path)} not comparable: "
                f"{reason}")
            doc["comparison"], doc["ok"] = [], True
    else:
        log("no BENCH_*.json baseline found; recording only")
        doc["baseline"] = None
        doc["comparison"], doc["ok"] = [], True

    for path in filter(None, (args.out, args.record)):
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        log(f"wrote {path}")

    for row in doc["comparison"]:
        mark = "ok" if row["ok"] else "REGRESSED"
        print(f"{row['metric']:<34} base={row['base']:<10.4g} "
              f"cur={row['cur']:<10.4g} delta={row['delta_frac']:+.1%} "
              f"({row['better']} is better) {mark}")
    print("BENCH_GATE " + json.dumps({
        "schema": "bench_gate.v1", "ok": doc["ok"],
        "compared": len(doc["comparison"]),
        "baseline": (doc.get("baseline") or {}).get("path"),
        "metrics": len(doc["metrics"]),
    }))
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
