#!/usr/bin/env python3
"""Sharded-executor evidence on a virtual CPU mesh (no multi-chip here).

Runs the 8-way (and smaller) ShardedTiledExecutor on an R-MAT graph on
``--xla_force_host_platform_device_count`` virtual CPU devices and
records per-iteration wall time plus the ANALYTIC per-device collective
volume. On this 2-core host the virtual devices share cores, so wall
times measure correctness + dispatch overhead, NOT scaling — the
collective-byte model is the honest scaling input (PERF.md carries the
extrapolation). Usage:

    python tools/bench_sharded.py [scale] [iters]
"""
import os
import sys

PARTS = (1, 2, 4, 8)
os.environ.setdefault("LUX_PLATFORM", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={max(PARTS)}"
).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

from bench import cached_graph, log


def main():
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    ef = 16
    cache = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".bench_cache",
    )

    from lux_tpu.utils.platform import ensure_backend

    log(f"platform: {ensure_backend()}")
    import jax

    log(f"devices: {len(jax.devices())}")

    from lux_tpu.engine.tiled import get_cached_plan
    from lux_tpu.engine.tiled_sharded import ShardedTiledExecutor
    from lux_tpu.graph import generate
    from lux_tpu.models import PageRank
    from lux_tpu.parallel.mesh import make_mesh

    g = cached_graph(
        cache, f"rmat{scale}_{ef}",
        lambda: generate.rmat(scale, ef, seed=42),
    )

    budget = 8 << 30
    plan_path = os.path.join(cache, f"plan_rmat{scale}_{ef}_8x2_8192.luxplan")
    t0 = time.time()
    plan = get_cached_plan(g, plan_path, levels=((8, 2),),
                           budget_bytes=budget, log=log)
    log(f"plan ready in {time.time()-t0:.0f}s (coverage {plan.coverage:.1%})")

    results = []
    for p in PARTS:
        t0 = time.time()
        ex = ShardedTiledExecutor(g, PageRank(), mesh=make_mesh(p), plan=plan)
        log(f"P={p}: executor built in {time.time()-t0:.0f}s "
            f"(max_nv={ex.max_nv})")
        vals = ex.run(1)                     # compile + settle
        t0 = time.perf_counter()
        vals = ex.run(iters, vals=vals)
        dt = (time.perf_counter() - t0) / iters
        # Analytic per-device per-iteration collective volume:
        # ring all-gather of the (max_nv,) f32 value shards ((P-1) segments
        # egress per device) + tiled reduce-scatter of the owner-stacked
        # strip accumulator ((P-1) tiles of max_nv f32 egress per device —
        # round 2's full-height psum cost 2(P-1)/P * nvb*128*4 and grew
        # toward 2x the global accumulator at large P).
        ag = (p - 1) * ex.max_nv * 4
        ps = (p - 1) * ex.max_nv * 4
        res = {
            "parts": p,
            "ms_per_iter": round(dt * 1e3, 1),
            "all_gather_bytes_per_dev": ag,
            "psum_bytes_per_dev": ps,
            "collective_bytes_per_dev": ag + ps,
        }
        log(f"P={p}: {res}")
        results.append(res)
        del ex

    print(json.dumps({
        "metric": f"sharded_tiled_pagerank_rmat{scale}_cpu_mesh",
        "iters": iters,
        "nv": g.nv,
        "ne": g.ne,
        "results": results,
    }))


if __name__ == "__main__":
    main()
