#!/usr/bin/env python3
"""Chaos acceptance harness for the robustness tier (`make chaos-stress`).

tests/ prove each mechanism in isolation; this tool proves they compose
under load, driving real HTTP traffic with *every* registered fault
point armed (utils/faults.py, seeded — reruns replay the same draws):

Phase A — seeded burst: concurrent SSSP/components/PageRank queries plus
  mid-burst WAL-queued edits and a flush-swap, with engine raises, build
  / fsync / warm / batcher delays, and cache-put failures injected.
  Asserts every request reaches a TERMINAL status (no hangs) and the
  per-code ``lux_requests_total`` deltas sum exactly to requests issued.

Phase B — breaker lifecycle: a hard engine fault trips the per-(program,
  fingerprint) breaker open (503 + Retry-After); after the cooldown the
  half-open probe rebuilds the pool entry and closes it. Asserts the
  open -> half_open -> closed transition counters all advanced and
  serving returns to 200.

Phase C — crash/recover: an injected CrashPoint (BaseException — no
  handler may absorb it) kills a swap between the durable WAL mint and
  the serving flip. The store is rebuilt via SnapshotStore.recover and
  asserted bitwise-identical (fingerprint) to the pre-crash head; a new
  session serves it and a disarmed steady-state burst must recompile
  NOTHING (the zero-recompile contract survives chaos + recovery).

Prints a one-line ``chaos_stress.v1`` JSON document last. Scale with
LUX_SMOKE_SCALE (default 10); CPU-sized.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Robustness knobs pinned before any lux_tpu import so flag reads and
# module wiring see them: fast retry, a 3-failure breaker with a short
# cooldown, and a WAL armed in a scratch dir.
os.environ.setdefault("LUX_PLATFORM", "cpu")
os.environ["LUX_RETRY_MAX"] = "1"
os.environ["LUX_RETRY_BACKOFF_MS"] = "10"
os.environ["LUX_BREAKER_THRESHOLD"] = "3"
os.environ["LUX_BREAKER_COOLDOWN_MS"] = "400"
WAL_DIR = tempfile.mkdtemp(prefix="lux-chaos-wal-")
os.environ["LUX_WAL_DIR"] = WAL_DIR

import numpy as np  # noqa: E402

BURST_FAULTS = (
    "serve.engine.execute:raise:0.25,"
    "pool.build:delay_ms:1.0:5,"
    "wal.fsync:delay_ms:1.0:5,"
    "snapshot.warm:delay_ms:1.0:5,"
    "batcher.assemble:delay_ms:0.5:2,"
    "cache.put:raise:0.5"
)


def _post(base, path, payload, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers)
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, dict(e.headers)


def _requests_by_code(metrics):
    out = {}
    for code in ("200", "400", "429", "500", "503", "504"):
        v = metrics.counter("lux_requests_total", {"code": code}).value
        if v:
            out[code] = int(v)
    return out


def _transitions(metrics):
    return {
        s: int(metrics.counter("lux_breaker_transitions_total",
                               {"to": s}).value)
        for s in ("open", "half_open", "closed")
    }


def main() -> int:
    from lux_tpu.utils import flags

    scale = flags.get_int("LUX_SMOKE_SCALE")

    import jax

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.graph import EdgeEdits, SnapshotStore, generate
    from lux_tpu.obs import metrics
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.serve.http import serve_in_thread
    from lux_tpu.utils import faults

    g = generate.rmat(scale, 8, seed=7)
    cfg = ServeConfig(max_batch=4, window_s=0.02, max_queue=512,
                      pagerank_iters=3)
    session = Session(g, cfg)
    server, _ = serve_in_thread(session)
    base = "http://127.0.0.1:%d" % server.server_address[1]
    rng = np.random.default_rng(23)

    def edit_payload(n):
        return {"insert": [[int(rng.integers(g.nv)), int(rng.integers(g.nv))]
                           for _ in range(n)]}

    # ---- Phase A: seeded burst with every fault point armed -------------
    before_codes = _requests_by_code(metrics)
    faults.arm(BURST_FAULTS, seed=flags.get_int("LUX_FAULTS_SEED"))
    jobs = ([{"app": "sssp", "start": int(r)}
             for r in rng.integers(0, g.nv, size=24)]
            + [{"app": "components"}] * 6
            + [{"app": "pagerank"}] * 6)
    issued = []

    def one_query(body):
        code, _ = _post(base, "/query", body)
        return code

    with ThreadPoolExecutor(max_workers=8) as tp:
        futs = [tp.submit(one_query, j) for j in jobs[: len(jobs) // 2]]
        # Mid-burst durable writes: two queued batches + one flush-swap
        # race the second half of the burst through the drain barrier.
        issued.append(_post(base, "/snapshot",
                            {**edit_payload(4), "queue": True})[0])
        issued.append(_post(base, "/snapshot",
                            {**edit_payload(4), "queue": True})[0])
        issued.append(_post(base, "/snapshot", {"flush": True})[0])
        futs += [tp.submit(one_query, j) for j in jobs[len(jobs) // 2:]]
        # .result() below would hang forever on a lost future — the
        # timeout IS the no-hangs assertion.
        issued += [f.result(timeout=300) for f in futs]
    faults.disarm()

    assert len(issued) == len(jobs) + 3, "a request never came back"
    after_codes = _requests_by_code(metrics)
    deltas = {c: after_codes.get(c, 0) - before_codes.get(c, 0)
              for c in set(before_codes) | set(after_codes)}
    deltas = {c: n for c, n in deltas.items() if n}
    assert sum(deltas.values()) == len(issued), (
        f"terminal statuses {deltas} do not sum to {len(issued)} issued")
    injected_burst = dict(faults.counts())
    assert injected_burst, "the armed burst never injected anything"

    # Let any in-flight breaker state from the burst settle before the
    # deterministic lifecycle phase (the probe heals open keys).
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        code, _ = _post(base, "/query", {"app": "sssp", "start": 0})
        if code == 200:
            break
        session.breaker.drain_probes()
        time.sleep(0.2)
    else:
        raise AssertionError("breaker never settled after the burst")

    # ---- Phase B: breaker open -> half_open -> closed -------------------
    t_before = _transitions(metrics)
    faults.arm("serve.engine.execute:raise:1.0")
    codes_b = []
    saw_retry_after = False
    for i in range(1, 8):
        code, hdrs = _post(base, "/query",
                           {"app": "sssp", "start": int(g.nv // 2 + i)})
        codes_b.append(code)
        if code == 503:
            assert float(hdrs.get("Retry-After", 0)) > 0, \
                "503 without Retry-After"
            saw_retry_after = True
            break
    assert saw_retry_after, f"breaker never opened: {codes_b}"
    faults.disarm()
    time.sleep(0.45)                       # cooldown elapses
    code, _ = _post(base, "/query", {"app": "sssp", "start": 1})
    session.breaker.drain_probes()         # half-open probe completes
    code, _ = _post(base, "/query", {"app": "sssp", "start": 2})
    assert code == 200, f"breaker did not close after probe (got {code})"
    t_after = _transitions(metrics)
    for s in ("open", "half_open", "closed"):
        assert t_after[s] > t_before[s], (
            f"breaker never reached {s}: {t_before} -> {t_after}")

    # ---- Phase C: crash mid-swap, recover, steady-state -----------------
    faults.arm("snapshot.warm:crash:1.0")
    crashed = False
    try:
        session.apply_edits(EdgeEdits.from_lists(
            insert=[[int(rng.integers(g.nv)), int(rng.integers(g.nv))]
                    for _ in range(4)]))
    except faults.CrashPoint:
        crashed = True
    faults.disarm()
    assert crashed, "CrashPoint was absorbed before the harness"
    head = session.store.current()
    pre_crash_version, pre_crash_fp = head.version, head.fingerprint
    assert pre_crash_version > session.version, \
        "crash fired after the flip, not between mint and flip"
    server.shutdown()
    session.close()

    base_graph = generate.rmat(scale, 8, seed=7)   # what a restart loads
    store = SnapshotStore.recover(base_graph, WAL_DIR)
    rhead = store.current()
    assert rhead.version == pre_crash_version, \
        f"recovered v{rhead.version}, expected v{pre_crash_version}"
    assert rhead.fingerprint == pre_crash_fp, "WAL replay parity violated"

    session2 = Session(store, cfg)          # warm=True: fresh warmup
    roots = [int(r) for r in rng.integers(0, rhead.graph.nv, size=12)]
    for r in roots:
        session2.query("sssp", start=r, timeout=300)
    session2.query("components", timeout=300)
    session2.query("pagerank", timeout=300)
    for r in roots:                          # steady state: all cached/warm
        session2.query("sssp", start=r, timeout=300)
    session2.pool.sentinel.assert_zero_recompiles()
    recompiles = session2.pool.stats()["recompiles"]
    assert recompiles == 0, f"{recompiles} steady-state recompiles"
    wal_stats = store.wal_stats()
    session2.close()

    print(f"chaos-stress PASS ({len(issued)} burst requests all terminal, "
          f"breaker open->half_open->closed, crash recovered to "
          f"v{rhead.version} bitwise, 0 steady-state recompiles)")
    print(json.dumps({
        "schema": "chaos_stress.v1",
        "graph": {"scale": scale, "nv": g.nv, "ne": g.ne},
        "burst": {"issued": len(issued), "codes": deltas,
                  "faults": BURST_FAULTS,
                  "injected": injected_burst},
        "breaker": {"transitions": {s: t_after[s] - t_before[s]
                                    for s in t_after}},
        "recovery": {"version": rhead.version,
                     "fingerprint": rhead.fingerprint[:12],
                     "wal_records": wal_stats["records"] if wal_stats
                     else None,
                     "parity": True},
        "steady_state_recompiles": 0,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
