#!/usr/bin/env python3
"""Edge-list → ``.lux`` converter CLI.

Same interface as the reference tool (tools/converter.cc:16-70):

    python tools/converter.py -nv NV -ne NE -input edges.txt -output g.lux

plus ``-weighted`` for 3-column (src dst weight) inputs. Uses the native
C++ fast path when available, falling back to numpy.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__, prefix_chars="-")
    p.add_argument("-nv", type=int, required=True, help="number of vertices")
    p.add_argument("-ne", type=int, required=True, help="number of edges")
    p.add_argument("-input", required=True, help="text edge list (src dst [w])")
    p.add_argument("-output", required=True, help="output .lux path")
    p.add_argument("-weighted", action="store_true")
    args = p.parse_args(argv)
    print(
        f"nv = {args.nv} ne = {args.ne} input = {args.input} "
        f"output = {args.output}"
    )
    t0 = time.time()
    from lux_tpu.native import io as native_io

    native_io.convert_edge_list(
        args.input, args.output, args.nv, args.ne, weighted=args.weighted
    )
    print(f"converted in {time.time() - t0:.2f}s")


if __name__ == "__main__":
    sys.exit(main())
