#!/usr/bin/env python3
"""Compacted-exchange smoke test (`make exchange-smoke`).

End-to-end acceptance run for the needed-rows compacted exchange
(ISSUE 13), on a 2x4 virtual CPU mesh (8 XLA host devices — the same
trick the serving smoke uses, so this runs in CI with no TPU):

1. generate a halo-exchange locality graph (uniform per-pair needed
   rows — the regime the compaction targets) and run SSSP (sharded
   push) and PageRank (sharded pull) under LUX_EXCHANGE=full and
   LUX_EXCHANGE=compact;
2. prove parity: both apps BIT-IDENTICAL between the two modes (the
   local/remote select happens before the unchanged segment reduction,
   so even float sum order is preserved);
3. prove the ledger: ``exchange_bytes_per_iter`` drops >= 5x under
   compact (SSSP's per-iteration exchange is static, so the late
   frontier-sparse tail pays the same compacted bytes as iteration 1),
   with useful_ratio >= 0.8 compact where full prices < 0.3;
4. prove the zero-recompile contract: warm re-runs of every engine
   trace nothing (RecompileSentinel, expect windows only around builds
   and first runs);
5. prove observability: a phase-fenced LUX_ENGOBS=1 run of the compact
   engines reports ``exchange_hidden_frac`` (the overlap budget).

Prints an ``exchange_smoke.v1`` JSON document on the last line.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MESH = "2x4"
PARTS = 8
BLOCK_SPAN = 512
HUBS = 23          # per-pair needed rows; 23 express + 1 chain-boundary
PR_ITERS = 8       # fixed-iteration pagerank parity run
DROP_FLOOR = 5.0   # required full/compact exchange-bytes ratio


def log(msg):
    print(f"# {msg}", flush=True)


def main() -> int:
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    from lux_tpu.utils.platform import virtual_cpu_flags

    os.environ["XLA_FLAGS"] = virtual_cpu_flags(PARTS)
    import jax

    from lux_tpu.utils import flags

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.analysis.sentinel import RecompileSentinel
    from lux_tpu.engine.pull_sharded import ShardedPullExecutor
    from lux_tpu.engine.push import ShardedPushExecutor
    from lux_tpu.graph import generate
    from lux_tpu.models import PageRank, SSSP
    from lux_tpu.obs import engobs
    from lux_tpu.parallel.mesh import make_mesh

    g = generate.halo(PARTS, BLOCK_SPAN, hubs=HUBS, weighted=True)
    mesh = make_mesh(PARTS)
    sent = RecompileSentinel("exchange-smoke")
    log(f"halo graph nv={g.nv} ne={g.ne} on a {MESH} virtual mesh "
        f"({PARTS} XLA host devices)")

    def build_run(key, build, run):
        """Build + first run under an expect window (compiles are
        budgeted there), then a warm re-run under watch (any compile is
        a sentinel failure)."""
        with sent.expect(key):
            ex = build()
            first = run(ex)
        with sent.watch(key):
            warm = run(ex)
        return ex, first, warm

    doc = {"schema": "exchange_smoke.v1",
           "graph": {"kind": "halo", "nv": g.nv, "ne": g.ne,
                     "hubs": HUBS},
           "mesh": {"spec": MESH, "num_parts": PARTS}}

    # -- 1+2: bitwise parity, full vs compact ---------------------------
    apps = {}
    for app, build, run in (
        ("sssp",
         lambda: ShardedPushExecutor(g, SSSP(), mesh=mesh),
         lambda ex: ex.run(start=0)),
        ("pagerank",
         lambda: ShardedPullExecutor(g, PageRank(), mesh=mesh),
         lambda ex: (ex.run(PR_ITERS, flush_every=0), None)),
    ):
        got = {}
        for mode in ("full", "compact"):
            os.environ["LUX_EXCHANGE"] = mode
            ex, (out, iters), _ = build_run(f"{app}-{mode}", build, run)
            assert ex.exchange_mode == mode, (
                f"{app}: requested {mode}, resolved {ex.exchange_mode} "
                "(plan unprofitable on this graph?)")
            got[mode] = {
                "values": ex.gather_values(out),
                "iters": iters,
                "bytes": ex.exchange_bytes_per_iter(),
                "ex": ex,
            }
        np.testing.assert_array_equal(
            got["full"]["values"], got["compact"]["values"],
            err_msg=f"{app}: full vs compact diverged")
        assert got["full"]["iters"] == got["compact"]["iters"]
        apps[app] = got
        log(f"{app}: full and compact bit-identical "
            f"({got['full']['iters'] or PR_ITERS} iters)")

    # -- 3: exchange ledger ---------------------------------------------
    ledger = {}
    for app, row_bytes in (("sssp", 5), ("pagerank", 4)):
        ex_c = apps[app]["compact"]["ex"]
        b_full = apps[app]["full"]["bytes"]
        b_comp = apps[app]["compact"]["bytes"]
        drop = b_full / b_comp
        full_led = engobs.useful_exchange(ex_c.sg, row_bytes)
        comp_led = engobs.useful_exchange(
            ex_c.sg, row_bytes,
            exchanged_rows=ex_c._xplan.exchanged_units_per_iter)
        ledger[app] = {
            "bytes_full": b_full, "bytes_compact": b_comp,
            "drop": round(drop, 1),
            "useful_ratio_full": round(full_led["ratio"], 3),
            "useful_ratio_compact": round(comp_led["ratio"], 3),
        }
        assert drop >= DROP_FLOOR, (
            f"{app}: exchange bytes dropped only {drop:.1f}x "
            f"({b_full} -> {b_comp}); need >= {DROP_FLOOR}x")
        assert full_led["ratio"] < 0.3 and comp_led["ratio"] >= 0.8, ledger
        log(f"{app}: exchange {b_full} -> {b_comp} B/iter "
            f"({drop:.1f}x), useful_ratio {full_led['ratio']:.3f} -> "
            f"{comp_led['ratio']:.3f}")
    doc["ledger"] = ledger

    # -- 3.5: static exchange-tier verification of the live plans -------
    # The same proof `make lint-exchange` runs, but against THESE
    # engines' plans with the full evidence chain (counts, pricing,
    # ledger): the smoke must never pass on a plan luxlint would flag.
    from lux_tpu.analysis import exchck

    for app, row_bytes in (("sssp", 5), ("pagerank", 4)):
        ex_c = apps[app]["compact"]["ex"]
        view = exchck.plan_view(
            ex_c._xplan,
            remote_read_counts=ex_c.sg.remote_read_counts(),
            row_bytes=row_bytes,
            declared_bytes_per_iter=ex_c.exchange_bytes_per_iter(),
            ledger=engobs.useful_exchange(
                ex_c.sg, row_bytes,
                exchanged_rows=ex_c._xplan.exchanged_units_per_iter))
        res = exchck.verify_exchange_plan(view, f"smoke@{app}")
        assert not res.findings and res.error is None, (
            [f.format() for f in res.findings], res.error)
    doc["exchange_lint_findings"] = 0
    log("exchck: LUX401-403 clean on both live compact plans "
        "(structure, permutation proof, pricing)")

    # -- 4: zero recompiles on every warm path --------------------------
    sent.assert_zero_recompiles()
    doc["recompiles"] = sent.recompiles()
    log("sentinel: 0 recompiles outside expect windows across "
        f"{len(apps) * 2} warm engine re-runs")

    # -- 5: phase-fenced observability (LUX_ENGOBS=1) -------------------
    os.environ["LUX_EXCHANGE"] = "compact"
    os.environ["LUX_ENGOBS"] = "1"
    try:
        engobs.reset()
        with sent.expect("sssp-compact-phased"):
            ex = ShardedPushExecutor(g, SSSP(), mesh=mesh)
            ex.run(start=0)
        hidden = {
            name: tel["run_exchange_hidden_frac"]
            for name, tel in engobs.latest().items()
            if tel.get("run_exchange_hidden_frac") is not None
        }
        assert hidden, (
            "LUX_ENGOBS=1 compact run reported no exchange_hidden_frac: "
            f"{engobs.latest()}")
        for name, frac in hidden.items():
            assert 0.0 <= frac <= 1.0, (name, frac)
        doc["exchange_hidden_frac"] = {
            k: round(v, 3) for k, v in hidden.items()}
        # The key stays `exchange_hidden_frac` for artifact
        # compatibility, but it is a BUDGET (upper bound): phase fencing
        # serializes the overlap it prices. The device-measured number
        # is `realized_hidden_frac` from a profile.v1 capture
        # (obs/prof.py) — surfaced next to the budget when one exists.
        doc["exchange_hidden_frac_note"] = "budget (upper bound)"
        from lux_tpu.obs import prof

        realized = prof.latest_realized()
        if realized is not None:
            doc["realized_hidden_frac"] = round(realized, 3)
        log(f"engobs: exchange_hidden_frac={doc['exchange_hidden_frac']} "
            "— budget (upper bound); device-measured realized_hidden_frac"
            f"={realized if realized is not None else 'n/a (no profile)'}"
            " via obs/prof.py capture windows")
    finally:
        del os.environ["LUX_ENGOBS"]
        del os.environ["LUX_EXCHANGE"]

    sent.close()
    print("exchange-smoke PASS (bitwise parity, >=5x exchange-byte "
          "drop, zero recompiles, hidden-frac reported)")
    print(json.dumps(doc, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
