#!/usr/bin/env python3
"""Render a flight.v1 postmortem dump (obs/flight.py) for humans.

The flight recorder writes self-contained JSON: the last N completed
request traces, the last N engine iteration records, a metrics-registry
snapshot, per-component context (sentinel/pool/batcher/cache stats),
and the LUX_* flag table — everything needed to ask "what was the
server doing when it shed that request" without reproducing anything.

    python tools/flight_summary.py /var/tmp/flight/flight-...-deadline_shed.json
    python tools/flight_summary.py /var/tmp/flight            # latest dump
    python tools/flight_summary.py dump.json --traces 5 --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def resolve(path: str) -> str:
    """A dump file, or the newest flight-*.json inside a directory."""
    if os.path.isdir(path):
        cands = sorted(
            f for f in os.listdir(path)
            if f.startswith("flight-") and f.endswith(".json")
        )
        if not cands:
            raise SystemExit(f"flight_summary: no flight-*.json in {path}")
        # Filenames embed a ms timestamp, so lexicographic == temporal.
        return os.path.join(path, cands[-1])
    return path


def load(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != "flight.v1":
        raise SystemExit(
            f"flight_summary: {path} is not a flight.v1 dump "
            f"(schema={doc.get('schema')!r})"
        )
    return doc


def fmt_trace(t: dict) -> list:
    lines = [f"  trace {t.get('trace_id')}  "
             f"total {t.get('duration_s', 0) * 1e3:.2f} ms"]
    for s in t.get("spans", []):
        attrs = s.get("attrs") or {}
        extra = "  " + " ".join(
            f"{k}={v}" for k, v in sorted(attrs.items())
        ) if attrs else ""
        lines.append(
            f"    {s.get('dur_s', 0) * 1e3:9.3f} ms  {s.get('name'):<22}"
            f" [{s.get('thread', '?')}]{extra}"
        )
    return lines


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="flight.v1 dump file, or a directory "
                    "(LUX_FLIGHT_DIR) to pick the latest from")
    ap.add_argument("--traces", type=int, default=3,
                    help="newest traces to expand (default 3)")
    ap.add_argument("--iters", type=int, default=8,
                    help="newest iteration records to list (default 8)")
    ap.add_argument("--json", action="store_true",
                    help="re-emit the parsed dump as one JSON line "
                    "(validation / piping)")
    args = ap.parse_args()

    path = resolve(args.path)
    doc = load(path)
    if args.json:
        print(json.dumps(doc, sort_keys=True))
        return 0

    print(f"flight.v1  {path}")
    print(f"reason: {doc.get('reason')}"
          + (f"  ({doc['detail']})" if doc.get("detail") else ""))
    print(f"pid {doc.get('pid')}  unix_time {doc.get('unix_time_s')}")

    traces = doc.get("traces") or []
    iters = doc.get("iterations") or []
    print(f"\ntraces: {len(traces)} recorded "
          f"(showing newest {min(args.traces, len(traces))})")
    for t in traces[-args.traces:]:
        for line in fmt_trace(t):
            print(line)

    print(f"\niterations: {len(iters)} recorded "
          f"(showing newest {min(args.iters, len(iters))})")
    for r in iters[-args.iters:]:
        wall = r.get("t_iter_s")
        wall_str = f"{wall * 1e3:9.3f} ms" if isinstance(
            wall, (int, float)) else f"{'?':>9}   "
        print(f"  {wall_str}  {r.get('engine', '?'):<12} "
              f"{r.get('program', '?'):<12} iter={r.get('iter', '?')} "
              f"frontier={r.get('frontier', '?')}")

    ctx = doc.get("context") or {}
    if ctx:
        print("\ncontext:")
        for name, val in sorted(ctx.items()):
            blob = json.dumps(val, sort_keys=True, default=str)
            print(f"  {name}: {blob}")

    m = doc.get("metrics") or []
    interesting = [x for x in m if x["kind"] != "histogram"
                   and float(x.get("value", 0)) != 0]
    if interesting:
        print(f"\nmetrics (nonzero counters/gauges, of {len(m)} total):")
        for x in interesting:
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(x["labels"].items()))
            print(f"  {x['name']}{'{' + lbl + '}' if lbl else ''} "
                  f"= {x['value']}")

    fl = doc.get("flags") or {}
    set_flags = {k: v for k, v in sorted(fl.items()) if v is not None}
    if set_flags:
        print("\nflags (set in environment):")
        for k, v in set_flags.items():
            print(f"  {k}={v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
