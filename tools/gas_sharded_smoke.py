#!/usr/bin/env python3
"""Sharded GAS serving smoke test (`make gas-sharded-smoke`).

End-to-end acceptance run for the direction-adaptive sharded GAS
engine (ISSUE 17), on a 2x4 virtual CPU mesh with
``LUX_EXCHANGE=frontier`` — the frontier-aware compact exchange:

1. start one warm sharded session over HTTP; every served app now
   builds its mesh engine (the per-chip GAS fallback is gone — any
   drop to a single-device build is counted and fails this smoke);
2. oracle-check every registry program: bfs (depth + parent), sssp,
   sssp_delta, components, labelprop, kcore at two k values, pagerank
   (allclose: float sum order), plus colfilter engine-level (not
   servable over HTTP: it needs a bipartite ratings graph) — bitwise
   where integral;
3. assert the single-lane adaptive BFS reports >= 1 mid-run
   push<->pull direction switch (scale >= 9) and concurrent BFS roots
   batch through the sharded multi-source engine;
4. assert the mesh-fallback surface is clean: /statusz ``fallbacks``
   empty, no warning, ``lux_serve_mesh_fallback_total`` at zero;
5. assert gas pool keys carry the mesh shape + exchange mode and the
   RecompileSentinel saw zero serve-phase recompiles (direction
   switches and frontier<->compact downgrades share one executable);
6. report the frontier-vs-compact per-iteration exchange-byte budget
   from the live plan (the PERF.md evidence).

Emits a ``gas_sharded_smoke.v1`` JSON line on success. Scale with
LUX_SMOKE_SCALE (default 10).
"""

from __future__ import annotations

import json
import os
import sys
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import urllib.request

MESH = "2x4"
PARTS = 8


def post(base, payload, timeout=300):
    req = urllib.request.Request(
        base + "/query", json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def main() -> int:
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    # Engines trace the exchange mode at build time: set it before the
    # session warms anything.
    os.environ["LUX_EXCHANGE"] = "frontier"
    from lux_tpu.utils.platform import virtual_cpu_flags

    os.environ["XLA_FLAGS"] = virtual_cpu_flags(PARTS)
    import jax

    from lux_tpu.utils import flags

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.engine.gas import AdaptiveExecutor, as_gas
    from lux_tpu.engine.gas_sharded import ShardedAdaptiveExecutor
    from lux_tpu.graph import generate
    from lux_tpu.models import get_program
    from lux_tpu.models.bfs import reference_bfs
    from lux_tpu.models.components import reference_components
    from lux_tpu.models.kcore import reference_kcore
    from lux_tpu.models.labelprop import reference_labelprop
    from lux_tpu.models.pagerank import reference_pagerank
    from lux_tpu.models.sssp import reference_sssp
    from lux_tpu.models.sssp_delta import reference_sssp_delta
    from lux_tpu.obs import metrics
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.serve.http import serve_in_thread

    scale = flags.get_int("LUX_SMOKE_SCALE")
    g = generate.undirected(generate.rmat(scale, 8, seed=3, weighted=True))

    session = Session(g, ServeConfig(max_batch=4, window_s=0.05,
                                     max_queue=256, pagerank_iters=5,
                                     mesh=MESH))
    server, _ = serve_in_thread(session, port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    assert session.meshspec.num_parts == PARTS, session.meshspec
    apps = set(session.APPS)
    assert {"bfs", "sssp", "sssp_delta", "components", "pagerank",
            "labelprop", "kcore"} <= apps, apps
    print(f"serving rmat scale={scale} (nv={g.nv} ne={g.ne}) on a "
          f"{MESH} mesh at {base}, LUX_EXCHANGE=frontier, "
          f"apps={sorted(apps)}")

    # -- single-lane adaptive BFS: the direction-switch acceptance -------
    bfs1 = post(base, {"app": "bfs", "start": 1, "full": True})
    depth, parent = reference_bfs(g, 1)
    np.testing.assert_array_equal(
        np.asarray(bfs1["values"], np.uint32), depth)
    np.testing.assert_array_equal(
        np.asarray(bfs1["parent"], np.int64), parent)
    assert bfs1["direction_push"] + bfs1["direction_pull"] == bfs1["iters"]
    if scale >= 9:
        assert bfs1["direction_switches"] >= 1, (
            f"adaptive sharded BFS never switched direction: "
            f"{bfs1['iters']} iters, push={bfs1['direction_push']} "
            f"pull={bfs1['direction_pull']}"
        )
    print(f"bfs[start=1] on the mesh: {bfs1['iters']} iters, "
          f"push={bfs1['direction_push']} pull={bfs1['direction_pull']} "
          f"switches={bfs1['direction_switches']}, depth+parent == oracle")

    # -- concurrent BFS roots: the sharded multi-source batch ------------
    roots = [2, 3, 4, 5]
    with ThreadPoolExecutor(max_workers=len(roots)) as tp:
        outs = [f.result() for f in
                [tp.submit(post, base, {"app": "bfs", "start": r,
                                        "full": True}) for r in roots]]
    for r, out in zip(roots, outs):
        d, p = reference_bfs(g, r)
        np.testing.assert_array_equal(np.asarray(out["values"],
                                                 np.uint32), d)
        np.testing.assert_array_equal(np.asarray(out["parent"],
                                                 np.int64), p)
    print(f"bfs x{len(roots)} concurrent roots: sharded lanes bitwise "
          "== per-root oracle")

    # -- the rest of the registry over HTTP ------------------------------
    sd = post(base, {"app": "sssp_delta", "start": 0, "full": True})
    np.testing.assert_array_equal(
        np.asarray(sd["values"], np.float32), reference_sssp_delta(g, 0))
    ss = post(base, {"app": "sssp", "start": 1, "full": True})
    np.testing.assert_array_equal(
        np.asarray(ss["values"], np.uint32), reference_sssp(g, 1))
    cc = post(base, {"app": "components", "full": True})
    np.testing.assert_array_equal(
        np.asarray(cc["values"], np.uint32), reference_components(g))
    lp = post(base, {"app": "labelprop", "full": True})
    np.testing.assert_array_equal(
        np.asarray(lp["values"], np.uint32), reference_labelprop(g))
    kc_sizes = {}
    for k in (2, 3):
        kc = post(base, {"app": "kcore", "k": k, "full": True})
        np.testing.assert_array_equal(
            np.asarray(kc["values"], np.uint32), reference_kcore(g, k))
        kc_sizes[k] = kc["core_size"]
    pr = post(base, {"app": "pagerank", "full": True})
    assert np.allclose(pr["values"], reference_pagerank(g, 5),
                       rtol=2e-5), "pagerank diverged"
    print(f"sssp + sssp_delta + components + labelprop + "
          f"kcore[k=2,3] bitwise == oracles; pagerank allclose; "
          f"kcore core sizes {kc_sizes}")

    # -- colfilter: engine-level (needs a bipartite ratings graph, so
    # it is not servable over HTTP; the mesh engine still must match
    # the single-device executor bitwise) --------------------------------
    ex = ShardedAdaptiveExecutor(g, get_program("colfilter"),
                                 num_parts=PARTS)
    st, _ = ex.run(max_iters=4)
    ref = AdaptiveExecutor(g, as_gas(get_program("colfilter")))
    rst, _ = ref.run(max_iters=4)
    np.testing.assert_array_equal(
        ex.gather_values(st), np.asarray(jax.device_get(rst.values)))
    print("colfilter engine-level: mesh bitwise == single-device "
          "(frontier-less: exchange honestly downgraded to "
          f"{ex.exchange_mode})")

    # -- mesh-fallback surface is clean ----------------------------------
    stats = get(base, "/stats")
    mesh = stats["mesh"]
    assert mesh["fallbacks"] == {}, mesh["fallbacks"]
    assert "warning" not in mesh, mesh
    fb = sum(m["value"] for m in metrics.snapshot()
             if m["name"] == "lux_serve_mesh_fallback_total")
    assert fb == 0, f"mesh fallback counter nonzero: {fb}"
    print("mesh fallbacks: none (statusz clean, "
          "lux_serve_mesh_fallback_total == 0)")

    # -- pool discipline: mesh-keyed gas engines, zero recompiles --------
    gas_keys = [k for k in session.pool.keys()
                if str(k[0]).startswith("gas")]
    assert gas_keys, "no sharded gas engines in the pool"
    assert all(k[-1] == (2, 4) for k in gas_keys), gas_keys
    assert all("frontier" in k for k in gas_keys), gas_keys
    recompiles = stats["pool"]["recompiles"]
    assert recompiles == 0, (
        f"RecompileSentinel saw {recompiles} XLA compile(s) in the "
        "post-warmup query phase (direction switches and frontier "
        "downgrades must share one executable)")
    session.pool.sentinel.assert_zero_recompiles()
    print(f"pool: {len(gas_keys)} gas engines keyed by mesh+exchange "
          f"mode, sentinel recompiles {recompiles}")

    # -- frontier-vs-compact exchange-byte budget (PERF evidence) --------
    bfs_ex = session._gas_single("bfs")
    assert bfs_ex.exchange_mode == "frontier"
    fe = bfs_ex.frontier_evidence()
    compact_bytes = bfs_ex.exchange_bytes_per_iter()
    frontier_bytes = fe["frontier_bytes_per_iter"]
    reduction = compact_bytes / max(1, frontier_bytes)
    assert frontier_bytes < compact_bytes, (fe, compact_bytes)
    ebytes = session.mesh_exchange_bytes()
    for key in ("gas_bfs", "gas_sssp_delta", "gas_labelprop",
                "gas_kcore"):
        assert key in ebytes and ebytes[key] > 0, (key, ebytes)
    print(f"exchange budget/iter: compact {compact_bytes} B -> frontier "
          f"{frontier_bytes} B ({reduction:.1f}x smaller admitted send, "
          f"capacity {fe['frontier_capacity']} rows/pair)")

    server.shutdown()
    session.close()

    print(json.dumps({
        "schema": "gas_sharded_smoke.v1",
        "scale": scale,
        "nv": int(g.nv),
        "ne": int(g.ne),
        "mesh": MESH,
        "exchange_mode": "frontier",
        "apps": sorted(apps) + ["colfilter (engine-level)"],
        "bfs": {
            "iters": bfs1["iters"],
            "direction_push": bfs1["direction_push"],
            "direction_pull": bfs1["direction_pull"],
            "direction_switches": bfs1["direction_switches"],
        },
        "kcore_sizes": {str(k): v for k, v in kc_sizes.items()},
        "mesh_fallbacks": 0,
        "recompiles": recompiles,
        "exchange_bytes_per_iter": {
            "compact": int(compact_bytes),
            "frontier": int(frontier_bytes),
            "reduction": round(reduction, 2),
        },
    }))
    print("gas-sharded-smoke PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
