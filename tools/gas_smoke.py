#!/usr/bin/env python3
"""GAS serving smoke test (`make gas-smoke`).

End-to-end acceptance run for the GAS subsystem (ISSUE 12):

1. generate a weighted undirected RMAT graph and start the HTTP server
   on an ephemeral port (every registry app's engines warmed before
   traffic — bfs/sssp_delta single + multi-lane, labelprop, kcore);
2. issue one single-lane adaptive BFS query and assert the response's
   per-iteration direction telemetry shows >= 1 mid-run push<->pull
   switch (scale >= 9; tiny graphs may legitimately never switch);
3. issue concurrent BFS root queries (multi-source batch), one
   sssp_delta root, labelprop, and kcore at two k values, all through
   the HTTP front end with ``full`` payloads;
4. validate every response against the host numpy oracles — BFS
   depth+parent, Dijkstra distances, label-propagation labels, k-core
   frozen degrees + alive mask — bitwise where integral;
5. assert the pool miss counter stayed flat across the query phase for
   warmed engines (the only allowed build is the non-default kcore k)
   and the RecompileSentinel saw zero serve-phase recompiles;
6. assert ``/statusz`` carries the ``gas`` direction-split block.

Emits a ``gas_smoke.v1`` JSON line on success. Scale with
LUX_SMOKE_SCALE (default 10).
"""

from __future__ import annotations

import json
import os
import sys
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import urllib.request


def post(base, payload, timeout=180):
    req = urllib.request.Request(
        base + "/query", json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def main() -> int:
    from lux_tpu.utils import flags

    scale = flags.get_int("LUX_SMOKE_SCALE")

    os.environ.setdefault("LUX_PLATFORM", "cpu")
    import jax

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.graph import generate
    from lux_tpu.models.bfs import reference_bfs
    from lux_tpu.models.kcore import reference_kcore
    from lux_tpu.models.labelprop import reference_labelprop
    from lux_tpu.models.sssp_delta import reference_sssp_delta
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.serve.http import serve_in_thread

    g = generate.undirected(generate.rmat(scale, 8, seed=3, weighted=True))
    cfg = ServeConfig(max_batch=4, window_s=0.5, max_queue=256)
    session = Session(g, cfg)
    server, _ = serve_in_thread(session, port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    health = get(base, "/healthz")
    assert health["ok"] and health["nv"] == g.nv, health
    apps = set(session.APPS)
    assert {"bfs", "sssp_delta", "labelprop", "kcore"} <= apps, apps
    print(f"server up: nv={health['nv']} ne={health['ne']} "
          f"engines={health['engines']} apps={sorted(apps)}")

    misses_before = get(base, "/stats")["pool"]["misses"]

    # -- single-lane adaptive BFS: the direction-switch acceptance -------
    bfs1 = post(base, {"app": "bfs", "start": 1, "full": True})
    depth, parent = reference_bfs(g, 1)
    np.testing.assert_array_equal(
        np.asarray(bfs1["values"], dtype=np.uint32), depth)
    np.testing.assert_array_equal(
        np.asarray(bfs1["parent"], dtype=np.int64), parent)
    assert bfs1["direction_push"] + bfs1["direction_pull"] == bfs1["iters"]
    if scale >= 9:
        assert bfs1["direction_switches"] >= 1, (
            f"adaptive BFS never switched direction: {bfs1['iters']} iters, "
            f"push={bfs1['direction_push']} pull={bfs1['direction_pull']}"
        )
    print(f"bfs[start=1]: {bfs1['iters']} iters, "
          f"push={bfs1['direction_push']} pull={bfs1['direction_pull']} "
          f"switches={bfs1['direction_switches']}, depth+parent == oracle")

    # -- concurrent BFS roots: multi-source GAS batch --------------------
    roots = [2, 3, 4, 5]
    with ThreadPoolExecutor(max_workers=len(roots)) as tp:
        futs = [tp.submit(post, base, {"app": "bfs", "start": r,
                                       "full": True}) for r in roots]
        outs = [f.result() for f in futs]
    for r, out in zip(roots, outs):
        d, p = reference_bfs(g, r)
        np.testing.assert_array_equal(
            np.asarray(out["values"], dtype=np.uint32), d)
        np.testing.assert_array_equal(
            np.asarray(out["parent"], dtype=np.int64), p)
    print(f"bfs x{len(roots)} concurrent roots: batched lanes bitwise == "
          "per-root oracle")

    # -- weighted delta-SSSP ---------------------------------------------
    sd = post(base, {"app": "sssp_delta", "start": 0, "full": True})
    np.testing.assert_array_equal(
        np.asarray(sd["values"], dtype=np.float32),
        reference_sssp_delta(g, 0))
    print(f"sssp_delta[start=0]: {sd['iters']} iters, bitwise == Dijkstra")

    # -- label propagation -----------------------------------------------
    lp = post(base, {"app": "labelprop", "full": True})
    np.testing.assert_array_equal(
        np.asarray(lp["values"], dtype=np.uint32), reference_labelprop(g))
    print(f"labelprop: {lp['iters']} iters, "
          f"{lp['num_communities']} communities, bitwise == oracle")

    # -- k-core at the warmed default k and one cold k -------------------
    kc_results = {}
    for k in (2, 3):
        kc = post(base, {"app": "kcore", "k": k, "full": True})
        ref = reference_kcore(g, k)
        np.testing.assert_array_equal(
            np.asarray(kc["values"], dtype=np.uint32), ref)
        np.testing.assert_array_equal(
            np.asarray(kc["alive"], dtype=np.uint8),
            (ref >= k).astype(np.uint8))
        kc_results[k] = kc["core_size"]
        print(f"kcore[k={k}]: core_size={kc['core_size']}, "
              "frozen degrees + alive mask bitwise == peeling oracle")

    # -- pool discipline: no builds beyond the declared cold k=3 engine --
    stats = get(base, "/stats")
    misses_after = stats["pool"]["misses"]
    assert misses_after <= misses_before + 1, (
        f"unexpected engine builds during the query phase: "
        f"{misses_before} -> {misses_after} (allowed: +1 for kcore k=3)"
    )
    recompiles = stats["pool"].get("recompiles", 0)
    assert recompiles == 0, (
        f"RecompileSentinel saw {recompiles} XLA compile(s) in the "
        "post-warmup query phase"
    )
    print(f"warm pool: {stats['pool']['engines']} engines, miss count "
          f"{misses_before} -> {misses_after} (cold kcore k=3 only), "
          f"sentinel recompiles {recompiles}")

    # -- /statusz direction-split block ----------------------------------
    sz = get(base, "/statusz")
    gas_block = sz.get("gas", {})
    assert "gas" in gas_block, sz
    rec = gas_block["gas"]
    assert rec["direction_push"] + rec["direction_pull"] \
        == rec["num_iters"], rec
    print(f"statusz gas block: {gas_block}")

    server.shutdown()
    session.close()

    print(json.dumps({
        "schema": "gas_smoke.v1",
        "scale": scale,
        "nv": int(g.nv),
        "ne": int(g.ne),
        "apps": sorted(apps),
        "bfs": {
            "iters": bfs1["iters"],
            "direction_push": bfs1["direction_push"],
            "direction_pull": bfs1["direction_pull"],
            "direction_switches": bfs1["direction_switches"],
        },
        "sssp_delta_iters": sd["iters"],
        "labelprop_communities": lp["num_communities"],
        "kcore_sizes": {str(k): v for k, v in kc_results.items()},
        "pool_misses_query_phase": misses_after - misses_before,
        "recompiles": recompiles,
    }))
    print("gas-smoke PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
