#!/usr/bin/env python
"""gasck_smoke: acceptance gate for the luxlint program-contract tier
(`make lint-programs`, wired into `make verify`).

Three claims, all asserted:

  1. **registry clean + fast** — proving every registered program's GAS
     algebra (LUX601-606) produces 0 findings inside the wall budget; a
     proof tier too slow for verify is a proof tier nobody runs;
  2. **artifact parity** — the freshly derived ``gascap.v1`` capability
     matrix has the same content-addressed id as the committed
     ``lux_tpu/analysis/gascap.json``: a program change that flips a
     derived capability fails verify until the artifact is regenerated
     (``luxlint --programs --gascap-out lux_tpu/analysis/gascap.json``)
     — the offline half of the LUX606 drift ratchet;
  3. **a seeded broken program is caught** — the committed LUX602
     fixture (inexact float32 sum posing as a reorderable combiner)
     must fail with exactly its rule, proving the tier distinguishes
     and not merely passes.

Exit status: 0 when all three hold. Emits one greppable
``GASCKSMOKE {...}`` summary line (``gasck_smoke.v1``, the merge_smoke
idiom).

Usage:
    python tools/gasck_smoke.py               # default: 2s budget
    python tools/gasck_smoke.py --budget-s 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Program hooks run as eager cpu jnp; no device mesh, no XLA flags.
os.environ["JAX_PLATFORMS"] = "cpu"

from lux_tpu.analysis import gasck  # noqa: E402

FIXTURE = os.path.join(_REPO, "tests", "gas_fixtures",
                       "lux602_inexact_sum.py")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="gasck_smoke", description=__doc__)
    ap.add_argument("--budget-s", type=float, default=2.0,
                    help="wall budget for proving the whole registry")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    report, art = gasck.prove_registry()
    prove_s = time.perf_counter() - t0

    for res in report.results:
        for f in res.findings:
            print(f.format())
        if res.error:
            print(f"{res.path}: {res.error}")

    clean = report.ok
    fast = prove_s <= args.budget_s

    committed_id = None
    parity = False
    try:
        committed = gasck.load_capmap(gasck.capmap_path())
        committed_id = committed["id"]
        parity = committed_id == art["id"]
    except Exception as e:  # missing or tampered artifact: loud, fatal
        print(f"gasck_smoke: committed gascap.v1 unusable: {e!r}")

    fix_rules = []
    fixture_caught = False
    if os.path.exists(FIXTURE):
        fix_rep = gasck.verify_fixture_paths([FIXTURE])
        fix_rules = sorted({f.rule for f in fix_rep.findings})
        fixture_caught = (not fix_rep.ok) and fix_rules == ["LUX602"]
    else:
        print(f"gasck_smoke: missing fixture {FIXTURE}")

    ok = clean and fast and parity and fixture_caught
    summary = {
        "schema": "gasck_smoke.v1",
        "programs": len(report.results),
        "findings": len(report.findings),
        "errors": sum(1 for r in report.results if r.error),
        "prove_s": round(prove_s, 3),
        "budget_s": args.budget_s,
        "clean": clean,
        "fast": fast,
        "artifact_id": art["id"],
        "committed_id": committed_id,
        "parity": parity,
        "fixture_rules": fix_rules,
        "fixture_caught": fixture_caught,
        "ok": ok,
    }
    print("GASCKSMOKE " + json.dumps(summary, sort_keys=True))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
