#!/usr/bin/env python3
"""Generate + plan RMAT27 (2^31 edges) — the reference's headline scale
(README.md:84). Host-only demonstration that the out-of-core generator
and the radix planner handle the full scale within RAM; records times
and peak RSS. Artifacts land in .bench_cache/."""
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from lux_tpu.graph import generate, write_lux  # noqa: E402
from lux_tpu.ops.tiled_spmv import plan_hybrid, save_plan  # noqa: E402


def rss():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main():
    cache = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".bench_cache",
    )
    os.makedirs(cache, exist_ok=True)
    t0 = time.time()
    g = generate.rmat(27, 16, seed=42)
    print(f"rmat27 generated: nv={g.nv} ne={g.ne} in {time.time()-t0:.0f}s "
          f"(peak RSS {rss():.1f} GB)", flush=True)
    t0 = time.time()
    write_lux(os.path.join(cache, "rmat27_16.lux"), g)
    print(f"written in {time.time()-t0:.0f}s", flush=True)

    t0 = time.time()
    plan = plan_hybrid(g, levels=((8, 2),), budget_bytes=8 << 30)
    print(f"rmat27 planned in {time.time()-t0:.0f}s: {plan.num_strips} "
          f"strips ({plan.strip_bytes/1e9:.2f} GB), "
          f"coverage={plan.coverage:.1%}, "
          f"tail={plan.tail_sb.shape[0]/1e6:.0f}M edges "
          f"(peak RSS {rss():.1f} GB)", flush=True)
    t0 = time.time()
    save_plan(os.path.join(cache, "plan_rmat27_16_8x2_8192.luxplan"), plan)
    print(f"plan saved in {time.time()-t0:.0f}s", flush=True)


if __name__ == "__main__":
    main()
