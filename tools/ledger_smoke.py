#!/usr/bin/env python3
"""Run-ledger + cost-attribution smoke test (`make ledger-smoke`).

End-to-end acceptance for the observability ledger (obs/ledger.py) and
per-query cost accounting (serve/cost.py) on a warm CPU serving
session, with ``LUX_LEDGER_DIR`` armed for the whole run:

1. warm serve burst from TWO tenants through the real HTTP front door
   (``X-Lux-Tenant`` request header in, ``X-Lux-Cost`` response header
   out) — zero errors, zero recompiles after warmup;
2. ``/costz`` totals agree EXACTLY with the ``lux_query_cost_*``
   metric values (the lockstep-increment invariant), and per-tenant
   request counts match what the client actually issued;
3. the ledger collected durable ``runrec.v1`` records for the warmup
   and the engine runs; every record validates (crc-clean, no torn
   segments) and carries the config_hash the live registry reproduces;
4. ``tools/lux_doctor.py`` reads the ledger back and renders a CLEAN
   report (single config cohort: nothing to regress against).

Prints a ``ledger_smoke.v1`` JSON document on the last line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import urllib.request
from concurrent.futures import ThreadPoolExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCALE = 8
TENANTS = ("acme", "globex")
ROOTS_PER_TENANT = 6


def log(msg):
    print(f"# {msg}", flush=True)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return json.loads(r.read())


def post_query(base, payload, tenant):
    req = urllib.request.Request(
        base + "/query", json.dumps(payload).encode(),
        {"Content-Type": "application/json", "X-Lux-Tenant": tenant},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read()), r.headers.get("X-Lux-Cost")


def metric_value(base, name, **labels):
    for m in get(base, "/metrics.json")["metrics"]:
        if m["name"] == name and m["labels"] == labels:
            return m["value"]
    return 0.0


def main() -> int:
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    import jax

    from lux_tpu.utils import flags

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    with tempfile.TemporaryDirectory() as td:
        ledger_dir = os.path.join(td, "ledger")
        os.environ["LUX_LEDGER_DIR"] = ledger_dir

        from lux_tpu.graph import generate
        from lux_tpu.obs import ledger
        from lux_tpu.serve import ServeConfig, Session
        from lux_tpu.serve.http import serve_in_thread

        ledger.reset()
        g = generate.rmat(SCALE, 8, seed=1)
        session = Session(g, ServeConfig(
            max_batch=4, window_s=0.05, max_queue=128, pagerank_iters=4,
        ))
        server, _ = serve_in_thread(session, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        log(f"server up at {base}, ledger armed at {ledger_dir}")

        # -- 1. two-tenant warm burst over HTTP ------------------------
        issued = {t: 0 for t in TENANTS}
        cost_headers = []

        def burst(tenant, seed):
            for i in range(ROOTS_PER_TENANT):
                _out, hdr = post_query(
                    base, {"app": "sssp",
                           "start": (seed * 37 + i * 11) % g.nv}, tenant)
                cost_headers.append((tenant, hdr))
                issued[tenant] += 1
            # PageRank twice: a miss, then a result-cache hit.
            for _ in range(2):
                _out, hdr = post_query(base, {"app": "pagerank"}, tenant)
                cost_headers.append((tenant, hdr))
                issued[tenant] += 1

        with ThreadPoolExecutor(max_workers=2) as tp:
            list(tp.map(burst, TENANTS, range(len(TENANTS))))

        assert all(h and f"tenant={t}" in h for t, h in cost_headers), (
            "every response must carry an X-Lux-Cost header",
            cost_headers[:3])
        hits = [h for _t, h in cost_headers if "outcome=hit" in h]
        assert hits, "repeat pagerank must be a cache hit"
        recompiles = get(base, "/stats")["pool"]["recompiles"]
        assert recompiles == 0, f"burst added {recompiles} recompiles"
        log(f"burst ok: {sum(issued.values())} queries, "
            f"{len(hits)} cache hits, 0 recompiles")

        # -- 2. /costz totals == metric values, counts == issued -------
        costz = get(base, "/costz")
        assert costz["schema"] == "costz.v1", costz
        parity = {}
        for t in TENANTS:
            tot = costz["totals"][t]
            assert tot["requests"] == issued[t], (t, tot, issued)
            assert tot["hits"] >= 1 and tot["misses"] >= 1, tot
            m_engine = metric_value(
                base, "lux_query_cost_engine_seconds", tenant=t)
            m_iters = metric_value(
                base, "lux_query_cost_iterations_total", tenant=t)
            assert m_engine == tot["engine_s"], (t, m_engine, tot)
            assert m_iters == tot["iterations"], (t, m_iters, tot)
            parity[t] = {"requests": tot["requests"],
                         "engine_s": tot["engine_s"],
                         "iterations": tot["iterations"]}
        assert costz["config"]["hash"] == flags.config_hash()
        log(f"costz parity ok: {parity}")

        # -- 3. durable records validate + config_hash reproduces ------
        recs = ledger.read_all(ledger_dir, strict=True)
        kinds = sorted({r["kind"] for r in recs})
        assert "serve_warmup" in kinds and "engine_run" in kinds, kinds
        chash = flags.config_hash()
        assert all(r["key"]["config_hash"] == chash for r in recs), (
            "a record's config_hash must reproduce from the live "
            "registry while the env is unchanged")
        v = ledger.validate_dir(ledger_dir)
        assert v["interior_bad"] == 0 and v["torn_segments"] == 0, v
        log(f"ledger ok: {len(recs)} records {kinds}, validate={v}")

        # -- 4. the doctor reads it back clean -------------------------
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lux_doctor.py"),
             "--dir", ledger_dir, "--json"],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert proc.returncode == 0, (proc.returncode, proc.stderr)
        doctor = json.loads(proc.stdout)
        assert doctor["ok"] is True, doctor
        assert doctor["records"] == len(recs), doctor
        log("doctor ok: CLEAN verdict over the smoke ledger")

        server.shutdown()
        session.close()
        os.environ.pop("LUX_LEDGER_DIR", None)
        ledger.reset()

        print(json.dumps({
            "schema": "ledger_smoke.v1",
            "ok": True,
            "queries": sum(issued.values()),
            "cache_hits": len(hits),
            "recompiles": recompiles,
            "records": len(recs),
            "kinds": kinds,
            "config_hash": chash,
            "tenants": parity,
            "validate": v,
            "doctor_ok": doctor["ok"],
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
