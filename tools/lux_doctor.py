#!/usr/bin/env python3
"""lux doctor: attribute regressions from the run ledger.

The ledger (lux_tpu/obs/ledger.py) stores every run as a
(config -> metrics) observation keyed by (graph_fingerprint, program,
engine_kind, mesh_shape, config_hash). The doctor closes the loop:
group records that measured the SAME workload (everything in the key
except config_hash), split each group into config cohorts, compare the
two most recent cohorts (or the ``--a``/``--b`` hashes), and report

- which metric moved past ``--tol`` (direction-aware: gteps down is a
  regression, execute_s up is),
- which phase is responsible — exchange vs compute vs build — by the
  largest absolute time mover among exchange_s/compute_s/compile_s,
- which flags differ between the cohorts' stored config snapshots
  (path-kind flags excluded: artifact sinks, not behavior).

``--tuned`` recognizes cohort pairs whose config diff is entirely
tuner-managed flags (lux_tpu/tune/space.py TUNER_MANAGED) and reports
them as one "tuned config" decision — the auto-tuner's selection —
instead of listing the raw knob diff.

``--bench A.json B.json`` additionally diffs two bench round artifacts
(BENCH_r0N.json lineage: headline + suite gteps) through the same
tolerance. Output is a human report on stdout; ``--json`` emits one
``doctor.v1`` JSON line instead. Exit 0 when clean, 3 when any
regression is attributed (the bench_gate convention).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lux_tpu.obs import ledger  # noqa: E402
from lux_tpu.utils import flags  # noqa: E402

# (metric path, higher_is_better). Paths reach into the nested summary.
METRICS = (
    ("gteps", True),
    ("execute_s", False),
    ("compile_s", False),
    ("phases.exchange_s", False),
    ("phases.compute_s", False),
    ("useful_ratio", True),
    ("phases.exchange_hidden_frac", True),
    ("realized_hidden_frac", True),
    ("warm_s", False),
)

# Phase attribution: the largest absolute mover among these names the
# responsible phase in the report.
PHASE_SOURCES = (
    ("exchange", "phases.exchange_s"),
    ("compute", "phases.compute_s"),
    ("build", "compile_s"),
)


def _get(record_metrics: dict, path: str):
    cur = record_metrics
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def _mean(xs):
    xs = [x for x in xs if x is not None]
    return sum(xs) / len(xs) if xs else None


def group_key(rec: dict) -> tuple:
    k = rec.get("key", {})
    return (k.get("graph_fingerprint"), k.get("program"),
            k.get("engine_kind"), k.get("mesh_shape"))


def cohorts(records, a_hash=None, b_hash=None):
    """Split one group's records into (A, B) config cohorts.

    Default pairing: B is the most recent config_hash seen, A the most
    recent DIFFERENT one before it — "what changed since the last
    config" — preserving record order as the arrow of time (ids are
    appended in order; ``at`` stamps break ties across segments)."""
    records = sorted(records, key=lambda r: r.get("at", 0.0))
    by_hash, order = {}, []
    for r in records:
        h = r.get("key", {}).get("config_hash")
        if h not in by_hash:
            by_hash[h] = []
        by_hash[h].append(r)
        if h in order:
            order.remove(h)
        order.append(h)           # most-recently-seen last
    if a_hash and b_hash:
        if a_hash not in by_hash or b_hash not in by_hash:
            return None
        return by_hash[a_hash], by_hash[b_hash]
    if len(order) < 2:
        return None
    return by_hash[order[-2]], by_hash[order[-1]]


def aggregate(records) -> dict:
    out = {}
    for path, _hib in METRICS:
        v = _mean([_get(r.get("metrics", {}), path) for r in records])
        if v is not None:
            out[path] = v
    return out


def config_diff(a_recs, b_recs) -> dict:
    """Flags that differ between the cohorts' stored snapshots,
    path-kind flags excluded (they name artifact sinks, and config_hash
    itself ignores them — a differing tmpdir is not a behavior diff)."""
    a_cfg = (a_recs[-1].get("config") or {}) if a_recs else {}
    b_cfg = (b_recs[-1].get("config") or {}) if b_recs else {}
    out = {}
    for name in sorted(set(a_cfg) | set(b_cfg)):
        if flags.declared(name) and flags._REGISTRY[name].kind == "path":
            continue
        av, bv = a_cfg.get(name), b_cfg.get(name)
        if av != bv:
            out[name] = {"a": av, "b": bv}
    return out


def tuned_config_diff(diff: dict) -> bool:
    """True when the cohorts differ ONLY in tuner-managed flags
    (lux_tpu/tune/space.py TUNER_MANAGED) — i.e. the delta between them
    IS the auto-tuner's doing (a tuned-vs-default pair, or two tuned
    configs), not a code or environment change. LUX_ENGOBS is also
    tuner-set: probes force phase measurement on."""
    from lux_tpu.tune.space import TUNER_MANAGED

    if not diff:
        return False
    return set(diff) <= (TUNER_MANAGED | {"LUX_ENGOBS"})


def compare(a_recs, b_recs, tol: float) -> dict:
    a_m, b_m = aggregate(a_recs), aggregate(b_recs)
    diff = config_diff(a_recs, b_recs)
    regressions, improvements = [], []
    for path, hib in METRICS:
        av, bv = a_m.get(path), b_m.get(path)
        if av is None or bv is None:
            continue
        base = max(abs(av), 1e-12)
        delta_frac = (bv - av) / base
        moved = abs(delta_frac) > tol
        if not moved:
            continue
        worse = (delta_frac < 0) if hib else (delta_frac > 0)
        entry = {"metric": path, "a": av, "b": bv,
                 "delta_frac": round(delta_frac, 4)}
        (regressions if worse else improvements).append(entry)
    # Phase attribution: among the time phases, who moved the most
    # wall-clock? That phase owns the regression story.
    phase, phase_delta = None, 0.0
    for name, path in PHASE_SOURCES:
        av, bv = a_m.get(path), b_m.get(path)
        if av is None or bv is None:
            continue
        d = bv - av
        if abs(d) > abs(phase_delta):
            phase, phase_delta = name, d
    for entry in regressions:
        entry["phase"] = phase
    return {
        "a": {"config_hash": a_recs[-1]["key"]["config_hash"],
              "n": len(a_recs), "metrics": a_m,
              "record_ids": [r.get("id") for r in a_recs]},
        "b": {"config_hash": b_recs[-1]["key"]["config_hash"],
              "n": len(b_recs), "metrics": b_m,
              "record_ids": [r.get("id") for r in b_recs]},
        "regressions": regressions,
        "improvements": improvements,
        "phase": phase,
        "phase_delta_s": round(phase_delta, 6) if phase else None,
        "config_diff": diff,
        "tuned_config": tuned_config_diff(diff),
    }


def diagnose(records, tol: float, a_hash=None, b_hash=None) -> list:
    groups = {}
    for r in records:
        if r.get("schema") != ledger.SCHEMA:
            continue
        groups.setdefault(group_key(r), []).append(r)
    pairs = []
    for gkey, recs in sorted(groups.items(), key=lambda kv: str(kv[0])):
        pair = cohorts(recs, a_hash, b_hash)
        if pair is None:
            continue
        result = compare(pair[0], pair[1], tol)
        result["key"] = {
            "graph_fingerprint": gkey[0], "program": gkey[1],
            "engine_kind": gkey[2], "mesh_shape": gkey[3],
        }
        pairs.append(result)
    return pairs


def bench_diff(a_path: str, b_path: str, tol: float) -> dict:
    """Diff two bench round artifacts (headline + suite gteps)."""
    def load(p):
        with open(p) as f:
            return json.load(f)

    a, b = load(a_path), load(b_path)
    moved = []
    rows = [("headline", a.get("value"), b.get("value"))]
    for name in sorted(set(a.get("suite") or {}) | set(b.get("suite") or {})):
        rows.append((
            f"suite.{name}",
            (a.get("suite") or {}).get(name, {}).get("gteps"),
            (b.get("suite") or {}).get(name, {}).get("gteps"),
        ))
    for name, av, bv in rows:
        if av is None or bv is None:
            continue
        delta_frac = (bv - av) / max(abs(av), 1e-12)
        if delta_frac < -tol:
            moved.append({"metric": f"{name}.gteps", "a": av, "b": bv,
                          "delta_frac": round(delta_frac, 4)})
    return {"a": a_path, "b": b_path, "regressions": moved}


def render(report: dict) -> str:
    lines = ["lux doctor: run-ledger regression attribution",
             f"  ledger: {report['dir']}  ({report['records']} records, "
             f"{len(report['pairs'])} comparable pair(s), "
             f"tol={report['tol']})"]
    if not report["pairs"]:
        lines.append("  no comparable (A, B) config cohorts found — need "
                     "two configs measuring the same "
                     "(graph, program, engine, mesh).")
    for pair in report["pairs"]:
        k = pair["key"]
        lines.append(
            "  workload: program={program} engine={engine_kind} "
            "mesh={mesh_shape} graph={graph_fingerprint}".format(
                **{**k, "graph_fingerprint":
                   str(k["graph_fingerprint"])[:20]}))
        lines.append(
            "    A config={} (n={})  ->  B config={} (n={})".format(
                pair["a"]["config_hash"], pair["a"]["n"],
                pair["b"]["config_hash"], pair["b"]["n"]))
        if not pair["regressions"]:
            lines.append("    OK: no metric moved past tolerance.")
        for reg in pair["regressions"]:
            lines.append(
                "    REGRESSION {metric}: {a:.6g} -> {b:.6g} "
                "({delta_frac:+.1%})".format(**reg))
            if reg.get("phase"):
                lines.append(
                    "      responsible phase: {} ({:+.6f}s)".format(
                        reg["phase"], pair["phase_delta_s"] or 0.0))
        if report.get("tuned_mode"):
            # The tuned-vs-default report cuts both ways: what the
            # selection bought is as load-bearing as what it cost.
            for imp in pair.get("improvements") or ():
                lines.append(
                    "    IMPROVED {metric}: {a:.6g} -> {b:.6g} "
                    "({delta_frac:+.1%})".format(**imp))
        if report.get("tuned_mode") and pair.get("tuned_config"):
            # The cohorts differ only in tuner-managed flags: the delta
            # IS the tuner's selection, so name it as one decision
            # instead of spelling out the raw knob diff.
            knobs = ", ".join(
                "{}={!r}".format(n, d["b"])
                for n, d in sorted(pair["config_diff"].items())
                if n != "LUX_ENGOBS")
            lines.append(
                "      tuned config: cohorts differ only in "
                "tuner-managed flags — B is the auto-tuner's "
                "selection ({})".format(knobs or "defaults"))
        else:
            for name, d in pair["config_diff"].items():
                lines.append(
                    "      config diff: {}: {!r} -> {!r}".format(
                        name, d["a"], d["b"]))
            if pair.get("tuned_config"):
                lines.append(
                    "      (all tuner-managed: a tuned-vs-default "
                    "cohort pair — rerun with --tuned for the "
                    "attribution line)")
        if pair["regressions"] and not pair["config_diff"]:
            lines.append("      config diff: none (same flags — suspect "
                         "the code or the environment, not a knob)")
    bench = report.get("bench")
    if bench:
        lines.append(f"  bench lineage: {bench['a']} -> {bench['b']}")
        if not bench["regressions"]:
            lines.append("    OK: no bench metric regressed.")
        for reg in bench["regressions"]:
            lines.append(
                "    REGRESSION {metric}: {a:.6g} -> {b:.6g} "
                "({delta_frac:+.1%})".format(**reg))
    lines.append("  verdict: " + ("CLEAN" if report["ok"]
                                  else "REGRESSED"))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="lux_doctor", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--dir", default=None,
                   help="ledger directory (default: LUX_LEDGER_DIR)")
    p.add_argument("--a", default=None, dest="a_hash",
                   help="baseline config_hash (default: second-newest)")
    p.add_argument("--b", default=None, dest="b_hash",
                   help="candidate config_hash (default: newest)")
    p.add_argument("--tol", type=float, default=0.2,
                   help="relative move past which a metric counts")
    p.add_argument("--bench", nargs=2, metavar=("A.json", "B.json"),
                   help="also diff two bench round artifacts")
    p.add_argument("--tuned", action="store_true",
                   help="attribute cohort pairs that differ only in "
                   "tuner-managed flags (lux_tpu/tune) as one 'tuned "
                   "config' decision instead of a raw flag diff")
    p.add_argument("--json", action="store_true",
                   help="emit one doctor.v1 JSON line instead of text")
    args = p.parse_args(argv)

    root = args.dir or flags.get("LUX_LEDGER_DIR")
    if not root:
        p.error("no ledger: pass --dir or set LUX_LEDGER_DIR")
    try:
        records = ledger.read_all(root)
    except ledger.LedgerCorruptError as e:
        print(f"lux doctor: corrupt ledger: {e}", file=sys.stderr)
        return 2
    pairs = diagnose(records, args.tol, args.a_hash, args.b_hash)
    report = {
        "schema": "doctor.v1",
        "dir": root,
        "records": len(records),
        "tol": args.tol,
        "tuned_mode": bool(args.tuned),
        "pairs": pairs,
        "validate": ledger.validate_dir(root),
    }
    if args.bench:
        report["bench"] = bench_diff(args.bench[0], args.bench[1],
                                     args.tol)
    regressed = any(p_["regressions"] for p_ in pairs) or bool(
        report.get("bench", {}).get("regressions"))
    report["ok"] = not regressed
    if args.json:
        print(json.dumps(report, separators=(",", ":")))
    else:
        print(render(report))
    return 3 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
