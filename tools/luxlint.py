#!/usr/bin/env python
"""luxlint: project-native static analysis over lux_tpu/ + tools/.

Usage:
    python tools/luxlint.py                  # lint the default tree
    python tools/luxlint.py path.py dir/     # lint specific targets
    python tools/luxlint.py --json           # full findings as JSON
    python tools/luxlint.py --list-rules     # rule table
    python tools/luxlint.py --select LUX001  # subset of rules

Exit status: 0 clean, 1 unsuppressed findings or syntax errors. Always
emits one greppable summary line (`LUXLINT {...}`, the merge_smoke
idiom) so CI logs carry the verdict even when output scrolls.

Suppress a finding inline, with a reason:
    x.item()  # luxlint: disable=LUX001 -- intended once-per-run sync
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from lux_tpu.analysis import all_rules, run_paths  # noqa: E402

DEFAULT_TARGETS = ("lux_tpu", "tools", "bench.py")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="luxlint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_TARGETS})")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON on stdout")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.title}\n       {r.doc}")
        return 0
    if args.select:
        want = {s.strip() for s in args.select.split(",") if s.strip()}
        unknown = want - {r.id for r in rules}
        if unknown:
            ap.error(f"unknown rule id(s): {sorted(unknown)}")
        rules = [r for r in rules if r.id in want]

    paths = args.paths or [os.path.join(_REPO, t) for t in DEFAULT_TARGETS]
    report = run_paths(paths, rules)

    if args.json:
        print(report.to_json())
    else:
        print(report.format_human())
    print("LUXLINT " + json.dumps(report.summary(), sort_keys=True))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
